# Developer entry points (the reference drives everything through its
# Makefile: test/envtest/codegen; this framework is pure Python + on-demand
# C++, so the surface is smaller but the verbs match).

PY ?= python
CPU_ENV = JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8

.PHONY: test test-fast test-wire test-chaos test-fleet test-tenancy test-failover test-shards test-store-shards test-slo soak-smoke lint lockcheck-report bench bench-quick bench-solver bench-wire bench-wire-v2 bench-wire-resume bench-observe bench-audit bench-lockcheck bench-node-chaos bench-tenancy bench-failover bench-shards bench-store-shards bench-slo bench-wire-driver bench-soak dryrun operator-demo ha-demo native clean

test:            ## full suite (no hardware needed; ~10 min)
	$(PY) -m pytest tests/ -q

test-fast:       ## the tier-1 fast lane: everything but the `slow`-marked jit-heavy numerics
	$(PY) -m pytest tests/ -q -m "not slow"

# Deterministic wire protocol-conformance lane (no timing asserts): framing,
# batch/coalesce/pagination semantics, codec, resume — catches protocol
# regressions in CI without the machine-load-sensitive wire benches.
test-wire:       ## fast deterministic wire protocol lane (framing/codec/resume)
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_wire.py tests/test_wire_v2.py \
	  tests/test_wire_fastpath.py tests/test_wire_resume.py -q

test-chaos:      ## the chaos/fault-injection lane: pod, store, wire, and node tiers
	$(PY) -m pytest tests/test_chaos.py tests/test_wire_chaos.py tests/test_node_lifecycle.py -q

test-fleet:      ## the fleet introspection lane: invariant rules, /fleet, top, event dedup
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_fleet.py -q

test-tenancy:    ## the multi-tenancy lane: quotas, priority, fair share, preemption
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_tenancy.py -q

# Deterministic control-plane HA lane: in-process HostChaos (WAL shipping,
# epoch-chained resume, promotion, the 120-job failover burst) plus the
# crash-window store tests — no OS-process spawning, kept out of `slow`.
test-failover:   ## control-plane failover lane (WAL standby, HostChaos, crash-safe store)
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_failover.py tests/test_store.py -q

# Operator scale-out lane: shard election primitives + the takeover-CAS
# fix, the 3-replica death-handoff burst with its single-writer pin, the
# follower-read client against a real primary/standby pair, INV010
# semantics, knob round-trips, and the 3-replica replica-kill soak smoke.
test-shards:     ## operator scale-out lane (shard leases, handoff, follower reads)
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_shards.py tests/test_config_knobs.py \
	  tests/test_soak.py -q -m "not slow" -k "not CompressedDay"

# Sharded write plane lane (deterministic, part of the default test flow —
# tests/test_store_shards.py is collected by `test`/`test-fast`): the
# (kind, namespace) routing map, StoreShardSet journals + ownership,
# INV011 semantics, the client-side shard router (fan-out lists, shard
# cursors, merged watch), per-shard outrun/failover healing, and the
# 2-shard soak smoke with one per-shard failover.
test-store-shards:  ## sharded write-plane lane (routing, INV011, shard router)
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_store_shards.py \
	  tests/test_config_knobs.py -q

# SLO engine lane (deterministic, part of the default test flow —
# tests/test_slo.py is collected by `test`/`test-fast`): sliding-window
# histograms, SLOPolicy admission, multi-window burn-rate evaluation +
# once-per-incident events, per-job latency attribution (`explain`), the
# owning-shard routing of timeline/explain reads, and the merged
# chrome-trace export.
test-slo:        ## SLO engine lane (burn rate, attribution, sharded explain)
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_slo.py -q

# The soak smoke tier: a compressed hour of fleet life with ALL FIVE chaos
# tiers live at once + one host failover, under the fail-fast INV001-INV011
# auditor, plus the single-seed replay pin and the bounded-growth/INV009
# unit tests. Part of the default `test`/`test-fast` flow (tests/test_soak.py
# is collected there); this lane runs it standalone.
soak-smoke:      ## compressed-hour five-tier soak smoke (~90s, `not slow`)
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_soak.py -q -m "not slow"

lint:            ## project code lint: AST discipline rules (CL001-CL013) + ruff (if present)
	$(PY) -m training_operator_tpu.analysis.codelint training_operator_tpu
	$(PY) -m training_operator_tpu.analysis.lockcheck training_operator_tpu
	@if command -v ruff >/dev/null 2>&1; then \
	  ruff check training_operator_tpu; \
	else \
	  echo "ruff not installed; skipping (config pinned in pyproject.toml)"; \
	fi

# The inferred lock->guarded-field map and static lock-order graph as
# JSON — the reviewable artifact behind CL010/CL011 (an empty
# "order_edges" means no class nests two owned locks lexically).
lockcheck-report:  ## lock ownership + order-graph JSON from the static analyzer
	$(PY) -m training_operator_tpu.analysis.lockcheck --report training_operator_tpu

bench:           ## headline benchmark (runs the trainer block on TPU if present)
	$(PY) bench.py

bench-quick:     ## 100-job smoke benchmark
	$(PY) bench.py --quick

# Incremental gang solver A/B: the SAME 1k-job burst through the
# pinned-legacy compat arm (solver_incremental=False + jax kernel), the
# incremental arm (per-group dirty tracking + delta snapshot + numpy
# kernel), AND the true pre-PR code from a worktree (interleaved, the
# bench-wire-v2 method), plus one cold 10k-node/2k-gang solve against the
# <2s budget. Headline = solver_wall/job speedup (target 10x vs pre-PR).
bench-solver:    ## incremental-solver A/B -> BENCH_SELF_SOLVER_r13.json
	git worktree add --force .bench-before $(BEFORE_REF)
	cp bench.py .bench-before/bench.py
	JAX_PLATFORMS=cpu $(PY) bench.py --solver-only --before-repo .bench-before; \
	rc=$$?; git worktree remove --force .bench-before; exit $$rc

dryrun:          ## multi-chip sharding gates on 8 virtual CPU devices
	$(CPU_ENV) $(PY) -c "import __graft_entry__ as g; fn, a = g.entry(); \
	import jax; print('entry loss:', float(jax.jit(fn)(*a))); \
	g.dryrun_multichip(8); print('DRYRUN OK')"

operator-demo:   ## the operator process end-to-end on the example workload
	$(PY) -m training_operator_tpu \
	  --cluster examples/process/cluster.json \
	  --workload examples/process/workload.json \
	  --virtual-clock

ha-demo:         ## wire deployment: host + 2 operator processes, leader killed
	$(PY) examples/remote_ha.py

# Quick-sized (100-job) wire-vs-inproc overhead + cache hit rates, printed
# as one JSON line — wire perf is reproducible without the full 1k-job sim.
bench-wire:      ## wire fast-path block standalone (quick-sized, one JSON line)
	JAX_PLATFORMS=cpu $(PY) bench.py --wire-overhead-only --wire-jobs 100

wire-bench: bench-wire  ## back-compat alias for bench-wire

# Wire protocol v2 before/after evidence: interleaved pairs against a
# pre-change worktree carrying this same harness (BENCH_SELF_WIRE_r06
# method; no TLS dep needed — the wire leg auto-falls back to --insecure
# loopback HTTP). BEFORE_REF defaults to HEAD: run BEFORE committing, or
# point it at the pre-PR commit afterwards.
BEFORE_REF ?= HEAD
WIRE_V2_PAIRS ?= 5
bench-wire-v2:   ## interleaved wire-v2 A/B pairs -> BENCH_SELF_WIRE_V2_r09.json
	git worktree add --force .bench-before $(BEFORE_REF)
	cp bench.py .bench-before/bench.py
	JAX_PLATFORMS=cpu $(PY) bench.py --wire-ab $(WIRE_V2_PAIRS) \
	  --before-repo .bench-before --wire-jobs 100 \
	  --ab-out BENCH_SELF_WIRE_V2_r09.json; \
	rc=$$?; git worktree remove --force .bench-before; exit $$rc

# Reap every watch session against a 1k-object cluster and compare the
# reconnect cost of ResourceVersion delta-resume vs the forced full relist.
bench-wire-resume:  ## watch-resume reconnect-cost block (one JSON line)
	JAX_PLATFORMS=cpu $(PY) bench.py --wire-resume-only

# Job-lifecycle tracing on vs off over the same gang burst: the
# instrumentation must stay under 5% to be left enabled in production.
bench-observe:   ## observability-overhead block (one JSON line)
	JAX_PLATFORMS=cpu $(PY) bench.py --observe-only

# Invariant auditor on vs off over the same 120-job gang burst (the
# BENCH_SELF_OBSERVE method): direct self-timed audit share decides the
# <2% budget; the burst itself runs with the auditor fail-fast, so a single
# violation fails the lane.
bench-audit:     ## auditor-overhead block (one JSON line + BENCH_SELF_AUDIT artifact)
	JAX_PLATFORMS=cpu $(PY) bench.py --audit-only

# Lock-order witness on vs off over the same 120-job gang burst (the
# bench-audit method): self-timed _note_acquire share decides the <2%
# budget; the on-arm runs with witness fail-fast, so a single
# acquisition-order cycle fails the lane.
bench-lockcheck: ## witness-overhead block (one JSON line + BENCH_SELF_LOCKCHECK artifact)
	JAX_PLATFORMS=cpu $(PY) bench.py --lockcheck-only

# Kill the primary host mid 120-job burst on real sockets: standby tails
# the WAL, auto-promotes on lease expiry, converges the burst under the
# fail-fast auditor. Reports failover MTTR (kill -> first acknowledged
# write), epoch-chained resume economics (replayed vs forced-relist events
# for N surviving watch sessions), and steady-state replication lag.
bench-failover:  ## control-plane failover MTTR block -> BENCH_SELF_FAILOVER artifact
	JAX_PLATFORMS=cpu $(PY) bench.py --failover-only

# Operator scale-out A/B: the same wire burst through 1/2/3 sharded
# operator OS processes (jobs/minute vs replica count), plus the 1k-session
# follower-read swarm (primary write p50: no sessions vs sessions-on-
# primary vs sessions-on-standby).
bench-shards:    ## operator scale-out block -> BENCH_SELF_SHARDS artifact
	JAX_PLATFORMS=cpu $(PY) bench.py --shards-only

# Sharded write plane headline: the SAME 5k-job write burst through 1, 2,
# and 4 fsync'd write-shard host processes behind the client-side router,
# interleaved legs (the bench-wire-v2 method). Reports write p50/p99 and
# jobs/minute per shard count; single-core caveat recorded in the artifact.
bench-store-shards:  ## write-shard scaling block -> BENCH_SELF_STORE_SHARDS_r17.json
	JAX_PLATFORMS=cpu $(PY) bench.py --store-shards-only

# SLO evaluator + attribution on vs off over the same 120-job gang burst
# (the bench-audit method): direct self-timed evaluate+explain share decides
# the <2% budget recorded in the BENCH_SELF_SLO artifact.
bench-slo:       ## SLO-engine overhead block (one JSON line + BENCH_SELF_SLO artifact)
	JAX_PLATFORMS=cpu $(PY) bench.py --slo-only

# External-baseline driver stub: emits the self-measured sharded-write proxy
# with external_baseline_unmeasured=true (no upstream kube-apiserver in this
# container to drive; the stub records the method so the comparison slots in
# when one is available).
bench-wire-driver:  ## external-baseline stub -> self-measured proxy JSON
	JAX_PLATFORMS=cpu $(PY) bench.py --wire-driver-stub

# Kill one host of a whole-slice TPU gang on a virtual clock and measure
# node-loss MTTR: detect (grace) -> evict (toleration) -> gang re-solve ->
# Running again, as one JSON line.
bench-node-chaos:  ## node-loss MTTR block (one JSON line)
	JAX_PLATFORMS=cpu $(PY) bench.py --node-chaos-only

# N teams x M jobs over-subscribing one chip pool, arbiter off (FCFS) vs on,
# on a virtual clock: Jain fairness over per-team mean running chips, p50/p99
# schedule->Running per priority tier, preemption count, and the
# checkpoint-resume proof (every preempted job Succeeded, >=1 resume from a
# nonzero step, restart budget untouched).
bench-tenancy:   ## contention fairness A/B block -> BENCH_SELF_TENANCY artifact
	JAX_PLATFORMS=cpu $(PY) bench.py --tenancy-only

# The full soak artifact: a simulated WEEK at 10k nodes (compression 4x ->
# 42 sim-hours of virtual clock), sustained heavy-tailed arrivals into
# oversubscribed queues, five chaos tiers + rolling maintenance + one
# mid-soak host failover, fail-fast auditing. Expect ~20-40 min of wall.
bench-soak:      ## simulated-week fleet soak -> BENCH_SELF_SOAK_r14.json
	JAX_PLATFORMS=cpu $(PY) bench.py --soak-only

native:          ## force-rebuild the C++ data-path core (drops the hash cache)
	$(PY) -c "from training_operator_tpu import native; import glob, os; \
	[os.remove(p) for p in glob.glob(str(native._cache_dir() / 'dataio-*.so'))]; \
	print(native.available() or native.build_error())"

clean:
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null; true
