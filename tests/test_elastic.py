"""Elasticity tests: HPA loop + incremental gang re-pack (BASELINE.md
config 4: incremental re-pack on scale events, not full re-schedule)."""

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import Container, PodTemplateSpec, ReplicaSpec
from training_operator_tpu.api.jobs import ElasticPolicy, ObjectMeta, PyTorchJob
from training_operator_tpu.cluster.inventory import GPU_RESOURCE, make_gpu_pool
from training_operator_tpu.cluster.objects import PodGroupPhase, PodPhase
from training_operator_tpu.cluster.runtime import (
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
)
from training_operator_tpu.controllers import OperatorManager, register_all
from training_operator_tpu.scheduler import GangScheduler, TPUPacker
from training_operator_tpu.scheduler.elastic import (
    HorizontalAutoscaler,
    StaticMetricsSource,
)


def elastic_job(name="el", min_r=2, max_r=6, metric_target=70.0):
    t = PodTemplateSpec(
        containers=[
            Container(name="pytorch", image="img",
                      resources={"cpu": 1.0, GPU_RESOURCE: 8.0})
        ]
    )
    return PyTorchJob(
        metadata=ObjectMeta(name=name),
        replica_specs={"Worker": ReplicaSpec(replicas=min_r, template=t)},
        elastic_policy=ElasticPolicy(
            min_replicas=min_r, max_replicas=max_r,
            metrics=[{"name": "gpu_util", "target": metric_target}],
        ),
    )


def make_env(gang=True, nodes=8):
    cluster = Cluster(VirtualClock())
    cluster.add_nodes(make_gpu_pool(nodes, gpus_per_node=8, nodes_per_nvlink_domain=4))
    DefaultScheduler(cluster)
    SimKubelet(cluster)
    metrics = StaticMetricsSource()
    HorizontalAutoscaler(cluster, metrics, sync_period=5.0, stabilization_seconds=10.0)
    if gang:
        GangScheduler(cluster, TPUPacker())
    mgr = OperatorManager(cluster, gang_enabled=gang)
    register_all(mgr)
    return cluster, mgr, metrics


def worker_pods(cluster, name):
    return [
        p for p in cluster.api.list("Pod", "default", {capi.JOB_NAME_LABEL: name})
        if p.status.phase == PodPhase.RUNNING
    ]


class TestAutoscaler:
    def test_scale_out_on_high_utilization(self):
        cluster, mgr, metrics = make_env()
        mgr.submit(elastic_job())
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 2, timeout=60)
        # 140% of target => desired = ceil(2 * 140/70) = 4.
        metrics.set("default", "el", "gpu_util", 140.0)
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 4, timeout=120)
        job = cluster.api.get("PyTorchJob", "default", "el")
        assert job.replica_specs["Worker"].replicas == 4
        hpa = cluster.api.get("HorizontalPodAutoscaler", "default", "el")
        assert hpa.desired_replicas == 4

    def test_scale_out_clamped_to_max(self):
        cluster, mgr, metrics = make_env()
        mgr.submit(elastic_job(max_r=3))
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 2, timeout=60)
        metrics.set("default", "el", "gpu_util", 700.0)
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 3, timeout=120)

    def test_scale_in_after_stabilization(self):
        cluster, mgr, metrics = make_env()
        mgr.submit(elastic_job(min_r=2, max_r=6))
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 2, timeout=60)
        metrics.set("default", "el", "gpu_util", 140.0)
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 4, timeout=120)
        metrics.set("default", "el", "gpu_util", 20.0)
        # desired = ceil(4 * 20/70) = 2, after the stabilization window.
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 2, timeout=200)

    def test_incremental_repack_keeps_existing_members(self):
        """Scale-out must not move running pods (config 4: incremental
        re-pack, not full re-schedule) and should prefer the gang's NVLink
        domain for new members."""
        cluster, mgr, metrics = make_env()
        mgr.submit(elastic_job(min_r=2, max_r=4))
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 2, timeout=60)
        before = {p.name: p.node_name for p in worker_pods(cluster, "el")}
        metrics.set("default", "el", "gpu_util", 140.0)
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 4, timeout=120)
        after = {p.name: p.node_name for p in worker_pods(cluster, "el")}
        for name, node in before.items():
            assert after[name] == node  # members did not move
        pg = cluster.api.get("PodGroup", "default", "el")
        assert pg.min_member == 4
        domains = {
            cluster.api.get("Node", "", n).accelerator.nvlink_domain
            for n in after.values()
        }
        assert len(domains) == 1  # locality preserved on growth

    def test_scale_in_releases_placement_entries(self):
        cluster, mgr, metrics = make_env()
        mgr.submit(elastic_job(min_r=2, max_r=6))
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 2, timeout=60)
        metrics.set("default", "el", "gpu_util", 140.0)
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 4, timeout=120)
        metrics.set("default", "el", "gpu_util", 20.0)
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 2, timeout=200)
        pg = cluster.api.get("PodGroup", "default", "el")
        assert len(pg.placement) == 2
        assert set(pg.placement) == {"el-worker-0", "el-worker-1"}


    def test_metric_demanding_current_capacity_blocks_downscale(self):
        """A metric proposing exactly `current` replicas must win over a
        later metric proposing fewer (max-over-metrics, no 0-sentinel)."""
        t = PodTemplateSpec(
            containers=[
                Container(name="pytorch", image="img",
                          resources={"cpu": 1.0, GPU_RESOURCE: 8.0})
            ]
        )
        job = PyTorchJob(
            metadata=ObjectMeta(name="el"),
            replica_specs={"Worker": ReplicaSpec(replicas=2, template=t)},
            elastic_policy=ElasticPolicy(
                min_replicas=1, max_replicas=6,
                metrics=[{"name": "gpu_util", "target": 70.0},
                         {"name": "queue_depth", "target": 100.0}],
            ),
        )
        cluster, mgr, metrics = make_env()
        mgr.submit(job)
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 2, timeout=60)
        metrics.set("default", "el", "gpu_util", 70.0)     # proposes exactly 2
        metrics.set("default", "el", "queue_depth", 10.0)  # proposes 1
        cluster.run_for(60)  # well past the downscale stabilization window
        job = cluster.api.get("PyTorchJob", "default", "el")
        assert job.replica_specs["Worker"].replicas == 2
