"""Elasticity tests: HPA loop + incremental gang re-pack (BASELINE.md
config 4: incremental re-pack on scale events, not full re-schedule)."""

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import Container, PodTemplateSpec, ReplicaSpec
from training_operator_tpu.api.jobs import ElasticPolicy, ObjectMeta, PyTorchJob
from training_operator_tpu.cluster.inventory import GPU_RESOURCE, make_gpu_pool
from training_operator_tpu.cluster.objects import PodGroupPhase, PodPhase
from training_operator_tpu.cluster.runtime import (
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
)
from training_operator_tpu.controllers import OperatorManager, register_all
from training_operator_tpu.scheduler import GangScheduler, TPUPacker
from training_operator_tpu.scheduler.elastic import (
    HorizontalAutoscaler,
    StaticMetricsSource,
)


def elastic_job(name="el", min_r=2, max_r=6, metric_target=70.0):
    t = PodTemplateSpec(
        containers=[
            Container(name="pytorch", image="img",
                      resources={"cpu": 1.0, GPU_RESOURCE: 8.0})
        ]
    )
    return PyTorchJob(
        metadata=ObjectMeta(name=name),
        replica_specs={"Worker": ReplicaSpec(replicas=min_r, template=t)},
        elastic_policy=ElasticPolicy(
            min_replicas=min_r, max_replicas=max_r,
            metrics=[{"name": "gpu_util", "target": metric_target}],
        ),
    )


def make_env(gang=True, nodes=8):
    cluster = Cluster(VirtualClock())
    cluster.add_nodes(make_gpu_pool(nodes, gpus_per_node=8, nodes_per_nvlink_domain=4))
    DefaultScheduler(cluster)
    SimKubelet(cluster)
    metrics = StaticMetricsSource()
    HorizontalAutoscaler(cluster, metrics, sync_period=5.0, stabilization_seconds=10.0)
    if gang:
        GangScheduler(cluster, TPUPacker())
    mgr = OperatorManager(cluster, gang_enabled=gang)
    register_all(mgr)
    return cluster, mgr, metrics


def worker_pods(cluster, name):
    return [
        p for p in cluster.api.list("Pod", "default", {capi.JOB_NAME_LABEL: name})
        if p.status.phase == PodPhase.RUNNING
    ]


class TestAutoscaler:
    def test_scale_out_on_high_utilization(self):
        cluster, mgr, metrics = make_env()
        mgr.submit(elastic_job())
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 2, timeout=60)
        # 140% of target => desired = ceil(2 * 140/70) = 4.
        metrics.set("default", "el", "gpu_util", 140.0)
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 4, timeout=120)
        job = cluster.api.get("PyTorchJob", "default", "el")
        assert job.replica_specs["Worker"].replicas == 4
        hpa = cluster.api.get("HorizontalPodAutoscaler", "default", "el")
        assert hpa.desired_replicas == 4

    def test_scale_out_clamped_to_max(self):
        cluster, mgr, metrics = make_env()
        mgr.submit(elastic_job(max_r=3))
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 2, timeout=60)
        metrics.set("default", "el", "gpu_util", 700.0)
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 3, timeout=120)

    def test_scale_in_after_stabilization(self):
        cluster, mgr, metrics = make_env()
        mgr.submit(elastic_job(min_r=2, max_r=6))
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 2, timeout=60)
        metrics.set("default", "el", "gpu_util", 140.0)
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 4, timeout=120)
        metrics.set("default", "el", "gpu_util", 20.0)
        # desired = ceil(4 * 20/70) = 2, after the stabilization window.
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 2, timeout=200)

    def test_incremental_repack_keeps_existing_members(self):
        """Scale-out must not move running pods (config 4: incremental
        re-pack, not full re-schedule) and should prefer the gang's NVLink
        domain for new members."""
        cluster, mgr, metrics = make_env()
        mgr.submit(elastic_job(min_r=2, max_r=4))
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 2, timeout=60)
        before = {p.name: p.node_name for p in worker_pods(cluster, "el")}
        metrics.set("default", "el", "gpu_util", 140.0)
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 4, timeout=120)
        after = {p.name: p.node_name for p in worker_pods(cluster, "el")}
        for name, node in before.items():
            assert after[name] == node  # members did not move
        pg = cluster.api.get("PodGroup", "default", "el")
        assert pg.min_member == 4
        domains = {
            cluster.api.get("Node", "", n).accelerator.nvlink_domain
            for n in after.values()
        }
        assert len(domains) == 1  # locality preserved on growth

    def test_scale_in_releases_placement_entries(self):
        cluster, mgr, metrics = make_env()
        mgr.submit(elastic_job(min_r=2, max_r=6))
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 2, timeout=60)
        metrics.set("default", "el", "gpu_util", 140.0)
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 4, timeout=120)
        metrics.set("default", "el", "gpu_util", 20.0)
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 2, timeout=200)
        pg = cluster.api.get("PodGroup", "default", "el")
        assert len(pg.placement) == 2
        assert set(pg.placement) == {"el-worker-0", "el-worker-1"}


    def test_metric_demanding_current_capacity_blocks_downscale(self):
        """A metric proposing exactly `current` replicas must win over a
        later metric proposing fewer (max-over-metrics, no 0-sentinel)."""
        t = PodTemplateSpec(
            containers=[
                Container(name="pytorch", image="img",
                          resources={"cpu": 1.0, GPU_RESOURCE: 8.0})
            ]
        )
        job = PyTorchJob(
            metadata=ObjectMeta(name="el"),
            replica_specs={"Worker": ReplicaSpec(replicas=2, template=t)},
            elastic_policy=ElasticPolicy(
                min_replicas=1, max_replicas=6,
                metrics=[{"name": "gpu_util", "target": 70.0},
                         {"name": "queue_depth", "target": 100.0}],
            ),
        )
        cluster, mgr, metrics = make_env()
        mgr.submit(job)
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 2, timeout=60)
        metrics.set("default", "el", "gpu_util", 70.0)     # proposes exactly 2
        metrics.set("default", "el", "queue_depth", 10.0)  # proposes 1
        cluster.run_for(60)  # well past the downscale stabilization window
        job = cluster.api.get("PyTorchJob", "default", "el")
        assert job.replica_specs["Worker"].replicas == 2


class TestLiveMetricsAndTPUResize:
    def test_live_pod_annotation_signal_drives_scaling(self):
        """No test pokes the metrics source: pods carry a load profile, the
        ClusterMetricsSource interpolates it as the virtual clock advances,
        and the HPA grows the job end-to-end."""
        import json as _json

        from training_operator_tpu.scheduler.elastic import (
            ANNOTATION_LOAD_PROFILE_PREFIX,
            ClusterMetricsSource,
        )

        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_gpu_pool(8, gpus_per_node=8, nodes_per_nvlink_domain=4))
        DefaultScheduler(cluster)
        SimKubelet(cluster)
        HorizontalAutoscaler(
            cluster, ClusterMetricsSource(cluster),
            sync_period=5.0, stabilization_seconds=10.0,
        )
        GangScheduler(cluster, TPUPacker())
        mgr = OperatorManager(cluster, gang_enabled=True)
        register_all(mgr)

        # max_r=4 pins the fixpoint: after the grow, the new pods' profiles
        # restart at their own start_time (70), the mix averages above
        # target, and an unbounded HPA would keep growing past the asserted
        # size by tick timing.
        job = elastic_job(max_r=4)
        # Utilization starts at target (70) and jumps to 140 at t=+30s.
        profile = _json.dumps([[0, 70.0], [30, 140.0]])
        for spec in job.replica_specs.values():
            spec.template.annotations[
                ANNOTATION_LOAD_PROFILE_PREFIX + "gpu_util"
            ] = profile
        mgr.submit(job)
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 2, timeout=60)
        # Before the ramp nothing scales; after t+30 the signal doubles and
        # desired = ceil(2 * 140/70) = 4.
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 4, timeout=200)
        job = cluster.api.get("PyTorchJob", "default", "el")
        assert job.replica_specs["Worker"].replicas == 4

    def test_tpu_gang_resize_restarts_whole_gang(self):
        """TPU elastic contract: scaling moves in whole-slice units — on
        grow, the gang is re-admitted atomically with more slices and every
        pod restarts with fresh world-size env."""
        from training_operator_tpu.api.jobs import JAXJob, TPUPolicy
        from training_operator_tpu.cluster.inventory import TPU_RESOURCE, make_tpu_pool

        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_tpu_pool(4, slice_topology="2x4"))
        DefaultScheduler(cluster)
        SimKubelet(cluster)
        GangScheduler(cluster, TPUPacker())
        mgr = OperatorManager(cluster, gang_enabled=True)
        register_all(mgr)

        t = PodTemplateSpec(
            containers=[Container(name="jax", image="trainer",
                                  resources={TPU_RESOURCE: 4.0})]
        )
        job = JAXJob(
            metadata=ObjectMeta(name="mesh"),
            replica_specs={"Worker": ReplicaSpec(replicas=2, template=t)},
            tpu_policy=TPUPolicy(accelerator="v5e-8", topology="2x4", num_slices=1),
        )
        mgr.submit(job)
        assert cluster.run_until(lambda: len(worker_pods(cluster, "mesh")) == 2, timeout=60)
        first_gen = {p.metadata.uid for p in worker_pods(cluster, "mesh")}
        assert worker_pods(cluster, "mesh")[0].spec.containers[0].env["NUM_PROCESSES"] == "2"

        # Operator (or HPA) grows the job by one whole slice: 2 -> 4 workers.
        live = cluster.api.get("JAXJob", "default", "mesh")
        live.replica_specs["Worker"].replicas = 4
        cluster.api.update(live)

        assert cluster.run_until(lambda: len(worker_pods(cluster, "mesh")) == 4, timeout=120)
        pods = worker_pods(cluster, "mesh")
        # Whole-gang restart: no first-generation pod survived.
        assert first_gen.isdisjoint({p.metadata.uid for p in pods})
        # Fresh world-size env everywhere.
        assert {p.spec.containers[0].env["NUM_PROCESSES"] for p in pods} == {"4"}
        # The group re-admitted as a 2-slice gang on distinct slices.
        pg = cluster.api.get("PodGroup", "default", "mesh")
        assert pg.num_slices == 2 and pg.phase == PodGroupPhase.RUNNING
        slices_used = {p.node_name.rsplit("-host-", 1)[0] for p in pods}
        assert len(slices_used) == 2
        jj = cluster.api.get("JAXJob", "default", "mesh")
        assert jj.tpu_policy.num_slices == 2

    def test_resize_remesh_restores_trainer_state(self, tmp_path):
        """The full elastic TPU story: train on a small mesh, checkpoint; the
        operator grows the job (whole-gang restart); the trainer rebuilds a
        LARGER mesh for the new world size and resumes from the checkpoint —
        step count carries over and the loss keeps improving."""
        import jax
        import jax.numpy as jnp

        from training_operator_tpu.trainer.checkpoint import (
            Checkpointer,
            restore_into_mesh,
        )
        from training_operator_tpu.trainer.mesh import MeshSpec, batch_sharding, build_mesh
        from training_operator_tpu.trainer.model import TransformerConfig
        from training_operator_tpu.trainer.train import (
            init_train_state,
            make_example_batch,
            make_optimizer,
            make_train_step,
        )

        config = TransformerConfig(
            vocab_size=128, d_model=64, n_layers=1, n_heads=4, n_kv_heads=4,
            d_ff=128, max_seq_len=64,
        )
        devices = jax.devices("cpu")
        optimizer = make_optimizer(total_steps=20)
        key = jax.random.PRNGKey(0)
        batch = make_example_batch(config, batch=4, seq=64, key=key)

        # Phase 1: world size 2 (the 2-worker gang's mesh).
        mesh_a = build_mesh(MeshSpec({"data": 2}), devices[:2])
        state = init_train_state(config, optimizer, key, mesh_a)
        step = make_train_step(config, optimizer, mesh_a)
        losses = []
        for _ in range(4):
            state, metrics = step(state, jax.device_put(batch, batch_sharding(mesh_a)))
            losses.append(float(metrics["loss"]))
        ckpt = Checkpointer(str(tmp_path))
        ckpt.save(state, force=True)
        ckpt.close()

        # Phase 2: the operator grew the gang to 4 workers -> world size 4.
        mesh_b = build_mesh(MeshSpec({"data": 4}), devices[:4])
        resumed = restore_into_mesh(str(tmp_path), config, optimizer, mesh_b)
        assert int(resumed.step) == int(state.step)  # step carried over
        step_b = make_train_step(config, optimizer, mesh_b)
        for _ in range(4):
            resumed, metrics = step_b(
                resumed, jax.device_put(batch, batch_sharding(mesh_b))
            )
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]  # loss kept improving across the resize


class TestVersionedElasticWrites:
    def test_hpa_write_racing_status_write_loses_neither(self):
        """An HPA scale write racing a reconciler status write: with
        version-checked updates the conflict is detected, the HPA re-reads
        and re-applies, and BOTH the status change and the resize survive."""
        cluster, mgr, metrics = make_env(gang=False)
        mgr.submit(elastic_job())
        assert cluster.run_until(lambda: len(worker_pods(cluster, "el")) == 2, timeout=60)

        from training_operator_tpu.scheduler.elastic import HorizontalAutoscaler

        hpa_loop = HorizontalAutoscaler(
            cluster, metrics, sync_period=1e9  # driven manually below
        )
        hpa = next(iter(cluster.api.list("HorizontalPodAutoscaler")))

        class RacingSource:
            """Between the HPA's job read and its write, a 'reconciler'
            lands a status update — exactly the interleaving last-write-wins
            used to destroy."""

            def __init__(self, api):
                self.api = api
                self.fired = False

            def get(self, namespace, target, metric):
                if not self.fired:
                    self.fired = True
                    j = self.api.get("PyTorchJob", namespace, target)
                    j.status.last_reconcile_time = 12345.0
                    self.api.update(j, check_version=True)
                return 140.0  # desired = ceil(2 * 140/70) = 4

        hpa_loop.metrics = RacingSource(cluster.api)
        hpa_loop._sync_one(hpa, now=cluster.clock.now())

        j = cluster.api.get("PyTorchJob", "default", "el")
        assert j.replica_specs["Worker"].replicas == 4  # resize landed
        assert j.status.last_reconcile_time == 12345.0  # status NOT lost

    def test_v2_trainjob_resize_derives_num_slices(self):
        """ADVICE r2: scaling a TrainJob's num_nodes must propagate a
        CONSISTENT workload — replicas and tpu_policy.num_slices move
        together (whole-slice contract), so the v2 controller's full-spec
        propagation converges instead of reverting the resize."""
        from training_operator_tpu.api.jobs import TPUPolicy
        from training_operator_tpu.cluster.inventory import TPU_RESOURCE, make_tpu_pool
        from training_operator_tpu.runtime.api import (
            ClusterTrainingRuntime,
            MLPolicy,
            ReplicatedJobTemplate,
            RuntimeRef,
            TRAINER_NODE,
            Trainer,
            TrainingRuntimeSpec,
            TrainJob,
        )
        from training_operator_tpu.runtime.controller import TrainJobManager

        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_tpu_pool(4, slice_topology="2x4"))
        DefaultScheduler(cluster)
        SimKubelet(cluster)
        GangScheduler(cluster, TPUPacker())
        mgr = OperatorManager(cluster, gang_enabled=True)
        register_all(mgr)
        v2 = TrainJobManager(cluster)

        rt = ClusterTrainingRuntime(
            metadata=ObjectMeta(name="tpu-rt", namespace=""),
            spec=TrainingRuntimeSpec(
                ml_policy=MLPolicy(
                    num_nodes=2,
                    tpu=TPUPolicy(accelerator="v5e-8", topology="2x4", num_slices=1),
                ),
                template=[
                    ReplicatedJobTemplate(
                        name=TRAINER_NODE, replicas=2,
                        template=PodTemplateSpec(
                            containers=[Container(name="trainer", image="trainer",
                                                  resources={TPU_RESOURCE: 4.0})]
                        ),
                    )
                ],
            ),
        )
        v2.submit(rt)
        tj = TrainJob(
            metadata=ObjectMeta(name="tj-elastic"),
            runtime_ref=RuntimeRef(name="tpu-rt"),
        )
        v2.submit(tj)
        assert cluster.run_until(
            lambda: len(worker_pods(cluster, "tj-elastic")) == 2, timeout=60
        )
        wl = cluster.api.get("JAXJob", "default", "tj-elastic")
        assert wl.tpu_policy.num_slices == 1

        # Elastic resize at the v2 surface: num_nodes 2 -> 4 (one more slice).
        live = cluster.api.get("TrainJob", "default", "tj-elastic")
        live.trainer = Trainer(num_nodes=4)
        cluster.api.update(live)

        def resized():
            w = cluster.api.try_get("JAXJob", "default", "tj-elastic")
            return (
                w is not None
                and w.replica_specs["Worker"].replicas == 4
                and w.tpu_policy.num_slices == 2
                and len(worker_pods(cluster, "tj-elastic")) == 4
            )

        assert cluster.run_until(resized, timeout=200)
        # And it CONVERGES: more reconciles don't flap it back.
        cluster.run_for(30)
        w = cluster.api.get("JAXJob", "default", "tj-elastic")
        assert w.replica_specs["Worker"].replicas == 4
        assert w.tpu_policy.num_slices == 2
