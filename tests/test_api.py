"""API layer tests: defaulting + validation.

Parity model: reference pkg/apis/kubeflow.org/v1/pytorch_defaults_test.go,
mpi_validation_test.go, and pkg/webhooks/* table-driven tests.
"""

import pytest

from training_operator_tpu.api.common import (
    Container,
    JobConditionType,
    JobStatus,
    PodTemplateSpec,
    ReplicaSpec,
    RestartPolicy,
    update_job_conditions,
    is_finished,
    has_condition,
)
from training_operator_tpu.api.defaults import default_job
from training_operator_tpu.api.jobs import (
    ElasticPolicy,
    JAXJob,
    MPIJob,
    ObjectMeta,
    PyTorchJob,
    TFJob,
    TPUPolicy,
)
from training_operator_tpu.api.validation import ValidationError, validate_job


def make_jaxjob(name="jax-test", workers=2, image="jax:latest"):
    return JAXJob(
        metadata=ObjectMeta(name=name),
        replica_specs={
            "Worker": ReplicaSpec(
                replicas=workers,
                template=PodTemplateSpec(containers=[Container(name="jax", image=image)]),
            )
        },
    )


class TestDefaults:
    def test_replicas_default_to_one(self):
        job = JAXJob(
            metadata=ObjectMeta(name="j"),
            replica_specs={
                "Worker": ReplicaSpec(
                    template=PodTemplateSpec(containers=[Container(name="jax", image="i")])
                )
            },
        )
        default_job(job)
        assert job.replica_specs["Worker"].replicas == 1

    def test_restart_policy_defaulted(self):
        job = make_jaxjob()
        default_job(job)
        assert job.replica_specs["Worker"].restart_policy == RestartPolicy.ON_FAILURE

    def test_default_port_injected(self):
        job = make_jaxjob()
        default_job(job)
        c = job.replica_specs["Worker"].template.main_container("jax")
        assert c.ports["jaxjob-port"] == 6666

    def test_uid_and_creation_time_set(self):
        job = default_job(make_jaxjob())
        assert job.uid
        assert job.metadata.creation_time is not None

    def test_elastic_policy_defaults(self):
        job = PyTorchJob(
            metadata=ObjectMeta(name="pt"),
            replica_specs={
                "Worker": ReplicaSpec(
                    replicas=4,
                    template=PodTemplateSpec(containers=[Container(name="pytorch", image="i")]),
                )
            },
            elastic_policy=ElasticPolicy(),
        )
        default_job(job)
        assert job.elastic_policy.max_restarts == 10
        assert job.elastic_policy.min_replicas == 4
        assert job.elastic_policy.max_replicas == 4

    def test_idempotent(self):
        job = default_job(make_jaxjob())
        uid = job.uid
        default_job(job)
        assert job.uid == uid


class TestValidation:
    def test_valid_job_passes(self):
        validate_job(default_job(make_jaxjob()))

    def test_bad_name_rejected(self):
        job = default_job(make_jaxjob(name="Bad_Name"))
        with pytest.raises(ValidationError, match="RFC1035"):
            validate_job(job)

    def test_missing_image_rejected(self):
        job = default_job(make_jaxjob(image=""))
        with pytest.raises(ValidationError, match="image"):
            validate_job(job)

    def test_missing_replica_specs_rejected(self):
        job = JAXJob(metadata=ObjectMeta(name="j"))
        with pytest.raises(ValidationError, match="at least one replica type"):
            validate_job(job)

    def test_wrong_replica_type_rejected(self):
        job = make_jaxjob()
        job.replica_specs["Master"] = job.replica_specs["Worker"]
        with pytest.raises(ValidationError, match="invalid replica type"):
            validate_job(default_job(job))

    def test_wrong_container_name_rejected(self):
        job = JAXJob(
            metadata=ObjectMeta(name="j"),
            replica_specs={
                "Worker": ReplicaSpec(
                    template=PodTemplateSpec(containers=[Container(name="main", image="i")])
                )
            },
        )
        with pytest.raises(ValidationError, match="container named 'jax'"):
            validate_job(job)

    def test_multi_slice_requires_divisible_workers(self):
        job = default_job(make_jaxjob(workers=3))
        job.tpu_policy = TPUPolicy(accelerator="v5e-16", topology="4x4", num_slices=2)
        with pytest.raises(ValidationError, match="divisible"):
            validate_job(job)
        job4 = default_job(make_jaxjob(workers=4))
        job4.tpu_policy = TPUPolicy(accelerator="v5e-16", topology="4x4", num_slices=2)
        validate_job(job4)

    def test_mpi_requires_single_launcher(self):
        job = MPIJob(
            metadata=ObjectMeta(name="m"),
            replica_specs={
                "Launcher": ReplicaSpec(
                    replicas=2,
                    template=PodTemplateSpec(containers=[Container(name="mpi", image="i")]),
                ),
                "Worker": ReplicaSpec(
                    replicas=2,
                    template=PodTemplateSpec(containers=[Container(name="mpi", image="i")]),
                ),
            },
        )
        with pytest.raises(ValidationError, match="Launcher"):
            validate_job(default_job(job))

    def test_tf_chief_and_master_conflict(self):
        job = TFJob(
            metadata=ObjectMeta(name="tf"),
            replica_specs={
                t: ReplicaSpec(
                    replicas=1,
                    template=PodTemplateSpec(containers=[Container(name="tensorflow", image="i")]),
                )
                for t in ("Chief", "Master", "Worker")
            },
        )
        with pytest.raises(ValidationError, match="Chief/Master"):
            validate_job(default_job(job))

    def test_elastic_min_max_ordering(self):
        job = PyTorchJob(
            metadata=ObjectMeta(name="pt"),
            replica_specs={
                "Worker": ReplicaSpec(
                    replicas=2,
                    template=PodTemplateSpec(containers=[Container(name="pytorch", image="i")]),
                )
            },
            elastic_policy=ElasticPolicy(min_replicas=4, max_replicas=2),
        )
        with pytest.raises(ValidationError, match="maxReplicas"):
            validate_job(job)

    def test_tpu_policy_mesh_axes_must_match_chips(self):
        job = make_jaxjob()
        job.tpu_policy = TPUPolicy(accelerator="v5e-8", mesh_axes={"data": 2, "tensor": 2})
        with pytest.raises(ValidationError, match="meshAxes"):
            validate_job(default_job(job))

    def test_tpu_policy_valid(self):
        job = make_jaxjob()
        job.tpu_policy = TPUPolicy(
            accelerator="v5e-8", topology="2x4", mesh_axes={"data": 2, "tensor": 4}
        )
        validate_job(default_job(job))
        assert job.tpu_policy.total_chips() == 8


class TestConditions:
    def test_condition_transitions(self):
        st = JobStatus()
        update_job_conditions(st, JobConditionType.CREATED, True, "JobCreated", "created", now=1.0)
        update_job_conditions(st, JobConditionType.RUNNING, True, "JobRunning", "running", now=2.0)
        assert has_condition(st, JobConditionType.RUNNING)
        assert not is_finished(st)
        update_job_conditions(st, JobConditionType.SUCCEEDED, True, "JobSucceeded", "done", now=3.0)
        assert is_finished(st)
        # Running cleared when terminal condition set.
        assert not has_condition(st, JobConditionType.RUNNING)

    def test_restarting_clears_running(self):
        st = JobStatus()
        update_job_conditions(st, JobConditionType.RUNNING, True, "JobRunning", "", now=1.0)
        update_job_conditions(st, JobConditionType.RESTARTING, True, "Restart", "", now=2.0)
        assert not has_condition(st, JobConditionType.RUNNING)
        update_job_conditions(st, JobConditionType.RUNNING, True, "JobRunning", "", now=3.0)
        assert not has_condition(st, JobConditionType.RESTARTING)

    def test_duplicate_update_is_noop(self):
        """Identical updates leave the condition untouched (so unchanged
        reconcile passes produce byte-identical status and skip the API
        write); a changed message bumps lastUpdateTime but not transition."""
        st = JobStatus()
        update_job_conditions(st, JobConditionType.CREATED, True, "JobCreated", "", now=1.0)
        update_job_conditions(st, JobConditionType.CREATED, True, "JobCreated", "", now=5.0)
        assert len(st.conditions) == 1
        assert st.conditions[0].last_update_time == 1.0
        assert st.conditions[0].last_transition_time == 1.0
        update_job_conditions(st, JobConditionType.CREATED, True, "JobCreated", "new", now=9.0)
        assert st.conditions[0].last_update_time == 9.0
        assert st.conditions[0].last_transition_time == 1.0


class TestSerialization:
    def test_status_roundtrip(self):
        st = JobStatus()
        update_job_conditions(st, JobConditionType.CREATED, True, "JobCreated", "msg", now=1.0)
        d = st.to_dict()
        st2 = JobStatus.from_dict(d)
        assert st2.conditions[0].type == JobConditionType.CREATED
        assert st2.conditions[0].status is True

    def test_replica_spec_roundtrip(self):
        rs = ReplicaSpec(
            replicas=3,
            template=PodTemplateSpec(
                containers=[Container(name="jax", image="i", env={"A": "1"}, ports={"p": 1})]
            ),
            restart_policy=RestartPolicy.EXIT_CODE,
        )
        rs2 = ReplicaSpec.from_dict(rs.to_dict())
        assert rs2.replicas == 3
        assert rs2.restart_policy == RestartPolicy.EXIT_CODE
        assert rs2.template.containers[0].env == {"A": "1"}
