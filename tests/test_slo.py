"""SLO engine (PR 19 tentpole): sliding-window histograms, SLOPolicy
admission, multi-window burn-rate evaluation with once-per-incident
events, per-job latency attribution (`explain`), the owning-shard routing
of the timeline/explain read plane, and the merged chrome-trace export.

The two acceptance properties this file pins:

- attribution rows sum EXACTLY to the job's measured time-to-running (the
  deterministic preempted + node-loss scenario in TestAttribution), and
- a breach that persists across evaluations is ONE SLOBurnRate incident
  event, not one event per pass.
"""

import json

import pytest

from training_operator_tpu.api.jobs import JAXJob, ObjectMeta
from training_operator_tpu.api.validation import ValidationError
from training_operator_tpu.cluster import wire
from training_operator_tpu.cluster.httpapi import (
    ApiHTTPServer,
    RemoteAPIServer,
    ShardedRemoteAPIServer,
)
from training_operator_tpu.cluster.objects import Event
from training_operator_tpu.cluster.runtime import Cluster, VirtualClock
from training_operator_tpu.cluster.shards import CLUSTER_SCOPED_KINDS, shard_for
from training_operator_tpu.observe import (
    SLOEvaluator,
    SLOObjective,
    SLOPolicy,
    attribute,
    explain,
    export_chrome_trace_merged,
    register_slo_admission,
    render_explain,
    render_slo,
    validate_slo_policy,
)
from training_operator_tpu.observe.attribution import (
    CAUSE_CONTROL_PLANE,
    CAUSE_NODE_LOSS_RECOVERY,
    CAUSE_PREEMPTION_DISPLACEMENT,
    CAUSE_PRIORITY_WAIT,
    CAUSE_STARTUP,
    CAUSES,
    aggregate_queue_shares,
)
from training_operator_tpu.observe.slo import _good_count
from training_operator_tpu.observe.timeline import TimelineStore
from training_operator_tpu.sdk import TrainingClient
from training_operator_tpu.utils import metrics
from training_operator_tpu.utils.metrics import (
    LabeledSlidingWindowHistogram,
    MetricsRegistry,
    SlidingWindowHistogram,
)

# crc32 pins for num_shards=2 (test_store_shards.py uses the same pair).
NS_S0 = "alpha"   # -> shard 0
NS_S1 = "beta"    # -> shard 1


def _policy(name="slo-ttr", **obj_kw):
    kw = dict(name="ttr", metric="time_to_running",
              threshold_seconds=60.0, target=0.9)
    kw.update(obj_kw)
    return SLOPolicy(metadata=ObjectMeta(name=name),
                     objectives=[SLOObjective(**kw)])


def _parse_render(lines):
    """'name{labels} value' sample lines -> dict, skipping # HELP/# TYPE."""
    out = {}
    for line in lines:
        if line.startswith("#"):
            continue
        key, _, value = line.rpartition(" ")
        out[key] = float(value)
    return out


# ---------------------------------------------------------------------------
# Sliding-window histograms (the metrics substrate)
# ---------------------------------------------------------------------------


class TestSlidingWindowHistogram:
    def test_windowed_view_vs_full_retention(self):
        h = SlidingWindowHistogram("t_sw", "", buckets=(1.0, 10.0),
                                   window_seconds=60.0, num_windows=10)
        h.observe(0.5, now=0.0)      # window 0
        h.observe(5.0, now=130.0)    # window 2
        full = h.cumulative_buckets()
        assert full[-1] == (float("inf"), 2)
        recent = h.cumulative_buckets(window_seconds=60.0, now=130.0)
        assert recent[-1] == (float("inf"), 1), "trailing window only"
        assert recent[0] == (1.0, 0), "the old <=1.0 obs is outside it"

    def test_retention_expiry_via_advance(self):
        h = SlidingWindowHistogram("t_exp", "", buckets=(1.0,),
                                   window_seconds=60.0, num_windows=3)
        h.observe(0.5, now=0.0)
        assert h.cumulative_buckets()[-1][1] == 1
        h.advance(1000.0)  # > 3 windows later: retention dropped it
        assert h.cumulative_buckets()[-1][1] == 0

    def test_stale_observation_folds_into_newest_window(self):
        h = SlidingWindowHistogram("t_st", "", buckets=(1.0,),
                                   window_seconds=60.0, num_windows=3)
        h.observe(0.5, now=600.0)
        h.observe(0.6, now=0.0)  # older than retention: folds, not lost
        assert h.cumulative_buckets()[-1][1] == 2

    def test_render_and_snapshot_expose_the_same_view(self):
        """The one-view rule: text and JSON exposition derive from the same
        cumulative_buckets() output — identical keys, identical values."""
        h = SlidingWindowHistogram("t_agree", "help", buckets=(1.0, 5.0))
        for v, t in ((0.5, 0.0), (3.0, 10.0), (99.0, 20.0)):
            h.observe(v, now=t)
        rendered = _parse_render(h.render())
        snap = h.snapshot_items()
        assert rendered == snap
        assert snap['t_agree_bucket{le="1.0"}'] == 1.0
        assert snap['t_agree_bucket{le="+Inf"}'] == 3.0
        assert snap["t_agree_count"] == 3.0
        assert snap["t_agree_sum"] == pytest.approx(102.5)

    def test_labeled_family_splices_and_agrees(self):
        fam = LabeledSlidingWindowHistogram(
            "t_fam", "", ("queue", "kind"), buckets=(1.0,))
        fam.observe(0.5, "q0", "JAXJob", now=0.0)
        fam.observe(2.0, "q1", "JAXJob", now=0.0)
        assert [lbls for lbls, _ in fam.children()] == [
            ("q0", "JAXJob"), ("q1", "JAXJob")]
        rendered = _parse_render(fam.render())
        assert rendered == fam.snapshot_items()
        assert rendered[
            't_fam_bucket{queue="q0",kind="JAXJob",le="1.0"}'] == 1.0

    def test_registry_duplicate_guard(self):
        reg = MetricsRegistry()
        a = reg.sliding_histogram("dup_sw", "", buckets=(1.0,),
                                  window_seconds=30.0)
        assert reg.sliding_histogram("dup_sw", "", buckets=(1.0,),
                                     window_seconds=30.0) is a
        with pytest.raises(ValueError):
            reg.sliding_histogram("dup_sw", "", buckets=(1.0,),
                                  window_seconds=60.0)


# ---------------------------------------------------------------------------
# SLOPolicy: kind registration, admission, codec
# ---------------------------------------------------------------------------


class TestSLOPolicy:
    def test_valid_policy_passes(self):
        validate_slo_policy(_policy())

    @pytest.mark.parametrize("bad", [
        dict(name=""),
        dict(metric="made_up"),
        dict(threshold_seconds=0.0),
        dict(target=1.0),
        dict(target=0.0),
        dict(fast_window_seconds=0.0),
        dict(fast_window_seconds=600.0, slow_window_seconds=600.0),
        dict(burn_threshold=0.0),
    ])
    def test_bad_objective_rejected(self, bad):
        with pytest.raises(ValidationError):
            validate_slo_policy(_policy(**bad))

    def test_no_objectives_rejected(self):
        with pytest.raises(ValidationError):
            validate_slo_policy(
                SLOPolicy(metadata=ObjectMeta(name="empty")))

    def test_admission_forces_cluster_scope(self):
        cluster = Cluster(VirtualClock())
        register_slo_admission(cluster.api)
        p = _policy()
        p.metadata.namespace = "some-team"
        cluster.api.create(p)
        assert cluster.api.get("SLOPolicy", "", "slo-ttr") is not None

    def test_admission_rejects_malformed(self):
        cluster = Cluster(VirtualClock())
        register_slo_admission(cluster.api)
        with pytest.raises(ValidationError):
            cluster.api.create(_policy(threshold_seconds=-1.0))

    def test_codec_round_trip_preserves_objectives(self):
        p = _policy(queue="prod", kind="JAXJob", burn_threshold=2.0)
        back = wire.decode(wire.encode(p))
        assert isinstance(back, SLOPolicy)
        assert len(back.objectives) == 1
        obj = back.objectives[0]
        assert isinstance(obj, SLOObjective)
        assert (obj.queue, obj.kind, obj.burn_threshold) == (
            "prod", "JAXJob", 2.0)

    def test_pinned_to_the_meta_shard(self):
        assert "SLOPolicy" in CLUSTER_SCOPED_KINDS
        for meta in (0, 1, 2):
            assert shard_for("SLOPolicy", "anything", 3, meta) == meta


# ---------------------------------------------------------------------------
# Burn-rate evaluation
# ---------------------------------------------------------------------------


class TestBurnRate:
    def test_good_count_interpolates_inside_the_straddling_bucket(self):
        view = [(1.0, 5), (2.0, 10), (float("inf"), 12)]
        assert _good_count(view, 1.5) == pytest.approx(7.5)
        assert _good_count(view, 2.0) == 10.0

    def test_inf_residue_is_conservatively_bad(self):
        view = [(1.0, 5), (2.0, 10), (float("inf"), 12)]
        assert _good_count(view, 100.0) == 10.0

    # Each test pins its objective to a unique queue selector: the metric
    # families are process-global, and suite neighbours observe into them
    # (some at wall-clock scale, which folds later virtual-clock samples
    # into THEIR newest window) — a per-test child is the isolation seam.
    def _seed(self, epoch, good, bad, queue, threshold=60.0):
        for i in range(good):
            metrics.slo_time_to_running_window.observe(
                threshold / 2.0, queue, "JAXJob", now=epoch + i)
        for i in range(bad):
            metrics.slo_time_to_running_window.observe(
                threshold * 10, queue, "JAXJob", now=epoch + i)

    def test_attainment_burn_and_budget(self):
        epoch = 10_000_000.0
        cluster = Cluster(VirtualClock())
        register_slo_admission(cluster.api)
        cluster.api.create(_policy(target=0.9, queue="brq-att"))
        self._seed(epoch, good=8, bad=2, queue="brq-att")
        ev = SLOEvaluator(cluster.api, cluster.clock.now)
        section = ev.evaluate(epoch + 10)
        [row] = section["objectives"]
        assert row["attainment"] == pytest.approx(0.8)
        # bad_fraction 0.2 over a 0.1 budget: 2x in both windows.
        assert row["burn_fast"] == pytest.approx(2.0)
        assert row["burn_slow"] == pytest.approx(2.0)
        assert row["budget_remaining"] == 0.0
        assert row["burning"] is True
        assert section["incidents"] == 1

    def test_incident_event_fires_once_per_incident(self):
        epoch = 20_000_000.0
        cluster = Cluster(VirtualClock())
        register_slo_admission(cluster.api)
        cluster.api.create(_policy(target=0.9, queue="brq-inc"))
        self._seed(epoch, good=0, bad=5, queue="brq-inc")
        ev = SLOEvaluator(cluster.api, cluster.clock.now)
        for dt in (10, 20, 30):  # persisting breach: one incident
            ev.evaluate(epoch + dt)
        [burn] = cluster.api.events(reason="SLOBurnRate")
        assert burn.count == 1, "three burning passes, ONE incident event"
        assert burn.event_type == "Warning"
        assert burn.object_kind == "SLOPolicy"
        # Recovery (windows age out), then a NEW breach: a second incident.
        # The server aggregates same-key events, so it shows as count=2.
        recovered = ev.evaluate(epoch + 40_000)
        assert recovered["incidents"] == 0
        self._seed(epoch + 50_000, good=0, bad=5, queue="brq-inc")
        ev.evaluate(epoch + 50_010)
        [burn] = cluster.api.events(reason="SLOBurnRate")
        assert burn.count == 2

    def test_no_data_means_attained_not_burning(self):
        epoch = 30_000_000.0
        cluster = Cluster(VirtualClock())
        register_slo_admission(cluster.api)
        cluster.api.create(_policy(queue="no-such-queue"))
        ev = SLOEvaluator(cluster.api, cluster.clock.now, enable_events=False)
        [row] = ev.evaluate(epoch)["objectives"]
        assert row["attainment"] == 1.0
        assert row["burning"] is False
        assert row["samples_slow"] == 0

    def test_gauges_published_and_zeroed_when_policy_removed(self):
        epoch = 40_000_000.0
        cluster = Cluster(VirtualClock())
        register_slo_admission(cluster.api)
        cluster.api.create(_policy(name="gauged", target=0.9,
                                   queue="brq-gau"))
        self._seed(epoch, good=4, bad=0, queue="brq-gau")
        ev = SLOEvaluator(cluster.api, cluster.clock.now, enable_events=False)
        ev.evaluate(epoch + 5)
        snap = metrics.registry.snapshot()
        key = ('training_slo_attainment_ratio'
               '{policy="gauged",objective="ttr",queue="brq-gau"}')
        assert snap[key] == 1.0
        cluster.api.delete("SLOPolicy", "", "gauged")
        ev.evaluate(epoch + 10)
        assert metrics.registry.snapshot()[key] == 0.0

    def test_render_slo_names_burning_objectives(self):
        epoch = 50_000_000.0
        cluster = Cluster(VirtualClock())
        register_slo_admission(cluster.api)
        cluster.api.create(_policy(target=0.9, queue="brq-ren"))
        self._seed(epoch, good=0, bad=4, queue="brq-ren")
        ev = SLOEvaluator(cluster.api, cluster.clock.now, enable_events=False)
        text = render_slo(ev.evaluate(epoch + 5))
        assert "ttr" in text and "BURNING" in text


# ---------------------------------------------------------------------------
# Attribution: the deterministic decomposition
# ---------------------------------------------------------------------------


def _span(name, start, end, wall=0.0):
    return {"name": name, "start": start, "end": end, "wall": wall,
            "attrs": {}}


def _event(reason, t, name="job-a", ns="default", etype="Warning"):
    return Event(object_kind="PodGroup", object_name=name, namespace=ns,
                 event_type=etype, reason=reason, message=reason,
                 timestamp=t, first_timestamp=t)


class _FakePodGroup:
    queue = "prod"


class TestAttribution:
    def test_preempted_plus_node_loss_sums_exactly_to_ttr(self):
        """THE acceptance property: a job that was preempted AND displaced
        by node loss itemizes causes that sum exactly to its measured
        time-to-running."""
        timeline = {
            "namespace": "default", "name": "job-a",
            "spans": [
                _span("time_to_running", 0.0, 100.0),
                _span("admission", 0.0, 0.0, wall=2.0),
                _span("gang_solve", 4.0, 5.0, wall=1.0),
                _span("node_evict", 50.0, 50.5),
            ],
            "marks": {},
        }
        events = [
            _event("Preempted", 10.0),
            _event("GangAdmitted", 40.0, etype="Normal"),
            _event("GangAdmitted", 90.0, etype="Normal"),
        ]
        report = attribute(timeline, events, podgroup=_FakePodGroup(),
                           now=100.0)
        assert report["running"] is True
        assert report["time_to_running_seconds"] == pytest.approx(100.0)
        rows = {r["cause"]: r["seconds"] for r in report["causes"]}
        assert sum(rows.values()) == pytest.approx(100.0, abs=1e-9)
        assert rows[CAUSE_NODE_LOSS_RECOVERY] == pytest.approx(40.0)
        assert rows[CAUSE_PREEMPTION_DISPLACEMENT] == pytest.approx(30.0)
        assert rows[CAUSE_STARTUP] == pytest.approx(22.0)
        assert rows[CAUSE_PRIORITY_WAIT] == pytest.approx(5.0)
        assert rows[CAUSE_CONTROL_PLANE] == pytest.approx(3.0)
        # Shares are the same decomposition, normalized.
        assert sum(r["share"] for r in report["causes"]) == pytest.approx(1.0)
        # Every cause is drawn from the registered taxonomy (CL013).
        assert all(r["cause"] in CAUSES for r in report["causes"])

    def test_live_job_window_ends_now(self):
        timeline = {"namespace": "d", "name": "j",
                    "spans": [_span("admission", 5.0, 5.5)], "marks": {}}
        report = attribute(timeline, [], now=30.0, created=0.0)
        assert report["running"] is False
        assert report["window"] == [0.0, 30.0]
        assert sum(
            r["seconds"] for r in report["causes"]) == pytest.approx(30.0)

    def test_empty_timeline_is_a_zero_window(self):
        report = attribute(None, [], now=7.0)
        assert report["time_to_running_seconds"] == 0.0
        assert report["causes"] == []

    def test_rows_sorted_by_seconds_desc(self):
        timeline = {"namespace": "d", "name": "j",
                    "spans": [_span("time_to_running", 0.0, 50.0)],
                    "marks": {}}
        report = attribute(timeline, [_event("Preempted", 10.0, name="j")],
                           now=50.0)
        secs = [r["seconds"] for r in report["causes"]]
        assert secs == sorted(secs, reverse=True)


class TestExplainSurfaces:
    def _seeded_cluster(self):
        cluster = Cluster(VirtualClock())
        tls = cluster.api.timelines
        tls.record_span("default", "job-a", "u1", "time_to_running",
                        0.0, 100.0)
        tls.record_span("default", "job-a", "u1", "gang_solve",
                        4.0, 5.0, wall=1.0)
        cluster.api.record_event(_event("Preempted", 10.0))
        cluster.api.record_event(_event("GangAdmitted", 40.0, etype="Normal"))
        return cluster

    def test_explain_against_the_in_process_api(self):
        cluster = self._seeded_cluster()
        report = explain(cluster.api, "default", "job-a")
        assert report["name"] == "job-a"
        rows = {r["cause"]: r["seconds"] for r in report["causes"]}
        assert sum(rows.values()) == pytest.approx(100.0)
        assert rows[CAUSE_PREEMPTION_DISPLACEMENT] == pytest.approx(30.0)
        text = render_explain(report)
        assert "job-a" in text and "preemption_displacement" in text

    def test_sdk_explain_job_and_get_slo(self):
        cluster = self._seeded_cluster()
        client = TrainingClient(cluster)
        report = client.explain_job("job-a")
        assert report["time_to_running_seconds"] == pytest.approx(100.0)
        register_slo_admission(cluster.api)
        client.create_slo_policy(_policy())
        assert [p.name for p in client.list_slo_policies()] == ["slo-ttr"]
        section = client.get_slo()
        assert section["policies"] == 1

    def test_aggregate_queue_shares_normalizes_per_queue(self):
        cluster = self._seeded_cluster()
        shares = aggregate_queue_shares(cluster.api, now=100.0)
        assert "default" in shares
        assert sum(shares["default"].values()) == pytest.approx(1.0)
        assert set(shares["default"]) <= set(CAUSES)


# ---------------------------------------------------------------------------
# Wire routes: /slo, /explain, /timelines (bare), and owning-shard routing
# ---------------------------------------------------------------------------


@pytest.fixture()
def wire_pair():
    cluster = Cluster(VirtualClock())
    register_slo_admission(cluster.api)
    server = ApiHTTPServer(cluster.api, port=0)
    try:
        yield cluster, RemoteAPIServer(server.url, timeout=10.0)
    finally:
        server.close()


class TestWireRoutes:
    def test_get_slo_route(self, wire_pair):
        cluster, remote = wire_pair
        cluster.api.create(_policy())
        section = remote.get_slo()
        assert section["policies"] == 1
        assert [r["objective"] for r in section["objectives"]] == ["ttr"]

    def test_explain_route(self, wire_pair):
        cluster, remote = wire_pair
        cluster.api.timelines.record_span(
            "default", "job-w", "u1", "time_to_running", 0.0, 42.0)
        report = remote.explain("default", "job-w")
        assert report["time_to_running_seconds"] == pytest.approx(42.0)
        assert sum(
            r["seconds"] for r in report["causes"]) == pytest.approx(42.0)

    def test_bare_timelines_route_lists_all(self, wire_pair):
        cluster, remote = wire_pair
        for n in ("t-a", "t-b"):
            cluster.api.timelines.record_span(
                "default", n, "u", "bind", 1.0, 2.0)
        names = {tl["name"] for tl in remote.get_timelines()}
        assert names == {"t-a", "t-b"}


@pytest.fixture()
def shard_pair():
    """Two live shard hosts + the router over them (shard 0 = meta)."""
    clusters = [Cluster(), Cluster()]
    servers = [ApiHTTPServer(c.api, port=0) for c in clusters]
    for c in clusters:
        register_slo_admission(c.api)
    router = ShardedRemoteAPIServer(
        shard_addresses=[[s.url] for s in servers], timeout=5.0
    )
    try:
        yield clusters, servers, router
    finally:
        for s in servers:
            s.close()


class TestShardedObservabilityRouting:
    def _seed_timeline(self, cluster, ns, name, end=50.0):
        cluster.api.timelines.record_span(
            ns, name, "u", "time_to_running", 0.0, end)

    def test_get_timeline_routes_to_the_owning_shard(self, shard_pair):
        clusters, _, router = shard_pair
        self._seed_timeline(clusters[0], NS_S0, "job-a0")
        self._seed_timeline(clusters[1], NS_S1, "job-b1", end=70.0)
        # Round-trip from each shard through the one router.
        tl0 = router.get_timeline(NS_S0, "job-a0")
        tl1 = router.get_timeline(NS_S1, "job-b1")
        assert tl0["spans"][0]["end"] == 50.0
        assert tl1["spans"][0]["end"] == 70.0
        # The non-owning shard genuinely does not hold the timeline.
        assert clusters[1].api.get_timeline(NS_S0, "job-a0") is None

    def test_get_timelines_fans_out_and_tags_the_shard(self, shard_pair):
        clusters, _, router = shard_pair
        self._seed_timeline(clusters[0], NS_S0, "job-a0")
        self._seed_timeline(clusters[1], NS_S1, "job-b1")
        merged = router.get_timelines()
        by_name = {tl["name"]: tl["shard"] for tl in merged}
        assert by_name == {"job-a0": 0, "job-b1": 1}

    def test_explain_served_from_the_owning_shard(self, shard_pair):
        clusters, _, router = shard_pair
        self._seed_timeline(clusters[1], NS_S1, "job-b1", end=100.0)
        # Evidence co-lives on the owning shard: events route there too.
        clusters[1].api.record_event(
            _event("Preempted", 10.0, name="job-b1", ns=NS_S1))
        clusters[1].api.record_event(
            _event("GangAdmitted", 40.0, name="job-b1", ns=NS_S1,
                   etype="Normal"))
        report = router.explain(NS_S1, "job-b1")
        rows = {r["cause"]: r["seconds"] for r in report["causes"]}
        assert sum(rows.values()) == pytest.approx(100.0)
        assert rows[CAUSE_PREEMPTION_DISPLACEMENT] == pytest.approx(30.0)

    def test_get_slo_comes_from_the_meta_shard(self, shard_pair):
        clusters, _, router = shard_pair
        router.create(_policy())  # cluster-scoped -> meta shard (0)
        assert len(clusters[0].api.list("SLOPolicy")) == 1
        assert len(clusters[1].api.list("SLOPolicy")) == 0
        section = router.get_slo()
        assert section["policies"] == 1

    def test_describe_round_trips_through_the_router(self, shard_pair):
        clusters, _, router = shard_pair
        from training_operator_tpu.observe import render_describe

        router.create(JAXJob(metadata=ObjectMeta(name="dj", namespace=NS_S1)))
        clusters[1].api.record_event(
            _event("GangAdmitted", 1.0, name="dj", ns=NS_S1, etype="Normal"))
        text = render_describe(router, NS_S1, "dj")
        assert "dj" in text and "GangAdmitted" in text


# ---------------------------------------------------------------------------
# Merged chrome-trace export
# ---------------------------------------------------------------------------


class TestMergedChromeTrace:
    def test_sources_become_processes_jobs_become_threads(self, tmp_path):
        s0, s1 = TimelineStore(), TimelineStore()
        s0.record_span("a", "j0", "u", "bind", 1.0, 2.0)
        s0.record_span("a", "j1", "u", "bind", 2.0, 3.0)
        s1.record_span("b", "j2", "u", "gang_solve", 0.0, 0.0, wall=1.5)
        out = str(tmp_path / "merged.json")
        doc = export_chrome_trace_merged(
            {"shard-1": s1, "shard-0": s0}, out)
        with open(out) as f:
            assert json.load(f) == doc
        procs = {e["args"]["name"]: e["pid"] for e in doc["traceEvents"]
                 if e["name"] == "process_name"}
        assert procs == {"shard-0": 1, "shard-1": 2}, "sorted labels -> pids"
        threads = {(e["pid"], e["tid"]): e["args"]["name"]
                   for e in doc["traceEvents"] if e["name"] == "thread_name"}
        assert threads[(1, 1)] == "a/j0"
        assert threads[(1, 2)] == "a/j1"
        assert threads[(2, 1)] == "b/j2"
        spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert {e["name"] for e in spans} == {"bind", "gang_solve"}
        solve = next(e for e in spans if e["name"] == "gang_solve")
        assert solve["dur"] == pytest.approx(1.5e6), "wall wins for virtual"
        bind0 = next(e for e in spans if e["ts"] == 1e6)
        assert bind0["dur"] == pytest.approx(1e6), "shared cluster clock"

    def test_router_fanout_feeds_the_merged_exporter(self, shard_pair):
        clusters, _, router = shard_pair
        clusters[0].api.timelines.record_span(
            NS_S0, "ja", "u", "bind", 1.0, 2.0)
        clusters[1].api.timelines.record_span(
            NS_S1, "jb", "u", "bind", 3.0, 4.0)
        by_shard = {}
        for tl in router.get_timelines():
            by_shard.setdefault(f"store-shard-{tl['shard']}", []).append(tl)
        doc = export_chrome_trace_merged(by_shard)
        procs = [e["args"]["name"] for e in doc["traceEvents"]
                 if e["name"] == "process_name"]
        assert procs == ["store-shard-0", "store-shard-1"]
        assert len([e for e in doc["traceEvents"] if e["ph"] == "X"]) == 2
