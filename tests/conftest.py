"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Real TPU hardware is never required by the test suite (SURVEY.md §4: the engine
must be testable with zero real accelerators). Multi-chip sharding paths are
exercised on 8 virtual CPU devices via --xla_force_host_platform_device_count.
Must run before jax initializes any backend, hence module-level in conftest.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# The axon TPU plugin (injected via a PYTHONPATH site dir) imports jax at
# INTERPRETER STARTUP with the ambient JAX_PLATFORMS=axon already captured,
# and backend init then BLOCKS whenever its tunnel is unreachable. The env
# write above is too late for this process — __graft_entry__'s import-time
# _honor_cpu_platform_request() forces the already-imported config back to
# CPU (no backend has initialized yet at conftest time). Scrub the site dir
# from the path/env so pytest-spawned subprocesses (the real-process e2e
# tier) start clean.
# The runtime lock-order witness (utils/locks.py) defaults ON for the test
# lanes so every chaos/soak leg runs under acquisition-order checking.
# Must be set before the package imports: locks.py samples the env once
# at import time. Benches opt in explicitly via --lockcheck instead.
os.environ.setdefault("TRAINING_LOCKCHECK", "1")

sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
os.environ["PYTHONPATH"] = os.pathsep.join(
    p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
    if p and ".axon_site" not in p
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from __graft_entry__ import _request_virtual_cpu_devices  # noqa: E402

_request_virtual_cpu_devices(8)
