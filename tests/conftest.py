"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Real TPU hardware is never required by the test suite (SURVEY.md §4: the engine
must be testable with zero real accelerators). Multi-chip sharding paths are
exercised on 8 virtual CPU devices via --xla_force_host_platform_device_count.
Must run before jax initializes any backend, hence module-level in conftest.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from __graft_entry__ import _request_virtual_cpu_devices  # noqa: E402

_request_virtual_cpu_devices(8)
