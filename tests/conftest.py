"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Real TPU hardware is never required by the test suite (SURVEY.md §4: the engine
must be testable with zero real accelerators). Multi-chip sharding paths are
exercised on 8 virtual CPU devices via --xla_force_host_platform_device_count.
Must run before jax initializes any backend, hence module-level in conftest.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
