"""Control-plane host failover: WAL-shipping warm standby, epoch-chained
resume, HostChaos tier five (PR 9 tentpole).

The harness runs TWO in-process "host processes" — a primary (durable
HostStore, wire server, cluster services, v1 jax controllers, fail-fast
invariant auditor, real-clock step thread) and a warm standby tailing the
primary's WAL — plus failover clients (`RemoteAPIServer(addresses=[p, s])`).
HostChaos kills the primary with SIGKILL semantics (step loop halted, wire
dark, store fd abandoned un-flushed); the standby must EARN promotion via
the replicated host lease, and surviving watch clients must heal by
epoch-chained delta resume, never a relist storm.

The acceptance pin lives in TestFailoverChaosBurst: primary killed mid
120-job burst -> standby promoted -> every job terminal-Succeeded with the
fail-fast auditor green on both hosts, and the surviving watch client
replays at most 2x the delta event count with zero too-old relists.
"""

import threading
import time

import pytest

import training_operator_tpu.api.common as capi
from training_operator_tpu import config as config_mod
from training_operator_tpu.api.common import (
    Container,
    PodTemplateSpec,
    ReplicaSpec,
)
from training_operator_tpu.api.defaults import default_job
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta
from training_operator_tpu.api.validation import validate_job
from training_operator_tpu.cluster.chaos import HostChaos
from training_operator_tpu.cluster.httpapi import (
    ApiHTTPServer,
    ApiUnavailableError,
    RemoteAPIServer,
)
from training_operator_tpu.cluster.inventory import make_cpu_pool
from training_operator_tpu.cluster.objects import ConfigMap
from training_operator_tpu.cluster.replication import (
    HOST_LEASE_NAME,
    HOST_LEASE_NAMESPACE,
    StandbyController,
    make_snapshot_source,
    start_host_lease,
)
from training_operator_tpu.cluster.runtime import ANNOTATION_SIM_DURATION, Cluster, WallClock
from training_operator_tpu.cluster.store import HostStore
from training_operator_tpu.config import OperatorConfig
from training_operator_tpu.observe.invariants import (
    RULES,
    FleetSources,
    InvariantAuditor,
)
from training_operator_tpu.utils import metrics
from training_operator_tpu.__main__ import build_stack

LEASE_SECONDS = 1.0   # short: auto-promotion keeps the tests fast
POLL_TIMEOUT = 0.2    # standby /wal long-poll window


def _cfg(**overrides) -> OperatorConfig:
    base = dict(
        enabled_schemes=["jax"],
        gang_scheduler_name="none",
        enable_v2=False,
        fleet_audit_interval=0.0,  # the harness runs its OWN fail-fast auditor
        replication_lease_seconds=LEASE_SECONDS,
        replication_poll_timeout=POLL_TIMEOUT,
    )
    base.update(overrides)
    return OperatorConfig(**base)


def _register_admission(cluster) -> None:
    # The run_host admission chain, minus v2 (enable_v2=False here).
    def admit(job) -> None:
        default_job(job, now=cluster.clock.now())
        validate_job(job)

    from training_operator_tpu.api.jobs import JOB_KINDS

    for kind in JOB_KINDS:
        cluster.api.register_admission(kind, admit)


class PrimaryStack:
    """An in-process primary 'host process': durable store, wire server
    with the replication routes, cluster services + jax controllers,
    host-primacy lease, fail-fast auditor, and a real-clock step thread."""

    def __init__(self, state_dir, identity="primary-1", audit_interval=0.5,
                 nodes=8, cpu_per_node=16.0):
        self.cfg = _cfg()
        self.cluster = Cluster(WallClock())
        self.store = HostStore(str(state_dir), wal_ring=65536)
        restored, _ = self.store.load_into(self.cluster.api)
        self.store.attach(self.cluster.api)
        if nodes and not restored:
            self.cluster.add_nodes(make_cpu_pool(nodes, cpu_per_node=cpu_per_node))
        _register_admission(self.cluster)
        self.mgr, _ = build_stack(self.cluster, self.cfg)
        self.server = ApiHTTPServer(
            self.cluster.api, port=0, now_fn=self.cluster.clock.now
        )
        self.server.wal_source = self.store.wal_page
        self.server.snapshot_source = make_snapshot_source(
            self.cluster.api, self.store, self.server.resume_ring
        )
        start_host_lease(self.cluster, identity, LEASE_SECONDS)
        self.auditor = InvariantAuditor(
            self.cluster.api, self.cluster.clock.now,
            sources=FleetSources(
                expectations=self.mgr.unfulfilled_expectations,
                journal_bytes=self.store.journal_bytes,
                journal_bound=lambda: self.cfg.compact_max_journal_bytes,
            ),
            interval=audit_interval, fail_fast=True,
        ).attach(self.cluster)
        self.errors = []
        self.stop = threading.Event()
        self.thread = threading.Thread(
            target=self._loop, name="primary-step", daemon=True
        )
        self.thread.start()

    @property
    def url(self) -> str:
        return self.server.url

    def _loop(self) -> None:
        while not self.stop.is_set():
            try:
                self.cluster.step()
            except Exception as e:  # noqa: BLE001 — surfaced to the test
                self.errors.append(e)
                self.stop.set()
                return
            time.sleep(0.005)

    def shutdown(self) -> None:
        """Graceful teardown (tests' finally); HostChaos is the violent one."""
        self.stop.set()
        self.thread.join(timeout=5.0)
        try:
            self.server.close()
        except Exception:
            pass
        try:
            self.store.close()
        except Exception:
            pass


class StandbyStack:
    """The warm-standby twin: bootstraps from the primary, tails its WAL,
    serves read-only, and on promotion builds the full host stack over the
    replicated state (the run_standby on_promote arm, in-process)."""

    def __init__(self, state_dir, primary_url, identity="standby-1",
                 auto_promote=True, audit_interval=0.5):
        self.cfg = _cfg()
        self.cluster = Cluster(WallClock())
        self.store = HostStore(str(state_dir), wal_ring=65536)
        self.ctrl = StandbyController(
            self.cluster, primary_url, store=self.store,
            poll_timeout=POLL_TIMEOUT, lease_duration=LEASE_SECONDS,
            auto_promote=auto_promote, identity=identity,
        )
        self.ctrl.bootstrap()
        _register_admission(self.cluster)
        self.server = ApiHTTPServer(
            self.cluster.api, port=0, now_fn=self.cluster.clock.now
        )
        self.ctrl.attach_server(self.server)
        self.server.wal_source = self.store.wal_page
        self.server.snapshot_source = make_snapshot_source(
            self.cluster.api, self.store, self.server.resume_ring
        )
        self.mgr = None
        # The run_standby wiring: the SERVER's fleet sources carry the
        # replication feed, so GET /fleet and the auditor read one truth.
        self._sources = self.server.fleet_sources
        self._sources.replication_lag = self.ctrl.lag
        self._sources.journal_bytes = self.store.journal_bytes
        self._sources.journal_bound = (
            lambda: self.cfg.compact_max_journal_bytes
        )
        self.ctrl.on_promote.append(self._on_promote)
        self.auditor = InvariantAuditor(
            self.cluster.api, self.cluster.clock.now,
            sources=self._sources, interval=audit_interval, fail_fast=True,
        ).attach(self.cluster)
        self.ctrl.start()
        self.errors = []
        self.stop = threading.Event()
        self.thread = threading.Thread(
            target=self._loop, name="standby-step", daemon=True
        )
        self.thread.start()

    @property
    def url(self) -> str:
        return self.server.url

    def _on_promote(self) -> None:
        self.mgr, _ = build_stack(self.cluster, self.cfg)
        self._sources.expectations = self.mgr.unfulfilled_expectations

    def _loop(self) -> None:
        while not self.stop.is_set():
            try:
                self.cluster.step()
                self.ctrl.maybe_complete_promotion()
            except Exception as e:  # noqa: BLE001 — surfaced to the test
                self.errors.append(e)
                self.stop.set()
                return
            time.sleep(0.005)

    def wait_caught_up(self, timeout=10.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            lag = self.ctrl.lag()
            if lag["connected"] and lag["records"] == 0:
                # lag() is computed from the last page the tailer FETCHED —
                # a write appended since then can sit invisible in the gap
                # between its WAL append and the tailer's next apply. Ask
                # the primary for its CURRENT head: only cursor >= head is
                # proof of catch-up (the flake this closes predates the
                # follower-read tests that also lean on this helper).
                try:
                    head = int(self.ctrl.remote.get_wal(
                        after=self.ctrl._cursor, limit=1, timeout=0.0,
                    ).get("head", 0))
                except Exception:  # noqa: BLE001 — transient; retry
                    head = None
                if head is not None and head <= self.ctrl._cursor:
                    return
            time.sleep(0.02)
        raise AssertionError(f"standby never caught up: {self.ctrl.lag()}")

    def wait_promoted(self, timeout=20.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.ctrl.promoted:
                return
            time.sleep(0.02)
        raise AssertionError("standby was never promoted")

    def shutdown(self) -> None:
        self.ctrl.stop()
        self.stop.set()
        self.thread.join(timeout=5.0)
        try:
            self.server.close()
        except Exception:
            pass
        try:
            self.store.close()
        except Exception:
            pass


def _cm(name: str) -> ConfigMap:
    return ConfigMap(metadata=ObjectMeta(name=name), data={"k": name})


def _job(name: str, run_seconds: float = 0.3, workers: int = 1) -> JAXJob:
    return JAXJob(
        metadata=ObjectMeta(name=name),
        replica_specs={
            "Worker": ReplicaSpec(
                replicas=workers,
                template=PodTemplateSpec(
                    containers=[Container(name="jax", image="trainer",
                                          resources={"cpu": 1.0})],
                    annotations={ANNOTATION_SIM_DURATION: str(run_seconds)},
                ),
            )
        },
    )


def _resume_counters():
    return {
        "delta": metrics.wire_resume_delta.total(),
        "replayed": metrics.wire_resume_replayed.total(),
        "too_old": metrics.wire_resume_too_old.total(),
    }


def _resume_deltas(before):
    now = _resume_counters()
    return {k: now[k] - before[k] for k in before}


@pytest.fixture()
def ha_pair(tmp_path):
    primary = PrimaryStack(tmp_path / "primary")
    standby = None
    try:
        standby = StandbyStack(tmp_path / "standby", primary.url)
        yield primary, standby
    finally:
        if standby is not None:
            standby.shutdown()
        primary.shutdown()


class TestWalShipping:
    def test_standby_converges_and_serves_reads_but_rejects_writes(self, ha_pair):
        primary, standby = ha_pair
        client = RemoteAPIServer(primary.url, timeout=5.0)
        for i in range(10):
            client.create(_cm(f"ship-{i}"))
        standby.wait_caught_up()

        ro = RemoteAPIServer(standby.url, timeout=5.0)
        names = sorted(c.metadata.name for c in ro.list("ConfigMap"))
        assert names == sorted(f"ship-{i}" for i in range(10))
        # resourceVersions are the PRIMARY's, verbatim (seq/rv lockstep).
        for i in (0, 9):
            assert (ro.get("ConfigMap", "default", f"ship-{i}").metadata
                    .resource_version
                    == client.get("ConfigMap", "default", f"ship-{i}").metadata
                    .resource_version)
        # A write to the standby is "not leader, try elsewhere", not a bug.
        with pytest.raises(ApiUnavailableError):
            ro.create(_cm("rejected"))
        # The replicated host lease is the standby's failure detector.
        lease = ro.get("Lease", HOST_LEASE_NAMESPACE, HOST_LEASE_NAME)
        assert lease.holder == "primary-1"
        # /fleet on the standby surfaces how warm it is (INV008's feed).
        fleet = ro.get_fleet()
        assert fleet["replication"]["role"] == "standby"
        assert fleet["replication"]["connected"] is True

    def test_deletes_and_events_replicate(self, ha_pair):
        primary, standby = ha_pair
        client = RemoteAPIServer(primary.url, timeout=5.0)
        for i in range(4):
            client.create(_cm(f"d-{i}"))
        client.delete("ConfigMap", "default", "d-1")
        client.delete("ConfigMap", "default", "d-3")
        standby.wait_caught_up()
        ro = RemoteAPIServer(standby.url, timeout=5.0)
        assert sorted(c.metadata.name for c in ro.list("ConfigMap")) == ["d-0", "d-2"]


class TestPromotion:
    def test_explicit_promote_verb_drains_tail_and_opens_writes(self, ha_pair):
        primary, standby = ha_pair
        client = RemoteAPIServer(primary.url, timeout=5.0)
        for i in range(5):
            client.create(_cm(f"pre-{i}"))

        sby = RemoteAPIServer(standby.url, timeout=15.0)
        result = sby.promote()
        assert result["promoted"] is True and result["identity"] == "standby-1"
        standby.wait_promoted(timeout=5.0)

        # The drained tail covers every pre-promotion write...
        assert sorted(c.metadata.name for c in sby.list("ConfigMap")) == sorted(
            f"pre-{i}" for i in range(5)
        )
        # ...and the write gate is open: the ex-standby IS the primary now.
        sby.create(_cm("post-promote"))
        assert sby.get("ConfigMap", "default", "post-promote") is not None
        # It took over the host-primacy lease (takeover increments
        # transitions — the observable failover record).
        lease = sby.get("Lease", HOST_LEASE_NAMESPACE, HOST_LEASE_NAME)
        assert lease.holder == "standby-1"
        assert lease.transitions >= 1
        # Promoted role: INV008 goes quiet (no standby to lag).
        assert standby.ctrl.lag()["role"] == "primary"

    def test_auto_promotion_needs_both_expired_lease_and_dead_tail(self, ha_pair):
        """Split-brain guard: while WAL pages still flow, a merely-stale
        lease must NOT promote (lag, not death)."""
        primary, standby = ha_pair
        standby.wait_caught_up()
        # Give the detector several lease windows with a healthy primary.
        time.sleep(LEASE_SECONDS * 3)
        assert not standby.ctrl.promoted
        assert not standby.ctrl._promote_requested.is_set()

    def test_auth_failure_never_auto_promotes(self, ha_pair):
        """The other split-brain guard: a standby that cannot AUTHENTICATE
        has no evidence the primary is dead — only that its own credentials
        are wrong (rotated token, TLS pin). The replicated lease expires
        locally because replication stopped, which is exactly the wrongful-
        promotion window if auth-blind read as disconnected."""
        primary, standby = ha_pair
        standby.wait_caught_up()
        real_get_wal = standby.ctrl.remote.get_wal

        def broken(*a, **k):
            raise PermissionError("GET /wal: bad or missing bearer token")

        standby.ctrl.remote.get_wal = broken
        try:
            time.sleep(LEASE_SECONDS * 3)
            lag = standby.ctrl.lag()
            assert lag["auth_failed"] is True and lag["connected"] is False
            assert not standby.ctrl.promoted
            assert not standby.ctrl._promote_requested.is_set()
        finally:
            standby.ctrl.remote.get_wal = real_get_wal
        # Healed credentials: the tail reconnects and the flag clears.
        standby.wait_caught_up()
        assert standby.ctrl.lag()["auth_failed"] is False

    def test_promotion_drain_is_not_page_capped(self, tmp_path):
        """A lagging standby drains the WHOLE reachable WAL tail before
        the write gate opens: the drain is wall-clock-bounded, not
        page-capped (a 3-page cap used to silently lose every acknowledged
        record past it on a planned promotion)."""
        primary = PrimaryStack(tmp_path / "drain-primary")
        try:
            cluster = Cluster(WallClock())
            ctrl = StandbyController(
                cluster, primary.url, poll_timeout=POLL_TIMEOUT,
                lease_duration=LEASE_SECONDS, auto_promote=False,
                identity="lagging-standby", page_limit=8,
            )
            ctrl.bootstrap()
            # The tailer is never started: the standby sits at its
            # bootstrap cursor while the primary accumulates 100 records
            # = 13 pages of backlog.
            client = RemoteAPIServer(primary.url, timeout=5.0)
            for i in range(100):
                client.create(_cm(f"lag-{i}"))
            ctrl.request_promotion("planned failover of a lagging standby")
            assert ctrl.maybe_complete_promotion() is True
            assert ctrl.lag_records == 0
            names = {c.metadata.name for c in cluster.api.list("ConfigMap")}
            assert names.issuperset({f"lag-{i}" for i in range(100)})
        finally:
            primary.shutdown()


class TestEpochChainedResume:
    def test_surviving_watch_heals_by_delta_across_failover(self, ha_pair, tmp_path):
        primary, standby = ha_pair
        client = RemoteAPIServer(
            addresses=[primary.url, standby.url], timeout=5.0
        )
        wq = client.watch(kinds=["ConfigMap"])
        for i in range(5):
            client.create(_cm(f"w-{i}"))
        got = []
        deadline = time.monotonic() + 5.0
        while len(got) < 5 and time.monotonic() < deadline:
            got.extend(wq.drain(timeout=0.5))
        assert len(got) == 5
        standby.wait_caught_up()

        chaos = HostChaos()
        kill_t = chaos.kill_inprocess(
            "primary-1", server=primary.server, store=primary.store,
            stop=primary.stop, threads=[primary.thread],
        )
        standby.wait_promoted()

        # MTTR: kill -> first successful write on the promoted standby,
        # via the failover client's ordinary retry arm (kill_t is WALL
        # time — HostChaos logs wall times, NodeChaos parity).
        before = _resume_counters()
        mttr = None
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            try:
                client.create(_cm("mttr-probe"))
                mttr = time.time() - kill_t
                break
            except ApiUnavailableError:
                time.sleep(0.05)
        assert mttr is not None, "no write ever succeeded after failover"
        assert 0 < mttr < 30.0, f"implausible failover MTTR {mttr}"

        for i in range(3):
            client.create(_cm(f"post-{i}"))

        # The surviving watch session heals by CHAINED delta: the standby
        # accepted the dead primary's epoch and seq watermarks. A relist
        # would call client.list — record any.
        lists = []
        orig_list = client.list
        client.list = lambda *a, **k: lists.append(a) or orig_list(*a, **k)
        try:
            events = []
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                try:
                    events.extend(wq.drain(timeout=0.5))
                except ApiUnavailableError:
                    continue
                names = {e.obj.metadata.name for e in events}
                if {"mttr-probe", "post-0", "post-1", "post-2"} <= names:
                    break
        finally:
            client.list = orig_list
        names = [e.obj.metadata.name for e in events]
        assert {"mttr-probe", "post-0", "post-1", "post-2"} <= set(names)
        # Exactly once each: the replay/subscribe overlap dedups by seq.
        assert len(names) == len(set(names))
        got = _resume_deltas(before)
        assert got["too_old"] == 0, "failover must not force a relist"
        assert lists == [], "the relist arm must never fire across failover"
        assert not standby.errors, standby.errors


def test_inv008_replication_lag_rule():
    """INV008 (satellite): lag over replication_max_lag_seconds on a
    standby fires once per incident; a promoted (primary) role or healed
    lag goes quiet."""
    old = config_mod.current()
    config_mod.set_current(OperatorConfig(replication_max_lag_seconds=1.0))
    try:
        cluster = Cluster()
        lag = {"role": "standby", "records": 7, "seconds": 9.0,
               "connected": False, "applied": 0, "bootstraps": 1}
        auditor = InvariantAuditor(
            cluster.api, cluster.clock.now,
            sources=FleetSources(replication_lag=lambda: dict(lag)),
            rules=[r for r in RULES if r.rule_id == "INV008"],
        )
        before = metrics.invariant_violations.value("INV008")
        active = auditor.audit()
        assert [v.rule for v in active] == ["INV008"]
        assert "9.0s" in active[0].message
        assert metrics.invariant_violations.value("INV008") == before + 1
        # Once per incident, not once per audit pass.
        auditor.audit()
        assert metrics.invariant_violations.value("INV008") == before + 1
        events = cluster.api.events(object_name="wal-tail", reason="INV008")
        assert len(events) == 1 and events[0].event_type == "Warning"
        # Healed: under the bound.
        lag["seconds"] = 0.2
        assert auditor.audit() == []
        # A promoted ex-standby is the primary: lag is meaningless.
        lag.update(role="primary", seconds=99.0)
        assert auditor.audit() == []
        # Standby again over the bound: a NEW incident reports again.
        lag.update(role="standby", seconds=5.0)
        assert [v.rule for v in auditor.audit()] == ["INV008"]
        assert metrics.invariant_violations.value("INV008") == before + 2
    finally:
        config_mod.set_current(old)


class TestFailoverChaosBurst:
    def test_primary_sigkill_mid_burst_standby_converges_all_jobs(
        self, ha_pair
    ):
        """THE acceptance pin: 120-job burst, primary SIGKILL'd mid-burst,
        standby auto-promotes, every job reaches terminal success with the
        fail-fast invariant auditor green on both hosts — and a client
        with live watch sessions across the failover heals by delta,
        replaying at most 2x the events it actually receives (no relist)."""
        primary, standby = ha_pair
        n_jobs = 120
        client = RemoteAPIServer(
            addresses=[primary.url, standby.url], timeout=5.0
        )
        wq = client.watch(kinds=["JAXJob", "Pod"])

        for i in range(n_jobs):
            client.create(_job(f"burst-{i:03d}", run_seconds=0.3))

        def drain():
            try:
                return wq.drain(timeout=0.2)
            except ApiUnavailableError:
                return []

        def succeeded():
            try:
                return sum(
                    1 for j in client.list("JAXJob")
                    if capi.is_succeeded(j.status)
                )
            except ApiUnavailableError:
                return -1

        # Mid-burst: wait for real progress (some terminal, most in flight).
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            drain()
            if succeeded() >= 30:
                break
            time.sleep(0.05)
        assert succeeded() >= 30, "burst never got going"
        standby.wait_caught_up(timeout=20.0)

        before = _resume_counters()
        chaos = HostChaos()
        kill_t = chaos.kill_inprocess(
            "primary-1", server=primary.server, store=primary.store,
            stop=primary.stop, threads=[primary.thread],
        )
        standby.wait_promoted()
        assert chaos.kills and chaos.kills[0][1] == "primary-1"

        # Every job converges on the promoted standby: the restored RUNNING
        # pods finish (kubelet backlog), pending ones schedule and run.
        post_kill_events = 0
        all_done = False
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            post_kill_events += len(drain())
            if succeeded() == n_jobs:
                all_done = True
                break
            time.sleep(0.05)
        assert all_done, (
            f"only {succeeded()}/{n_jobs} jobs Succeeded after failover "
            f"(standby errors: {standby.errors})"
        )
        recovery_wall = time.time() - kill_t
        assert 0 < recovery_wall < 120.0

        # Fail-fast auditors stayed green on BOTH hosts for the whole run
        # (a violation raises out of the step loop into .errors).
        assert not primary.errors, primary.errors
        assert not standby.errors, standby.errors
        assert standby.auditor.last_violations == []
        assert standby.auditor.audits > 0

        # The surviving watch client healed by chained resume: zero
        # too-old relists, and the replayed events are bounded by what it
        # actually received after the kill (<= 2x the delta, not O(store)).
        got = _resume_deltas(before)
        assert got["too_old"] == 0, "failover forced a relist"
        assert got["delta"] >= 1, "the resume arm never fired"
        assert post_kill_events >= 1
        assert got["replayed"] <= 2 * post_kill_events, (
            f"replayed {got['replayed']} events for {post_kill_events} "
            f"delivered — a relist storm in delta clothing"
        )
