"""Sharded write plane (PR 17 tentpole): the (kind, namespace) routing
map, the StoreShardSet behind the APIServer's single journal-sink seam,
the client-side shard router with cross-shard watch fan-in, INV011
ownership auditing, and the sharded soak smoke.

The contract under test, end to end:

- One object lives on exactly ONE shard — journal, WAL ring, standby,
  epoch chain. Cluster-scoped kinds (Node, PriorityClass, ClusterQueue,
  Lease) and empty namespaces pin to the meta-shard.
- `store_shards=1` is byte-identical to the pre-shard topology (the
  replay pin): make_store returns a plain HostStore over the same layout.
- One shard's failure degrades exactly that shard: its ring outrun
  relists only its keys (ShardRelistReset), its failover heals its
  watch sessions by chained delta, and the other shards never notice.
"""

import time

import pytest

from training_operator_tpu.api.jobs import JAXJob, ObjectMeta
from training_operator_tpu.cluster import wire
from training_operator_tpu.cluster.httpapi import (
    ApiHTTPServer,
    CachedReadAPI,
    RemoteAPIServer,
    ShardedRemoteAPIServer,
    ShardRelistReset,
)
from training_operator_tpu.cluster.objects import ConfigMap, Node
from training_operator_tpu.cluster.runtime import Cluster, VirtualClock
from training_operator_tpu.cluster.shards import (
    CLUSTER_SCOPED_KINDS,
    StoreShardSet,
    make_store,
    shard_for,
    shard_root,
)
from training_operator_tpu.cluster.store import HostStore
from training_operator_tpu.controllers.leader import shard_of
from training_operator_tpu.observe.invariants import (
    FleetSources,
    InvariantAuditor,
    RULES,
)
from training_operator_tpu.utils import metrics

# crc32 pins for num_shards=2 (the map is stable by construction — it is
# the ShardElector's): these namespaces land where the tests assume.
NS_S0 = "alpha"   # -> shard 0
NS_S1 = "beta"    # -> shard 1


def _cm(name, ns):
    return ConfigMap(metadata=ObjectMeta(name=name, namespace=ns),
                     data={"k": name})


def _job(name, ns):
    return JAXJob(metadata=ObjectMeta(name=name, namespace=ns))


def _resume_counters():
    return {
        "delta": metrics.wire_resume_delta.total(),
        "too_old": metrics.wire_resume_too_old.total(),
    }


# ---------------------------------------------------------------------------
# The routing map
# ---------------------------------------------------------------------------


class TestRoutingMap:
    def test_namespace_hash_matches_the_shard_elector(self):
        """One map for both planes: an operator shard's namespaces all
        land on one write shard because shard_for IS shard_of."""
        for ns in ("alpha", "beta", "team-0", "prod", "x" * 40):
            for n in (2, 3, 4, 7):
                assert shard_for("JAXJob", ns, n) == shard_of(ns, n)

    def test_pins_for_this_file(self):
        assert shard_for("ConfigMap", NS_S0, 2) == 0
        assert shard_for("ConfigMap", NS_S1, 2) == 1

    def test_cluster_scoped_kinds_pin_to_meta_shard(self):
        for kind in CLUSTER_SCOPED_KINDS:
            for meta in (0, 1, 2):
                assert shard_for(kind, "anything", 3, meta) == meta

    def test_empty_namespace_pins_to_meta_shard(self):
        assert shard_for("ConfigMap", "", 4, 2) == 2
        assert shard_for("ConfigMap", None, 4, 2) == 2

    def test_single_shard_is_always_zero(self):
        assert shard_for("JAXJob", "any", 1) == 0
        assert shard_for("Node", "", 1) == 0

    def test_shard_root_layout(self, tmp_path):
        root = str(tmp_path)
        assert shard_root(root, 0, 1) == root, "shards=1 is the old layout"
        assert shard_root(root, 2, 4).endswith("store-shard-2")


# ---------------------------------------------------------------------------
# StoreShardSet: the in-process shape
# ---------------------------------------------------------------------------


class TestStoreShardSet:
    def test_make_store_one_shard_is_a_plain_host_store(self, tmp_path):
        store = make_store(str(tmp_path))
        assert type(store) is HostStore, "the replay pin: no wrapper at 1"
        store.close()

    def test_shard_set_refuses_one_shard(self, tmp_path):
        with pytest.raises(ValueError):
            StoreShardSet(str(tmp_path), 1)

    def test_writes_land_on_exactly_one_shard_journal(self, tmp_path):
        cluster = Cluster(VirtualClock())
        store = make_store(str(tmp_path), num_shards=2)
        store.load_into(cluster.api)
        store.attach(cluster.api)
        cluster.api.create(_cm("a", NS_S0))
        cluster.api.create(_cm("b", NS_S1))
        cluster.api.create(Node(metadata=ObjectMeta(name="n0", namespace=""),
                                capacity={"cpu": 1}))
        assert store.object_counts() == {0: 2, 1: 1}  # node pins to meta
        assert store.shards[0].journal_records() == 2
        assert store.shards[1].journal_records() == 1
        rep = store.ownership_report()
        assert rep["duplicates"] == [] and rep["misrouted"] == []
        store.close()

    def test_reload_restores_every_shard_and_ownership(self, tmp_path):
        cluster = Cluster(VirtualClock())
        store = make_store(str(tmp_path), num_shards=3)
        store.load_into(cluster.api)
        store.attach(cluster.api)
        for i in range(12):
            cluster.api.create(_job(f"j{i}", f"team-{i}"))
        counts = store.object_counts()
        store.close()

        fresh = Cluster(VirtualClock())
        store2 = make_store(str(tmp_path), num_shards=3)
        objects, _ = store2.load_into(fresh.api)
        assert objects == 12
        assert len(fresh.api.list("JAXJob")) == 12
        assert store2.object_counts() == counts
        rep = store2.ownership_report()
        assert rep["duplicates"] == [] and rep["misrouted"] == []
        store2.close()

    def test_deletes_unwind_ownership(self, tmp_path):
        cluster = Cluster(VirtualClock())
        store = make_store(str(tmp_path), num_shards=2)
        store.load_into(cluster.api)
        store.attach(cluster.api)
        cluster.api.create(_cm("a", NS_S0))
        cluster.api.delete("ConfigMap", NS_S0, "a")
        assert store.object_counts() == {0: 0, 1: 0}
        store.close()

    def test_abandon_shard_degrades_only_that_shard(self, tmp_path):
        cluster = Cluster(VirtualClock())
        store = make_store(str(tmp_path), num_shards=2)
        store.load_into(cluster.api)
        store.attach(cluster.api)
        before = metrics.store_shard_failovers.value("1")
        store.abandon_shard(1)
        assert store.shards[1].degraded and not store.shards[0].degraded
        assert store.degraded  # the set reports the worst shard
        assert metrics.store_shard_failovers.value("1") == before + 1
        # The healthy shard keeps journaling.
        cluster.api.create(_cm("still-up", NS_S0))
        assert store.shards[0].journal_records() == 1
        store.close()

    def test_replace_shard_adopts_a_standby_store(self, tmp_path):
        cluster = Cluster(VirtualClock())
        store = make_store(str(tmp_path), num_shards=2)
        store.load_into(cluster.api)
        store.attach(cluster.api)
        cluster.api.create(_cm("pre", NS_S1))
        store.abandon_shard(1)
        adopted = make_store(str(tmp_path / "standby-1"))
        adopted.open_journal()
        store.replace_shard(1, adopted)
        assert not store.shards[1].degraded
        cluster.api.create(_cm("post", NS_S1))
        assert adopted.journal_records() == 1, "writes flow to the adoptee"
        # Ownership tracked the SLOT across the swap: pre + post both owned.
        assert store.object_counts()[1] == 2
        store.close()

    def test_shard_writes_metric_labels_by_shard(self, tmp_path):
        cluster = Cluster(VirtualClock())
        store = make_store(str(tmp_path), num_shards=2)
        store.load_into(cluster.api)
        store.attach(cluster.api)
        b0 = metrics.store_shard_writes.value("0")
        b1 = metrics.store_shard_writes.value("1")
        cluster.api.create(_cm("a", NS_S0))
        cluster.api.create(_cm("b", NS_S1))
        cluster.api.create(_cm("c", NS_S1))
        assert metrics.store_shard_writes.value("0") == b0 + 1
        assert metrics.store_shard_writes.value("1") == b1 + 2
        store.close()


# ---------------------------------------------------------------------------
# INV011: shard-ownership invariant
# ---------------------------------------------------------------------------


class TestINV011:
    def _auditor(self, cluster, feed):
        return InvariantAuditor(
            cluster.api, cluster.clock.now,
            sources=FleetSources(store_shards=feed), interval=10.0,
        )

    def _detect(self, cluster, auditor):
        grace = next(r for r in RULES if r.rule_id == "INV011").grace
        first = auditor.audit()
        cluster.clock.advance(grace + 0.001)
        return first, auditor.audit()

    def test_registered_in_the_catalog(self):
        assert any(r.rule_id == "INV011" for r in RULES)

    def test_clean_report_is_quiet(self, tmp_path):
        cluster = Cluster(VirtualClock())
        store = make_store(str(tmp_path), num_shards=2)
        store.load_into(cluster.api)
        store.attach(cluster.api)
        cluster.api.create(_cm("a", NS_S0))
        auditor = self._auditor(cluster, store.ownership_report)
        first, second = self._detect(cluster, auditor)
        assert first == [] and second == []
        store.close()

    def test_duplicate_key_fires(self):
        cluster = Cluster(VirtualClock())
        key = ("ConfigMap", NS_S0, "split")
        feed = lambda: {
            "num_shards": 2, "meta_shard": 0,
            "counts": {0: 1, 1: 1},
            "duplicates": [(0, 1, key)], "misrouted": [],
        }
        first, second = self._detect(cluster, self._auditor(cluster, feed))
        assert [v.rule for v in second] == ["INV011"]
        assert second[0].name == "split"
        assert "shards 0 and 1" in second[0].message

    def test_misrouted_key_fires(self):
        cluster = Cluster(VirtualClock())
        feed = lambda: {
            "num_shards": 2, "meta_shard": 0,
            "counts": {0: 1, 1: 0},
            "duplicates": [], "misrouted": [(0, ("ConfigMap", NS_S1, "lost"))],
        }
        first, second = self._detect(cluster, self._auditor(cluster, feed))
        assert [v.rule for v in second] == ["INV011"]
        assert "routes it elsewhere" in second[0].message

    def test_unsharded_feed_is_exempt(self):
        cluster = Cluster(VirtualClock())
        feed = lambda: {"num_shards": 1, "counts": {0: 5},
                        "duplicates": [(0, 0, ("X", "", "y"))], "misrouted": []}
        first, second = self._detect(cluster, self._auditor(cluster, feed))
        assert first == [] and second == []


# ---------------------------------------------------------------------------
# The wire router
# ---------------------------------------------------------------------------


@pytest.fixture()
def shard_pair():
    """Two live shard hosts + the router over them (shard 0 = meta)."""
    clusters = [Cluster(), Cluster()]
    servers = [ApiHTTPServer(c.api, port=0) for c in clusters]
    router = ShardedRemoteAPIServer(
        shard_addresses=[[s.url] for s in servers], timeout=5.0
    )
    try:
        yield clusters, servers, router
    finally:
        for s in servers:
            s.close()


class TestShardedWire:
    def test_writes_and_strong_reads_route_by_namespace(self, shard_pair):
        clusters, _, router = shard_pair
        router.create(_cm("a", NS_S0))
        router.create(_cm("b", NS_S1))
        # Physical placement: each host holds exactly its shard's objects.
        assert [c.metadata.name for c in clusters[0].api.list("ConfigMap")] == ["a"]
        assert [c.metadata.name for c in clusters[1].api.list("ConfigMap")] == ["b"]
        # Strong reads come from the owning shard.
        assert router.get("ConfigMap", NS_S0, "a").data["k"] == "a"
        assert router.get("ConfigMap", NS_S1, "b").data["k"] == "b"
        # Update/delete route home too.
        b = router.get("ConfigMap", NS_S1, "b")
        b.data["k"] = "b2"
        router.update(b)
        assert clusters[1].api.get("ConfigMap", NS_S1, "b").data["k"] == "b2"
        router.delete("ConfigMap", NS_S1, "b")
        assert router.try_get("ConfigMap", NS_S1, "b") is None

    def test_cluster_scoped_kinds_live_on_the_meta_shard(self, shard_pair):
        clusters, _, router = shard_pair
        router.create(Node(metadata=ObjectMeta(name="n0", namespace=""),
                           capacity={"cpu": 1}))
        assert len(clusters[0].api.list("Node")) == 1
        assert len(clusters[1].api.list("Node")) == 0
        assert router.get("Node", "", "n0") is not None
        assert len(router.list("Node")) == 1, "no fan-out for pinned kinds"

    def test_unnamespaced_list_fans_out_and_merges(self, shard_pair):
        _, _, router = shard_pair
        for i in range(3):
            router.create(_cm(f"a{i}", NS_S0))
        for i in range(2):
            router.create(_cm(f"b{i}", NS_S1))
        assert len(router.list("ConfigMap")) == 5
        assert len(router.list("ConfigMap", namespace=NS_S0)) == 3
        assert len(router.list("ConfigMap", namespace=NS_S1)) == 2

    def test_list_page_walks_shards_with_a_shard_cursor(self, shard_pair):
        _, _, router = shard_pair
        for i in range(5):
            router.create(_cm(f"a{i}", NS_S0))
        for i in range(4):
            router.create(_cm(f"b{i}", NS_S1))
        pages, token, names = 0, None, []
        while True:
            items, token = router.list_page("ConfigMap", limit=3,
                                            continue_token=token)
            names.extend(o.metadata.name for o in items)
            pages += 1
            if token is None:
                break
            assert ":" in token, "continue token carries the shard cursor"
        assert sorted(names) == sorted(
            [f"a{i}" for i in range(5)] + [f"b{i}" for i in range(4)]
        )
        assert len(names) == len(set(names)), "no page overlap across shards"
        assert pages >= 4

    def test_merged_watch_delivers_exactly_once(self, shard_pair):
        _, _, router = shard_pair
        wq = router.watch(kinds=["ConfigMap"])
        expected = set()
        for i in range(4):
            router.create(_cm(f"a{i}", NS_S0))
            expected.add(f"a{i}")
        for i in range(4):
            router.create(_cm(f"b{i}", NS_S1))
            expected.add(f"b{i}")
        got = []
        deadline = time.monotonic() + 5.0
        while len(got) < 8 and time.monotonic() < deadline:
            got.extend(wq.drain(timeout=0.5))
        names = [e.obj.metadata.name for e in got]
        assert sorted(names) == sorted(expected), "each event exactly once"
        router.unwatch(wq)

    def test_get_fleet_sums_across_shards(self, shard_pair):
        _, _, router = shard_pair
        router.create(_cm("a", NS_S0))
        router.create(_cm("b", NS_S1))
        fleet = router.get_fleet()
        assert fleet["objects"].get("ConfigMap") == 2
        plane = fleet["store_shards"]
        assert plane["num_shards"] == 2 and plane["meta_shard"] == 0
        assert plane["counts"] == {0: 1, 1: 1}
        assert len(plane["per_shard"]) == 2

    def test_events_and_pod_logs_route_by_namespace(self, shard_pair):
        clusters, _, router = shard_pair
        router.append_pod_log(NS_S1, "pod-x", "hello", ts=1.0)
        lines, _ = clusters[1].api.read_pod_log(NS_S1, "pod-x")
        assert any("hello" in l for l in lines)
        lines0, _ = clusters[0].api.read_pod_log(NS_S1, "pod-x")
        assert lines0 == []
        lines_r, _ = router.read_pod_log(NS_S1, "pod-x")
        assert any("hello" in l for l in lines_r)

    def test_sdk_surface_delegates_to_meta_shard(self, shard_pair):
        _, servers, router = shard_pair
        # SyncedClock / TLS plumbing read whole-cluster attributes.
        assert router.base_url == servers[0].url
        assert router.addresses == [servers[0].url]

    def test_group_count_validation(self, shard_pair):
        _, servers, _ = shard_pair
        with pytest.raises(ValueError):
            ShardedRemoteAPIServer(shard_addresses=[[servers[0].url]])


# ---------------------------------------------------------------------------
# Cross-shard watch fan-in: per-shard watermarks, per-shard healing
# ---------------------------------------------------------------------------


class TestPerShardResume:
    def test_one_shard_outrun_relists_only_that_shard(self):
        """Shard 1's ring is outrun; shard 0's session never dropped. The
        heal must relist shard 1 ONLY: shard 0 stays on the delta path and
        its remote's list() is never called."""
        clusters = [Cluster(), Cluster()]
        servers = [
            ApiHTTPServer(clusters[0].api, port=0),  # roomy ring
            ApiHTTPServer(clusters[1].api, port=0, resume_ring_size=4),
        ]
        try:
            router = ShardedRemoteAPIServer(
                shard_addresses=[[s.url] for s in servers], timeout=5.0
            )
            wq = router.watch(kinds=["ConfigMap"])
            router.create(_cm("seed-a", NS_S0))
            router.create(_cm("seed-b", NS_S1))
            got = []
            deadline = time.monotonic() + 5.0
            while len(got) < 2 and time.monotonic() < deadline:
                got.extend(wq.drain(timeout=0.5))
            assert len(got) == 2

            # Kill both shards' sessions; outrun ONLY shard 1's ring.
            for s in servers:
                s.reap_all_sessions()
            router.create(_cm("a-delta", NS_S0))      # 1 missed on shard 0
            for i in range(10):                        # 10 missed >> ring 4
                router.create(_cm(f"b{i}", NS_S1))

            before = _resume_counters()
            lists = [[], []]
            origs = [r.list for r in router.shard_remotes]
            for i, r in enumerate(router.shard_remotes):
                r.list = (lambda i=i, orig=origs[i]: lambda *a, **k:
                          lists[i].append(a[0]) or orig(*a, **k))()
            try:
                events = []
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    events.extend(wq.drain(timeout=0.5))
                    names = {e.obj.metadata.name for e in events
                             if not isinstance(e, ShardRelistReset)}
                    if "a-delta" in names and "b9" in names:
                        break
            finally:
                for r, orig in zip(router.shard_remotes, origs):
                    r.list = orig
            got = _resume_counters()
            assert got["too_old"] - before["too_old"] == 1, (
                "exactly one shard relisted"
            )
            assert got["delta"] - before["delta"] >= 1, (
                "the intact shard healed by delta"
            )
            assert lists[0] == [], "shard 0 must never relist"
            assert sorted(lists[1]) == sorted(wire.KIND_REGISTRY)
            names = [e.obj.metadata.name for e in events
                     if not isinstance(e, ShardRelistReset)]
            # Shard 0's delta arrives exactly once; shard 1's relist
            # re-announces its full state (seed-b + b0..b9), once each.
            assert names.count("a-delta") == 1
            assert names.count("seed-a") == 0, "no relist echo from shard 0"
            assert names.count("b9") == 1
        finally:
            for s in servers:
                s.close()

    def test_shard_relist_reset_is_scoped_for_mirrors(self):
        """With reset_on_relist, the merged queue delivers a
        ShardRelistReset carrying the ownership predicate — a mirror drops
        only that shard's keys (CachedReadAPI path)."""
        clusters = [Cluster(), Cluster()]
        servers = [
            ApiHTTPServer(clusters[0].api, port=0),
            ApiHTTPServer(clusters[1].api, port=0, resume_ring_size=4),
        ]
        try:
            router = ShardedRemoteAPIServer(
                shard_addresses=[[s.url] for s in servers], timeout=5.0
            )
            cached = CachedReadAPI(router)
            pump = router.watch()  # the manager-tick analogue that pumps
            router.create(_cm("a0", NS_S0))
            router.create(_cm("b0", NS_S1))
            pump.drain(timeout=1.0)
            assert len(cached.list("ConfigMap")) == 2  # primes the mirror

            for s in servers:
                s.reap_all_sessions()
            for i in range(10):
                router.create(_cm(f"b{i + 1}", NS_S1))
            router.delete("ConfigMap", NS_S1, "b0")  # ghost-at-risk key

            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                pump.drain(timeout=0.5)
                names = {c.metadata.name for c in cached.list("ConfigMap")}
                if names == {"a0"} | {f"b{i + 1}" for i in range(10)}:
                    break
                time.sleep(0.05)
            names = {c.metadata.name for c in cached.list("ConfigMap")}
            assert "b0" not in names, "the shard relist must drop the ghost"
            assert "a0" in names, "the intact shard's mirror entry survives"
            assert len(names) == 11
        finally:
            for s in servers:
                s.close()

    def test_shard_relist_reset_sentinel_shape(self):
        ev = ShardRelistReset(2, lambda kind, ns: ns == NS_S1)
        assert ev.shard == 2
        assert ev.owns("ConfigMap", NS_S1)
        assert not ev.owns("ConfigMap", NS_S0)


# ---------------------------------------------------------------------------
# Per-shard failover over the wire: epoch-chained delta, one shard only
# ---------------------------------------------------------------------------


class TestPerShardFailover:
    def test_one_shard_fails_over_by_chained_delta_others_undisturbed(
            self, tmp_path):
        """Shard 1 is a real HA pair (primary + WAL-tailing standby with
        the epoch chain); shard 0 is a plain host. Kill shard 1's primary:
        the router's shard-1 client rotates to the promoted standby and
        the merged watch heals that shard by CHAINED delta — zero relists
        — while shard 0's session, objects, and writes never notice."""
        from training_operator_tpu.cluster.chaos import HostChaos
        from tests.test_failover import PrimaryStack, StandbyStack, _resume_deltas

        shard0 = Cluster()
        server0 = ApiHTTPServer(shard0.api, port=0)
        primary = PrimaryStack(tmp_path / "s1-primary", nodes=0)
        standby = None
        try:
            standby = StandbyStack(tmp_path / "s1-standby", primary.url)
            router = ShardedRemoteAPIServer(
                shard_addresses=[[server0.url],
                                 [primary.url, standby.url]],
                timeout=5.0,
            )
            wq = router.watch(kinds=["ConfigMap"])
            router.create(_cm("a-pre", NS_S0))
            router.create(_cm("b-pre", NS_S1))
            got = []
            deadline = time.monotonic() + 5.0
            while len(got) < 2 and time.monotonic() < deadline:
                got.extend(wq.drain(timeout=0.5))
            assert len(got) == 2
            standby.wait_caught_up()

            before = _resume_counters()
            HostChaos().kill_inprocess(
                "primary-1", server=primary.server, store=primary.store,
                stop=primary.stop, threads=[primary.thread],
            )
            standby.wait_promoted()

            # Shard 1 writes ride the rotation to the promoted standby;
            # shard 0 writes never blocked at all.
            router.create(_cm("a-during", NS_S0))
            wrote = False
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                try:
                    router.create(_cm("b-post", NS_S1))
                    wrote = True
                    break
                except Exception:
                    time.sleep(0.05)
            assert wrote, "shard 1 never accepted a write after failover"

            lists = [[], []]
            origs = [r.list for r in router.shard_remotes]
            for i, r in enumerate(router.shard_remotes):
                r.list = (lambda i=i, orig=origs[i]: lambda *a, **k:
                          lists[i].append(a[0]) or orig(*a, **k))()
            try:
                events = []
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline:
                    try:
                        events.extend(wq.drain(timeout=0.5))
                    except Exception:
                        continue
                    names = {e.obj.metadata.name for e in events
                             if not isinstance(e, ShardRelistReset)}
                    if {"a-during", "b-post"} <= names:
                        break
            finally:
                for r, orig in zip(router.shard_remotes, origs):
                    r.list = orig
            names = [e.obj.metadata.name for e in events
                     if not isinstance(e, ShardRelistReset)]
            assert {"a-during", "b-post"} <= set(names)
            assert len(names) == len(set(names)), "exactly once across merge"
            got = _resume_deltas(before)
            assert got["too_old"] == 0, "failover must heal by chained delta"
            assert lists == [[], []], "no relist on either shard"
            # Shard 0 held its state the whole time.
            assert {c.metadata.name for c in shard0.api.list("ConfigMap")} \
                == {"a-pre", "a-during"}
            assert not standby.errors, standby.errors
        finally:
            if standby is not None:
                standby.shutdown()
            primary.shutdown()
            server0.close()


# ---------------------------------------------------------------------------
# Sharded soak smoke: 2 write shards + one per-shard failover, INV011 live
# ---------------------------------------------------------------------------


class TestShardedSoakSmoke:
    def test_compressed_hour_with_two_store_shards(self, tmp_path):
        """The acceptance smoke: a compressed fleet hour with all five
        chaos tiers, store_shards=2 (each shard with its own lockstep
        standby), the host tier's failover taken as a PER-SHARD failover,
        under the fail-fast INV001-INV011 auditor."""
        from tests.test_soak import smoke_config
        from training_operator_tpu.soak.harness import SoakHarness

        h = SoakHarness(smoke_config(store_shards=2), str(tmp_path))
        report = h.run()
        jobs = report["jobs"]
        assert jobs["completed"] == jobs["submitted"] > 100
        assert jobs["failed"] == 0, jobs
        assert report["auditor"]["violations"] == 0
        assert report["chaos"].get("host:failover", 0) == 1
        plane = report["store_shards"]
        assert plane["num_shards"] == 2
        # Exactly one per-shard failover, starting on a non-meta shard,
        # with WAL parity and the other shard's journal undisturbed.
        assert len(plane["failovers"]) == 1
        fo = plane["failovers"][0]
        assert fo["shard"] != plane["meta_shard"]
        assert fo["replication_parity"]
        assert fo["other_shards_undisturbed"]
        assert fo["wal_records_replicated"] > 0
        # INV011's evidence stayed clean to the end.
        own = plane["ownership"]
        assert own["duplicates"] == [] and own["misrouted"] == []
        assert sum(own["counts"].values()) > 0
        # Both shards actually took writes (the namespace spread works).
        assert all(c > 0 for c in own["counts"].values()), own["counts"]
