"""Adversarial transport faults against the wire deployment (WireChaos).

VERDICT r4 weak #4: the in-process `APIChaos` tier cannot reach the wire's
own failure modes. This matrix drives a full remote operator (OperatorManager
on RemoteRuntime over real HTTP) through seeded storms of injected 5xx
responses, connection resets, and watch-session reaps, and asserts the same
invariants TestControlPlaneChaos pins in-process: every job converges,
no duplicate pods, and the operator's retry/resubscribe arms — not luck —
did the surviving (the storm is asserted to have actually happened).
"""

import pytest

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import Container, PodTemplateSpec, ReplicaSpec
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta
from training_operator_tpu.cluster.chaos import WireChaos
from training_operator_tpu.cluster.httpapi import (
    ApiHTTPServer,
    ApiServerError,
    ApiUnavailableError,
    RemoteAPIServer,
    RemoteRuntime,
)
from training_operator_tpu.cluster.inventory import make_cpu_pool
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    Cluster,
    DefaultScheduler,
    SimKubelet,
)
from training_operator_tpu.controllers import OperatorManager
from training_operator_tpu.controllers.jax import JAXController


def _host() -> Cluster:
    cluster = Cluster()
    cluster.add_nodes(make_cpu_pool(2, cpu_per_node=8.0))
    DefaultScheduler(cluster)
    SimKubelet(cluster)
    return cluster


def _jobs(n=3):
    out = []
    for i in range(n):
        tmpl = PodTemplateSpec(
            containers=[Container(name="jax", resources={"cpu": 1.0})],
            annotations={ANNOTATION_SIM_DURATION: "0.2"},
        )
        out.append(
            JAXJob(metadata=ObjectMeta(name=f"storm-{i}"),
                   replica_specs={"Worker": ReplicaSpec(replicas=2, template=tmpl)})
        )
    return out


def _run_storm(seed, error_rate, reset_rate, reap_rate, timeout=60.0):
    host = _host()
    chaos = WireChaos(seed=seed, error_rate=error_rate,
                      reset_rate=reset_rate, reap_rate=reap_rate)
    server = ApiHTTPServer(host.api, port=0, chaos=chaos)
    try:
        remote = RemoteAPIServer(server.url, timeout=10.0)
        runtime = RemoteRuntime(remote, tick_interval=0.0)
        # Boot-time watch subscriptions can be hit by the storm too; a
        # crashed operator process is restarted by its supervisor (kubelet
        # restarting the operator pod) — model that as construction retry.
        for _ in range(50):
            try:
                # Short resync: session reaps lose the events buffered
                # server-side; the designed healing is the periodic resync
                # (controller-runtime SyncPeriod, 300s in production) —
                # compressed here so the matrix runs in test time.
                mgr = OperatorManager(runtime, gang_enabled=False,
                                      resync_period=2.0)
                mgr.register(JAXController(runtime.api))
                break
            except (ApiUnavailableError, ApiServerError):
                continue
        else:
            raise AssertionError("operator never booted through the storm")

        # Submission itself must survive the storm: retry like any client.
        for job in _jobs():
            for _ in range(200):
                try:
                    remote.create(job)
                    break
                except (ApiUnavailableError, ApiServerError):
                    continue
            else:
                raise AssertionError("create never got through the storm")

        def all_succeeded():
            for i in range(3):
                j = host.api.try_get("JAXJob", "default", f"storm-{i}")
                if j is None or not capi.is_succeeded(j.status):
                    return False
            return True

        deadline = host.clock.now() + timeout
        while host.clock.now() < deadline and not all_succeeded():
            host.step()
            try:
                # The exact arms run_forever retries on; anything else is a
                # local bug and must fail the test loudly.
                runtime.step()
            except (ApiUnavailableError, ApiServerError):
                pass
        assert all_succeeded(), {
            f"storm-{i}": getattr(
                host.api.try_get("JAXJob", "default", f"storm-{i}"), "status", None
            )
            for i in range(3)
        }

        # Invariant: no duplicate pods — expectations + resync healed every
        # replayed/refused write without double-creating.
        pods = host.api.list("Pod")
        names = [p.metadata.name for p in pods]
        assert len(names) == len(set(names))
        per_job = {}
        for p in pods:
            per_job.setdefault(
                p.metadata.labels.get("training.tpu.dev/job-name"), []
            ).append(p)
        assert set(per_job) == {f"storm-{i}" for i in range(3)}
        for job_name, job_pods in per_job.items():
            assert len(job_pods) == 2, (job_name, [p.metadata.name for p in job_pods])

        mgr.stop()
        return chaos
    finally:
        server.close()


class TestWireChaosMatrix:
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_error_storm(self, seed):
        chaos = _run_storm(seed, error_rate=0.15, reset_rate=0.0, reap_rate=0.0)
        assert chaos.injected["error"] > 5

    @pytest.mark.parametrize("seed", [1, 2])
    def test_reset_storm(self, seed):
        chaos = _run_storm(seed, error_rate=0.0, reset_rate=0.10, reap_rate=0.0)
        assert chaos.injected["reset"] > 3

    @pytest.mark.parametrize("seed", [1, 2])
    def test_session_reap_storm(self, seed):
        """Watch sessions yanked mid-flight: RemoteWatchQueue must
        resubscribe (drain -> 404 -> fresh watch) and the manager's resync
        must heal the events lost in between."""
        chaos = _run_storm(seed, error_rate=0.0, reset_rate=0.0, reap_rate=0.05)
        assert chaos.injected["reap"] > 2

    def test_full_storm(self):
        chaos = _run_storm(7, error_rate=0.10, reset_rate=0.05, reap_rate=0.03)
        assert sum(chaos.injected.values()) > 10


class TestWireChaosSpec:
    def test_from_spec_round_trip(self):
        c = WireChaos.from_spec("seed=3,error=0.1,reset=0.05,reap=0.02")
        assert (c.error_rate, c.reset_rate, c.reap_rate) == (0.1, 0.05, 0.02)

    def test_from_spec_rejects_unknown_key(self):
        with pytest.raises(ValueError):
            WireChaos.from_spec("seed=1,banana=0.5")
