"""Reconcile engine integration tests on the virtual cluster.

Parity model: reference envtest suites — job_test.go (restart/backoff/TTL),
pod_test.go (cluster-spec env), status_test.go (condition transitions) — with
the SimKubelet playing the role the tests' manual phase mutation plays in
envtest, plus direct expectation-gate tests (expectation_test.go:152).
"""

import pytest

from training_operator_tpu.api import common as capi
from training_operator_tpu.api.common import (
    Container,
    JobConditionType,
    PodTemplateSpec,
    ReplicaSpec,
    RestartPolicy,
)
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta
from training_operator_tpu.cluster.objects import PodPhase
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    ANNOTATION_SIM_EXIT_CODE,
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
    mark_pod_finished,
)
from training_operator_tpu.cluster.inventory import make_cpu_pool
from training_operator_tpu.controllers.jax import JAXController
from training_operator_tpu.controllers.manager import OperatorManager


def make_env(workers=2, nodes=4, kubelet=True, start_latency=0.0):
    cluster = Cluster(VirtualClock())
    cluster.add_nodes(make_cpu_pool(nodes))
    DefaultScheduler(cluster)
    if kubelet:
        SimKubelet(cluster, start_latency=start_latency)
    mgr = OperatorManager(cluster)
    mgr.register(JAXController(cluster.api))
    return cluster, mgr


def make_job(name="jax-mnist", workers=2, restart_policy=None, **annotations):
    tmpl = PodTemplateSpec(
        containers=[Container(name="jax", image="jax:latest", resources={"cpu": 1.0})]
    )
    tmpl.annotations.update(annotations)
    return JAXJob(
        metadata=ObjectMeta(name=name),
        replica_specs={
            "Worker": ReplicaSpec(replicas=workers, template=tmpl, restart_policy=restart_policy)
        },
    )


def get_job(cluster, name="jax-mnist"):
    return cluster.api.get("JAXJob", "default", name)


def job_has(cluster, cond, name="jax-mnist"):
    return capi.has_condition(get_job(cluster, name).status, cond)


class TestJobLifecycle:
    def test_created_to_running_to_succeeded(self):
        cluster, mgr = make_env()
        job = make_job(**{ANNOTATION_SIM_DURATION: "1.0"})
        mgr.submit(job)

        assert cluster.run_until(
            lambda: job_has(cluster, JobConditionType.RUNNING), timeout=30
        ), "job should reach Running"
        pods = cluster.api.list("Pod", "default")
        assert len(pods) == 2
        svcs = cluster.api.list("Service", "default")
        assert len(svcs) == 2

        assert cluster.run_until(
            lambda: job_has(cluster, JobConditionType.SUCCEEDED), timeout=60
        ), "job should reach Succeeded"
        st = get_job(cluster).status
        assert st.completion_time is not None
        assert st.replica_statuses["Worker"].succeeded == 2

    def test_env_injection_contract(self):
        """Reference jax/envvar.go:37-77 contract."""
        cluster, mgr = make_env(workers=3)
        mgr.submit(make_job(workers=3))
        assert cluster.run_until(
            lambda: len(cluster.api.list("Pod", "default")) == 3, timeout=30
        )
        pods = sorted(cluster.api.list("Pod", "default"), key=lambda p: p.name)
        for i, pod in enumerate(pods):
            env = pod.spec.containers[0].env
            assert env["COORDINATOR_ADDRESS"] == "jax-mnist-worker-0"
            assert env["COORDINATOR_PORT"] == "6666"
            assert env["NUM_PROCESSES"] == "3"
            assert env["PROCESS_ID"] == str(i)
            assert env["PYTHONUNBUFFERED"] == "1"
            assert pod.metadata.labels[capi.REPLICA_INDEX_LABEL] == str(i)
            assert pod.metadata.labels[capi.REPLICA_TYPE_LABEL] == "Worker"
        # worker-0 carries the master role label (coordinator)
        assert pods[0].metadata.labels.get(capi.JOB_ROLE_LABEL) == "master"
        assert capi.JOB_ROLE_LABEL not in pods[1].metadata.labels

    def test_multi_slice_env_contract(self):
        """num_slices>1: complete per-slice bootstrap env (slice identity,
        per-slice coordinator, inter-slice DCN/megascale coordinator),
        internally consistent with the contiguous worker->slice mapping."""
        from training_operator_tpu.api.jobs import TPUPolicy

        cluster, mgr = make_env(workers=4, nodes=8)
        job = make_job(workers=4)
        job.tpu_policy = TPUPolicy(accelerator="v5e-16", topology="4x4", num_slices=2)
        mgr.submit(job)
        assert cluster.run_until(
            lambda: len(cluster.api.list("Pod", "default")) == 4, timeout=30
        )
        pods = sorted(cluster.api.list("Pod", "default"), key=lambda p: p.name)
        for i, pod in enumerate(pods):
            env = pod.spec.containers[0].env
            slice_id = i // 2
            assert env["TPU_NUM_SLICES"] == "2"
            assert env["TPU_SLICE_ID"] == str(slice_id)
            assert env["TPU_WORKER_ID_IN_SLICE"] == str(i % 2)
            assert env["TPU_WORKERS_PER_SLICE"] == "2"
            assert env["TPU_SLICE_COORDINATOR_ADDRESS"] == (
                f"jax-mnist-worker-{slice_id * 2}"
            )
            assert env["TPU_SLICE_COORDINATOR_PORT"] == "6666"
            # Inter-slice coordinator: worker-0, beside jax.distributed's.
            assert env["MEGASCALE_COORDINATOR_ADDRESS"] == "jax-mnist-worker-0"
            assert env["MEGASCALE_PORT"] == "6667"
            assert env["MEGASCALE_NUM_SLICES"] == "2"
            assert env["MEGASCALE_SLICE_ID"] == str(slice_id)
            # The global jax.distributed contract is unchanged.
            assert env["COORDINATOR_ADDRESS"] == "jax-mnist-worker-0"
            assert env["NUM_PROCESSES"] == "4"
            # The DCN port is exposed on the service.
            assert pod.spec.containers[0].ports["jaxjob-dcn-port"] == 6667
        # Single-slice jobs carry none of the multi-slice surface.
        job1 = make_job(name="jax-single", workers=2)
        job1.tpu_policy = TPUPolicy(accelerator="v5e-16", topology="4x4")
        mgr.submit(job1)
        assert cluster.run_until(
            lambda: len(cluster.api.list("Pod", "default")) == 6, timeout=30
        )
        p0 = cluster.api.get("Pod", "default", "jax-single-worker-0")
        assert "TPU_SLICE_ID" not in p0.spec.containers[0].env
        assert "MEGASCALE_COORDINATOR_ADDRESS" not in p0.spec.containers[0].env

    def test_headless_service_per_replica(self):
        cluster, mgr = make_env()
        mgr.submit(make_job())
        assert cluster.run_until(
            lambda: len(cluster.api.list("Service", "default")) == 2, timeout=30
        )
        svcs = sorted(cluster.api.list("Service", "default"), key=lambda s: s.name)
        assert svcs[0].name == "jax-mnist-worker-0"
        assert svcs[0].ports == {"jaxjob-port": 6666}
        assert svcs[0].selector[capi.REPLICA_INDEX_LABEL] == "0"


class TestFailurePolicies:
    def test_exit_code_retryable_restarts_pod(self):
        """Exit 137 (SIGKILL) is retryable under ExitCode policy."""
        cluster, mgr = make_env()
        job = make_job(
            restart_policy=RestartPolicy.EXIT_CODE,
            **{ANNOTATION_SIM_DURATION: "1.0", ANNOTATION_SIM_EXIT_CODE: "137"},
        )
        mgr.submit(job)
        assert cluster.run_until(
            lambda: job_has(cluster, JobConditionType.RESTARTING), timeout=60
        )
        assert not job_has(cluster, JobConditionType.FAILED)
        ev = cluster.api.events(reason="RestartingPod")
        assert ev, "RestartingPod event expected"

    def test_exit_code_permanent_fails_job(self):
        """Exit 1 is permanent under ExitCode policy."""
        cluster, mgr = make_env()
        job = make_job(
            restart_policy=RestartPolicy.EXIT_CODE,
            **{ANNOTATION_SIM_DURATION: "1.0", ANNOTATION_SIM_EXIT_CODE: "1"},
        )
        mgr.submit(job)
        assert cluster.run_until(lambda: job_has(cluster, JobConditionType.FAILED), timeout=60)

    def test_backoff_limit_exceeded(self):
        """OnFailure pods restart in place; restart counts trip the limit
        (reference core/job.go:95)."""
        cluster, mgr = make_env()
        job = make_job(
            restart_policy=RestartPolicy.ON_FAILURE,
            **{ANNOTATION_SIM_DURATION: "0.5", ANNOTATION_SIM_EXIT_CODE: "1"},
        )
        job.run_policy.backoff_limit = 3
        mgr.submit(job)
        assert cluster.run_until(lambda: job_has(cluster, JobConditionType.FAILED), timeout=120)
        cond = capi.get_condition(get_job(cluster).status, JobConditionType.FAILED)
        assert cond.reason == "BackoffLimitExceeded"
        assert not cluster.api.list("Pod", "default"), "pods cleaned up on failure"

    def test_exit_code_recreate_restarts_trip_backoff_limit(self):
        """ExitCode recreates pods with restart_count=0; the engine's restart
        annotation must still trip the backoff limit."""
        cluster, mgr = make_env()
        job = make_job(
            restart_policy=RestartPolicy.EXIT_CODE,
            **{ANNOTATION_SIM_DURATION: "0.5", ANNOTATION_SIM_EXIT_CODE: "137"},
        )
        job.run_policy.backoff_limit = 2
        mgr.submit(job)
        assert cluster.run_until(lambda: job_has(cluster, JobConditionType.FAILED), timeout=120)
        cond = capi.get_condition(get_job(cluster).status, JobConditionType.FAILED)
        assert cond.reason == "BackoffLimitExceeded"

    def test_active_deadline_enforced_after_resume(self):
        """Resume must re-arm the deadline requeue timer."""
        cluster, mgr = make_env()
        job = make_job()
        job.run_policy.active_deadline_seconds = 5
        mgr.submit(job)
        assert cluster.run_until(lambda: job_has(cluster, JobConditionType.RUNNING), timeout=30)
        j = get_job(cluster)
        j.run_policy.suspend = True
        cluster.api.update(j, check_version=False)
        assert cluster.run_until(lambda: job_has(cluster, JobConditionType.SUSPENDED), timeout=30)
        cluster.run_for(20.0)  # outlive the original deadline timer while suspended
        j = get_job(cluster)
        j.run_policy.suspend = False
        cluster.api.update(j, check_version=False)
        assert cluster.run_until(lambda: job_has(cluster, JobConditionType.FAILED), timeout=60)
        cond = capi.get_condition(get_job(cluster).status, JobConditionType.FAILED)
        assert cond.reason == "DeadlineExceeded"

    def test_active_deadline_exceeded(self):
        cluster, mgr = make_env()
        job = make_job()  # runs forever
        job.run_policy.active_deadline_seconds = 5
        mgr.submit(job)
        assert cluster.run_until(lambda: job_has(cluster, JobConditionType.RUNNING), timeout=30)
        assert cluster.run_until(lambda: job_has(cluster, JobConditionType.FAILED), timeout=60)
        cond = capi.get_condition(get_job(cluster).status, JobConditionType.FAILED)
        assert cond.reason == "DeadlineExceeded"


class TestSuspendResume:
    def test_suspend_deletes_pods_and_resume_recreates(self):
        cluster, mgr = make_env()
        job = make_job()
        mgr.submit(job)
        assert cluster.run_until(lambda: job_has(cluster, JobConditionType.RUNNING), timeout=30)

        j = get_job(cluster)
        j.run_policy.suspend = True
        cluster.api.update(j, check_version=False)
        assert cluster.run_until(
            lambda: job_has(cluster, JobConditionType.SUSPENDED)
            and not cluster.api.list("Pod", "default"),
            timeout=30,
        )
        assert get_job(cluster).status.start_time is None

        j = get_job(cluster)
        j.run_policy.suspend = False
        cluster.api.update(j, check_version=False)
        assert cluster.run_until(lambda: job_has(cluster, JobConditionType.RUNNING), timeout=30)
        assert get_job(cluster).status.start_time is not None
        assert len(cluster.api.list("Pod", "default")) == 2
        assert cluster.api.events(reason="JobResumed")

    def test_job_created_suspended_never_creates_pods(self):
        cluster, mgr = make_env()
        job = make_job()
        job.run_policy.suspend = True
        mgr.submit(job)
        assert cluster.run_until(lambda: job_has(cluster, JobConditionType.SUSPENDED), timeout=30)
        assert not cluster.api.list("Pod", "default")


class TestCleanupAndTTL:
    def test_clean_pod_policy_all(self):
        cluster, mgr = make_env()
        job = make_job(**{ANNOTATION_SIM_DURATION: "0.5"})
        job.run_policy.clean_pod_policy = capi.CleanPodPolicy.ALL
        mgr.submit(job)
        assert cluster.run_until(lambda: job_has(cluster, JobConditionType.SUCCEEDED), timeout=60)
        assert cluster.run_until(
            lambda: not cluster.api.list("Pod", "default")
            and not cluster.api.list("Service", "default"),
            timeout=30,
        )

    def test_clean_pod_policy_none_keeps_pods(self):
        cluster, mgr = make_env()
        job = make_job(**{ANNOTATION_SIM_DURATION: "0.5"})
        mgr.submit(job)
        assert cluster.run_until(lambda: job_has(cluster, JobConditionType.SUCCEEDED), timeout=60)
        cluster.run_for(1.0)
        assert len(cluster.api.list("Pod", "default")) == 2

    def test_ttl_deletes_job(self):
        cluster, mgr = make_env()
        job = make_job(**{ANNOTATION_SIM_DURATION: "0.5"})
        job.run_policy.ttl_seconds_after_finished = 5
        mgr.submit(job)
        assert cluster.run_until(lambda: job_has(cluster, JobConditionType.SUCCEEDED), timeout=60)
        assert cluster.run_until(
            lambda: cluster.api.try_get("JAXJob", "default", "jax-mnist") is None, timeout=60
        )


class TestScaling:
    def test_scale_out_and_in(self):
        cluster, mgr = make_env(workers=2)
        mgr.submit(make_job(workers=2))
        assert cluster.run_until(
            lambda: len(cluster.api.list("Pod", "default")) == 2, timeout=30
        )
        j = get_job(cluster)
        j.replica_specs["Worker"].replicas = 4
        cluster.api.update(j, check_version=False)
        assert cluster.run_until(
            lambda: len(cluster.api.list("Pod", "default")) == 4, timeout=30
        )
        j = get_job(cluster)
        j.replica_specs["Worker"].replicas = 1
        cluster.api.update(j, check_version=False)
        assert cluster.run_until(
            lambda: len(cluster.api.list("Pod", "default")) == 1
            and len(cluster.api.list("Service", "default")) == 1,
            timeout=30,
        )
        # NUM_PROCESSES on surviving pod reflects the original spec at creation;
        # index 0 remains.
        pod = cluster.api.list("Pod", "default")[0]
        assert pod.metadata.labels[capi.REPLICA_INDEX_LABEL] == "0"


class TestExpectations:
    def test_no_duplicate_creation_before_informer_echo(self):
        """Reconcile twice without draining watch events: the expectations
        gate must suppress the second mutation pass (reference
        expectation_test.go + SatisfiedExpectations)."""
        cluster, _ = make_env(kubelet=False)
        from training_operator_tpu.engine.controller import JobController
        from training_operator_tpu.utils import metrics

        ctrl = JAXController(cluster.api)
        jc = JobController(cluster.api, ctrl, now_fn=cluster.clock.now)
        job = make_job()
        from training_operator_tpu.api.defaults import default_job

        cluster.api.create(default_job(job))
        before = metrics.created_pods.total()
        jc.reconcile("default", "jax-mnist")
        assert metrics.created_pods.total() == before + 2
        # Second reconcile before any watch echo: gate blocks mutation,
        # no AlreadyExists error, no extra create attempts.
        jc.reconcile("default", "jax-mnist")
        assert metrics.created_pods.total() == before + 2
        # Echo observed -> expectations satisfied again.
        from training_operator_tpu.engine.expectations import gen_expectation_key

        for _ in range(2):
            jc.expectations.creation_observed(
                gen_expectation_key("default/jax-mnist", "Worker", "pods")
            )
            jc.expectations.creation_observed(
                gen_expectation_key("default/jax-mnist", "Worker", "services")
            )
        assert jc._satisfied_expectations(cluster.api.get("JAXJob", "default", "jax-mnist"))

    def test_expectation_ttl_expiry_unblocks(self):
        clock = VirtualClock()
        from training_operator_tpu.engine.expectations import (
            ControllerExpectations,
            EXPECTATION_TIMEOUT_SECONDS,
        )

        exp = ControllerExpectations(clock.now)
        exp.expect_creations("k", 2)
        assert not exp.satisfied_expectations("k")
        clock.advance(EXPECTATION_TIMEOUT_SECONDS + 1)
        assert exp.satisfied_expectations("k")


class TestManualPhaseControl:
    def test_envtest_style_manual_phases(self):
        """No kubelet attached: tests drive pod phases directly, like the
        reference's envtest suites where pods never actually run."""
        cluster, mgr = make_env(kubelet=False)
        mgr.submit(make_job())
        assert cluster.run_until(
            lambda: len(cluster.api.list("Pod", "default")) == 2, timeout=30
        )
        for pod in cluster.api.list("Pod", "default"):
            mark_pod_finished(cluster.api, pod, 0, now=cluster.clock.now())
        assert cluster.run_until(
            lambda: job_has(cluster, JobConditionType.SUCCEEDED), timeout=30
        )


class TestAdoptOrphan:
    """ControllerRefManager claim semantics (reference
    control/controller_ref_manager.go:380 via common/pod.go:242-253)."""

    def test_orphan_with_matching_labels_is_adopted_and_counted(self):
        """Pods stranded without an owner ref (e.g. after an operator restart
        with a fresh uid counter) must be claimed, not re-created: the job
        reaches Running on its orphans and no duplicate pods appear."""
        cluster, mgr = make_env(kubelet=False)
        mgr.submit(make_job())
        assert cluster.run_until(
            lambda: len(cluster.api.list("Pod", "default")) == 2, timeout=30
        )
        # Simulate operator-restart orphaning: strip owner refs in the store.
        for pod in cluster.api.list("Pod", "default"):
            pod.metadata.owner_uid = None
            cluster.api.update(pod)
        # Run the pods; reconcile must adopt them and count them active.
        for pod in cluster.api.list("Pod", "default"):
            pod.status.phase = PodPhase.RUNNING
            cluster.api.update(pod)
        assert cluster.run_until(
            lambda: job_has(cluster, JobConditionType.RUNNING), timeout=30
        )
        pods = cluster.api.list("Pod", "default")
        assert len(pods) == 2  # adopted, not duplicated
        job = get_job(cluster)
        assert all(p.metadata.owner_uid == job.uid for p in pods)

    def test_relabeled_pod_is_released_and_replaced(self):
        """A dependent whose labels no longer match the selector is released
        (owner ref cleared) and the engine creates a replacement for the
        missing index."""
        from training_operator_tpu.api.common import JOB_KIND_LABEL

        cluster, mgr = make_env(kubelet=False)
        mgr.submit(make_job())
        assert cluster.run_until(
            lambda: len(cluster.api.list("Pod", "default")) == 2, timeout=30
        )
        # Mutate a secondary selector label (job-kind): the pod still appears
        # in the job-name list but fails the full-selector match — exactly
        # the case release exists for. (A job-name relabel removes the pod
        # from the list entirely, in the reference too.)
        victim = sorted(cluster.api.list("Pod", "default"), key=lambda p: p.name)[0]
        victim.metadata.labels[JOB_KIND_LABEL] = "Impostor"
        cluster.api.update(victim)
        # The engine releases the mismatched pod: owner ref cleared, pod NOT
        # deleted. (As in the reference, replica names are deterministic, so
        # the released pod squats on the name until an operator deletes it —
        # release is an ownership operation, not a replacement.)
        assert cluster.run_until(
            lambda: cluster.api.get("Pod", "default", victim.name).metadata.owner_uid
            is None,
            timeout=30,
        )
        released = cluster.api.get("Pod", "default", victim.name)
        assert released.metadata.labels[JOB_KIND_LABEL] == "Impostor"
        # The other worker is still owned and counted.
        job = get_job(cluster)
        owned = [
            p for p in cluster.api.list("Pod", "default")
            if p.metadata.owner_uid == job.uid
        ]
        assert len(owned) == 1

    def test_foreign_owned_pod_is_never_touched(self):
        """A pod with someone else's owner ref but matching labels must be
        ignored entirely (no adoption, no release, no deletion)."""
        from training_operator_tpu.engine import core

        cluster, mgr = make_env(kubelet=False)
        job = make_job(workers=1)
        mgr.submit(job)
        assert cluster.run_until(
            lambda: len(cluster.api.list("Pod", "default")) == 1, timeout=30
        )
        # Plant an impostor carrying matching labels but a foreign owner.
        from training_operator_tpu.cluster.objects import Pod
        from training_operator_tpu.api.jobs import ObjectMeta as OM

        live = get_job(cluster)
        impostor = Pod(
            metadata=OM(
                name="impostor",
                namespace="default",
                labels=dict(
                    core.replica_labels(live.kind, live, "Worker", 7, False)
                ),
                owner_uid="uid-of-someone-else",
            )
        )
        cluster.api.create(impostor)
        cluster.run_for(2.0)
        after = cluster.api.get("Pod", "default", "impostor")
        assert after.metadata.owner_uid == "uid-of-someone-else"


class TestOperatorRestart:
    def test_restart_mid_burst_converges_without_duplicates(self):
        """Kill the operator mid-burst, build a fresh manager on the same
        APIServer: adoption re-owns live pods, expectations rebuild from the
        re-list, no duplicate pods are created, and every job still reaches
        Succeeded (reference: informer resync + ControllerRefManager
        adoption, control/controller_ref_manager.go:380)."""
        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_cpu_pool(8))
        DefaultScheduler(cluster)
        SimKubelet(cluster)
        mgr = OperatorManager(cluster)
        mgr.register(JAXController(cluster.api))

        # Count every pod Added event EVER — a duplicate create after the
        # restart would show up here even if it were later cleaned up.
        watch = cluster.api.watch(kinds=("Pod",))
        added = []
        cluster.add_ticker(lambda: added.extend(
            ev.obj.name for ev in watch.drain() if ev.type == "Added"
        ))

        jobs = [make_job(name=f"burst-{i}", workers=2, **{ANNOTATION_SIM_DURATION: "5"})
                for i in range(4)]
        for j in jobs:
            mgr.submit(j)
        # Mid-burst: some pods running, none finished.
        assert cluster.run_until(
            lambda: sum(
                1 for p in cluster.api.list("Pod")
                if p.status.phase == PodPhase.RUNNING
            ) >= 4,
            timeout=30,
        )
        assert not any(job_has(cluster, capi.JobConditionType.SUCCEEDED, j.name)
                       for j in jobs)

        mgr.stop()  # operator process dies
        cluster.run_for(2)  # cluster life goes on without a controller

        # Fresh operator process against the surviving cluster state.
        mgr2 = OperatorManager(cluster)
        mgr2.register(JAXController(cluster.api))

        for j in jobs:
            assert cluster.run_until(
                lambda j=j: job_has(cluster, capi.JobConditionType.SUCCEEDED, j.name),
                timeout=120,
            ), f"{j.name} did not converge after operator restart"

        # No duplicate pod was ever created: each deterministic pod name
        # appeared exactly once across both manager generations.
        assert len(added) == len(set(added)), sorted(added)
        assert len(added) == 4 * 2
        # And the live pod set is exactly the expected one (adoption, not
        # recreate-and-orphan).
        for j in jobs:
            pods = cluster.api.list("Pod", "default", {capi.JOB_NAME_LABEL: j.name})
            assert len(pods) == 2
            st = get_job(cluster, j.name).status
            assert st.replica_statuses["Worker"].succeeded == 2


class TestLeaderElection:
    """Lease-based leader election (reference --enable-leader-election via
    controller-runtime; here controllers/leader.py + the Lease object)."""

    def _env(self):
        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_cpu_pool(8))
        DefaultScheduler(cluster)
        SimKubelet(cluster)
        return cluster

    def _manager(self, cluster, identity):
        mgr = OperatorManager(cluster, leader_elect=True, identity=identity)
        mgr.register(JAXController(cluster.api))
        return mgr

    def test_single_winner_and_only_leader_reconciles(self):
        cluster = self._env()
        a = self._manager(cluster, "op-a")
        b = self._manager(cluster, "op-b")
        a.submit(make_job(name="le-job", workers=2, **{ANNOTATION_SIM_DURATION: "1"}))
        assert cluster.run_until(
            lambda: job_has(cluster, capi.JobConditionType.SUCCEEDED, "le-job"),
            timeout=60,
        )
        # Exactly one manager ever led; the standby queue stayed untouched.
        assert a.elector.is_leader != b.elector.is_leader
        lease = cluster.api.get("Lease", "operator-system",
                                "training-operator-tpu")
        assert lease.holder in ("op-a", "op-b")
        standby = b if a.elector.is_leader else a
        assert len(standby.queue) == 0

    def test_failover_on_leader_death(self):
        cluster = self._env()
        a = self._manager(cluster, "op-a")
        b = self._manager(cluster, "op-b")
        cluster.run_for(1)
        leader, standby = (a, b) if a.elector.is_leader else (b, a)
        assert leader.elector.is_leader and not standby.elector.is_leader

        # Job in flight when the leader dies WITHOUT releasing (hard crash:
        # detach the ticker only, so the lease must expire on its own).
        leader.submit(make_job(name="fo-job", workers=2,
                               **{ANNOTATION_SIM_DURATION: "30"}))
        assert cluster.run_until(
            lambda: job_has(cluster, capi.JobConditionType.RUNNING, "fo-job"),
            timeout=30,
        )
        cluster.remove_ticker(leader.tick)
        cluster.api.unwatch(leader._watch)

        # Standby takes over once the lease expires, resyncs, and drives the
        # job to completion; transitions recorded on the lease.
        assert cluster.run_until(lambda: standby.elector.is_leader, timeout=60)
        lease = cluster.api.get("Lease", "operator-system",
                                "training-operator-tpu")
        assert lease.holder == standby.elector.identity
        assert lease.transitions == 1
        assert cluster.run_until(
            lambda: job_has(cluster, capi.JobConditionType.SUCCEEDED, "fo-job"),
            timeout=120,
        )
        # Adoption, not duplication: still exactly 2 pods.
        pods = cluster.api.list("Pod", "default", {capi.JOB_NAME_LABEL: "fo-job"})
        assert len(pods) == 2

    def test_graceful_stop_hands_over_immediately(self):
        cluster = self._env()
        a = self._manager(cluster, "op-a")
        b = self._manager(cluster, "op-b")
        cluster.run_for(1)
        leader, standby = (a, b) if a.elector.is_leader else (b, a)
        leader.stop()  # releases the lease
        # Well before the 15s lease duration could expire:
        assert cluster.run_until(lambda: standby.elector.is_leader, timeout=5)

    def test_renewal_keeps_leadership(self):
        cluster = self._env()
        a = self._manager(cluster, "op-a")
        b = self._manager(cluster, "op-b")
        cluster.run_for(120)  # many lease durations
        assert a.elector.is_leader != b.elector.is_leader
        lease = cluster.api.get("Lease", "operator-system",
                                "training-operator-tpu")
        assert lease.transitions == 0

    def test_rewin_clears_stale_expectations(self):
        """A manager that loses leadership discards watch events; any
        expectation raised in its previous term references echoes that will
        never arrive. Re-winning must clear them or resync'd jobs gate on
        satisfied_expectations forever."""
        cluster = self._env()
        a = self._manager(cluster, "op-a")
        cluster.run_for(1)
        assert a.elector.is_leader
        _, jc = a.controllers["JAXJob"]
        jc.expectations.expect_creations("stale-key", 2)

        # An intruder steals the lease (valid) -> a steps down.
        lease = cluster.api.get("Lease", "operator-system",
                                "training-operator-tpu")
        lease.holder = "intruder"
        lease.renew_time = cluster.clock.now()
        cluster.api.update(lease)
        assert cluster.run_until(lambda: not a.elector.is_leader, timeout=10)
        assert not jc.expectations.satisfied_expectations("stale-key")

        # The intruder dies (stops renewing) -> a re-wins -> expectations
        # from the old term are gone.
        assert cluster.run_until(lambda: a.elector.is_leader, timeout=60)
        assert jc.expectations.satisfied_expectations("stale-key")
