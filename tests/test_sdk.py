"""SDK + initializer tests (reference training_client_test.py model:
mocked-server client behavior; here the in-process cluster IS the server)."""

import pytest

from training_operator_tpu.api.common import (
    Container,
    JobConditionType,
    PodTemplateSpec,
    ReplicaSpec,
)
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta
from training_operator_tpu.api.validation import ValidationError
from training_operator_tpu.cluster.inventory import make_cpu_pool
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    ANNOTATION_SIM_EXIT_CODE,
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
)
from training_operator_tpu.controllers import OperatorManager, register_all
from training_operator_tpu.initializers import InitializerConfig, download, get_provider
from training_operator_tpu.runtime import MLPolicy, ClusterTrainingRuntime
from training_operator_tpu.runtime.api import (
    ReplicatedJobTemplate,
    TrainingRuntimeSpec,
    TRAINER_NODE,
)
from training_operator_tpu.runtime.controller import TrainJobManager
from training_operator_tpu.sdk import TrainingClient
from training_operator_tpu.sdk.client import TimeoutException


def make_env():
    cluster = Cluster(VirtualClock())
    cluster.add_nodes(make_cpu_pool(8))
    DefaultScheduler(cluster)
    SimKubelet(cluster)
    mgr = OperatorManager(cluster)
    register_all(mgr)
    v2 = TrainJobManager(cluster)
    return cluster, TrainingClient(cluster)


def jax_job(name, replicas=2, duration="2", exit_code=None):
    t = PodTemplateSpec(
        containers=[Container(name="jax", image="img", resources={"cpu": 0.5})]
    )
    t.annotations[ANNOTATION_SIM_DURATION] = duration
    if exit_code:
        t.annotations[ANNOTATION_SIM_EXIT_CODE] = exit_code
    return JAXJob(
        metadata=ObjectMeta(name=name),
        replica_specs={"Worker": ReplicaSpec(replicas=replicas, template=t)},
    )


class TestTrainingClient:
    def test_create_wait_succeeded(self):
        _, client = make_env()
        client.create_job(jax_job("t1"))
        job = client.wait_for_job_conditions("t1", timeout=60)
        assert client.is_job_succeeded("t1")
        assert job.status.completion_time is not None

    def test_wait_raises_on_failure(self):
        from training_operator_tpu.api.common import RestartPolicy

        _, client = make_env()
        job = jax_job("boom", duration="1", exit_code="3")
        job.replica_specs["Worker"].restart_policy = RestartPolicy.NEVER
        client.create_job(job)
        with pytest.raises(RuntimeError, match="failed"):
            client.wait_for_job_conditions("boom", timeout=60)

    def test_wait_timeout(self):
        cluster, client = make_env()
        client.create_job(jax_job("slow", duration="500"))
        with pytest.raises(TimeoutException):
            client.wait_for_job_conditions("slow", timeout=5)

    def test_pod_names_and_logs(self):
        import json

        from training_operator_tpu.cluster.runtime import ANNOTATION_SIM_LOG_LINES

        cluster, client = make_env()
        job = jax_job("p1", replicas=2)
        # Each "container" prints its own identity — logs must differ per pod.
        for spec in job.replica_specs.values():
            spec.template.annotations[ANNOTATION_SIM_LOG_LINES] = json.dumps(
                ["step 1 loss 5.0", "step 2 loss 4.2"]
            )
        client.create_job(job)
        client.wait_for_job_conditions(
            "p1", expected_conditions=[JobConditionType.RUNNING], timeout=60
        )
        names = client.get_job_pod_names("p1")
        assert names == ["p1-worker-0", "p1-worker-1"]
        masters = client.get_job_pod_names("p1", is_master=True)
        assert masters == ["p1-worker-0"]  # worker-0 = coordinator
        # Pod OBJECTS with replica-type/index filters (reference
        # get_job_pods, training_client.py:982).
        pods = client.get_job_pods("p1", replica_type="Worker")
        assert [p.name for p in pods] == names
        assert all(p.status.phase.value == "Running" for p in pods)
        one = client.get_job_pods("p1", replica_index=1)
        assert [p.name for p in one] == ["p1-worker-1"]
        # Invalid replica types raise like the reference
        # (training_client.py:1028-1053), instead of silently matching
        # nothing — "Master" isn't a JAXJob replica type, nor is the
        # reference-style lowercase "worker".
        with pytest.raises(ValueError):
            client.get_job_pods("p1", replica_type="Master")
        with pytest.raises(ValueError):
            client.get_job_pods("p1", replica_type="worker")
        logs = client.get_job_logs("p1")
        assert set(logs) == {"p1-worker-0", "p1-worker-1"}
        # Per-pod content: each pod's log names ITS container start, not a
        # shared job-event dump.
        assert "Started container jax" in logs["p1-worker-0"]
        assert "step 2 loss 4.2" in logs["p1-worker-1"]
        # Buffers are genuinely per-pod: a line written to worker-0 must
        # never surface in worker-1's log.
        cluster.api.append_pod_log("default", "p1-worker-0", "unique-to-w0", 0.0)
        logs = client.get_job_logs("p1")
        assert "unique-to-w0" in logs["p1-worker-0"]
        assert "unique-to-w0" not in logs["p1-worker-1"]
        # tail limits per pod.
        tailed = client.get_job_logs("p1", tail=1)
        assert all(len(v.splitlines()) == 1 for v in tailed.values())

    def test_follow_job_logs_streams_a_running_job(self):
        """Tail a RUNNING job: lines emitted after the follow starts are
        streamed, and the generator ends when the job finishes."""
        cluster, client = make_env()
        client.create_job(jax_job("stream", replicas=2, duration="5"))
        client.wait_for_job_conditions(
            "stream", expected_conditions=[JobConditionType.RUNNING], timeout=60
        )
        seen = []
        late_line_at = {"armed": False}

        def tick():
            # Inject a mid-flight stdout line once the follow loop is live.
            if not late_line_at["armed"] and cluster.clock.now() > 1.0:
                late_line_at["armed"] = True
                cluster.api.append_pod_log(
                    "default", "stream-worker-1", "late line from worker 1",
                    cluster.clock.now(),
                )

        cluster.add_ticker(tick)
        for pod_name, line in client.follow_job_logs("stream", timeout=120):
            seen.append((pod_name, line))
        assert any(
            p == "stream-worker-1" and "late line from worker 1" in ln
            for p, ln in seen
        )
        # Terminal lifecycle line observed through the stream too.
        assert any("exited with code 0" in ln for _, ln in seen)
        assert client.is_job_succeeded("stream")

    def test_list_update_delete(self):
        cluster, client = make_env()
        client.create_job(jax_job("a"))
        client.create_job(jax_job("b"))
        assert {j.name for j in client.list_jobs()} == {"a", "b"}
        job = client.get_job("a")
        job.run_policy.suspend = True
        client.update_job(job)
        cluster.run_for(1)
        assert client.is_job_suspended("a")
        client.delete_job("b")
        assert {j.name for j in client.list_jobs()} == {"a"}

    def test_validation_propagates(self):
        _, client = make_env()
        with pytest.raises(ValidationError):
            client.create_job(JAXJob(metadata=ObjectMeta(name="Bad_Name")))

    def test_train_high_level(self):
        cluster, client = make_env()
        # The catalog preset is pre-installed (runtime/presets.py, the
        # reference's manifests/v2/base/runtimes). Customize it the way an
        # operator would — here: sim duration so pods complete.
        rt = cluster.api.get(ClusterTrainingRuntime.KIND, "", "tpu-jax-default")
        tmpl = rt.spec.template[0].template
        tmpl.annotations[ANNOTATION_SIM_DURATION] = "2"
        tmpl.containers[0].resources = {"cpu": 0.5}
        cluster.api.update(rt)
        tj = client.train(
            name="finetune",
            model_uri="hf://org/model",
            dataset_uri="hf://org/data",
            args=["--lr", "1e-4"],
            num_nodes=2,
        )
        assert tj.runtime_ref.name == "tpu-jax-default"
        done = client.wait_for_trainjob("finetune", timeout=60)
        assert done.is_finished()
        jj = cluster.api.get("JAXJob", "default", "finetune")
        inits = [c.name for c in jj.replica_specs["Worker"].template.init_containers]
        assert inits == ["dataset-initializer", "model-initializer"]
        assert jj.replica_specs["Worker"].template.containers[0].args == ["--lr", "1e-4"]

    def test_train_on_fresh_cluster_uses_preset(self):
        """`client.train("j")` must work with ZERO setup: the built-in
        catalog (VERDICT r3 missing #3) supplies `tpu-jax-default`, and the
        resulting JAXJob carries its TPU mesh policy."""
        cluster, client = make_env()
        tj = client.train(name="fresh")
        assert tj.runtime_ref.name == "tpu-jax-default"
        assert cluster.run_until(
            lambda: cluster.api.try_get("JAXJob", "default", "fresh") is not None,
            timeout=30,
        )
        jj = cluster.api.get("JAXJob", "default", "fresh")
        assert jj.tpu_policy is not None
        assert jj.tpu_policy.topology == "2x4"
        assert jj.tpu_policy.mesh_axes == {"data": 2, "fsdp": 4}
        assert jj.replica_specs["Worker"].replicas == 2
        env = jj.replica_specs["Worker"].template.containers[0].env
        assert env.get("TPU_MESH_AXES") == "data=2,fsdp=4"
        # And every other catalog entry resolves by name too.
        for name in ("tpu-jax-multislice", "torch-distributed", "plainml"):
            assert cluster.api.try_get(ClusterTrainingRuntime.KIND, "", name) is not None


class TestInitializers:
    def test_file_provider_roundtrip(self, tmp_path):
        src = tmp_path / "data"
        src.mkdir()
        (src / "train.jsonl").write_text('{"x": 1}\n')
        out = tmp_path / "workspace"
        dest = download(f"file://{src}", str(out))
        assert (out / "data" / "train.jsonl").exists()
        assert dest.endswith("data")

    def test_scheme_dispatch(self):
        assert get_provider("file:///x").scheme == "file"
        assert get_provider("/plain/path").scheme == "file"
        assert get_provider("hf://org/repo").scheme == "hf"
        assert get_provider("s3://bucket/k").scheme == "s3"
        with pytest.raises(ValueError):
            get_provider("gs://nope")

    def test_config_from_env(self):
        cfg = InitializerConfig.from_env(
            {"STORAGE_URI": "hf://d", "TARGET_DIR": "/tmp/t", "ACCESS_TOKEN": "tok"}
        )
        assert cfg.storage_uri == "hf://d"
        assert cfg.target_dir == "/tmp/t"
        assert cfg.access_token == "tok"


class TestSecretResolution:
    def test_secret_ref_resolves_to_token(self):
        from training_operator_tpu.initializers.core import InitializerConfig

        cfg = InitializerConfig.from_env({
            "SECRET_REF": "hf-creds",
            "SECRET_HF_CREDS": "tok-abc",
        })
        assert cfg.access_token == "tok-abc"
        # Explicit ACCESS_TOKEN wins over the reference.
        cfg = InitializerConfig.from_env({
            "ACCESS_TOKEN": "direct",
            "SECRET_REF": "hf-creds",
            "SECRET_HF_CREDS": "tok-abc",
        })
        assert cfg.access_token == "direct"


class TestCreateRetry:
    """SDK-level resilience for the post-host-restart window: the pooled
    keep-alive connection targets a dead socket; the wire client refuses to
    auto-retry non-idempotent calls, so the SDK resolves the ambiguity."""

    class _FlakyAPI:
        def __init__(self, real, failures, exc):
            self._real = real
            self._failures = failures
            self._exc = exc
            self.attempts = 0

        def create(self, obj):
            self.attempts += 1
            if self.attempts <= self._failures:
                raise self._exc
            return self._real.create(obj)

        def __getattr__(self, name):
            return getattr(self._real, name)

    def _client_with_flaky_api(self, failures, exc):
        from training_operator_tpu.cluster.runtime import Cluster

        cluster = Cluster()
        client = TrainingClient(cluster)
        client.api = self._FlakyAPI(cluster.api, failures, exc)
        return cluster, client

    def test_transient_unavailable_retried(self):
        from training_operator_tpu.cluster.httpapi import ApiUnavailableError

        cluster, client = self._client_with_flaky_api(
            2, ApiUnavailableError("conn reset"))
        job = JAXJob(metadata=ObjectMeta(name="r"),
                     replica_specs={"Worker": ReplicaSpec(replicas=1)})
        client.create_job(job)
        assert client.api.attempts == 3
        assert cluster.api.try_get("JAXJob", "default", "r") is not None

    def test_exhausted_retries_raise(self):
        from training_operator_tpu.cluster.httpapi import ApiUnavailableError

        cluster, client = self._client_with_flaky_api(
            99, ApiUnavailableError("host gone"))
        job = JAXJob(metadata=ObjectMeta(name="r2"),
                     replica_specs={"Worker": ReplicaSpec(replicas=1)})
        with pytest.raises(ApiUnavailableError):
            client.create_job(job)

    def test_first_attempt_conflict_is_genuine(self):
        """AlreadyExists on the FIRST attempt is a real name conflict and
        must surface — only a retry's echo is treated as success."""
        from training_operator_tpu.cluster.apiserver import AlreadyExistsError
        from training_operator_tpu.cluster.runtime import Cluster

        cluster = Cluster()
        client = TrainingClient(cluster)
        job = JAXJob(metadata=ObjectMeta(name="dup"),
                     replica_specs={"Worker": ReplicaSpec(replicas=1)})
        client.create_job(job)
        again = JAXJob(metadata=ObjectMeta(name="dup"),
                       replica_specs={"Worker": ReplicaSpec(replicas=1)})
        with pytest.raises(AlreadyExistsError):
            client.create_job(again)
