"""Fleet introspection plane (PR 7): the standing invariant auditor
(INV001-INV006, table-driven per rule), the /fleet wire route + its
version-keyed byte cache, the `top` renderer against a live host, Event
aggregation, the metric satellite (shared Gauge render, labeled
histograms), and the four-tier chaos matrix green under a fail-fast
auditor."""

from __future__ import annotations

import pytest

from training_operator_tpu import observe
from training_operator_tpu.api import common as capi
from training_operator_tpu.api.common import (
    Container,
    JOB_KIND_LABEL,
    JOB_NAME_LABEL,
    JobConditionType,
    PodTemplateSpec,
    ReplicaSpec,
    RestartPolicy,
    update_job_conditions,
)
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta, TPUPolicy
from training_operator_tpu.cluster.inventory import (
    TPU_RESOURCE,
    make_cpu_pool,
    make_tpu_pool,
)
from training_operator_tpu.cluster.objects import (
    Event,
    Pod,
    PodGroup,
    PodGroupPhase,
    PodPhase,
    set_node_condition,
)
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
)
from training_operator_tpu.controllers import OperatorManager, register_all
from training_operator_tpu.observe.invariants import (
    FleetSources,
    InvariantAuditor,
    InvariantViolationError,
    RULES,
)
from training_operator_tpu.utils import metrics

AUDIT_INTERVAL = 10.0


def make_cluster(tpu_slices: int = 2):
    cluster = Cluster(VirtualClock())
    if tpu_slices:
        cluster.add_nodes(make_tpu_pool(tpu_slices, slice_topology="4x4"))
    cluster.add_nodes(make_cpu_pool(2))
    return cluster


def make_auditor(cluster, sources=None, toleration=30.0, **kw):
    return InvariantAuditor(
        cluster.api, cluster.clock.now, sources=sources,
        interval=AUDIT_INTERVAL, toleration_seconds=toleration, **kw,
    )


def detect(cluster, auditor, grace):
    """One audit to open the grace window, advance past it, audit again —
    'detected within one audit interval' once the transient window has
    provably passed."""
    first = auditor.audit()
    cluster.clock.advance(grace + 0.001)
    return first, auditor.audit()


def orphan_pod(api, name="orphan", kind="JAXJob", job="ghost"):
    return api.create(Pod(metadata=ObjectMeta(
        name=name, namespace="default",
        labels={JOB_KIND_LABEL: kind, JOB_NAME_LABEL: job},
    )))


def rule_by_id(rule_id):
    return next(r for r in RULES if r.rule_id == rule_id)


# ---------------------------------------------------------------------------
# Rule catalog, table-driven per INV id
# ---------------------------------------------------------------------------


class TestInvariantRules:
    def test_catalog_is_complete_and_unique(self):
        ids = [r.rule_id for r in RULES]
        assert ids == sorted(set(ids))
        assert ids == [f"INV00{i}" for i in range(1, 10)] + ["INV010", "INV011"]

    def test_inv001_orphaned_pod(self):
        cluster = make_cluster(tpu_slices=0)
        auditor = make_auditor(cluster)
        orphan_pod(cluster.api)
        first, second = detect(cluster, auditor, rule_by_id("INV001").grace)
        assert first == [], "grace must absorb the cascade-GC window"
        assert [v.rule for v in second] == ["INV001"]
        assert second[0].name == "orphan"

    def test_inv001_owned_pod_is_clean(self):
        cluster = make_cluster(tpu_slices=0)
        auditor = make_auditor(cluster)
        cluster.api.create(JAXJob(
            metadata=ObjectMeta(name="alive"),
            replica_specs={"Worker": ReplicaSpec(
                replicas=1, template=PodTemplateSpec(
                    containers=[Container(name="jax")]
                ),
            )},
        ))
        orphan_pod(cluster.api, name="owned", job="alive")
        _, second = detect(cluster, auditor, rule_by_id("INV001").grace)
        assert second == []

    @pytest.mark.parametrize("placement,num_slices,expect", [
        # Gang split across two failure domains while asking for one slice.
        ({"p0": "slice-0-host-0", "p1": "slice-1-host-0"}, 1, "failure domains"),
        # Hosts 0 and 2 of one slice: a hole in the ICI mesh.
        ({"p0": "slice-0-host-0", "p1": "slice-0-host-2"}, 1, "ICI-contiguous"),
        # A recorded placement onto a node that no longer exists.
        ({"p0": "slice-0-host-0", "p1": "gone-host"}, 1, "no longer exists"),
    ])
    def test_inv002_broken_placement(self, placement, num_slices, expect):
        cluster = make_cluster()
        auditor = make_auditor(cluster)
        pg = PodGroup(
            metadata=ObjectMeta(name="gang", namespace="default"),
            min_member=len(placement),
            topology_request="2x4",
            num_slices=num_slices,
            phase=PodGroupPhase.INQUEUE,
            placement=dict(placement),
        )
        cluster.api.create(pg)
        _, second = detect(cluster, auditor, rule_by_id("INV002").grace)
        assert [v.rule for v in second] == ["INV002"], second
        assert expect in second[0].message

    def test_inv002_contiguous_single_slice_is_clean(self):
        cluster = make_cluster()
        auditor = make_auditor(cluster)
        cluster.api.create(PodGroup(
            metadata=ObjectMeta(name="gang", namespace="default"),
            min_member=2,
            topology_request="2x4",
            num_slices=1,
            phase=PodGroupPhase.INQUEUE,
            placement={"p0": "slice-0-host-1", "p1": "slice-0-host-2"},
        ))
        _, second = detect(cluster, auditor, rule_by_id("INV002").grace)
        assert second == []

    def test_inv003_running_pod_on_dead_node(self):
        cluster = make_cluster()
        toleration = 30.0
        auditor = make_auditor(cluster, toleration=toleration)
        node = cluster.api.get("Node", "", "slice-0-host-0")
        set_node_condition(node, "Ready", "Unknown", "NodeStatusUnknown",
                           "heartbeat lapsed", cluster.clock.now())
        cluster.api.update(node, check_version=False)
        pod = Pod(metadata=ObjectMeta(name="stale", namespace="default"))
        pod.node_name = "slice-0-host-0"
        pod.status.phase = PodPhase.RUNNING
        cluster.api.create(pod)
        # Within the toleration: not even a candidate.
        cluster.clock.advance(toleration / 2)
        assert auditor.audit() == []
        # Past toleration the candidate opens; past the grace it reports.
        cluster.clock.advance(toleration)
        _, second = detect(cluster, auditor, rule_by_id("INV003").grace)
        assert [v.rule for v in second] == ["INV003"]
        assert "NotReady" in second[0].message

    def test_inv003_vanished_node(self):
        cluster = make_cluster(tpu_slices=0)
        auditor = make_auditor(cluster)
        pod = Pod(metadata=ObjectMeta(name="lost", namespace="default"))
        pod.node_name = "never-existed"
        pod.status.phase = PodPhase.RUNNING
        cluster.api.create(pod)
        _, second = detect(cluster, auditor, rule_by_id("INV003").grace)
        assert [v.rule for v in second] == ["INV003"]
        assert "vanished" in second[0].message

    def test_inv004_wedged_expectation(self):
        cluster = make_cluster(tpu_slices=0)
        ages = {"JAXJob|default/j/worker/pods": 400.0}
        auditor = make_auditor(
            cluster, sources=FleetSources(expectations=lambda: dict(ages))
        )
        # grace 0: the 5-minute TTL in the age check IS the grace.
        out = auditor.audit()
        assert [v.rule for v in out] == ["INV004"]
        # A young expectation is normal informer asynchrony.
        ages = {"JAXJob|default/j/worker/pods": 5.0}
        assert auditor.audit() == []

    def test_inv004_live_manager_feed(self):
        """The real provider chain: a raised-but-never-observed expectation
        in a live manager trips INV004 once it ages past the TTL."""
        from training_operator_tpu.engine.expectations import (
            EXPECTATION_TIMEOUT_SECONDS,
        )

        cluster = make_cluster(tpu_slices=0)
        mgr = OperatorManager(cluster, resync_period=None)
        register_all(mgr)
        _, jc = mgr.controllers["JAXJob"]
        jc.expectations.raise_expectations("default/wedged/worker/pods", 1, 0)
        auditor = make_auditor(
            cluster,
            sources=FleetSources(expectations=mgr.unfulfilled_expectations),
        )
        assert auditor.audit() == []
        cluster.clock.advance(EXPECTATION_TIMEOUT_SECONDS + 1)
        out = auditor.audit()
        assert [v.rule for v in out] == ["INV004"]
        assert "default/wedged" in out[0].name

    def test_inv005_journal_and_ring_bounds(self):
        cluster = make_cluster(tpu_slices=0)
        state = {"bytes": 10, "ring": {"Pod": (4, 8192)}}
        auditor = make_auditor(cluster, sources=FleetSources(
            journal_bytes=lambda: state["bytes"],
            journal_bound=lambda: 64,
            resume_ring=lambda: dict(state["ring"]),
        ))
        _, clean = detect(cluster, auditor, rule_by_id("INV005").grace)
        assert clean == []
        state["bytes"] = 1024  # compaction wedged
        state["ring"] = {"Pod": (9000, 8192)}  # ring over its bound
        _, second = detect(cluster, auditor, rule_by_id("INV005").grace)
        assert sorted(v.name for v in second) == ["Pod", "journal"]
        assert all(v.rule == "INV005" for v in second)

    def test_inv006_condition_disagreement(self):
        from training_operator_tpu.runtime.api import (
            TrainJob,
            TrainJobConditionType,
        )

        cluster = make_cluster(tpu_slices=0)
        auditor = make_auditor(cluster)
        tj = TrainJob(metadata=ObjectMeta(name="split", namespace="default"))
        tj.set_condition(TrainJobConditionType.COMPLETE, True,
                         "JobsSucceeded", "done", now=1.0)
        cluster.api.create(tj)
        wj = JAXJob(
            metadata=ObjectMeta(name="split", namespace="default"),
            replica_specs={"Worker": ReplicaSpec(
                replicas=1,
                template=PodTemplateSpec(containers=[Container(name="jax")]),
            )},
        )
        update_job_conditions(wj.status, JobConditionType.FAILED, True,
                              "JobFailed", "boom", now=1.0)
        cluster.api.create(wj)
        _, second = detect(cluster, auditor, rule_by_id("INV006").grace)
        assert [v.rule for v in second] == ["INV006"]
        assert second[0].object_kind == "TrainJob"

    def test_healed_candidate_never_reports(self):
        cluster = make_cluster(tpu_slices=0)
        auditor = make_auditor(cluster)
        orphan_pod(cluster.api)
        before = metrics.invariant_violations.value("INV001")
        assert auditor.audit() == []
        cluster.api.delete("Pod", "default", "orphan")  # healed in time
        cluster.clock.advance(rule_by_id("INV001").grace + 1)
        assert auditor.audit() == []
        assert metrics.invariant_violations.value("INV001") == before

    def test_report_side_effects_once_per_incident(self):
        cluster = make_cluster(tpu_slices=0)
        auditor = make_auditor(cluster)
        orphan_pod(cluster.api)
        before = metrics.invariant_violations.value("INV001")
        _, second = detect(cluster, auditor, rule_by_id("INV001").grace)
        assert second
        # Persisting violation: stays active, but counts ONE incident.
        cluster.clock.advance(AUDIT_INTERVAL)
        third = auditor.audit()
        assert [v.rule for v in third] == ["INV001"]
        assert metrics.invariant_violations.value("INV001") == before + 1
        events = cluster.api.events(object_name="orphan", reason="INV001")
        assert len(events) == 1 and events[0].event_type == "Warning"
        assert metrics.fleet_violations.value() == 1.0
        # Healing zeroes the active gauge.
        cluster.api.delete("Pod", "default", "orphan")
        assert auditor.audit() == []
        assert metrics.fleet_violations.value() == 0.0

    def test_fail_fast_raises(self):
        cluster = make_cluster(tpu_slices=0)
        auditor = make_auditor(cluster, fail_fast=True)
        orphan_pod(cluster.api)
        auditor.audit()
        cluster.clock.advance(rule_by_id("INV001").grace + 1)
        with pytest.raises(InvariantViolationError, match="INV001"):
            auditor.audit()

    def test_attached_auditor_runs_on_the_virtual_clock(self):
        cluster = make_cluster(tpu_slices=0)
        auditor = make_auditor(cluster).attach(cluster)
        cluster.run_for(AUDIT_INTERVAL * 3 + 1)
        assert auditor.audits >= 3
        auditor.detach()

    def test_inv002_span_lands_on_the_gang_timeline(self):
        cluster = make_cluster()
        auditor = make_auditor(cluster)
        cluster.api.create(PodGroup(
            metadata=ObjectMeta(name="gang", namespace="default"),
            min_member=2, topology_request="2x4", num_slices=1,
            phase=PodGroupPhase.INQUEUE,
            placement={"p0": "slice-0-host-0", "p1": "slice-1-host-0"},
        ))
        detect(cluster, auditor, rule_by_id("INV002").grace)
        tl = cluster.api.get_timeline("default", "gang")
        assert tl is not None
        spans = [s for s in tl["spans"] if s["name"] == "invariant"]
        assert spans and spans[0]["attrs"]["rule"] == "INV002"


class TestInv010ShardOwnership:
    """PR 15 satellite: the shard-ownership contract, unit-tested incident/
    grace/heal semantics (the live exercise is the replica-kill soak smoke
    in tests/test_soak.py and the handoff burst in tests/test_shards.py)."""

    GRACE = 5.0

    def _feed(self, state):
        return lambda: {
            "num_shards": state.get("num_shards", 2),
            "grace": self.GRACE,
            "claims": state["claims"],
        }

    def _shard_lease(self, api, shard, holder, renew_time, duration=None):
        from training_operator_tpu.controllers.leader import (
            SHARD_NAMESPACE, shard_lease_name,
        )
        from training_operator_tpu.cluster.objects import Lease

        return api.create(Lease(
            metadata=ObjectMeta(
                name=shard_lease_name(shard), namespace=SHARD_NAMESPACE),
            holder=holder, lease_duration=duration or self.GRACE,
            acquire_time=renew_time, renew_time=renew_time,
        ))

    def test_double_claim_fires_after_grace_and_heals(self):
        cluster = make_cluster(tpu_slices=0)
        state = {"claims": {"op-a": [0, 1], "op-b": [1]}}
        auditor = make_auditor(
            cluster, sources=FleetSources(shards=self._feed(state)))
        first, second = detect(cluster, auditor, rule_by_id("INV010").grace)
        assert first == [], "handoff windows must ride the grace"
        assert [v.rule for v in second] == ["INV010"]
        assert second[0].name == "shard-1"
        assert "op-a" in second[0].message and "op-b" in second[0].message
        # Once per incident, not once per audit pass.
        before = metrics.invariant_violations.value("INV010")
        auditor.audit()
        assert metrics.invariant_violations.value("INV010") == before
        # Heal: the loser observed its lost lease and dropped the claim.
        state["claims"] = {"op-a": [0], "op-b": [1]}
        # Shard leases present and live so the unowned arm stays quiet.
        now = cluster.clock.now()
        self._shard_lease(cluster.api, 0, "op-a", now)
        self._shard_lease(cluster.api, 1, "op-b", now)
        assert auditor.audit() == []

    def test_unowned_past_takeover_grace_fires(self):
        cluster = make_cluster(tpu_slices=0)
        now = cluster.clock.now()
        state = {"claims": {"op-a": [0]}}  # shard 1 claimed by nobody
        auditor = make_auditor(
            cluster, sources=FleetSources(shards=self._feed(state)))
        self._shard_lease(cluster.api, 0, "op-a", now + 1000.0)
        # Shard 1's lease expired long ago: unowned_for > grace already.
        self._shard_lease(cluster.api, 1, "op-dead", now - 100.0)
        first, second = detect(cluster, auditor, rule_by_id("INV010").grace)
        assert first == []
        assert [v.rule for v in second] == ["INV010"]
        assert second[0].name == "shard-1"
        assert "unowned" in second[0].message
        # Heal: a survivor adopts (claims it; lease renewed).
        state["claims"] = {"op-a": [0, 1]}
        assert auditor.audit() == []

    def test_recently_expired_lease_is_within_grace(self):
        """A dead replica's shard is legitimately unowned for up to the
        takeover grace — the lease arithmetic must not condemn it early."""
        cluster = make_cluster(tpu_slices=0)
        now = cluster.clock.now()
        state = {"claims": {"op-a": [0]}}
        auditor = make_auditor(
            cluster, sources=FleetSources(shards=self._feed(state)))
        self._shard_lease(cluster.api, 0, "op-a", now + 1000.0)
        # Expired JUST now: within the takeover grace, survivors still
        # have time — not a violation no matter how long it persists
        # unless the lease stays stale.
        self._shard_lease(cluster.api, 1, "op-dead", now - self.GRACE - 0.5)
        first, second = detect(cluster, auditor, rule_by_id("INV010").grace)
        assert first == [] and second == []

    def test_released_lease_ages_from_the_release_instant(self):
        """A voluntarily released lease (rebalance handoff in flight) is
        backdated by exactly one duration, so the unowned age counts from
        the RELEASE — a fresh release is within the grace no matter how
        negative renew_time looks, and a stale one is condemned."""
        cluster = make_cluster(tpu_slices=0)
        now = cluster.clock.now()
        state = {"claims": {"op-a": [0]}}
        auditor = make_auditor(
            cluster, sources=FleetSources(shards=self._feed(state)))
        self._shard_lease(cluster.api, 0, "op-a", now + 1000.0)
        # Released JUST now: renew_time = release - duration.
        self._shard_lease(cluster.api, 1, "", now - self.GRACE)
        first, second = detect(cluster, auditor, rule_by_id("INV010").grace)
        # After detect's clock advance the release is ~30s old > grace —
        # the candidate appears on the SECOND pass only (first-seen), so
        # no violation yet; a third pass past the rule grace condemns it.
        assert first == [] and second == []
        cluster.clock.advance(rule_by_id("INV010").grace + 0.1)
        third = auditor.audit()
        assert [v.rule for v in third] == ["INV010"]
        assert "release" in third[0].message

    def test_missing_lease_with_live_replicas_fires(self):
        cluster = make_cluster(tpu_slices=0)
        state = {"claims": {"op-a": [0]}}  # shard 1: no claim, no lease
        auditor = make_auditor(
            cluster, sources=FleetSources(shards=self._feed(state)))
        now = cluster.clock.now()
        self._shard_lease(cluster.api, 0, "op-a", now + 1000.0)
        first, second = detect(cluster, auditor, rule_by_id("INV010").grace)
        assert first == []
        assert [v.rule for v in second] == ["INV010"]
        assert "no lease" in second[0].message

    def test_unsharded_and_feedless_are_clean(self):
        cluster = make_cluster(tpu_slices=0)
        # No feed at all.
        auditor = make_auditor(cluster)
        _, second = detect(cluster, auditor, rule_by_id("INV010").grace)
        assert second == []
        # Single shard (unsharded deployment shape).
        state = {"num_shards": 1, "claims": {"op-a": [0]}}
        auditor2 = make_auditor(
            cluster, sources=FleetSources(shards=self._feed(state)))
        _, second = detect(cluster, auditor2, rule_by_id("INV010").grace)
        assert second == []


# ---------------------------------------------------------------------------
# A clean, fully-converged stack audits clean over time
# ---------------------------------------------------------------------------


class TestCleanFleetAuditsClean:
    def test_gang_burst_stays_audit_clean(self):
        from training_operator_tpu.scheduler import GangScheduler, TPUPacker

        cluster = make_cluster()
        DefaultScheduler(cluster)
        SimKubelet(cluster)
        GangScheduler(cluster, TPUPacker())
        mgr = OperatorManager(cluster, gang_enabled=True)
        register_all(mgr)
        auditor = make_auditor(
            cluster,
            sources=FleetSources(expectations=mgr.unfulfilled_expectations),
            fail_fast=True,
        ).attach(cluster)
        tmpl = PodTemplateSpec(
            containers=[Container(name="jax", image="img",
                                  resources={"cpu": 1.0, TPU_RESOURCE: 4.0})],
            annotations={ANNOTATION_SIM_DURATION: "5"},
        )
        jobs = []
        for i in range(3):
            jobs.append(mgr.submit(JAXJob(
                metadata=ObjectMeta(name=f"clean-{i}"),
                replica_specs={"Worker": ReplicaSpec(
                    replicas=2, template=tmpl.copy(),
                    restart_policy=RestartPolicy.EXIT_CODE,
                )},
                tpu_policy=TPUPolicy(accelerator="v5e-8", topology="2x4"),
            )))

        def all_done():
            return all(
                (j := cluster.live(job)) is not None
                and capi.is_succeeded(j.status)
                for job in jobs
            )

        # A fail-fast auditor is ticking throughout: any violation raises
        # out of run_until and fails this test.
        assert cluster.run_until(all_done, timeout=600)
        cluster.run_for(AUDIT_INTERVAL * 6)  # post-convergence soak
        assert auditor.audits >= 5
        assert auditor.last_violations == []


# ---------------------------------------------------------------------------
# Fleet snapshot + gauges
# ---------------------------------------------------------------------------


class TestFleetSnapshot:
    def test_collect_counts_nodes_slices_chips(self):
        cluster = make_cluster(tpu_slices=2)
        pod = Pod(metadata=ObjectMeta(name="busy", namespace="default"))
        pod.spec.containers = [Container(name="c", resources={TPU_RESOURCE: 4.0})]
        pod.node_name = "slice-0-host-0"
        pod.status.phase = PodPhase.RUNNING
        cluster.api.create(pod)
        fleet = observe.collect_fleet(cluster.api, cluster.clock.now())
        assert fleet["nodes"]["total"] == 10  # 8 TPU hosts + 2 CPU
        assert fleet["chips"] == {"total": 32.0, "used": 4.0}
        s0 = next(s for s in fleet["slices"] if s["slice"] == "slice-0")
        assert s0["free_hosts"] == 3 and s0["chips_used"] == 4.0
        assert fleet["whole_free_slices"] == 1
        assert fleet["objects"]["Node"] == 10
        assert fleet["free_tpu_hosts"] == 7

    def test_solver_stats_in_fleet_and_top(self):
        """PR 10 satellite: /fleet (and therefore `top`) carries the gang
        solver's cycle stats from the training_solver_* families."""
        from training_operator_tpu.utils import metrics as M

        cluster = make_cluster(tpu_slices=1)
        before = int(M.solver_cycles.total())
        M.solver_cycles.inc()
        M.solver_incremental_cycles.inc()
        M.solver_groups_resolved.inc(amount=3)
        fleet = observe.collect_fleet(cluster.api, cluster.clock.now())
        solver = fleet["solver"]
        assert solver["cycles"] == before + 1
        assert solver["incremental_cycles"] >= 1
        assert solver["groups_resolved"] >= 3
        assert "snapshot_rebuilds" in solver and "wall_mean_s" in solver
        rendered = observe.render_top(fleet)
        assert "solver:" in rendered and "incremental" in rendered

    def test_job_states_by_kind(self):
        cluster = make_cluster(tpu_slices=0)
        tmpl = PodTemplateSpec(containers=[Container(name="jax")])
        run = JAXJob(metadata=ObjectMeta(name="r"),
                     replica_specs={"Worker": ReplicaSpec(replicas=1, template=tmpl)})
        update_job_conditions(run.status, JobConditionType.RUNNING, True,
                              "JobRunning", "", now=1.0)
        done = JAXJob(metadata=ObjectMeta(name="d"),
                      replica_specs={"Worker": ReplicaSpec(replicas=1, template=tmpl)})
        update_job_conditions(done.status, JobConditionType.SUCCEEDED, True,
                              "JobSucceeded", "", now=2.0)
        pend = JAXJob(metadata=ObjectMeta(name="p"),
                      replica_specs={"Worker": ReplicaSpec(replicas=1, template=tmpl)})
        for j in (run, done, pend):
            cluster.api.create(j)
        fleet = observe.collect_fleet(cluster.api, cluster.clock.now())
        assert fleet["jobs"]["JAXJob"] == {
            "running": 1, "succeeded": 1, "pending": 1,
        }

    def test_collector_publishes_gauges(self):
        cluster = make_cluster(tpu_slices=1)
        collector = observe.FleetCollector(cluster, interval=AUDIT_INTERVAL)
        collector.collect()
        assert metrics.fleet_chips_total.value() == 16.0
        assert metrics.fleet_nodes.value("ready") == 6.0  # 4 TPU + 2 CPU
        assert metrics.fleet_objects.value("Node") == 6.0
        collector.stop()

    def test_emptied_gauge_buckets_are_zeroed(self):
        """A label bucket that empties (every Pending gang admitted, a job
        population drained) must read 0 on the next publish, not hold its
        last value — a phantom pending-gang gauge would tell an autoscaler
        there is work forever."""
        cluster = make_cluster(tpu_slices=0)
        collector = observe.FleetCollector(cluster, interval=AUDIT_INTERVAL)
        pg = PodGroup(metadata=ObjectMeta(name="g", namespace="default"),
                      phase=PodGroupPhase.PENDING)
        cluster.api.create(pg)
        collector.collect()
        assert metrics.fleet_podgroups.value("Pending") == 1.0
        assert metrics.fleet_objects.value("PodGroup") == 1.0
        live = cluster.api.get("PodGroup", "default", "g")
        live.phase = PodGroupPhase.RUNNING
        cluster.api.update(live, check_version=False)
        collector.collect()
        assert metrics.fleet_podgroups.value("Pending") == 0.0
        assert metrics.fleet_podgroups.value("Running") == 1.0
        cluster.api.delete("PodGroup", "default", "g")
        collector.collect()
        assert metrics.fleet_podgroups.value("Running") == 0.0
        assert metrics.fleet_objects.value("PodGroup") == 0.0
        collector.stop()

    def test_collector_ticks_on_the_clock(self):
        cluster = make_cluster(tpu_slices=0)
        collector = observe.FleetCollector(cluster, interval=AUDIT_INTERVAL)
        assert collector.last is None
        cluster.run_for(AUDIT_INTERVAL + 1)
        assert collector.last is not None
        collector.stop()


# ---------------------------------------------------------------------------
# /fleet over the wire + its version-keyed cache, and `top`
# ---------------------------------------------------------------------------


class TestFleetWire:
    @pytest.fixture()
    def served(self):
        from training_operator_tpu.cluster.httpapi import (
            ApiHTTPServer,
            RemoteAPIServer,
        )

        cluster = Cluster()
        cluster.add_nodes(make_tpu_pool(1, slice_topology="2x4"))
        server = ApiHTTPServer(cluster.api, port=0)
        remote = RemoteAPIServer(server.url, timeout=10.0)
        try:
            yield cluster, server, remote
        finally:
            server.close()

    def test_fleet_round_trips(self, served):
        cluster, server, remote = served
        fleet = remote.get_fleet()
        assert fleet["nodes"]["total"] == 2
        assert fleet["chips"]["total"] == 8.0
        assert [s["slice"] for s in fleet["slices"]] == ["slice-0"]
        # The server contributed its own occupancy sources.
        assert "watch_sessions" in fleet["store"]
        assert "resume_ring_events" in fleet["store"]
        assert fleet["violations"] == []

    def test_fleet_cache_hits_until_a_write(self, served):
        cluster, server, remote = served
        hits0 = metrics.wire_fleet_cache_hits.total()
        misses0 = metrics.wire_fleet_cache_misses.total()
        remote.get_fleet()
        remote.get_fleet()
        remote.get_fleet()
        assert metrics.wire_fleet_cache_misses.total() - misses0 == 1
        assert metrics.wire_fleet_cache_hits.total() - hits0 == 2
        # Any store write moves the version and invalidates the snapshot.
        orphan_pod(cluster.api, name="inval")
        remote.get_fleet()
        assert metrics.wire_fleet_cache_misses.total() - misses0 == 2

    def test_fleet_cache_is_age_bounded(self, served):
        """Out-of-store feeds (sessions, journal bytes, the snapshot's own
        clock) change without a store write; with the auditor disabled the
        audit seq never moves either — validity must be age-bounded or a
        quiet store serves a frozen snapshot forever."""
        import time as _t

        cluster, server, remote = served
        server.fleet_cache_max_age = 0.05
        misses0 = metrics.wire_fleet_cache_misses.total()
        t1 = remote.get_fleet()["t"]
        _t.sleep(0.1)
        t2 = remote.get_fleet()["t"]  # no store write in between
        assert metrics.wire_fleet_cache_misses.total() - misses0 == 2
        assert t2 > t1

    def test_violations_ride_fleet_and_invalidate_cache(self, served):
        cluster, server, remote = served
        auditor = InvariantAuditor(
            cluster.api, cluster.clock.now, interval=1.0,
        )
        server.auditor = auditor
        orphan_pod(cluster.api)
        assert remote.get_fleet()["violations"] == []
        auditor.audit()
        # Force the grace window shut deterministically (real clock here):
        # backdate the candidate's first-seen stamp.
        for key in auditor._first_seen:
            auditor._first_seen[key] -= rule_by_id("INV001").grace + 1
        auditor.audit()  # seq moved -> cached bytes invalid
        fleet = remote.get_fleet()
        assert [v["rule"] for v in fleet["violations"]] == ["INV001"]

    def test_top_cli_renders_live_host(self, served, capsys):
        from training_operator_tpu.__main__ import main

        cluster, server, remote = served
        rc = main(["top", "--api-server", server.url])
        out = capsys.readouterr().out
        assert rc == 0
        assert "fleet @" in out
        assert "slice-0" in out
        assert "violations: none" in out

    def test_render_top_shows_violations(self):
        fleet = observe.collect_fleet(Cluster(VirtualClock()).api, 0.0)
        fleet["violations"] = [{
            "rule": "INV003", "object_kind": "Pod", "namespace": "default",
            "name": "stale", "message": "RUNNING on NotReady node", "since": 1.0,
        }]
        text = observe.render_top(fleet)
        assert "1 ACTIVE" in text and "INV003" in text and "default/stale" in text


# ---------------------------------------------------------------------------
# Event aggregation (k8s parity)
# ---------------------------------------------------------------------------


def ev(reason="Backoff", message="restarting", ts=1.0):
    return Event(object_kind="Pod", object_name="p0", namespace="default",
                 event_type="Warning", reason=reason, message=message,
                 timestamp=ts)


class TestEventAggregation:
    def test_identical_events_aggregate(self):
        cluster = make_cluster(tpu_slices=0)
        for ts in (1.0, 2.0, 3.0):
            cluster.api.record_event(ev(ts=ts))
        out = cluster.api.events(object_name="p0")
        assert len(out) == 1
        assert out[0].count == 3
        assert out[0].first_timestamp == 1.0
        assert out[0].timestamp == 3.0

    def test_distinct_messages_stay_distinct(self):
        cluster = make_cluster(tpu_slices=0)
        cluster.api.record_event(ev(message="exit 137"))
        cluster.api.record_event(ev(message="exit 1"))
        cluster.api.record_event(ev(reason="Started", message="exit 137"))
        out = cluster.api.events(object_name="p0")
        assert len(out) == 3
        assert all(e.count == 1 for e in out)

    def test_journal_replay_preserves_counts(self, tmp_path):
        from training_operator_tpu.cluster.apiserver import APIServer
        from training_operator_tpu.cluster.store import HostStore

        api = APIServer()
        store = HostStore(str(tmp_path))
        store.attach(api)
        for ts in (1.0, 2.0, 3.0):
            api.record_event(ev(ts=ts))
        store.close()

        api2 = APIServer()
        store2 = HostStore(str(tmp_path))
        store2.load_into(api2)
        out = api2.events(object_name="p0")
        assert len(out) == 1 and out[0].count == 3
        assert out[0].first_timestamp == 1.0 and out[0].timestamp == 3.0
        store2.close()

    def test_describe_shows_aggregated_count(self):
        cluster = make_cluster(tpu_slices=0)
        cluster.api.create(JAXJob(
            metadata=ObjectMeta(name="noisy"),
            replica_specs={"Worker": ReplicaSpec(
                replicas=1,
                template=PodTemplateSpec(containers=[Container(name="jax")]),
            )},
        ))
        for ts in (1.0, 2.0, 3.0):
            cluster.api.record_event(Event(
                object_kind="JAXJob", object_name="noisy", namespace="default",
                event_type="Warning", reason="Flapping", message="again",
                timestamp=ts,
            ))
        text = observe.render_describe(cluster.api, "default", "noisy")
        assert "Flapping" in text and "(x3)" in text


# ---------------------------------------------------------------------------
# Metric satellite: shared Gauge render + labeled histograms
# ---------------------------------------------------------------------------


class TestMetricSatellite:
    def test_gauge_text_and_json_share_one_view(self):
        from training_operator_tpu.utils.metrics import MetricsRegistry

        reg = MetricsRegistry()
        g = reg.gauge("g_demo", "demo", labels=("state",))
        g.set("ready", value=3.0)
        text = reg.render()
        assert "# TYPE g_demo gauge" in text
        assert 'g_demo{state="ready"} 3.0' in text
        assert reg.snapshot()['g_demo{state="ready"}'] == 3.0

    def test_gauge_render_is_the_shared_counter_renderer(self):
        from training_operator_tpu.utils.metrics import Counter, Gauge

        # The ONLY difference is the TYPE line (satellite: dedup'd render).
        assert Gauge.render is Counter.render
        assert Gauge.METRIC_TYPE == "gauge" and Counter.METRIC_TYPE == "counter"

    def test_labeled_histogram_exposition(self):
        from training_operator_tpu.utils.metrics import MetricsRegistry

        reg = MetricsRegistry()
        h = reg.histogram("h_demo", "demo", buckets=(0.1, 1.0),
                          labels=("kind",))
        h.observe(0.05, "JAXJob")
        h.observe(0.5, "JAXJob")
        h.observe(2.0, "TFJob")
        snap = reg.snapshot()
        assert snap['h_demo_bucket{kind="JAXJob",le="0.1"}'] == 1.0
        assert snap['h_demo_bucket{kind="JAXJob",le="+Inf"}'] == 2.0
        assert snap['h_demo_count{kind="JAXJob"}'] == 2.0
        assert snap['h_demo_sum{kind="TFJob"}'] == 2.0
        text = reg.render()
        assert "# TYPE h_demo histogram" in text
        # One view: every rendered sample is the snapshot's number.
        for line in text.splitlines():
            if line.startswith("h_demo"):
                key, val = line.rsplit(" ", 1)
                assert snap[key] == float(val)

    def test_labeled_histogram_registry_guard(self):
        from training_operator_tpu.utils.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.histogram("h_guard", "demo", labels=("kind",))
        assert reg.histogram("h_guard", labels=("kind",)) is not None
        with pytest.raises(ValueError):
            reg.histogram("h_guard", labels=("other",))
        with pytest.raises(ValueError):
            reg.histogram("h_guard")  # plain histogram under the same name

    def test_reconcile_duration_by_kind_observed(self):
        cluster = make_cluster(tpu_slices=0)
        mgr = OperatorManager(cluster, resync_period=None)
        register_all(mgr)
        before = metrics.reconcile_duration.labels("JAXJob").count
        mgr.submit(JAXJob(
            metadata=ObjectMeta(name="timed"),
            replica_specs={"Worker": ReplicaSpec(
                replicas=1,
                template=PodTemplateSpec(containers=[Container(
                    name="jax", image="img", resources={"cpu": 0.5},
                )]),
            )},
        ))
        cluster.step()
        cluster.step()
        assert metrics.reconcile_duration.labels("JAXJob").count > before
        snap = metrics.registry.snapshot()
        assert 'training_reconcile_duration_seconds_count{kind="JAXJob"}' in snap


# ---------------------------------------------------------------------------
# All four chaos tiers at once, under a fail-fast auditor
# ---------------------------------------------------------------------------


class TestChaosMatrixWithAuditor:
    def test_four_tiers_green_under_fail_fast_audit(self):
        import logging

        from training_operator_tpu.cluster.chaos import (
            APIChaos,
            ChaosMonkey,
            NodeChaos,
            WireChaos,
        )
        from training_operator_tpu.cluster.httpapi import (
            ApiHTTPServer,
            ApiServerError,
            ApiUnavailableError,
            RemoteAPIServer,
            RemoteRuntime,
        )
        from training_operator_tpu.controllers.jax import JAXController
        from training_operator_tpu.controllers.nodelifecycle import (
            NodeLifecycleController,
        )

        mgr_log = logging.getLogger("training_operator_tpu.controllers.manager")
        prev_disabled = mgr_log.disabled
        mgr_log.disabled = True

        host = Cluster()  # real clock: the wire tier needs real HTTP
        host.add_nodes(make_cpu_pool(4, cpu_per_node=8.0))
        DefaultScheduler(host)
        kubelet = SimKubelet(host, heartbeat_interval=0.2)
        NodeLifecycleController(host, grace_period=0.8, toleration_seconds=0.3)
        wire = WireChaos(seed=9, error_rate=0.08, reset_rate=0.03)
        server = ApiHTTPServer(host.api, port=0, chaos=wire)
        # The standing auditor in fail-fast mode: any invariant violation
        # raises out of host.step() and fails this test. Toleration matches
        # the lifecycle controller's so INV003 measures the same contract.
        auditor = InvariantAuditor(
            host.api, host.clock.now, sources=server.fleet_sources,
            interval=0.5, fail_fast=True, toleration_seconds=0.3,
        ).attach(host)
        # Fourth tier: store-level conflict injection on version-checked
        # writes (the remote operator's status writes see injected 409s and
        # must heal through the graft arm).
        api_chaos = APIChaos(host, seed=9, conflict_rate=0.05)
        try:
            remote = RemoteAPIServer(server.url, timeout=10.0)
            runtime = RemoteRuntime(remote, tick_interval=0.0)
            for _ in range(50):
                try:
                    mgr = OperatorManager(runtime, resync_period=2.0)
                    mgr.register(JAXController(runtime.api))
                    break
                except (ApiUnavailableError, ApiServerError):
                    continue
            else:
                raise AssertionError("operator never booted through the storm")

            monkey = ChaosMonkey(host, kubelet, seed=9, interval=0.6, budget=3)
            nodes = NodeChaos(host, kubelet, seed=9, interval=1.0, budget=1,
                              recover_after=2.0)
            jobs = []
            for i in range(4):
                tmpl = PodTemplateSpec(
                    containers=[Container(name="jax", resources={"cpu": 1.0})],
                    annotations={ANNOTATION_SIM_DURATION: "1.0"},
                )
                jobs.append(JAXJob(
                    metadata=ObjectMeta(name=f"audited-{i}"),
                    replica_specs={"Worker": ReplicaSpec(
                        replicas=2, template=tmpl,
                        restart_policy=RestartPolicy.EXIT_CODE,
                    )},
                ))
            for job in jobs:
                for _ in range(200):
                    try:
                        remote.create(job)
                        break
                    except (ApiUnavailableError, ApiServerError):
                        continue
                else:
                    raise AssertionError("create never got through the storm")

            def all_done():
                return all(
                    (j := host.api.try_get("JAXJob", "default", f"audited-{i}"))
                    is not None and capi.is_succeeded(j.status)
                    for i in range(4)
                )

            deadline = host.clock.now() + 120.0
            while host.clock.now() < deadline and not (
                all_done() and nodes.kills and monkey.kills
            ):
                host.step()  # auditor violations raise straight through
                try:
                    runtime.step()
                except (ApiUnavailableError, ApiServerError):
                    pass
            assert all_done(), {
                f"audited-{i}": getattr(
                    host.api.try_get("JAXJob", "default", f"audited-{i}"),
                    "status", None,
                )
                for i in range(4)
            }
            # No vacuous pass: every tier actually struck, and the auditor
            # actually audited the storm.
            assert nodes.kills, "NodeChaos never killed a node"
            assert monkey.kills, "ChaosMonkey never killed a pod"
            assert sum(wire.injected.values()) > 0, wire.injected
            assert api_chaos.injected_conflicts > 0
            assert auditor.audits >= 3  # the auditor lived through the storm
            assert auditor.last_violations == []
            mgr.stop()
        finally:
            mgr_log.disabled = prev_disabled
            auditor.detach()
            api_chaos.stop()
            server.close()
