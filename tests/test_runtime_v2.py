"""v2 TrainJob/TrainingRuntime tests.

Parity model: reference test/integration/controller.v2/
trainjob_controller_test.go (TrainJob -> JobSet creation, suspend-only
updates, Torch env assertions, Complete/Failed conditions at :119,159,266,
338,432) and pkg/runtime.v2 framework tests — re-targeted at the
workload-builder redesign (TrainJob -> v1 job kinds -> pods).
"""

import pytest

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import Container, PodTemplateSpec, ReplicaSpec
from training_operator_tpu.api.jobs import ObjectMeta, TPUPolicy
from training_operator_tpu.api.validation import ValidationError
from training_operator_tpu.cluster.inventory import TPU_RESOURCE, make_cpu_pool, make_tpu_pool
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
)
from training_operator_tpu.controllers import OperatorManager, register_all
from training_operator_tpu.runtime import (
    ClusterTrainingRuntime,
    MLPolicy,
    RuntimeRef,
    TorchPolicy,
    Trainer,
    TrainingRuntime,
    TrainJob,
    TrainJobConditionType,
)
from training_operator_tpu.runtime.api import (
    CoschedulingPolicy,
    PodGroupPolicy,
    ReplicatedJobTemplate,
    TrainingRuntimeSpec,
    TRAINER_NODE,
)
from training_operator_tpu.runtime.controller import TrainJobManager
from training_operator_tpu.scheduler import GangScheduler, TPUPacker


def trainer_template(cpu=0.5, chips=None, duration="3"):
    res = {"cpu": cpu}
    if chips:
        res[TPU_RESOURCE] = chips
    t = PodTemplateSpec(
        containers=[Container(name="trainer", image="runtime-img", resources=res)]
    )
    t.annotations[ANNOTATION_SIM_DURATION] = duration
    return t


def tpu_runtime(name="tpu-v5e-16", topology="4x4", num_nodes=4):
    return ClusterTrainingRuntime(
        metadata=ObjectMeta(name=name, namespace=""),
        spec=TrainingRuntimeSpec(
            ml_policy=MLPolicy(
                num_nodes=num_nodes,
                tpu=TPUPolicy(accelerator="v5e-16", topology=topology,
                              mesh_axes={"data": 2, "tensor": 8}),
            ),
            pod_group_policy=PodGroupPolicy(coscheduling=CoschedulingPolicy(60)),
            template=[
                ReplicatedJobTemplate(
                    name=TRAINER_NODE, replicas=num_nodes,
                    template=trainer_template(chips=4.0),
                )
            ],
        ),
    )


def make_env(gang=True):
    cluster = Cluster(VirtualClock())
    cluster.add_nodes(make_tpu_pool(2, slice_topology="4x4"))
    cluster.add_nodes(make_cpu_pool(4))
    DefaultScheduler(cluster)
    SimKubelet(cluster)
    if gang:
        GangScheduler(cluster, TPUPacker())
    v1 = OperatorManager(cluster, gang_enabled=gang)
    register_all(v1)
    v2 = TrainJobManager(cluster)
    return cluster, v2


class TestTrainJobToWorkload:
    def test_tpu_trainjob_end_to_end(self):
        """TrainJob -> JAXJob -> gang-placed pods -> Complete condition."""
        cluster, v2 = make_env()
        v2.submit(tpu_runtime())
        job = TrainJob(
            metadata=ObjectMeta(name="llm-pretrain"),
            runtime_ref=RuntimeRef(name="tpu-v5e-16"),
        )
        v2.submit(job)
        assert cluster.run_until(
            lambda: cluster.api.get("TrainJob", "default", "llm-pretrain").is_finished(),
            timeout=120,
        )
        tj = cluster.api.get("TrainJob", "default", "llm-pretrain")
        done = tj.condition(TrainJobConditionType.COMPLETE)
        assert done is not None and done.status
        # Underlying JAXJob inherited the runtime's TPU policy + mesh env.
        jj = cluster.api.get("JAXJob", "default", "llm-pretrain")
        assert jj.tpu_policy.topology == "4x4"
        pods = cluster.api.list("Pod", "default")
        workers = [p for p in pods if "llm-pretrain" in p.name]
        assert len(workers) == 4
        env = workers[0].spec.containers[0].env
        assert env["TPU_MESH_AXES"] == "data=2,tensor=8"
        assert "COORDINATOR_ADDRESS" in env  # v1 JAX bootstrap still applies
        # All four hosts from one slice (gang placement).
        assert len({p.node_name.rsplit("-host-", 1)[0] for p in workers}) == 1

    def test_trainer_overrides_win(self):
        """TrainJob.trainer overrides runtime template (reference
        jobset/builder.go:140-191 + torch.go precedence)."""
        cluster, v2 = make_env(gang=False)
        rt = ClusterTrainingRuntime(
            metadata=ObjectMeta(name="torch-rt", namespace=""),
            spec=TrainingRuntimeSpec(
                ml_policy=MLPolicy(num_nodes=2, torch=TorchPolicy(num_proc_per_node=4)),
                template=[ReplicatedJobTemplate(name=TRAINER_NODE,
                                                template=trainer_template())],
            ),
        )
        v2.submit(rt)
        job = TrainJob(
            metadata=ObjectMeta(name="ft"),
            runtime_ref=RuntimeRef(name="torch-rt"),
            trainer=Trainer(image="custom:latest", num_nodes=3, num_proc_per_node=8,
                            env={"LR": "1e-4"}),
        )
        v2.submit(job)
        assert cluster.run_until(
            lambda: cluster.api.try_get("PyTorchJob", "default", "ft") is not None,
            timeout=30,
        )
        pt = cluster.api.get("PyTorchJob", "default", "ft")
        spec = pt.replica_specs["Worker"]
        assert spec.replicas == 3  # TrainJob wins over runtime numNodes
        c = spec.template.containers[0]
        assert c.image == "custom:latest"
        assert c.env["LR"] == "1e-4"
        assert c.env["PET_NPROC_PER_NODE"] == "8"

    def test_initializers_become_init_containers(self):
        from training_operator_tpu.runtime.api import DatasetConfig, ModelConfig

        cluster, v2 = make_env(gang=False)
        v2.submit(tpu_runtime(name="rt"))
        job = TrainJob(
            metadata=ObjectMeta(name="with-data"),
            runtime_ref=RuntimeRef(name="rt"),
            dataset_config=DatasetConfig(storage_uri="hf://squad"),
            model_config=ModelConfig(input_storage_uri="hf://llama-3"),
        )
        v2.submit(job)
        assert cluster.run_until(
            lambda: cluster.api.try_get("JAXJob", "default", "with-data") is not None,
            timeout=30,
        )
        jj = cluster.api.get("JAXJob", "default", "with-data")
        inits = jj.replica_specs["Worker"].template.init_containers
        names = [c.name for c in inits]
        assert names == ["dataset-initializer", "model-initializer"]
        assert inits[0].env["STORAGE_URI"] == "hf://squad"

    def test_suspend_and_resume(self):
        cluster, v2 = make_env(gang=False)
        v2.submit(tpu_runtime(name="rt"))
        job = TrainJob(
            metadata=ObjectMeta(name="pausable"),
            runtime_ref=RuntimeRef(name="rt"),
            suspend=True,
        )
        v2.submit(job)
        cluster.run_for(2)
        tj = cluster.api.get("TrainJob", "default", "pausable")
        cond = tj.condition(TrainJobConditionType.SUSPENDED)
        assert cond is not None and cond.status
        jj = cluster.api.get("JAXJob", "default", "pausable")
        assert jj.run_policy.suspend
        assert cluster.api.list("Pod", "default") == []
        # Resume.
        tj.suspend = False
        cluster.api.update(tj, check_version=False)
        assert cluster.run_until(
            lambda: cluster.api.get("TrainJob", "default", "pausable").is_finished(),
            timeout=120,
        )

    def test_missing_runtime_surfaces_condition(self):
        cluster, v2 = make_env(gang=False)
        job = TrainJob(metadata=ObjectMeta(name="orphan"),
                       runtime_ref=RuntimeRef(name="nope"))
        v2.submit(job)
        cluster.run_for(1)
        tj = cluster.api.get("TrainJob", "default", "orphan")
        cond = tj.condition(TrainJobConditionType.CREATED)
        assert cond is not None and not cond.status and cond.reason == "RuntimeNotFound"

    def test_cascade_delete(self):
        cluster, v2 = make_env(gang=False)
        v2.submit(tpu_runtime(name="rt"))
        job = TrainJob(metadata=ObjectMeta(name="gone"), runtime_ref=RuntimeRef(name="rt"))
        v2.submit(job)
        assert cluster.run_until(
            lambda: cluster.api.try_get("JAXJob", "default", "gone") is not None,
            timeout=30,
        )
        cluster.api.delete("TrainJob", "default", "gone")
        cluster.run_for(1)
        assert cluster.api.try_get("JAXJob", "default", "gone") is None


class TestV2Validation:
    def test_trainjob_name_and_ref(self):
        cluster, v2 = make_env(gang=False)
        with pytest.raises(ValidationError):
            v2.submit(TrainJob(metadata=ObjectMeta(name="Bad_Name"),
                               runtime_ref=RuntimeRef(name="rt")))
        with pytest.raises(ValidationError):
            v2.submit(TrainJob(metadata=ObjectMeta(name="ok")))  # no ref

    def test_runtime_single_policy_and_container_count(self):
        cluster, v2 = make_env(gang=False)
        rt = tpu_runtime(name="bad")
        rt.spec.ml_policy.torch = TorchPolicy()
        with pytest.raises(ValidationError):
            v2.submit(rt)
        rt2 = tpu_runtime(name="two-containers")
        rt2.spec.template[0].template.containers.append(Container(name="extra", image="x"))
        with pytest.raises(ValidationError):
            v2.submit(rt2)


class TestReconcileRetry:
    def test_failed_reconcile_retries_the_failed_key(self):
        """A reconcile failure must re-enqueue the key that failed, not the
        last key drained in the same tick (late-binding closure regression)."""
        cluster, v2 = make_env(gang=False)
        calls = []

        def fake_reconcile(ns, name):
            calls.append(name)
            if name == "bad":
                raise RuntimeError("boom")

        v2.controller.reconcile = fake_reconcile
        v2.queue.add("default/bad")
        v2.queue.add("default/ok")
        v2.tick()
        assert calls == ["bad", "ok"]
        calls.clear()
        cluster.run_for(30)  # past the failure backoff delay
        assert "bad" in calls
        assert "ok" not in calls


class TestTolerationsAndTaints:
    def test_override_tolerations_reach_pods_and_gate_placement(self):
        """PodSpecOverride tolerations flow TrainJob -> workload template ->
        pods, and placement honors node taints: with every TPU slice tainted,
        an untolerated TrainJob stays pending while a tolerated one runs
        (reference trainjob_types.go:310-357; taint semantics as in k8s)."""
        from training_operator_tpu.runtime.api import PodSpecOverride

        cluster, v2 = make_env()
        # Taint every TPU node.
        for node in cluster.api.list("Node"):
            if node.accelerator.kind == "tpu":
                node.taints = [
                    {"key": "tpu-reserved", "value": "team-a", "effect": "NoSchedule"}
                ]
                cluster.api.update(node)
        v2.submit(tpu_runtime())

        blocked = TrainJob(
            metadata=ObjectMeta(name="no-toleration"),
            runtime_ref=RuntimeRef(name="tpu-v5e-16"),
        )
        v2.submit(blocked)
        cluster.run_for(10.0)
        pods = [
            p for p in cluster.api.list("Pod", "default")
            if "no-toleration" in p.name and p.node_name
        ]
        assert pods == []  # untolerated: nothing bound onto tainted slices

        tolerated_job = TrainJob(
            metadata=ObjectMeta(name="with-toleration"),
            runtime_ref=RuntimeRef(name="tpu-v5e-16"),
            pod_spec_overrides=[
                PodSpecOverride(
                    tolerations=[
                        {"key": "tpu-reserved", "operator": "Equal",
                         "value": "team-a", "effect": "NoSchedule"}
                    ],
                    volumes=[{"name": "scratch", "emptyDir": {}}],
                )
            ],
        )
        v2.submit(tolerated_job)
        assert cluster.run_until(
            lambda: cluster.api.get("TrainJob", "default", "with-toleration").is_finished(),
            timeout=120,
        )
        workers = [
            p for p in cluster.api.list("Pod", "default") if "with-toleration" in p.name
        ]
        assert len(workers) == 4 and all(p.node_name for p in workers)
        # Tolerations AND volumes arrived on the pods themselves.
        assert workers[0].spec.tolerations[0]["key"] == "tpu-reserved"
        assert workers[0].spec.volumes[0]["name"] == "scratch"

    def test_default_scheduler_respects_taints(self):
        """Non-gang pods: a tainted node is skipped unless tolerated."""
        from training_operator_tpu.cluster.objects import Pod
        from training_operator_tpu.api.common import Container, PodTemplateSpec
        from training_operator_tpu.api.jobs import ObjectMeta as OM

        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_cpu_pool(1))
        node = cluster.api.list("Node")[0]
        node.taints = [{"key": "dedicated", "value": "x", "effect": "NoSchedule"}]
        cluster.api.update(node)
        DefaultScheduler(cluster)
        plain = Pod(metadata=OM(name="plain", namespace="default"),
                    spec=PodTemplateSpec(containers=[Container(name="c", image="i", resources={"cpu": 1.0})]))
        tol = Pod(metadata=OM(name="tol", namespace="default"),
                  spec=PodTemplateSpec(
                      containers=[Container(name="c", image="i", resources={"cpu": 1.0})],
                      tolerations=[{"key": "dedicated", "operator": "Exists"}]))
        cluster.api.create(plain)
        cluster.api.create(tol)
        cluster.run_for(2.0)
        assert cluster.api.get("Pod", "default", "plain").node_name == ""
        assert cluster.api.get("Pod", "default", "tol").node_name != ""


class TestModelExport:
    def test_output_uri_reaches_trainer_env(self):
        """ModelConfig.output_storage_uri (reference reserved the field,
        trainjob_types.go:226-228) rides to the trainer container as
        MODEL_EXPORT_URI."""
        from training_operator_tpu.runtime.api import ModelConfig

        cluster, v2 = make_env()
        v2.submit(tpu_runtime())
        job = TrainJob(
            metadata=ObjectMeta(name="ft-export"),
            runtime_ref=RuntimeRef(name="tpu-v5e-16"),
            model_config=ModelConfig(
                input_storage_uri="hf://org/base",
                output_storage_uri="file:///models/out",
            ),
        )
        v2.submit(job)
        assert cluster.run_until(
            lambda: len(cluster.api.list("Pod", "default")) >= 1, timeout=60
        )
        pod = cluster.api.list("Pod", "default")[0]
        assert pod.spec.containers[0].env["MODEL_EXPORT_URI"] == "file:///models/out"
        # The input side still becomes a model-initializer init container.
        assert any("model" in c.name for c in pod.spec.init_containers)

    def test_file_provider_roundtrip_upload(self, tmp_path):
        from training_operator_tpu.initializers.core import download, upload

        src = tmp_path / "artifact"
        src.mkdir()
        (src / "weights.bin").write_text("w")
        out_uri = f"file://{tmp_path}/exported"
        assert upload(str(src), out_uri) == out_uri
        assert (tmp_path / "exported" / "weights.bin").read_text() == "w"
        got = download(out_uri, str(tmp_path / "fetched"))
        assert (tmp_path / "fetched" / "exported" / "weights.bin").read_text() == "w"
