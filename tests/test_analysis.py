"""Static-analysis tests: speclint rule table, gang-queue analysis, the
admission-webhook lint path, codelint, and the CLI.

Table discipline: every bad spec trips EXACTLY its rule at ERROR level
(warnings may ride along only where noted), and every built-in preset
lints clean — the analyzer must never cry wolf on the stock catalog.
"""

import json

import pytest

from training_operator_tpu.analysis import (
    analyze_gang_queue,
    analyze_runtime,
    analyze_trainjob,
)
from training_operator_tpu.analysis.codelint import check_paths, check_source
from training_operator_tpu.analysis.diagnostics import RULES, Severity
from training_operator_tpu.api.common import Container, PodTemplateSpec
from training_operator_tpu.api.jobs import ObjectMeta, TPUPolicy
from training_operator_tpu.api.validation import ValidationError
from training_operator_tpu.cluster.inventory import TPU_RESOURCE, make_tpu_pool
from training_operator_tpu.cluster.objects import PodGroup, PodGroupPhase
from training_operator_tpu.cluster.runtime import Cluster, VirtualClock
from training_operator_tpu.runtime.api import (
    ClusterTrainingRuntime,
    MLPolicy,
    ReplicatedJobTemplate,
    RuntimeRef,
    TorchPolicy,
    Trainer,
    TrainingRuntimeSpec,
    TrainJob,
    TRAINER_NODE,
)
from training_operator_tpu.runtime.controller import TrainJobManager
from training_operator_tpu.runtime.presets import builtin_runtimes
from training_operator_tpu.runtime.webhooks import LINT_ANNOTATION
from training_operator_tpu.utils import metrics


def rt(
    num_nodes=2,
    topology="2x4",
    num_slices=1,
    accelerator="v5e-8",
    mesh_axes=None,
    torch=None,
    tpu=True,
    template=True,
    name="rt-under-test",
):
    ml = MLPolicy(num_nodes=num_nodes, torch=torch)
    if tpu:
        ml.tpu = TPUPolicy(
            accelerator=accelerator,
            topology=topology,
            num_slices=num_slices,
            mesh_axes=dict(mesh_axes or {}),
        )
    trainer_rj = ReplicatedJobTemplate(
        name=TRAINER_NODE,
        template=PodTemplateSpec(
            containers=[Container(name="trainer", image="trainer-img")]
        ),
    )
    return ClusterTrainingRuntime(
        metadata=ObjectMeta(name=name, namespace=""),
        spec=TrainingRuntimeSpec(
            ml_policy=ml,
            template=[trainer_rj] if template else [],
        ),
    )


def job(name="lint-me", trainer=None, runtime_name="rt-under-test"):
    return TrainJob(
        metadata=ObjectMeta(name=name),
        runtime_ref=RuntimeRef(name=runtime_name),
        trainer=trainer,
    )


class TestPresetCatalog:
    def test_all_builtin_presets_lint_clean(self):
        for preset in builtin_runtimes():
            report = analyze_runtime(preset)
            assert not report.diagnostics, report.render()

    def test_presets_clean_against_matching_inventory(self):
        nodes = make_tpu_pool(2, slice_topology="2x4", chips_per_host=4)
        nodes += make_tpu_pool(2, slice_topology="4x4", chips_per_host=4,
                               slice_prefix="big")
        for preset in builtin_runtimes():
            report = analyze_runtime(preset, nodes=nodes)
            assert report.ok(), report.render()


# (case id, job, runtime, rule that must fire, severity)
RULE_TABLE = [
    ("tpu001-nodes-cannot-tile",
     job(), rt(num_nodes=3, topology="2x4"), "TPU001", Severity.ERROR),
    ("tpu001-proc-disagrees",
     job(trainer=Trainer(num_proc_per_node=3)), rt(), "TPU001", Severity.ERROR),
    ("tpu001-override-times-proc-not-whole-slices",
     job(trainer=Trainer(num_nodes=3, num_proc_per_node=4)), rt(),
     "TPU001", Severity.ERROR),
    ("tpu002-hosts-cannot-tile-minor-axis",
     job(), rt(num_nodes=3, topology="2x6"), "TPU002", Severity.ERROR),
    ("tpu003-mesh-product-wrong",
     job(), rt(mesh_axes={"data": 3}), "TPU003", Severity.ERROR),
    ("tpu004-nodes-not-divisible-by-slices",
     job(), rt(num_nodes=3, num_slices=2), "TPU004", Severity.ERROR),
    ("tpu005-accelerator-suffix-wrong",
     job(), rt(accelerator="v5e-16"), "TPU005", Severity.WARN),
    ("env001-jax-bootstrap-clash",
     job(trainer=Trainer(env={"COORDINATOR_ADDRESS": "h", "SAFE": "1"})),
     rt(), "ENV001", Severity.WARN),
    ("env001-torch-bootstrap-clash",
     job(trainer=Trainer(env={"MASTER_ADDR": "h"})),
     rt(tpu=False, torch=TorchPolicy(num_proc_per_node=1)),
     "ENV001", Severity.WARN),
    ("pol001-elastic-range-inverted",
     job(), rt(tpu=False, torch=TorchPolicy(elastic_min_nodes=4,
                                            elastic_max_nodes=2)),
     "POL001", Severity.ERROR),
    ("pol001-nodes-outside-range",
     job(trainer=Trainer(num_nodes=9)),
     rt(tpu=False, torch=TorchPolicy(elastic_min_nodes=1, elastic_max_nodes=4)),
     "POL001", Severity.ERROR),
    ("pol002-negative-restarts",
     job(), rt(tpu=False, torch=TorchPolicy(max_restarts=-1)),
     "POL002", Severity.ERROR),
    ("rt001-runtime-missing",
     job(), None, "RT001", Severity.ERROR),
    ("rt002-no-trainer-template",
     job(), rt(template=False), "RT002", Severity.WARN),
    ("job001-bad-name",
     job(name="Bad_Name"), rt(), "JOB001", Severity.ERROR),
    ("node001-override-not-whole-slice",
     job(trainer=Trainer(num_nodes=3)), rt(), "NODE001", Severity.WARN),
    # NODE002: multi-host TPU job whose restart budget can't survive one
    # host failure — torchrun's max_restarts defaults to 0 when unset.
    ("node002-torch-max-restarts-unset-defaults-to-zero",
     job(), rt(torch=TorchPolicy()), "NODE002", Severity.WARN),
    ("node002-torch-max-restarts-explicit-zero",
     job(), rt(torch=TorchPolicy(max_restarts=0)), "NODE002", Severity.WARN),
]


class TestRuleTable:
    @pytest.mark.parametrize(
        "case,tj,runtime,rule,severity",
        RULE_TABLE,
        ids=[c[0] for c in RULE_TABLE],
    )
    def test_bad_spec_trips_exactly_its_rule(self, case, tj, runtime, rule, severity):
        report = analyze_trainjob(tj, runtime)
        assert report.has(rule), f"{case}: wanted {rule}, got {report.render()}"
        fired = {d.rule_id for d in report.diagnostics if d.severity == severity}
        assert fired == {rule}, f"{case}: extra {severity.value}s: {report.render()}"
        if severity == Severity.ERROR:
            assert not report.ok()
        else:
            assert report.ok(), report.render()

    def test_zero_num_nodes_diagnosed_not_crashed(self):
        # CLI inline runtimes bypass webhook validation; the analyzer must
        # emit TPU004, not divide by zero.
        report = analyze_trainjob(job(), rt(num_nodes=0))
        assert report.has("TPU004") and not report.ok(), report.render()

    def test_good_spec_with_whole_slice_override_is_clean(self):
        report = analyze_trainjob(job(trainer=Trainer(num_nodes=4)), rt())
        assert not report.diagnostics, report.render()

    def test_every_fired_rule_is_documented(self):
        for _, tj, runtime, rule, _ in RULE_TABLE:
            assert rule in RULES
            r = RULES[rule]
            assert r.catches and r.fix and r.slug


class TestNode002RestartBudget:
    """NODE002 edges beyond the table: the Never-template arm, the
    single-host exemption, and the smallest budget that clears it."""

    def test_never_trainer_template_fires(self):
        from training_operator_tpu.api.common import RestartPolicy

        runtime = rt()
        runtime.spec.template[0].template.restart_policy = RestartPolicy.NEVER
        report = analyze_trainjob(job(), runtime)
        assert report.has("NODE002"), report.render()
        assert report.ok()  # WARN, not fatal

    def test_single_host_job_is_exempt(self):
        # One host = no "surviving workers" to cascade; host loss is plain
        # rescheduling, which node-lost triage covers budget-free.
        report = analyze_trainjob(
            job(), rt(num_nodes=1, topology="1x4", accelerator="v5e-4",
                      torch=TorchPolicy(max_restarts=0)),
        )
        assert not report.has("NODE002"), report.render()

    def test_budget_of_one_clears(self):
        report = analyze_trainjob(job(), rt(torch=TorchPolicy(max_restarts=1)))
        assert not report.has("NODE002"), report.render()


class TestInventoryRules:
    def test_cap001_not_enough_slices(self):
        nodes = make_tpu_pool(1, slice_topology="2x4")
        report = analyze_trainjob(
            job(), rt(num_nodes=4, num_slices=2), nodes=nodes
        )
        assert report.has("CAP001") and not report.ok(), report.render()

    def test_cap001_wrong_family(self):
        nodes = make_tpu_pool(1, slice_topology="2x4", tpu_type="v5p")
        report = analyze_trainjob(job(), rt(), nodes=nodes)
        assert report.has("CAP001"), report.render()

    def test_tpu002_no_slice_geometry_fits(self):
        nodes = make_tpu_pool(2, slice_topology="2x4")
        report = analyze_trainjob(
            job(), rt(num_nodes=4, topology="4x4", accelerator="v5e-16"),
            nodes=nodes,
        )
        assert report.has("TPU002"), report.render()

    def test_matching_inventory_is_clean(self):
        nodes = make_tpu_pool(2, slice_topology="2x4")
        report = analyze_trainjob(job(), rt(), nodes=nodes, podgroups=[])
        assert not report.diagnostics, report.render()


def pending_gang(name, topology, chips=0.0, num_slices=1):
    return PodGroup(
        metadata=ObjectMeta(name=name, namespace="default"),
        min_member=1,
        min_resources={TPU_RESOURCE: chips} if chips else {},
        topology_request=topology,
        num_slices=num_slices,
        phase=PodGroupPhase.PENDING,
    )


class TestGangQueue:
    def test_gang001_never_placeable(self):
        nodes = make_tpu_pool(2, slice_topology="4x4")
        report = analyze_gang_queue([pending_gang("g1", "8x8")], nodes)
        assert report.has("GANG001"), report.render()

    def test_cap002_chip_oversubscription(self):
        nodes = make_tpu_pool(2, slice_topology="4x4")  # 32 chips
        report = analyze_gang_queue(
            [pending_gang("g1", "4x4", chips=16.0)], nodes, extra_chips=32.0
        )
        assert report.has("CAP002"), report.render()

    def test_gang002_slice_contention(self):
        nodes = make_tpu_pool(2, slice_topology="4x4")
        gangs = [pending_gang(f"g{i}", "4x4") for i in range(3)]
        report = analyze_gang_queue(gangs, nodes)
        assert report.has("GANG002"), report.render()
        assert not report.has("GANG001")

    def test_lint_of_existing_job_excludes_its_own_podgroup(self):
        # An exactly-fitting queued job must not double-count: its own
        # pending PodGroup + the extra_chips of the lint pass.
        nodes = make_tpu_pool(1, slice_topology="2x4")  # 8 chips, 1 slice
        own = pending_gang("stored", "2x4", chips=8.0)
        tj = job(name="stored")
        report = analyze_trainjob(tj, rt(), nodes=nodes, podgroups=[own])
        assert not report.has("CAP002") and not report.has("GANG002"), report.render()

    def test_cross_family_gangs_do_not_invent_contention(self):
        # Supply and demand both span all families: queued v5p gangs on a
        # disjoint v5p pool must not trip GANG002 for a v5e job.
        nodes = make_tpu_pool(1, slice_topology="2x4", tpu_type="v5e")
        nodes += make_tpu_pool(4, slice_topology="2x4", tpu_type="v5p",
                               slice_prefix="p")
        gangs = [pending_gang(f"p{i}", "2x4") for i in range(2)]
        report = analyze_trainjob(job(), rt(), nodes=nodes, podgroups=gangs)
        assert not report.has("GANG002"), report.render()

    def test_malformed_queued_topology_is_gang001_not_a_crash(self):
        # PodGroups have no admission hook: junk topology_request must be
        # diagnosed, not allowed to explode every later admission/lint.
        nodes = make_tpu_pool(1, slice_topology="2x4")
        report = analyze_gang_queue([pending_gang("junk", "4x")], nodes)
        assert report.has("GANG001"), report.render()

    def test_junk_node_topology_label_skipped(self):
        nodes = make_tpu_pool(1, slice_topology="2x4")
        for n in nodes:
            n.accelerator.slice_topology = "totally-bogus"
        report = analyze_trainjob(job(), rt(), nodes=nodes)
        # The poisoned slice is dropped, leaving no usable inventory.
        assert report.has("CAP001"), report.render()

    def test_running_gangs_ignored(self):
        nodes = make_tpu_pool(2, slice_topology="4x4")
        g = pending_gang("g1", "8x8")
        g.phase = PodGroupPhase.RUNNING
        report = analyze_gang_queue([g], nodes)
        assert not report.diagnostics, report.render()


def v2_env(nodes=None):
    cluster = Cluster(VirtualClock())
    if nodes:
        cluster.add_nodes(nodes)
    mgr = TrainJobManager(cluster)
    return cluster, mgr


class TestAdmissionLint:
    def test_fatal_rule_rejects_at_admission(self):
        cluster, mgr = v2_env()
        mgr.submit(rt(num_nodes=3, topology="2x4"))
        with pytest.raises(ValidationError) as ei:
            mgr.submit(job())
        assert "TPU001" in str(ei.value)

    def test_warn_rule_annotates_not_rejects(self):
        cluster, mgr = v2_env()
        mgr.submit(rt(accelerator="v5e-16"))  # TPU005 WARN only
        before = metrics.lint_diagnostics.value("TPU005", "WARN")
        mgr.submit(job(name="warned"))
        stored = cluster.api.get(TrainJob.KIND, "default", "warned")
        assert "TPU005" in stored.annotations.get(LINT_ANNOTATION, "")
        assert metrics.lint_diagnostics.value("TPU005", "WARN") == before + 1

    def test_clean_spec_admits_without_annotation(self):
        cluster, mgr = v2_env(nodes=make_tpu_pool(1, slice_topology="2x4"))
        mgr.submit(rt())
        mgr.submit(job(name="clean"))
        stored = cluster.api.get(TrainJob.KIND, "default", "clean")
        assert LINT_ANNOTATION not in stored.annotations

    def test_missing_runtime_still_admits(self):
        # RT001 is advisory at admission: the controller surfaces
        # RuntimeNotFound as a condition (test_runtime_v2 relies on this).
        cluster, mgr = v2_env()
        mgr.submit(job(name="orphan", runtime_name="nope"))
        stored = cluster.api.get(TrainJob.KIND, "default", "orphan")
        assert "RT001" in stored.annotations.get(LINT_ANNOTATION, "")

    def test_runtime_name_dns1035_enforced(self):
        cluster, mgr = v2_env()
        with pytest.raises(ValidationError):
            mgr.submit(rt(name="Bad_Runtime_Name"))


def tenancy_objects():
    from training_operator_tpu.tenancy import ClusterQueue, PriorityClass

    classes = [
        PriorityClass(metadata=ObjectMeta(name="gold", namespace=""), value=900),
        PriorityClass(metadata=ObjectMeta(name="bronze", namespace=""), value=10),
    ]
    queues = [
        ClusterQueue(metadata=ObjectMeta(name="small-q", namespace=""),
                     quota={TPU_RESOURCE: 4.0}),
        ClusterQueue(metadata=ObjectMeta(name="big-q", namespace=""),
                     quota={TPU_RESOURCE: 64.0},
                     borrowing_limit={TPU_RESOURCE: 64.0}),
        ClusterQueue(metadata=ObjectMeta(name="tight-q", namespace=""),
                     quota={TPU_RESOURCE: 4.0},
                     borrowing_limit={TPU_RESOURCE: 2.0}),
    ]
    return classes, queues


def tenancy_job(queue=None, prio=None, name="tenant"):
    from training_operator_tpu.tenancy import (
        PRIORITY_CLASS_LABEL,
        QUEUE_LABEL,
    )

    tj = job(name=name)
    if queue is not None:
        tj.labels[QUEUE_LABEL] = queue
    if prio is not None:
        tj.labels[PRIORITY_CLASS_LABEL] = prio
    return tj


# (case id, queue label, priority label, rule fired or None, severity)
TENANCY_TABLE = [
    ("ten001-unknown-priority-class",
     None, "platinum", "TEN001", Severity.ERROR),
    ("ten001-known-class-clean", None, "gold", None, None),
    ("ten002-unknown-queue", "ghost-q", None, "TEN002", Severity.WARN),
    ("ten002-quota-can-never-fit",
     "small-q", None, "TEN002", Severity.WARN),
    ("ten002-borrowing-still-too-small",
     "tight-q", None, "TEN002", Severity.WARN),
    ("ten002-big-queue-fits", "big-q", None, None, None),
    ("tenancy-unlabeled-job-is-exempt", None, None, None, None),
]


class TestTenancyRules:
    """TEN001/TEN002: tenancy references checked at lint/admission. The
    rt() default gang is 2x4 = 8 chips; small-q caps at 4, tight-q at
    4 + 2 borrowing, big-q comfortably fits it."""

    @pytest.mark.parametrize(
        "case,queue,prio,rule,severity",
        TENANCY_TABLE,
        ids=[c[0] for c in TENANCY_TABLE],
    )
    def test_table(self, case, queue, prio, rule, severity):
        classes, queues = tenancy_objects()
        report = analyze_trainjob(
            tenancy_job(queue=queue, prio=prio), rt(),
            priority_classes=classes, cluster_queues=queues,
        )
        if rule is None:
            assert not report.diagnostics, f"{case}: {report.render()}"
            return
        assert report.has(rule), f"{case}: wanted {rule}, got {report.render()}"
        fired = {d.rule_id for d in report.diagnostics if d.severity == severity}
        assert fired == {rule}, f"{case}: extra {severity.value}s: {report.render()}"
        if severity == Severity.ERROR:
            assert not report.ok()
        else:
            assert report.ok(), report.render()

    def test_rules_skipped_without_tenancy_inputs(self):
        # None = "no tenancy view provided": the analyzer must never guess.
        report = analyze_trainjob(
            tenancy_job(queue="ghost-q", prio="platinum"), rt()
        )
        assert not report.has("TEN001") and not report.has("TEN002")

    def test_ten_rules_documented(self):
        for rule_id in ("TEN001", "TEN002"):
            r = RULES[rule_id]
            assert r.catches and r.fix and r.slug

    def test_ten001_fatal_at_admission(self):
        from training_operator_tpu.tenancy import PRIORITY_CLASS_LABEL

        cluster, mgr = v2_env()
        mgr.submit(rt())
        bad = job(name="classless")
        bad.labels[PRIORITY_CLASS_LABEL] = "no-such-class"
        with pytest.raises(ValidationError) as ei:
            mgr.submit(bad)
        assert "TEN001" in str(ei.value)

    def test_ten002_annotates_not_rejects(self):
        from training_operator_tpu.tenancy import (
            ClusterQueue, QUEUE_LABEL, register_tenancy_admission,
        )

        cluster, mgr = v2_env()
        register_tenancy_admission(cluster.api)
        cluster.api.create(ClusterQueue(
            metadata=ObjectMeta(name="small-q"),
            quota={TPU_RESOURCE: 4.0},
        ))
        mgr.submit(rt())
        queued = job(name="squeezed")
        queued.labels[QUEUE_LABEL] = "small-q"
        mgr.submit(queued)
        stored = cluster.api.get(TrainJob.KIND, "default", "squeezed")
        assert "TEN002" in stored.annotations.get(LINT_ANNOTATION, "")


class TestSDKLint:
    def test_lint_presubmit_object(self):
        from training_operator_tpu.sdk.client import TrainingClient

        cluster, _ = v2_env(nodes=make_tpu_pool(2, slice_topology="2x4"))
        client = TrainingClient(cluster, job_kind="TrainJob")
        good = job(name="ok", runtime_name="tpu-jax-default")
        good.runtime_ref.kind = ClusterTrainingRuntime.KIND
        assert client.lint(good).ok()

        bad = TrainJob(
            metadata=ObjectMeta(name="bad"),
            runtime_ref=RuntimeRef(name="tpu-jax-default"),
            trainer=Trainer(num_proc_per_node=3),
        )
        report = client.lint(bad)
        assert report.has("TPU001") and not report.ok()

    def test_lint_existing_job_by_name(self):
        from training_operator_tpu.sdk.client import TrainingClient

        cluster, mgr = v2_env()
        mgr.submit(rt(accelerator="v5e-16"))
        mgr.submit(job(name="stored"))
        client = TrainingClient(cluster, job_kind="TrainJob")
        report = client.lint("stored")
        assert report.has("TPU005")


class TestCodelint:
    def test_tree_is_clean(self):
        import training_operator_tpu

        pkg_root = training_operator_tpu.__path__[0]
        findings = check_paths([pkg_root])
        assert not findings, "\n".join(f.render() for f in findings)

    def test_scoped_rules_survive_subpath_invocation(self, tmp_path):
        # check_paths on a single file / subdirectory must anchor the scope
        # at the package root, or CL001/CL002 silently turn off.
        import training_operator_tpu

        pkg_root = training_operator_tpu.__path__[0]
        bad_dir = tmp_path / "training_operator_tpu" / "engine"
        bad_dir.mkdir(parents=True)
        bad = bad_dir / "bad.py"
        bad.write_text("import time\ndef tick():\n    time.sleep(1)\n")
        assert [f.rule_id for f in check_paths([str(bad)])] == ["CL001"]
        # And a legal scheduler-side commit stays legal when checked singly.
        sched = check_paths([f"{pkg_root}/scheduler/gang.py"])
        assert not [f for f in sched if f.rule_id == "CL002"], sched

    def test_cl001_sleep_in_control_loop(self):
        src = "import time\ndef tick():\n    time.sleep(1)\n"
        found = check_source("x.py", src, package_rel="engine/x.py")
        assert [f.rule_id for f in found] == ["CL001"]
        # Same code outside a control-loop package is fine (entry points
        # may wall-block).
        assert not check_source("x.py", src, package_rel="cluster/x.py")

    def test_cl002_snapshot_mutation(self):
        src = "def f(snapshot):\n    snapshot.free['n'] = {}\n"
        found = check_source("x.py", src, package_rel="runtime/x.py")
        assert [f.rule_id for f in found] == ["CL002"]
        assert not check_source("x.py", src, package_rel="scheduler/x.py")

    def test_cl002_commit_outside_scheduler(self):
        src = "def f(snap, req):\n    snap.commit(req, 'node')\n"
        found = check_source("x.py", src, package_rel="engine/x.py")
        assert [f.rule_id for f in found] == ["CL002"]

    def test_cl003_naked_thread(self):
        src = ("import threading\n"
               "def f():\n    t = threading.Thread(target=f)\n    t.start()\n")
        found = check_source("x.py", src, package_rel="utils/x.py")
        assert [f.rule_id for f in found] == ["CL003"]

    def test_cl003_nested_function_reports_once(self):
        src = ("import threading\n"
               "def outer():\n"
               "    def inner():\n"
               "        threading.Thread(target=outer).start()\n"
               "    inner()\n")
        found = check_source("x.py", src, package_rel="utils/x.py")
        assert [f.rule_id for f in found] == ["CL003"], found

    def test_cl003_module_level_thread_flagged(self):
        src = "import threading\nthreading.Thread(target=print).start()\n"
        found = check_source("x.py", src, package_rel="utils/x.py")
        assert [f.rule_id for f in found] == ["CL003"], found

    def test_cl004_wire_internal_import_flagged(self):
        src = ("from training_operator_tpu.cluster.wire_watch import _SharedWatch\n")
        found = check_source("x.py", src, package_rel="controllers/x.py")
        assert [f.rule_id for f in found] == ["CL004"]
        src2 = ("from training_operator_tpu.cluster.httpapi import _anything\n")
        found = check_source("x.py", src2, package_rel="engine/x.py")
        assert [f.rule_id for f in found] == ["CL004"]

    def test_cl004_public_facade_imports_ok(self):
        src = ("from training_operator_tpu.cluster.httpapi import (\n"
               "    ApiHTTPServer, RemoteAPIServer, CachedReadAPI)\n")
        assert not check_source("x.py", src, package_rel="sdk/x.py")

    def test_cl004_wire_modules_exempt_among_themselves(self):
        # The four wire modules are one subsystem: wire_server importing a
        # transport helper is inside the seam, not across it.
        src = ("from training_operator_tpu.cluster.wire_transport import _seg_ns\n")
        assert not check_source(
            "wire_server.py", src, package_rel="cluster/wire_server.py"
        )
        # ...but the same import from anywhere else is a violation.
        assert check_source("x.py", src, package_rel="cluster/store.py")

    def test_cl005_metric_registration_outside_metrics(self):
        src = ("from training_operator_tpu.utils import metrics\n"
               "c = metrics.registry.counter('my_total', 'help', ())\n")
        found = check_source("x.py", src, package_rel="controllers/x.py")
        assert [f.rule_id for f in found] == ["CL005"], found
        # All three factory verbs are covered, including a bare `registry`.
        src2 = "h = registry.histogram('x_seconds')\n"
        found2 = check_source("x.py", src2, package_rel="engine/x.py")
        assert [f.rule_id for f in found2] == ["CL005"], found2
        src3 = "g = registry.gauge('depth', '', ())\n"
        assert [f.rule_id for f in check_source(
            "x.py", src3, package_rel="scheduler/x.py"
        )] == ["CL005"]

    def test_cl005_metrics_module_exempt(self):
        # The one legal registration site; USING a metric elsewhere
        # (inc/observe/set) is not a registration and stays legal.
        src = "c = registry.counter('my_total', 'help', ())\n"
        assert not check_source(
            "metrics.py", src, package_rel="utils/metrics.py"
        )
        use = ("from training_operator_tpu.utils import metrics\n"
               "metrics.jobs_created.inc('ns', 'JAXJob')\n"
               "metrics.reconcile_seconds.observe(0.1)\n")
        assert not check_source("x.py", use, package_rel="controllers/x.py")

    def test_cl007_full_store_walk_in_scheduler(self):
        # Unfiltered Pod/Node walks in scheduler/ are the O(cluster)
        # regression CL007 fences off...
        src = ("def solve(api):\n"
               "    pods = api.list('Pod')\n"
               "    nodes = api.list_refs('Node')\n")
        found = check_source("x.py", src, package_rel="scheduler/gang.py")
        assert [f.rule_id for f in found] == ["CL007", "CL007"], found
        # ...but snapshot.py owns the prime/rebuild walks...
        assert not check_source(
            "snapshot.py", src, package_rel="scheduler/snapshot.py"
        )
        # ...and outside scheduler/ the rule does not apply.
        assert not check_source("x.py", src, package_rel="observe/x.py")

    def test_cl007_filtered_and_small_kinds_exempt(self):
        # A namespace/label-filtered list is an index read, not a walk; the
        # tiny control-plane kinds stay legal anywhere in scheduler/.
        src = ("def f(api, ns):\n"
               "    a = api.list('Pod', ns, {'label': 'x'})\n"
               "    b = api.list('PodGroup')\n"
               "    c = api.list_refs('ClusterQueue')\n")
        assert not check_source("x.py", src, package_rel="scheduler/elastic.py")

    def test_cl003_daemon_or_join_ok(self):
        daemon = ("import threading\n"
                  "def f():\n    threading.Thread(target=f, daemon=True).start()\n")
        joined = ("import threading\n"
                  "def f():\n    t = threading.Thread(target=f)\n"
                  "    t.start()\n    t.join()\n")
        assert not check_source("x.py", daemon, package_rel="utils/x.py")
        assert not check_source("x.py", joined, package_rel="utils/x.py")

    # CL012 — HostStore construction outside the shard factory seam
    # (cluster/shards.py make_store): a direct ctor bypasses the routing
    # map, so the object's journal records can land off their mapped shard.
    CL012_TABLE = [
        ("direct-ctor",
         "from training_operator_tpu.cluster.store import HostStore\n"
         "def f(root):\n    return HostStore(root)\n",
         "cluster/runtime.py", ["CL012"]),
        ("attribute-ctor",
         "from training_operator_tpu.cluster import store\n"
         "def f(root):\n    return store.HostStore(root, wal_ring=16)\n",
         "soak/harness.py", ["CL012"]),
        ("module-level-ctor",
         "from training_operator_tpu.cluster.store import HostStore\n"
         "S = HostStore('/tmp/x')\n",
         "observe/fleet.py", ["CL012"]),
        ("factory-module-exempt",
         "def make(root):\n    return HostStore(root)\n",
         "cluster/shards.py", []),
        ("make_store-call-legal",
         "from training_operator_tpu.cluster.shards import make_store\n"
         "def f(root):\n    return make_store(root, num_shards=2)\n",
         "cluster/replication.py", []),
        ("type-hint-not-a-ctor",
         "from training_operator_tpu.cluster.store import HostStore\n"
         "def f(s: HostStore) -> HostStore:\n    return s\n",
         "cluster/replication.py", []),
    ]

    @pytest.mark.parametrize(
        "case,src,rel,want", CL012_TABLE, ids=[c[0] for c in CL012_TABLE]
    )
    def test_cl012_table(self, case, src, rel, want):
        found = check_source(rel.split("/")[-1], src, package_rel=rel)
        assert [f.rule_id for f in found] == want, (case, found)

    def test_cl012_message_names_the_seam(self):
        src = ("from training_operator_tpu.cluster.store import HostStore\n"
               "s = HostStore('/x')\n")
        found = check_source("x.py", src, package_rel="engine/x.py")
        assert len(found) == 1 and "make_store" in found[0].message

    # CL013 — attribution causes must come from the registered taxonomy
    # in observe/attribution.py: no register_cause() calls elsewhere, no
    # free-text {"cause": "..."} strings outside the registered ids.
    CL013_TABLE = [
        ("register-outside",
         "from training_operator_tpu.observe.attribution import register_cause\n"
         "register_cause('my_cause', 'desc')\n",
         "controllers/x.py", ["CL013"]),
        ("attribute-register-outside",
         "from training_operator_tpu.observe import attribution\n"
         "attribution.register_cause('my_cause', 'desc')\n",
         "engine/x.py", ["CL013"]),
        ("register-in-attribution-module",
         "CAUSES = {}\n"
         "def register_cause(c, d):\n    CAUSES[c] = d\n"
         "register_cause('quota_wait', 'waiting on quota')\n",
         "observe/attribution.py", []),
        ("free-text-cause",
         "row = {'cause': 'vibes', 'seconds': 1.0}\n",
         "observe/fleet.py", ["CL013"]),
        ("registered-cause-literal-ok",
         "row = {'cause': 'preemption_displacement', 'seconds': 1.0}\n",
         "observe/fleet.py", []),
        ("dynamic-cause-value-ok",
         "def f(c):\n    return {'cause': c, 'seconds': 0.0}\n",
         "sdk/client.py", []),
    ]

    @pytest.mark.parametrize(
        "case,src,rel,want", CL013_TABLE, ids=[c[0] for c in CL013_TABLE]
    )
    def test_cl013_table(self, case, src, rel, want):
        found = check_source(rel.split("/")[-1], src, package_rel=rel)
        assert [f.rule_id for f in found] == want, (case, found)

    def test_cl013_taxonomy_matches_attribution_registry(self):
        # The lint table is a hardcoded copy; this pins it to the live
        # registry so adding a cause without updating CL013 fails loudly.
        from training_operator_tpu.analysis import codelint
        from training_operator_tpu.observe import attribution

        assert codelint.CAUSE_TAXONOMY == tuple(attribution.CAUSES)


class TestCLI:
    def test_all_presets_exit_zero(self, capsys):
        from training_operator_tpu.analysis.cli import run

        assert run(["--all-presets"]) == 0
        assert "0 error(s)" in capsys.readouterr().out

    def test_bad_spec_exits_nonzero_with_rule_id(self, tmp_path, capsys):
        from training_operator_tpu.analysis.cli import run

        spec = tmp_path / "bad.json"
        spec.write_text(json.dumps({
            "name": "bad",
            "runtime": {"numNodes": 3,
                        "tpu": {"accelerator": "v5e-8", "topology": "2x4"}},
        }))
        assert run([str(spec)]) == 1
        assert "TPU001" in capsys.readouterr().out

    def test_unknown_preset_exits_nonzero(self, capsys):
        from training_operator_tpu.analysis.cli import run

        assert run(["--preset", "nope"]) == 1
        assert "RT001" in capsys.readouterr().out

    def test_inventory_capacity(self, tmp_path, capsys):
        from training_operator_tpu.analysis.cli import run

        inv = tmp_path / "inv.json"
        inv.write_text(json.dumps(
            {"tpu_pools": [{"slices": 1, "topology": "2x4"}]}
        ))
        spec = tmp_path / "big.json"
        spec.write_text(json.dumps({
            "name": "big",
            "runtime": {"numNodes": 4,
                        "tpu": {"accelerator": "v5e-8", "topology": "2x4",
                                "numSlices": 2}},
        }))
        assert run(["--inventory", str(inv), str(spec)]) == 1
        assert "CAP001" in capsys.readouterr().out

    def test_malformed_yaml_is_a_load_error(self, tmp_path, capsys):
        from training_operator_tpu.analysis.cli import run

        spec = tmp_path / "broken.yaml"
        spec.write_text("name: [unclosed\n")
        assert run([str(spec)]) == 2
        assert "cannot load" in capsys.readouterr().err

    def test_zero_nodes_spec_diagnosed_not_crashed(self, tmp_path, capsys):
        from training_operator_tpu.analysis.cli import run

        spec = tmp_path / "zero.json"
        spec.write_text(json.dumps({
            "name": "zero",
            "runtime": {"numNodes": 0,
                        "tpu": {"accelerator": "v5e-8", "topology": "2x4"}},
        }))
        assert run([str(spec)]) == 1
        assert "TPU004" in capsys.readouterr().out

    def test_rules_listing(self, capsys):
        from training_operator_tpu.analysis.cli import run

        assert run(["--rules"]) == 0
        out = capsys.readouterr().out
        for rule_id in RULES:
            assert rule_id in out

    def test_main_module_dispatch(self, capsys):
        from training_operator_tpu.__main__ import main

        assert main(["lint", "--all-presets"]) == 0
