"""Fault injection: jobs must converge under sustained random pod kills.

This is the substrate-level chaos tier the reference lacks — its recovery
machinery (ExitCode triage at common/pod.go:350-374, backoff sums at
core/job.go:95, restart policies) is exercised here under a seeded random
failure schedule instead of one hand-set phase per test.
"""

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import (
    Container,
    JobConditionType,
    PodTemplateSpec,
    ReplicaSpec,
    RestartPolicy,
)
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta
from training_operator_tpu.cluster.chaos import ChaosMonkey
from training_operator_tpu.cluster.inventory import make_cpu_pool
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
)
from training_operator_tpu.controllers.jax import JAXController
from training_operator_tpu.controllers.manager import OperatorManager


def make_env(nodes=8):
    cluster = Cluster(VirtualClock())
    cluster.add_nodes(make_cpu_pool(nodes))
    DefaultScheduler(cluster)
    kubelet = SimKubelet(cluster)
    mgr = OperatorManager(cluster)
    mgr.register(JAXController(cluster.api))
    return cluster, kubelet, mgr


def make_job(name, workers=2, duration="20"):
    tmpl = PodTemplateSpec(
        containers=[Container(name="jax", image="img", resources={"cpu": 1.0})]
    )
    tmpl.annotations[ANNOTATION_SIM_DURATION] = duration
    return JAXJob(
        metadata=ObjectMeta(name=name),
        replica_specs={
            "Worker": ReplicaSpec(
                replicas=workers,
                template=tmpl,
                restart_policy=RestartPolicy.EXIT_CODE,
            )
        },
    )


def succeeded(cluster, name):
    job = cluster.api.get("JAXJob", "default", name)
    return capi.has_condition(job.status, JobConditionType.SUCCEEDED)


class TestChaos:
    def test_jobs_converge_under_random_kills(self):
        """Six SIGKILLs (exit 137 — retryable under the >= 128 rule) across
        three 2-worker jobs: every kill must be triaged as a restart (pod
        deleted + recreated by the engine), and every job must still reach
        Succeeded."""
        cluster, kubelet, mgr = make_env()
        chaos = ChaosMonkey(cluster, kubelet, seed=7, interval=4.0, budget=6)
        for i in range(3):
            mgr.submit(make_job(f"chaos-{i}"))

        assert cluster.run_until(
            lambda: all(succeeded(cluster, f"chaos-{i}") for i in range(3)),
            timeout=600,
        ), [
            (j, cluster.api.get("JAXJob", "default", j).status.conditions[-1])
            for j in (f"chaos-{i}" for i in range(3))
        ]
        # The budget was actually spent on running pods.
        assert len(chaos.kills) == 6, chaos.kills
        # Terminal state: every worker finished despite the kills.
        for i in range(3):
            st = cluster.api.get("JAXJob", "default", f"chaos-{i}").status
            assert st.replica_statuses["Worker"].succeeded == 2

    def test_same_seed_same_kill_sequence(self):
        """Chaos is deterministic: identical seeds replay identical kill
        schedules (name AND time), so a failing chaos run is reproducible."""
        seqs = []
        for _ in range(2):
            cluster, kubelet, mgr = make_env()
            chaos = ChaosMonkey(cluster, kubelet, seed=3, interval=3.0, budget=4)
            mgr.submit(make_job("det", workers=3, duration="60"))
            cluster.run_until(lambda: len(chaos.kills) >= 4, timeout=300)
            seqs.append(list(chaos.kills))
        assert seqs[0] == seqs[1]
        assert len(seqs[0]) == 4

    def test_permanent_exit_code_fails_job(self):
        """A non-retryable exit (1-127) under ExitCode policy must FAIL the
        job — chaos with exit_code=1 proves the triage branch."""
        cluster, kubelet, mgr = make_env()
        ChaosMonkey(cluster, kubelet, seed=1, interval=3.0, budget=1, exit_code=1)
        mgr.submit(make_job("perm", duration="30"))
        assert cluster.run_until(
            lambda: capi.has_condition(
                cluster.api.get("JAXJob", "default", "perm").status,
                JobConditionType.FAILED,
            ),
            timeout=120,
        )


# ---------------------------------------------------------------------------
# Control-plane chaos: API faults x pod kills (VERDICT r3 missing #4)
# ---------------------------------------------------------------------------


class DuplicatePodDetector:
    """Ticker asserting the expectations/claim invariant: at no instant do
    two live (non-terminal) pods exist for the same (job, replica type,
    index) — the duplicate the expectations cache exists to prevent
    (reference expectation/expectation.go:29-40)."""

    def __init__(self, cluster):
        self.cluster = cluster
        self.violations = []
        cluster.add_ticker(self.tick)

    def tick(self):
        import collections

        live = collections.Counter()
        for p in self.cluster.api.list("Pod"):
            if p.is_terminal():
                continue
            key = (
                p.metadata.labels.get(capi.JOB_NAME_LABEL),
                p.metadata.labels.get(capi.REPLICA_TYPE_LABEL),
                p.metadata.labels.get(capi.REPLICA_INDEX_LABEL),
            )
            live[key] += 1
        for key, n in live.items():
            if n > 1:
                self.violations.append((self.cluster.clock.now(), key, n))


class TestControlPlaneChaos:
    """Matrix over (API fault mix) x (pod kills) x seeds. Invariants:
    no duplicate pods ever, no lost jobs, every job converges."""

    def _run(self, seed, conflict=0.0, drop=0.0, dup=0.0, stall=None, kills=False):
        from training_operator_tpu.cluster.chaos import APIChaos, ChaosMonkey

        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_cpu_pool(8))
        DefaultScheduler(cluster)
        kubelet = SimKubelet(cluster)
        # Short resync so dropped events heal within the test horizon.
        mgr = OperatorManager(cluster, resync_period=30.0)
        mgr.register(JAXController(cluster.api))
        detector = DuplicatePodDetector(cluster)
        chaos = APIChaos(
            cluster, seed=seed, conflict_rate=conflict, drop_rate=drop,
            dup_rate=dup, stall=stall, victims=[mgr._watch],
        )
        monkey = None
        if kills:
            monkey = ChaosMonkey(cluster, kubelet, seed=seed, interval=7.0, budget=6)
        jobs = [make_job(f"cp-{seed}-{i}", workers=2, duration="10") for i in range(6)]
        for j in jobs:
            mgr.submit(j)

        def all_done():
            return all(succeeded(cluster, j.name) for j in jobs)

        ok = cluster.run_until(all_done, timeout=2000)
        # Diagnostics on failure: which fault dominated.
        stats = {
            "conflicts": chaos.injected_conflicts,
            "dropped": chaos.dropped_events,
            "duplicated": chaos.duplicated_events,
            "stalled": chaos.stalled_events,
            "kills": len(monkey.kills) if monkey else 0,
        }
        assert ok, (stats, [cluster.api.get("JAXJob", "default", j.name).status
                            for j in jobs])
        assert detector.violations == [], detector.violations
        # No lost jobs: every submitted job still exists.
        assert all(cluster.api.try_get("JAXJob", "default", j.name) for j in jobs)
        chaos.stop()
        return stats

    def test_conflict_storm(self):
        for seed in (1, 2, 3):
            stats = self._run(seed, conflict=0.3)
            assert stats["conflicts"] > 0

    def test_dropped_watch_events(self):
        for seed in (1, 2, 3):
            stats = self._run(seed, drop=0.3)
            assert stats["dropped"] > 0

    def test_duplicated_watch_events(self):
        for seed in (1, 2, 3):
            stats = self._run(seed, dup=0.4)
            assert stats["duplicated"] > 0

    def test_informer_stall(self):
        stats = self._run(7, stall=(5.0, 40.0))
        assert stats["stalled"] > 0

    def test_everything_at_once_with_kills(self):
        """The full storm: conflicts + drops + duplicates + an informer
        stall + SIGKILLed pods, three seeds. The engine must converge every
        job with zero duplicate pods."""
        for seed in (11, 12, 13):
            stats = self._run(
                seed, conflict=0.2, drop=0.2, dup=0.2, stall=(10.0, 30.0),
                kills=True,
            )
            assert stats["kills"] > 0

    def test_scheduler_pause(self):
        """Default-scheduler outage window: pods queue, nothing errors, all
        jobs converge once it returns (GangPause on the scheduler tick)."""
        from training_operator_tpu.cluster.chaos import GangPause

        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_cpu_pool(8))
        sched = DefaultScheduler(cluster)
        SimKubelet(cluster)
        mgr = OperatorManager(cluster, resync_period=30.0)
        mgr.register(JAXController(cluster.api))
        pause = GangPause(cluster, sched.tick, start=0.0, duration=60.0)
        jobs = [make_job(f"sp-{i}", workers=2, duration="5") for i in range(4)]
        for j in jobs:
            mgr.submit(j)
        # Nothing can run while the scheduler is down...
        cluster.run_for(30.0)
        assert all(not succeeded(cluster, j.name) for j in jobs)
        # ...and everything converges after it comes back.
        assert cluster.run_until(
            lambda: all(succeeded(cluster, j.name) for j in jobs), timeout=500
        )
        pause.stop()


class TestGangChaos:
    """The gang path (PodGroup admission, placement persistence, pod
    binding) under injected control-plane conflicts + pod kills: the gang
    scheduler must absorb ConflictErrors (skip + re-derive next tick),
    never crash the cluster loop, and converge every TPU gang."""

    def test_gang_jobs_converge_under_conflict_storm(self):
        from training_operator_tpu.api.jobs import TPUPolicy
        from training_operator_tpu.cluster.chaos import APIChaos, ChaosMonkey
        from training_operator_tpu.cluster.inventory import TPU_RESOURCE, make_tpu_pool
        from training_operator_tpu.scheduler import GangScheduler, TPUPacker

        for seed in (21, 22):
            cluster = Cluster(VirtualClock())
            cluster.add_nodes(make_tpu_pool(2, slice_topology="4x4"))
            DefaultScheduler(cluster)
            kubelet = SimKubelet(cluster)
            GangScheduler(cluster, TPUPacker(), min_solve_interval=0.25)
            mgr = OperatorManager(cluster, gang_enabled=True, resync_period=30.0)
            mgr.register(JAXController(cluster.api))
            detector = DuplicatePodDetector(cluster)
            chaos = APIChaos(cluster, seed=seed, conflict_rate=0.25,
                             victims=[mgr._watch], drop_rate=0.15)
            monkey = ChaosMonkey(cluster, kubelet, seed=seed, interval=9.0, budget=4)

            jobs = []
            for i in range(4):
                tmpl = PodTemplateSpec(
                    containers=[Container(
                        name="jax", image="img",
                        resources={"cpu": 1.0, TPU_RESOURCE: 4.0},
                    )],
                    annotations={ANNOTATION_SIM_DURATION: "12"},
                )
                jobs.append(JAXJob(
                    metadata=ObjectMeta(name=f"gang-{seed}-{i}"),
                    replica_specs={"Worker": ReplicaSpec(
                        replicas=2, template=tmpl,
                        restart_policy=RestartPolicy.EXIT_CODE,
                    )},
                    tpu_policy=TPUPolicy(accelerator="v5e-8", topology="2x4"),
                ))
            for j in jobs:
                mgr.submit(j)

            ok = cluster.run_until(
                lambda: all(succeeded(cluster, j.name) for j in jobs),
                timeout=3000,
            )
            assert ok, {
                "conflicts": chaos.injected_conflicts,
                "kills": len(monkey.kills),
                "statuses": [cluster.api.get("JAXJob", "default", j.name).status
                             for j in jobs],
            }
            assert detector.violations == []
            assert chaos.injected_conflicts > 0
            chaos.stop()
