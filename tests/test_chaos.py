"""Fault injection: jobs must converge under sustained random pod kills.

This is the substrate-level chaos tier the reference lacks — its recovery
machinery (ExitCode triage at common/pod.go:350-374, backoff sums at
core/job.go:95, restart policies) is exercised here under a seeded random
failure schedule instead of one hand-set phase per test.
"""

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import (
    Container,
    JobConditionType,
    PodTemplateSpec,
    ReplicaSpec,
    RestartPolicy,
)
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta
from training_operator_tpu.cluster.chaos import ChaosMonkey
from training_operator_tpu.cluster.inventory import make_cpu_pool
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
)
from training_operator_tpu.controllers.jax import JAXController
from training_operator_tpu.controllers.manager import OperatorManager


def make_env(nodes=8):
    cluster = Cluster(VirtualClock())
    cluster.add_nodes(make_cpu_pool(nodes))
    DefaultScheduler(cluster)
    kubelet = SimKubelet(cluster)
    mgr = OperatorManager(cluster)
    mgr.register(JAXController(cluster.api))
    return cluster, kubelet, mgr


def make_job(name, workers=2, duration="20"):
    tmpl = PodTemplateSpec(
        containers=[Container(name="jax", image="img", resources={"cpu": 1.0})]
    )
    tmpl.annotations[ANNOTATION_SIM_DURATION] = duration
    return JAXJob(
        metadata=ObjectMeta(name=name),
        replica_specs={
            "Worker": ReplicaSpec(
                replicas=workers,
                template=tmpl,
                restart_policy=RestartPolicy.EXIT_CODE,
            )
        },
    )


def succeeded(cluster, name):
    job = cluster.api.get("JAXJob", "default", name)
    return capi.has_condition(job.status, JobConditionType.SUCCEEDED)


class TestChaos:
    def test_jobs_converge_under_random_kills(self):
        """Six SIGKILLs (exit 137 — retryable under the >= 128 rule) across
        three 2-worker jobs: every kill must be triaged as a restart (pod
        deleted + recreated by the engine), and every job must still reach
        Succeeded."""
        cluster, kubelet, mgr = make_env()
        chaos = ChaosMonkey(cluster, kubelet, seed=7, interval=4.0, budget=6)
        for i in range(3):
            mgr.submit(make_job(f"chaos-{i}"))

        assert cluster.run_until(
            lambda: all(succeeded(cluster, f"chaos-{i}") for i in range(3)),
            timeout=600,
        ), [
            (j, cluster.api.get("JAXJob", "default", j).status.conditions[-1])
            for j in (f"chaos-{i}" for i in range(3))
        ]
        # The budget was actually spent on running pods.
        assert len(chaos.kills) == 6, chaos.kills
        # Terminal state: every worker finished despite the kills.
        for i in range(3):
            st = cluster.api.get("JAXJob", "default", f"chaos-{i}").status
            assert st.replica_statuses["Worker"].succeeded == 2

    def test_same_seed_same_kill_sequence(self):
        """Chaos is deterministic: identical seeds replay identical kill
        schedules (name AND time), so a failing chaos run is reproducible."""
        seqs = []
        for _ in range(2):
            cluster, kubelet, mgr = make_env()
            chaos = ChaosMonkey(cluster, kubelet, seed=3, interval=3.0, budget=4)
            mgr.submit(make_job("det", workers=3, duration="60"))
            cluster.run_until(lambda: len(chaos.kills) >= 4, timeout=300)
            seqs.append(list(chaos.kills))
        assert seqs[0] == seqs[1]
        assert len(seqs[0]) == 4

    def test_permanent_exit_code_fails_job(self):
        """A non-retryable exit (1-127) under ExitCode policy must FAIL the
        job — chaos with exit_code=1 proves the triage branch."""
        cluster, kubelet, mgr = make_env()
        ChaosMonkey(cluster, kubelet, seed=1, interval=3.0, budget=1, exit_code=1)
        mgr.submit(make_job("perm", duration="30"))
        assert cluster.run_until(
            lambda: capi.has_condition(
                cluster.api.get("JAXJob", "default", "perm").status,
                JobConditionType.FAILED,
            ),
            timeout=120,
        )
