"""Multi-tenant fleet scheduling: quota admission/borrow/reclaim, priority
ordering, checkpoint-aware preemption, starvation guards, INV007, and the
tenancy surfaces (wire kinds, /fleet queues, describe, top).

Everything drives the public paths a deployment uses — ClusterQueue/
PriorityClass objects in the store, jobs routed via RunPolicy's scheduling
policy, the arbiter consulted by the gang scheduler — never by hand-setting
arbitration state. Virtual clock throughout: every assertion is an exact
instant, so admission order and preemption decisions are pinned, not raced.
"""

import json

import pytest

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import (
    Container,
    JobConditionType,
    PodTemplateSpec,
    ReplicaSpec,
    RestartPolicy,
    RunPolicy,
    SchedulingPolicy,
)
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta, TPUPolicy
from training_operator_tpu.api.validation import ValidationError
from training_operator_tpu.cluster.inventory import TPU_RESOURCE, make_tpu_pool
from training_operator_tpu.cluster.objects import PodGroupPhase, PodPhase
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
)
from training_operator_tpu.cluster import wire
from training_operator_tpu.controllers.jax import JAXController
from training_operator_tpu.controllers.manager import OperatorManager
from training_operator_tpu.engine.core import job_recreate_restarts
from training_operator_tpu.observe.fleet import collect_fleet, render_queues, render_top
from training_operator_tpu.observe.invariants import InvariantAuditor, RULES
from training_operator_tpu.scheduler import GangScheduler, TPUPacker
from training_operator_tpu.tenancy import (
    PREEMPTION_NEVER,
    ClusterQueue,
    PriorityClass,
    TenancyArbiter,
    register_tenancy_admission,
)

SOLVE_TIMEOUT = 2000.0


def make_env(starvation=100_000.0, max_preemptions=3, arbiter=True, slices=2):
    cluster = Cluster(VirtualClock())
    cluster.add_nodes(make_tpu_pool(slices, slice_topology="4x4"))
    DefaultScheduler(cluster)
    SimKubelet(cluster)
    register_tenancy_admission(cluster.api)
    arb = None
    if arbiter:
        arb = TenancyArbiter(
            cluster.api, cluster.clock.now,
            starvation_seconds=starvation, max_preemptions=max_preemptions,
        )
    sched = GangScheduler(cluster, TPUPacker(), arbiter=arb)
    mgr = OperatorManager(cluster, gang_enabled=True)
    mgr.register(JAXController(cluster.api))
    return cluster, mgr, sched


def priority_class(api, name, value, policy=None, default=False):
    pc = PriorityClass(metadata=ObjectMeta(name=name), value=value,
                       global_default=default)
    if policy:
        pc.preemption_policy = policy
    return api.create(pc)


def cluster_queue(api, name, chips, borrow=0.0, weight=1.0, namespaces=()):
    return api.create(ClusterQueue(
        metadata=ObjectMeta(name=name),
        quota={TPU_RESOURCE: float(chips)},
        borrowing_limit={TPU_RESOURCE: float(borrow)} if borrow else {},
        weight=weight,
        namespaces=list(namespaces),
    ))


def gang(name, queue="", prio="", duration="400", workers=4, topology="4x4"):
    """One TPU gang: `workers` x 4-chip hosts of one `topology` sub-mesh."""
    tmpl = PodTemplateSpec(
        containers=[Container(name="jax", image="img",
                              resources={"cpu": 1.0, TPU_RESOURCE: 4.0})],
        annotations={ANNOTATION_SIM_DURATION: duration},
    )
    chips = 4 * workers
    return JAXJob(
        metadata=ObjectMeta(name=name),
        replica_specs={"Worker": ReplicaSpec(
            replicas=workers, template=tmpl,
            restart_policy=RestartPolicy.EXIT_CODE,
        )},
        tpu_policy=TPUPolicy(accelerator=f"v5e-{chips}", topology=topology),
        run_policy=RunPolicy(scheduling_policy=SchedulingPolicy(
            queue=queue, priority_class=prio,
        )),
    )


def running(cluster, name, after=-1.0):
    job = cluster.api.get("JAXJob", "default", name)
    c = capi.get_condition(job.status, JobConditionType.RUNNING)
    return c is not None and c.status and c.last_transition_time > after


def running_at(cluster, name):
    job = cluster.api.get("JAXJob", "default", name)
    c = capi.get_condition(job.status, JobConditionType.RUNNING)
    return c.last_transition_time if c is not None and c.status else None


def succeeded(cluster, name):
    job = cluster.api.get("JAXJob", "default", name)
    return capi.is_succeeded(job.status)


def phase(cluster, name):
    pg = cluster.api.try_get("PodGroup", "default", name)
    return pg.phase if pg is not None else None


# ---------------------------------------------------------------------------
# Quota admission / borrowing / reclaim
# ---------------------------------------------------------------------------


class TestQuotaAdmission:
    def test_quota_caps_admitted_chips(self):
        cluster, mgr, _ = make_env()
        cluster_queue(cluster.api, "team-a", chips=16)
        mgr.submit(gang("a-1", queue="team-a", duration="50"))
        mgr.submit(gang("a-2", queue="team-a", duration="50"))
        assert cluster.run_until(lambda: running(cluster, "a-1"), timeout=60)
        # Pool has a whole free slice, but the QUEUE is full: a-2 waits.
        cluster.run_for(20.0)
        assert not running(cluster, "a-2")
        assert phase(cluster, "a-2") == PodGroupPhase.PENDING
        evs = cluster.api.events(object_name="a-2", reason="QuotaExceeded")
        assert evs and "team-a" in evs[0].message
        # Quota frees when a-1 finishes: a-2 admits (reclaim-on-complete).
        assert cluster.run_until(lambda: running(cluster, "a-2"),
                                 timeout=SOLVE_TIMEOUT)

    def test_borrowing_up_to_limit(self):
        cluster, mgr, _ = make_env()
        cluster_queue(cluster.api, "team-a", chips=16, borrow=16)
        mgr.submit(gang("a-1", queue="team-a"))
        mgr.submit(gang("a-2", queue="team-a"))
        assert cluster.run_until(
            lambda: running(cluster, "a-1") and running(cluster, "a-2"),
            timeout=120,
        )
        fleet = collect_fleet(cluster.api, cluster.clock.now())
        row = {r["queue"]: r for r in fleet["queues"]}["team-a"]
        assert row["admitted_chips"] == 32.0
        assert row["borrowed_chips"] == 16.0

    def test_borrowing_limit_is_hard(self):
        cluster, mgr, _ = make_env()
        cluster_queue(cluster.api, "team-a", chips=16, borrow=8)
        mgr.submit(gang("a-1", queue="team-a"))
        mgr.submit(gang("a-2", queue="team-a"))
        assert cluster.run_until(lambda: running(cluster, "a-1"), timeout=60)
        cluster.run_for(30.0)
        assert phase(cluster, "a-2") == PodGroupPhase.PENDING

    def test_unknown_queue_waits_not_bypasses(self):
        cluster, mgr, _ = make_env()
        cluster_queue(cluster.api, "team-a", chips=16)
        mgr.submit(gang("typo", queue="team-z", duration="50"))
        cluster.run_for(30.0)
        assert not running(cluster, "typo")
        evs = cluster.api.events(object_name="typo", reason="QuotaExceeded")
        assert evs and "does not exist" in evs[0].message
        # Creating the queue (watch-driven re-arbitration) unblocks it.
        cluster_queue(cluster.api, "team-z", chips=16)
        assert cluster.run_until(lambda: running(cluster, "typo"),
                                 timeout=SOLVE_TIMEOUT)

    def test_no_tenancy_objects_is_passthrough(self):
        cluster, mgr, _ = make_env()
        mgr.submit(gang("j-1"))
        mgr.submit(gang("j-2"))
        assert cluster.run_until(
            lambda: running(cluster, "j-1") and running(cluster, "j-2"),
            timeout=120,
        )

    def test_namespace_default_queue_routing(self):
        cluster, mgr, _ = make_env()
        cluster_queue(cluster.api, "ns-queue", chips=16,
                      namespaces=["default"])
        mgr.submit(gang("a-1", duration="50"))  # names no queue
        mgr.submit(gang("a-2", duration="50"))
        assert cluster.run_until(lambda: running(cluster, "a-1"), timeout=60)
        cluster.run_for(20.0)
        # Routed into ns-queue by namespace: the 16-chip quota gates a-2.
        assert phase(cluster, "a-2") == PodGroupPhase.PENDING

    def test_reclaim_preempts_borrower(self):
        cluster, mgr, _ = make_env()
        cluster_queue(cluster.api, "team-a", chips=16, borrow=16)
        cluster_queue(cluster.api, "team-b", chips=16)
        mgr.submit(gang("a-1", queue="team-a", duration="500"))
        mgr.submit(gang("a-2", queue="team-a", duration="500"))
        assert cluster.run_until(
            lambda: running(cluster, "a-1") and running(cluster, "a-2"),
            timeout=120,
        )
        # team-b reclaims its NOMINAL share: the borrowing gang of team-a
        # is displaced even at equal priority.
        mgr.submit(gang("b-1", queue="team-b", duration="100"))
        assert cluster.run_until(lambda: running(cluster, "b-1"),
                                 timeout=SOLVE_TIMEOUT)
        pgs = {p.name: p for p in cluster.api.list("PodGroup")}
        preempted = [n for n, p in pgs.items() if p.preemption_count > 0]
        assert len(preempted) == 1 and preempted[0].startswith("a-")

    def test_reclaim_accounting_is_live_within_one_cycle(self):
        # Two reclaimers arrive while team-a borrows ONE slice's worth.
        # Planning the first eviction returns team-a to nominal quota, so
        # the second reclaimer must see it as a non-borrower in the SAME
        # planning pass and wait — stale accounting would displace both
        # team-a gangs at equal priority for one slice of actual need.
        cluster, mgr, _ = make_env()
        cluster_queue(cluster.api, "team-a", chips=16, borrow=16)
        cluster_queue(cluster.api, "team-b", chips=16)
        cluster_queue(cluster.api, "team-c", chips=16)
        mgr.submit(gang("a-1", queue="team-a", duration="500"))
        mgr.submit(gang("a-2", queue="team-a", duration="500"))
        assert cluster.run_until(
            lambda: running(cluster, "a-1") and running(cluster, "a-2"),
            timeout=120,
        )
        mgr.submit(gang("b-1", queue="team-b", duration="100"))
        mgr.submit(gang("c-1", queue="team-c", duration="100"))
        assert cluster.run_until(
            lambda: running(cluster, "b-1") or running(cluster, "c-1"),
            timeout=SOLVE_TIMEOUT,
        )
        pgs = {p.name: p for p in cluster.api.list("PodGroup")}
        preempted = [n for n, p in pgs.items() if p.preemption_count > 0]
        assert len(preempted) == 1 and preempted[0].startswith("a-")
        # The surviving team-a gang keeps running; everyone converges once
        # capacity actually frees (no futile double displacement).
        assert cluster.run_until(
            lambda: all(succeeded(cluster, n)
                        for n in ("a-1", "a-2", "b-1", "c-1")),
            timeout=2000,
        )


# ---------------------------------------------------------------------------
# Priority ordering + default class
# ---------------------------------------------------------------------------


class TestPriorityOrdering:
    def test_high_priority_tier_solves_first(self):
        cluster, mgr, _ = make_env(slices=1)
        priority_class(cluster.api, "high", 1000)
        priority_class(cluster.api, "low", 100)
        # Both pending before the first solve; only one slice exists.
        mgr.submit(gang("low-j", prio="low", duration="50"))
        mgr.submit(gang("high-j", prio="high", duration="50"))
        assert cluster.run_until(lambda: running(cluster, "high-j"),
                                 timeout=60)
        assert not running(cluster, "low-j")
        # FIFO would have admitted low-j (created first): priority won.
        assert cluster.run_until(lambda: running(cluster, "low-j"),
                                 timeout=SOLVE_TIMEOUT)

    def test_default_priority_class_stamped_from_config(self):
        from training_operator_tpu import config as cfgmod

        old = cfgmod.current()
        try:
            cfg = cfgmod.OperatorConfig(default_priority_class="bronze")
            cfgmod.set_current(cfg)
            cluster, mgr, _ = make_env()
            priority_class(cluster.api, "bronze", 50)
            mgr.submit(gang("plain"))
            assert cluster.run_until(
                lambda: phase(cluster, "plain") is not None, timeout=30
            )
            pg = cluster.api.get("PodGroup", "default", "plain")
            assert pg.priority_class == "bronze"
        finally:
            cfgmod.set_current(old)

    def test_explicit_class_stamped_on_podgroup(self):
        cluster, mgr, _ = make_env()
        priority_class(cluster.api, "gold", 900)
        mgr.submit(gang("vip", prio="gold", queue="q1"))
        assert cluster.run_until(
            lambda: phase(cluster, "vip") is not None, timeout=30
        )
        pg = cluster.api.get("PodGroup", "default", "vip")
        assert pg.priority_class == "gold"
        assert pg.queue == "q1"


# ---------------------------------------------------------------------------
# Preemption: victims, checkpoints, budgets, guards
# ---------------------------------------------------------------------------


class TestPreemption:
    def fill_and_preempt(self, max_preemptions=3):
        cluster, mgr, sched = make_env(max_preemptions=max_preemptions)
        priority_class(cluster.api, "high", 1000)
        priority_class(cluster.api, "low", 100)
        mgr.submit(gang("low-1", prio="low", duration="400"))
        mgr.submit(gang("low-2", prio="low", duration="400"))
        assert cluster.run_until(
            lambda: running(cluster, "low-1") and running(cluster, "low-2"),
            timeout=120,
        )
        cluster.run_for(50.0)
        mgr.submit(gang("prod", prio="high", duration="100"))
        assert cluster.run_until(lambda: running(cluster, "prod"),
                                 timeout=SOLVE_TIMEOUT)
        return cluster, mgr

    def test_preemption_checkpoint_resume_round_trip(self):
        cluster, mgr = self.fill_and_preempt()
        pgs = {p.name: p for p in cluster.api.list("PodGroup")}
        victims = [p for p in pgs.values() if p.preemption_count > 0]
        assert len(victims) == 1
        victim = victims[0]
        assert victim.phase == PodGroupPhase.PENDING
        assert victim.checkpointed_seconds == pytest.approx(50.0, abs=2.0)
        assert cluster.api.events(object_name=victim.name, reason="Preempted")
        assert cluster.api.events(object_name=victim.name, reason="Requeued")
        # Everyone converges; the victim resumed from its checkpoint: with
        # 50s saved it finishes ~350s after resuming, not 400.
        assert cluster.run_until(
            lambda: all(succeeded(cluster, n)
                        for n in ("low-1", "low-2", "prod")),
            timeout=SOLVE_TIMEOUT,
        )
        # Restart budget untouched: preemption rides the retryable path.
        for n in ("low-1", "low-2", "prod"):
            job = cluster.api.get("JAXJob", "default", n)
            assert job_recreate_restarts(job) == 0
        # A full re-run (step 0) of the victim would end at >= 50 + 100 +
        # 400; checkpoint resume lands it a checkpoint earlier.
        assert cluster.clock.now() < 50 + 100 + 400

    def test_recreated_pod_runs_only_remaining_work(self):
        cluster, mgr = self.fill_and_preempt()
        victim = next(p for p in cluster.api.list("PodGroup")
                      if p.preemption_count > 0)
        assert cluster.run_until(
            lambda: any(
                p.status.phase == PodPhase.RUNNING
                for p in cluster.api.list(
                    "Pod", "default",
                    {"training.tpu.dev/job-name": victim.name})
            ),
            timeout=SOLVE_TIMEOUT,
        )
        pod = cluster.api.list(
            "Pod", "default", {"training.tpu.dev/job-name": victim.name}
        )[0]
        dur = float(pod.spec.annotations[ANNOTATION_SIM_DURATION])
        assert dur == pytest.approx(400.0 - victim.checkpointed_seconds,
                                    abs=2.0)

    def test_preemption_picks_cheapest_victims(self):
        cluster, mgr, _ = make_env()
        priority_class(cluster.api, "high", 1000)
        priority_class(cluster.api, "low", 100)
        # slice-0: one whole-slice low gang (16 chips). slice-1: two
        # half-slice low gangs (8 chips each). Staged so the pool fills
        # deterministically regardless of batch-solve spreading.
        mgr.submit(gang("big", prio="low", duration="500"))
        assert cluster.run_until(lambda: running(cluster, "big"), timeout=60)
        mgr.submit(gang("small-1", prio="low", duration="500",
                        workers=2, topology="2x4"))
        mgr.submit(gang("small-2", prio="low", duration="500",
                        workers=2, topology="2x4"))
        assert cluster.run_until(
            lambda: all(running(cluster, n)
                        for n in ("big", "small-1", "small-2")),
            timeout=120,
        )
        # An 8-chip high gang needs one victim: the cheapest (8 chips),
        # never the 16-chip whole-slice gang.
        mgr.submit(gang("urgent", prio="high", duration="50",
                        workers=2, topology="2x4"))
        assert cluster.run_until(lambda: running(cluster, "urgent"),
                                 timeout=SOLVE_TIMEOUT)
        pgs = {p.name: p for p in cluster.api.list("PodGroup")}
        assert pgs["big"].preemption_count == 0
        displaced = [n for n in ("small-1", "small-2")
                     if pgs[n].preemption_count > 0]
        assert len(displaced) == 1

    def test_never_policy_class_does_not_preempt(self):
        cluster, mgr, _ = make_env(slices=1)
        priority_class(cluster.api, "meek", 1000,
                       policy=PREEMPTION_NEVER)
        priority_class(cluster.api, "low", 100)
        mgr.submit(gang("low-1", prio="low", duration="200"))
        assert cluster.run_until(lambda: running(cluster, "low-1"),
                                 timeout=60)
        mgr.submit(gang("polite", prio="meek", duration="50"))
        cluster.run_for(60.0)
        assert not running(cluster, "polite")
        pg = cluster.api.get("PodGroup", "default", "low-1")
        assert pg.preemption_count == 0

    def test_max_preemptions_immunity(self):
        cluster, mgr, _ = make_env(slices=1, max_preemptions=1)
        priority_class(cluster.api, "high", 1000)
        priority_class(cluster.api, "low", 100)
        mgr.submit(gang("victim", prio="low", duration="300"))
        assert cluster.run_until(lambda: running(cluster, "victim"),
                                 timeout=60)
        cluster.run_for(20.0)
        mgr.submit(gang("h-1", prio="high", duration="50"))
        assert cluster.run_until(lambda: running(cluster, "h-1"),
                                 timeout=SOLVE_TIMEOUT)
        # Victim displaced once; resumes after h-1.
        assert cluster.run_until(
            lambda: running(cluster, "victim",
                            after=running_at(cluster, "h-1") or 0.0),
            timeout=SOLVE_TIMEOUT,
        )
        resumed_at = running_at(cluster, "victim")
        mgr.submit(gang("h-2", prio="high", duration="50"))
        cluster.run_for(60.0)
        pg = cluster.api.get("PodGroup", "default", "victim")
        assert pg.preemption_count == 1, "immune victim displaced again"
        # h-2 waits for the victim to finish instead.
        assert cluster.run_until(lambda: running(cluster, "h-2"),
                                 timeout=SOLVE_TIMEOUT)


class TestStarvationGuard:
    def test_low_priority_eventually_runs(self):
        # A CONTINUOUS high-priority stream (one fresh gang every 40s) on a
        # one-slice pool: strict priority would starve the low gang until
        # the stream dries up (t=300); the guard promotes it once it has
        # waited 120s — and the promotion shields it from being preempted
        # right back by the stream.
        cluster, mgr, _ = make_env(slices=1, starvation=120.0)
        priority_class(cluster.api, "high", 1000)
        priority_class(cluster.api, "low", 100)
        mgr.submit(gang("meek", prio="low", duration="50"))
        mgr.submit(gang("h-0", prio="high", duration="50"))
        for i in range(1, 6):
            cluster.schedule_at(
                40.0 * i,
                lambda i=i: mgr.submit(gang(f"h-{i}", prio="high",
                                            duration="50")),
            )
        assert cluster.run_until(lambda: running(cluster, "meek"),
                                 timeout=SOLVE_TIMEOUT)
        meek_at = running_at(cluster, "meek")
        # Strict priority would run meek LAST (~t=300); the guard runs it
        # as soon as it crosses the 120s starvation bound.
        assert 120.0 <= meek_at < 250.0
        assert cluster.run_until(
            lambda: all(succeeded(cluster, f"h-{i}") for i in range(6)),
            timeout=SOLVE_TIMEOUT,
        )


# ---------------------------------------------------------------------------
# Determinism under seeded contention
# ---------------------------------------------------------------------------


class TestDeterminism:
    def _run(self):
        import random

        rng = random.Random(7)
        cluster, mgr, _ = make_env()
        priority_class(cluster.api, "high", 1000)
        priority_class(cluster.api, "low", 100)
        cluster_queue(cluster.api, "t-a", chips=16, borrow=16)
        cluster_queue(cluster.api, "t-b", chips=16, borrow=16)
        names = []
        for i in range(8):
            q = "t-a" if i % 2 == 0 else "t-b"
            name = f"j{i}"
            names.append(name)
            mgr.submit(gang(name, queue=q, prio="low",
                            duration=str(rng.randint(40, 120)),
                            workers=2, topology="2x4"))
        cluster.run_for(30.0)
        mgr.submit(gang("hot", prio="high", duration="60"))
        assert cluster.run_until(
            lambda: all(succeeded(cluster, n) for n in names + ["hot"]),
            timeout=SOLVE_TIMEOUT,
        )
        admitted = [
            (e.object_name, round(e.timestamp, 3))
            for e in cluster.api.events(reason="GangAdmitted")
        ]
        preempted = [
            (e.object_name, round(e.timestamp, 3))
            for e in cluster.api.events(reason="Preempted")
            if e.object_kind == "PodGroup"
        ]
        return admitted, preempted

    def test_same_seed_same_decisions(self):
        first = self._run()
        second = self._run()
        assert first == second


# ---------------------------------------------------------------------------
# INV007 + the chaos matrix with the arbiter live
# ---------------------------------------------------------------------------


class TestInvariants:
    def test_inv007_registered(self):
        assert any(r.rule_id == "INV007" for r in RULES)

    def test_inv007_fires_on_over_admission(self):
        cluster, mgr, _ = make_env()
        cluster_queue(cluster.api, "team-a", chips=16)
        mgr.submit(gang("a-1", queue="team-a", duration="800"))
        assert cluster.run_until(lambda: running(cluster, "a-1"), timeout=60)
        # Shrink the quota below live usage: the arbiter never reclaims
        # unpressured capacity, so the standing auditor must surface it.
        cq = cluster.api.get("ClusterQueue", "", "team-a")
        cq.quota = {TPU_RESOURCE: 8.0}
        cluster.api.update(cq, check_version=False)
        auditor = InvariantAuditor(cluster.api, cluster.clock.now)
        assert auditor.audit() == []  # grace absorbs the first sighting
        cluster.run_for(35.0)
        violations = auditor.audit()
        assert [v.rule for v in violations] == ["INV007"]
        assert violations[0].name == "team-a"
        assert "16" in violations[0].message

    def test_inv007_clean_under_arbiter(self):
        cluster, mgr, _ = make_env()
        cluster_queue(cluster.api, "team-a", chips=16, borrow=8)
        mgr.submit(gang("a-1", queue="team-a", duration="50"))
        mgr.submit(gang("a-2", queue="team-a", duration="50"))
        auditor = InvariantAuditor(cluster.api, cluster.clock.now)
        assert cluster.run_until(
            lambda: succeeded(cluster, "a-1") and succeeded(cluster, "a-2"),
            timeout=SOLVE_TIMEOUT,
        )
        cluster.run_for(40.0)
        assert auditor.audit() == []

def test_chaos_matrix_with_tenancy():
    """The PR 5/7 chaos matrix with queues, priorities, AND the fail-fast
    auditor (all seven INV rules incl. INV007 quota accounting) live: pod
    kills + node loss over a contested pool, every job still converges,
    no invariant ever fires."""
    from training_operator_tpu.cluster.chaos import ChaosMonkey, NodeChaos
    from training_operator_tpu.controllers.nodelifecycle import (
        NodeLifecycleController,
    )
    from training_operator_tpu.observe import FleetSources

    cluster = Cluster(VirtualClock())
    cluster.add_nodes(make_tpu_pool(4, slice_topology="4x4"))
    DefaultScheduler(cluster)
    kubelet = SimKubelet(cluster, heartbeat_interval=5.0)
    NodeLifecycleController(cluster, grace_period=12.0, toleration_seconds=6.0)
    register_tenancy_admission(cluster.api)
    arb = TenancyArbiter(cluster.api, cluster.clock.now,
                         starvation_seconds=100_000.0)
    GangScheduler(cluster, TPUPacker(), arbiter=arb)
    mgr = OperatorManager(cluster, gang_enabled=True)
    mgr.register(JAXController(cluster.api))

    priority_class(cluster.api, "high", 1000)
    priority_class(cluster.api, "low", 100)
    cluster_queue(cluster.api, "t-a", chips=32, borrow=16)
    cluster_queue(cluster.api, "t-b", chips=32, borrow=16)

    auditor = InvariantAuditor(
        cluster.api, cluster.clock.now,
        sources=FleetSources(expectations=mgr.unfulfilled_expectations),
        interval=10.0, fail_fast=True, toleration_seconds=6.0,
    ).attach(cluster)

    names = []
    for i in range(6):
        name = f"c{i}"
        names.append(name)
        mgr.submit(gang(
            name, queue="t-a" if i % 2 else "t-b",
            prio="low" if i < 4 else "high",
            duration="120", workers=2, topology="2x4",
        ))

    monkey = ChaosMonkey(cluster, kubelet, seed=5, interval=11.0, budget=3)
    node_chaos = NodeChaos(cluster, kubelet, seed=9, interval=45.0, budget=2,
                           recover_after=30.0)

    def all_done():
        return all(succeeded(cluster, n) for n in names)

    assert cluster.run_until(all_done, timeout=20_000), (
        "contested chaos burst did not converge"
    )
    monkey.stop()
    node_chaos.stop()
    # Quiescent close: fleet must audit clean after convergence too.
    cluster.run_for(30.0)
    assert auditor.audit() == []
    assert auditor.audits > 0


# ---------------------------------------------------------------------------
# Wire, fleet, describe, admission surfaces
# ---------------------------------------------------------------------------


class TestSurfaces:
    def test_tenancy_kinds_wire_roundtrip(self):
        pc = PriorityClass(metadata=ObjectMeta(name="gold"), value=900,
                           global_default=True, description="vip")
        cq = ClusterQueue(
            metadata=ObjectMeta(name="team-a"),
            quota={TPU_RESOURCE: 64.0},
            borrowing_limit={TPU_RESOURCE: 16.0},
            weight=2.0, namespaces=["prod", "staging"],
        )
        for obj in (pc, cq):
            data = json.loads(json.dumps(wire.encode(obj)))
            back = wire.decode(data)
            assert back == obj
            # Compiled codec agrees with the reflection spec.
            assert wire.reflect_encode(obj) == wire.encode(obj)

    def test_podgroup_preemption_fields_roundtrip(self):
        from training_operator_tpu.cluster.objects import PodGroup

        pg = PodGroup(metadata=ObjectMeta(name="g"), preemption_count=2,
                      last_preempted_at=12.5, checkpointed_seconds=99.25)
        back = wire.decode(json.loads(json.dumps(wire.encode(pg))))
        assert back.preemption_count == 2
        assert back.checkpointed_seconds == 99.25
        # Old payloads without the fields decode to the defaults.
        data = wire.encode(pg)
        for key in ("preemption_count", "last_preempted_at",
                    "checkpointed_seconds"):
            data.pop(key)
        old = wire.decode(data)
        assert old.preemption_count == 0

    def test_admission_rejects_malformed_objects(self):
        cluster, _, _ = make_env()
        with pytest.raises(ValidationError):
            cluster.api.create(ClusterQueue(
                metadata=ObjectMeta(name="neg"),
                quota={TPU_RESOURCE: -1.0},
            ))
        with pytest.raises(ValidationError):
            cluster.api.create(ClusterQueue(
                metadata=ObjectMeta(name="w0"), weight=0.0,
            ))
        with pytest.raises(ValidationError):
            cluster.api.create(PriorityClass(
                metadata=ObjectMeta(name="bad-policy"),
                preemption_policy="Sometimes",
            ))
        with pytest.raises(ValidationError):
            cluster.api.create(PriorityClass(
                metadata=ObjectMeta(name="Bad_Name"), value=1,
            ))

    def test_fleet_queue_gauges_and_top(self):
        from training_operator_tpu.observe.fleet import FleetCollector
        from training_operator_tpu.utils import metrics

        cluster, mgr, _ = make_env()
        cluster_queue(cluster.api, "team-a", chips=16)
        cluster_queue(cluster.api, "idle-q", chips=8)
        mgr.submit(gang("a-1", queue="team-a", duration="200"))
        mgr.submit(gang("a-2", queue="team-a", duration="200"))
        assert cluster.run_until(lambda: running(cluster, "a-1"), timeout=60)
        collector = FleetCollector(cluster, interval=5.0)
        fleet = collector.collect()
        rows = {r["queue"]: r for r in fleet["queues"]}
        assert rows["team-a"]["admitted_chips"] == 16.0
        assert rows["team-a"]["pending_chips"] == 16.0
        assert rows["idle-q"]["admitted_chips"] == 0.0
        assert metrics.queue_admitted_chips.value("team-a") == 16.0
        assert metrics.queue_pending_chips.value("team-a") == 16.0
        rendered = render_top(fleet)
        assert "CLUSTERQUEUE" in rendered and "team-a" in rendered
        assert "team-a" in render_queues(fleet["queues"])
        collector.stop()

    def test_describe_shows_tenancy_and_preempt_phase(self):
        cluster, mgr, _ = make_env(slices=1)
        priority_class(cluster.api, "high", 1000)
        priority_class(cluster.api, "low", 100)
        mgr.submit(gang("victim", prio="low", duration="300"))
        assert cluster.run_until(lambda: running(cluster, "victim"),
                                 timeout=60)
        cluster.run_for(20.0)
        mgr.submit(gang("hot", prio="high", duration="50"))
        assert cluster.run_until(lambda: running(cluster, "hot"),
                                 timeout=SOLVE_TIMEOUT)
        from training_operator_tpu.observe import render_describe

        text = render_describe(cluster.api, "default", "victim")
        assert "Preemptions: 1" in text
        assert "low" in text and "Queue:" in text
        tl = cluster.api.get_timeline("default", "victim")
        assert any(s.get("name") == "preempt" for s in tl["spans"])

    def test_config_knob_validation(self):
        from training_operator_tpu.config import OperatorConfig

        with pytest.raises(ValueError):
            OperatorConfig(tenancy_max_preemptions=-1).validate()
        cfg = OperatorConfig(default_priority_class="x",
                             tenancy_starvation_seconds=0.0)
        cfg.validate()
