"""Deployment-surface round-trip for the tail-SLO scheduler knobs
(VERDICT r4 weak #2: `drain_reserve_seconds` / `max_drain_fraction` were
constructor arguments only — "a documented SLO knob nobody can turn isn't
an SLO knob"). Pinned: CLI flags -> OperatorConfig -> the TPUPacker that
wire_cluster_services actually constructs, plus config-file parsing and
validation bounds.
"""

import json

import pytest

from training_operator_tpu.__main__ import (
    build_config,
    parse_args,
    wire_cluster_services,
)
from training_operator_tpu.cluster.runtime import Cluster, VirtualClock
from training_operator_tpu.config import OperatorConfig


def _packer_from(cfg):
    """Build the cluster services exactly as the process entry point does
    and dig out the gang scheduler's placer."""
    cluster = Cluster(VirtualClock())
    wire_cluster_services(cluster, cfg)
    from training_operator_tpu.scheduler.gang import GangScheduler

    gangs = [t for t in cluster._tickers
             if getattr(t, "__self__", None).__class__ is GangScheduler]
    assert gangs, "gang scheduler not wired"
    return gangs[0].__self__.placer


class TestTailSLOKnobs:
    def test_cli_flags_reach_the_packer(self):
        args = parse_args([
            "--gang-scheduler-name", "tpu-packer",
            "--drain-reserve-seconds", "150",
            "--max-drain-fraction", "0.15",
            "--aging-seconds", "120",
        ])
        cfg = build_config(args)
        packer = _packer_from(cfg)
        assert packer.drain_reserve_seconds == 150.0
        assert packer.max_drain_fraction == 0.15
        assert packer.aging_seconds == 120.0

    def test_config_file_reaches_the_packer(self, tmp_path):
        path = tmp_path / "op.json"
        path.write_text(json.dumps({
            "drain_reserve_seconds": 0,  # disables drain reservations
            "max_drain_fraction": 0.2,
            "aging_seconds": 600,
        }))
        args = parse_args(["--config", str(path)])
        cfg = build_config(args)
        packer = _packer_from(cfg)
        assert packer.drain_reserve_seconds == 0
        assert packer.max_drain_fraction == 0.2
        assert packer.aging_seconds == 600

    def test_defaults_match_measured_sweet_spot(self):
        packer = _packer_from(OperatorConfig())
        assert packer.drain_reserve_seconds == 300.0
        assert packer.max_drain_fraction == 0.08
        assert packer.aging_seconds == 300.0

    def test_validation_bounds(self):
        with pytest.raises(ValueError):
            OperatorConfig(max_drain_fraction=1.5).validate()
        with pytest.raises(ValueError):
            OperatorConfig(aging_seconds=-1).validate()


class TestSolverKnobs:
    """PR 10 satellite (same discipline as the tail-SLO knobs): the
    incremental-solver knobs ride CLI flags -> OperatorConfig -> the
    GangScheduler/TPUPacker wire_cluster_services actually constructs.
    `solver_incremental=False` pins today's pre-incremental behavior as
    the compat arm."""

    def _sched_from(self, cfg):
        cluster = Cluster(VirtualClock())
        wire_cluster_services(cluster, cfg)
        from training_operator_tpu.scheduler.gang import GangScheduler

        gangs = [t for t in cluster._tickers
                 if getattr(t, "__self__", None).__class__ is GangScheduler]
        assert gangs, "gang scheduler not wired"
        return gangs[0].__self__

    def test_cli_flags_reach_scheduler_and_packer(self):
        args = parse_args([
            "--no-solver-incremental",
            "--solver-kernel", "jax",
            "--snapshot-selfcheck-every", "64",
        ])
        cfg = build_config(args)
        sched = self._sched_from(cfg)
        assert sched.incremental is False
        assert sched._maintainer is None  # compat arm: per-cycle snapshots
        assert sched.snapshot_selfcheck_every == 64
        assert sched.placer.kernel == "jax"

    def test_config_file_round_trip(self, tmp_path):
        path = tmp_path / "op.json"
        path.write_text(json.dumps({
            "solver_incremental": True,
            "solver_kernel": "python",
            "snapshot_selfcheck_every": 8,
        }))
        cfg = build_config(parse_args(["--config", str(path)]))
        sched = self._sched_from(cfg)
        assert sched.incremental is True
        assert sched._maintainer is not None
        assert sched.snapshot_selfcheck_every == 8
        assert sched.placer.kernel == "python"
        # CLI overrides the file (the standard precedence).
        cfg2 = build_config(parse_args(
            ["--config", str(path), "--solver-kernel", "numpy"]
        ))
        assert cfg2.solver_kernel == "numpy"

    def test_defaults_incremental_numpy(self):
        cfg = OperatorConfig()
        assert cfg.solver_incremental is True
        assert cfg.solver_kernel == "numpy"
        assert cfg.snapshot_selfcheck_every == 0
        sched = self._sched_from(cfg)
        assert sched.incremental is True
        assert sched.placer.kernel == "numpy"

    def test_validation_bounds(self):
        with pytest.raises(ValueError):
            OperatorConfig(solver_kernel="cuda").validate()
        with pytest.raises(ValueError):
            OperatorConfig(snapshot_selfcheck_every=-1).validate()
        with pytest.raises(ValueError):
            from training_operator_tpu.scheduler import TPUPacker

            TPUPacker(kernel="fortran")


class TestDurabilityKnobs:
    """VERDICT r5 Next #8, same discipline as the tail-SLO knobs above: a
    documented durability knob nobody can turn isn't a knob. CLI flags ->
    OperatorConfig -> the HostStore run_host actually constructs."""

    def test_cli_flags_reach_the_store(self, tmp_path):
        from training_operator_tpu.__main__ import make_host_store

        args = parse_args([
            "--compact-every", "128",
            "--compact-max-journal-bytes", "1048576",
            "--journal-fsync",
        ])
        cfg = build_config(args)
        store = make_host_store(cfg, str(tmp_path))
        assert store.compact_every == 128
        assert store.compact_max_bytes == 1048576
        assert store.fsync_per_record is True
        store.close()

    def test_config_file_reaches_the_store(self, tmp_path):
        from training_operator_tpu.__main__ import make_host_store

        path = tmp_path / "op.json"
        path.write_text(json.dumps({
            "compact_every": 16,
            "compact_max_journal_bytes": 0,  # disables the bytes trigger
            "journal_fsync": False,
        }))
        args = parse_args(["--config", str(path)])
        cfg = build_config(args)
        store = make_host_store(cfg, str(tmp_path / "state"))
        assert store.compact_every == 16
        assert store.compact_max_bytes == 0
        assert store.fsync_per_record is False
        store.close()

    def test_defaults_match_store_defaults(self, tmp_path):
        from training_operator_tpu.__main__ import make_host_store
        from training_operator_tpu.cluster.store import HostStore

        store = make_host_store(OperatorConfig(), str(tmp_path))
        bare = HostStore(str(tmp_path / "bare"))
        assert store.compact_every == bare.compact_every == 4096
        assert store.compact_max_bytes == bare.compact_max_bytes == 64 * 1024 * 1024
        assert store.fsync_per_record is bare.fsync_per_record is False
        store.close()
        bare.close()

    def test_validation_bounds(self):
        with pytest.raises(ValueError):
            OperatorConfig(compact_every=0).validate()
        with pytest.raises(ValueError):
            OperatorConfig(compact_max_journal_bytes=-1).validate()
        with pytest.raises(ValueError):
            OperatorConfig(watch_ring_size=0).validate()


class TestWatchRingKnob:
    def test_cli_flag_reaches_the_wire_server(self):
        from training_operator_tpu.cluster.httpapi import ApiHTTPServer
        from training_operator_tpu.cluster.runtime import Cluster

        args = parse_args(["--watch-ring-size", "33"])
        cfg = build_config(args)
        cluster = Cluster()
        server = ApiHTTPServer(cluster.api, port=0,
                               resume_ring_size=cfg.watch_ring_size)
        try:
            assert server._ring.size == 33
        finally:
            server.close()


class TestReplicationKnobs:
    """PR 9 satellite: the replication_* knobs ride the same
    flag -> OperatorConfig -> real-construction path as every other knob
    (make_host_store for the WAL ring; StandbyController for the tail)."""

    def test_cli_flags_reach_config_and_store(self, tmp_path):
        from training_operator_tpu.__main__ import make_host_store

        args = parse_args([
            "--replication-wal-ring", "128",
            "--replication-lease-seconds", "2.5",
            "--replication-poll-timeout", "0.75",
            "--replication-max-lag-seconds", "11.0",
        ])
        cfg = build_config(args)
        assert cfg.replication_wal_ring == 128
        assert cfg.replication_lease_seconds == 2.5
        assert cfg.replication_poll_timeout == 0.75
        assert cfg.replication_max_lag_seconds == 11.0
        store = make_host_store(cfg, str(tmp_path))
        assert store.wal_ring == 128
        store.close()

    def test_config_file_round_trip(self, tmp_path):
        path = tmp_path / "op.json"
        path.write_text(json.dumps({
            "replication_wal_ring": 64,
            "replication_lease_seconds": 3.0,
            "replication_poll_timeout": 1.5,
            "replication_max_lag_seconds": 45.0,
        }))
        args = parse_args(["--config", str(path)])
        cfg = build_config(args)
        assert cfg.replication_wal_ring == 64
        assert cfg.replication_lease_seconds == 3.0
        assert cfg.replication_poll_timeout == 1.5
        assert cfg.replication_max_lag_seconds == 45.0
        # CLI overrides the file (the standard precedence).
        args = parse_args(["--config", str(path),
                           "--replication-lease-seconds", "9"])
        assert build_config(args).replication_lease_seconds == 9.0

    def test_knobs_reach_the_standby_controller(self, tmp_path):
        from training_operator_tpu.cluster.replication import StandbyController

        cfg = build_config(parse_args([
            "--replication-poll-timeout", "0.5",
            "--replication-lease-seconds", "4.0",
        ]))
        cluster = Cluster()
        ctrl = StandbyController(
            cluster, "http://127.0.0.1:1",
            poll_timeout=cfg.replication_poll_timeout,
            lease_duration=cfg.replication_lease_seconds,
        )
        assert ctrl.poll_timeout == 0.5
        assert ctrl.lease_duration == 4.0

    def test_defaults_match_store_defaults(self, tmp_path):
        from training_operator_tpu.__main__ import make_host_store
        from training_operator_tpu.cluster.store import HostStore

        store = make_host_store(OperatorConfig(), str(tmp_path))
        bare = HostStore(str(tmp_path / "bare"))
        assert store.wal_ring == bare.wal_ring == 65536
        store.close()
        bare.close()

    def test_validation_bounds(self):
        with pytest.raises(ValueError):
            OperatorConfig(replication_wal_ring=0).validate()
        with pytest.raises(ValueError):
            OperatorConfig(replication_lease_seconds=0.0).validate()
        with pytest.raises(ValueError):
            OperatorConfig(replication_poll_timeout=0.0).validate()
        with pytest.raises(ValueError):
            OperatorConfig(replication_max_lag_seconds=-1.0).validate()

    def test_api_server_flag_accepts_ha_address_list(self):
        from training_operator_tpu.__main__ import make_remote_api

        cfg = build_config(parse_args([]))
        remote = make_remote_api(
            cfg, "http://127.0.0.1:1001, http://127.0.0.1:1002"
        )
        assert remote.addresses == [
            "http://127.0.0.1:1001", "http://127.0.0.1:1002"
        ]
        assert remote.base_url == "http://127.0.0.1:1001"


class TestSoakKnobs:
    """PR 14 satellite: the soak_* knobs ride the same flag ->
    OperatorConfig -> SoakConfig.from_operator_config path the harness
    consumes (bench.py --soak-only and the soak test tiers)."""

    def test_cli_flags_reach_soak_config(self):
        from training_operator_tpu.soak import SoakConfig

        args = parse_args([
            "--soak-hours", "48",
            "--soak-arrival-per-minute", "3.5",
            "--soak-compression", "8",
            "--soak-chaos", "pod=2,api=0.5,wire=0,node=1.5,host=0",
            "--soak-seed", "99",
        ])
        cfg = build_config(args)
        assert cfg.soak_hours == 48.0
        assert cfg.soak_arrival_per_minute == 3.5
        assert cfg.soak_compression == 8.0
        assert cfg.soak_seed == 99
        sc = SoakConfig.from_operator_config(cfg)
        assert sc.sim_hours == 48.0
        assert sc.arrival_per_minute == 3.5
        assert sc.compression == 8.0
        assert sc.seed == 99
        assert sc.chaos == {
            "pod": 2.0, "api": 0.5, "wire": 0.0, "node": 1.5, "host": 0.0,
        }
        # Compression maps fleet seconds onto sim seconds and back.
        assert sc.sim(3600.0) == 450.0
        assert sc.fleet(450.0) == 3600.0
        assert sc.sim_seconds == 48 * 3600.0 / 8.0

    def test_config_file_round_trip(self, tmp_path):
        path = tmp_path / "op.json"
        path.write_text(json.dumps({
            "soak_hours": 12.0,
            "soak_arrival_per_minute": 1.25,
            "soak_compression": 2.0,
            "soak_chaos": "pod=0,api=0,wire=0,node=0,host=0",
            "soak_seed": 7,
        }))
        cfg = build_config(parse_args(["--config", str(path)]))
        assert cfg.soak_hours == 12.0
        assert cfg.soak_arrival_per_minute == 1.25
        assert cfg.soak_compression == 2.0
        assert cfg.soak_seed == 7
        # CLI overrides the file (the standard precedence).
        cfg = build_config(parse_args(
            ["--config", str(path), "--soak-hours", "24"]))
        assert cfg.soak_hours == 24.0

    def test_chaos_spec_parsing(self):
        from training_operator_tpu.config import parse_chaos_intensity

        # Unnamed tiers default to 1.0; named ones scale.
        assert parse_chaos_intensity("pod=2")["pod"] == 2.0
        assert parse_chaos_intensity("pod=2")["node"] == 1.0
        assert parse_chaos_intensity("")["host"] == 1.0
        with pytest.raises(ValueError):
            parse_chaos_intensity("warp=1")
        with pytest.raises(ValueError):
            parse_chaos_intensity("pod=-0.5")

    def test_validation_bounds(self):
        with pytest.raises(ValueError):
            OperatorConfig(soak_hours=0.0).validate()
        with pytest.raises(ValueError):
            OperatorConfig(soak_arrival_per_minute=0.0).validate()
        with pytest.raises(ValueError):
            OperatorConfig(soak_compression=0.0).validate()
        with pytest.raises(ValueError):
            OperatorConfig(soak_chaos="bogus=1").validate()

    def test_defaults_are_the_week_shape(self):
        from training_operator_tpu.soak import SoakConfig

        cfg = OperatorConfig()
        assert cfg.soak_hours == 168.0
        sc = SoakConfig.from_operator_config(cfg)
        assert sc.sim_hours == 168.0
        assert all(v == 1.0 for v in sc.chaos.values())


class TestShardKnobs:
    """PR 15 satellite: operator scale-out knobs ride CLI flags ->
    OperatorConfig -> the OperatorManager / RemoteAPIServer the process
    entry points actually construct (the make_host_store discipline)."""

    def test_cli_flags_reach_the_manager(self):
        from training_operator_tpu.__main__ import build_cluster, build_stack

        args = parse_args([
            "--operator-shards", "4",
            "--shard-takeover-grace", "2.5",
            "--virtual-clock",
        ])
        args.cluster = None
        cfg = build_config(args)
        assert cfg.operator_shards == 4
        assert cfg.shard_takeover_grace == 2.5
        cluster = build_cluster(args)
        mgr, _v2 = build_stack(cluster, cfg)
        try:
            assert mgr.shard_elector is not None
            assert mgr.num_shards == 4
            assert mgr.shard_elector.takeover_grace == 2.5
            # Every shard elector rides the configured grace as its lease
            # duration (the INV010 bound).
            assert all(
                el.lease_duration == 2.5
                for el in mgr.shard_elector.electors
            )
        finally:
            mgr.stop()

    def test_read_from_standby_reaches_the_wire_client(self):
        from training_operator_tpu.__main__ import make_remote_api

        cfg = build_config(parse_args(["--read-from-standby"]))
        assert cfg.read_from_standby is True
        api = make_remote_api(
            cfg, "http://127.0.0.1:1,http://127.0.0.1:2")
        assert api.read_from_standby is True
        assert api.read_url == "http://127.0.0.1:2"
        assert api.base_url == "http://127.0.0.1:1"
        # One address: follower reads self-disable (nowhere to follow).
        api1 = make_remote_api(cfg, "http://127.0.0.1:1")
        assert api1.read_from_standby is False

    def test_config_file_round_trip(self, tmp_path):
        path = tmp_path / "op.json"
        path.write_text(json.dumps({
            "operator_shards": 3,
            "shard_takeover_grace": 7.0,
            "read_from_standby": True,
        }))
        cfg = build_config(parse_args(["--config", str(path)]))
        assert cfg.operator_shards == 3
        assert cfg.shard_takeover_grace == 7.0
        assert cfg.read_from_standby is True
        # CLI overrides the file.
        cfg = build_config(parse_args(
            ["--config", str(path), "--operator-shards", "5"]))
        assert cfg.operator_shards == 5

    def test_defaults_are_unsharded_primary_reads(self):
        cfg = OperatorConfig()
        assert cfg.operator_shards == 1
        assert cfg.shard_takeover_grace == 10.0
        assert cfg.read_from_standby is False

    def test_validation_bounds(self):
        with pytest.raises(ValueError):
            OperatorConfig(operator_shards=0).validate()
        with pytest.raises(ValueError):
            OperatorConfig(shard_takeover_grace=0.0).validate()


class TestStoreShardKnobs:
    """PR 17 satellite: store_shards / store_meta_shard ride the same
    flag -> OperatorConfig -> real-construction path as every other knob
    (make_host_store for the shard factory seam, make_remote_api for the
    client-side router). store_shards=1 pins today's topology exactly."""

    def test_cli_flags_reach_the_shard_factory(self, tmp_path):
        from training_operator_tpu.__main__ import make_host_store
        from training_operator_tpu.cluster.shards import StoreShardSet

        args = parse_args(["--store-shards", "3", "--store-meta-shard", "1"])
        cfg = build_config(args)
        store = make_host_store(cfg, str(tmp_path))
        assert isinstance(store, StoreShardSet)
        assert store.num_shards == 3 and store.meta_shard == 1
        store.close()

    def test_config_file_round_trip(self, tmp_path):
        path = tmp_path / "op.json"
        path.write_text(json.dumps({"store_shards": 2}))
        cfg = build_config(parse_args(["--config", str(path)]))
        assert cfg.store_shards == 2 and cfg.store_meta_shard == 0
        # CLI overrides the file.
        cfg = build_config(parse_args(
            ["--config", str(path), "--store-shards", "4"]))
        assert cfg.store_shards == 4

    def test_default_is_a_plain_host_store(self, tmp_path):
        from training_operator_tpu.__main__ import make_host_store
        from training_operator_tpu.cluster.store import HostStore

        cfg = build_config(parse_args([]))
        assert cfg.store_shards == 1 and cfg.store_meta_shard == 0
        store = make_host_store(cfg, str(tmp_path))
        assert type(store) is HostStore, "shards=1 is the pre-shard topology"
        store.close()

    def test_durability_knobs_reach_every_shard(self, tmp_path):
        from training_operator_tpu.__main__ import make_host_store

        args = parse_args(["--store-shards", "2", "--compact-every", "64",
                           "--journal-fsync", "--replication-wal-ring", "128"])
        store = make_host_store(build_config(args), str(tmp_path))
        for s in store.shards:
            assert s.compact_every == 64
            assert s.fsync_per_record is True
            assert s.wal_ring == 128
        store.close()

    def test_remote_api_builds_the_shard_router(self):
        from training_operator_tpu.__main__ import make_remote_api
        from training_operator_tpu.cluster.httpapi import (
            RemoteAPIServer,
            ShardedRemoteAPIServer,
        )

        cfg = build_config(parse_args(["--store-shards", "2",
                                       "--store-meta-shard", "1"]))
        remote = make_remote_api(
            cfg,
            "http://127.0.0.1:1001,http://127.0.0.1:1002 ;"
            " http://127.0.0.1:2001",
        )
        assert isinstance(remote, ShardedRemoteAPIServer)
        assert remote.meta_shard == 1
        assert remote.shard_remotes[0].addresses == [
            "http://127.0.0.1:1001", "http://127.0.0.1:1002"]
        assert remote.shard_remotes[1].addresses == ["http://127.0.0.1:2001"]
        # One address group stays the plain client (compat pin).
        cfg = build_config(parse_args([]))
        remote = make_remote_api(cfg, "http://127.0.0.1:1001")
        assert isinstance(remote, RemoteAPIServer)

    def test_remote_api_group_count_mismatch_refuses(self):
        from training_operator_tpu.__main__ import make_remote_api

        cfg = build_config(parse_args(["--store-shards", "3"]))
        with pytest.raises(SystemExit):
            make_remote_api(cfg, "http://127.0.0.1:1001;http://127.0.0.1:2001")

    def test_host_and_standby_roles_refuse_multi_shard(self):
        from training_operator_tpu.__main__ import run_host, run_standby

        args = parse_args(["--role", "host", "--store-shards", "2"])
        with pytest.raises(SystemExit, match="one write shard"):
            run_host(args, build_config(args))
        args = parse_args(["--role", "standby", "--store-shards", "2",
                           "--standby-of", "http://127.0.0.1:9"])
        with pytest.raises(SystemExit, match="one shard host"):
            run_standby(args, build_config(args))

    def test_validation_bounds(self):
        with pytest.raises(ValueError):
            OperatorConfig(store_shards=0).validate()
        with pytest.raises(ValueError):
            OperatorConfig(store_shards=2, store_meta_shard=2).validate()
        with pytest.raises(ValueError):
            OperatorConfig(store_meta_shard=-1).validate()
