"""Real-process e2e tier: the operator-injected bootstrap env drives REAL
`jax.distributed` processes.

This is the substrate analogue of the reference's kind-cluster e2e tests
(sdk/python/test/e2e/test_e2e_pytorchjob.py:50, examples/jax/cpu-demo/
train.py): submit a 2-worker JAXJob, let the operator render pods with the
bootstrap env (controllers/jax.py set_cluster_spec), then spawn one actual
OS process per pod with exactly that env. Each process runs
`jax.distributed.initialize()` from the env, proves the collective fabric
works (global psum), consumes its disjoint TokenDataset shard, and runs a
few data-parallel train steps with psum-averaged gradients. Exit codes flow
back through SimKubelet.complete_pod so the job reaches Succeeded — the
full loop: API -> controller -> pods -> env -> real JAX -> exit -> status.
"""

import os
import socket
import subprocess
import sys

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import Container, PodTemplateSpec, ReplicaSpec
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta
from training_operator_tpu.cluster.inventory import make_cpu_pool
from training_operator_tpu.cluster.objects import PodPhase
from training_operator_tpu.cluster.runtime import (
    Clock,
    Cluster,
    DefaultScheduler,
    SimKubelet,
)
from training_operator_tpu.controllers import OperatorManager, register_all

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The worker program each spawned process runs. It sees ONLY the env the
# operator injected (plus interpreter plumbing): COORDINATOR_ADDRESS/PORT,
# NUM_PROCESSES, PROCESS_ID. Everything below is driven from those.
WORKER_PROGRAM = r"""
import os
import numpy as np

addr = os.environ["COORDINATOR_ADDRESS"]
port = int(os.environ["COORDINATOR_PORT"])
num = int(os.environ["NUM_PROCESSES"])

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

jax.distributed.initialize(
    coordinator_address=f"{addr}:{port}",
    num_processes=num,
    process_id=int(os.environ["PROCESS_ID"]),
)
assert jax.process_count() == num, jax.process_count()
assert jax.local_device_count() == 1
assert jax.device_count() == num, jax.device_count()

from training_operator_tpu.trainer.data import DataLoader, TokenDataset, process_shard

pid, nprocs = process_shard()  # reads the same injected env
assert nprocs == num

TOTAL_ROWS, SEQ = 8, 4
rows = np.arange(TOTAL_ROWS * (SEQ + 1), dtype=np.int32).reshape(TOTAL_ROWS, SEQ + 1)
ds = TokenDataset(rows, pid, nprocs)

# The recommended multi-process bootstrap (what the trainer itself uses):
# a process-spanning mesh + NamedSharding under jit — no pmap anywhere.
mesh = jax.make_mesh((num,), ("data",))
data_sh = NamedSharding(mesh, P("data"))
repl_sh = NamedSharding(mesh, P())

# Collective proof #1: the shards tile the dataset exactly (disjoint, equal,
# complete) — a jit-reduced global sum of per-process shard sizes across
# REAL processes equals the total row count.
sizes = jax.make_array_from_process_local_data(
    data_sh, np.array([float(len(ds.rows))]), (num,)
)
total = jax.jit(jnp.sum, out_shardings=repl_sh)(sizes)
assert int(total) == TOTAL_ROWS, total

# A few data-parallel train steps: linear next-token scorer; the batch is a
# GLOBAL array sharded over the data axis, so the mean-loss gradient carries
# an XLA all-reduce across processes (no hand-written pmean).
loader = DataLoader(ds, batch_size=len(ds.rows), shuffle=False)
batch = next(iter(loader))
x_local = np.asarray(batch["tokens"], np.float32) / 40.0
y_local = np.asarray(batch["targets"], np.float32)[:, 0] / 40.0
x = jax.make_array_from_process_local_data(data_sh, x_local, (TOTAL_ROWS, SEQ))
y = jax.make_array_from_process_local_data(data_sh, y_local, (TOTAL_ROWS,))


@jax.jit
def step(w, x, y):
    def loss_fn(w):
        pred = x @ w
        return jnp.mean((pred - y) ** 2)

    loss, g = jax.value_and_grad(loss_fn)(w)
    return w - 0.05 * g, loss


w = jax.device_put(jnp.zeros((SEQ,), jnp.float32), repl_sh)
losses = []
for _ in range(5):
    w, loss = step(w, x, y)
    losses.append(float(loss))
assert losses[-1] < losses[0], losses  # training actually trained

# Collective proof #2: every process holds the SAME weights afterwards —
# gather each process's local view into a global (num, SEQ) array and
# jit-reduce the cross-process spread to a replicated scalar.
mine = np.asarray(jax.device_get(w))[None]
views = jax.make_array_from_process_local_data(data_sh, mine, (num, SEQ))
spread = jax.jit(
    lambda v: jnp.max(jnp.max(v, axis=0) - jnp.min(v, axis=0)),
    out_shardings=repl_sh,
)(views)
assert float(spread) < 1e-6, float(spread)
print(f"worker {pid}: ok, loss {losses[0]:.4f} -> {losses[-1]:.4f}")
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _drain(procs):
    """communicate() every worker in order; on any timeout/failure kill the
    stragglers so a hung rank cannot leak peers holding the rendezvous
    port. Returns each process's combined output."""
    outputs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outputs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    return outputs


def test_bootstrap_env_drives_real_jax_distributed(tmp_path):
    cluster = Cluster(Clock())
    cluster.add_nodes(make_cpu_pool(2, cpu_per_node=8.0))
    DefaultScheduler(cluster)
    kubelet = SimKubelet(cluster)
    mgr = OperatorManager(cluster, gang_enabled=False)
    register_all(mgr)

    port = _free_port()
    job = JAXJob(
        metadata=ObjectMeta(name="jax-e2e"),
        replica_specs={
            "Worker": ReplicaSpec(
                replicas=2,
                template=PodTemplateSpec(
                    containers=[
                        Container(name="jax", image="trainer", resources={"cpu": 1.0})
                    ]
                ),
            )
        },
        coordinator_port=port,
    )
    mgr.submit(job)

    def pods_running():
        pods = [p for p in cluster.api.list("Pod") if p.status.phase == PodPhase.RUNNING]
        return len(pods) == 2

    assert cluster.run_until(pods_running, timeout=30)

    pods = sorted(cluster.api.list("Pod"), key=lambda p: p.name)
    assert [p.name for p in pods] == ["jax-e2e-worker-0", "jax-e2e-worker-1"]

    # The coordinator address is the worker-0 headless service; the substrate
    # has no DNS, so resolve it the way cluster DNS would — every process in
    # this test shares the host netns, so the service name maps to loopback.
    services = {s.name for s in cluster.api.list("Service")}
    script = tmp_path / "worker.py"
    script.write_text(WORKER_PROGRAM)

    procs = []
    for pod in pods:
        env = {}
        for c in pod.spec.containers:
            env.update(c.env)
        # The injected contract, asserted before use:
        assert env["COORDINATOR_ADDRESS"] == "jax-e2e-worker-0"
        assert env["COORDINATOR_ADDRESS"] in services
        assert env["COORDINATOR_PORT"] == str(port)
        assert env["NUM_PROCESSES"] == "2"
        assert env["PROCESS_ID"] == pod.name.rsplit("-", 1)[1]
        penv = {
            "PATH": os.environ.get("PATH", ""),
            "HOME": os.environ.get("HOME", "/tmp"),
            "PYTHONPATH": REPO_ROOT,
            # Real processes, CPU backend, one device each — the operator's
            # env must be the ONLY distributed configuration they receive.
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            **env,
            "COORDINATOR_ADDRESS": "127.0.0.1",
        }
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=penv,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )

    outputs = _drain(procs)
    for i, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"worker {i}: ok" in out

    # Exit codes propagate through the kubelet into pod -> job status, and
    # each process's REAL stdout becomes its pod's log.
    for pod, p, out in zip(pods, procs, outputs):
        assert kubelet.complete_pod(pod.namespace, pod.name, p.returncode, log=out)
    assert cluster.run_until(
        lambda: capi.is_succeeded(
            cluster.api.get("JAXJob", "default", "jax-e2e").status
        ),
        timeout=30,
    )
    from training_operator_tpu.sdk import TrainingClient

    logs = TrainingClient(cluster).get_job_logs("jax-e2e")
    assert "worker 0: ok" in logs["jax-e2e-worker-0"]
    assert "worker 1: ok" in logs["jax-e2e-worker-1"]


# The torch analogue: only the operator-injected MASTER_ADDR/MASTER_PORT/
# RANK/WORLD_SIZE drive a REAL torch.distributed gloo group (the bootstrap
# contract of the reference's primary e2e, test_e2e_pytorchjob.py:50).
TORCH_WORKER_PROGRAM = r"""
import os
import torch
import torch.distributed as dist

rank = int(os.environ["RANK"])
world = int(os.environ["WORLD_SIZE"])
dist.init_process_group("gloo")  # env:// rendezvous from the injected env
assert dist.get_rank() == rank and dist.get_world_size() == world

# Collective proof: all-reduce of one-hot rank vectors = all-ones.
t = torch.zeros(world)
t[rank] = 1.0
dist.all_reduce(t)
assert torch.allclose(t, torch.ones(world)), t

# A few data-parallel SGD steps on rank-disjoint data shards with manual
# gradient all-reduce (what DDP does under the hood).
torch.manual_seed(rank)
x = torch.randn(8, 4)
y = x @ torch.arange(4.0).reshape(4, 1)
w = torch.zeros(4, 1, requires_grad=True)
first = last = None
for _ in range(20):
    loss = ((x @ w - y) ** 2).mean()
    loss.backward()
    with torch.no_grad():
        dist.all_reduce(w.grad)
        w.grad /= world
        w -= 0.05 * w.grad
        w.grad.zero_()
    first = first if first is not None else float(loss)
    last = float(loss)
assert last < first, (first, last)

# Weights are identical everywhere (the averaged-gradient invariant).
ws = [torch.empty_like(w) for _ in range(world)]
dist.all_gather(ws, w)
for other in ws:
    assert torch.allclose(other, w)
dist.barrier()
print(f"torch rank {rank}: ok, loss {first:.3f} -> {last:.3f}")
"""


def test_bootstrap_env_drives_real_torch_distributed(tmp_path):
    import pytest

    pytest.importorskip("torch")
    from training_operator_tpu.api.jobs import PyTorchJob

    cluster = Cluster(Clock())
    cluster.add_nodes(make_cpu_pool(2, cpu_per_node=8.0))
    DefaultScheduler(cluster)
    kubelet = SimKubelet(cluster)
    mgr = OperatorManager(cluster, gang_enabled=False)
    register_all(mgr)

    port = _free_port()

    def tmpl():
        return PodTemplateSpec(
            containers=[
                Container(
                    name="pytorch", image="trainer", resources={"cpu": 1.0},
                    ports={"pytorchjob-port": port},
                )
            ]
        )

    mgr.submit(
        PyTorchJob(
            metadata=ObjectMeta(name="torch-e2e"),
            replica_specs={
                "Master": ReplicaSpec(replicas=1, template=tmpl()),
                "Worker": ReplicaSpec(replicas=1, template=tmpl()),
            },
        )
    )

    assert cluster.run_until(
        lambda: sum(
            p.status.phase == PodPhase.RUNNING for p in cluster.api.list("Pod")
        ) == 2,
        timeout=30,
    )
    pods = sorted(cluster.api.list("Pod"), key=lambda p: p.name)
    assert [p.name for p in pods] == ["torch-e2e-master-0", "torch-e2e-worker-0"]

    script = tmp_path / "torch_worker.py"
    script.write_text(TORCH_WORKER_PROGRAM)
    procs = []
    for pod in pods:
        env = {}
        for c in pod.spec.containers:
            env.update(c.env)
        assert env["MASTER_ADDR"] == "torch-e2e-master-0"
        assert env["MASTER_PORT"] == str(port)
        assert env["WORLD_SIZE"] == "2"
        assert env["RANK"] == ("0" if "master" in pod.name else "1")
        penv = {
            "PATH": os.environ.get("PATH", ""),
            "HOME": os.environ.get("HOME", "/tmp"),
            **env,
            # Substrate has no DNS; the master service resolves to loopback
            # exactly as in the JAX tier above.
            "MASTER_ADDR": "127.0.0.1",
            "GLOO_SOCKET_IFNAME": "lo",
        }
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=penv,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )

    outputs = _drain(procs)
    for rank, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"torch rank {rank}: ok" in out

    for pod, p, out in zip(pods, procs, outputs):
        assert kubelet.complete_pod(pod.namespace, pod.name, p.returncode, log=out)
    assert cluster.run_until(
        lambda: capi.is_succeeded(
            cluster.api.get("PyTorchJob", "default", "torch-e2e").status
        ),
        timeout=30,
    )


def test_v2_trainjob_drives_real_jax_distributed(tmp_path):
    """The v2 path end-to-end with REAL compute: TrainJob -> runtime plugins
    -> JAXJob workload -> rendered pods -> real jax.distributed processes ->
    exit codes -> TrainJob Complete. (The reference's e2e tier covers v1
    kinds only; its v2 stack stops at envtest integration.)"""
    from training_operator_tpu.runtime.api import (
        ClusterTrainingRuntime,
        MLPolicy,
        ReplicatedJobTemplate,
        RuntimeRef,
        TrainingRuntimeSpec,
        TrainJob,
        TrainJobConditionType,
        TRAINER_NODE,
    )
    from training_operator_tpu.runtime.controller import TrainJobManager

    cluster = Cluster(Clock())
    cluster.add_nodes(make_cpu_pool(2, cpu_per_node=8.0))
    DefaultScheduler(cluster)
    kubelet = SimKubelet(cluster)
    mgr = OperatorManager(cluster, gang_enabled=False)
    register_all(mgr)
    v2 = TrainJobManager(cluster)

    v2.submit(
        ClusterTrainingRuntime(
            metadata=ObjectMeta(name="cpu-demo", namespace=""),
            spec=TrainingRuntimeSpec(
                ml_policy=MLPolicy(num_nodes=2),
                template=[
                    ReplicatedJobTemplate(
                        name=TRAINER_NODE,
                        replicas=2,
                        template=PodTemplateSpec(
                            containers=[
                                Container(
                                    name="trainer", image="trainer",
                                    resources={"cpu": 1.0},
                                )
                            ]
                        ),
                    )
                ],
            ),
        )
    )
    v2.submit(
        TrainJob(
            metadata=ObjectMeta(name="v2-e2e"),
            runtime_ref=RuntimeRef(name="cpu-demo", kind="ClusterTrainingRuntime"),
        )
    )

    assert cluster.run_until(
        lambda: sum(
            p.status.phase == PodPhase.RUNNING for p in cluster.api.list("Pod")
        ) == 2,
        timeout=30,
    )
    pods = sorted(cluster.api.list("Pod"), key=lambda p: p.name)

    script = tmp_path / "worker.py"
    script.write_text(WORKER_PROGRAM)
    port = _free_port()
    procs = []
    for pod in pods:
        env = {}
        for c in pod.spec.containers:
            env.update(c.env)
        # The v2-built workload carries the complete v1 bootstrap contract.
        assert env["NUM_PROCESSES"] == "2"
        assert env["PROCESS_ID"] in ("0", "1")
        assert "COORDINATOR_ADDRESS" in env and "COORDINATOR_PORT" in env
        penv = {
            "PATH": os.environ.get("PATH", ""),
            "HOME": os.environ.get("HOME", "/tmp"),
            "PYTHONPATH": REPO_ROOT,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            **env,
            # No DNS / shared netns in the substrate: service name -> lo,
            # and the well-known default port -> a free one for this host.
            "COORDINATOR_ADDRESS": "127.0.0.1",
            "COORDINATOR_PORT": str(port),
        }
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)], env=penv,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
        )

    outputs = _drain(procs)
    for i, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"

    for pod, p, out in zip(pods, procs, outputs):
        assert kubelet.complete_pod(pod.namespace, pod.name, p.returncode, log=out)
    assert cluster.run_until(
        lambda: cluster.api.get("TrainJob", "default", "v2-e2e").is_finished(),
        timeout=30,
    )
    tj = cluster.api.get("TrainJob", "default", "v2-e2e")
    done = tj.condition(TrainJobConditionType.COMPLETE)
    assert done is not None and done.status


# The multi-slice worker: consumes the FULL per-slice bootstrap contract
# (controllers/jax.py:18-39 — TPU_SLICE_ID / TPU_WORKERS_PER_SLICE /
# per-slice coordinator / MEGASCALE_*), initializes jax.distributed across
# ALL slices, builds the mesh from TPU_MESH_AXES with the data axis spanning
# slices, and runs a data-parallel step whose gradient all-reduce crosses
# the slice boundary.
MULTISLICE_WORKER_PROGRAM = r"""
import os
import numpy as np

pid = int(os.environ["PROCESS_ID"])
num = int(os.environ["NUM_PROCESSES"])
num_slices = int(os.environ["TPU_NUM_SLICES"])
per_slice = int(os.environ["TPU_WORKERS_PER_SLICE"])
slice_id = int(os.environ["TPU_SLICE_ID"])

# The per-slice contract must be self-consistent with the global identity.
assert num == num_slices * per_slice
assert slice_id == pid // per_slice
assert int(os.environ["TPU_WORKER_ID_IN_SLICE"]) == pid % per_slice
assert int(os.environ["MEGASCALE_NUM_SLICES"]) == num_slices
assert int(os.environ["MEGASCALE_SLICE_ID"]) == slice_id

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

jax.distributed.initialize(
    coordinator_address=f"{os.environ['COORDINATOR_ADDRESS']}:{os.environ['COORDINATOR_PORT']}",
    num_processes=num,
    process_id=pid,
)
assert jax.process_count() == num

# Mesh from the operator-injected TPU_MESH_AXES. AXIS_ORDER puts `data`
# before `fsdp`, so with data=num_slices the data axis is OUTERMOST —
# i.e. it strides across slices (DCN) while fsdp rides inside a slice
# (ICI), the layout trainer/mesh.py documents.
from training_operator_tpu.trainer.mesh import mesh_from_env

mesh = mesh_from_env()
assert mesh.shape["data"] == num_slices, mesh.shape
assert mesh.shape["fsdp"] == per_slice, mesh.shape

# Verify the geometry physically: walking the data axis at fixed fsdp
# index crosses slice boundaries (device -> owning process -> slice).
devs = np.asarray(mesh.devices).reshape(num_slices, per_slice)
for d in range(num_slices):
    for f in range(per_slice):
        owning = devs[d, f].process_index
        assert owning // per_slice == d, (d, f, owning)

# Data-parallel step over a batch sharded on the data (cross-slice) axis:
# the mean-loss gradient all-reduce must cross the slice boundary.
data_sh = NamedSharding(mesh, P(("data", "fsdp")))
repl_sh = NamedSharding(mesh, P())
ROWS, DIM = num, 4
x = jax.make_array_from_process_local_data(
    data_sh, np.full((1, DIM), pid + 1.0, np.float32), (ROWS, DIM)
)
y = jax.make_array_from_process_local_data(
    data_sh, np.array([float(pid % 2)], np.float32), (ROWS,)
)


@jax.jit
def step(w, x, y):
    def loss_fn(w):
        return jnp.mean((x @ w - y) ** 2)

    loss, g = jax.value_and_grad(loss_fn)(w)
    return w - 0.005 * g, loss


w = jax.device_put(jnp.zeros((DIM,), jnp.float32), repl_sh)
losses = []
for _ in range(4):
    w, loss = step(w, x, y)
    losses.append(float(loss))
assert losses[-1] < losses[0], losses

# Cross-slice agreement: every process (both slices) holds identical
# weights — only true if the gradient reduction crossed DCN.
mine = np.asarray(jax.device_get(w))[None]
views = jax.make_array_from_process_local_data(data_sh, mine, (num, DIM))
spread = jax.jit(
    lambda v: jnp.max(jnp.max(v, axis=0) - jnp.min(v, axis=0)),
    out_shardings=repl_sh,
)(views)
assert float(spread) < 1e-6, float(spread)
print(f"worker {pid} (slice {slice_id}): ok, loss {losses[0]:.4f} -> {losses[-1]:.4f}")
"""


def test_multislice_bootstrap_drives_real_jax_distributed(tmp_path):
    """num_slices=2, 4-worker JAXJob: REAL processes consume the multi-slice
    contract (VERDICT r3 next #6) — TPU_SLICE_ID/MEGASCALE_* env asserted in
    each process, jax.distributed across all 4, mesh from TPU_MESH_AXES with
    the data axis spanning slices, gradient all-reduce crossing the slice
    boundary."""
    from training_operator_tpu.api.jobs import TPUPolicy

    cluster = Cluster(Clock())
    cluster.add_nodes(make_cpu_pool(2, cpu_per_node=8.0))
    DefaultScheduler(cluster)
    kubelet = SimKubelet(cluster)
    mgr = OperatorManager(cluster, gang_enabled=False)
    register_all(mgr)

    port = _free_port()
    job = JAXJob(
        metadata=ObjectMeta(name="ms-e2e"),
        replica_specs={
            "Worker": ReplicaSpec(
                replicas=4,
                template=PodTemplateSpec(
                    containers=[
                        Container(name="jax", image="trainer", resources={"cpu": 1.0})
                    ]
                ),
            )
        },
        coordinator_port=port,
        tpu_policy=TPUPolicy(
            accelerator="v5e-2",
            topology="1x2",  # 2 chips/slice x 2 slices = 4 = mesh size
            num_slices=2,
            mesh_axes={"data": 2, "fsdp": 2},
        ),
    )
    mgr.submit(job)

    def pods_running():
        pods = [p for p in cluster.api.list("Pod") if p.status.phase == PodPhase.RUNNING]
        return len(pods) == 4

    assert cluster.run_until(pods_running, timeout=30)
    pods = sorted(cluster.api.list("Pod"), key=lambda p: p.name)

    script = tmp_path / "ms_worker.py"
    script.write_text(MULTISLICE_WORKER_PROGRAM)
    procs = []
    for pod in pods:
        env = {}
        for c in pod.spec.containers:
            env.update(c.env)
        idx = int(pod.name.rsplit("-", 1)[1])
        # Assert the operator-injected multi-slice contract BEFORE use.
        assert env["TPU_NUM_SLICES"] == "2"
        assert env["TPU_SLICE_ID"] == str(idx // 2)
        assert env["TPU_WORKER_ID_IN_SLICE"] == str(idx % 2)
        assert env["TPU_WORKERS_PER_SLICE"] == "2"
        assert env["TPU_SLICE_COORDINATOR_ADDRESS"] == f"ms-e2e-worker-{(idx // 2) * 2}"
        assert env["MEGASCALE_COORDINATOR_ADDRESS"] == "ms-e2e-worker-0"
        assert env["MEGASCALE_NUM_SLICES"] == "2"
        assert env["MEGASCALE_SLICE_ID"] == str(idx // 2)
        assert env["TPU_MESH_AXES"] == "data=2,fsdp=2"
        penv = {
            "PATH": os.environ.get("PATH", ""),
            "HOME": os.environ.get("HOME", "/tmp"),
            "PYTHONPATH": REPO_ROOT,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            **env,
            "COORDINATOR_ADDRESS": "127.0.0.1",
        }
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=penv,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )

    outputs = _drain(procs)
    for i, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"worker {i} (slice {i // 2}): ok" in out

    for pod, p, out in zip(pods, procs, outputs):
        assert kubelet.complete_pod(pod.namespace, pod.name, p.returncode, log=out)
    assert cluster.run_until(
        lambda: capi.is_succeeded(
            cluster.api.get("JAXJob", "default", "ms-e2e").status
        ),
        timeout=30,
    )
