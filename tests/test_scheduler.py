"""Gang scheduler + tpu-packer tests.

Covers: candidate enumeration (ICI sub-mesh validity), snapshot capacity
accounting (reservations), baseline vs packer placement quality (contiguity,
best-fit anti-fragmentation), multi-slice gangs, NVLink locality for GPU
gangs, and the end-to-end gang path through the reconcile engine
(PodGroup Pending -> Inqueue -> pods bound -> job Succeeded).
"""

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import Container, JobConditionType, PodTemplateSpec, ReplicaSpec
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta, PyTorchJob, TPUPolicy
from training_operator_tpu.cluster.inventory import (
    TPU_RESOURCE,
    GPU_RESOURCE,
    make_cpu_pool,
    make_gpu_pool,
    make_tpu_pool,
)
from training_operator_tpu.cluster.objects import PodGroupPhase, PodPhase
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
)
from training_operator_tpu.controllers import OperatorManager, register_all
from training_operator_tpu.scheduler import (
    BaselinePlacer,
    ClusterSnapshot,
    GangScheduler,
    TPUPacker,
)
from training_operator_tpu.scheduler.candidates import enumerate_candidates
from training_operator_tpu.scheduler.snapshot import build_gang_request


def tpu_tmpl(chips=4.0, cpu=1.0, **annotations):
    t = PodTemplateSpec(
        containers=[
            Container(name="jax", image="trainer", resources={"cpu": cpu, TPU_RESOURCE: chips})
        ]
    )
    t.annotations.update(annotations)
    return t


def make_jax_job(name, workers, topology, num_slices=1, accelerator=None, duration=None):
    if accelerator is None:
        chips = 1
        for d in topology.split("x"):
            chips *= int(d)
        accelerator = f"v5e-{chips}"
    ann = {ANNOTATION_SIM_DURATION: str(duration)} if duration else {}
    return JAXJob(
        metadata=ObjectMeta(name=name),
        replica_specs={"Worker": ReplicaSpec(replicas=workers, template=tpu_tmpl(**ann))},
        tpu_policy=TPUPolicy(accelerator=accelerator, topology=topology, num_slices=num_slices),
    )


def make_gang_env(placer, slices=2, topology="4x4", gpu_nodes=0):
    cluster = Cluster(VirtualClock())
    cluster.add_nodes(make_tpu_pool(slices, slice_topology=topology))
    if gpu_nodes:
        cluster.add_nodes(make_gpu_pool(gpu_nodes))
    cluster.add_nodes(make_cpu_pool(2))
    DefaultScheduler(cluster)
    SimKubelet(cluster)
    GangScheduler(cluster, placer)
    mgr = OperatorManager(cluster, gang_enabled=True)
    register_all(mgr)
    return cluster, mgr


class TestCandidates:
    def test_full_slice(self):
        cs = enumerate_candidates("4x4", 4, "4x4")
        assert cs is not None and cs.num_candidates == 1
        assert cs.masks[0] == (True, True, True, True)

    def test_sub_mesh_2x4(self):
        cs = enumerate_candidates("4x4", 4, "2x4")
        # Host grid is 4x1: 2x4 chips = 2 adjacent host rows -> origins 0,1,2.
        assert cs is not None and cs.num_candidates == 3
        for mask in cs.masks:
            hosts = [h for h, used in enumerate(mask) if used]
            assert hosts[1] == hosts[0] + 1  # contiguity

    def test_single_host(self):
        cs = enumerate_candidates("4x4", 4, "1x4")
        assert cs is not None and cs.num_candidates == 4

    def test_permuted_request(self):
        # 4x2 permutes to 2x4 which is host-feasible.
        cs = enumerate_candidates("4x4", 4, "4x2")
        assert cs is not None and cs.num_candidates == 3

    def test_infeasible_not_host_aligned(self):
        assert enumerate_candidates("4x4", 4, "2x2") is None

    def test_8x8_slice_2x4_request(self):
        # Host grid 8x2 (4-chip hosts on minor axis 8): 2x4 chips = 2x1 host
        # blocks (the 4x2 orientation doesn't tile whole hosts) -> 7x2 origins.
        cs = enumerate_candidates("8x8", 4, "2x4")
        assert cs is not None
        assert cs.num_candidates == 7 * 2


class TestPackerPlacement:
    def _snapshot_with_busy_hosts(self, cluster, busy):
        from training_operator_tpu.cluster.objects import Pod

        api = cluster.api
        for i, node in enumerate(busy):
            p = Pod(metadata=ObjectMeta(name=f"busy-{i}", namespace="default"))
            p.spec.containers = [Container(name="c", resources={TPU_RESOURCE: 4.0})]
            p.node_name = node
            p.status.phase = PodPhase.RUNNING
            api.create(p)
        return ClusterSnapshot(api)

    def test_contiguity_respected(self):
        """Free-but-scattered hosts must NOT satisfy a 2x4 gang."""
        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_tpu_pool(1, slice_topology="4x4"))
        snap = self._snapshot_with_busy_hosts(
            cluster, ["slice-0-host-1", "slice-0-host-3"]
        )  # hosts 0,2 free but not adjacent
        mgr = OperatorManager(cluster, gang_enabled=True)
        register_all(mgr)
        job = make_jax_job("frag", workers=2, topology="2x4")
        mgr.submit(job)
        for _ in range(3):
            cluster.step()
        pg = cluster.api.get("PodGroup", "default", "frag")
        req = build_gang_request(cluster.api, pg)
        placements = TPUPacker().place([req], snap)
        assert placements[req.key] is None

    def test_best_fit_prefers_tight_slice(self):
        """Packer packs a 1-host gang into the fuller slice, keeping the empty
        slice whole for future full-slice gangs (first-fit does not)."""
        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_tpu_pool(2, slice_topology="4x4"))
        snap = self._snapshot_with_busy_hosts(cluster, ["slice-0-host-0"])
        mgr = OperatorManager(cluster, gang_enabled=True)
        register_all(mgr)
        job = make_jax_job("small", workers=1, topology="1x4")
        mgr.submit(job)
        for _ in range(3):
            cluster.step()
        pg = cluster.api.get("PodGroup", "default", "small")
        req = build_gang_request(cluster.api, pg)
        placement = TPUPacker().place([req], snap)[req.key]
        assert placement is not None
        (node,) = placement.assignments.values()
        assert node.startswith("slice-0")  # the partially-used slice


class TestGangEndToEnd:
    def run_one(self, placer):
        cluster, mgr = make_gang_env(placer, slices=2)
        job = make_jax_job("train", workers=4, topology="4x4", duration=5)
        mgr.submit(job)
        done = cluster.run_until(
            lambda: capi.is_succeeded(
                cluster.api.get("JAXJob", "default", "train").status
            ),
            timeout=120,
        )
        assert done
        return cluster

    def test_packer_end_to_end(self):
        cluster = self.run_one(TPUPacker())
        # All four pods must have landed on one slice's four hosts.
        pods = cluster.api.list("Pod", "default", {capi.JOB_NAME_LABEL: "train"})
        slices = {p.node_name.rsplit("-host-", 1)[0] for p in pods}
        assert len(slices) == 1

    def test_baseline_end_to_end(self):
        self.run_one(BaselinePlacer())

    def test_multi_slice_gang(self):
        cluster, mgr = make_gang_env(TPUPacker(), slices=3)
        job = make_jax_job("multi", workers=8, topology="4x4", num_slices=2, duration=5)
        mgr.submit(job)
        assert cluster.run_until(
            lambda: capi.is_succeeded(cluster.api.get("JAXJob", "default", "multi").status),
            timeout=120,
        )
        pods = cluster.api.list("Pod", "default", {capi.JOB_NAME_LABEL: "multi"})
        assert len(pods) == 8
        slices = {p.node_name.rsplit("-host-", 1)[0] for p in pods}
        assert len(slices) == 2  # distinct whole slices

    def test_multi_slice_env_matches_placement(self):
        """The per-slice bootstrap env (TPU_SLICE_ID) must agree with the
        physical placement: all workers sharing a TPU_SLICE_ID land on one
        slice, distinct TPU_SLICE_IDs land on distinct slices — the
        contiguous index->slice convention shared by controllers/jax.py and
        the packer's stitching."""
        cluster, mgr = make_gang_env(TPUPacker(), slices=3)
        job = make_jax_job("msenv", workers=8, topology="4x4", num_slices=2, duration=30)
        mgr.submit(job)
        assert cluster.run_until(
            lambda: sum(
                1 for p in cluster.api.list("Pod", "default", {capi.JOB_NAME_LABEL: "msenv"})
                if p.node_name
            ) == 8,
            timeout=120,
        )
        by_env_slice = {}
        for p in cluster.api.list("Pod", "default", {capi.JOB_NAME_LABEL: "msenv"}):
            env = p.spec.containers[0].env
            phys = p.node_name.rsplit("-host-", 1)[0]
            by_env_slice.setdefault(env["TPU_SLICE_ID"], set()).add(phys)
            assert env["MEGASCALE_SLICE_ID"] == env["TPU_SLICE_ID"]
        assert set(by_env_slice) == {"0", "1"}
        assert all(len(v) == 1 for v in by_env_slice.values()), by_env_slice
        assert by_env_slice["0"] != by_env_slice["1"]

    def test_gang_all_or_nothing(self):
        """A gang that cannot fit stays Pending with zero pods created."""
        cluster, mgr = make_gang_env(TPUPacker(), slices=1)
        big = make_jax_job("big", workers=8, topology="4x4", num_slices=2)
        mgr.submit(big)
        cluster.run_for(5)
        assert cluster.api.list("Pod", "default", {capi.JOB_NAME_LABEL: "big"}) == []
        pg = cluster.api.get("PodGroup", "default", "big")
        assert pg.phase == PodGroupPhase.PENDING

    def test_queued_gang_admitted_when_capacity_frees(self):
        cluster, mgr = make_gang_env(TPUPacker(), slices=1)
        first = make_jax_job("first", workers=4, topology="4x4", duration=10)
        second = make_jax_job("second", workers=4, topology="4x4", duration=10)
        mgr.submit(first)
        mgr.submit(second)
        assert cluster.run_until(
            lambda: capi.is_succeeded(cluster.api.get("JAXJob", "default", "second").status),
            timeout=300,
        )
        f = cluster.api.get("JAXJob", "default", "first")
        s = cluster.api.get("JAXJob", "default", "second")
        # second queued behind first on the single slice.
        assert s.status.completion_time > f.status.completion_time

    def test_gpu_gang_nvlink_locality(self):
        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_gpu_pool(8, gpus_per_node=8, nodes_per_nvlink_domain=4))
        DefaultScheduler(cluster)
        SimKubelet(cluster)
        GangScheduler(cluster, TPUPacker())
        mgr = OperatorManager(cluster, gang_enabled=True)
        register_all(mgr)
        t = PodTemplateSpec(
            containers=[
                Container(name="pytorch", image="trainer", resources={"cpu": 1.0, GPU_RESOURCE: 8.0})
            ]
        )
        t.annotations[ANNOTATION_SIM_DURATION] = "5"
        job = PyTorchJob(
            metadata=ObjectMeta(name="ddp"),
            replica_specs={"Worker": ReplicaSpec(replicas=4, template=t)},
        )
        mgr.submit(job)
        assert cluster.run_until(
            lambda: capi.is_succeeded(cluster.api.get("PyTorchJob", "default", "ddp").status),
            timeout=120,
        )
        pods = cluster.api.list("Pod", "default", {capi.JOB_NAME_LABEL: "ddp"})
        domains = {
            cluster.api.get("Node", "", p.node_name).accelerator.nvlink_domain
            for p in pods
        }
        assert len(domains) == 1  # all four 8-GPU nodes in one NVLink domain


class TestSnapshotAccounting:
    def test_admitted_reservation_blocks_double_placement(self):
        """Two gangs solved in different cycles must not share hosts even
        before the first gang's pods exist."""
        cluster, mgr = make_gang_env(TPUPacker(), slices=1)
        a = make_jax_job("ja", workers=4, topology="4x4")
        mgr.submit(a)
        for _ in range(4):
            cluster.step()
        pg_a = cluster.api.get("PodGroup", "default", "ja")
        assert pg_a.phase == PodGroupPhase.INQUEUE
        # Before any pod of A binds, solve B: must find nothing.
        b = make_jax_job("jb", workers=4, topology="4x4")
        mgr.submit(b)
        for _ in range(4):
            cluster.step()
        pg_b = cluster.api.get("PodGroup", "default", "jb")
        assert pg_b.phase == PodGroupPhase.PENDING


class TestDistinctSlices:
    def test_sub_slice_multi_slice_gang_lands_on_distinct_slices(self):
        """A multi-slice gang with sub-slice topology must occupy one distinct
        physical slice per sub-request (inter-slice traffic rides DCN; two
        sub-meshes on one slice would break the assumed topology)."""
        cluster, mgr = make_gang_env(TPUPacker(), slices=2)
        # 2x4 = 2 hosts per slice on a 4-host 4x4 slice; both subs fit on
        # slice-0 capacity-wise, so only the distinct-slice constraint forces
        # them apart.
        job = make_jax_job("ring", workers=4, topology="2x4", num_slices=2, duration=5)
        mgr.submit(job)
        assert cluster.run_until(
            lambda: capi.is_succeeded(cluster.api.get("JAXJob", "default", "ring").status),
            timeout=120,
        )
        pods = cluster.api.list("Pod", "default", {capi.JOB_NAME_LABEL: "ring"})
        assert len(pods) == 4
        slices = {p.node_name.rsplit("-host-", 1)[0] for p in pods}
        assert len(slices) == 2

    def test_generic_gang_never_lands_on_tpu_hosts(self):
        """A CPU/GPU gang in a TPU-only pool stays pending instead of
        silently consuming TPU-host capacity."""
        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_tpu_pool(1, slice_topology="4x4"))
        DefaultScheduler(cluster)
        SimKubelet(cluster)
        GangScheduler(cluster, TPUPacker())
        mgr = OperatorManager(cluster, gang_enabled=True)
        register_all(mgr)
        t = PodTemplateSpec(
            containers=[Container(name="pytorch", image="img", resources={"cpu": 1.0})]
        )
        job = PyTorchJob(
            metadata=ObjectMeta(name="cpu-gang"),
            replica_specs={"Worker": ReplicaSpec(replicas=2, template=t)},
        )
        mgr.submit(job)
        cluster.run_for(10)
        assert cluster.api.list("Pod", "default", {capi.JOB_NAME_LABEL: "cpu-gang"}) == []
        pg = cluster.api.get("PodGroup", "default", "cpu-gang")
        assert pg.phase == PodGroupPhase.PENDING


class TestSolveTrace:
    def test_per_cycle_structured_trace(self):
        """Every solve cycle leaves a structured record: queue shape, solver
        geometry, admissions, and post-admission pool state."""
        import json

        cluster, mgr = make_gang_env(TPUPacker(), slices=2)
        sched = next(
            t.__self__ for t in cluster._tickers
            if isinstance(getattr(t, "__self__", None), GangScheduler)
        )
        mgr.submit(make_jax_job("t1", 2, "2x4"))
        mgr.submit(make_jax_job("t2", 2, "2x4"))
        assert cluster.run_until(
            lambda: all(
                pg.phase.value in ("Inqueue", "Running")
                for pg in cluster.api.list("PodGroup")
            )
            and len(cluster.api.list("PodGroup")) == 2,
            timeout=60,
        )
        trace = sched.dump_trace()
        assert trace, "no solve cycles recorded"
        json.dumps(trace)  # serializable as-is
        rec = trace[0]
        for key in (
            "t", "solve_wall_s", "pending", "pending_tpu", "pending_generic",
            "admitted", "free_tpu_hosts", "whole_free_slices",
        ):
            assert key in rec, rec
        assert rec["solver"]["batch_items"] >= 1  # packer geometry present
        assert sum(r["admitted"] for r in trace) >= 2


class TestLargeSliceTopologies:
    """Packing on big slices with 2D host grids (8x8 chips / 4 per host =
    a 8x2 host grid): sub-mesh candidates must stay contiguous rectangles
    and the kernel must place multiple gangs without overlap."""

    def test_submeshes_on_8x8_slice(self):
        from training_operator_tpu.scheduler.candidates import enumerate_candidates

        cset = enumerate_candidates("8x8", 4, "2x4")
        assert cset is not None and cset.hosts_per_slice == 16
        # A 2x4-chip ask on a 8x2 host grid occupies a contiguous block.
        for mask in cset.masks:
            used = [i for i, u in enumerate(mask) if u]
            rows = sorted({i // 2 for i in used})
            cols = sorted({i % 2 for i in used})
            assert rows == list(range(rows[0], rows[-1] + 1))
            assert cols == list(range(cols[0], cols[-1] + 1))
            assert len(used) == len(rows) * len(cols)  # full rectangle

    def test_pack_multiple_gangs_on_8x8_pool(self):
        cluster, mgr = make_gang_env(
            TPUPacker(), slices=2, topology="8x8"
        )
        # 4 gangs of 4x4 (4 hosts each) + 4 gangs of 2x4 (2 hosts each)
        # = 24 hosts over 32 available; all must run concurrently.
        for i in range(4):
            mgr.submit(make_jax_job(f"big-{i}", 4, "4x4", duration="30"))
        for i in range(4):
            mgr.submit(make_jax_job(f"small-{i}", 2, "2x4", duration="30"))
        assert cluster.run_until(
            lambda: sum(
                1 for p in cluster.api.list("Pod")
                if p.status.phase.value == "Running"
            ) == 4 * 4 + 4 * 2,
            timeout=120,
        )
        # No host double-booked.
        hosts = [p.node_name for p in cluster.api.list("Pod") if p.node_name]
        assert len(hosts) == len(set(hosts))
        # Each gang is confined to one slice (contiguity prerequisite).
        from collections import defaultdict
        by_job = defaultdict(set)
        for p in cluster.api.list("Pod"):
            if p.node_name:
                by_job[p.metadata.labels.get("training.tpu.dev/job-name")].add(
                    p.node_name.rsplit("-host-", 1)[0]
                )
        assert all(len(slices) == 1 for slices in by_job.values()), by_job


class TestWeightedSJF:
    """The wsjf-aging discipline: declared expected duration weights the
    admission priority (demand x duration = work), and the annotation is
    parsed into GangRequest.expected_duration."""

    def _request_for(self, cluster, mgr, job):
        mgr.submit(job)
        cluster.run_for(0.1)
        pg = next(
            pg for pg in cluster.api.list("PodGroup") if pg.name == job.name
        )
        return build_gang_request(cluster.api, pg)

    def test_expected_duration_parsed_from_annotation(self):
        from training_operator_tpu.scheduler.snapshot import (
            ANNOTATION_EXPECTED_DURATION,
        )

        cluster, mgr = make_gang_env(TPUPacker(), slices=2)
        job = make_jax_job("declared", 1, "1x4")
        job.replica_specs["Worker"].template.annotations[
            ANNOTATION_EXPECTED_DURATION
        ] = "90"
        req = self._request_for(cluster, mgr, job)
        assert req.expected_duration == 90.0
        # Malformed hints are ignored, not fatal.
        bad = make_jax_job("malformed", 1, "1x4")
        bad.replica_specs["Worker"].template.annotations[
            ANNOTATION_EXPECTED_DURATION
        ] = "soon"
        req2 = self._request_for(cluster, mgr, bad)
        assert req2.expected_duration is None

    def test_wsjf_orders_by_work_not_demand(self):
        """A 2-host 30s gang (work 480 chip-s) outranks a 1-host 120s gang
        (work 480... use 16x30=480 vs 4x120=480 -> tie broken by creation;
        make it strict: 8x30=240 beats 4x120=480)."""
        from training_operator_tpu.scheduler.snapshot import (
            ANNOTATION_EXPECTED_DURATION,
        )

        cluster, mgr = make_gang_env(TPUPacker(), slices=2)
        small_long = make_jax_job("small-long", 1, "1x4")  # 4 chips x 120s
        small_long.replica_specs["Worker"].template.annotations[
            ANNOTATION_EXPECTED_DURATION
        ] = "120"
        big_short = make_jax_job("big-short", 2, "2x4")  # 8 chips x 30s
        big_short.replica_specs["Worker"].template.annotations[
            ANNOTATION_EXPECTED_DURATION
        ] = "30"
        r_long = self._request_for(cluster, mgr, small_long)
        r_short = self._request_for(cluster, mgr, big_short)
        packer = TPUPacker()
        ordered = packer._order(
            [r_long, r_short], now=0.0, demand=lambda r: r.total_chips()
        )
        assert [r.group.name for r in ordered] == ["big-short", "small-long"]
        # sjf-aging (demand-only) prefers the smaller gang instead.
        packer2 = TPUPacker(discipline="sjf-aging")
        ordered2 = packer2._order(
            [r_long, r_short], now=0.0, demand=lambda r: r.total_chips()
        )
        assert [r.group.name for r in ordered2] == ["small-long", "big-short"]

    def test_aging_still_promotes_starved_gangs(self):
        cluster, mgr = make_gang_env(TPUPacker(), slices=2)
        old_big = make_jax_job("old-big", 4, "4x4")
        fresh_small = make_jax_job("fresh-small", 1, "1x4")
        r_big = self._request_for(cluster, mgr, old_big)
        r_small = self._request_for(cluster, mgr, fresh_small)
        packer = TPUPacker(aging_seconds=300.0)
        r_big.group.metadata.creation_time = 0.0
        r_small.group.metadata.creation_time = 290.0  # waited 11s: not starved
        ordered = packer._order(
            [r_small, r_big], now=301.0,
            demand=lambda r: r.total_chips(),
        )
        assert ordered[0].group.name == "old-big"


class TestDrainPreassign:
    """Tail-latency controls: starved whole-slice gangs get drained slices
    handed to them directly; sticky reservations keep draining slices out
    of smaller gangs' reach (packer drain_reserve_seconds/_drain_and_preassign)."""

    def _env(self, slices=2):
        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_tpu_pool(slices, slice_topology="4x4"))
        mgr = OperatorManager(cluster, gang_enabled=True)
        register_all(mgr)
        return cluster, mgr

    def _request(self, cluster, mgr, name, workers, topology, num_slices=1, created=0.0):
        job = make_jax_job(name, workers=workers, topology=topology, num_slices=num_slices)
        mgr.submit(job)
        for _ in range(3):
            cluster.step()
        pg = cluster.api.get("PodGroup", "default", name)
        pg.metadata.creation_time = created
        return build_gang_request(cluster.api, pg)

    def test_starved_whole_slice_gang_preassigned_before_kernel(self):
        """A whole-slice gang past the drain threshold takes the fully-free
        slice directly — the backlog of small gangs in the same batch must
        not nibble it first despite their higher (smallest-work) priority."""
        cluster, mgr = self._env(slices=1)
        snap = ClusterSnapshot(cluster.api)
        big = self._request(cluster, mgr, "big", 4, "4x4", created=0.0)
        smalls = [
            self._request(cluster, mgr, f"small-{i}", 1, "1x4", created=500.0)
            for i in range(4)
        ]
        packer = TPUPacker(drain_reserve_seconds=150.0)
        placements = packer.place([big] + smalls, snap, now=500.0)
        assert placements[big.key] is not None
        assert packer.last_drain_stats["preassigned_gangs"] == 1
        # the one slice went whole to the starved gang; smalls wait
        assert all(placements[s.key] is None for s in smalls)

    def test_sticky_reservation_blocks_small_gangs_while_draining(self):
        """A partially-busy slice under drain reservation is invisible to
        small gangs even though it has free hosts."""
        from training_operator_tpu.cluster.objects import Pod

        cluster, mgr = self._env(slices=1)
        # one busy host -> slice partially free (3 free hosts)
        p = Pod(metadata=ObjectMeta(name="busy", namespace="default"))
        p.spec.containers = [Container(name="c", resources={TPU_RESOURCE: 4.0})]
        p.node_name = "slice-0-host-0"
        p.status.phase = PodPhase.RUNNING
        cluster.api.create(p)
        snap = ClusterSnapshot(cluster.api)
        big = self._request(cluster, mgr, "big", 4, "4x4", created=0.0)
        small = self._request(cluster, mgr, "small", 1, "1x4", created=500.0)
        packer = TPUPacker(drain_reserve_seconds=150.0)
        placements = packer.place([big, small], snap, now=500.0)
        # neither runs: big needs the whole slice (still draining), small is
        # fenced off the reserved slice
        assert placements[big.key] is None
        assert placements[small.key] is None
        assert packer.last_drain_stats["reserved_slices"] == 1
        assert "slice-0" in packer._drain_set
        # without the reservation the small gang WOULD have been placed
        baseline = TPUPacker(drain_reserve_seconds=0)
        placements2 = baseline.place([big, small], ClusterSnapshot(cluster.api), now=500.0)
        assert placements2[small.key] is not None

    def test_drain_disabled_by_default_profile_unchanged(self):
        """drain_reserve_seconds=0 disables the mechanism entirely."""
        cluster, mgr = self._env(slices=1)
        snap = ClusterSnapshot(cluster.api)
        big = self._request(cluster, mgr, "big", 4, "4x4", created=0.0)
        packer = TPUPacker(drain_reserve_seconds=0)
        placements = packer.place([big], snap, now=500.0)
        # kernel still places it (slice is free) — but through the solve,
        # not the preassign path
        assert placements[big.key] is not None
        assert packer.last_drain_stats == {}
        assert packer._drain_set == set()

    def test_multi_slice_starved_gang_accumulates_slices(self):
        """A starved 2-slice gang with only one free slice keeps it reserved
        (masked from others) until the second drains."""
        from training_operator_tpu.cluster.objects import Pod

        cluster, mgr = self._env(slices=2)
        p = Pod(metadata=ObjectMeta(name="busy", namespace="default"))
        p.spec.containers = [Container(name="c", resources={TPU_RESOURCE: 4.0})]
        p.node_name = "slice-1-host-0"
        p.status.phase = PodPhase.RUNNING
        cluster.api.create(p)
        snap = ClusterSnapshot(cluster.api)
        multi = self._request(cluster, mgr, "multi", 8, "4x4", num_slices=2, created=0.0)
        small = self._request(cluster, mgr, "small", 1, "1x4", created=500.0)
        packer = TPUPacker(drain_reserve_seconds=150.0, max_drain_fraction=0.5)
        placements = packer.place([multi, small], snap, now=500.0)
        assert placements[multi.key] is None  # only 1 of 2 slices free
        # slice-1 (partial) is sticky-reserved until it drains...
        assert "slice-1" in packer._drain_set
        # ...and the free slice-0 is ALSO effectively held: the aged multi
        # gang at front priority claims it in the kernel every cycle (and
        # forfeits, staying pending), so the small gang cannot nibble it —
        # the accumulation behavior a 2-slice gang needs.
        assert placements[small.key] is None
        # Once the contender is gone the reservation clears (demand-driven)
        # and the small gang places normally — by best-fit, onto the FULLER
        # slice-1, which is no longer fenced.
        placements2 = packer.place([small], ClusterSnapshot(cluster.api), now=500.0)
        assert placements2[small.key] is not None
        assert packer._drain_set == set()


class TestEstimateRobustness:
    """WSJF ordering under degraded estimates: estimate-less gangs are
    charged the batch's MEDIAN declared duration (not a pessimistic
    constant that would send them to the back of every queue)."""

    def _req(self, cluster, mgr, name, workers, topology, created, duration=None):
        job = make_jax_job(name, workers=workers, topology=topology)
        if duration is not None:
            from training_operator_tpu.scheduler.snapshot import (
                ANNOTATION_EXPECTED_DURATION,
            )

            for spec in job.replica_specs.values():
                spec.template.annotations[ANNOTATION_EXPECTED_DURATION] = str(duration)
        mgr.submit(job)
        for _ in range(3):
            cluster.step()
        pg = cluster.api.get("PodGroup", "default", name)
        pg.metadata.creation_time = created
        return build_gang_request(cluster.api, pg)

    def test_missing_estimate_charged_batch_median(self):
        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_tpu_pool(4, slice_topology="4x4"))
        mgr = OperatorManager(cluster, gang_enabled=True)
        register_all(mgr)
        # Same shape/demand; only the declared duration differs.
        short = self._req(cluster, mgr, "short", 1, "1x4", created=0.0, duration=10)
        long = self._req(cluster, mgr, "long", 1, "1x4", created=0.0, duration=1000)
        nodecl = self._req(cluster, mgr, "nodecl", 1, "1x4", created=0.0)
        packer = TPUPacker(default_expected_duration=600.0)
        ordered = packer._order([long, nodecl, short], now=1.0,
                                demand=lambda r: r.total_chips())
        names = [r.group.name for r in ordered]
        # Median of declared = (10+1000)/2-ish -> sorted() median picks 1000
        # for an even list's upper middle; with [10, 1000] the charge is
        # 1000, so nodecl ties with long and FIFO (creation) breaks it.
        # The essential property: nodecl must NOT be dead-last merely for
        # declaring nothing when the batch median is small.
        assert names[0] == "short"
        # And with a batch whose median is small, the estimate-less gang
        # outranks a declared-long gang:
        short2 = self._req(cluster, mgr, "short2", 1, "1x4", created=0.0, duration=20)
        ordered2 = packer._order([long, nodecl, short, short2], now=1.0,
                                 demand=lambda r: r.total_chips())
        names2 = [r.group.name for r in ordered2]
        assert names2.index("nodecl") < names2.index("long"), names2
