"""Direct unit tests for the batched placement kernel (packer._solve_batch).

The kernel is the subtlest code in the scheduler: class-rank desync of
identical items, exclusive cumulative-OR conflict resolution, padding, and
rank clamping. These tests drive it with hand-built tensors (no cluster, no
snapshot) and check its hard invariants, plus property-tests against a
sequential greedy reference on random instances.

Invariants (see _solve_batch docstring):
  validity    — every admitted item committed a valid, feasible candidate and
                no host is granted twice;
  maximality  — at termination no unadmitted item has any feasible candidate
                left against the final free state (the loop only exits when a
                round commits nothing, and a round always commits the
                highest-priority feasible pick);
  greedy parity — when no two classes share hosts, the result equals
                sequential highest-priority-first greedy admission exactly.
"""

from __future__ import annotations

import numpy as np
import pytest

from training_operator_tpu.scheduler.packer import (
    _NEG,
    _solve_batch,
    _solve_batch_numpy,
    _solve_batch_python,
)


def solve(free, cand_mask, cand_slice, cand_valid, origin_rank, item_class, item_active):
    out = _solve_batch(
        np.asarray(free, dtype=bool),
        np.asarray(cand_mask, dtype=bool),
        np.asarray(cand_slice, dtype=np.int32),
        np.asarray(cand_valid, dtype=bool),
        np.asarray(origin_rank, dtype=np.int32),
        np.asarray(item_class, dtype=np.int32),
        np.asarray(item_active, dtype=bool),
    )
    return np.asarray(out)


def check_invariants(chosen, free, cand_mask, cand_slice, cand_valid, item_class, item_active):
    """Validity + maximality against the final free state. Returns final free."""
    free = np.array(free, dtype=bool, copy=True)
    for g, c in enumerate(chosen):
        if c < 0:
            continue
        k = item_class[g]
        assert item_active[g], f"padding item {g} was admitted"
        assert cand_valid[k, c], f"item {g} committed invalid candidate {c}"
        s = cand_slice[k, c]
        mask = cand_mask[k, c]
        assert (free[s] | ~mask).all(), f"item {g} granted non-free hosts (double-booking)"
        free[s] &= ~mask
    for g in range(len(chosen)):
        if chosen[g] >= 0 or not item_active[g]:
            continue
        k = item_class[g]
        for c in range(cand_valid.shape[1]):
            if not cand_valid[k, c]:
                continue
            s = cand_slice[k, c]
            assert not (free[s] | ~cand_mask[k, c]).all() or not cand_mask[k, c].any() or (
                cand_mask[k, c] & ~free[s]
            ).any(), f"unadmitted item {g} still has feasible candidate {c} (not maximal)"
    return free


def greedy_reference(free, cand_mask, cand_slice, cand_valid, origin_rank, item_class, item_active):
    """Sequential highest-priority-first greedy with the kernel's score
    (best-fit: fewest free hosts on the slice; contiguity; origin rank)."""
    free = np.array(free, dtype=bool, copy=True)
    h = free.shape[1]
    chosen = np.full(len(item_class), -1, dtype=int)
    for g in range(len(item_class)):
        if not item_active[g]:
            continue
        k = item_class[g]
        best, best_score = -1, None
        for c in range(cand_valid.shape[1]):
            if not cand_valid[k, c]:
                continue
            s = cand_slice[k, c]
            mask = cand_mask[k, c]
            if (mask & ~free[s]).any():
                continue
            free_cnt = int(free[s].sum())
            after = free[s] & ~mask
            pairs = int((after[:-1] & after[1:]).sum())
            score = (free_cnt * h + (h - pairs)) * h + int(origin_rank[k, c])
            if best_score is None or score < best_score:
                best, best_score = c, score
        if best >= 0:
            chosen[g] = best
            s = cand_slice[k, best]
            free[s] &= ~cand_mask[k, best]
    return chosen


def host_mask(h_total, hosts):
    m = np.zeros(h_total, dtype=bool)
    m[list(hosts)] = True
    return m


class TestSolveBatch:
    def test_identical_gang_desync(self):
        """G identical single-host items on one 4-host slice: all four must be
        admitted in ONE solve on distinct hosts (the rank desync), not one per
        round with duplicates rejected."""
        free = np.ones((1, 4), dtype=bool)
        cand_mask = np.stack([[host_mask(4, [i]) for i in range(4)]])  # (1, 4, 4)
        cand_slice = np.zeros((1, 4), dtype=int)
        cand_valid = np.ones((1, 4), dtype=bool)
        origin_rank = np.arange(4, dtype=int)[None, :]
        item_class = np.zeros(4, dtype=int)
        item_active = np.ones(4, dtype=bool)
        chosen = solve(free, cand_mask, cand_slice, cand_valid, origin_rank, item_class, item_active)
        assert (chosen >= 0).all()
        assert len({int(c) for c in chosen}) == 4  # four distinct hosts
        check_invariants(chosen, free, cand_mask, cand_slice, cand_valid, item_class, item_active)

    def test_cross_class_conflict_priority(self):
        """Two classes whose only candidates overlap on host 0: the
        higher-priority (earlier) item wins, the other is rejected."""
        free = np.ones((1, 2), dtype=bool)
        # class 0: hosts {0,1}; class 1: host {0} — mutually exclusive.
        cand_mask = np.zeros((2, 1, 2), dtype=bool)
        cand_mask[0, 0] = host_mask(2, [0, 1])
        cand_mask[1, 0] = host_mask(2, [0])
        cand_slice = np.zeros((2, 1), dtype=int)
        cand_valid = np.ones((2, 1), dtype=bool)
        origin_rank = np.zeros((2, 1), dtype=int)
        item_class = np.array([0, 1])
        item_active = np.ones(2, dtype=bool)
        chosen = solve(free, cand_mask, cand_slice, cand_valid, origin_rank, item_class, item_active)
        assert chosen[0] == 0 and chosen[1] == -1  # priority order respected
        # Reversed priority: the single-host class wins, whole-slice loses.
        chosen = solve(free, cand_mask, cand_slice, cand_valid, origin_rank, [1, 0], item_active)
        assert chosen[0] == 0 and chosen[1] == -1

    def test_padding_rows_ignored(self):
        """Inactive (padding) items must stay -1 and consume nothing."""
        free = np.ones((1, 2), dtype=bool)
        cand_mask = np.zeros((1, 2, 2), dtype=bool)
        cand_mask[0, 0] = host_mask(2, [0])
        cand_mask[0, 1] = host_mask(2, [1])
        cand_slice = np.zeros((1, 2), dtype=int)
        cand_valid = np.ones((1, 2), dtype=bool)
        origin_rank = np.array([[0, 1]])
        item_class = np.zeros(4, dtype=int)
        item_active = np.array([True, False, True, False])
        chosen = solve(free, cand_mask, cand_slice, cand_valid, origin_rank, item_class, item_active)
        assert chosen[1] == -1 and chosen[3] == -1
        assert (chosen[[0, 2]] >= 0).all()
        assert chosen[0] != chosen[2]

    def test_infeasible_leftovers(self):
        """More identical items than capacity: exactly capacity admitted."""
        free = np.ones((2, 4), dtype=bool)  # 2 slices x 4 hosts = 8 host slots
        # class: 2-adjacent-host pairs on either slice (3 origins x 2 slices).
        cands = []
        for s in range(2):
            for o in range(3):
                cands.append((s, host_mask(4, [o, o + 1]), o))
        cand_mask = np.stack([[m for _, m, _ in cands]])
        cand_slice = np.array([[s for s, _, _ in cands]])
        cand_valid = np.ones((1, len(cands)), dtype=bool)
        origin_rank = np.array([[r for _, _, r in cands]])
        g = 6  # ask for 6 pairs; only 4 fit (2 per slice)
        chosen = solve(free, cand_mask, cand_slice, cand_valid, origin_rank, np.zeros(g, dtype=int), np.ones(g, dtype=bool))
        assert (chosen >= 0).sum() == 4
        check_invariants(chosen, free, cand_mask, cand_slice, cand_valid, np.zeros(g, dtype=int), np.ones(g, dtype=bool))

    def test_rank_clamp_more_items_than_candidates(self):
        """G items of a class with C < G candidates: the rank min(rank, C-1)
        clamp must not admit duplicates or crash."""
        free = np.ones((1, 2), dtype=bool)
        cand_mask = np.zeros((1, 1, 2), dtype=bool)
        cand_mask[0, 0] = host_mask(2, [0])
        cand_slice = np.zeros((1, 1), dtype=int)
        cand_valid = np.ones((1, 1), dtype=bool)
        origin_rank = np.zeros((1, 1), dtype=int)
        g = 5
        chosen = solve(free, cand_mask, cand_slice, cand_valid, origin_rank, np.zeros(g, dtype=int), np.ones(g, dtype=bool))
        assert (chosen >= 0).sum() == 1
        assert chosen[0] == 0  # highest priority got it

    def test_empty_free_terminates(self):
        """Fully-busy pool: nothing admitted, loop terminates immediately."""
        free = np.zeros((2, 4), dtype=bool)
        cand_mask = np.ones((1, 2, 4), dtype=bool)
        cand_slice = np.array([[0, 1]])
        cand_valid = np.ones((1, 2), dtype=bool)
        origin_rank = np.zeros((1, 2), dtype=int)
        chosen = solve(free, cand_mask, cand_slice, cand_valid, origin_rank, np.zeros(3, dtype=int), np.ones(3, dtype=bool))
        assert (chosen == -1).all()

    def test_best_fit_prefers_fuller_slice(self):
        """Equal candidates on a 3-free-host slice vs a 1-free-host slice:
        best-fit must take the fuller (fewer free hosts) slice."""
        free = np.array([[True, True, True, False], [True, False, False, False]])
        cands = [(0, host_mask(4, [0]), 0), (1, host_mask(4, [0]), 0)]
        cand_mask = np.stack([[m for _, m, _ in cands]])
        cand_slice = np.array([[s for s, _, _ in cands]])
        cand_valid = np.ones((1, 2), dtype=bool)
        origin_rank = np.array([[r for _, _, r in cands]])
        chosen = solve(free, cand_mask, cand_slice, cand_valid, origin_rank, np.zeros(1, dtype=int), np.ones(1, dtype=bool))
        assert chosen[0] == 1  # candidate on the nearly-full slice

    def test_contiguity_prefers_edge_over_middle(self):
        """A 1-host ask on a fully-free 4-line: taking the middle splits the
        residue (pairs 1), taking an edge keeps 2 adjacent pairs — the score
        must pick an edge host (origin 0 via corner rank + pairs)."""
        free = np.ones((1, 4), dtype=bool)
        cands = [(0, host_mask(4, [i]), i) for i in range(4)]
        cand_mask = np.stack([[m for _, m, _ in cands]])
        cand_slice = np.zeros((1, 4), dtype=int)
        cand_valid = np.ones((1, 4), dtype=bool)
        origin_rank = np.array([[r for _, _, r in cands]])
        chosen = solve(free, cand_mask, cand_slice, cand_valid, origin_rank, np.zeros(1, dtype=int), np.ones(1, dtype=bool))
        assert chosen[0] in (0, 3)

    @pytest.mark.parametrize("seed", range(8))
    def test_property_random_instances(self, seed):
        """Random instances: validity + maximality always hold; when classes
        don't share hosts across slices, result matches sequential greedy."""
        rng = np.random.default_rng(seed)
        s, h = 3, 4
        k = int(rng.integers(1, 4))
        c = int(rng.integers(1, 7))
        g = int(rng.integers(1, 12))
        free = rng.random((s, h)) < 0.7
        cand_mask = rng.random((k, c, h)) < 0.4
        cand_slice = rng.integers(0, s, size=(k, c))
        cand_valid = (rng.random((k, c)) < 0.9) & cand_mask.any(axis=-1)
        origin_rank = rng.integers(0, h, size=(k, c))
        item_class = rng.integers(0, k, size=g)
        item_active = rng.random(g) < 0.9
        chosen = solve(free, cand_mask, cand_slice, cand_valid, origin_rank, item_class, item_active)
        check_invariants(chosen, free, cand_mask, cand_slice, cand_valid, item_class, item_active)

    @pytest.mark.parametrize("seed", range(8))
    def test_greedy_parity_disjoint_classes(self, seed):
        """Classes on disjoint slices (no cross-class conflicts): the kernel
        must equal sequential highest-priority-first greedy EXACTLY."""
        rng = np.random.default_rng(100 + seed)
        k, c, h = 2, 5, 4
        s = k  # one slice per class -> disjoint
        free = rng.random((s, h)) < 0.8
        cand_mask = rng.random((k, c, h)) < 0.5
        cand_slice = np.tile(np.arange(k)[:, None], (1, c))  # class k -> slice k
        cand_valid = cand_mask.any(axis=-1)
        origin_rank = rng.integers(0, h, size=(k, c))
        g = 8
        item_class = rng.integers(0, k, size=g)
        item_active = np.ones(g, dtype=bool)
        chosen = solve(free, cand_mask, cand_slice, cand_valid, origin_rank, item_class, item_active)
        ref = greedy_reference(free, cand_mask, cand_slice, cand_valid, origin_rank, item_class, item_active)
        assert (chosen == ref).all(), f"kernel {chosen} != greedy {ref}"


class TestKernelEquivalence:
    """The solver_kernel knob's contract: all three kernels (jit, numpy,
    pure-python) implement the SAME parallel-rounds algorithm and must
    return bit-identical placements on any instance — the property that
    makes the knob a perf choice, never a scheduling-quality one."""

    @staticmethod
    def _args(rng):
        s, h = int(rng.integers(1, 4)), 4
        k = int(rng.integers(1, 4))
        c = int(rng.integers(1, 8))
        g = int(rng.integers(1, 14))
        return (
            rng.random((s, h)) < 0.7,
            rng.random((k, c, h)) < 0.4,
            rng.integers(0, s, size=(k, c)).astype(np.int32),
            (rng.random((k, c)) < 0.9),
            rng.integers(0, h, size=(k, c)).astype(np.int32),
            rng.integers(0, k, size=g).astype(np.int32),
            rng.random(g) < 0.9,
        )

    @pytest.mark.parametrize("seed", range(16))
    def test_three_kernels_identical(self, seed):
        rng = np.random.default_rng(1000 + seed)
        free, cand_mask, cand_slice, cand_valid, origin_rank, item_class, item_active = self._args(rng)
        cand_valid = cand_valid & cand_mask.any(axis=-1)
        via_jax = solve(free, cand_mask, cand_slice, cand_valid, origin_rank,
                        item_class, item_active)
        via_np = _solve_batch_numpy(
            np.asarray(free, dtype=bool), np.asarray(cand_mask, dtype=bool),
            np.asarray(cand_slice, dtype=np.int32),
            np.asarray(cand_valid, dtype=bool),
            np.asarray(origin_rank, dtype=np.int32),
            np.asarray(item_class, dtype=np.int32),
            np.asarray(item_active, dtype=bool),
        )
        via_py = _solve_batch_python(
            np.asarray(free, dtype=bool), np.asarray(cand_mask, dtype=bool),
            np.asarray(cand_slice, dtype=np.int32),
            np.asarray(cand_valid, dtype=bool),
            np.asarray(origin_rank, dtype=np.int32),
            np.asarray(item_class, dtype=np.int32),
            np.asarray(item_active, dtype=bool),
        )
        assert (via_jax == via_np).all(), f"jax {via_jax} != numpy {via_np}"
        assert (via_jax == via_py).all(), f"jax {via_jax} != python {via_py}"
