"""Durable host state: snapshot + journal recovery (cluster/store.py).

The reference substrate survives apiserver restarts because etcd is durable
(SURVEY.md §1 substrate row); these tests pin the same property onto the
HostStore: every acknowledged write is recoverable, a torn final journal
record (crash mid-write) is dropped without corrupting the prefix, and
compaction loses nothing.
"""

import json
import os

import pytest

from training_operator_tpu.api.common import Container, PodTemplateSpec, ReplicaSpec
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta
from training_operator_tpu.cluster.apiserver import APIServer
from training_operator_tpu.cluster.objects import Event, Lease, Pod
from training_operator_tpu.cluster.store import (
    SNAPSHOT,
    HostStore,
    JournalWriteError,
    journal_name,
)


def _job(name: str) -> JAXJob:
    return JAXJob(
        metadata=ObjectMeta(name=name),
        replica_specs={
            "Worker": ReplicaSpec(
                replicas=2,
                template=PodTemplateSpec(
                    containers=[Container(name="jax", image="trainer")]
                ),
            )
        },
    )


def _pod(name: str) -> Pod:
    return Pod(
        metadata=ObjectMeta(name=name),
        spec=PodTemplateSpec(containers=[Container(name="c", image="trainer")]),
    )


def _recover(tmp_path) -> APIServer:
    api = APIServer()
    HostStore(str(tmp_path)).load_into(api)
    return api


class TestJournalRecovery:
    def test_writes_survive_restart(self, tmp_path):
        api = APIServer()
        store = HostStore(str(tmp_path))
        store.load_into(api)
        store.attach(api)

        api.create(_job("alpha"))
        api.create(_pod("alpha-worker-0"))
        job = api.get("JAXJob", "default", "alpha")
        api.update(job)  # a version-bumping update rides the journal too
        api.create(
            Lease(metadata=ObjectMeta(name="l", namespace="sys"), holder="op-a",
                  renew_time=123.0)
        )
        api.record_event(Event(object_name="alpha", reason="Created", message="m"))
        api.append_pod_log("default", "alpha-worker-0", "line one\nline two", 1.5)
        api.delete("Pod", "default", "alpha-worker-0")
        store.close()

        api2 = _recover(tmp_path)
        assert api2.try_get("JAXJob", "default", "alpha") is not None
        assert api2.try_get("Pod", "default", "alpha-worker-0") is None
        lease = api2.get("Lease", "sys", "l")
        assert lease.holder == "op-a" and lease.renew_time == 123.0
        assert [e.reason for e in api2.events("alpha")] == ["Created"]
        # resourceVersion counter resumes past every persisted write: a new
        # write can never collide with a pre-crash version.
        rv_before = api.version()
        assert api2.version() >= rv_before

    def test_torn_final_record_dropped(self, tmp_path):
        api = APIServer()
        store = HostStore(str(tmp_path))
        store.load_into(api)
        store.attach(api)
        api.create(_job("keep-me"))
        store.close()

        # Crash mid-write: the final record is half a JSON object.
        with open(tmp_path / journal_name(0), "a") as f:
            f.write('{"op": "put", "obj": {"kind": "JAXJob", "metadata"')

        api2 = _recover(tmp_path)
        assert api2.try_get("JAXJob", "default", "keep-me") is not None
        assert len(api2.list("JAXJob")) == 1

    def test_replay_is_idempotent_across_snapshot_and_journal(self, tmp_path):
        """An object present in the snapshot AND re-written in the journal
        converges to the journal (later) state."""
        api = APIServer()
        store = HostStore(str(tmp_path))
        store.load_into(api)
        store.attach(api)
        lease = Lease(metadata=ObjectMeta(name="l", namespace="sys"), holder="a")
        api.create(lease)
        store.compact(api)  # snapshot holds holder=a
        got = api.get("Lease", "sys", "l")
        got.holder = "b"
        api.update(got)     # journal holds holder=b
        store.close()

        api2 = _recover(tmp_path)
        assert api2.get("Lease", "sys", "l").holder == "b"

    def test_pod_logs_and_cursors_survive(self, tmp_path):
        api = APIServer()
        store = HostStore(str(tmp_path))
        store.load_into(api)
        store.attach(api)
        api.create(_pod("p"))
        api.append_pod_log("default", "p", "first", 1.0)
        api.append_pod_log("default", "p", "second", 2.0)
        store.close()

        api2 = _recover(tmp_path)
        lines, cursor = api2.read_pod_log("default", "p")
        assert [ln.split(" ", 1)[1] for ln in lines] == ["first", "second"]
        assert cursor == 2

    def test_uid_counter_advances_past_restored_uids(self, tmp_path):
        api = APIServer()
        store = HostStore(str(tmp_path))
        store.load_into(api)
        store.attach(api)
        created = api.create(_pod("p"))
        old_uid = created.metadata.uid
        store.close()

        api2 = _recover(tmp_path)
        api2.delete("Pod", "default", "p")
        fresh = api2.create(_pod("p"))
        # A recreated name must get a NEW incarnation uid — controllers key
        # liveness decisions on uid.
        assert fresh.metadata.uid != old_uid


class TestCompaction:
    def test_compaction_truncates_journal_losslessly(self, tmp_path):
        api = APIServer()
        store = HostStore(str(tmp_path), compact_every=5)
        store.load_into(api)
        store.attach(api)
        for i in range(12):
            api.create(_pod(f"p-{i}"))
            store.maybe_compact(api)
        store.close()

        # The journal was rotated at least once: old generations deleted,
        # the live one shorter than the full history.
        import json as _json
        snap_gen = _json.load(open(tmp_path / SNAPSHOT))["gen"]
        assert snap_gen >= 1
        assert not os.path.exists(tmp_path / journal_name(0))
        live = open(tmp_path / journal_name(snap_gen)).read().strip().splitlines()
        assert len(live) < 12

        api2 = _recover(tmp_path)
        assert len(api2.list("Pod")) == 12

    def test_boot_compaction_folds_torn_tail(self, tmp_path):
        api = APIServer()
        store = HostStore(str(tmp_path))
        store.load_into(api)
        store.attach(api)
        api.create(_pod("p"))
        store.close()
        with open(tmp_path / journal_name(0), "a") as f:
            f.write('{"op": "pu')  # torn, no trailing newline

        # Recovery TRUNCATES the torn tail, so a process appending to the
        # same generation can never merge a record onto the fragment and
        # silently lose everything after the corrupt line.
        api2 = APIServer()
        store2 = HostStore(str(tmp_path))
        store2.load_into(api2)
        store2.attach(api2)
        api2.create(_pod("q"))  # appends to the truncated gen-0 journal
        store2.close()

        api3 = _recover(tmp_path)
        assert api3.try_get("Pod", "default", "p") is not None
        assert api3.try_get("Pod", "default", "q") is not None

    def test_stale_journal_not_double_applied(self, tmp_path):
        """Crash window: snapshot landed but the old-generation journal was
        not yet deleted. Recovery must skip it — events and pod-log records
        are append-only and would otherwise be applied twice."""
        api = APIServer()
        store = HostStore(str(tmp_path))
        store.load_into(api)
        store.attach(api)
        api.create(_pod("p"))
        api.record_event(Event(object_name="p", reason="Scheduled", message="m"))
        api.append_pod_log("default", "p", "only-once", 1.0)
        store.compact(api)
        store.close()

        # Simulate the crash: resurrect the pre-compact journal the store
        # deleted (its records are all inside the snapshot now).
        with open(tmp_path / journal_name(0), "w") as f:
            f.write(json.dumps({"op": "event", "event": {
                "object_name": "p", "reason": "Scheduled", "message": "m"}}) + "\n")
            f.write(json.dumps({"op": "log", "ns": "default", "name": "p",
                                "line": "only-once", "ts": 1.0}) + "\n")

        api2 = _recover(tmp_path)
        assert len(api2.events("p")) == 1
        lines, _ = api2.read_pod_log("default", "p")
        assert len(lines) == 1
        # And the stale file was cleaned up.
        assert not os.path.exists(tmp_path / journal_name(0))

    def test_compact_during_concurrent_writes_loses_nothing(self, tmp_path):
        """Records landing while the snapshot file is being written (outside
        the API lock) belong to the new generation and survive recovery."""
        import threading

        api = APIServer()
        store = HostStore(str(tmp_path))
        store.load_into(api)
        store.attach(api)
        stop = threading.Event()
        created = []

        def writer():
            i = 0
            while not stop.is_set():
                api.create(_pod(f"w-{i}"))
                created.append(f"w-{i}")
                i += 1

        t = threading.Thread(target=writer)
        t.start()
        try:
            for _ in range(20):
                store.compact(api)
        finally:
            stop.set()
            t.join()
        store.close()

        api2 = _recover(tmp_path)
        names = {p.metadata.name for p in api2.list("Pod")}
        assert names == set(created)

    def test_snapshot_is_atomic(self, tmp_path):
        """No .tmp file left behind; the snapshot is valid JSON."""
        api = APIServer()
        store = HostStore(str(tmp_path))
        store.load_into(api)
        store.attach(api)
        api.create(_pod("p"))
        store.compact(api)
        store.close()
        assert not os.path.exists(tmp_path / (SNAPSHOT + ".tmp"))
        snap = json.load(open(tmp_path / SNAPSHOT))
        assert snap["rv"] >= 1 and len(snap["objects"]) == 1


class TestDurabilityKnobs:
    def test_bytes_trigger_compacts_under_large_objects(self, tmp_path):
        """VERDICT r5 Next #8: record-count-triggered compaction alone lets
        a few huge objects grow the journal unboundedly — far under the
        4096-record default, the BYTES bound must fire, rotate the journal,
        and lose nothing."""
        from training_operator_tpu.cluster.objects import ConfigMap

        api = APIServer()
        store = HostStore(str(tmp_path), compact_every=4096,
                          compact_max_bytes=256 * 1024)
        store.load_into(api)
        store.attach(api)
        big = "x" * 64 * 1024
        for i in range(2):
            api.create(ConfigMap(metadata=ObjectMeta(name=f"big-{i}"),
                                 data={"blob": big}))
        assert store.maybe_compact(api) is False, "under both bounds: no compact"
        for i in range(2, 8):
            api.create(ConfigMap(metadata=ObjectMeta(name=f"big-{i}"),
                                 data={"blob": big}))
        # 8 records << 4096, but ~512KiB of journal >= the 256KiB bound.
        assert store.maybe_compact(api) is True
        assert os.path.exists(os.path.join(str(tmp_path), SNAPSHOT))
        assert os.path.getsize(
            os.path.join(str(tmp_path), journal_name(store._gen))
        ) == 0, "fresh generation after the bytes-triggered rotate"
        store.close()

        api2 = _recover(tmp_path)
        assert {
            o.metadata.name for o in api2.list("ConfigMap")
        } == {f"big-{i}" for i in range(8)}
        assert api2.get("ConfigMap", "default", "big-0").data["blob"] == big

    def test_bytes_trigger_disabled_with_zero(self, tmp_path):
        from training_operator_tpu.cluster.objects import ConfigMap

        api = APIServer()
        store = HostStore(str(tmp_path), compact_every=4096, compact_max_bytes=0)
        store.load_into(api)
        store.attach(api)
        api.create(ConfigMap(metadata=ObjectMeta(name="b"),
                             data={"blob": "x" * 1024 * 1024}))
        assert store.maybe_compact(api) is False
        store.close()

    def test_fsync_per_record_opt_in(self, tmp_path):
        """The flush-vs-fsync policy knob: fsync_per_record=True must fsync
        the journal fd on every record (power-loss durability), and the
        default must not (etcd-style batched fsync economics)."""
        fsyncs = []
        real_fsync = os.fsync

        def counting_fsync(fd):
            fsyncs.append(fd)
            return real_fsync(fd)

        api = APIServer()
        store = HostStore(str(tmp_path), fsync_per_record=True)
        store.load_into(api)
        store.attach(api)
        os.fsync = counting_fsync
        try:
            api.create(_pod("p0"))
            api.create(_pod("p1"))
        finally:
            os.fsync = real_fsync
        assert len(fsyncs) == 2
        store.close()

        api = APIServer()
        store2 = HostStore(str(tmp_path / "nofsync"))
        store2.load_into(api)
        store2.attach(api)
        os.fsync = counting_fsync
        try:
            fsyncs.clear()
            api.create(_pod("p2"))
        finally:
            os.fsync = real_fsync
        assert fsyncs == [], "default policy must flush, not fsync, per record"
        store2.close()


class _BoomFH:
    """A journal file handle whose writes fail (disk full / revoked fd)."""

    def write(self, s):
        raise OSError(28, "No space left on device")

    def flush(self):
        pass

    def close(self):
        pass


class TestJournalWriteFailure:
    """ADVICE r5: a failed journal append must be FATAL-loud (etcd-style)
    and latched — never a silent memory/disk divergence that a later
    restart converts into lost writes. The journal is write-ahead, so the
    failing write aborts cleanly: no watcher ever observed it."""

    def test_failure_raises_latches_and_keeps_disk_honest(self, tmp_path):
        api = APIServer()
        store = HostStore(str(tmp_path))
        store.load_into(api)
        store.attach(api)
        api.create(_pod("durable"))
        assert store.degraded is False

        store._journal_fh = _BoomFH()
        with pytest.raises(JournalWriteError):
            api.create(_pod("diverged"))
        assert store.degraded is True
        # Write-ahead: the aborted write never reached memory (and so was
        # never broadcast to watchers) — memory and disk agree.
        assert api.try_get("Pod", "default", "diverged") is None

        # Latched: every subsequent mutation fails loudly too, even though
        # the broken fh is gone — a degraded store never quietly resumes.
        store._journal_fh = None
        with pytest.raises(JournalWriteError):
            api.create(_pod("after-latch"))

        # A compaction attempt while degraded must REFUSE: snapshotting the
        # diverged in-memory state would durably resurrect the write whose
        # journal append failed (its client saw an error).
        store._records_since_snapshot = 10**6
        assert store.maybe_compact(api) is False
        store.compact(api)  # direct call refuses too
        assert not os.path.exists(tmp_path / SNAPSHOT)

        # Disk stays honest: recovery sees exactly the acknowledged-and-
        # journaled prefix, not the diverged write.
        api2 = _recover(tmp_path)
        names = {p.metadata.name for p in api2.list("Pod")}
        assert names == {"durable"}


class TestTornTailTolerance:
    """PR 9 satellite: a crash mid-append (routine with journal_fsync off)
    must degrade to 'lose the torn suffix', never 'refuse to start' — and
    replay itself must stay read-only so inspecting a crashed state dir
    cannot alter the evidence. The physical truncation is deferred to the
    next append (attach), the one moment it becomes load-bearing."""

    def test_replay_is_read_only_counts_and_logs(self, tmp_path):
        from training_operator_tpu.utils import metrics

        api = APIServer()
        store = HostStore(str(tmp_path))
        store.load_into(api)
        store.attach(api)
        for i in range(3):
            api.create(_pod(f"whole-{i}"))
        store.close()
        path = tmp_path / journal_name(0)
        with open(path, "a") as f:
            f.write('{"op": "put", "obj": {"kind": "Pod", "met')
        size_torn = os.path.getsize(path)

        before = metrics.journal_torn_tail.total()
        api2 = APIServer()
        store2 = HostStore(str(tmp_path))
        store2.load_into(api2)
        # Every whole record replayed; the tear detected and counted...
        assert len(api2.list("Pod")) == 3
        assert metrics.journal_torn_tail.total() == before + 1
        # ...but the file is UNTOUCHED: replay never writes.
        assert os.path.getsize(path) == size_torn
        assert str(path) in store2._torn_tails

    def test_attach_truncates_then_appends_cleanly(self, tmp_path):
        api = APIServer()
        store = HostStore(str(tmp_path))
        store.load_into(api)
        store.attach(api)
        api.create(_pod("keep"))
        store.close()
        path = tmp_path / journal_name(0)
        whole = os.path.getsize(path)
        with open(path, "a") as f:
            f.write('{"op": "put", "obj"')

        api2 = APIServer()
        store2 = HostStore(str(tmp_path))
        store2.load_into(api2)
        store2.attach(api2)  # the truncation moment
        assert os.path.getsize(path) == whole
        api2.create(_pod("after-tear"))  # appends at the clean boundary
        store2.close()

        api3 = _recover(tmp_path)
        assert {p.metadata.name for p in api3.list("Pod")} == {
            "keep", "after-tear"
        }

    def test_torn_tail_with_fsync_off_is_not_fatal_at_scale(self, tmp_path):
        """A tear after MANY records: the full prefix survives, only the
        torn suffix is lost, and startup never raises."""
        api = APIServer()
        store = HostStore(str(tmp_path))
        store.load_into(api)
        store.attach(api)
        for i in range(50):
            api.create(_pod(f"p-{i:02d}"))
        store.close()
        with open(tmp_path / journal_name(0), "a") as f:
            f.write('{"op"')
        api2 = _recover(tmp_path)
        assert len(api2.list("Pod")) == 50


class TestCrashSafeCompaction:
    """PR 9 satellite: compaction's crash windows. The sequence is
    temp-write + fsync -> atomic rename -> dir fsync -> THEN unlink old
    journals; a crash at any point must leave either (old snapshot + all
    journals) or (new snapshot + all journals) — never neither."""

    def _seed(self, tmp_path, n=5):
        api = APIServer()
        store = HostStore(str(tmp_path))
        store.load_into(api)
        store.attach(api)
        for i in range(n):
            api.create(_pod(f"c-{i}"))
        return api, store

    def test_crash_between_temp_write_and_replace_loses_nothing(
        self, tmp_path, monkeypatch
    ):
        api, store = self._seed(tmp_path)
        real_replace = os.replace

        def boom(src, dst, *a, **k):
            if str(dst).endswith(SNAPSHOT):
                raise OSError("injected crash before the rename")
            return real_replace(src, dst, *a, **k)

        monkeypatch.setattr(os, "replace", boom)
        with pytest.raises(OSError):
            store.compact(api)
        monkeypatch.undo()

        # The snapshot never landed, and every journal generation is still
        # on disk (including the freshly rotated one the compact opened):
        # recovery replays the full history.
        assert not os.path.exists(tmp_path / SNAPSHOT)
        api2 = _recover(tmp_path)
        assert len(api2.list("Pod")) == 5

    def test_crash_between_replace_and_unlink_loses_nothing(
        self, tmp_path, monkeypatch
    ):
        api, store = self._seed(tmp_path)
        real_unlink = os.unlink

        def boom(path, *a, **k):
            if journal_name(0) in str(path):
                raise OSError("injected crash before old-journal unlink")
            return real_unlink(path, *a, **k)

        monkeypatch.setattr(os, "unlink", boom)
        store.compact(api)  # unlink failure is absorbed (crash-equivalent)
        monkeypatch.undo()

        # New snapshot + the stale gen-0 journal coexist; recovery must
        # skip the stale generation (gen filter), not double-apply it.
        assert os.path.exists(tmp_path / SNAPSHOT)
        assert os.path.exists(tmp_path / journal_name(0))
        api2 = _recover(tmp_path)
        assert len(api2.list("Pod")) == 5
        # The stale journal is cleaned up by that recovery pass.
        assert not os.path.exists(tmp_path / journal_name(0))

    def test_leftover_temp_snapshot_is_ignored(self, tmp_path):
        api, store = self._seed(tmp_path)
        # A crash mid-temp-write leaves a partial .tmp; it must never be
        # read as a snapshot.
        (tmp_path / (SNAPSHOT + ".tmp")).write_text('{"rv": 999, "objec')
        api2 = _recover(tmp_path)
        assert len(api2.list("Pod")) == 5
