"""Node lifecycle & failure-domain recovery: heartbeats -> NotReady ->
unreachable taint -> eviction -> gang re-placement, plus the NodeChaos tier.

The scenario the subsystem exists for: a dead TPU host breaks a whole
slice's ICI mesh, so recovery is not "restart a pod" but "re-solve the
gang's placement around the dead hardware". Every test here drives that
machinery through the same public paths a real deployment uses — kubelet
heartbeats, the lifecycle controller, engine triage, the gang scheduler —
never by hand-setting the recovered state.
"""

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import (
    Container,
    JobConditionType,
    PodTemplateSpec,
    ReplicaSpec,
    RestartPolicy,
)
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta, TPUPolicy
from training_operator_tpu.cluster.chaos import ChaosMonkey, NodeChaos
from training_operator_tpu.cluster.inventory import (
    TPU_RESOURCE,
    make_cpu_pool,
    make_tpu_pool,
)
from training_operator_tpu.cluster.objects import (
    NODE_LEASE_NAMESPACE,
    TAINT_UNREACHABLE,
    PodPhase,
    has_taint,
    node_ready,
)
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
    bind_pod,
)
from training_operator_tpu.controllers.jax import JAXController
from training_operator_tpu.controllers.manager import OperatorManager
from training_operator_tpu.controllers.nodelifecycle import NodeLifecycleController
from training_operator_tpu.engine.core import NODE_LOST_MESSAGE_PREFIX
from training_operator_tpu.scheduler import GangScheduler, TPUPacker
from training_operator_tpu.utils import metrics

HEARTBEAT = 5.0
GRACE = 12.0
TOLERATION = 6.0


def make_env(nodes=None, tpu_slices=0, gang=False):
    cluster = Cluster(VirtualClock())
    if tpu_slices:
        cluster.add_nodes(make_tpu_pool(tpu_slices, slice_topology="4x4"))
    else:
        cluster.add_nodes(make_cpu_pool(nodes or 4))
    DefaultScheduler(cluster)
    kubelet = SimKubelet(cluster, heartbeat_interval=HEARTBEAT)
    lifecycle = NodeLifecycleController(
        cluster, grace_period=GRACE, toleration_seconds=TOLERATION
    )
    if gang:
        GangScheduler(cluster, TPUPacker())
    mgr = OperatorManager(cluster, gang_enabled=gang)
    mgr.register(JAXController(cluster.api))
    return cluster, kubelet, lifecycle, mgr


def cpu_job(name, workers=2, duration="20", cpu=1.0):
    tmpl = PodTemplateSpec(
        containers=[Container(name="jax", image="img", resources={"cpu": cpu})]
    )
    tmpl.annotations[ANNOTATION_SIM_DURATION] = duration
    return JAXJob(
        metadata=ObjectMeta(name=name),
        replica_specs={
            "Worker": ReplicaSpec(
                replicas=workers, template=tmpl,
                restart_policy=RestartPolicy.EXIT_CODE,
            )
        },
    )


def gang_job(name, duration="500"):
    """One whole-slice TPU gang: 4 workers x 4 chips on a 4x4 slice."""
    tmpl = PodTemplateSpec(
        containers=[Container(
            name="jax", image="img",
            resources={"cpu": 1.0, TPU_RESOURCE: 16.0},
        )],
        annotations={ANNOTATION_SIM_DURATION: duration},
    )
    return JAXJob(
        metadata=ObjectMeta(name=name),
        replica_specs={"Worker": ReplicaSpec(
            replicas=4, template=tmpl, restart_policy=RestartPolicy.EXIT_CODE,
        )},
        tpu_policy=TPUPolicy(accelerator="v5e-16", topology="4x4"),
    )


def running_since(cluster, name, after=-1.0):
    job = cluster.api.get("JAXJob", "default", name)
    c = capi.get_condition(job.status, JobConditionType.RUNNING)
    return c is not None and c.status and c.last_transition_time > after


def succeeded(cluster, name):
    job = cluster.api.get("JAXJob", "default", name)
    return capi.has_condition(job.status, JobConditionType.SUCCEEDED)


class TestHeartbeatDetection:
    def test_heartbeats_keep_nodes_ready(self):
        cluster, _, _, _ = make_env(nodes=3)
        cluster.run_for(GRACE * 4)
        leases = cluster.api.list("Lease", NODE_LEASE_NAMESPACE)
        assert len(leases) == 3
        now = cluster.clock.now()
        assert all(now - l.renew_time <= HEARTBEAT for l in leases)
        assert all(node_ready(n) for n in cluster.api.list("Node"))
        assert not cluster.api.events(reason="NodeNotReady")

    def test_lapsed_heartbeat_flips_notready_and_taints(self):
        cluster, kubelet, _, _ = make_env(nodes=2)
        cluster.run_for(HEARTBEAT)
        kubelet.kill_node("cpu-0")
        t_kill = cluster.clock.now()
        assert cluster.run_until(
            lambda: not node_ready(cluster.api.get("Node", "", "cpu-0")),
            timeout=GRACE * 3,
        )
        node = cluster.api.get("Node", "", "cpu-0")
        assert has_taint(node, TAINT_UNREACHABLE)
        detect = [e for e in cluster.api.events(reason="NodeNotReady")]
        assert detect and detect[0].timestamp >= t_kill + GRACE - HEARTBEAT
        assert metrics.node_notready.value("cpu-0") >= 1.0
        # The healthy node is untouched.
        assert node_ready(cluster.api.get("Node", "", "cpu-1"))

    def test_eviction_after_toleration_fails_pods_with_node_lost(self):
        cluster, kubelet, _, mgr = make_env(nodes=2)
        mgr.submit(cpu_job("victim", workers=2, duration="500"))
        assert cluster.run_until(
            lambda: sum(
                p.status.phase == PodPhase.RUNNING
                for p in cluster.api.list("Pod")
            ) == 2,
            timeout=60,
        )
        target = next(
            p.node_name for p in cluster.api.list("Pod")
            if p.status.phase == PodPhase.RUNNING
        )
        kubelet.kill_node(target)

        def evicted():
            return metrics.node_evictions.value(target) >= 1.0

        before = metrics.node_evictions.value(target)
        assert cluster.run_until(
            lambda: metrics.node_evictions.value(target) > before,
            timeout=(GRACE + TOLERATION) * 3,
        )
        assert cluster.api.events(reason="PodEvicted")
        # The engine recreates the evicted pods on the healthy node and the
        # job converges without burning its restart budget (EXIT_CODE
        # policy + no exit code would otherwise fail it permanently).
        assert cluster.run_until(
            lambda: all(
                p.node_name != target
                for p in cluster.api.list("Pod") if not p.is_terminal()
            ),
            timeout=120,
        )

    def test_recovered_heartbeat_clears_taint(self):
        cluster, kubelet, _, _ = make_env(nodes=2)
        cluster.run_for(HEARTBEAT)
        kubelet.kill_node("cpu-0")
        assert cluster.run_until(
            lambda: not node_ready(cluster.api.get("Node", "", "cpu-0")),
            timeout=GRACE * 3,
        )
        kubelet.recover_node("cpu-0")
        assert cluster.run_until(
            lambda: node_ready(cluster.api.get("Node", "", "cpu-0")),
            timeout=GRACE * 3,
        )
        node = cluster.api.get("Node", "", "cpu-0")
        assert not has_taint(node, TAINT_UNREACHABLE)
        assert cluster.api.events(reason="NodeReady")
        assert metrics.node_recovered.value("cpu-0") >= 1.0


class TestKubeletLiveness:
    """Satellite bugfixes: the kubelet must not run pods on dead or
    nonexistent hardware, and exec must see host loss."""

    def test_pod_bound_to_nonexistent_node_stays_pending(self):
        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_cpu_pool(1))
        SimKubelet(cluster, heartbeats=False)
        pod_tmpl = PodTemplateSpec(
            containers=[Container(name="c", resources={"cpu": 1.0})],
            annotations={ANNOTATION_SIM_DURATION: "1"},
        )
        from training_operator_tpu.cluster.objects import Pod

        pod = Pod(
            metadata=ObjectMeta(name="ghost", namespace="default",
                                labels={"app": "x"}),
            spec=pod_tmpl,
        )
        cluster.api.create(pod)
        live = cluster.api.get("Pod", "default", "ghost")
        bind_pod(cluster.api, live, "no-such-node", now=cluster.clock.now())
        cluster.run_for(30.0)
        assert (
            cluster.api.get("Pod", "default", "ghost").status.phase
            == PodPhase.PENDING
        )

    def test_dead_node_freezes_pod_until_recovery(self):
        cluster, kubelet, _, mgr = make_env(nodes=1)
        mgr.submit(cpu_job("froze", workers=1, duration="30"))
        assert cluster.run_until(
            lambda: any(
                p.status.phase == PodPhase.RUNNING
                for p in cluster.api.list("Pod")
            ),
            timeout=60,
        )
        kubelet.kill_node("cpu-0")
        # complete_pod is the chaos/workload seam: it must refuse too.
        pod = next(p for p in cluster.api.list("Pod"))
        assert not kubelet.complete_pod("default", pod.name, exit_code=0)
        # The annotated 30s finish timer fires during the outage: no exit
        # code can surface from a dead host, so the pod must NOT complete.
        cluster.run_for(40.0)
        # (either still RUNNING-stale or already evicted NodeLost — never
        # SUCCEEDED off a dead host)
        p = cluster.api.try_get("Pod", "default", pod.name)
        if p is not None:
            assert p.status.phase != PodPhase.SUCCEEDED

    def test_exec_into_pod_on_dead_node_is_nonzero(self):
        cluster, kubelet, _, mgr = make_env(nodes=2)
        mgr.submit(cpu_job("mpiish", workers=2, duration="500"))
        assert cluster.run_until(
            lambda: sum(
                p.status.phase == PodPhase.RUNNING
                for p in cluster.api.list("Pod")
            ) == 2,
            timeout=60,
        )
        pod = next(
            p for p in cluster.api.list("Pod")
            if p.status.phase == PodPhase.RUNNING
        )
        rc, _ = cluster.exec.exec_in_pod("default", pod.name, ["hostname"])
        assert rc == 0
        kubelet.kill_node(pod.node_name)
        rc, msg = cluster.exec.exec_in_pod("default", pod.name, ["hostname"])
        assert rc != 0 and pod.node_name in msg


class TestGangNodeLoss:
    """The acceptance e2e: a multi-host TPU gang survives kill_node with
    the dead node absent from the re-solved placement."""

    def test_gang_resolved_onto_intact_slice(self):
        cluster, kubelet, _, mgr = make_env(tpu_slices=2, gang=True)
        mgr.submit(gang_job("gang"))
        assert cluster.run_until(
            lambda: running_since(cluster, "gang"), timeout=120
        )
        pods0 = [p for p in cluster.api.list("Pod") if not p.is_terminal()]
        placed0 = sorted(p.node_name for p in pods0)
        assert len(placed0) == 4 and len(set(placed0)) == 4
        slice0 = placed0[0].rsplit("-host-", 1)[0]

        chaos = NodeChaos(cluster, kubelet)
        kill_t = cluster.clock.now()
        chaos.kill_node(placed0[0])
        assert chaos.kills, "kill schedule must be non-empty"

        # Full pipeline: NotReady detected -> pods evicted -> gang re-solved
        # -> Running again.
        assert cluster.run_until(
            lambda: running_since(cluster, "gang", after=kill_t), timeout=600
        ), cluster.api.get("JAXJob", "default", "gang").status
        mttr = (
            capi.get_condition(
                cluster.api.get("JAXJob", "default", "gang").status,
                JobConditionType.RUNNING,
            ).last_transition_time - kill_t
        )
        assert GRACE <= mttr <= (GRACE + TOLERATION) * 3

        pods1 = [p for p in cluster.api.list("Pod") if not p.is_terminal()]
        placed1 = sorted(p.node_name for p in pods1)
        assert placed0[0] not in placed1, "dead node in new placement"
        # One host of a whole-slice gang died -> contiguity on slice0 is
        # broken -> the re-solve must migrate the whole gang to the intact
        # slice.
        assert all(not n.startswith(slice0) for n in placed1), placed1
        pg = cluster.api.get("PodGroup", "default", "gang")
        assert placed0[0] not in pg.placement.values()

        # Observability: the recovery is visible end to end.
        assert cluster.api.events(reason="NodeNotReady")
        assert cluster.api.events(reason="PodEvicted")
        # Exactly ONE invalidation: the gang's own re-placement evictions
        # must not re-trigger it (that would discard the fresh placement
        # and add a full evict->solve cycle to every node-loss MTTR).
        invalidated = cluster.api.events(reason="PlacementInvalidated")
        # One record AND count 1: event aggregation collapses identical
        # repeats into a count bump, so the length alone can't pin this.
        assert len(invalidated) == 1 and invalidated[0].count == 1, invalidated
        tl = cluster.api.get_timeline("default", "gang")
        span_names = {s["name"] for s in tl["spans"]}
        assert "node_evict" in span_names, span_names
        assert "gang_solve" in span_names

    def test_describe_shows_pod_nodes_and_conditions(self):
        cluster, kubelet, _, mgr = make_env(tpu_slices=2, gang=True)
        mgr.submit(gang_job("viz"))
        assert cluster.run_until(
            lambda: running_since(cluster, "viz"), timeout=120
        )
        target = next(
            p.node_name for p in cluster.api.list("Pod") if not p.is_terminal()
        )
        kubelet.kill_node(target)
        assert cluster.run_until(
            lambda: not node_ready(cluster.api.get("Node", "", target)),
            timeout=GRACE * 3,
        )
        from training_operator_tpu.observe import render_describe

        text = render_describe(cluster.api, "default", "viz")
        assert "Pods:" in text and "NODE-STATE" in text
        assert "NotReady" in text, text

    def test_pending_placement_on_dead_node_is_resolved(self):
        """An admitted-but-unbound placement whose node dies before binding:
        the binder must invalidate and the gang re-admit elsewhere."""
        cluster, kubelet, _, mgr = make_env(tpu_slices=2, gang=True)
        # Kill a slice-0 host BEFORE submitting: the packer can still pick
        # slice-0 only if it ignores readiness — it must not.
        cluster.run_for(HEARTBEAT)
        kubelet.kill_node("slice-0-host-1")
        assert cluster.run_until(
            lambda: not node_ready(cluster.api.get("Node", "", "slice-0-host-1")),
            timeout=GRACE * 3,
        )
        mgr.submit(gang_job("late"))
        assert cluster.run_until(
            lambda: running_since(cluster, "late"), timeout=300
        )
        placed = {
            p.node_name for p in cluster.api.list("Pod") if not p.is_terminal()
        }
        assert placed == {f"slice-1-host-{i}" for i in range(4)}, placed


class TestNodeChaos:
    def test_same_seed_same_schedule(self):
        logs = []
        for _ in range(2):
            cluster, kubelet, _, mgr = make_env(nodes=4)
            chaos = NodeChaos(
                cluster, kubelet, seed=5, interval=7.0, budget=2,
                recover_after=20.0,
            )
            for i in range(2):
                mgr.submit(cpu_job(f"det-{i}", workers=2, duration="120"))
            cluster.run_until(lambda: len(chaos.kills) >= 2, timeout=400)
            logs.append(list(chaos.kills))
        assert logs[0] == logs[1]
        assert len(logs[0]) == 2

    def test_kill_slice_is_a_correlated_failure(self):
        cluster, kubelet, _, mgr = make_env(tpu_slices=2, gang=True)
        mgr.submit(gang_job("corr"))
        assert cluster.run_until(
            lambda: running_since(cluster, "corr"), timeout=120
        )
        placed = sorted(
            p.node_name for p in cluster.api.list("Pod") if not p.is_terminal()
        )
        victim_slice = placed[0].rsplit("-host-", 1)[0]
        chaos = NodeChaos(cluster, kubelet)
        kill_t = cluster.clock.now()
        dead = chaos.kill_slice(victim_slice)
        assert len(dead) == 4 and len(chaos.kills) == 4
        assert cluster.run_until(
            lambda: running_since(cluster, "corr", after=kill_t), timeout=600
        )
        survivors = sorted(
            p.node_name for p in cluster.api.list("Pod") if not p.is_terminal()
        )
        assert all(not n.startswith(victim_slice) for n in survivors)

    def test_maintenance_window_cordons_drains_uncordons(self):
        cluster, kubelet, _, mgr = make_env(nodes=2)
        mgr.submit(cpu_job("maint", workers=2, duration="60"))
        assert cluster.run_until(
            lambda: sum(
                p.status.phase == PodPhase.RUNNING
                for p in cluster.api.list("Pod")
            ) == 2,
            timeout=60,
        )
        target = next(
            p.node_name for p in cluster.api.list("Pod")
            if p.status.phase == PodPhase.RUNNING
        )
        chaos = NodeChaos(cluster, kubelet)
        start = cluster.clock.now() + 5.0
        chaos.maintenance_window(target, start=start, duration=30.0)
        assert cluster.run_until(
            lambda: cluster.api.get("Node", "", target).unschedulable,
            timeout=60,
        )
        # Drained pods carry the NODE_LOST marker and get rescheduled off
        # the cordoned node; the job still converges.
        assert cluster.api.events(reason="NodeDrained")
        assert cluster.run_until(
            lambda: not cluster.api.get("Node", "", target).unschedulable,
            timeout=120,
        )
        assert cluster.run_until(lambda: succeeded(cluster, "maint"), timeout=400)
        assert ("maintenance_begin", target) in [
            (a, t) for _, a, t in chaos.log
        ]


class TestDrainVerbs:
    def test_sdk_cordon_drain_uncordon(self):
        from training_operator_tpu.sdk import TrainingClient

        cluster, kubelet, _, mgr = make_env(nodes=3)
        client = TrainingClient(cluster)
        mgr.submit(cpu_job("drainee", workers=2, duration="300"))
        assert cluster.run_until(
            lambda: sum(
                p.status.phase == PodPhase.RUNNING
                for p in cluster.api.list("Pod")
            ) == 2,
            timeout=60,
        )
        target = next(
            p.node_name for p in cluster.api.list("Pod")
            if p.status.phase == PodPhase.RUNNING
        )
        client.cordon_node(target)
        assert cluster.api.get("Node", "", target).unschedulable
        evicted = client.drain_node(target)
        assert evicted, "drain must evict the running pods"
        for pod_name in evicted:
            p = cluster.api.try_get("Pod", "default", pod_name)
            if p is not None and p.status.phase == PodPhase.FAILED:
                assert p.status.message.startswith(NODE_LOST_MESSAGE_PREFIX)
        # Recreated pods land elsewhere; the drained node stays empty.
        assert cluster.run_until(
            lambda: all(
                p.node_name != target
                for p in cluster.api.list("Pod") if not p.is_terminal()
            ) and sum(
                p.status.phase == PodPhase.RUNNING
                for p in cluster.api.list("Pod")
            ) == 2,
            timeout=200,
        )
        client.uncordon_node(target)
        assert not cluster.api.get("Node", "", target).unschedulable


class TestChaosMatrix:
    """Satellite: NodeChaos + WireChaos + ChaosMonkey in one seeded
    scenario — node deaths, wire faults against a remote operator, and pod
    SIGKILLs at once — and every job still converges. Kill schedules are
    asserted non-empty so the pass can't be vacuous."""

    def test_all_three_tiers_at_once(self):
        import logging

        from training_operator_tpu.cluster.chaos import WireChaos
        from training_operator_tpu.cluster.httpapi import (
            ApiHTTPServer,
            ApiServerError,
            ApiUnavailableError,
            RemoteAPIServer,
            RemoteRuntime,
        )

        # The storm makes the manager log a traceback per failed reconcile
        # (~8% of thousands); pytest's log capture formatting those eats
        # the real-clock deadline. The errors are the EXPECTED chaos, not
        # diagnostics — silence the logger for the storm's duration.
        mgr_log = logging.getLogger("training_operator_tpu.controllers.manager")
        prev_disabled = mgr_log.disabled
        mgr_log.disabled = True

        host = Cluster()  # real clock: the wire tier needs real HTTP
        host.add_nodes(make_cpu_pool(4, cpu_per_node=8.0))
        DefaultScheduler(host)
        kubelet = SimKubelet(host, heartbeat_interval=0.2)
        NodeLifecycleController(host, grace_period=0.8, toleration_seconds=0.3)
        wire = WireChaos(seed=9, error_rate=0.08, reset_rate=0.03)
        server = ApiHTTPServer(host.api, port=0, chaos=wire)
        try:
            remote = RemoteAPIServer(server.url, timeout=10.0)
            runtime = RemoteRuntime(remote, tick_interval=0.0)
            for _ in range(50):
                try:
                    mgr = OperatorManager(runtime, resync_period=2.0)
                    mgr.register(JAXController(runtime.api))
                    break
                except (ApiUnavailableError, ApiServerError):
                    continue
            else:
                raise AssertionError("operator never booted through the storm")

            monkey = ChaosMonkey(host, kubelet, seed=9, interval=0.6, budget=3)
            nodes = NodeChaos(host, kubelet, seed=9, interval=1.0, budget=1,
                              recover_after=2.0)
            jobs = []
            for i in range(4):
                tmpl = PodTemplateSpec(
                    containers=[Container(name="jax", resources={"cpu": 1.0})],
                    annotations={ANNOTATION_SIM_DURATION: "1.0"},
                )
                jobs.append(JAXJob(
                    metadata=ObjectMeta(name=f"matrix-{i}"),
                    replica_specs={"Worker": ReplicaSpec(
                        replicas=2, template=tmpl,
                        restart_policy=RestartPolicy.EXIT_CODE,
                    )},
                ))
            for job in jobs:
                for _ in range(200):
                    try:
                        remote.create(job)
                        break
                    except (ApiUnavailableError, ApiServerError):
                        continue
                else:
                    raise AssertionError("create never got through the storm")

            def all_done():
                return all(
                    (j := host.api.try_get("JAXJob", "default", f"matrix-{i}"))
                    is not None and capi.is_succeeded(j.status)
                    for i in range(4)
                )

            deadline = host.clock.now() + 120.0
            while host.clock.now() < deadline and not (
                all_done() and nodes.kills and monkey.kills
            ):
                host.step()
                try:
                    runtime.step()
                except (ApiUnavailableError, ApiServerError):
                    pass
            assert all_done(), {
                f"matrix-{i}": getattr(
                    host.api.try_get("JAXJob", "default", f"matrix-{i}"),
                    "status", None,
                )
                for i in range(4)
            }
            # No vacuous pass: every tier actually struck.
            assert nodes.kills, "NodeChaos never killed a node"
            assert monkey.kills, "ChaosMonkey never killed a pod"
            assert sum(wire.injected.values()) > 0, wire.injected
            mgr.stop()
        finally:
            mgr_log.disabled = prev_disabled
            server.close()
