"""Wire boundary tests: codec round-trips, HTTP CRUD/watch/logs over a real
localhost socket, and a remote operator driving jobs through the full engine.

Parity target: the reference's every layer crosses real process boundaries —
SDK REST (training_client.py:41), operator watch streams, webhook admission
(cmd/training-operator.v1/main.go:134-166). These tests prove the substrate's
HTTP front-end preserves the in-process APIServer's semantics (conflicts,
admission, watch asynchrony) across a socket.
"""

import threading

import pytest

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import (
    Container,
    JobCondition,
    JobConditionType,
    PodTemplateSpec,
    ReplicaSpec,
    RestartPolicy,
    RunPolicy,
    SchedulingPolicy,
)
from training_operator_tpu.api.jobs import (
    ElasticPolicy,
    JAXJob,
    MPIJob,
    ObjectMeta,
    PyTorchJob,
    RDZVBackend,
    TFJob,
    TPUPolicy,
)
from training_operator_tpu.cluster import wire
from training_operator_tpu.cluster.apiserver import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
)
from training_operator_tpu.cluster.httpapi import (
    ApiHTTPServer,
    RemoteAPIServer,
    RemoteRuntime,
)
from training_operator_tpu.cluster.objects import (
    AcceleratorInfo,
    ContainerStatus,
    Event,
    Lease,
    Node,
    Pod,
    PodGroup,
    PodGroupPhase,
    PodPhase,
    PodStatus,
)
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    Cluster,
    DefaultScheduler,
    SimKubelet,
)
from training_operator_tpu.controllers import OperatorManager
from training_operator_tpu.controllers.jax import JAXController
from training_operator_tpu.runtime.api import (
    MLPolicy,
    ReplicatedJobTemplate,
    RuntimeRef,
    Trainer,
    TrainingRuntimeSpec,
    TrainJob,
    ClusterTrainingRuntime,
    DatasetConfig,
)
from training_operator_tpu.sdk.client import TrainingClient


def _rich_pod() -> Pod:
    return Pod(
        metadata=ObjectMeta(
            name="w-0", namespace="ns1", uid="u1",
            labels={capi.JOB_NAME_LABEL: "j", capi.REPLICA_INDEX_LABEL: "0"},
            annotations={"a": "b"}, resource_version=7,
        ),
        spec=PodTemplateSpec(
            containers=[Container(name="jax", image="img", env={"X": "1"},
                                  resources={"cpu": 2.0})],
            tolerations=[{"key": "tpu", "operator": "Exists", "effect": "NoSchedule"}],
            volumes=[{"name": "v", "mountPath": "/etc/mpi", "configMap": {"name": "cm"}}],
            restart_policy=RestartPolicy.EXIT_CODE,
        ),
        status=PodStatus(
            phase=PodPhase.RUNNING,
            container_statuses=[ContainerStatus(name="jax", restart_count=2, running=True)],
            start_time=4.5,
        ),
        node_name="node-1",
    )


ROUND_TRIP_OBJECTS = [
    _rich_pod(),
    Node(
        metadata=ObjectMeta(name="n0"),
        capacity={"cpu": 8.0, "tpu.dev/chips": 4.0},
        accelerator=AcceleratorInfo(kind="tpu", chips=4, tpu_type="v5e",
                                    tpu_slice="slice-0", slice_topology="4x4",
                                    ici_coords=[0, 2]),
        taints=[{"key": "tpu", "effect": "NoSchedule"}],
    ),
    PodGroup(
        metadata=ObjectMeta(name="pg", namespace="d"),
        min_member=4, min_resources={"cpu": 8.0}, phase=PodGroupPhase.INQUEUE,
        placement={"p-0": "n0"}, topology_request="2x4", num_slices=2,
        reserved_nodes=["n1"],
    ),
    Lease(metadata=ObjectMeta(name="lease", namespace="sys"), holder="op-a",
          acquire_time=1.0, renew_time=2.0, transitions=3),
    JAXJob(
        metadata=ObjectMeta(name="jj", namespace="d"),
        replica_specs={"Worker": ReplicaSpec(
            replicas=2,
            template=PodTemplateSpec(containers=[Container(name="jax")]),
            restart_policy=RestartPolicy.ON_FAILURE,
        )},
        run_policy=RunPolicy(backoff_limit=3,
                             scheduling_policy=SchedulingPolicy(min_available=2,
                                                                topology="2x4")),
        tpu_policy=TPUPolicy(accelerator="v5e-8", topology="2x4", num_slices=2,
                             mesh_axes={"data": 2, "fsdp": 4}),
    ),
    PyTorchJob(
        metadata=ObjectMeta(name="pj"),
        replica_specs={"Master": ReplicaSpec(replicas=1)},
        elastic_policy=ElasticPolicy(min_replicas=1, max_replicas=4,
                                     rdzv_backend=RDZVBackend.C10D,
                                     metrics=[{"name": "util", "target": 0.8}]),
        nproc_per_node=4,
    ),
    TFJob(metadata=ObjectMeta(name="tj"), enable_dynamic_worker=True),
    MPIJob(metadata=ObjectMeta(name="mj"), slots_per_worker=2),
    TrainJob(
        metadata=ObjectMeta(name="tjob", namespace="d"),
        runtime_ref=RuntimeRef(name="tpu-jax-default", kind="ClusterTrainingRuntime"),
        trainer=Trainer(num_nodes=2, env={"A": "1"}),
        dataset_config=DatasetConfig(storage_uri="hf://ds"),
    ),
    ClusterTrainingRuntime(
        metadata=ObjectMeta(name="rt"),
        spec=TrainingRuntimeSpec(
            ml_policy=MLPolicy(num_nodes=2, tpu=TPUPolicy(topology="2x2")),
            template=[ReplicatedJobTemplate(
                name="trainer-node", replicas=2,
                template=PodTemplateSpec(containers=[Container(name="trainer")]),
            )],
        ),
    ),
    Event(object_kind="JAXJob", object_name="jj", namespace="d",
          reason="SuccessfulCreatePod", message="created pod w-0", timestamp=3.0),
]


class TestWireCodec:
    @pytest.mark.parametrize(
        "obj", ROUND_TRIP_OBJECTS, ids=lambda o: type(o).__name__
    )
    def test_round_trip(self, obj):
        encoded = wire.encode(obj)
        # must be pure JSON data
        import json

        json.dumps(encoded)
        decoded = wire.decode(encoded) if encoded.get("kind") else wire.decode(
            encoded, type(obj)
        )
        assert decoded == obj
        assert type(decoded) is type(obj)

    def test_job_with_conditions_round_trip(self):
        job = JAXJob(metadata=ObjectMeta(name="c"))
        capi.update_job_conditions(job.status, JobConditionType.RUNNING, True,
                                   "JobRunning", "running", now=5.0)
        out = wire.decode(wire.encode(job))
        assert out.status.conditions == job.status.conditions
        assert isinstance(out.status.conditions[0], JobCondition)
        assert out.status.conditions[0].type is JobConditionType.RUNNING

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError):
            wire.decode({"kind": "Nope"})


@pytest.fixture()
def served_cluster():
    cluster = Cluster()
    server = ApiHTTPServer(cluster.api, port=0)
    try:
        yield cluster, RemoteAPIServer(server.url, timeout=10.0)
    finally:
        server.close()


class TestHTTPApi:
    def test_crud_round_trip(self, served_cluster):
        cluster, remote = served_cluster
        pod = _rich_pod()
        pod.metadata.resource_version = 0
        remote.create(pod)
        assert pod.metadata.uid  # assigned server-side, reflected back
        got = remote.get("Pod", "ns1", "w-0")
        assert got.spec.containers[0].resources == {"cpu": 2.0}
        assert got.status.phase is PodPhase.RUNNING
        got.status.phase = PodPhase.SUCCEEDED
        remote.update(got)
        assert cluster.api.get("Pod", "ns1", "w-0").status.phase is PodPhase.SUCCEEDED
        assert remote.resource_version("Pod", "ns1", "w-0") == got.metadata.resource_version
        remote.delete("Pod", "ns1", "w-0")
        assert remote.try_get("Pod", "ns1", "w-0") is None
        assert remote.try_delete("Pod", "ns1", "w-0") is None

    def test_cluster_scoped_objects_round_trip(self, served_cluster):
        """Empty-namespace (cluster-scoped) objects must survive the URL
        path: Node, ClusterTrainingRuntime — get/update/delete, not just
        create (regression: empty path segments collapsed to 404s)."""
        _, remote = served_cluster
        rt = ClusterTrainingRuntime(
            metadata=ObjectMeta(name="preset", namespace=""),
            spec=TrainingRuntimeSpec(ml_policy=MLPolicy(num_nodes=2)),
        )
        remote.create(rt)
        got = remote.get("ClusterTrainingRuntime", "", "preset")
        assert got.spec.ml_policy.num_nodes == 2
        got.spec.ml_policy.num_nodes = 4
        remote.update(got)
        assert remote.try_get("ClusterTrainingRuntime", "", "preset").spec.ml_policy.num_nodes == 4
        assert remote.resource_version("ClusterTrainingRuntime", "", "preset") is not None
        node = Node(metadata=ObjectMeta(name="cn0", namespace=""), capacity={"cpu": 4.0})
        remote.create(node)
        assert remote.get("Node", "", "cn0").capacity == {"cpu": 4.0}
        remote.delete("ClusterTrainingRuntime", "", "preset")
        assert remote.try_get("ClusterTrainingRuntime", "", "preset") is None

    def test_create_returns_server_side_defaulted_object(self, served_cluster):
        """Remote create must hand back the SERVER's stored state (admission
        mutations included), not the caller's local copy."""
        cluster, remote = served_cluster
        from training_operator_tpu.api.defaults import default_job

        cluster.api.register_admission(
            "JAXJob", lambda j: default_job(j, now=cluster.clock.now())
        )
        job = JAXJob(
            metadata=ObjectMeta(name="defaulted"),
            replica_specs={"Worker": ReplicaSpec(
                replicas=None,  # defaulting fills this server-side
                template=PodTemplateSpec(containers=[Container(name="jax", image="t")]),
            )},
        )
        assert job.run_policy.clean_pod_policy is None
        out = remote.create(job)
        # Server-side defaulting (replicas, restart/clean-pod policies) must
        # be visible in the returned object even though the local copy never
        # saw it — otherwise a follow-up update would strip the defaults.
        assert out.replica_specs["Worker"].replicas == 1
        assert out.replica_specs["Worker"].restart_policy is not None
        assert out.run_policy.clean_pod_policy is not None
        assert job.metadata.uid == out.metadata.uid

    def test_error_mapping(self, served_cluster):
        cluster, remote = served_cluster
        with pytest.raises(NotFoundError):
            remote.get("Pod", "d", "missing")
        pod = _rich_pod()
        remote.create(pod)
        with pytest.raises(AlreadyExistsError):
            remote.create(_rich_pod())
        stale = remote.get("Pod", "ns1", "w-0")
        fresh = remote.get("Pod", "ns1", "w-0")
        remote.update(fresh)
        with pytest.raises(ConflictError):
            remote.update(stale)

    def test_label_selector_list(self, served_cluster):
        _, remote = served_cluster
        remote.create(_rich_pod())
        other = _rich_pod()
        other.metadata.name = "w-1"
        other.metadata.uid = ""
        other.metadata.labels = {capi.JOB_NAME_LABEL: "other"}
        remote.create(other)
        out = remote.list("Pod", "ns1", {capi.JOB_NAME_LABEL: "j"})
        assert [p.name for p in out] == ["w-0"]

    def test_watch_sessions(self, served_cluster):
        cluster, remote = served_cluster
        wq = remote.watch(kinds=["Pod"])
        assert wq.drain() == []
        remote.create(_rich_pod())
        cluster.api.create(Node(metadata=ObjectMeta(name="n9"), capacity={"cpu": 1}))
        events = wq.drain()
        assert [e.type for e in events] == ["Added"]  # Node filtered out
        assert events[0].obj.name == "w-0"
        # After the server forgets the session (explicit unwatch here; TTL
        # GC in production), drain() transparently re-subscribes presenting
        # its ResourceVersion watermark — the informer resume contract: the
        # server replays only events newer than the watermark. Everything
        # here was already observed, so the heal delivers NOTHING (the old
        # O(cluster) behavior re-announced w-0; never NotFoundError killing
        # the operator loop, never silently-lost events wedging the
        # expectations cache until its TTL).
        remote.unwatch(wq)
        assert wq.drain() == []  # delta resume: no redundant re-announcement
        remote.create(Node(metadata=ObjectMeta(name="n10"), capacity={"cpu": 1}))
        cluster.api.delete("Pod", "ns1", "w-0")
        # Explicit timeout = explicit fetch (bare drain() may defer to the
        # shared session's next block window). Events written AFTER the
        # heal flow normally — the resumed session is live.
        events = wq.drain(timeout=1.0)
        assert [e.type for e in events] == ["Deleted"]  # kinds filter survived

    def test_logs_and_events(self, served_cluster):
        cluster, remote = served_cluster
        remote.append_pod_log("d", "p0", "hello", ts=1.0)
        cluster.api.append_pod_log("d", "p0", "world", 2.0)
        lines, cursor = remote.read_pod_log("d", "p0")
        assert [ln.split(" ", 1)[1] for ln in lines] == ["hello", "world"]
        more, _ = remote.read_pod_log("d", "p0", since=cursor)
        assert more == []
        remote.record_event(Event(object_kind="Pod", object_name="p0",
                                  reason="Started", message="ok"))
        assert [e.reason for e in remote.events(object_name="p0")] == ["Started"]

    def test_admission_runs_server_side(self, served_cluster):
        cluster, remote = served_cluster
        from training_operator_tpu.api.defaults import default_job
        from training_operator_tpu.api.validation import validate_job

        def admit(job):
            default_job(job, now=cluster.clock.now())
            validate_job(job)

        cluster.api.register_admission("JAXJob", admit)
        bad = JAXJob(metadata=ObjectMeta(name="Bad_Name!"),
                     replica_specs={"Worker": ReplicaSpec(replicas=1)})
        with pytest.raises(ValueError):
            remote.create(bad)


class TestRemoteOperator:
    """A full OperatorManager running against RemoteAPIServer: the operator
    half of the process boundary, in-process for determinism (the
    three-OS-process version lives in test_e2e_ha.py)."""

    def _host(self):
        cluster = Cluster()
        from training_operator_tpu.cluster.inventory import make_cpu_pool

        cluster.add_nodes(make_cpu_pool(2, cpu_per_node=8.0))
        DefaultScheduler(cluster)
        SimKubelet(cluster)
        return cluster

    def test_remote_manager_converges_job(self):
        host = self._host()
        server = ApiHTTPServer(host.api, port=0)
        try:
            runtime = RemoteRuntime(RemoteAPIServer(server.url, timeout=10.0),
                                    tick_interval=0.0)
            mgr = OperatorManager(runtime, gang_enabled=False)
            mgr.register(JAXController(runtime.api))

            client = TrainingClient(server.url)
            tmpl = PodTemplateSpec(
                containers=[Container(name="jax", resources={"cpu": 1.0})],
                annotations={ANNOTATION_SIM_DURATION: "0"},
            )
            job = JAXJob(metadata=ObjectMeta(name="remote-j"),
                         replica_specs={"Worker": ReplicaSpec(replicas=2, template=tmpl)})
            client.create_job(job)

            deadline = host.clock.now() + 30.0

            def succeeded():
                j = host.api.try_get("JAXJob", "default", "remote-j")
                return j is not None and capi.is_succeeded(j.status)

            while host.clock.now() < deadline and not succeeded():
                host.step()
                runtime.step()
            assert succeeded(), host.api.try_get("JAXJob", "default", "remote-j").status
            pods = client.get_job_pods("remote-j")
            assert len(pods) == 2
            logs = client.get_job_logs("remote-j")
            assert len(logs) == 2 and all("Started container" in v for v in logs.values())
            mgr.stop()
        finally:
            server.close()


class TestRemoteV2:
    """The v2 TrainJob stack across the wire: a remote TrainJobManager (on
    RemoteRuntime) resolves the preset catalog it installed through the
    HTTP API, expands the TrainJob into a JAXJob, and the remote v1 manager
    converges it — the full client.train() -> preset -> workload -> status
    loop with every control-plane actor on the far side of a socket."""

    def test_remote_train_via_preset(self):
        from training_operator_tpu.cluster.inventory import make_cpu_pool
        from training_operator_tpu.runtime.api import ClusterTrainingRuntime
        from training_operator_tpu.runtime.controller import TrainJobManager

        host = Cluster()
        host.add_nodes(make_cpu_pool(2, cpu_per_node=16.0))
        DefaultScheduler(host)
        SimKubelet(host)
        server = ApiHTTPServer(host.api, port=0)
        try:
            runtime = RemoteRuntime(RemoteAPIServer(server.url, timeout=10.0),
                                    tick_interval=0.0)
            mgr = OperatorManager(runtime, gang_enabled=False)
            mgr.register(JAXController(runtime.api))
            TrainJobManager(runtime)

            # Presets were installed REMOTELY (cluster-scoped create over
            # the wire) by the v2 manager's startup.
            assert host.api.try_get(
                ClusterTrainingRuntime.KIND, "", "tpu-jax-default"
            ) is not None

            # Customize the preset over the wire: sim duration so pods end.
            client = TrainingClient(server.url)
            rt = client.api.get(ClusterTrainingRuntime.KIND, "", "tpu-jax-default")
            rt.spec.template[0].template.annotations[
                "sim.tpu.dev/run-seconds"
            ] = "0"
            rt.spec.template[0].template.containers[0].resources = {"cpu": 0.5}
            client.api.update(rt)

            client.train(name="wire-ft", dataset_uri="file:///tmp/nope")

            import time as _t

            deadline = _t.monotonic() + 40

            def finished():
                tj = host.api.try_get("TrainJob", "default", "wire-ft")
                return tj is not None and tj.is_finished()

            while _t.monotonic() < deadline and not finished():
                host.step()
                runtime.step()
            assert finished(), host.api.try_get("TrainJob", "default", "wire-ft")
            jj = host.api.get("JAXJob", "default", "wire-ft")
            assert jj.tpu_policy is not None  # preset's TPU policy applied
            assert jj.replica_specs["Worker"].template.init_containers, (
                "dataset initializer expected"
            )
            mgr.stop()
        finally:
            server.close()


class TestWireAuth:
    """Bearer-token gate on the wire API (the secure-serving analogue of
    the reference's cert-gated apiserver connection; probes stay open)."""

    def test_token_required_and_honored(self):
        cluster = Cluster()
        server = ApiHTTPServer(cluster.api, port=0, token="s3cret")
        try:
            anon = RemoteAPIServer(server.url, timeout=10.0)
            with pytest.raises(PermissionError):
                anon.list("Pod")
            wrong = RemoteAPIServer(server.url, timeout=10.0, token="nope")
            with pytest.raises(PermissionError):
                wrong.create(_rich_pod())
            authed = RemoteAPIServer(server.url, timeout=10.0, token="s3cret")
            authed.create(_rich_pod())
            assert [p.name for p in authed.list("Pod")] == ["w-0"]
            # probes stay open without auth (kubelet-style)
            import json as _json
            import urllib.request as _rq

            with _rq.urlopen(f"{server.url}/healthz", timeout=5) as r:
                assert _json.loads(r.read())["ok"] is True
            # the SDK passes the token through
            client = TrainingClient(server.url, api_token="s3cret")
            assert client.api.try_get("Pod", "ns1", "w-0") is not None
        finally:
            server.close()


class TestCodecFuzz:
    """Randomized round-trip: the codec must be lossless for arbitrary
    populated model objects, not just the hand-picked fixtures above."""

    def test_randomized_jobs_round_trip(self):
        import random

        rng = random.Random(1234)
        kinds = [JAXJob, PyTorchJob, TFJob, MPIJob]
        for i in range(50):
            cls = rng.choice(kinds)
            job = cls(
                metadata=ObjectMeta(
                    name=f"f{i}", namespace=rng.choice(["default", "ns2", ""]),
                    labels={f"k{j}": f"v{j}" for j in range(rng.randint(0, 3))},
                    annotations={"n": str(rng.random())},
                    resource_version=rng.randint(0, 9),
                ),
                replica_specs={
                    rng.choice(["Worker", "Master"]): ReplicaSpec(
                        replicas=rng.choice([None, 1, 4]),
                        template=PodTemplateSpec(
                            containers=[Container(
                                name="c", image="i",
                                command=["run"] * rng.randint(0, 2),
                                env={"A": "1"} if rng.random() < 0.5 else {},
                                resources={"cpu": rng.choice([0.5, 2.0])},
                            )],
                            tolerations=[{"key": "t", "operator": "Exists"}]
                            if rng.random() < 0.3 else [],
                            restart_policy=rng.choice(list(RestartPolicy) + [None]),
                        ),
                    )
                },
                run_policy=RunPolicy(
                    backoff_limit=rng.choice([None, 0, 3]),
                    ttl_seconds_after_finished=rng.choice([None, 60]),
                    suspend=rng.random() < 0.2,
                ),
                tpu_policy=TPUPolicy(
                    topology=rng.choice([None, "2x4"]),
                    num_slices=rng.randint(1, 3),
                    mesh_axes={"data": 2} if rng.random() < 0.5 else {},
                ) if rng.random() < 0.5 else None,
            )
            capi.update_job_conditions(
                job.status, rng.choice(list(JobConditionType)), True, "R", "m",
                now=float(i),
            )
            out = wire.decode(wire.encode(job))
            assert out == job and type(out) is cls, (cls, i)


class TestCachedReadAPI:
    """The operator-side lister cache (client-go listers analogue)."""

    def _stack(self):
        cluster = Cluster()
        server = ApiHTTPServer(cluster.api, port=0)
        remote = RemoteAPIServer(server.url, timeout=5.0)
        from training_operator_tpu.cluster.httpapi import CachedReadAPI

        return cluster, server, remote, CachedReadAPI(remote)

    def test_lists_served_from_mirror_after_priming(self):
        cluster, server, remote, cached = self._stack()
        try:
            cluster.api.create(_rich_pod())
            assert [p.name for p in cached.list("Pod")] == ["w-0"]
            # Mirror returns copies: mutating a listed object must not
            # corrupt later reads (the APIServer copy-on-read contract).
            listed = cached.list("Pod")[0]
            listed.metadata.labels["mutated"] = "yes"
            assert "mutated" not in cached.list("Pod")[0].metadata.labels
        finally:
            server.close()

    def test_mirror_tracks_watch_events(self):
        cluster, server, remote, cached = self._stack()
        try:
            # The cache PIGGYBACKS on whatever consumer pumps the shared
            # session (in production: the manager tick). Model that with a
            # plain subscriber whose drains distribute to the cache too.
            pump = remote.watch()
            assert cached.list("Pod") == []  # primes
            cluster.api.create(_rich_pod())
            pump.drain(timeout=1.0)
            assert [p.name for p in cached.list("Pod")] == ["w-0"]
            cluster.api.delete("Pod", "ns1", "w-0")
            pump.drain(timeout=1.0)
            assert cached.list("Pod") == []
        finally:
            server.close()

    def test_relist_reset_expires_ghosts(self):
        """Objects deleted while the watch session was LOST must not live
        in the mirror forever: the post-reconnect relist resets it to the
        full current state (their Deleted events are gone for good)."""
        cluster, server, remote, cached = self._stack()
        try:
            pump = remote.watch()
            cluster.api.create(_rich_pod())
            assert [p.name for p in cached.list("Pod")] == ["w-0"]
            # Session dies server-side; the pod dies while it is down.
            server._reap_all_sessions()
            cluster.api.delete("Pod", "ns1", "w-0")
            # The next pump hits resubscribe -> relist; the cache's queue
            # receives RELIST_RESET + the (pod-less) full state.
            pump.drain(timeout=1.0)
            assert cached.list("Pod") == [], "ghost pod survived the relist"
        finally:
            server.close()

    def test_writes_delegate(self):
        cluster, server, remote, cached = self._stack()
        try:
            pod = _rich_pod()
            cached.create(pod)
            assert cluster.api.try_get("Pod", "ns1", "w-0") is not None
            got = cached.get("Pod", "ns1", "w-0")  # direct, not cached
            assert got.metadata.resource_version >= 1
        finally:
            server.close()

    def test_overflow_reprimes_instead_of_ghosting(self):
        """A consumer that stops draining (a STANDBY operator never lists)
        must not accumulate events unboundedly; on overflow the mirror is
        rebuilt from authoritative lists — correct, not just bounded."""
        cluster, server, remote, cached = self._stack()
        try:
            pump = remote.watch()
            cached._q.overflow_limit = 8  # tiny, to trip it in-test
            assert cached.list("Pod") == []  # primes
            # A burst far past the limit while the cache never drains.
            for i in range(40):
                cluster.api.create(
                    Pod(metadata=ObjectMeta(name=f"b-{i}", namespace="d"),
                        spec=PodTemplateSpec(containers=[Container(name="c")]))
                )
            pump.drain(timeout=1.0)  # distributes; cache queue overflows
            assert len(cached._q._local) <= 8
            # Deleting one while the history is already gone must not ghost.
            cluster.api.delete("Pod", "d", "b-0")
            pump.drain(timeout=1.0)
            names = {p.metadata.name for p in cached.list("Pod")}
            assert len(names) == 39 and "b-0" not in names
        finally:
            server.close()
