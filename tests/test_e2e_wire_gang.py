"""Gang scheduling across the wire deployment: the flagship path end to end.

Every other wire e2e runs `--gang-scheduler-name none`; this one exercises
what the framework is FOR — a TPU gang (TPUPolicy topology) submitted over
verified HTTPS to a host whose tpu-packer places it on contiguous ICI
sub-meshes — with the operator as a separate OS process creating pods and
PodGroups through the HTTP API. Parity target: the reference's gang path
(volcano/scheduler-plugins PodGroups) driven through a real apiserver
boundary, which its e2e suite exercises via kind clusters.
"""

import os
import time

import pytest

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import Container, PodTemplateSpec, ReplicaSpec
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta, TPUPolicy
from training_operator_tpu.cluster.httpapi import RemoteAPIServer
from training_operator_tpu.cluster.inventory import TPU_RESOURCE
from training_operator_tpu.cluster.runtime import ANNOTATION_SIM_DURATION
from training_operator_tpu.sdk.client import TrainingClient
from training_operator_tpu.utils.procio import read_announcement

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(args):
    from training_operator_tpu.utils.procio import spawn_module_process

    # conftest scrubbed any site-injected accelerator plugin from
    # PYTHONPATH, so the host's solver jit-compiles on clean CPU.
    return spawn_module_process(args, REPO_ROOT,
                                env_extra={"JAX_PLATFORMS": "cpu"})


def _tpu_job(name: str, topology: str, workers: int, run_seconds: float) -> JAXJob:
    chips = 1
    for d in topology.split("x"):
        chips *= int(d)
    return JAXJob(
        metadata=ObjectMeta(name=name),
        replica_specs={
            "Worker": ReplicaSpec(
                replicas=workers,
                template=PodTemplateSpec(
                    containers=[Container(
                        name="jax", image="trainer",
                        resources={"cpu": 1.0, TPU_RESOURCE: 4.0},
                    )],
                    annotations={ANNOTATION_SIM_DURATION: str(run_seconds)},
                ),
            )
        },
        tpu_policy=TPUPolicy(
            accelerator=f"v5e-{chips}", topology=topology, num_slices=1
        ),
    )


def test_tpu_gang_placed_and_converged_over_the_wire(tmp_path):
    inv = tmp_path / "cluster.json"
    inv.write_text(
        '{"tpu_pools": [{"slices": 2, "topology": "4x4",'
        ' "chips_per_host": 4, "tpu_type": "v5e"}]}'
    )
    host = _spawn([
        "--role", "host", "--serve-port", "0",
        "--gang-scheduler-name", "tpu-packer", "--cluster", str(inv),
    ])
    procs = [host]
    try:
        # Generous: host boot includes the solver prewarm jit compile.
        url = read_announcement(host, "WIRE_API", timeout=120.0,
                                error=AssertionError)
        ca = read_announcement(host, "WIRE_CA", timeout=30.0,
                               error=AssertionError)
        op = _spawn([
            "--role", "operator", "--api-server", url, "--ca-cert", ca,
            "--enable-scheme", "jax", "--gang-scheduler-name", "tpu-packer",
        ])
        procs.append(op)
        read_announcement(op, "OPERATOR_UP", timeout=60.0, error=AssertionError)

        client = TrainingClient(url, ca_file=ca)
        api = RemoteAPIServer(url, timeout=10.0, ca_file=ca)

        # A sub-slice gang (2x4 = 2 hosts) and a whole-slice gang (4x4 = 4
        # hosts) — the packer must place both, ICI-contiguously. Run long
        # enough to inspect placement WHILE RUNNING: PodGroups are
        # garbage-collected with their finished jobs.
        client.create_job(_tpu_job("gang-sub", "2x4", workers=2, run_seconds=8.0))
        client.create_job(_tpu_job("gang-full", "4x4", workers=4, run_seconds=8.0))

        for name in ("gang-sub", "gang-full"):
            client.wait_for_job_conditions(
                name, expected_conditions=(capi.JobConditionType.RUNNING,),
                timeout=150,
            )

        # The gangs actually went through PodGroups + packer placement:
        groups = api.list("PodGroup")
        by_name = {g.metadata.name: g for g in groups}
        assert set(by_name) == {"gang-sub", "gang-full"}, by_name
        for g in groups:
            assert g.placement, f"{g.metadata.name} was not packer-placed"

        # Placement is topology-faithful: each gang's pods landed on TPU
        # hosts of ONE slice (ICI contiguity is a single-slice property),
        # and the two gangs share no host.
        nodes = {n.metadata.name: n for n in api.list("Node")}
        used = []
        for name, workers in (("gang-sub", 2), ("gang-full", 4)):
            pods = client.get_job_pods(name)
            assert len(pods) == workers
            assert all(p.node_name for p in pods)
            slices = {nodes[p.node_name].accelerator.tpu_slice for p in pods}
            assert len(slices) == 1, (name, slices)
            used.extend(p.node_name for p in pods)
        assert len(used) == len(set(used))

        # Then both converge.
        for name in ("gang-sub", "gang-full"):
            job = client.wait_for_job_conditions(
                name, expected_conditions=(capi.JobConditionType.SUCCEEDED,),
                timeout=120,
            )
            assert capi.is_succeeded(job.status), (name, job.status)
    finally:
        from training_operator_tpu.utils.procio import kill_all

        kill_all(procs)
