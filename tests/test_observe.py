"""Observability surfaces (PR 4): bucketed histogram exposition, the
timeline span tracer + ring bounds, lifecycle Events from the controller
path, the describe renderer on a completed preset job, the wire
/timelines and /metrics.txt routes, and the Chrome-trace exporter."""

from __future__ import annotations

import math

import pytest

from training_operator_tpu import observe
from training_operator_tpu.cluster.inventory import TPU_RESOURCE, make_tpu_pool
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
)
from training_operator_tpu.controllers import OperatorManager, register_all
from training_operator_tpu.observe.timeline import TimelineStore
from training_operator_tpu.runtime.api import ClusterTrainingRuntime
from training_operator_tpu.runtime.controller import TrainJobManager
from training_operator_tpu.scheduler import GangScheduler, TPUPacker
from training_operator_tpu.sdk import TrainingClient
from training_operator_tpu.utils.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)


# ---------------------------------------------------------------------------
# Bucketed histograms + registry guards (satellite 1 & 2)
# ---------------------------------------------------------------------------


class TestBucketedHistogram:
    def test_cumulative_buckets_and_minmax(self):
        h = Histogram("t_seconds", "help", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        cum = dict(
            (("+Inf" if b == math.inf else b), c) for b, c in h.cumulative_buckets()
        )
        assert cum == {0.1: 1, 1.0: 3, 10.0: 4, "+Inf": 5}
        assert h.count == 5
        assert h.min == 0.05 and h.max == 50.0
        assert h.sum == pytest.approx(56.05)

    def test_boundary_value_counts_le(self):
        # Prometheus buckets are `le` (less-or-equal): an observation ON the
        # bound lands in that bucket.
        h = Histogram("b_seconds", "", buckets=(1.0, 2.0))
        h.observe(1.0)
        cum = dict(h.cumulative_buckets())
        assert cum[1.0] == 1

    def test_render_text_and_json_snapshot_agree(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", "latency", buckets=(0.5, 5.0))
        c = reg.counter("ops_total", "ops", ("kind",))
        h.observe(0.25)
        h.observe(2.5)
        c.inc("JAXJob")
        snap = reg.snapshot()
        rendered = {}
        for line in reg.render().splitlines():
            if not line or line.startswith("#"):
                continue
            key, _, val = line.rpartition(" ")
            rendered[key] = float(val)
        assert rendered == {k: float(v) for k, v in snap.items()}
        # The exposition carries real le-labeled buckets plus the envelope.
        assert snap['lat_seconds_bucket{le="0.5"}'] == 1.0
        assert snap['lat_seconds_bucket{le="+Inf"}'] == 2.0
        assert snap["lat_seconds_min"] == 0.25
        assert snap["lat_seconds_max"] == 2.5
        assert snap["lat_seconds_count"] == 2.0
        assert 'ops_total{kind="JAXJob"}' in snap

    def test_empty_histogram_renders_zero_envelope(self):
        h = Histogram("e_seconds", "", buckets=(1.0,))
        items = h.snapshot_items()
        assert items["e_seconds_min"] == 0.0
        assert items["e_seconds_max"] == 0.0
        assert items['e_seconds_bucket{le="+Inf"}'] == 0.0


class TestRegistryGuards:
    def test_same_registration_is_memoized(self):
        reg = MetricsRegistry()
        a = reg.counter("x_total", "h", ("ns",))
        b = reg.counter("x_total", "h", ("ns",))
        assert a is b

    def test_type_change_raises(self):
        reg = MetricsRegistry()
        reg.counter("x_total", "h", ())
        with pytest.raises(ValueError, match="already registered as Counter"):
            reg.gauge("x_total", "h", ())
        with pytest.raises(ValueError, match="already registered as Counter"):
            reg.histogram("x_total", "h")

    def test_gauge_is_not_a_counter(self):
        reg = MetricsRegistry()
        reg.gauge("g", "h", ())
        with pytest.raises(ValueError, match="Gauge"):
            reg.counter("g", "h", ())

    def test_label_change_raises(self):
        reg = MetricsRegistry()
        reg.counter("y_total", "h", ("a", "b"))
        with pytest.raises(ValueError, match="labels"):
            reg.counter("y_total", "h", ("a",))

    def test_bucket_change_raises(self):
        reg = MetricsRegistry()
        reg.histogram("z_seconds", "h", buckets=(1.0, 2.0))
        with pytest.raises(ValueError, match="buckets"):
            reg.histogram("z_seconds", "h", buckets=(1.0, 3.0))

    def test_counter_value_and_total_locked_reads(self):
        c = Counter("v_total", "h", ("k",))
        c.inc("a", amount=2.0)
        c.inc("b")
        assert c.value("a") == 2.0
        assert c.value("missing") == 0.0
        assert c.total() == 3.0
        g = Gauge("g", "h", ())
        g.set(value=7.0)
        assert g.value() == 7.0


# ---------------------------------------------------------------------------
# Timeline tracer: ordering, ring bounds, toggle
# ---------------------------------------------------------------------------


class TestTimelineStore:
    def test_span_ordering_is_by_start(self):
        ts = TimelineStore(now_fn=lambda: 0.0)
        ts.record_span("ns", "j", "u1", "late", start=5.0, end=6.0)
        ts.record_span("ns", "j", "u1", "early", start=1.0, end=2.0)
        tl = ts.timeline("ns", "j")
        assert [s.name for s in tl.sorted_spans()] == ["early", "late"]
        d = tl.to_dict()
        assert [s["name"] for s in d["spans"]] == ["early", "late"]
        assert d["uids"] == ["u1"]

    def test_per_job_span_ring_is_bounded(self):
        ts = TimelineStore(now_fn=lambda: 0.0, max_spans=4)
        for i in range(10):
            ts.record_span("ns", "j", "", f"s{i}", start=float(i), end=float(i))
        tl = ts.timeline("ns", "j")
        assert len(tl.spans) == 4
        assert [s.name for s in tl.sorted_spans()] == ["s6", "s7", "s8", "s9"]

    def test_job_lru_ring_is_bounded(self):
        ts = TimelineStore(now_fn=lambda: 0.0, max_jobs=2)
        for name in ("a", "b", "c"):
            ts.record_span("ns", name, "", "x", start=0.0, end=0.0)
        assert ts.timeline("ns", "a") is None  # oldest evicted
        assert ts.timeline("ns", "b") is not None
        assert ts.timeline("ns", "c") is not None
        # Touching "b" makes "c" the eviction candidate.
        ts.record_span("ns", "b", "", "x2", start=1.0, end=1.0)
        ts.record_span("ns", "d", "", "x", start=2.0, end=2.0)
        assert ts.timeline("ns", "c") is None
        assert ts.timeline("ns", "b") is not None

    def test_marks_are_first_wins(self):
        ts = TimelineStore(now_fn=lambda: 0.0)
        ts.mark("ns", "j", "", "created", t=1.0)
        ts.mark("ns", "j", "", "created", t=9.0)
        assert ts.timeline("ns", "j").marks == {"created": 1.0}

    def test_global_toggle_disables_recording(self):
        ts = TimelineStore(now_fn=lambda: 0.0)
        observe.set_enabled(False)
        try:
            ts.record_span("ns", "j", "", "x", start=0.0, end=1.0)
            ts.mark("ns", "j", "", "m", t=0.0)
            assert ts.timeline("ns", "j") is None
        finally:
            observe.set_enabled(True)

    def test_wall_duration_wins_over_instant_interval(self):
        ts = TimelineStore(now_fn=lambda: 0.0)
        ts.record_span("ns", "j", "", "solve", start=3.0, end=3.0, wall=0.25)
        span = ts.timeline("ns", "j").sorted_spans()[0]
        assert span.duration() == 0.25

    def test_uid_history_is_capped(self):
        # A name resubmitted forever must not grow uids unboundedly; the
        # first incarnation stays, recent ones are kept.
        ts = TimelineStore(now_fn=lambda: 0.0)
        for i in range(50):
            ts.record_span("ns", "nightly", f"uid-{i}", "x", start=0.0, end=0.0)
        uids = ts.timeline("ns", "nightly").uids
        assert len(uids) <= TimelineStore.MAX_UIDS
        assert uids[0] == "uid-0" and uids[-1] == "uid-49"

    def test_hostile_attr_keys_ride_the_attrs_dict(self):
        # Wire ingest passes client-chosen attr keys; ones that collide
        # with the record_span signature must not blow up the call.
        ts = TimelineStore(now_fn=lambda: 0.0)
        ts.record_span("ns", "j", "", "x", start=1.0, end=2.0,
                       attrs={"start": 99.0, "name": "evil", "wall": 7.0})
        span = ts.timeline("ns", "j").sorted_spans()[0]
        assert span.start == 1.0 and span.name == "x" and span.wall == 0.0
        assert span.attrs["start"] == 99.0 and span.attrs["name"] == "evil"


class TestWorkqueueWaitStamps:
    def test_stamps_do_not_outlive_queue_membership(self):
        from training_operator_tpu.engine.workqueue import RateLimitingQueue

        q = RateLimitingQueue(now_fn=lambda: 1.0)
        for i in range(10):
            q.add(f"k{i}")
        q.drain()
        assert not q._enqueued_at  # settled at pop
        # A consumer that never reads waits (v2 manager) stays bounded:
        # the next drain clears the unread waits.
        q.add("k0")
        q.drain()
        assert list(q._pop_waits) == ["k0"]
        assert q.waited("k0") >= 0.0
        assert not q._pop_waits

    def test_waited_reports_enqueue_to_pop(self):
        from training_operator_tpu.engine.workqueue import RateLimitingQueue

        clock = [0.0]
        q = RateLimitingQueue(now_fn=lambda: clock[0])
        q.add("a")
        clock[0] = 2.5
        assert q.get() == "a"
        assert q.waited("a") == 2.5
        assert q.waited("a") == 0.0  # consumed


# ---------------------------------------------------------------------------
# The full path: preset TrainJob -> completion -> describe / wire / export
# ---------------------------------------------------------------------------


def preset_env(start_latency: float = 0.5):
    """Gang-scheduled TPU cluster + v1/v2 managers + SDK, with the
    tpu-jax-default preset customized the way an operator would (sim
    duration so pods complete, chip resources, nonzero kubelet start
    latency so time-to-running is a real interval)."""
    cluster = Cluster(VirtualClock())
    cluster.add_nodes(make_tpu_pool(1, slice_topology="2x4", chips_per_host=4))
    DefaultScheduler(cluster)
    SimKubelet(cluster, start_latency=start_latency)
    GangScheduler(cluster, TPUPacker())
    mgr = OperatorManager(cluster, gang_enabled=True)
    register_all(mgr)
    TrainJobManager(cluster)
    client = TrainingClient(cluster)
    rt = cluster.api.get(ClusterTrainingRuntime.KIND, "", "tpu-jax-default")
    tmpl = rt.spec.template[0].template
    tmpl.annotations[ANNOTATION_SIM_DURATION] = "2"
    tmpl.containers[0].resources = {"cpu": 0.5, TPU_RESOURCE: 4.0}
    cluster.api.update(rt)
    return cluster, client


class TestDescribePresetJob:
    @pytest.fixture(scope="class")
    def completed(self):
        cluster, client = preset_env()
        client.train(name="demo")
        done = client.wait_for_trainjob("demo", timeout=120)
        assert done.is_finished()
        return cluster, client

    def test_timeline_has_all_phases_with_nonzero_durations(self, completed):
        cluster, client = completed
        tl = client.get_job_timeline("demo")
        assert tl is not None
        rows = {r["phase"]: r for r in observe.phase_table(tl)}
        for phase in ("admission", "queue_wait", "reconcile", "gang_solve",
                      "bind", "time_to_running", "total"):
            assert phase in rows, f"missing phase {phase}: {sorted(rows)}"
        # The acceptance trio must be REAL durations, not zeros.
        assert rows["queue_wait"]["total_s"] > 0.0
        assert rows["gang_solve"]["total_s"] > 0.0
        assert rows["time_to_running"]["total_s"] > 0.0

    def test_describe_renders_conditions_events_and_phase_table(self, completed):
        cluster, client = completed
        text = client.describe_job("demo")
        # Condition history (v2 TrainJob resolves first for the name).
        assert "Kind:         TrainJob" in text
        assert "Created" in text and "Complete" in text
        # The uniform lifecycle Event stream from the controller path.
        for reason in ("JobCreated", "JobRunning", "JobSucceeded",
                       "GangAdmitted", "JobsCreated"):
            assert reason in text, f"missing event {reason}:\n{text}"
        # The phase table with the acceptance trio present.
        for phase in ("queue_wait", "gang_solve", "time_to_running"):
            assert phase in text

    def test_time_to_running_metric_observed(self, completed):
        from training_operator_tpu.utils import metrics

        assert metrics.job_time_to_running_seconds.count > 0
        assert metrics.job_time_to_running_seconds.max > 0.0
        assert metrics.job_queue_wait_seconds.count > 0
        assert metrics.job_admission_seconds.count > 0

    def test_chrome_trace_round_trips_spans(self, completed, tmp_path):
        import json

        cluster, client = completed
        tl = client.get_job_timeline("demo")
        out = tmp_path / "trace.json"
        doc = observe.export_chrome_trace(tl, str(out))
        names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
        assert {s["name"] for s in tl["spans"]} == names
        # Every duration event carries microsecond ts/dur fields.
        for e in doc["traceEvents"]:
            if e["ph"] == "X":
                assert e["ts"] >= 0 and e["dur"] >= 0
        on_disk = json.loads(out.read_text())
        assert on_disk["traceEvents"] == doc["traceEvents"]
        # A store export covers every job the ring retains.
        full = observe.export_chrome_trace(cluster.api.timelines)
        procs = [e for e in full["traceEvents"] if e["ph"] == "M"]
        assert any(e["args"]["name"] == "default/demo" for e in procs)

    def test_describe_unknown_job_raises(self, completed):
        cluster, client = completed
        with pytest.raises(ValueError, match="no job"):
            client.describe_job("nope")


class TestTimeToRunningFirstRunOnly:
    def test_restart_retransition_does_not_reobserve(self):
        import copy

        import training_operator_tpu.api.common as capi
        from training_operator_tpu.api.common import (
            JobConditionType,
            update_job_conditions,
        )
        from training_operator_tpu.api.jobs import JAXJob, ObjectMeta
        from training_operator_tpu.cluster.apiserver import APIServer
        from training_operator_tpu.controllers.jax import JAXController
        from training_operator_tpu.engine import core
        from training_operator_tpu.engine.controller import JobController
        from training_operator_tpu.utils import metrics

        api = APIServer()
        jc = JobController(api, JAXController(api), now_fn=lambda: 10.0)
        job = JAXJob(metadata=ObjectMeta(name="r", namespace="default"))
        job.metadata.creation_time = 0.0
        prev = copy.deepcopy(job.status)
        update_job_conditions(
            job.status, JobConditionType.RUNNING, True, "JobRunning", "m", now=5.0
        )
        before = metrics.job_time_to_running_seconds.count
        jc._observe_transitions(job, prev)
        assert metrics.job_time_to_running_seconds.count == before + 1

        # Restart cycle: Restarting was set (clearing Running), then the
        # new pod runs — the re-transition must NOT re-observe.
        prev2 = copy.deepcopy(job.status)
        update_job_conditions(
            prev2, JobConditionType.RESTARTING, True, "JobRestarting", "m", now=20.0
        )
        job.metadata.annotations[core.RESTART_COUNT_ANNOTATION] = "1"
        update_job_conditions(
            job.status, JobConditionType.RUNNING, True, "JobRunning", "m", now=25.0
        )
        jc._observe_transitions(job, prev2)
        assert metrics.job_time_to_running_seconds.count == before + 1
        spans = [
            s for s in api.get_timeline("default", "r")["spans"]
            if s["name"] == "time_to_running"
        ]
        assert len(spans) == 1


class TestFailureEventStream:
    def test_failed_job_gets_failed_event_once(self):
        from training_operator_tpu.api.common import (
            Container,
            PodTemplateSpec,
            ReplicaSpec,
            RestartPolicy,
        )
        from training_operator_tpu.api.jobs import JAXJob, ObjectMeta
        from training_operator_tpu.cluster.inventory import make_cpu_pool
        from training_operator_tpu.cluster.runtime import (
            ANNOTATION_SIM_EXIT_CODE,
        )

        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_cpu_pool(4))
        DefaultScheduler(cluster)
        SimKubelet(cluster)
        mgr = OperatorManager(cluster)
        register_all(mgr)
        client = TrainingClient(cluster)
        t = PodTemplateSpec(
            containers=[Container(name="jax", image="img", resources={"cpu": 0.5})]
        )
        t.annotations[ANNOTATION_SIM_DURATION] = "1"
        t.annotations[ANNOTATION_SIM_EXIT_CODE] = "3"
        job = JAXJob(
            metadata=ObjectMeta(name="boom"),
            replica_specs={"Worker": ReplicaSpec(
                replicas=1, template=t, restart_policy=RestartPolicy.NEVER,
            )},
        )
        client.create_job(job)
        with pytest.raises(RuntimeError):
            client.wait_for_job_conditions("boom", timeout=60)
        cluster.run_for(1.0)  # let the terminal pass settle
        evs = cluster.api.events(object_name="boom", reason="JobFailed")
        # count==1 too: aggregation would fold duplicate emissions into one
        # record, so the length alone no longer pins "emitted once".
        assert len(evs) == 1 and evs[0].count == 1, evs
        assert evs[0].event_type == "Warning"
        created = cluster.api.events(object_name="boom", reason="JobCreated")
        assert len(created) == 1 and created[0].count == 1
        # Terminal span landed with the failure outcome.
        tl = cluster.api.get_timeline("default", "boom")
        totals = [s for s in tl["spans"] if s["name"] == "total"]
        assert totals and totals[0]["attrs"]["outcome"] == "Failed"


# ---------------------------------------------------------------------------
# Wire surfaces: /timelines round-trip, /metrics.txt, remote span push
# ---------------------------------------------------------------------------


class TestWireObservability:
    @pytest.fixture()
    def served(self):
        from training_operator_tpu.cluster.httpapi import (
            ApiHTTPServer,
            RemoteAPIServer,
        )

        cluster, client = preset_env()
        server = ApiHTTPServer(cluster.api, port=0)
        remote = RemoteAPIServer(server.url, timeout=10.0)
        try:
            yield cluster, client, server, remote
        finally:
            server.close()

    def test_timeline_round_trips_over_the_wire(self, served):
        cluster, client, server, remote = served
        client.train(name="wired")
        assert client.wait_for_trainjob("wired", timeout=120).is_finished()
        local = cluster.api.get_timeline("default", "wired")
        over_wire = remote.get_timeline("default", "wired")
        assert over_wire is not None
        assert over_wire["spans"] == local["spans"]
        assert over_wire["marks"] == local["marks"]
        # And the exporter accepts the wire shape unchanged.
        doc = observe.export_chrome_trace(over_wire)
        assert any(e["ph"] == "X" for e in doc["traceEvents"])

    def test_missing_timeline_is_none_over_the_wire(self, served):
        _, _, _, remote = served
        assert remote.get_timeline("default", "ghost") is None

    def test_metrics_text_exposition_served(self, served):
        _, _, _, remote = served
        text = remote.metrics_text()
        assert "# TYPE training_operator_reconcile_seconds histogram" in text
        assert 'training_job_queue_wait_seconds_bucket{le="' in text
        # Text and JSON views are the same registry, same numbers.
        snap = remote.metrics_snapshot()
        assert 'training_job_queue_wait_seconds_bucket{le="+Inf"}' in snap

    def test_remote_span_push_lands_in_host_ring(self, served):
        cluster, _, _, remote = served
        rec = remote.timelines
        rec.record_span("default", "pushed", "uid-1", "queue_wait",
                        start=1.0, end=1.0, wall=0.125, kind="JAXJob")
        rec.mark("default", "pushed", "", "created", t=1.0)
        rec.flush()
        tl = cluster.api.get_timeline("default", "pushed")
        assert tl is not None
        span = tl["spans"][0]
        assert span["name"] == "queue_wait" and span["wall"] == 0.125
        assert span["attrs"]["uid"] == "uid-1"
        assert tl["marks"] == {"created": 1.0}
