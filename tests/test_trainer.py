"""Trainer runtime tests: mesh building, ring attention numerics, sharded
training steps, and the graft entry points.

All multi-device paths run on the virtual 8-device CPU platform (the axon
TPU plugin ignores JAX_PLATFORMS, so tests select CPU devices explicitly).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from training_operator_tpu.trainer.attention import plain_attention, ring_attention
from training_operator_tpu.trainer.mesh import MeshSpec, batch_sharding, build_mesh
from training_operator_tpu.trainer.model import (
    TransformerConfig,
    forward,
    init_params,
    loss_fn,
)
from training_operator_tpu.trainer.train import (
    init_train_state,
    make_example_batch,
    make_optimizer,
    make_train_step,
)

_CPU = None


def cpu_devices():
    """jax.devices("cpu"), resolved lazily: calling it at module level would
    initialize the JAX backend during pytest COLLECTION — and when the axon
    TPU plugin's tunnel is unreachable, backend init blocks, hanging
    `pytest --collect-only` for minutes before a single test runs."""
    global _CPU
    if _CPU is None:
        _CPU = jax.devices("cpu")
    return _CPU


@pytest.fixture(autouse=True)
def _pin_cpu():
    """All trainer tests compute on the CPU platform: the axon TPU plugin
    hijacks the default backend, and mixing TPU-resident arrays into
    CPU-mesh shard_maps corrupts data (see attention.ring_attention)."""
    with jax.default_device(cpu_devices()[0]):
        yield


def cpu_mesh(**axes):
    return build_mesh(MeshSpec(axes), cpu_devices())


def tiny_config(**kw):
    defaults = dict(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=64, max_seq_len=64,
    )
    defaults.update(kw)
    return TransformerConfig(**defaults)


@pytest.fixture(scope="module")
def fsdp2_bundle():
    """Module-scoped compiled bundle for the default tiny_config on an
    fsdp=2 mesh: (config, mesh, optimizer, initial state, jitted step).

    Every `make_train_step` call returns a FRESH closure, so per-test
    construction re-jits the identical program once per test — the r5
    slow-tier finding. The checkpoint/data/convergence tests that all
    train this exact (config, mesh, batch-shape) share ONE compile here.
    The step DONATES its input state's buffers, so the bundle hands out a
    state FACTORY, not a shared state — a donated pytree is consumed by
    the first test that steps it."""
    with jax.default_device(cpu_devices()[0]):
        config = tiny_config()
        mesh = cpu_mesh(fsdp=2)
        optimizer = make_optimizer(learning_rate=1e-2, warmup_steps=1,
                                   total_steps=50)
        step = make_train_step(config, optimizer, mesh)

        def fresh_state(seed: int = 0):
            with jax.default_device(cpu_devices()[0]):
                return init_train_state(
                    config, optimizer, jax.random.PRNGKey(seed), mesh
                )

    return config, mesh, optimizer, fresh_state, step


class TestMesh:
    def test_spec_parsing(self):
        spec = MeshSpec.from_string("data=2, tensor=4")
        assert spec.axes == {"data": 2, "tensor": 4}
        assert spec.size() == 8

    def test_unknown_axis_rejected(self):
        with pytest.raises(ValueError):
            MeshSpec({"bogus": 2})

    def test_build(self):
        mesh = cpu_mesh(fsdp=2, tensor=2)
        assert mesh.shape["fsdp"] == 2 and mesh.shape["tensor"] == 2

    def test_default_factorization(self):
        assert MeshSpec.for_devices(8).size() <= 8
        assert MeshSpec.for_devices(1).size() == 1


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_plain_attention(self, causal):
        """Ring attention across 4 sequence shards must equal single-shard
        attention to float tolerance — the blockwise softmax is exact."""
        mesh = cpu_mesh(sequence=4)
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        shape = (2, 32, 4, 8)  # B, S, H, D
        q = jax.random.normal(kq, shape, jnp.float32)
        k = jax.random.normal(kk, shape, jnp.float32)
        v = jax.random.normal(kv, shape, jnp.float32)
        expected = plain_attention(q, k, v, causal=causal)
        with jax.default_device(cpu_devices()[0]):
            got = ring_attention(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)

    def test_ring_with_tensor_and_batch_axes(self):
        mesh = cpu_mesh(data=2, sequence=2, tensor=2)
        key = jax.random.PRNGKey(1)
        q = jax.random.normal(key, (4, 16, 4, 8), jnp.float32)
        expected = plain_attention(q, q, q, causal=True)
        got = ring_attention(q, q, q, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


class TestModel:
    def test_forward_shapes_and_loss(self):
        config = tiny_config()
        params = init_params(config, jax.random.PRNGKey(0))
        batch = make_example_batch(config, 2, 16, jax.random.PRNGKey(1))
        logits = forward(params, batch["tokens"], config)
        assert logits.shape == (2, 16, config.vocab_size)
        assert logits.dtype == jnp.float32
        loss = loss_fn(params, batch, config)
        # Random init: loss ~= ln(vocab).
        assert abs(float(loss) - np.log(config.vocab_size)) < 1.0

    def test_causality(self):
        """Changing a future token must not change earlier logits."""
        config = tiny_config(remat=False)
        params = init_params(config, jax.random.PRNGKey(0))
        tokens = jnp.zeros((1, 16), jnp.int32)
        logits_a = forward(params, tokens, config)
        tokens_b = tokens.at[0, 10].set(7)
        logits_b = forward(params, tokens_b, config)
        np.testing.assert_allclose(
            np.asarray(logits_a[0, :10]), np.asarray(logits_b[0, :10]), atol=1e-5
        )
        assert not np.allclose(np.asarray(logits_a[0, 10:]), np.asarray(logits_b[0, 10:]))

    def test_gqa(self):
        config = tiny_config(n_heads=4, n_kv_heads=2)
        params = init_params(config, jax.random.PRNGKey(0))
        batch = make_example_batch(config, 1, 8, jax.random.PRNGKey(1))
        assert jnp.isfinite(loss_fn(params, batch, config))


class TestShardedTraining:
    def _run_steps(self, mesh, config, n=3, seq=32):
        optimizer = make_optimizer(warmup_steps=1, total_steps=100)
        state = init_train_state(config, optimizer, jax.random.PRNGKey(0), mesh)
        step = make_train_step(config, optimizer, mesh)
        losses = []
        for i in range(n):
            batch = make_example_batch(config, 4, seq, jax.random.PRNGKey(i))
            if mesh is not None:
                batch = jax.device_put(batch, batch_sharding(mesh))
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        return losses

    @pytest.mark.slow
    def test_fsdp_tensor_mesh_step(self):
        mesh = cpu_mesh(fsdp=2, tensor=2)
        losses = self._run_steps(mesh, tiny_config())
        assert all(np.isfinite(l) for l in losses)

    @pytest.mark.slow
    def test_full_4axis_mesh_matches_single_device(self):
        """The same seed must produce the same loss trajectory on a
        dp x fsdp x sp x tp mesh as on one device — sharding must not change
        the math."""
        config = tiny_config(remat=False)
        single = self._run_steps(None, config)
        mesh = cpu_mesh(data=2, fsdp=1, sequence=2, tensor=2)
        sharded = self._run_steps(mesh, config)
        np.testing.assert_allclose(single, sharded, rtol=2e-3)

    @pytest.mark.slow
    def test_pipeline_matches_single_device(self):
        """GPipe schedule over a pipeline=2 mesh: same seed, same loss
        trajectory as one device — the rotating-buffer schedule must not
        change the math (VERDICT r1 #10 done-criterion)."""
        config = tiny_config(n_layers=4, remat=False, pipeline_microbatches=4)
        single = self._run_steps(None, config)
        mesh = cpu_mesh(pipeline=2, data=2)
        piped = self._run_steps(mesh, config)
        np.testing.assert_allclose(single, piped, rtol=2e-3)

    @pytest.mark.slow
    def test_pipeline_with_tensor_and_fsdp(self):
        """pipeline composes with tensor + fsdp sharding in one program."""
        config = tiny_config(n_layers=4, pipeline_microbatches=2)
        mesh = cpu_mesh(pipeline=2, fsdp=2, tensor=2)
        losses = self._run_steps(mesh, config)
        assert all(np.isfinite(l) for l in losses)

    @pytest.mark.slow
    def test_moe_expert_parallel_matches_flat(self):
        """Switch-MoE with experts sharded over the expert axis: trajectory
        matches the unsharded run (dispatch/combine all-to-alls are pure
        data movement)."""
        config = tiny_config(n_experts=4, remat=False)
        single = self._run_steps(None, config)
        mesh = cpu_mesh(expert=2, data=2)
        sharded = self._run_steps(mesh, config)
        np.testing.assert_allclose(single, sharded, rtol=2e-2)

    @pytest.mark.slow
    def test_moe_loss_decreases_and_balances(self):
        """MoE training converges on a fixed batch and the router spreads
        load: by the end every expert receives a nonzero token share."""
        import jax.numpy as jnp

        from training_operator_tpu.trainer.model import forward_with_aux

        config = tiny_config(n_experts=4, d_ff=32)
        mesh = cpu_mesh(expert=2, fsdp=2)
        optimizer = make_optimizer(learning_rate=1e-2, warmup_steps=1, total_steps=50)
        state = init_train_state(config, optimizer, jax.random.PRNGKey(0), mesh)
        step = make_train_step(config, optimizer, mesh)
        batch = make_example_batch(config, 4, 32, jax.random.PRNGKey(0))
        batch = jax.device_put(batch, batch_sharding(mesh))
        first = last = None
        for _ in range(10):
            state, metrics = step(state, batch)
            if first is None:
                first = float(metrics["loss"])
            last = float(metrics["loss"])
        assert last < first - 0.5, (first, last)
        # Aux (load-balance) loss near its uniform-routing minimum of 1.0,
        # and no expert starved: every expert gets a nonzero token share.
        _, aux = jax.jit(
            lambda p, t: forward_with_aux(p, t, config, mesh)
        )(state.params, batch["tokens"])
        assert float(aux["router_balance"]) < 1.6
        tokens = batch["tokens"]
        router = state.params["layers"]["router"][0]  # first layer [D, E]
        embeds = state.params["embed"][tokens.reshape(-1)]  # rough probe
        choice = jnp.argmax(embeds.astype(jnp.float32) @ router.astype(jnp.float32), -1)
        shares = jnp.bincount(choice, length=config.n_experts) / choice.shape[0]
        assert float(shares.min()) > 0.0, shares

    @pytest.mark.slow
    def test_pipeline_moe_tensor_together(self):
        """PP + EP + TP in one jitted program on an 8-device mesh."""
        config = tiny_config(n_layers=4, n_experts=2, pipeline_microbatches=2)
        mesh = cpu_mesh(pipeline=2, expert=2, tensor=2)
        losses = self._run_steps(mesh, config)
        assert all(np.isfinite(l) for l in losses)

    @pytest.mark.slow
    @pytest.mark.parametrize(
        "policy", ["mlp_only", "save_attn", "save_attn_qkv", "save_dots"]
    )
    def test_remat_policy_matches_full(self, policy):
        """Selective remat changes only what is stored vs recomputed — loss
        and gradients must match full remat to accumulation-order noise."""
        import dataclasses

        config = tiny_config()
        params = init_params(config, jax.random.PRNGKey(0))
        batch = make_example_batch(config, 2, 32, jax.random.PRNGKey(1))
        ref_l, ref_g = jax.value_and_grad(
            lambda p: loss_fn(p, batch, config, None)
        )(params)
        cfg = dataclasses.replace(config, remat_policy=policy)
        got_l, got_g = jax.value_and_grad(
            lambda p: loss_fn(p, batch, cfg, None)
        )(params)
        assert abs(float(got_l) - float(ref_l)) < 1e-6
        for a, b in zip(jax.tree.leaves(ref_g), jax.tree.leaves(got_g)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-3, rtol=1e-3
            )

    @pytest.mark.slow
    def test_save_attn_elides_flash_backward_rerun(self):
        """The core mechanism of the save_attn* policies: the (out, lse)
        names inside flash.py:_fwd mark the custom_vjp residuals saveable,
        so the backward jaxpr drops the forward-kernel re-run (4 -> 3
        pallas_calls) while gradients stay equal. Uses the interpreted
        pallas path (head_dim 64) so the real custom_vjp wiring is traced
        on CPU."""
        import dataclasses

        from training_operator_tpu.trainer.model import loss_fn as lf

        base = TransformerConfig(
            vocab_size=128, d_model=128, n_layers=2, n_heads=2, n_kv_heads=2,
            d_ff=128, max_seq_len=64, attn_impl="flash",
        )
        params = init_params(base, jax.random.PRNGKey(0))
        batch = make_example_batch(base, 2, 64, jax.random.PRNGKey(1))
        counts, grads = {}, {}
        for pol in ("full", "save_attn"):
            cfg = dataclasses.replace(base, remat_policy=pol)
            grad_fn = jax.grad(lambda p: lf(p, batch, cfg, None))
            counts[pol] = str(jax.make_jaxpr(grad_fn)(params)).count("pallas_call")
            grads[pol] = grad_fn(params)
        assert counts["full"] == 4 and counts["save_attn"] == 3, counts
        for a, b in zip(jax.tree.leaves(grads["full"]), jax.tree.leaves(grads["save_attn"])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-3, rtol=1e-3)

    def test_unknown_remat_policy_rejected(self):
        import dataclasses

        cfg = dataclasses.replace(tiny_config(), remat_policy="save_atn")
        with pytest.raises(ValueError, match="remat_policy"):
            init_params(cfg, jax.random.PRNGKey(0))

    @pytest.mark.slow
    def test_remat_policy_in_pipeline(self):
        """Selective remat composes with the GPipe schedule."""
        import dataclasses

        config = tiny_config(
            n_layers=4, pipeline_microbatches=4, remat_policy="save_attn"
        )
        ref = tiny_config(n_layers=4, pipeline_microbatches=4)
        mesh = cpu_mesh(pipeline=2)
        params = init_params(config, jax.random.PRNGKey(0))
        batch = make_example_batch(config, 4, 32, jax.random.PRNGKey(1))
        with mesh:
            got = float(loss_fn(params, batch, config, mesh))
            want = float(loss_fn(params, batch, ref, mesh))
        assert abs(got - want) < 1e-5

    def test_loss_decreases_on_fixed_batch(self, fsdp2_bundle):
        config, mesh, _optimizer, fresh_state, step = fsdp2_bundle
        state = fresh_state()
        batch = make_example_batch(config, 4, 32, jax.random.PRNGKey(0))
        batch = jax.device_put(batch, batch_sharding(mesh))
        first = last = None
        for _ in range(10):
            state, metrics = step(state, batch)
            if first is None:
                first = float(metrics["loss"])
            last = float(metrics["loss"])
        assert last < first - 0.5, (first, last)


class TestGraftEntry:
    def test_entry(self):
        import __graft_entry__ as g

        fn, args = g.entry()
        loss = float(jax.jit(fn)(*args))
        assert np.isfinite(loss)

    def test_dryrun_multichip(self):
        import __graft_entry__ as g

        g.dryrun_multichip(8)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_interpret_matches_reference(self, causal):
        from training_operator_tpu.trainer.flash import flash_attention

        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        shape = (2, 256, 4, 64)
        q = jax.random.normal(kq, shape, jnp.float32)
        k = jax.random.normal(kk, shape, jnp.float32)
        v = jax.random.normal(kv, shape, jnp.float32)
        exp = plain_attention(q, k, v, causal=causal)
        got = flash_attention(q, k, v, causal, 128, 128, True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp), atol=2e-5)

    @pytest.mark.slow
    def test_gradients_match_reference(self):
        """The PALLAS backward kernels (dq + dk/dv) against AD of the XLA
        reference — distinct q/k/v so each gradient path is checked."""
        from training_operator_tpu.trainer.flash import flash_attention

        kq, kk, kv = jax.random.split(jax.random.PRNGKey(1), 3)
        q = jax.random.normal(kq, (1, 128, 2, 64), jnp.float32)
        k = jax.random.normal(kk, (1, 128, 2, 64), jnp.float32)
        v = jax.random.normal(kv, (1, 128, 2, 64), jnp.float32)
        gf = jax.grad(
            lambda a, b_, c: (flash_attention(a, b_, c, True, 128, 128, True) ** 2).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        gr = jax.grad(
            lambda a, b_, c: (plain_attention(a, b_, c, causal=True) ** 2).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        for got, exp, name in zip(gf, gr, ("dq", "dk", "dv")):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(exp), atol=2e-4, err_msg=name
            )

    @pytest.mark.slow
    @pytest.mark.parametrize("seq", [100, 200])
    def test_odd_seq_len_padded(self, seq):
        """Sequence lengths that don't tile by 128: the kernel pads + masks
        instead of silently falling back — forward AND gradients exact."""
        from training_operator_tpu.trainer.flash import flash_attention

        kq, kk, kv = jax.random.split(jax.random.PRNGKey(2), 3)
        shape = (2, seq, 2, 64)
        q = jax.random.normal(kq, shape, jnp.float32)
        k = jax.random.normal(kk, shape, jnp.float32)
        v = jax.random.normal(kv, shape, jnp.float32)
        exp = plain_attention(q, k, v, causal=True)
        got = flash_attention(q, k, v, True, 128, 128, True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp), atol=2e-5)
        gf = jax.grad(lambda a: (flash_attention(a, k, v, True, 128, 128, True) ** 2).sum())(q)
        gr = jax.grad(lambda a: (plain_attention(a, k, v, causal=True) ** 2).sum())(q)
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), atol=2e-4)

    def test_gqa_through_dispatcher(self):
        """GQA kv shapes route through flash (expanded at the dispatcher),
        matching the model's repeat + plain attention."""
        from training_operator_tpu.trainer.attention import attention

        kq, kk, kv = jax.random.split(jax.random.PRNGKey(3), 3)
        q = jax.random.normal(kq, (2, 128, 4, 64), jnp.float32)
        k = jax.random.normal(kk, (2, 128, 2, 64), jnp.float32)
        v = jax.random.normal(kv, (2, 128, 2, 64), jnp.float32)
        kr = jnp.repeat(k, 2, axis=2)
        vr = jnp.repeat(v, 2, axis=2)
        exp = plain_attention(q, kr, vr, causal=True)
        got = attention(q, k, v, mesh=None, causal=True, impl="flash")
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp), atol=2e-5)


class TestCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path, fsdp2_bundle):
        from training_operator_tpu.trainer.checkpoint import Checkpointer

        config, mesh, optimizer, fresh_state, step = fsdp2_bundle
        state = fresh_state()
        batch = make_example_batch(config, 4, 32, jax.random.PRNGKey(0))
        batch = jax.device_put(batch, batch_sharding(mesh))
        for _ in range(3):
            state, _ = step(state, batch)
        ckpt = Checkpointer(str(tmp_path / "ckpt"))
        assert ckpt.save(state)
        assert ckpt.latest_step() == 3
        template = init_train_state(config, optimizer, jax.random.PRNGKey(7), mesh)
        restored = ckpt.restore(template)
        ckpt.close()
        assert int(restored.step) == 3
        np.testing.assert_allclose(
            np.asarray(restored.params["embed"]), np.asarray(state.params["embed"]), atol=0
        )

    def test_overwrite_same_step_is_crash_safe(self, tmp_path, fsdp2_bundle):
        """Overwriting a step (the forced final save landing on the interval
        save's step) must keep the old copy durable until the new one is
        written — and leave no stale directory behind."""
        import os

        from training_operator_tpu.trainer.checkpoint import Checkpointer

        config, mesh, optimizer, fresh_state, _step = fsdp2_bundle
        state = fresh_state()
        ckpt = Checkpointer(str(tmp_path / "ckpt"), max_to_keep=1)
        assert ckpt.save(state, force=True)
        # Leftover stale dir from a hypothetical interrupted overwrite is
        # swept, and the overwrite itself succeeds.
        stale = str(tmp_path / "ckpt") + ".stale.0"
        os.makedirs(stale, exist_ok=True)
        assert ckpt.save(state, force=True)
        assert not os.path.isdir(stale)
        assert ckpt.latest_step() == 0
        template = init_train_state(config, optimizer, jax.random.PRNGKey(7), mesh)
        restored = ckpt.restore(template)
        ckpt.close()
        np.testing.assert_allclose(
            np.asarray(restored.params["embed"]), np.asarray(state.params["embed"]), atol=0
        )
        # Preemption between move-aside and replacement save: the step dir
        # is gone and only the stale copy remains. A fresh Checkpointer must
        # recover it so auto-resume still finds the newest checkpoint.
        os.rename(str(tmp_path / "ckpt" / "0"), stale)
        ckpt2 = Checkpointer(str(tmp_path / "ckpt"), max_to_keep=1)
        assert not os.path.isdir(stale)
        assert ckpt2.latest_step() == 0
        restored2 = ckpt2.restore(template)
        ckpt2.close()
        np.testing.assert_allclose(
            np.asarray(restored2.params["embed"]), np.asarray(state.params["embed"]), atol=0
        )
        # A DECLINED overwrite (unforced off-interval save onto an existing
        # step) must put the moved-aside copy back, not delete it.
        ckpt3 = Checkpointer(str(tmp_path / "c3"), save_interval_steps=5)
        ckpt3.save(state, step=10, force=True)
        ckpt3.save(state, step=12, force=True)
        assert ckpt3.save(state, step=10, force=False) is False
        assert sorted(ckpt3.manager.all_steps()) == [10, 12]
        assert not os.path.isdir(str(tmp_path / "c3") + ".stale.10")
        ckpt3.close()

    @pytest.mark.slow
    def test_elastic_remesh_restore(self, tmp_path):
        """Resize story: train on a 4-way mesh, restore onto a 2-way mesh;
        the restored state must continue training bit-compatibly."""
        from training_operator_tpu.trainer.checkpoint import Checkpointer, restore_into_mesh

        config = tiny_config(remat=False)
        optimizer = make_optimizer(warmup_steps=1, total_steps=50)
        mesh4 = cpu_mesh(fsdp=2, tensor=2)
        state = init_train_state(config, optimizer, jax.random.PRNGKey(0), mesh4)
        step4 = make_train_step(config, optimizer, mesh4)
        batch = make_example_batch(config, 4, 32, jax.random.PRNGKey(0))
        state, _ = step4(state, jax.device_put(batch, batch_sharding(mesh4)))
        Checkpointer(str(tmp_path / "c")).save(state)

        mesh2 = cpu_mesh(fsdp=2)
        restored = restore_into_mesh(str(tmp_path / "c"), config, optimizer, mesh2)
        assert int(restored.step) == 1
        # One more step on each mesh gives identical losses.
        step2 = make_train_step(config, optimizer, mesh2)
        b2 = make_example_batch(config, 4, 32, jax.random.PRNGKey(9))
        _, m4 = step4(state, jax.device_put(b2, batch_sharding(mesh4)))
        _, m2 = step2(restored, jax.device_put(b2, batch_sharding(mesh2)))
        # Different meshes reduce in different orders; small float drift.
        np.testing.assert_allclose(float(m4["loss"]), float(m2["loss"]), rtol=1e-3)


class TestData:
    def test_process_sharding_disjoint(self):
        from training_operator_tpu.trainer.data import TokenDataset

        rows = np.arange(40).reshape(10, 4)
        shards = [TokenDataset(rows, pid, 2).rows for pid in range(2)]
        assert len(shards[0]) + len(shards[1]) == 10
        assert not set(map(tuple, shards[0])) & set(map(tuple, shards[1]))

    def test_loader_batches_feed_train_step(self, fsdp2_bundle):
        from training_operator_tpu.trainer.data import DataLoader, TokenDataset

        config, mesh, _optimizer, fresh_state, step = fsdp2_bundle
        state = fresh_state()
        ds = TokenDataset.synthetic(config.vocab_size, seq_len=32, num_rows=16)
        loader = DataLoader(ds, batch_size=4, mesh=mesh)
        n = 0
        for batch in loader:
            state, metrics = step(state, batch)
            n += 1
        assert n == 4
        assert np.isfinite(float(metrics["loss"]))

    def test_pack_tokens(self):
        from training_operator_tpu.trainer.data import pack_tokens

        rows = pack_tokens(np.arange(100), seq_len=9)
        assert rows.shape == (10, 10)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_plain_attention(self, causal):
        """All-to-all sequence parallelism is exact: sequence-sharded inputs,
        full-sequence math."""
        from training_operator_tpu.trainer.attention import ulysses_attention

        mesh = cpu_mesh(sequence=2, tensor=2)
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        shape = (2, 64, 4, 16)
        q = jax.random.normal(kq, shape, jnp.float32)
        k = jax.random.normal(kk, shape, jnp.float32)
        v = jax.random.normal(kv, shape, jnp.float32)
        exp = plain_attention(q, k, v, causal=causal)
        got = ulysses_attention(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp), atol=2e-5)

    def test_training_with_ulysses_matches_ring(self):
        """Same seed: a sequence-sharded training run converges identically
        whether the sequence axis uses ring or Ulysses attention."""
        config_ring = tiny_config(remat=False, attn_impl="ring")
        config_uly = tiny_config(remat=False, attn_impl="ulysses")
        mesh = cpu_mesh(sequence=2, fsdp=2)

        def run(config):
            optimizer = make_optimizer(warmup_steps=1, total_steps=100)
            state = init_train_state(config, optimizer, jax.random.PRNGKey(0), mesh)
            step = make_train_step(config, optimizer, mesh)
            losses = []
            for i in range(3):
                batch = make_example_batch(config, 4, 32, jax.random.PRNGKey(i))
                batch = jax.device_put(batch, batch_sharding(mesh))
                state, metrics = step(state, batch)
                losses.append(float(metrics["loss"]))
            return losses

        np.testing.assert_allclose(run(config_ring), run(config_uly), rtol=2e-3)

    def test_indivisible_heads_rejected(self):
        from training_operator_tpu.trainer.attention import ulysses_attention

        mesh = cpu_mesh(sequence=2, tensor=2)
        q = jnp.zeros((1, 32, 2, 16))  # 2 heads % (2*2) != 0
        with pytest.raises(ValueError):
            ulysses_attention(q, q, q, mesh)


class TestPrefetch:
    def test_order_and_count_preserved(self):
        from training_operator_tpu.trainer.data import DataLoader, TokenDataset, prefetch

        ds = TokenDataset.synthetic(64, 16, 24)
        loader = DataLoader(ds, batch_size=4, shuffle=False)
        plain = [b["tokens"] for b in loader.epoch(0)]
        fetched = [b["tokens"] for b in prefetch(loader.epoch(0), size=3)]
        assert len(plain) == len(fetched) == 6
        for a, b in zip(plain, fetched):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_short_iterator_and_size_one(self):
        from training_operator_tpu.trainer.data import prefetch

        assert list(prefetch(iter([]), size=4)) == []
        assert list(prefetch(iter([1, 2]), size=8)) == [1, 2]
        assert list(prefetch(iter([1, 2, 3]), size=1)) == [1, 2, 3]


class TestVisionFamily:
    """The conv/vision model family (reference's MNIST-class examples as a
    first-class trainer payload, trainer/vision.py)."""

    def _setup(self, mesh=None):
        import optax

        from training_operator_tpu.trainer.vision import (
            VisionConfig,
            init_vision_params,
            make_vision_train_step,
            synthetic_mnist,
            vision_param_shardings,
        )

        config = VisionConfig(image_size=16, channels=(8, 16), dense=32)
        params = init_vision_params(config, jax.random.PRNGKey(0))
        opt = optax.sgd(0.1, momentum=0.9)
        if mesh is not None:
            params = jax.device_put(params, vision_param_shardings(config, mesh))
        opt_state = opt.init(params)
        step = make_vision_train_step(config, opt, mesh)
        batch = synthetic_mnist(jax.random.PRNGKey(1), 64, config)
        return config, params, opt_state, step, batch

    @pytest.mark.slow
    def test_learns_synthetic_digits(self):
        _, params, opt_state, step, batch = self._setup()
        acc = None
        for _ in range(40):
            params, opt_state, m = step(params, opt_state, batch)
            acc = float(m["accuracy"])
        assert acc > 0.9, acc
        assert np.isfinite(float(m["loss"]))

    def test_data_parallel_matches_single_device(self):
        from training_operator_tpu.trainer.vision import vision_loss_fn

        config, params, opt_state, step, batch = self._setup()
        want = float(vision_loss_fn(params, batch, config, None))
        mesh = cpu_mesh(data=2, fsdp=2)
        config2, params2, opt_state2, step2, _ = self._setup(mesh)
        got = float(vision_loss_fn(params2, batch, config2, mesh))
        assert abs(got - want) < 1e-2, (got, want)
        params2, opt_state2, m = step2(params2, opt_state2, batch)
        assert np.isfinite(float(m["loss"]))


class TestRematNames:
    """The save_attn* remat policies depend on the 'attn_out' checkpoint
    name being bound on EVERY attention backend — the flash custom_vjp
    names it internally, and the dispatch names the ring/Ulysses/XLA
    outputs (advisor r3: under GPipe the stage body pins attn_impl='xla',
    which previously had no name, silently degrading save_attn to full
    remat). Guard: the name survives into the jaxpr."""

    def test_attn_out_named_on_xla_path(self):
        import jax
        import jax.numpy as jnp

        from training_operator_tpu.trainer.attention import attention

        q = jnp.zeros((1, 8, 2, 16))
        jaxpr = str(jax.make_jaxpr(lambda a, b, c: attention(a, b, c, impl="xla"))(q, q, q))
        assert "attn_out" in jaxpr

    def test_attn_out_named_on_ring_and_ulysses(self):
        import jax
        import jax.numpy as jnp

        from training_operator_tpu.trainer.attention import attention
        from training_operator_tpu.trainer.mesh import MeshSpec, build_mesh

        mesh = build_mesh(MeshSpec({"sequence": 2}))
        q = jnp.zeros((1, 8, 2, 16))
        for impl in ("ring", "ulysses"):
            jaxpr = str(jax.make_jaxpr(
                lambda a, b, c: attention(a, b, c, mesh=mesh, impl=impl)
            )(q, q, q))
            assert "attn_out" in jaxpr, impl


class TestTrainerE2EBench:
    @pytest.mark.slow
    def test_e2e_loop_runs_with_checkpoints_on_cpu(self, tmp_path):
        """The trainer_e2e bench block's loop (dataio -> jitted step ->
        periodic orbax save) on the CPU smoke path: completes, checkpoints
        fire, accounting fields are sane."""
        from training_operator_tpu.trainer.bench import bench_trainer_e2e

        out = bench_trainer_e2e(steps=6, ckpt_every=3)
        assert out["steps"] == 6
        assert out["ckpt_saves"] == 2
        assert out["tokens_per_s_wall"] > 0
        assert 0.0 <= out["data_pct"] <= 100.0
        assert 0.0 <= out["ckpt_pct"] <= 100.0
        # The loss is finite — the loop actually trained.
        assert out["final_loss"] == out["final_loss"]  # not NaN
