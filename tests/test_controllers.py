"""Per-kind controller tests: env-injection contracts + status semantics.

Parity model: reference pod_test.go (cluster-spec env assertions),
tfjob_controller_test.go (success policy), pytorchjob_controller_test.go
(elastic/HPA), mpijob_controller_test.go (hostfile/launcher gating).
"""

import json

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import (
    Container,
    JobConditionType,
    PodTemplateSpec,
    ReplicaSpec,
)
from training_operator_tpu.api.jobs import (
    ElasticPolicy,
    MPIJob,
    ObjectMeta,
    PaddleJob,
    PyTorchJob,
    RDZVBackend,
    SuccessPolicy,
    TFJob,
    XGBoostJob,
)
from training_operator_tpu.cluster.inventory import make_cpu_pool
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
    mark_pod_finished,
)
from training_operator_tpu.controllers import OperatorManager, register_all


def make_env(kubelet=True):
    cluster = Cluster(VirtualClock())
    cluster.add_nodes(make_cpu_pool(8))
    DefaultScheduler(cluster)
    if kubelet:
        SimKubelet(cluster)
    mgr = OperatorManager(cluster)
    register_all(mgr)
    return cluster, mgr


def tmpl(cname, image="img", cpu=0.5, **annotations):
    t = PodTemplateSpec(containers=[Container(name=cname, image=image, resources={"cpu": cpu})])
    t.annotations.update(annotations)
    return t


def pods_of(cluster, name, rtype=None):
    sel = {capi.JOB_NAME_LABEL: name}
    if rtype:
        sel[capi.REPLICA_TYPE_LABEL] = rtype
    return sorted(cluster.api.list("Pod", "default", sel), key=lambda p: p.name)


class TestPyTorch:
    def test_master_worker_env(self):
        cluster, mgr = make_env()
        job = PyTorchJob(
            metadata=ObjectMeta(name="pt"),
            replica_specs={
                "Master": ReplicaSpec(replicas=1, template=tmpl("pytorch")),
                "Worker": ReplicaSpec(replicas=2, template=tmpl("pytorch")),
            },
        )
        mgr.submit(job)
        assert cluster.run_until(lambda: len(pods_of(cluster, "pt")) == 3, timeout=30)
        master = pods_of(cluster, "pt", "Master")[0]
        env = master.spec.containers[0].env
        assert env["MASTER_ADDR"] == "pt-master-0"
        assert env["MASTER_PORT"] == "23456"
        assert env["WORLD_SIZE"] == "3"
        assert env["RANK"] == "0"
        assert env["PET_NNODES"] == "3"
        workers = pods_of(cluster, "pt", "Worker")
        for i, w in enumerate(workers):
            assert w.spec.containers[0].env["RANK"] == str(i + 1)  # master offset
            assert w.spec.containers[0].env["PET_NODE_RANK"] == str(i + 1)
            # workers wait on the master service
            assert w.spec.init_containers[0].name == "pytorch-init"
            assert "pt-master-0" in " ".join(w.spec.init_containers[0].command)
        assert not master.spec.init_containers

    def test_elastic_env_and_hpa(self):
        cluster, mgr = make_env()
        job = PyTorchJob(
            metadata=ObjectMeta(name="el"),
            replica_specs={"Worker": ReplicaSpec(replicas=2, template=tmpl("pytorch"))},
            elastic_policy=ElasticPolicy(
                min_replicas=1, max_replicas=4, rdzv_backend=RDZVBackend.C10D, max_restarts=3
            ),
        )
        mgr.submit(job)
        assert cluster.run_until(lambda: len(pods_of(cluster, "el")) == 2, timeout=30)
        env = pods_of(cluster, "el")[0].spec.containers[0].env
        assert env["PET_RDZV_ENDPOINT"] == "el-worker-0:23456"
        assert env["PET_RDZV_BACKEND"] == "c10d"
        assert env["PET_NNODES"] == "1:4"
        assert env["PET_MAX_RESTARTS"] == "3"
        assert "MASTER_ADDR" not in env  # no master spec
        hpa = cluster.api.try_get("HorizontalPodAutoscaler", "default", "el")
        assert hpa is not None and hpa.min_replicas == 1 and hpa.max_replicas == 4

    def test_nproc_per_node_world_size(self):
        cluster, mgr = make_env()
        job = PyTorchJob(
            metadata=ObjectMeta(name="np"),
            replica_specs={
                "Master": ReplicaSpec(replicas=1, template=tmpl("pytorch")),
                "Worker": ReplicaSpec(replicas=1, template=tmpl("pytorch")),
            },
            nproc_per_node=4,
        )
        mgr.submit(job)
        assert cluster.run_until(lambda: len(pods_of(cluster, "np")) == 2, timeout=30)
        env = pods_of(cluster, "np", "Master")[0].spec.containers[0].env
        assert env["WORLD_SIZE"] == "8"  # 2 replicas x 4 procs
        assert env["PET_NPROC_PER_NODE"] == "4"


class TestTensorFlow:
    def job(self, name="tf", dynamic=False, policy=SuccessPolicy.DEFAULT, chief=True):
        specs = {
            "Worker": ReplicaSpec(replicas=2, template=tmpl("tensorflow")),
            "PS": ReplicaSpec(replicas=1, template=tmpl("tensorflow")),
        }
        if chief:
            specs["Chief"] = ReplicaSpec(replicas=1, template=tmpl("tensorflow"))
        return TFJob(
            metadata=ObjectMeta(name=name),
            replica_specs=specs,
            success_policy=policy,
            enable_dynamic_worker=dynamic,
        )

    def test_tf_config(self):
        cluster, mgr = make_env()
        mgr.submit(self.job())
        assert cluster.run_until(lambda: len(pods_of(cluster, "tf")) == 4, timeout=30)
        w1 = pods_of(cluster, "tf", "Worker")[1]
        cfg = json.loads(w1.spec.containers[0].env["TF_CONFIG"])
        assert cfg["task"] == {"type": "worker", "index": 1}
        assert cfg["environment"] == "cloud"
        assert cfg["cluster"]["worker"] == [
            "tf-worker-0.default.svc:2222",
            "tf-worker-1.default.svc:2222",
        ]
        assert cfg["cluster"]["ps"] == ["tf-ps-0.default.svc:2222"]
        assert cfg["cluster"]["chief"] == ["tf-chief-0.default.svc:2222"]

    def test_sparse_tf_config_dynamic_worker(self):
        cluster, mgr = make_env()
        mgr.submit(self.job(name="tfd", dynamic=True, chief=False))
        assert cluster.run_until(lambda: len(pods_of(cluster, "tfd")) == 3, timeout=30)
        w1 = pods_of(cluster, "tfd", "Worker")[1]
        cfg = json.loads(w1.spec.containers[0].env["TF_CONFIG"])
        assert cfg["cluster"]["worker"] == {"1": "tfd-worker-1.default.svc:2222"}
        assert cfg["cluster"]["ps"] == ["tfd-ps-0.default.svc:2222"]

    def test_chief_success_ends_job(self):
        cluster, mgr = make_env(kubelet=False)
        mgr.submit(self.job(name="tfc"))
        assert cluster.run_until(lambda: len(pods_of(cluster, "tfc")) == 4, timeout=30)
        chief = pods_of(cluster, "tfc", "Chief")[0]
        mark_pod_finished(cluster.api, chief, 0, cluster.clock.now())
        assert cluster.run_until(
            lambda: capi.is_succeeded(cluster.api.get("TFJob", "default", "tfc").status),
            timeout=30,
        )

    def test_all_workers_success_policy(self):
        cluster, mgr = make_env(kubelet=False)
        mgr.submit(self.job(name="tfa", policy=SuccessPolicy.ALL_WORKERS, chief=False))
        assert cluster.run_until(lambda: len(pods_of(cluster, "tfa")) == 3, timeout=30)
        workers = pods_of(cluster, "tfa", "Worker")
        mark_pod_finished(cluster.api, workers[0], 0, cluster.clock.now())
        cluster.run_for(1.0)
        assert not capi.is_succeeded(cluster.api.get("TFJob", "default", "tfa").status)
        mark_pod_finished(cluster.api, workers[1], 0, cluster.clock.now())
        assert cluster.run_until(
            lambda: capi.is_succeeded(cluster.api.get("TFJob", "default", "tfa").status),
            timeout=30,
        )

    def test_chiefless_worker0_success(self):
        cluster, mgr = make_env(kubelet=False)
        mgr.submit(self.job(name="tfw", chief=False))
        assert cluster.run_until(lambda: len(pods_of(cluster, "tfw")) == 3, timeout=30)
        w0 = pods_of(cluster, "tfw", "Worker")[0]
        mark_pod_finished(cluster.api, w0, 0, cluster.clock.now())
        assert cluster.run_until(
            lambda: capi.is_succeeded(cluster.api.get("TFJob", "default", "tfw").status),
            timeout=30,
        )


class TestXGBoost:
    def test_rabit_env(self):
        cluster, mgr = make_env()
        job = XGBoostJob(
            metadata=ObjectMeta(name="xgb"),
            replica_specs={
                "Master": ReplicaSpec(replicas=1, template=tmpl("xgboost")),
                "Worker": ReplicaSpec(replicas=2, template=tmpl("xgboost")),
            },
        )
        mgr.submit(job)
        assert cluster.run_until(lambda: len(pods_of(cluster, "xgb")) == 3, timeout=30)
        w0 = pods_of(cluster, "xgb", "Worker")[0]
        env = w0.spec.containers[0].env
        assert env["MASTER_ADDR"] == "xgb-master-0"
        assert env["MASTER_PORT"] == "9999"
        assert env["WORLD_SIZE"] == "3"
        assert env["RANK"] == "1"  # worker 0 offset by 1 master
        assert env["WORKER_ADDRS"] == "xgb-worker-0,xgb-worker-1"
        assert env["WORKER_PORT"] == "9999"


class TestPaddle:
    def test_collective_mode(self):
        cluster, mgr = make_env()
        job = PaddleJob(
            metadata=ObjectMeta(name="pd"),
            replica_specs={"Worker": ReplicaSpec(replicas=2, template=tmpl("paddle"))},
        )
        mgr.submit(job)
        assert cluster.run_until(lambda: len(pods_of(cluster, "pd")) == 2, timeout=30)
        env = pods_of(cluster, "pd")[0].spec.containers[0].env
        assert env["PADDLE_MASTER"] == "pd-worker-0:37777"
        assert env["PADDLE_NNODES"] == "2"
        assert env["PADDLE_JOB_ID"] == "pd"

    def test_ps_mode(self):
        cluster, mgr = make_env()
        job = PaddleJob(
            metadata=ObjectMeta(name="pdps"),
            replica_specs={
                "Master": ReplicaSpec(replicas=1, template=tmpl("paddle")),
                "Worker": ReplicaSpec(replicas=2, template=tmpl("paddle")),
            },
        )
        mgr.submit(job)
        assert cluster.run_until(lambda: len(pods_of(cluster, "pdps")) == 3, timeout=30)
        m = pods_of(cluster, "pdps", "Master")[0].spec.containers[0].env
        w = pods_of(cluster, "pdps", "Worker")[0].spec.containers[0].env
        assert m["PADDLE_MASTER"] == "pdps-master-0:37777"
        assert m["PADDLE_SERVER_NUM"] == "1"
        assert w["PADDLE_TRAINER_NUM"] == "1"


class TestMPI:
    def job(self, name="mpi", workers=2, slots=2):
        return MPIJob(
            metadata=ObjectMeta(name=name),
            replica_specs={
                "Launcher": ReplicaSpec(replicas=1, template=tmpl("mpi")),
                "Worker": ReplicaSpec(replicas=workers, template=tmpl("mpi")),
            },
            slots_per_worker=slots,
        )

    def test_launcher_gated_on_workers_then_hostfile(self):
        cluster, mgr = make_env()
        mgr.submit(self.job())
        # Workers first; launcher only after they are Running.
        assert cluster.run_until(
            lambda: len(pods_of(cluster, "mpi", "Launcher")) == 1, timeout=60
        )
        workers = pods_of(cluster, "mpi", "Worker")
        assert all(p.status.phase.value == "Running" for p in workers)

        cm = cluster.api.get("ConfigMap", "default", "mpi-config")
        assert cm.data["hostfile"] == "mpi-worker-0 slots=2\nmpi-worker-1 slots=2\n"
        assert "echo mpi-worker-0" in cm.data["discover_hosts.sh"]

        launcher = pods_of(cluster, "mpi", "Launcher")[0]
        env = launcher.spec.containers[0].env
        assert env["OMPI_MCA_orte_default_hostfile"] == "/etc/mpi/hostfile"
        assert "exec-agent" in env["OMPI_MCA_plm_rsh_agent"]
        # Workers get no bootstrap env
        assert "OMPI_MCA_orte_default_hostfile" not in workers[0].spec.containers[0].env

    def test_no_services_created(self):
        cluster, mgr = make_env()
        mgr.submit(self.job(name="mpi2"))
        cluster.run_for(2.0)
        assert not cluster.api.list("Service", "default", {capi.JOB_NAME_LABEL: "mpi2"})

    def test_launcher_success_completes_job(self):
        cluster, mgr = make_env()
        job = self.job(name="mpi3")
        job.replica_specs["Launcher"].template.annotations[ANNOTATION_SIM_DURATION] = "1.0"
        mgr.submit(job)
        assert cluster.run_until(
            lambda: capi.is_succeeded(cluster.api.get("MPIJob", "default", "mpi3").status),
            timeout=60,
        ), "launcher completion must complete the job even with workers running"

    def test_intel_env(self):
        from training_operator_tpu.api.jobs import MPIImplementation

        cluster, mgr = make_env()
        job = self.job(name="mpi4")
        job.mpi_implementation = MPIImplementation.INTEL
        mgr.submit(job)
        assert cluster.run_until(
            lambda: len(pods_of(cluster, "mpi4", "Launcher")) == 1, timeout=60
        )
        env = pods_of(cluster, "mpi4", "Launcher")[0].spec.containers[0].env
        assert env["I_MPI_HYDRA_HOST_FILE"] == "/etc/mpi/hostfile"


class TestMPIExecChannel:
    """The substrate exec channel + ConfigMap mounting (replacing the
    reference's kubectl-delivery + per-job RBAC, mpijob_controller.go:
    1227-1393): every path the launcher env references must resolve."""

    def job(self, name="mpix", workers=2):
        return MPIJob(
            metadata=ObjectMeta(name=name),
            replica_specs={
                "Launcher": ReplicaSpec(replicas=1, template=tmpl("mpi")),
                "Worker": ReplicaSpec(replicas=workers, template=tmpl("mpi")),
            },
            slots_per_worker=2,
        )

    def test_launcher_mounts_resolve(self):
        from training_operator_tpu.cluster.runtime import resolve_pod_files

        cluster, mgr = make_env()
        mgr.submit(self.job())
        assert cluster.run_until(
            lambda: len(pods_of(cluster, "mpix", "Launcher")) == 1, timeout=60
        )
        launcher = pods_of(cluster, "mpix", "Launcher")[0]
        files = resolve_pod_files(cluster.api, launcher)
        # Every env-referenced path exists in the pod's mounted view.
        env = launcher.spec.containers[0].env
        assert env["OMPI_MCA_orte_default_hostfile"] in files
        assert env["OMPI_MCA_plm_rsh_agent"] in files
        assert files["/etc/mpi/hostfile"].startswith("mpix-worker-0 slots=2")
        assert "cluster-exec" in files["/etc/mpi/exec-agent"]
        assert "discover_hosts.sh" in "".join(files)  # elastic discovery too

    def test_exec_channel_reaches_running_workers_only(self):
        cluster, mgr = make_env()
        mgr.submit(self.job(name="mpiy"))
        assert cluster.run_until(
            lambda: len(pods_of(cluster, "mpiy", "Launcher")) == 1, timeout=60
        )
        # The launcher's rsh agent execs into a running worker: recorded.
        rc, _ = cluster.exec.exec_in_pod("default", "mpiy-worker-0", ["orted"])
        assert rc == 0
        assert ("default", "mpiy-worker-0", ("orted",)) in cluster.exec.log
        # A nonexistent member is refused like a failed rsh.
        rc, msg = cluster.exec.exec_in_pod("default", "mpiy-worker-9", ["orted"])
        assert rc == 127 and "not found" in msg

    def test_exec_into_pending_pod_fails(self):
        from training_operator_tpu.cluster.objects import Pod
        from training_operator_tpu.api.jobs import ObjectMeta as OM

        cluster, _ = make_env()
        cluster.api.create(Pod(metadata=OM(name="idle", namespace="default")))
        rc, msg = cluster.exec.exec_in_pod("default", "idle", ["true"])
        assert rc == 1 and "not Running" in msg
