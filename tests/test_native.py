"""Native data-path core (training_operator_tpu/native): build, correctness
of the threaded gather and prefetcher against numpy, and DataLoader parity
between the native and fallback paths.

The toolchain (g++) is part of the supported environment, so a build
failure is a real failure here — not a skip — except where a test
explicitly exercises the degraded path.
"""

import numpy as np
import pytest

from training_operator_tpu import native
from training_operator_tpu.trainer.data import DataLoader, TokenDataset


def test_native_builds():
    assert native.available(), native.build_error()


class TestGather:
    @pytest.mark.parametrize("threads", [1, 4])
    @pytest.mark.parametrize("shape", [(1, 3), (64, 129), (1000, 33)])
    def test_matches_numpy(self, shape, threads):
        rng = np.random.RandomState(0)
        rows = rng.randint(0, 1 << 30, size=shape).astype(np.int32)
        idx = rng.randint(0, shape[0], size=shape[0] * 2).astype(np.int64)
        got = native.gather_rows(rows, idx, threads=threads)
        np.testing.assert_array_equal(got, rows[idx])

    def test_empty_index(self):
        rows = np.arange(12, dtype=np.int32).reshape(4, 3)
        got = native.gather_rows(rows, np.empty(0, dtype=np.int64))
        assert got.shape == (0, 3)

    def test_out_of_range_rejected(self):
        rows = np.zeros((4, 3), dtype=np.int32)
        with pytest.raises(ValueError):
            native.gather_rows(rows, np.array([4], dtype=np.int64))
        with pytest.raises(ValueError):
            native.gather_rows(rows, np.array([-1], dtype=np.int64))

    def test_caller_buffer_reused(self):
        rows = np.arange(20, dtype=np.int32).reshape(5, 4)
        out = np.empty((2, 4), dtype=np.int32)
        got = native.gather_rows(rows, np.array([3, 0], dtype=np.int64), out=out)
        assert got is out
        np.testing.assert_array_equal(out, rows[[3, 0]])


class TestPrefetcher:
    def test_pipeline_order(self):
        rng = np.random.RandomState(1)
        rows = rng.randint(0, 100, size=(50, 7)).astype(np.int32)
        batches = [
            rng.randint(0, 50, size=8).astype(np.int64) for _ in range(5)
        ]
        with native.Prefetcher(rows) as pf:
            pf.submit(batches[0])
            for i, idx in enumerate(batches):
                got = pf.wait()
                if i + 1 < len(batches):
                    pf.submit(batches[i + 1])
                np.testing.assert_array_equal(got, rows[idx])

    def test_protocol_misuse(self):
        rows = np.zeros((4, 3), dtype=np.int32)
        with native.Prefetcher(rows) as pf:
            with pytest.raises(RuntimeError):
                pf.wait()  # nothing submitted
            pf.submit(np.array([0], dtype=np.int64))
            with pytest.raises(RuntimeError):
                pf.submit(np.array([1], dtype=np.int64))  # already in flight
            pf.wait()

    def test_wait_during_inflight_gather_blocks(self):
        """Regression: a wait() that lands while the worker has dequeued the
        request but not yet posted the result must BLOCK (in-flight state),
        not read as 'nothing submitted' — that misread made Python drop the
        staging buffer mid-memcpy (use-after-free). A large gather plus an
        immediate wait reliably lands in that window."""
        rng = np.random.RandomState(0)
        rows = rng.randint(0, 1 << 20, size=(20_000, 512)).astype(np.int32)
        idx = rng.randint(0, 20_000, size=50_000).astype(np.int64)
        with native.Prefetcher(rows, threads=2) as pf:
            for _ in range(3):
                pf.submit(idx)
                got = pf.wait()  # immediately — worker is mid-gather
            np.testing.assert_array_equal(got, rows[idx])

    def test_wrong_shape_out_rejected(self):
        rows = np.zeros((8, 4), dtype=np.int32)
        idx = np.arange(8, dtype=np.int64)
        with pytest.raises(ValueError, match="out must be"):
            native.gather_rows(rows, idx, out=np.empty((2, 4), np.int32))
        with pytest.raises(ValueError, match="out must be"):
            native.gather_rows(rows, idx, out=np.empty((8, 4), np.int64))


class TestLoaderParity:
    def test_native_matches_numpy_path(self):
        ds = TokenDataset.synthetic(vocab_size=97, seq_len=16, num_rows=40, seed=3)
        a = DataLoader(ds, batch_size=8, shuffle=True, seed=5, use_native=True)
        b = DataLoader(ds, batch_size=8, shuffle=True, seed=5, use_native=False)
        assert a.use_native and not b.use_native
        batches_a, batches_b = list(a.epoch(2)), list(b.epoch(2))
        assert len(batches_a) == len(batches_b) == 5
        for ba, bb in zip(batches_a, batches_b):
            for k in ("tokens", "targets", "mask"):
                np.testing.assert_array_equal(np.asarray(ba[k]), np.asarray(bb[k]))

    def test_token_file_mmap_roundtrip(self, tmp_path):
        rng = np.random.RandomState(0)
        flat = rng.randint(0, 1000, size=4 * 17 + 5).astype(np.int32)
        path = tmp_path / "tokens.bin"
        flat.tofile(path)
        ds = TokenDataset.from_token_file(str(path), seq_len=16)
        assert len(ds) == 4 and ds.rows.shape == (4, 17)
        np.testing.assert_array_equal(
            np.asarray(ds.rows).ravel(), flat[: 4 * 17]
        )
        # The mmap'd arena feeds the native gather directly.
        loader = DataLoader(ds, batch_size=2, shuffle=False)
        batch = next(iter(loader))
        np.testing.assert_array_equal(
            np.asarray(batch["tokens"]), ds.rows[:2, :-1]
        )

    def test_process_sharded_file(self, tmp_path):
        flat = np.arange(6 * 9, dtype=np.int32)
        path = tmp_path / "tokens.bin"
        flat.tofile(path)
        shard0 = TokenDataset.from_token_file(str(path), 8, 0, 2)
        shard1 = TokenDataset.from_token_file(str(path), 8, 1, 2)
        assert len(shard0) == len(shard1) == 3
        assert not np.shares_memory(
            np.asarray(shard0.rows), np.asarray(shard1.rows)
        ) or not np.may_share_memory(
            np.asarray(shard0.rows), np.asarray(shard1.rows)
        )
        np.testing.assert_array_equal(
            np.concatenate([shard0.rows, shard1.rows]).ravel(), flat[: 6 * 9]
        )
