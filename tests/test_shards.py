"""Operator scale-out (PR 15): sharded reconcile ownership + follower reads.

Four planes, matching the tentpole's two halves plus their satellites:

  primitives     namespace->shard hashing, rendezvous ownership (minimal
                 movement on membership change), and the LeaderElector
                 takeover-CAS conflict fix (re-read the winner instead of
                 flapping _set_leader)
  shard elector  leader-per-shard leases: single-member grab-all, join
                 rebalance, death handoff within the grace, suspect-then-
                 confirm under clock jumps, graceful release
  sharded manager  3 replicas over one cluster: replica death mid-burst ->
                 survivors adopt its shards within shard_takeover_grace,
                 every job converges, and the single-writer pin — every
                 reconcile runs on the replica that owns the shard at that
                 instant, with no other live replica claiming it
  follower reads  the PR 9 warm standby serves LISTs and whole watch
                 sessions for a `read_from_standby` client at bounded
                 staleness (X-Training-Staleness observed client-side);
                 writes and strong single-object reads stay on the
                 primary; a dead standby degrades reads, never writes
"""

from __future__ import annotations

import itertools

import pytest

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import (
    Container,
    PodTemplateSpec,
    ReplicaSpec,
)
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta
from training_operator_tpu.cluster.apiserver import APIServer, ConflictError
from training_operator_tpu.cluster.inventory import make_cpu_pool
from training_operator_tpu.cluster.objects import Lease
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
)
from training_operator_tpu.controllers import JAXController, OperatorManager
from training_operator_tpu.controllers.leader import (
    LeaderElector,
    ShardElector,
    rendezvous_owner,
    shard_lease_name,
    shard_of,
    SHARD_NAMESPACE,
)


# ---------------------------------------------------------------------------
# Primitives
# ---------------------------------------------------------------------------


class TestShardPrimitives:
    def test_shard_of_is_stable_and_in_range(self):
        for n in (1, 2, 3, 7):
            for ns in ("", "default", "team-a", "soak-ns-5"):
                s = shard_of(ns, n)
                assert 0 <= s < n
                assert s == shard_of(ns, n)  # pure function
        assert shard_of("anything", 1) == 0

    def test_shard_of_spreads_namespaces(self):
        shards = {shard_of(f"ns-{i}", 4) for i in range(64)}
        assert shards == {0, 1, 2, 3}

    def test_rendezvous_minimal_movement(self):
        """Removing one member moves ONLY that member's shards — the
        rebalance-protocol property the rendezvous hash was chosen for."""
        members = [f"op-{i}" for i in range(5)]
        before = {s: rendezvous_owner(s, members) for s in range(32)}
        gone = "op-2"
        survivors = [m for m in members if m != gone]
        after = {s: rendezvous_owner(s, survivors) for s in range(32)}
        for s in range(32):
            if before[s] != gone:
                assert after[s] == before[s], "a survivor's shard moved"
            else:
                assert after[s] in survivors

    def test_rendezvous_deterministic_across_order(self):
        assert rendezvous_owner(3, ["b", "a", "c"]) == rendezvous_owner(
            3, ["c", "b", "a"]
        )


class TestTakeoverConflictNoFlap:
    """Satellite: the `_try_takeover` CAS must tolerate a 409 from a
    concurrent claimant by re-reading the winner — not by unconditionally
    flapping `_set_leader` to False."""

    def _expired_lease(self, api, now):
        lease = Lease(
            metadata=ObjectMeta(name="race-lease", namespace="operator-system"),
            holder="dead-holder", lease_duration=1.0,
            acquire_time=now - 100.0, renew_time=now - 100.0,
        )
        return api.create(lease)

    def test_losing_claimant_stays_standby_without_callbacks(self):
        api = APIServer()
        clock = VirtualClock()
        self._expired_lease(api, clock.now())
        a = LeaderElector(api, clock.now, "op-a", lease_name="race-lease")
        b = LeaderElector(api, clock.now, "op-b", lease_name="race-lease")
        stops = []
        b.on_stopped_leading.append(lambda: stops.append("b"))
        # Both read the expired lease; A's CAS lands first, B's conflicts.
        lease_a = api.get(Lease.KIND, "operator-system", "race-lease")
        lease_b = api.get(Lease.KIND, "operator-system", "race-lease")
        a._try_takeover(lease_a, clock.now())
        assert a.is_leader
        b._try_takeover(lease_b, clock.now())
        assert not b.is_leader
        assert stops == []  # was never leader; no spurious stop callback
        assert api.get(Lease.KIND, "operator-system", "race-lease").holder == "op-a"

    def test_own_racing_claim_does_not_flap(self):
        """The 409 whose winner is US (double-tick paths: a timer and an
        explicit tick driving one elector, a retried wire request landing
        twice): the elector must keep/become leader, with zero
        stopped-leading callbacks fired."""
        api = APIServer()
        clock = VirtualClock()
        self._expired_lease(api, clock.now())
        c = LeaderElector(api, clock.now, "op-c", lease_name="race-lease")
        flaps = []
        c.on_stopped_leading.append(lambda: flaps.append("stop"))
        stale = api.get(Lease.KIND, "operator-system", "race-lease")
        c._try_takeover(
            api.get(Lease.KIND, "operator-system", "race-lease"), clock.now()
        )
        assert c.is_leader
        # The stale copy's CAS conflicts — but the stored holder is c
        # itself, so this must NOT step down.
        with pytest.raises(ConflictError):
            api.update(stale)  # prove the copy really is stale
        c._try_takeover(stale, clock.now())
        assert c.is_leader
        assert flaps == []

    def test_two_managers_race_one_winner(self):
        """Two-elector integration arm: an expired lease contested by two
        live electors resolves to exactly one leader and stays stable
        across further ticks."""
        api = APIServer()
        clock = VirtualClock()
        self._expired_lease(api, clock.now())
        a = LeaderElector(api, clock.now, "op-a", lease_name="race-lease")
        b = LeaderElector(api, clock.now, "op-b", lease_name="race-lease")
        for _ in range(5):
            a.tick()
            b.tick()
            clock.advance(0.2)
            assert a.is_leader != b.is_leader  # exactly one, every round
        assert a.is_leader  # first ticker won and keeps renewing


# ---------------------------------------------------------------------------
# ShardElector
# ---------------------------------------------------------------------------


def _elector(api, clock, ident, shards=4, grace=5.0):
    return ShardElector(api, clock.now, ident, num_shards=shards,
                        takeover_grace=grace)


class TestShardElector:
    def test_single_member_owns_everything(self):
        api = APIServer()
        clock = VirtualClock()
        a = _elector(api, clock, "op-a")
        assert a.tick() == frozenset(range(4))
        assert a.claims()["shards"] == [0, 1, 2, 3]

    def test_join_rebalances_to_rendezvous_assignment(self):
        api = APIServer()
        clock = VirtualClock()
        a = _elector(api, clock, "op-a")
        a.tick()
        b = _elector(api, clock, "op-b")
        # A few alternating ticks: releases and acquisitions settle.
        for _ in range(4):
            b.tick()
            a.tick()
            clock.advance(0.5)
        desired = {
            s: rendezvous_owner(s, ["op-a", "op-b"]) for s in range(4)
        }
        assert a.owned == frozenset(
            s for s, o in desired.items() if o == "op-a")
        assert b.owned == frozenset(
            s for s, o in desired.items() if o == "op-b")
        assert a.owned | b.owned == frozenset(range(4))
        assert not (a.owned & b.owned)
        assert a.rebalances > 0  # a released what b now owns

    def test_death_handoff_within_grace(self):
        api = APIServer()
        clock = VirtualClock()
        a = _elector(api, clock, "op-a", grace=5.0)
        b = _elector(api, clock, "op-b", grace=5.0)
        for _ in range(4):
            a.tick()
            b.tick()
            clock.advance(0.5)
        dead_shards = set(b.owned)
        assert dead_shards
        # b dies: stops ticking. Its leases expire after the grace; a
        # needs the suspect tick plus the confirm tick past expiry.
        t_death = clock.now()
        adopted_at = None
        for _ in range(40):
            clock.advance(0.5)
            a.tick()
            if a.owned == frozenset(range(4)):
                adopted_at = clock.now()
                break
        assert adopted_at is not None, "survivor never adopted"
        # Handoff bound: lease expiry (<= grace after death) + the
        # suspect/confirm tick pair.
        assert adopted_at - t_death <= 5.0 + 2 * 0.5 + 1e-9
        assert a.handoffs >= len(dead_shards)

    def test_clock_jump_does_not_steal_live_holders_shards(self):
        """Suspect-then-confirm: a virtual-clock jump past the grace makes
        every lease look expired at once; the first replica to tick must
        NOT steal a live peer's shards (the peer renews on its own tick in
        the same round)."""
        api = APIServer()
        clock = VirtualClock()
        a = _elector(api, clock, "op-a", grace=5.0)
        b = _elector(api, clock, "op-b", grace=5.0)
        for _ in range(4):
            a.tick()
            b.tick()
            clock.advance(0.5)
        owned_a, owned_b = set(a.owned), set(b.owned)
        handoffs_before = a.handoffs + b.handoffs
        clock.advance(60.0)  # way past every lease
        for _ in range(4):
            a.tick()
            b.tick()
            clock.advance(0.1)
        assert set(a.owned) == owned_a
        assert set(b.owned) == owned_b
        assert a.handoffs + b.handoffs == handoffs_before

    def test_release_all_hands_over_without_waiting_grace(self):
        api = APIServer()
        clock = VirtualClock()
        a = _elector(api, clock, "op-a", grace=30.0)
        b = _elector(api, clock, "op-b", grace=30.0)
        for _ in range(4):
            a.tick()
            b.tick()
            clock.advance(0.5)
        handoffs_before = b.handoffs
        a.release_all()
        assert a.owned == frozenset()
        # b adopts the released leases on ordinary ticks — no 30s wait.
        t0 = clock.now()
        for _ in range(6):
            b.tick()
            clock.advance(0.5)
        assert b.owned == frozenset(range(4))
        assert clock.now() - t0 < 30.0
        # Adopting RELEASED leases is a rebalance pickup, not a death
        # handoff: the handoff counter (and its metric) must not move.
        assert b.handoffs == handoffs_before


# ---------------------------------------------------------------------------
# Sharded manager: replica death mid-burst
# ---------------------------------------------------------------------------


def _job(name, ns, dur="3.0"):
    return JAXJob(
        metadata=ObjectMeta(name=name, namespace=ns),
        replica_specs={"Worker": ReplicaSpec(
            replicas=1,
            template=PodTemplateSpec(
                containers=[Container(name="jax", image="trainer",
                                      resources={"cpu": 0.5})],
                annotations={ANNOTATION_SIM_DURATION: dur},
            ),
        )},
    )


class TestShardedManagerFailover:
    GRACE = 5.0

    def _stack(self, replicas=3):
        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_cpu_pool(8, cpu_per_node=16.0))
        DefaultScheduler(cluster)
        SimKubelet(cluster)
        seq = itertools.count()
        events = []  # (seq, identity, key, owns, others_claim)
        mgrs = []
        for i in range(replicas):
            m = OperatorManager(
                cluster, operator_shards=replicas,
                shard_takeover_grace=self.GRACE,
                identity=f"op-{i}", resync_period=30.0,
            )
            m.register(JAXController(cluster.api))

            def probe(key, _m=m, _orig=None):
                pass

            orig = m._process

            def probe(key, _m=m, _orig=orig):  # noqa: F811
                kind, nsname = key.split("|", 1)
                ns = nsname.split("/", 1)[0]
                shard = shard_of(ns, _m.num_shards)
                others = [
                    o.identity for o in mgrs
                    if o is not _m and o._alive and shard in o.owned_shards
                ]
                events.append((
                    next(seq), _m.identity, key,
                    shard in _m.owned_shards, others,
                ))
                _orig(key)

            m._process = probe
            m._alive = True
            mgrs.append(m)
        return cluster, mgrs, events

    def test_replica_death_handoff_converges_single_writer(self):
        cluster, mgrs, events = self._stack()
        names = []
        for i in range(30):
            ns = f"team-{i % 9}"
            cluster.api.create(_job(f"j-{i}", ns))
            names.append((ns, f"j-{i}"))
        cluster.run_for(2.0)  # election settles; burst is in flight
        victim = max(mgrs, key=lambda m: len(m.owned_shards))
        stranded = set(victim.owned_shards)
        assert stranded, "victim owned nothing; test is vacuous"
        kill_t = cluster.clock.now()
        kill_marker = len(events)
        victim.kill()
        victim._alive = False
        survivors = [m for m in mgrs if m is not victim]

        # Survivors adopt the stranded shards within the grace bound
        # (lease expiry + the suspect/confirm tick pair).
        adopted = cluster.run_until(
            lambda: stranded <= set().union(
                *(m.owned_shards for m in survivors)
            ),
            timeout=self.GRACE * 4,
        )
        assert adopted, "stranded shards were never adopted"
        assert cluster.clock.now() - kill_t <= self.GRACE * 3

        # Every job converges despite the mid-burst death.
        done = cluster.run_until(
            lambda: all(
                capi.is_succeeded(cluster.api.get("JAXJob", ns, n).status)
                for ns, n in names
            ),
            timeout=600,
        )
        assert done, "burst did not converge after the replica death"

        # Single-writer pin: every reconcile ran on a replica that owned
        # the key's shard at that instant, with NO other live replica
        # claiming it — reconciling one job generation twice would need
        # exactly the overlap this forbids.
        assert events
        for _s, ident, key, owned, others in events:
            assert owned, f"{ident} reconciled {key} without owning its shard"
            assert not others, (
                f"{ident} reconciled {key} while {others} also claimed it"
            )

        # The dead replica stays silent after the kill: its ticker was
        # removed, so no reconcile of its is recorded past the marker.
        assert all(
            e[1] != victim.identity for e in events[kill_marker:]
        ), "the killed replica kept reconciling"

    def test_rebalance_handoff_no_double_reconcile(self):
        """A live rebalance (replica joins late) keeps the single-writer
        contract: the releasing replica's queue keys for a moved shard are
        dropped at pop, never reconciled."""
        cluster, mgrs, events = self._stack(replicas=3)
        names = []
        for i in range(18):
            ns = f"team-{i % 6}"
            cluster.api.create(_job(f"r-{i}", ns))
            names.append((ns, f"r-{i}"))
        done = cluster.run_until(
            lambda: all(
                capi.is_succeeded(cluster.api.get("JAXJob", ns, n).status)
                for ns, n in names
            ),
            timeout=600,
        )
        assert done
        for _s, ident, key, owned, others in events:
            assert owned and not others

    def test_unsharded_manager_unchanged(self):
        """operator_shards=1 keeps the exact pre-shard shape: no shard
        elector, no shard leases, single leader election still available."""
        cluster = Cluster(VirtualClock())
        m = OperatorManager(cluster, operator_shards=1, leader_elect=True)
        assert m.shard_elector is None
        assert m.elector is not None
        assert m.owns_namespace("anything")
        m2 = OperatorManager(cluster)
        assert m2.shard_elector is None and m2.elector is None
        assert m2.owns_namespace("x")


# ---------------------------------------------------------------------------
# INV010 feed shape (unit semantics live in tests/test_fleet.py)
# ---------------------------------------------------------------------------


class TestShardClaimsFeed:
    def test_manager_claims_shape(self):
        cluster = Cluster(VirtualClock())
        m = OperatorManager(cluster, operator_shards=3, identity="op-x",
                            shard_takeover_grace=7.0)
        cluster.step()
        c = m.shard_claims()
        assert c["identity"] == "op-x"
        assert c["num_shards"] == 3
        assert c["grace"] == 7.0
        assert c["shards"] == [0, 1, 2]  # sole member owns everything

    def test_shard_feed_aggregates(self):
        from training_operator_tpu.__main__ import shard_feed

        cluster = Cluster(VirtualClock())
        a = OperatorManager(cluster, operator_shards=2, identity="op-a",
                            shard_takeover_grace=3.0)
        b = OperatorManager(cluster, operator_shards=2, identity="op-b",
                            shard_takeover_grace=3.0)
        for _ in range(4):
            cluster.step()
            cluster.clock.advance(0.5)
        feed = shard_feed([a, b])
        assert feed["num_shards"] == 2
        assert feed["grace"] == 3.0
        assert set(feed["claims"]) == {"op-a", "op-b"}
        owned = sorted(
            s for shards in feed["claims"].values() for s in shards
        )
        assert owned == [0, 1]  # disjoint and complete

    def test_shard_leases_visible_in_fleet_snapshot(self):
        from training_operator_tpu.observe.fleet import collect_fleet, render_top
        from training_operator_tpu.__main__ import shard_feed
        from training_operator_tpu.observe.invariants import FleetSources

        cluster = Cluster(VirtualClock())
        m = OperatorManager(cluster, operator_shards=2, identity="op-f",
                            shard_takeover_grace=5.0)
        cluster.step()
        fleet = collect_fleet(
            cluster.api, cluster.clock.now(),
            FleetSources(shards=lambda: shard_feed([m])),
        )
        shards = fleet["shards"]
        assert shards["num_shards"] == 2
        assert shards["owners"] == {"op-f": 2}
        assert shards["unowned"] == 0
        assert shards["members"] == ["op-f"]
        assert shards["claims"] == {"op-f": [0, 1]}
        assert "shards:" in render_top(fleet)

    def test_shard_handoff_timeline_spans(self):
        from training_operator_tpu import observe

        cluster = Cluster(VirtualClock())
        prev = observe.enabled()
        observe.set_enabled(True)
        try:
            m = OperatorManager(cluster, operator_shards=2, identity="op-t",
                                shard_takeover_grace=5.0)
            cluster.step()
            tl = cluster.api.get_timeline("operator-system", "shard-0")
            assert tl is not None
            spans = [s["name"] for s in tl["spans"]]
            assert "shard_handoff" in spans
        finally:
            observe.set_enabled(prev)


# ---------------------------------------------------------------------------
# Follower reads: the warm standby serves LISTs + watch sessions
# ---------------------------------------------------------------------------


class TestFollowerReads:
    """Rides the PR 9 in-process HA pair (tests/test_failover.py stacks):
    a `read_from_standby` client routes LISTs/fleet/events and its whole
    watch session to the standby at bounded staleness while writes and
    strong single-object reads stay on the primary."""

    @pytest.fixture()
    def ha_pair(self, tmp_path):
        from tests.test_failover import PrimaryStack, StandbyStack

        primary = PrimaryStack(tmp_path / "primary")
        standby = None
        try:
            standby = StandbyStack(tmp_path / "standby", primary.url)
            yield primary, standby
        finally:
            if standby is not None:
                standby.shutdown()
            primary.shutdown()

    def _client(self, primary, standby, **kw):
        from training_operator_tpu.cluster.httpapi import RemoteAPIServer

        return RemoteAPIServer(
            addresses=[primary.url, standby.url], timeout=5.0,
            read_from_standby=True, **kw,
        )

    def test_lists_ride_standby_with_staleness_header(self, ha_pair):
        import time as _t

        from training_operator_tpu.cluster.objects import ConfigMap
        from training_operator_tpu.utils import metrics

        primary, standby = ha_pair
        client = self._client(primary, standby)
        assert client.base_url == primary.url      # writes
        assert client.read_url == standby.url      # follower reads
        for i in range(5):
            client.create(ConfigMap(
                metadata=ObjectMeta(name=f"fr-{i}"), data={"k": str(i)},
            ))
        standby.wait_caught_up()
        before = metrics.read_staleness_seconds.count
        deadline = _t.monotonic() + 10.0
        got = []
        while _t.monotonic() < deadline:
            got = client.list("ConfigMap")
            if len(got) >= 5:
                break
            _t.sleep(0.05)
        assert len(got) >= 5
        # The standby stamped the response: observed staleness proves the
        # read really was served by the follower, at bounded lag.
        assert metrics.read_staleness_seconds.count > before
        assert metrics.read_staleness_seconds.max < 30.0

    def test_primary_reads_carry_no_staleness(self, ha_pair):
        from training_operator_tpu.cluster.httpapi import RemoteAPIServer
        from training_operator_tpu.cluster.objects import ConfigMap
        from training_operator_tpu.utils import metrics

        primary, standby = ha_pair
        direct = RemoteAPIServer(primary.url, timeout=5.0)
        direct.create(ConfigMap(metadata=ObjectMeta(name="np-1"), data={}))
        before = metrics.read_staleness_seconds.count
        direct.list("ConfigMap")
        direct.get("ConfigMap", "default", "np-1")
        assert metrics.read_staleness_seconds.count == before

    def test_strong_reads_and_writes_stay_on_primary(self, ha_pair):
        """get/try_get read their own writes immediately — they ride the
        primary, not the (possibly lagging) standby."""
        from training_operator_tpu.cluster.objects import ConfigMap

        primary, standby = ha_pair
        client = self._client(primary, standby)
        client.create(ConfigMap(
            metadata=ObjectMeta(name="ryw-1"), data={"v": "1"},
        ))
        # Read-your-write with NO wait for replication: only the primary
        # can guarantee this.
        got = client.get("ConfigMap", "default", "ryw-1")
        assert got.data["v"] == "1"
        got.data["v"] = "2"
        client.update(got, status_only=False)
        assert client.get("ConfigMap", "default", "ryw-1").data["v"] == "2"

    def test_watch_session_served_from_standby(self, ha_pair):
        import time as _t

        from training_operator_tpu.cluster.objects import ConfigMap

        primary, standby = ha_pair
        client = self._client(primary, standby)
        q = client.watch(kinds=["ConfigMap"])
        assert client.read_url == standby.url
        # The whole SESSION lives on the standby: minted there (POST
        # /watches rides the read channel), polled there. A session minted
        # on the primary instead would 404 every standby poll and
        # degenerate into a permanent heal-and-relist loop — pinned below
        # by the session id staying constant across drains.
        wid = client._shared_watch.watch_id
        assert wid is not None
        with standby.server._sessions_lock:
            assert wid in standby.server._sessions
        client.create(ConfigMap(metadata=ObjectMeta(name="w-1"), data={}))
        seen = []
        deadline = _t.monotonic() + 10.0
        while _t.monotonic() < deadline and not any(
            ev.obj.metadata.name == "w-1" for ev in seen
        ):
            seen.extend(q.drain(timeout=0.2))
        assert any(ev.obj.metadata.name == "w-1" for ev in seen), (
            "write to the primary never arrived via the standby session"
        )
        # Replicated delivery, not relist synthesis: the event carries the
        # primary's seq (relist-synthesized events carry seq 0), and the
        # session never healed/reopened.
        assert all(ev.seq > 0 for ev in seen if ev.obj.metadata.name == "w-1")
        assert client._shared_watch.watch_id == wid
        with primary.server._sessions_lock:
            assert not primary.server._sessions, (
                "watch sessions leaked onto the primary"
            )
        client.unwatch(q)

    def test_dead_standby_degrades_reads_not_writes(self, ha_pair):
        import time as _t

        from training_operator_tpu.cluster.httpapi import ApiUnavailableError
        from training_operator_tpu.cluster.objects import ConfigMap

        primary, standby = ha_pair
        client = self._client(primary, standby)
        client.create(ConfigMap(metadata=ObjectMeta(name="deg-1"), data={}))
        standby.wait_caught_up()
        assert client.list("ConfigMap")  # served by the standby
        standby.ctrl.stop()
        standby.server.kill()  # sever the read channel mid-life
        # Reads degrade to the primary (one visible failure while the read
        # channel rotates is allowed — the ordinary retry arm).
        got = None
        deadline = _t.monotonic() + 10.0
        while _t.monotonic() < deadline:
            try:
                got = client.list("ConfigMap")
                break
            except ApiUnavailableError:
                _t.sleep(0.05)
        assert got, "reads never degraded to the primary"
        assert client.read_url == primary.url
        # Writes never moved off the healthy primary.
        assert client.base_url == primary.url
        client.create(ConfigMap(metadata=ObjectMeta(name="deg-2"), data={}))

    def test_read_degrade_recovers_toward_preferred_standby(self, ha_pair):
        """A transient read-side failure must not park reads on the
        primary forever: after read_retry_interval the client re-probes
        the preferred standby address."""
        import time as _t

        primary, standby = ha_pair
        client = self._client(primary, standby)
        standby.wait_caught_up()
        assert client.list("ConfigMap") is not None
        assert client.read_url == standby.url
        # Simulate the degrade a transient standby blip causes.
        client._rotate_read(client._read_gen)
        assert client.read_url == primary.url
        client.read_retry_interval = 0.05
        _t.sleep(0.1)
        client.list("ConfigMap")  # the re-probe rides this read
        assert client.read_url == standby.url
