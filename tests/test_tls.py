"""TLS on the wire (cluster/certs.py + httpapi TLS integration).

Parity target: the reference serves HTTPS with self-signed certs minted at
startup and rotated in-process (pkg/cert/cert.go:45, consumed by
cmd/training-operator.v1/main.go:152-166). Pinned here: the host-minted CA
verifies, a foreign CA is rejected LOUDLY (config error, not a silent
retry), plain HTTP against the TLS port fails, and cert rotation is
invisible to clients because their trust anchor is the CA.
"""

import pytest

from training_operator_tpu.api.jobs import ObjectMeta
from training_operator_tpu.cluster import certs
from training_operator_tpu.cluster.apiserver import APIServer
from training_operator_tpu.cluster.httpapi import (
    ApiHTTPServer,
    ApiUnavailableError,
    RemoteAPIServer,
)
from training_operator_tpu.cluster.objects import ConfigMap


@pytest.fixture()
def tls_server(tmp_path):
    ca_cert, ca_key = certs.mint_ca(str(tmp_path))
    cert, key = certs.mint_server_cert(str(tmp_path), ca_cert, ca_key)
    api = APIServer()
    server = ApiHTTPServer(api, tls=(cert, key))
    yield server, ca_cert, (str(tmp_path), ca_cert, ca_key)
    server.close()


def _cm(name="c"):
    return ConfigMap(metadata=ObjectMeta(name=name), data={"k": "v"})


class TestWireTLS:
    def test_verified_roundtrip(self, tls_server):
        server, ca, _ = tls_server
        assert server.url.startswith("https://")
        remote = RemoteAPIServer(server.url, timeout=5.0, ca_file=ca)
        remote.create(_cm())
        assert remote.get("ConfigMap", "default", "c").data == {"k": "v"}

    def test_foreign_ca_rejected_loudly(self, tls_server, tmp_path):
        """A server cert not signed by the pinned CA is a config error /
        impersonation — PermissionError, never the retryable transport arm
        (an operator retry-looping a bad pin forever would mask it)."""
        server, _, _ = tls_server
        other_dir = tmp_path / "other"
        other_ca, _ = certs.mint_ca(str(other_dir))
        remote = RemoteAPIServer(server.url, timeout=5.0, ca_file=str(other_ca))
        with pytest.raises(PermissionError):
            remote.list("ConfigMap")

    def test_plain_http_cannot_reach_tls_port(self, tls_server):
        server, _, _ = tls_server
        plain = RemoteAPIServer(
            server.url.replace("https://", "http://"), timeout=5.0
        )
        with pytest.raises(ApiUnavailableError):
            plain.list("ConfigMap")

    def test_rotation_invisible_to_pinned_client(self, tls_server):
        """Re-minting the serving cert and hot-loading it must not disturb
        a client whose trust anchor is the CA — the reference's rotated
        webhook serving certs behave identically."""
        server, ca, (cert_dir, ca_cert, ca_key) = tls_server
        remote = RemoteAPIServer(server.url, timeout=5.0, ca_file=ca)
        remote.create(_cm("before"))

        fresh = certs.mint_server_cert(cert_dir, ca_cert, ca_key)
        server.rotate_cert(*fresh)

        remote.create(_cm("after"))  # new connection, new handshake
        assert {c.metadata.name for c in remote.list("ConfigMap")} == {
            "before", "after"
        }

    def test_rotate_without_tls_raises(self):
        api = APIServer()
        server = ApiHTTPServer(api)
        try:
            with pytest.raises(RuntimeError):
                server.rotate_cert("x", "y")
        finally:
            server.close()

    def test_ca_reused_across_mints(self, tmp_path):
        """mint_ca is idempotent per directory — operator pins must survive
        a host restart (the restart e2e asserts the same end to end)."""
        a = certs.mint_ca(str(tmp_path))
        b = certs.mint_ca(str(tmp_path))
        assert a == b
        assert open(a[0], "rb").read() == open(b[0], "rb").read()

    def test_server_cert_sans_cover_loopback_and_extra_hosts(self, tmp_path):
        from cryptography import x509

        ca_cert, ca_key = certs.mint_ca(str(tmp_path))
        cert_path, _ = certs.mint_server_cert(
            str(tmp_path), ca_cert, ca_key,
            hosts=["10.0.0.7", "host.internal", "0.0.0.0"],
        )
        cert = x509.load_pem_x509_certificate(open(cert_path, "rb").read())
        sans = cert.extensions.get_extension_for_class(
            x509.SubjectAlternativeName
        ).value
        dns = set(sans.get_values_for_type(x509.DNSName))
        ips = {str(ip) for ip in sans.get_values_for_type(x509.IPAddress)}
        assert "localhost" in dns and "host.internal" in dns
        assert "127.0.0.1" in ips and "10.0.0.7" in ips
        assert "0.0.0.0" not in ips  # bind wildcard, not a dialable address
