"""Concurrency-discipline plane (PR 16): the static lock/ownership
analyzer (CL008-CL011 + the allowlist pragma contract), the runtime
lock-order witness (cycle detection, once-per-pair reporting, order
exceptions, disabled-mode zero-allocation, Condition integration), the
metrics lock-hygiene pin (registry vs metric ordering under concurrent
render), and the chaos-matrix leg asserting the wire storm runs clean
under the witness with fail-fast armed."""

from __future__ import annotations

import textwrap
import threading
import time

import pytest

import training_operator_tpu.api.common as capi
from training_operator_tpu.analysis.lockcheck import (
    analyze_source,
    check_paths,
    check_source,
    report_paths,
)
from training_operator_tpu.observe.invariants import InvariantViolationError
from training_operator_tpu.utils import locks, metrics


def _src(code: str) -> str:
    return textwrap.dedent(code)


def _rules(code: str, rel: str = "cluster/x.py"):
    return [f.rule_id for f in check_source("x.py", _src(code), package_rel=rel)]


# -- static rules ----------------------------------------------------------


class TestCL008RawLock:
    CASES = [
        ("lock", "import threading\n_l = threading.Lock()\n", ["CL008"]),
        ("rlock", "import threading\n_l = threading.RLock()\n", ["CL008"]),
        ("cond", "import threading\n_c = threading.Condition()\n", ["CL008"]),
        ("tracked", "from training_operator_tpu.utils.locks import "
                    "TrackedLock\n_l = TrackedLock('x')\n", []),
    ]

    @pytest.mark.parametrize("case,src,want", CASES,
                             ids=[c[0] for c in CASES])
    def test_table(self, case, src, want):
        assert _rules(src) == want

    def test_locks_module_itself_is_exempt(self):
        src = "import threading\n_meta = threading.Lock()\n"
        assert _rules(src, rel="utils/locks.py") == []

    def test_method_body_ctor_flagged(self):
        src = """
        import threading
        class S:
            def __init__(self):
                self._lock = threading.Lock()
        """
        assert _rules(src) == ["CL008"]


class TestCL009BlockingUnderLock:
    CASES = [
        ("fsync_direct", """
         import os
         from training_operator_tpu.utils.locks import TrackedLock
         class S:
             def __init__(self):
                 self._lock = TrackedLock('s')
             def write(self, fh):
                 with self._lock:
                     os.fsync(fh.fileno())
         """, ["CL009"]),
        ("fsync_outside_lock_clean", """
         import os
         from training_operator_tpu.utils.locks import TrackedLock
         class S:
             def __init__(self):
                 self._lock = TrackedLock('s')
             def write(self, fh):
                 with self._lock:
                     pass
                 os.fsync(fh.fileno())
         """, []),
        ("wire_request", """
         from training_operator_tpu.utils.locks import TrackedLock
         class S:
             def __init__(self):
                 self._lock = TrackedLock('s')
             def push(self, conn):
                 with self._lock:
                     conn.request('POST', '/x')
         """, ["CL009"]),
        ("sleep", """
         import time
         from training_operator_tpu.utils.locks import TrackedLock
         class S:
             def __init__(self):
                 self._lock = TrackedLock('s')
             def spin(self):
                 with self._lock:
                     time.sleep(1.0)
         """, ["CL009"]),
        ("subprocess", """
         import subprocess
         from training_operator_tpu.utils.locks import TrackedLock
         class S:
             def __init__(self):
                 self._lock = TrackedLock('s')
             def build(self):
                 with self._lock:
                     subprocess.check_call(['make'])
         """, ["CL009"]),
        ("no_timeout_wait", """
         from training_operator_tpu.utils.locks import TrackedCondition, TrackedLock
         class S:
             def __init__(self):
                 self._lock = TrackedLock('s')
                 self._cond = TrackedCondition(self._lock, name='s')
             def park(self):
                 with self._cond:
                     self._cond.wait()
         """, ["CL009"]),
        ("bounded_wait_clean", """
         from training_operator_tpu.utils.locks import TrackedCondition, TrackedLock
         class S:
             def __init__(self):
                 self._lock = TrackedLock('s')
                 self._cond = TrackedCondition(self._lock, name='s')
             def park(self):
                 with self._cond:
                     self._cond.wait(timeout=1.0)
         """, []),
    ]

    @pytest.mark.parametrize("case,src,want", CASES,
                             ids=[c[0] for c in CASES])
    def test_table(self, case, src, want):
        assert _rules(src) == want

    def test_helper_one_level_deep(self):
        """A blocking call inside self._flush() is reached under the lock
        when the caller holds it at the call site."""
        src = """
        import os
        from training_operator_tpu.utils.locks import TrackedLock
        class S:
            def __init__(self):
                self._lock = TrackedLock('s')
            def write(self, fh):
                with self._lock:
                    self._flush(fh)
            def _flush(self, fh):
                os.fsync(fh.fileno())
        """
        found = check_source("x.py", _src(src), package_rel="cluster/x.py")
        assert [f.rule_id for f in found] == ["CL009"]
        assert "reached under lock" in found[0].message


class TestCL010OrderCycle:
    def test_opposite_orders_cycle(self):
        src = """
        from training_operator_tpu.utils.locks import TrackedLock
        class S:
            def __init__(self):
                self._a = TrackedLock('a')
                self._b = TrackedLock('b')
            def one(self):
                with self._a:
                    with self._b:
                        pass
            def two(self):
                with self._b:
                    with self._a:
                        pass
        """
        found = check_source("x.py", _src(src), package_rel="cluster/x.py")
        assert [f.rule_id for f in found] == ["CL010"]
        assert "_a" in found[0].message and "_b" in found[0].message

    def test_consistent_order_clean(self):
        src = """
        from training_operator_tpu.utils.locks import TrackedLock
        class S:
            def __init__(self):
                self._a = TrackedLock('a')
                self._b = TrackedLock('b')
            def one(self):
                with self._a:
                    with self._b:
                        pass
            def two(self):
                with self._a:
                    with self._b:
                        pass
        """
        assert _rules(src) == []

    def test_cycle_via_helper(self):
        """one() holds _a and calls a helper that takes _b; two() nests
        them the other way lexically — still a cycle."""
        src = """
        from training_operator_tpu.utils.locks import TrackedLock
        class S:
            def __init__(self):
                self._a = TrackedLock('a')
                self._b = TrackedLock('b')
            def one(self):
                with self._a:
                    self._grab_b()
            def _grab_b(self):
                with self._b:
                    pass
            def two(self):
                with self._b:
                    with self._a:
                        pass
        """
        assert "CL010" in _rules(src)

    def test_condition_shares_lock_order_class(self):
        """with self._cond: resolves to the lock the Condition wraps, so
        cond-then-peer and peer-then-lock is a real cycle."""
        src = """
        from training_operator_tpu.utils.locks import TrackedCondition, TrackedLock
        class S:
            def __init__(self):
                self._lock = TrackedLock('s')
                self._cond = TrackedCondition(self._lock, name='s')
                self._peer = TrackedLock('p')
            def one(self):
                with self._cond:
                    with self._peer:
                        pass
            def two(self):
                with self._peer:
                    with self._lock:
                        pass
        """
        assert "CL010" in _rules(src)


class TestCL011GuardedFieldWrite:
    GUARDED = """
    from training_operator_tpu.utils.locks import TrackedLock
    import threading as _t
    class S:
        def __init__(self):
            self._lock = TrackedLock('s')
            self._buf = []
            self._n = 0
        def start(self):
            _t.Thread(target=self._run).start()
        def _run(self):
            with self._lock:
                self._buf.append(1)
                self._n += 1
        def flush(self):
            self._buf = []
    """

    def test_unguarded_write_with_entry_point(self):
        found = check_source("x.py", _src(self.GUARDED),
                             package_rel="cluster/x.py")
        rules = [f.rule_id for f in found]
        assert "CL011" in rules
        msgs = [f.message for f in found if f.rule_id == "CL011"]
        assert any("_buf" in m and "_lock" in m for m in msgs)

    def test_no_entry_points_no_finding(self):
        src = self.GUARDED.replace(
            "_t.Thread(target=self._run).start()", "pass")
        found = [f.rule_id for f in
                 check_source("x.py", _src(src), package_rel="cluster/x.py")]
        assert "CL011" not in found

    def test_init_writes_exempt(self):
        """__init__ seeds guarded fields before any second thread exists."""
        src = """
        from training_operator_tpu.utils.locks import TrackedLock
        import threading as _t
        class S:
            def __init__(self):
                self._lock = TrackedLock('s')
                self._buf = []
                _t.Thread(target=self._run).start()
            def _run(self):
                with self._lock:
                    self._buf.append(1)
        """
        assert _rules(src) == []

    def test_mutating_call_counts_as_write(self):
        """flush() mutating via .clear() (no assignment) is still an
        unguarded write to a guarded container."""
        src = """
        from training_operator_tpu.utils.locks import TrackedLock
        import threading as _t
        class S:
            def __init__(self):
                self._lock = TrackedLock('s')
                self._buf = []
            def start(self):
                _t.Thread(target=self._run).start()
            def _run(self):
                with self._lock:
                    self._buf.append(1)
            def flush(self):
                self._buf.clear()
        """
        found = check_source("x.py", _src(src), package_rel="cluster/x.py")
        assert "CL011" in [f.rule_id for f in found]


class TestAllowlistPragma:
    def test_pragma_with_reason_suppresses(self):
        src = """
        import os
        from training_operator_tpu.utils.locks import TrackedLock
        class S:
            def __init__(self):
                self._lock = TrackedLock('s')
            def write(self, fh):
                with self._lock:
                    # lockcheck: allow CL009 — journal order IS write order
                    os.fsync(fh.fileno())
        """
        assert _rules(src) == []

    def test_pragma_on_flagged_line(self):
        src = """
        import os
        from training_operator_tpu.utils.locks import TrackedLock
        class S:
            def __init__(self):
                self._lock = TrackedLock('s')
            def write(self, fh):
                with self._lock:
                    os.fsync(fh.fileno())  # lockcheck: allow CL009 — ordered write
        """
        assert _rules(src) == []

    def test_bare_pragma_is_a_finding(self):
        src = """
        import os
        from training_operator_tpu.utils.locks import TrackedLock
        class S:
            def __init__(self):
                self._lock = TrackedLock('s')
            def write(self, fh):
                with self._lock:
                    # lockcheck: allow CL009
                    os.fsync(fh.fileno())
        """
        rules = _rules(src)
        assert "CL000" in rules and "CL009" in rules

    def test_pragma_for_wrong_rule_does_not_suppress(self):
        src = """
        import os
        from training_operator_tpu.utils.locks import TrackedLock
        class S:
            def __init__(self):
                self._lock = TrackedLock('s')
            def write(self, fh):
                with self._lock:
                    # lockcheck: allow CL008 — wrong rule id
                    os.fsync(fh.fileno())
        """
        assert "CL009" in _rules(src)


class TestTreeAndReport:
    def test_package_tree_is_clean(self):
        """The whole package under lockcheck: zero unallowlisted findings.
        This is the line CL008 holds against new raw locks."""
        import training_operator_tpu
        root = training_operator_tpu.__path__[0]
        found = check_paths([root])
        assert found == [], "\n".join(f.render() for f in found)

    def test_report_maps_store_locks(self):
        """The --report JSON names the store's lock, its condition alias,
        and guarded fields — the reviewable lock->field map."""
        import training_operator_tpu
        root = training_operator_tpu.__path__[0]
        rep = report_paths([root])
        store = rep["files"]["cluster/store.py"]["HostStore"]
        assert store["locks"].get("_lock") == "lock"
        assert store["condition_aliases"].get("_wal_cond") == "_lock"
        assert "_wal" in store["guarded_fields"]["_lock"]
        # No class in the tree lexically nests two owned locks — the
        # merged static order graph is empty, and must STAY empty (new
        # nesting shows up here for review before the runtime witness
        # ever sees the interleaving).
        assert rep["order_edges"] == []

    def test_guarded_field_inference(self):
        fa = analyze_source("x.py", _src(TestCL011GuardedFieldWrite.GUARDED),
                            package_rel="cluster/x.py")
        model = next(s for s in fa.scopes if s.qualname == "S")
        assert model.guarded_fields() == {"_buf": "_lock", "_n": "_lock"}
        assert "_run" in model.entry_points


# -- runtime witness -------------------------------------------------------


@pytest.fixture
def witness():
    """Fresh witness state; restores fail-fast/sink and re-enables after."""
    locks.reset_witness()
    locks.set_fail_fast(False)
    locks.set_violation_sink(None)
    was_enabled = locks.lockcheck_enabled()
    yield locks
    locks.enable(was_enabled)
    locks.set_fail_fast(False)
    locks.set_violation_sink(None)
    locks.reset_witness(clear_exceptions=True)


def _invert(a, b):
    with a:
        with b:
            pass
    with b:
        with a:
            pass


class TestWitness:
    def test_order_cycle_detected_with_evidence(self, witness):
        a, b = locks.TrackedLock("wa"), locks.TrackedLock("wb")
        before = metrics.lock_order_violations.total()
        _invert(a, b)
        v = locks.witness_violations()
        assert len(v) == 1
        assert v[0]["pair"] == "wb->wa"
        assert v[0]["cycle"] == ["wa", "wb", "wa"]
        # Both halves of the evidence: the closing site and the first
        # observation of every edge on the cycle.
        assert "test_lockcheck.py" in v[0]["site"]
        assert set(v[0]["other_sites"]) == {"wa->wb", "wb->wa"}
        assert metrics.lock_order_violations.total() == before + 1

    def test_once_per_edge_pair(self, witness):
        a, b = locks.TrackedLock("oa"), locks.TrackedLock("ob")
        _invert(a, b)
        _invert(a, b)
        with b:
            with a:
                pass
        assert len(locks.witness_violations()) == 1

    def test_order_classes_are_names_not_instances(self, witness):
        """Two locks in the same class ('store') generalize: inverting
        against DIFFERENT instances still closes the cycle — the property
        per-shard store replication relies on."""
        s1, s2 = locks.TrackedLock("cls.s"), locks.TrackedLock("cls.s")
        t = locks.TrackedLock("cls.t")
        with s1:
            with t:
                pass
        with t:
            with s2:
                pass
        assert [v["pair"] for v in locks.witness_violations()] == ["cls.t->cls.s"]

    def test_violation_sink_fires(self, witness):
        got = []
        locks.set_violation_sink(got.append)
        _invert(locks.TrackedLock("sa"), locks.TrackedLock("sb"))
        assert len(got) == 1 and got[0]["pair"] == "sb->sa"

    def test_fail_fast_raises(self, witness):
        locks.set_fail_fast(True)
        a, b = locks.TrackedLock("fa"), locks.TrackedLock("fb")
        with a:
            with b:
                pass
        with pytest.raises(InvariantViolationError, match="lock-order cycle"):
            with b:
                with a:
                    pass
        # The failed acquire must not leak a held entry or the inner lock.
        assert not a.locked() and not b.locked()
        with a:
            pass

    def test_order_exception_sanctions_inversion(self, witness):
        locks.register_order_exception("ea", "eb", "handoff protocol: "
                                       "promotion path inverts by design")
        _invert(locks.TrackedLock("ea"), locks.TrackedLock("eb"))
        assert locks.witness_violations() == []
        assert locks.order_exceptions()[("ea", "eb")].startswith("handoff")

    def test_order_exception_requires_reason(self, witness):
        with pytest.raises(ValueError):
            locks.register_order_exception("a", "b", "")
        with pytest.raises(ValueError):
            locks.register_order_exception("a", "b", "   ")

    def test_order_exception_idempotent_reregistration(self, witness):
        """The pytest re-import case: registering the same pair again must
        update, not error or duplicate (the PR 7 register_invariant rule)."""
        locks.register_order_exception("ia", "ib", "first")
        locks.register_order_exception("ia", "ib", "second")
        locks.register_order_exception("ib", "ia", "third")
        assert locks.order_exceptions() == {("ia", "ib"): "third"}

    def test_reset_keeps_exceptions_unless_cleared(self, witness):
        locks.register_order_exception("ka", "kb", "kept across rebuilds")
        _invert(locks.TrackedLock("xa"), locks.TrackedLock("xb"))
        locks.reset_witness()
        assert locks.witness_violations() == []
        assert locks.order_graph() == {}
        assert ("ka", "kb") in locks.order_exceptions()
        locks.reset_witness(clear_exceptions=True)
        assert locks.order_exceptions() == {}

    def test_reset_reopens_reporting(self, witness):
        """After reset the SAME inversion reports again — the soak rebuild
        must not inherit the torn-down stack's reported-pair set."""
        a, b = locks.TrackedLock("ra"), locks.TrackedLock("rb")
        _invert(a, b)
        locks.reset_witness()
        _invert(a, b)
        assert len(locks.witness_violations()) == 1

    def test_rlock_reentry_is_not_an_edge(self, witness):
        r = locks.TrackedRLock("rr")
        b = locks.TrackedLock("rb2")
        with r:
            with r:
                with b:
                    pass
        assert locks.order_graph() == {"rr": ["rb2"]}
        assert locks.witness_violations() == []

    def test_condition_wait_releases_held_set(self, witness):
        """While a waiter is parked in cond.wait(), its thread must NOT be
        charged with holding the lock — a notifier taking peer->lock is
        normal operation, not an inversion against the parked holder."""
        lk = locks.TrackedLock("cw.lock")
        cond = locks.TrackedCondition(lk, name="cw.lock")
        peer = locks.TrackedLock("cw.peer")
        woke = []

        def waiter():
            with cond:
                cond.wait(timeout=5.0)
                woke.append(True)

        t = threading.Thread(target=waiter, name="cw-waiter")
        t.start()
        time.sleep(0.05)
        with peer:
            with cond:
                cond.notify_all()
        t.join(timeout=5.0)
        assert woke == [True]
        assert locks.witness_violations() == []

    def test_disabled_mode_returns_raw_primitives(self, witness):
        """Disabled = production: no wrapper allocation at all, and no
        acquisition bookkeeping."""
        locks.enable(False)
        lk = locks.TrackedLock("off")
        rl = locks.TrackedRLock("off")
        assert type(lk) is type(threading.Lock())
        assert type(rl) is type(threading.RLock())
        base = locks.acquisitions()
        with lk:
            pass
        assert locks.acquisitions() == base
        assert locks.order_graph() == {}

    def test_enabled_mode_counts_acquisitions(self, witness):
        lk = locks.TrackedLock("cnt")
        base = locks.acquisitions()
        for _ in range(3):
            with lk:
                pass
        assert locks.acquisitions() == base + 3


class TestMetricsLockHygiene:
    def test_registry_and_metric_order_is_clean_under_concurrency(self, witness):
        """Satellite 2 pin: metrics are written from every thread while
        render()/snapshot() run on the HTTP handler thread. The registry
        lock must never be held across a metric lock in one direction and
        the reverse elsewhere — assert the witness sees no cycle while
        both paths hammer concurrently, and that registration-under-read
        (the factory path) stays clean too."""
        reg = metrics.MetricsRegistry()
        c = reg.counter("hygiene_total", "x", ("k",))
        h = reg.histogram("hygiene_seconds", "x")
        stop = threading.Event()

        def writer():
            i = 0
            while not stop.is_set():
                c.inc(f"k{i % 3}")
                h.observe(0.001 * i)
                i += 1

        def reader():
            while not stop.is_set():
                reg.render()
                reg.snapshot()

        def registrar():
            i = 0
            while not stop.is_set():
                reg.counter(f"hygiene_extra_{i}_total", "x")
                i += 1
                time.sleep(0.001)

        threads = [threading.Thread(target=f, name=f"hyg-{f.__name__}")
                   for f in (writer, writer, reader, registrar)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
        assert locks.witness_violations() == [], locks.witness_violations()
        # The graph may legitimately contain registry->metric (factory
        # registers under the registry lock); the reverse edge must not
        # exist — render copies the family list instead of iterating
        # under the registry lock.
        graph = locks.order_graph()
        for src_name in ("metrics.metric", "metrics.family"):
            assert "metrics.registry" not in graph.get(src_name, []), graph


class TestChaosMatrixUnderWitness:
    def test_full_storm_zero_lock_order_violations(self, witness):
        """Chaos-matrix leg: the full wire storm (5xx + resets + session
        reaps against a real HTTP operator) under the witness with
        fail-fast armed. Any acquisition-order cycle anywhere in the
        store/apiserver/wire/metrics planes raises out of the acquire and
        fails the leg; the explicit assert pins the zero-violation claim."""
        locks.set_fail_fast(True)
        from training_operator_tpu.api.common import (
            Container, PodTemplateSpec, ReplicaSpec,
        )
        from training_operator_tpu.api.jobs import JAXJob, ObjectMeta
        from training_operator_tpu.cluster.chaos import WireChaos
        from training_operator_tpu.cluster.httpapi import (
            ApiHTTPServer, ApiServerError, ApiUnavailableError,
            RemoteAPIServer, RemoteRuntime,
        )
        from training_operator_tpu.cluster.inventory import make_cpu_pool
        from training_operator_tpu.cluster.runtime import (
            ANNOTATION_SIM_DURATION, Cluster, DefaultScheduler, SimKubelet,
        )
        from training_operator_tpu.controllers import OperatorManager
        from training_operator_tpu.controllers.jax import JAXController

        host = Cluster()
        host.add_nodes(make_cpu_pool(2, cpu_per_node=8.0))
        DefaultScheduler(host)
        SimKubelet(host)
        chaos = WireChaos(seed=16, error_rate=0.10, reset_rate=0.05,
                          reap_rate=0.03)
        server = ApiHTTPServer(host.api, port=0, chaos=chaos)
        try:
            remote = RemoteAPIServer(server.url, timeout=10.0)
            runtime = RemoteRuntime(remote, tick_interval=0.0)
            for _ in range(50):
                try:
                    mgr = OperatorManager(runtime, gang_enabled=False,
                                          resync_period=2.0)
                    mgr.register(JAXController(runtime.api))
                    break
                except (ApiUnavailableError, ApiServerError):
                    continue
            else:
                raise AssertionError("operator never booted through the storm")
            tmpl = PodTemplateSpec(
                containers=[Container(name="jax", resources={"cpu": 1.0})],
                annotations={ANNOTATION_SIM_DURATION: "0.2"},
            )
            job = JAXJob(
                metadata=ObjectMeta(name="witness-storm"),
                replica_specs={"Worker": ReplicaSpec(replicas=2,
                                                     template=tmpl)},
            )
            for _ in range(200):
                try:
                    remote.create(job)
                    break
                except (ApiUnavailableError, ApiServerError):
                    continue
            else:
                raise AssertionError("create never got through the storm")

            def done():
                j = host.api.try_get("JAXJob", "default", "witness-storm")
                return j is not None and capi.is_succeeded(j.status)

            deadline = host.clock.now() + 60.0
            while host.clock.now() < deadline and not done():
                host.step()
                try:
                    runtime.step()
                except (ApiUnavailableError, ApiServerError):
                    pass
            assert done()
            mgr.stop()
        finally:
            server.close()
        assert sum(chaos.injected.values()) > 0, "storm never struck"
        assert locks.witness_violations() == [], locks.witness_violations()
        # The storm exercised real tracked acquisitions — no vacuous pass.
        assert locks.acquisitions() > 100
