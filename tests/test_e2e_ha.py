"""HA e2e across REAL process boundaries: one substrate host process serving
the API over HTTPS (host-minted CA, client-verified), TWO operator OS
processes racing one lease, a kill -9 of the elected leader, and the standby
process converging the same jobs — plus the dual failure mode: the HOST
kill -9'd mid-job and restarted from its durable state dir.

Parity target: the reference's real deployment shape — operator pods with
--enable-leader-election against a kube-apiserver
(cmd/training-operator.v1/main.go:134-166, mgr.Start leader election), where
leader election protects against a *process* dying, not an in-process
detach. Round-3 review called out that the previous leader-election tests
never crossed a process boundary; this one is ≥3 OS processes over
localhost sockets.
"""

import os
import signal
import time

import pytest

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import Container, PodTemplateSpec, ReplicaSpec
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta
from training_operator_tpu.cluster.httpapi import RemoteAPIServer
from training_operator_tpu.cluster.runtime import ANNOTATION_SIM_DURATION
from training_operator_tpu.controllers.leader import DEFAULT_LEASE_NAME
from training_operator_tpu.sdk.client import TrainingClient

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LEASE_SECONDS = 2.0  # short so dead-leader takeover keeps the test fast


def _spawn(args):
    from training_operator_tpu.utils.procio import spawn_module_process

    return spawn_module_process(args, REPO_ROOT)


def _read_line_with_prefix(proc, prefix, timeout=30.0):
    from training_operator_tpu.utils.procio import read_announcement

    return read_announcement(proc, prefix, timeout=timeout, error=AssertionError)


from training_operator_tpu.utils.procio import kill_all as _kill_all


def _job(name: str, run_seconds: float) -> JAXJob:
    return JAXJob(
        metadata=ObjectMeta(name=name),
        replica_specs={
            "Worker": ReplicaSpec(
                replicas=2,
                template=PodTemplateSpec(
                    containers=[Container(name="jax", image="trainer",
                                          resources={"cpu": 1.0})],
                    annotations={ANNOTATION_SIM_DURATION: str(run_seconds)},
                ),
            )
        },
    )


from test_e2e_process import _free_port  # shared e2e helper (rootdir import)


def test_host_killed_restarts_from_disk_operators_reconnect(tmp_path):
    """Durability e2e (VERDICT r4 missing #3): kill -9 the HOST mid-job,
    restart it on the same port from its --state-dir, and assert both
    operator processes survive the outage (RemoteRuntime.run_forever
    backoff + watch re-subscribe exercised for real) and the restored job
    converges. The reference gets this for free from etcd; here the
    snapshot+journal HostStore supplies it."""
    inv = tmp_path / "cluster.json"
    inv.write_text('{"cpu_pools": [{"nodes": 2, "cpu_per_node": 8.0}]}')
    state_dir = tmp_path / "state"
    port = _free_port()
    host_args = [
        "--role", "host", "--serve-port", str(port),
        "--gang-scheduler-name", "none", "--cluster", str(inv),
        "--state-dir", str(state_dir),
    ]

    host = _spawn(host_args)
    procs = [host]
    try:
        url = _read_line_with_prefix(host, "WIRE_API")
        ca = _read_line_with_prefix(host, "WIRE_CA")
        assert url.startswith("https://"), url
        operators = {}
        for ident in ("op-a", "op-b"):
            p = _spawn([
                "--role", "operator", "--api-server", url, "--ca-cert", ca,
                "--enable-scheme", "jax", "--gang-scheduler-name", "none",
                "--enable-leader-election", "--leader-identity", ident,
                "--leader-lease-seconds", str(LEASE_SECONDS),
            ])
            procs.append(p)
            operators[ident] = p
            _read_line_with_prefix(p, "OPERATOR_UP")

        client = TrainingClient(url, ca_file=ca)
        # Job long enough that the host dies while it is RUNNING.
        client.create_job(_job("durable-job", run_seconds=8.0))
        client.wait_for_job_conditions(
            "durable-job", expected_conditions=(capi.JobConditionType.RUNNING,),
            timeout=30,
        )

        # kill -9 the host mid-job; the cluster "disappears".
        host.send_signal(signal.SIGKILL)
        host.communicate()
        time.sleep(1.0)  # let the operators hit their retry/backoff arm

        # Restart the host from disk on the same port.
        host2 = _spawn(host_args)
        procs.append(host2)
        url2 = _read_line_with_prefix(host2, "WIRE_API")
        assert url2 == url
        # The CA lives in the state dir and is REUSED on restart, so the
        # operators' standing CA pins stay valid across the outage.
        assert _read_line_with_prefix(host2, "WIRE_CA") == ca

        # The restored job converges, driven by the SAME operator
        # processes reconnecting over the wire (no operator restarts).
        job = client.wait_for_job_conditions(
            "durable-job",
            expected_conditions=(capi.JobConditionType.SUCCEEDED,),
            timeout=90,
        )
        assert capi.is_succeeded(job.status)
        assert all(operators[i].poll() is None for i in operators), (
            "an operator process died during the host outage"
        )

        # Post-restart control plane is fully live: brand-new work converges.
        client.create_job(_job("post-restart-job", run_seconds=0.5))
        job2 = client.wait_for_job_conditions(
            "post-restart-job",
            expected_conditions=(capi.JobConditionType.SUCCEEDED,),
            timeout=60,
        )
        assert capi.is_succeeded(job2.status)

        # The job's pods were restored (not recreated): still exactly 2.
        assert len(client.get_job_pods("durable-job")) == 2
    finally:
        _kill_all(procs)


def test_leader_killed_standby_process_converges(tmp_path):
    inv = tmp_path / "cluster.json"
    inv.write_text('{"cpu_pools": [{"nodes": 2, "cpu_per_node": 8.0}]}')

    host = _spawn([
        "--role", "host", "--serve-port", "0",
        "--gang-scheduler-name", "none", "--cluster", str(inv),
    ])
    procs = [host]
    try:
        url = _read_line_with_prefix(host, "WIRE_API")
        ca = _read_line_with_prefix(host, "WIRE_CA")
        assert url.startswith("https://"), url
        operators = {}
        for ident in ("op-a", "op-b"):
            p = _spawn([
                "--role", "operator", "--api-server", url, "--ca-cert", ca,
                "--enable-scheme", "jax", "--gang-scheduler-name", "none",
                "--enable-leader-election", "--leader-identity", ident,
                "--leader-lease-seconds", str(LEASE_SECONDS),
            ])
            procs.append(p)
            operators[ident] = p
            _read_line_with_prefix(p, "OPERATOR_UP")

        api = RemoteAPIServer(url, timeout=10.0, ca_file=ca)
        client = TrainingClient(url, ca_file=ca)

        # One operator must win the lease.
        deadline = time.monotonic() + 30
        lease = None
        while time.monotonic() < deadline:
            lease = api.try_get("Lease", "operator-system", DEFAULT_LEASE_NAME)
            if lease is not None and lease.holder in operators:
                break
            time.sleep(0.1)
        assert lease is not None and lease.holder in operators, lease
        leader, standby = lease.holder, next(i for i in operators if i != lease.holder)

        # Submit a job long enough to outlive the leader, prove it reaches
        # Running under the current leader...
        client.create_job(_job("ha-job", run_seconds=6.0))
        client.wait_for_job_conditions(
            "ha-job", expected_conditions=(capi.JobConditionType.RUNNING,),
            timeout=30,
        )

        # ...then kill -9 the leader process mid-job.
        operators[leader].send_signal(signal.SIGKILL)
        operators[leader].communicate()

        # The standby takes over the expired lease and converges the job.
        job = client.wait_for_job_conditions(
            "ha-job", expected_conditions=(capi.JobConditionType.SUCCEEDED,),
            timeout=60,
        )
        assert capi.is_succeeded(job.status)

        lease = api.get("Lease", "operator-system", DEFAULT_LEASE_NAME)
        assert lease.holder == standby
        assert lease.transitions >= 1

        # The new leader also handles brand-new work end to end.
        client.create_job(_job("ha-job-2", run_seconds=0.5))
        job2 = client.wait_for_job_conditions(
            "ha-job-2", expected_conditions=(capi.JobConditionType.SUCCEEDED,),
            timeout=60,
        )
        assert capi.is_succeeded(job2.status)

        # Exactly one live operator did all of this; its pods and statuses
        # came over the wire.
        assert operators[standby].poll() is None
        assert len(client.get_job_pods("ha-job")) == 2
    finally:
        _kill_all(procs)


def test_token_authed_wire_deployment(tmp_path):
    """The full wire deployment with BOTH auth layers on: TLS (transport)
    + bearer token (authn). An operator with the right token converges
    work; a client with a wrong token is rejected loudly (PermissionError,
    not a silent retry)."""
    inv = tmp_path / "cluster.json"
    inv.write_text('{"cpu_pools": [{"nodes": 2, "cpu_per_node": 8.0}]}')

    host = _spawn([
        "--role", "host", "--serve-port", "0",
        "--gang-scheduler-name", "none", "--cluster", str(inv),
        "--api-token", "wire-secret",
    ])
    procs = [host]
    try:
        url = _read_line_with_prefix(host, "WIRE_API")
        ca = _read_line_with_prefix(host, "WIRE_CA")
        op = _spawn([
            "--role", "operator", "--api-server", url, "--ca-cert", ca,
            "--api-token", "wire-secret",
            "--enable-scheme", "jax", "--gang-scheduler-name", "none",
        ])
        procs.append(op)
        _read_line_with_prefix(op, "OPERATOR_UP")

        client = TrainingClient(url, api_token="wire-secret", ca_file=ca)
        client.create_job(_job("authed-job", run_seconds=0.5))
        job = client.wait_for_job_conditions(
            "authed-job", expected_conditions=(capi.JobConditionType.SUCCEEDED,),
            timeout=60,
        )
        assert capi.is_succeeded(job.status)

        # Wrong token: loud config error on a verified TLS channel.
        bad = RemoteAPIServer(url, timeout=10.0, token="nope", ca_file=ca)
        with pytest.raises(PermissionError):
            bad.list("JAXJob")
        # Missing token: same.
        anon = RemoteAPIServer(url, timeout=10.0, ca_file=ca)
        with pytest.raises(PermissionError):
            anon.list("JAXJob")
    finally:
        _kill_all(procs)


def _wait_job(client, name, pred, timeout):
    """Poll a failover client for a job condition, absorbing the rotation
    errors a dead address surfaces mid-failover."""
    from training_operator_tpu.cluster.httpapi import ApiUnavailableError

    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            last = client.try_get("JAXJob", "default", name)
        except ApiUnavailableError:
            last = None
        if last is not None and pred(last):
            return last
        time.sleep(0.1)
    raise AssertionError(f"job {name} never satisfied predicate; last={last}")


def test_dual_failure_standby_promoted_then_new_primary_killed(tmp_path):
    """PR 9 dual-failure e2e, ≥3 OS processes over localhost sockets:

      host A (primary, durable)  <-WAL-  standby B (durable)  <-wire- op C

    Kill -9 A mid-job -> B auto-promotes (lease expiry) and the SAME
    operator process converges the job over the failover client. Writes
    accepted on B's new epoch are then put to the sword: kill -9 B and
    restart it from ITS OWN state dir — nothing accepted on either epoch
    is lost, and the test-process client that stayed connected throughout
    relists at most once (B's restart is an unchained incarnation; the
    A->B failover itself heals by chained delta). The training_wire_resume
    counters live in the SERVER processes here, so the relist evidence is
    client-side: the watch client's relist arm goes through its own
    `.list`, recorded for the whole scenario."""
    inv = tmp_path / "cluster.json"
    inv.write_text('{"cpu_pools": [{"nodes": 2, "cpu_per_node": 8.0}]}')
    state_a = tmp_path / "state-a"
    state_b = tmp_path / "state-b"
    port_a, port_b = _free_port(), _free_port()

    host_a = _spawn([
        "--role", "host", "--serve-port", str(port_a), "--insecure",
        "--gang-scheduler-name", "none", "--cluster", str(inv),
        "--state-dir", str(state_a),
        "--replication-lease-seconds", "1", "--leader-identity", "host-a",
    ])
    procs = [host_a]
    try:
        url_a = _read_line_with_prefix(host_a, "WIRE_API")
        standby_b = _spawn([
            "--standby-of", url_a, "--serve-port", str(port_b), "--insecure",
            "--gang-scheduler-name", "none", "--state-dir", str(state_b),
            "--replication-lease-seconds", "1",
            "--replication-poll-timeout", "0.3",
            "--leader-identity", "host-b",
        ])
        procs.append(standby_b)
        url_b = _read_line_with_prefix(standby_b, "WIRE_API")

        operator = _spawn([
            "--role", "operator", "--api-server", f"{url_a},{url_b}",
            "--enable-scheme", "jax", "--gang-scheduler-name", "none",
        ])
        procs.append(operator)
        _read_line_with_prefix(operator, "OPERATOR_UP")

        client = RemoteAPIServer(addresses=[url_a, url_b], timeout=5.0)
        # A DEDICATED client for the watch, so every `.list` it makes is a
        # relist (the CRUD/poll client below lists on purpose).
        watcher = RemoteAPIServer(addresses=[url_a, url_b], timeout=5.0)
        wq = watcher.watch(kinds=["JAXJob"])
        relists = []
        orig_list = watcher.list
        watcher.list = lambda *a, **k: relists.append(a) or orig_list(*a, **k)

        def drain():
            from training_operator_tpu.cluster.httpapi import (
                ApiUnavailableError,
            )

            try:
                return wq.drain(timeout=0.2)
            except ApiUnavailableError:
                return []

        client.create(_job("dual-1", run_seconds=6.0))
        _wait_job(client, "dual-1", lambda j: capi.is_running(j.status),
                  timeout=30)
        drain()

        # -- failure one: the primary dies mid-job ----------------------
        host_a.send_signal(signal.SIGKILL)
        host_a.communicate()
        assert _read_line_with_prefix(standby_b, "PROMOTED", timeout=30.0) \
            == "host-b"

        job1 = _wait_job(client, "dual-1",
                         lambda j: capi.is_succeeded(j.status), timeout=60)
        assert capi.is_succeeded(job1.status)
        # A write accepted on the NEW epoch (B's primacy).
        from training_operator_tpu.cluster.httpapi import ApiUnavailableError

        deadline = time.monotonic() + 30
        while True:
            try:
                client.create(_job("dual-2", run_seconds=0.5))
                break
            except ApiUnavailableError:
                assert time.monotonic() < deadline
                time.sleep(0.1)
        _wait_job(client, "dual-2",
                  lambda j: capi.is_succeeded(j.status), timeout=60)
        # The surviving watch session observed the post-failover history
        # (delta over the epoch chain), without relisting.
        deadline = time.monotonic() + 15
        seen = set()
        while time.monotonic() < deadline:
            seen |= {e.obj.metadata.name for e in drain()}
            if "dual-2" in seen:
                break
        assert "dual-2" in seen, f"watch never saw the post-failover job: {seen}"
        assert relists == [], (
            "the A->B failover forced a relist on a chained watermark"
        )

        # -- failure two: the NEW primary dies and restarts from disk ---
        standby_b.send_signal(signal.SIGKILL)
        standby_b.communicate()
        host_b2 = _spawn([
            "--role", "host", "--serve-port", str(port_b), "--insecure",
            "--gang-scheduler-name", "none", "--cluster", str(inv),
            "--state-dir", str(state_b),
            "--replication-lease-seconds", "1", "--leader-identity", "host-b",
        ])
        procs.append(host_b2)
        assert _read_line_with_prefix(host_b2, "WIRE_API") == url_b

        # NOTHING accepted on either epoch was lost: the job driven by the
        # old primary AND the one accepted only by the promoted standby
        # both survive B's own death, terminal state intact.
        deadline = time.monotonic() + 30
        names = {}
        while time.monotonic() < deadline:
            try:
                names = {j.metadata.name: j for j in client.list("JAXJob")}
                if {"dual-1", "dual-2"} <= set(names):
                    break
            except ApiUnavailableError:
                pass
            time.sleep(0.2)
        assert {"dual-1", "dual-2"} <= set(names), sorted(names)
        assert capi.is_succeeded(names["dual-1"].status)
        assert capi.is_succeeded(names["dual-2"].status)

        # The surviving operator converges brand-new work end to end.
        deadline = time.monotonic() + 30
        while True:
            try:
                client.create(_job("dual-3", run_seconds=0.5))
                break
            except ApiUnavailableError:
                assert time.monotonic() < deadline
                time.sleep(0.1)
        _wait_job(client, "dual-3",
                  lambda j: capi.is_succeeded(j.status), timeout=60)
        assert operator.poll() is None, "the operator process died"

        # Drain until the watch has healed over B's restart, then count
        # the damage: the chained A->B failover cost ZERO relists, B's
        # unchained disk restart at most ONE — a third never happens.
        deadline = time.monotonic() + 15
        healed = False
        while time.monotonic() < deadline:
            if any(e.obj.metadata.name == "dual-3" for e in drain()):
                healed = True
                break
        assert healed, "the watch never healed across B's restart"
        # One relist EPISODE walks every registered kind once; count
        # episodes by the watched kind's appearances.
        episodes = sum(1 for a in relists if a and a[0] == "JAXJob")
        assert episodes <= 1, (
            f"{episodes} relist episodes for one unchained restart: {relists}"
        )
    finally:
        _kill_all(procs)
