"""HA e2e across REAL process boundaries: one substrate host process serving
the API over HTTPS (host-minted CA, client-verified), TWO operator OS
processes racing one lease, a kill -9 of the elected leader, and the standby
process converging the same jobs — plus the dual failure mode: the HOST
kill -9'd mid-job and restarted from its durable state dir.

Parity target: the reference's real deployment shape — operator pods with
--enable-leader-election against a kube-apiserver
(cmd/training-operator.v1/main.go:134-166, mgr.Start leader election), where
leader election protects against a *process* dying, not an in-process
detach. Round-3 review called out that the previous leader-election tests
never crossed a process boundary; this one is ≥3 OS processes over
localhost sockets.
"""

import os
import signal
import time

import pytest

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import Container, PodTemplateSpec, ReplicaSpec
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta
from training_operator_tpu.cluster.httpapi import RemoteAPIServer
from training_operator_tpu.cluster.runtime import ANNOTATION_SIM_DURATION
from training_operator_tpu.controllers.leader import DEFAULT_LEASE_NAME
from training_operator_tpu.sdk.client import TrainingClient

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LEASE_SECONDS = 2.0  # short so dead-leader takeover keeps the test fast


def _spawn(args):
    from training_operator_tpu.utils.procio import spawn_module_process

    return spawn_module_process(args, REPO_ROOT)


def _read_line_with_prefix(proc, prefix, timeout=30.0):
    from training_operator_tpu.utils.procio import read_announcement

    return read_announcement(proc, prefix, timeout=timeout, error=AssertionError)


from training_operator_tpu.utils.procio import kill_all as _kill_all


def _job(name: str, run_seconds: float) -> JAXJob:
    return JAXJob(
        metadata=ObjectMeta(name=name),
        replica_specs={
            "Worker": ReplicaSpec(
                replicas=2,
                template=PodTemplateSpec(
                    containers=[Container(name="jax", image="trainer",
                                          resources={"cpu": 1.0})],
                    annotations={ANNOTATION_SIM_DURATION: str(run_seconds)},
                ),
            )
        },
    )


from test_e2e_process import _free_port  # shared e2e helper (rootdir import)


def test_host_killed_restarts_from_disk_operators_reconnect(tmp_path):
    """Durability e2e (VERDICT r4 missing #3): kill -9 the HOST mid-job,
    restart it on the same port from its --state-dir, and assert both
    operator processes survive the outage (RemoteRuntime.run_forever
    backoff + watch re-subscribe exercised for real) and the restored job
    converges. The reference gets this for free from etcd; here the
    snapshot+journal HostStore supplies it."""
    inv = tmp_path / "cluster.json"
    inv.write_text('{"cpu_pools": [{"nodes": 2, "cpu_per_node": 8.0}]}')
    state_dir = tmp_path / "state"
    port = _free_port()
    host_args = [
        "--role", "host", "--serve-port", str(port),
        "--gang-scheduler-name", "none", "--cluster", str(inv),
        "--state-dir", str(state_dir),
    ]

    host = _spawn(host_args)
    procs = [host]
    try:
        url = _read_line_with_prefix(host, "WIRE_API")
        ca = _read_line_with_prefix(host, "WIRE_CA")
        assert url.startswith("https://"), url
        operators = {}
        for ident in ("op-a", "op-b"):
            p = _spawn([
                "--role", "operator", "--api-server", url, "--ca-cert", ca,
                "--enable-scheme", "jax", "--gang-scheduler-name", "none",
                "--enable-leader-election", "--leader-identity", ident,
                "--leader-lease-seconds", str(LEASE_SECONDS),
            ])
            procs.append(p)
            operators[ident] = p
            _read_line_with_prefix(p, "OPERATOR_UP")

        client = TrainingClient(url, ca_file=ca)
        # Job long enough that the host dies while it is RUNNING.
        client.create_job(_job("durable-job", run_seconds=8.0))
        client.wait_for_job_conditions(
            "durable-job", expected_conditions=(capi.JobConditionType.RUNNING,),
            timeout=30,
        )

        # kill -9 the host mid-job; the cluster "disappears".
        host.send_signal(signal.SIGKILL)
        host.communicate()
        time.sleep(1.0)  # let the operators hit their retry/backoff arm

        # Restart the host from disk on the same port.
        host2 = _spawn(host_args)
        procs.append(host2)
        url2 = _read_line_with_prefix(host2, "WIRE_API")
        assert url2 == url
        # The CA lives in the state dir and is REUSED on restart, so the
        # operators' standing CA pins stay valid across the outage.
        assert _read_line_with_prefix(host2, "WIRE_CA") == ca

        # The restored job converges, driven by the SAME operator
        # processes reconnecting over the wire (no operator restarts).
        job = client.wait_for_job_conditions(
            "durable-job",
            expected_conditions=(capi.JobConditionType.SUCCEEDED,),
            timeout=90,
        )
        assert capi.is_succeeded(job.status)
        assert all(operators[i].poll() is None for i in operators), (
            "an operator process died during the host outage"
        )

        # Post-restart control plane is fully live: brand-new work converges.
        client.create_job(_job("post-restart-job", run_seconds=0.5))
        job2 = client.wait_for_job_conditions(
            "post-restart-job",
            expected_conditions=(capi.JobConditionType.SUCCEEDED,),
            timeout=60,
        )
        assert capi.is_succeeded(job2.status)

        # The job's pods were restored (not recreated): still exactly 2.
        assert len(client.get_job_pods("durable-job")) == 2
    finally:
        _kill_all(procs)


def test_leader_killed_standby_process_converges(tmp_path):
    inv = tmp_path / "cluster.json"
    inv.write_text('{"cpu_pools": [{"nodes": 2, "cpu_per_node": 8.0}]}')

    host = _spawn([
        "--role", "host", "--serve-port", "0",
        "--gang-scheduler-name", "none", "--cluster", str(inv),
    ])
    procs = [host]
    try:
        url = _read_line_with_prefix(host, "WIRE_API")
        ca = _read_line_with_prefix(host, "WIRE_CA")
        assert url.startswith("https://"), url
        operators = {}
        for ident in ("op-a", "op-b"):
            p = _spawn([
                "--role", "operator", "--api-server", url, "--ca-cert", ca,
                "--enable-scheme", "jax", "--gang-scheduler-name", "none",
                "--enable-leader-election", "--leader-identity", ident,
                "--leader-lease-seconds", str(LEASE_SECONDS),
            ])
            procs.append(p)
            operators[ident] = p
            _read_line_with_prefix(p, "OPERATOR_UP")

        api = RemoteAPIServer(url, timeout=10.0, ca_file=ca)
        client = TrainingClient(url, ca_file=ca)

        # One operator must win the lease.
        deadline = time.monotonic() + 30
        lease = None
        while time.monotonic() < deadline:
            lease = api.try_get("Lease", "operator-system", DEFAULT_LEASE_NAME)
            if lease is not None and lease.holder in operators:
                break
            time.sleep(0.1)
        assert lease is not None and lease.holder in operators, lease
        leader, standby = lease.holder, next(i for i in operators if i != lease.holder)

        # Submit a job long enough to outlive the leader, prove it reaches
        # Running under the current leader...
        client.create_job(_job("ha-job", run_seconds=6.0))
        client.wait_for_job_conditions(
            "ha-job", expected_conditions=(capi.JobConditionType.RUNNING,),
            timeout=30,
        )

        # ...then kill -9 the leader process mid-job.
        operators[leader].send_signal(signal.SIGKILL)
        operators[leader].communicate()

        # The standby takes over the expired lease and converges the job.
        job = client.wait_for_job_conditions(
            "ha-job", expected_conditions=(capi.JobConditionType.SUCCEEDED,),
            timeout=60,
        )
        assert capi.is_succeeded(job.status)

        lease = api.get("Lease", "operator-system", DEFAULT_LEASE_NAME)
        assert lease.holder == standby
        assert lease.transitions >= 1

        # The new leader also handles brand-new work end to end.
        client.create_job(_job("ha-job-2", run_seconds=0.5))
        job2 = client.wait_for_job_conditions(
            "ha-job-2", expected_conditions=(capi.JobConditionType.SUCCEEDED,),
            timeout=60,
        )
        assert capi.is_succeeded(job2.status)

        # Exactly one live operator did all of this; its pods and statuses
        # came over the wire.
        assert operators[standby].poll() is None
        assert len(client.get_job_pods("ha-job")) == 2
    finally:
        _kill_all(procs)


def test_token_authed_wire_deployment(tmp_path):
    """The full wire deployment with BOTH auth layers on: TLS (transport)
    + bearer token (authn). An operator with the right token converges
    work; a client with a wrong token is rejected loudly (PermissionError,
    not a silent retry)."""
    inv = tmp_path / "cluster.json"
    inv.write_text('{"cpu_pools": [{"nodes": 2, "cpu_per_node": 8.0}]}')

    host = _spawn([
        "--role", "host", "--serve-port", "0",
        "--gang-scheduler-name", "none", "--cluster", str(inv),
        "--api-token", "wire-secret",
    ])
    procs = [host]
    try:
        url = _read_line_with_prefix(host, "WIRE_API")
        ca = _read_line_with_prefix(host, "WIRE_CA")
        op = _spawn([
            "--role", "operator", "--api-server", url, "--ca-cert", ca,
            "--api-token", "wire-secret",
            "--enable-scheme", "jax", "--gang-scheduler-name", "none",
        ])
        procs.append(op)
        _read_line_with_prefix(op, "OPERATOR_UP")

        client = TrainingClient(url, api_token="wire-secret", ca_file=ca)
        client.create_job(_job("authed-job", run_seconds=0.5))
        job = client.wait_for_job_conditions(
            "authed-job", expected_conditions=(capi.JobConditionType.SUCCEEDED,),
            timeout=60,
        )
        assert capi.is_succeeded(job.status)

        # Wrong token: loud config error on a verified TLS channel.
        bad = RemoteAPIServer(url, timeout=10.0, token="nope", ca_file=ca)
        with pytest.raises(PermissionError):
            bad.list("JAXJob")
        # Missing token: same.
        anon = RemoteAPIServer(url, timeout=10.0, ca_file=ca)
        with pytest.raises(PermissionError):
            anon.list("JAXJob")
    finally:
        _kill_all(procs)
