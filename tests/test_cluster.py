"""Virtual cluster substrate tests: API server semantics, watch echo,
scheduler binding, kubelet lifecycle, inventory topology."""

import pytest

from training_operator_tpu.api.common import Container, PodTemplateSpec
from training_operator_tpu.api.jobs import ObjectMeta
from training_operator_tpu.cluster.apiserver import (
    AlreadyExistsError,
    APIServer,
    ConflictError,
    NotFoundError,
)
from training_operator_tpu.cluster.inventory import (
    TPU_RESOURCE,
    make_cpu_pool,
    make_gpu_pool,
    make_tpu_pool,
)
from training_operator_tpu.cluster.objects import Pod, PodPhase
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
)


def make_pod(name, cpu=1.0, labels=None, **kw):
    return Pod(
        metadata=ObjectMeta(name=name, labels=labels or {}),
        spec=PodTemplateSpec(
            containers=[Container(name="main", image="img", resources={"cpu": cpu})], **kw
        ),
    )


class TestAPIServer:
    def test_create_get_delete(self):
        api = APIServer()
        api.create(make_pod("p1"))
        assert api.get("Pod", "default", "p1").name == "p1"
        api.delete("Pod", "default", "p1")
        with pytest.raises(NotFoundError):
            api.get("Pod", "default", "p1")

    def test_duplicate_create_rejected(self):
        api = APIServer()
        api.create(make_pod("p1"))
        with pytest.raises(AlreadyExistsError):
            api.create(make_pod("p1"))

    def test_optimistic_concurrency(self):
        api = APIServer()
        pod = api.create(make_pod("p1"))
        import copy

        stale = copy.deepcopy(pod)
        api.update(pod)  # bumps rv
        with pytest.raises(ConflictError):
            api.update(stale)

    def test_watch_events_are_queued_not_synchronous(self):
        api = APIServer()
        w = api.watch(["Pod"])
        api.create(make_pod("p1"))
        api.create(make_pod("p2"))
        evs = w.drain()
        assert [e.type for e in evs] == ["Added", "Added"]
        assert w.drain() == []

    def test_watch_kind_filter(self):
        api = APIServer()
        w = api.watch(["Service"])
        api.create(make_pod("p1"))
        assert w.drain() == []

    def test_list_with_label_selector(self):
        api = APIServer()
        api.create(make_pod("a", labels={"job": "x"}))
        api.create(make_pod("b", labels={"job": "y"}))
        assert [p.name for p in api.list("Pod", "default", {"job": "x"})] == ["a"]

    def test_admission_hook_rejects(self):
        api = APIServer()

        def deny(obj):
            raise ValueError("nope")

        api.register_admission("Pod", deny)
        with pytest.raises(ValueError):
            api.create(make_pod("p1"))


class TestCopyOnRead:
    """The aliasing-proof semantics real k8s has: reads are copies, in-place
    mutation never reaches the store, version checks have no identity escape."""

    def test_get_returns_copy(self):
        api = APIServer()
        api.create(make_pod("p1"))
        read = api.get("Pod", "default", "p1")
        read.status.phase = PodPhase.FAILED
        read.metadata.labels["injected"] = "yes"
        fresh = api.get("Pod", "default", "p1")
        assert fresh.status.phase == PodPhase.PENDING
        assert "injected" not in fresh.metadata.labels

    def test_list_returns_copies(self):
        api = APIServer()
        api.create(make_pod("p1"))
        api.list("Pod")[0].node_name = "hacked"
        assert api.get("Pod", "default", "p1").node_name == ""

    def test_create_detaches_caller_object(self):
        api = APIServer()
        pod = make_pod("p1")
        api.create(pod)
        pod.status.phase = PodPhase.FAILED  # caller-side mutation
        assert api.get("Pod", "default", "p1").status.phase == PodPhase.PENDING

    def test_same_identity_stale_write_conflicts(self):
        """The old `current is not obj` escape let a component that held the
        live instance skip the version check entirely; with copies + strict
        comparison, a stale write always conflicts."""
        api = APIServer()
        api.create(make_pod("p1"))
        a = api.get("Pod", "default", "p1")
        b = api.get("Pod", "default", "p1")
        a.node_name = "n1"
        api.update(a)
        b.node_name = "n2"
        with pytest.raises(ConflictError):
            api.update(b)  # lost update surfaced, not silently applied
        assert api.get("Pod", "default", "p1").node_name == "n1"

    def test_label_index_tracks_updates(self):
        api = APIServer()
        api.create(make_pod("a", labels={"job": "x", "role": "w"}))
        api.create(make_pod("b", labels={"job": "x", "role": "m"}))
        assert {p.name for p in api.list("Pod", None, {"job": "x"})} == {"a", "b"}
        assert [p.name for p in api.list("Pod", None, {"job": "x", "role": "m"})] == ["b"]
        # Relabel a; the index must follow.
        a = api.get("Pod", "default", "a")
        a.metadata.labels["job"] = "y"
        api.update(a)
        assert [p.name for p in api.list("Pod", None, {"job": "x"})] == ["b"]
        assert [p.name for p in api.list("Pod", None, {"job": "y"})] == ["a"]
        api.delete("Pod", "default", "b")
        assert api.list("Pod", None, {"job": "x"}) == []

    def test_shared_informer_lags_then_converges(self):
        cluster = Cluster(VirtualClock())
        cluster.api.create(make_pod("p1"))
        # Not yet synced: the informer hasn't applied the Added event.
        assert cluster.informer.get("Pod", "default", "p1") is None
        cluster.step()
        cached = cluster.informer.get("Pod", "default", "p1")
        assert cached is not None and cached.name == "p1"
        # Store mutations don't leak into the cache between syncs.
        live = cluster.api.get("Pod", "default", "p1")
        live.node_name = "n1"
        cluster.api.update(live)
        assert cluster.informer.get("Pod", "default", "p1").node_name == ""
        cluster.step()
        assert cluster.informer.get("Pod", "default", "p1").node_name == "n1"


class TestInventory:
    def test_tpu_slice_topology(self):
        nodes = make_tpu_pool(num_slices=2, slice_topology="4x4", chips_per_host=4)
        assert len(nodes) == 8  # 16 chips / 4 per host x 2 slices
        n0 = nodes[0]
        assert n0.capacity[TPU_RESOURCE] == 4.0
        assert n0.accelerator.tpu_slice == "slice-0"
        assert n0.accelerator.ici_coords == [0, 0]
        assert nodes[1].accelerator.ici_coords == [1, 0]
        assert nodes[3].accelerator.ici_coords == [3, 0]

    def test_gpu_nvlink_domains(self):
        nodes = make_gpu_pool(num_nodes=8, nodes_per_nvlink_domain=4)
        assert nodes[0].accelerator.nvlink_domain == "nvl-0"
        assert nodes[4].accelerator.nvlink_domain == "nvl-1"

    def test_bad_topology_rejected(self):
        with pytest.raises(ValueError):
            make_tpu_pool(1, slice_topology="3x3", chips_per_host=4)


class TestSchedulerAndKubelet:
    def test_pod_binds_and_runs(self):
        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_cpu_pool(2))
        DefaultScheduler(cluster)
        SimKubelet(cluster, start_latency=0.5)
        pod = make_pod("p1")
        cluster.api.create(pod)
        assert cluster.run_until(
            lambda: cluster.api.get("Pod", "default", "p1").status.phase == PodPhase.RUNNING,
            timeout=10,
        )
        # Copy-on-read: the submitted object never mutates — re-read.
        live = cluster.live(pod)
        assert live.node_name.startswith("cpu-")
        assert live.status.start_time is not None

    def test_node_selector_respected(self):
        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_cpu_pool(2))
        DefaultScheduler(cluster)
        pod = make_pod("p1", node_selector={"kubernetes.io/hostname": "cpu-1"})
        cluster.api.create(pod)
        cluster.run_until(lambda: cluster.live(pod).node_name != "", timeout=5)
        assert cluster.live(pod).node_name == "cpu-1"

    def test_resource_exhaustion_leaves_pod_pending(self):
        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_cpu_pool(1, cpu_per_node=2.0))
        DefaultScheduler(cluster)
        SimKubelet(cluster)
        cluster.api.create(make_pod("big1", cpu=2.0))
        cluster.api.create(make_pod("big2", cpu=2.0))
        cluster.run_for(1.0)
        pods = {p.name: p for p in cluster.api.list("Pod")}
        bound = [p for p in pods.values() if p.node_name]
        assert len(bound) == 1

    def test_sim_duration_completes_pod(self):
        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_cpu_pool(1))
        DefaultScheduler(cluster)
        SimKubelet(cluster)
        pod = make_pod("p1")
        pod.spec.annotations[ANNOTATION_SIM_DURATION] = "1.0"
        cluster.api.create(pod)
        assert cluster.run_until(
            lambda: cluster.live(pod).status.phase == PodPhase.SUCCEEDED, timeout=30
        )
        assert cluster.live(pod).status.container_statuses[0].exit_code == 0

    def test_failed_pod_releases_resources(self):
        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_cpu_pool(1, cpu_per_node=2.0))
        DefaultScheduler(cluster)
        SimKubelet(cluster)
        p1 = make_pod("p1", cpu=2.0)
        p1.spec.annotations[ANNOTATION_SIM_DURATION] = "0.5"
        cluster.api.create(p1)
        p2 = make_pod("p2", cpu=2.0)
        cluster.api.create(p2)
        assert cluster.run_until(
            lambda: cluster.live(p2).status.phase == PodPhase.RUNNING, timeout=30
        )
