"""ResourceVersion-resumable watch sessions (the informer resume contract).

The wire used to heal EVERY reaped/reconnected session by relisting every
kind, every object — O(cluster) per reconnect. These tests pin the O(delta)
protocol: clients present per-kind watermarks on resubscribe, the server
replays only the missed events from its bounded per-kind ring, and the
"410 too old → full relist" arm fires only when the ring was outrun (and
exactly once — a relist rebases the watermarks so the next reconnect is a
delta again). Observability rides the `training_wire_resume_*` counters,
the same ones the `wire_resume` bench block reports.
"""

import pytest

from training_operator_tpu.api.jobs import ObjectMeta
from training_operator_tpu.cluster import wire
from training_operator_tpu.cluster.apiserver import WatchEvent
from training_operator_tpu.cluster.httpapi import (
    ApiHTTPServer,
    CachedReadAPI,
    RemoteAPIServer,
)
from training_operator_tpu.cluster.objects import ConfigMap
from training_operator_tpu.cluster.runtime import Cluster
from training_operator_tpu.utils import metrics


def _cm(i):
    return ConfigMap(metadata=ObjectMeta(name=f"cm-{i}"), data={"i": str(i)})


@pytest.fixture()
def served():
    cluster = Cluster()
    server = ApiHTTPServer(cluster.api, port=0)
    try:
        yield cluster, server
    finally:
        server.close()


def _counters():
    return {
        "delta": metrics.wire_resume_delta.total(),
        "replayed": metrics.wire_resume_replayed.total(),
        "too_old": metrics.wire_resume_too_old.total(),
    }


def _deltas(before):
    now = _counters()
    return {k: now[k] - before[k] for k in before}


class TestDeltaResume:
    def test_reap_heals_by_delta_not_relist(self, served):
        """The steady case the acceptance pins: reconnect after a reap
        replays ONLY the missed events — delta_total climbs, too_old stays
        zero, and nothing already observed is re-delivered."""
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=5.0)
        wq = remote.watch(kinds=["ConfigMap"])
        for i in range(5):
            cluster.api.create(_cm(i))
        assert len(wq.drain(timeout=1.0)) == 5  # watermark now current

        server.reap_all_sessions()
        for i in range(5, 8):  # written while the session is gone
            cluster.api.create(_cm(i))

        before = _counters()
        lists = []
        orig_list = remote.list
        remote.list = lambda *a, **k: lists.append(a) or orig_list(*a, **k)
        events = wq.drain(timeout=1.0)
        remote.list = orig_list
        assert sorted(e.obj.metadata.name for e in events) == [
            "cm-5", "cm-6", "cm-7"
        ], "delta resume must replay exactly the missed events"
        got = _deltas(before)
        assert got["delta"] == 1 and got["replayed"] == 3 and got["too_old"] == 0
        assert lists == [], "a delta resume must not relist anything"

    def test_watermark_survives_session_reap(self, served):
        """The watermark lives client-side, not in the server session:
        repeated reaps each heal by delta, never degrading to relist."""
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=5.0)
        wq = remote.watch(kinds=["ConfigMap"])
        before = _counters()
        for round_ in range(3):
            cluster.api.create(_cm(round_))
            assert len(wq.drain(timeout=1.0)) == 1
            server.reap_all_sessions()
        cluster.api.create(_cm(99))
        events = wq.drain(timeout=1.0)
        assert [e.obj.metadata.name for e in events] == ["cm-99"]
        got = _deltas(before)
        # One delta heal per reap survived (3 reaps), zero too-old: the
        # watermark carried across every session loss.
        assert got["delta"] == 3 and got["too_old"] == 0

    def test_lost_drain_response_healed_by_delta(self, served):
        """ADVICE r5's destructive-drain case, upgraded: a poll whose
        response is lost marks `_needs_relist`, but the heal now resumes
        from the watermark — the lost events come back from the ring."""
        import http.client

        from training_operator_tpu.cluster.httpapi import ApiUnavailableError

        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=5.0)
        wq = remote.watch(kinds=["ConfigMap"])
        cluster.api.create(_cm(0))
        assert len(wq.drain(timeout=1.0)) == 1

        class _Boom:
            def request(self, *a, **k):
                raise http.client.RemoteDisconnected("stale keep-alive")

            def close(self):
                pass

        cluster.api.create(_cm(1))
        remote._local.conn_watch = _Boom()
        with pytest.raises(ApiUnavailableError):
            wq.drain(timeout=1.0)
        before = _counters()
        events = wq.drain(timeout=1.0)
        assert [e.obj.metadata.name for e in events] == ["cm-1"]
        assert _deltas(before) == {"delta": 1, "replayed": 1, "too_old": 0}


class TestRingOutrun:
    def test_outrun_forces_exactly_one_relist_then_deltas_again(self):
        """More events missed than the ring retains: the 410-style arm
        fires ONCE (full relist, every kind listed exactly once), the
        watermarks rebase, and the NEXT reap is back to O(delta)."""
        cluster = Cluster()
        server = ApiHTTPServer(cluster.api, port=0, resume_ring_size=4)
        try:
            remote = RemoteAPIServer(server.url, timeout=5.0)
            wq = remote.watch(kinds=["ConfigMap"])
            cluster.api.create(_cm(0))
            assert len(wq.drain(timeout=1.0)) == 1

            server.reap_all_sessions()
            for i in range(1, 11):  # 10 missed >> ring of 4
                cluster.api.create(_cm(i))

            before = _counters()
            lists = []
            orig_list = remote.list
            remote.list = lambda *a, **k: lists.append(a[0]) or orig_list(*a, **k)
            events = wq.drain(timeout=1.0)
            remote.list = orig_list
            # Relist arm: full state re-announced (synthetic Added, seq 0).
            assert {e.obj.metadata.name for e in events} == {
                f"cm-{i}" for i in range(11)
            }
            got = _deltas(before)
            assert got["too_old"] == 1 and got["delta"] == 0
            assert sorted(lists) == sorted(wire.KIND_REGISTRY), (
                "exactly one relist: each kind listed exactly once"
            )

            # Recovered: the relist rebased the watermarks, so the next
            # reap heals by delta — one outrun must not poison the future.
            server.reap_all_sessions()
            cluster.api.create(_cm(99))
            before = _counters()
            events = wq.drain(timeout=1.0)
            assert [e.obj.metadata.name for e in events] == ["cm-99"]
            got = _deltas(before)
            assert got["delta"] == 1 and got["too_old"] == 0
        finally:
            server.close()

    def test_unwatched_kind_churn_cannot_outrun_filtered_session(self):
        """A kind-filtered session's resume is judged against ITS kinds
        only: unrelated churn past the ring bound must not degrade a
        Pod-only watcher to O(cluster) relists forever."""
        from training_operator_tpu.api.jobs import ObjectMeta as OM
        from training_operator_tpu.cluster.objects import Node

        cluster = Cluster()
        server = ApiHTTPServer(cluster.api, port=0, resume_ring_size=4)
        try:
            cluster.api.create(Node(metadata=OM(name="n0"), capacity={"cpu": 1}))
            node_seq = cluster.api.event_seq()
            for i in range(10):  # ConfigMap churn outruns the size-4 ring
                cluster.api.create(_cm(i))
            ring = server._ring
            # Scoped to Node: the ConfigMap floor is irrelevant — delta OK.
            out = ring.replay({"Node": node_seq}, base=0, kinds=["Node"])
            assert out == []
            # Unscoped: the outrun ConfigMap ring forces too-old.
            assert ring.replay({"Node": node_seq}, base=0, kinds=None) is None
        finally:
            server.close()

    def test_new_server_incarnation_epoch_mismatch_relists(self):
        """Watermarks are scoped to one ring epoch: a new ApiHTTPServer
        (host restart) must answer too-old no matter how the seq numbers
        compare, and the client must converge by relist."""
        cluster = Cluster()
        server = ApiHTTPServer(cluster.api, port=0)
        remote = RemoteAPIServer(server.url, timeout=5.0)
        wq = remote.watch(kinds=["ConfigMap"])
        cluster.api.create(_cm(0))
        assert len(wq.drain(timeout=1.0)) == 1
        server.close()

        server2 = ApiHTTPServer(cluster.api, port=0)
        try:
            # Same port is gone; point the client at the new incarnation
            # the way a restarted host announces a fresh URL.
            remote2 = RemoteAPIServer(server2.url, timeout=5.0)
            remote2._shared_watch = remote._shared_watch
            remote._shared_watch._remote = remote2
            before = _counters()
            events = wq.drain(timeout=1.0)
            assert {e.obj.metadata.name for e in events} == {"cm-0"}
            assert _deltas(before)["too_old"] == 1
        finally:
            server2.close()

    def test_resume_disabled_client_always_relists(self, served):
        """`RemoteAPIServer(resume=False)` pins the pre-resume behavior —
        the bench's forced-relist comparison leg."""
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=5.0, resume=False)
        wq = remote.watch(kinds=["ConfigMap"])
        cluster.api.create(_cm(0))
        assert len(wq.drain(timeout=1.0)) == 1
        server.reap_all_sessions()
        cluster.api.create(_cm(1))
        before = _counters()
        events = wq.drain(timeout=1.0)
        # Relist: the full state comes back, including what was seen.
        assert {e.obj.metadata.name for e in events} == {"cm-0", "cm-1"}
        got = _deltas(before)
        assert got["delta"] == 0 and got["too_old"] == 0


class TestExactlyOnce:
    def test_replay_overlap_deduplicated_by_seq(self, served):
        """The server subscribes the fresh session BEFORE computing the
        delta, so an event written in that window travels twice (replay +
        session). The watermark dedup must collapse it to exactly one
        delivery."""
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=5.0)
        wq = remote.watch(kinds=["ConfigMap"])
        shared = remote._shared_watch
        cluster.api.create(_cm(0))
        assert len(wq.drain(timeout=1.0)) == 1
        ev = WatchEvent("Added", "ConfigMap", _cm(7), seq=999)
        with shared._lock:
            shared._distribute(ev)
            shared._distribute(ev)  # the overlap copy
        assert len(wq.drain(timeout=0.0)) == 1, (
            "an event distributed twice (replay overlap) must reach "
            "consumers exactly once"
        )

    def test_lister_cache_not_double_applied_and_no_ghosts(self, served):
        """CachedReadAPI over a reap: replayed Modified lands once, a
        Deleted replay expires the mirror entry — correct without any
        RELIST_RESET (the delta path never clears the mirror)."""
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=5.0)
        cached = CachedReadAPI(remote)
        pump = remote.watch()  # the manager-tick analogue that pumps
        cluster.api.create(_cm(0))
        cluster.api.create(_cm(1))
        pump.drain(timeout=1.0)
        assert {o.metadata.name for o in cached.list("ConfigMap")} == {"cm-0", "cm-1"}

        server.reap_all_sessions()
        live = cluster.api.get("ConfigMap", "default", "cm-0")
        live.data["i"] = "updated"
        cluster.api.update(live)
        cluster.api.delete("ConfigMap", "default", "cm-1")

        before = _counters()
        pump.drain(timeout=1.0)
        out = cached.list("ConfigMap")
        assert [o.metadata.name for o in out] == ["cm-0"], "ghost survived delta"
        assert out[0].data["i"] == "updated"
        got = _deltas(before)
        assert got["delta"] == 1 and got["replayed"] == 2 and got["too_old"] == 0
