"""Wire protocol v2 semantics: pipelined batch envelopes, status-write
coalescing, paginated + projected LISTs, and the v1<->v2 compat matrix.

These are the deterministic protocol-conformance tests (`make test-wire`):
no timing assertions, so CI catches framing regressions without the noisy
wire benches. The perf evidence lives in BENCH_SELF_WIRE_V2_r09.json.

Compat matrix proven here (the fourth cell — old client against the new
server — is the entire pre-existing wire suite, which never sends
limit/fields/batch and must keep passing unchanged):

  client \\ server |  v2 host            |  v1 host (no /batch)
  ----------------+---------------------+----------------------------
  v2 (pipeline)   |  batch + coalesce   |  degrades to per-request
  v1 (pipeline=F) |  per-request (v1)   |  per-request (v1)
"""

import json

import pytest

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import (
    Container,
    PodTemplateSpec,
    ReplicaSpec,
)
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta
from training_operator_tpu.cluster import wire
from training_operator_tpu.cluster.apiserver import NotFoundError
from training_operator_tpu.cluster.httpapi import (
    ApiHTTPServer,
    ApiUnavailableError,
    RemoteAPIServer,
    RemoteRuntime,
)
from training_operator_tpu.cluster.objects import (
    ConfigMap,
    Pod,
    PodPhase,
    PodStatus,
)
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    Cluster,
    DefaultScheduler,
    SimKubelet,
)
from training_operator_tpu.cluster.wire_server import (
    decode_continue_token,
    encode_continue_token,
)
from training_operator_tpu.cluster.wire_transport import quote_seg
from training_operator_tpu.controllers.jax import JAXController
from training_operator_tpu.controllers.manager import OperatorManager
from training_operator_tpu.utils import metrics


@pytest.fixture()
def served():
    cluster = Cluster()
    server = ApiHTTPServer(cluster.api, port=0)
    try:
        yield cluster, server
    finally:
        server.close()


def _job(name: str, replicas: int = 1) -> JAXJob:
    tmpl = PodTemplateSpec(
        containers=[Container(name="jax", image="t", resources={"cpu": 0.5})],
        annotations={ANNOTATION_SIM_DURATION: "0"},
    )
    return JAXJob(
        metadata=ObjectMeta(name=name),
        replica_specs={"Worker": ReplicaSpec(replicas=replicas, template=tmpl)},
    )


def _put_op(obj, check_version: bool = True):
    ns = getattr(obj.metadata, "namespace", "") or ""
    body = json.dumps(wire.encode(obj), separators=(",", ":")).encode()
    return (
        "PUT",
        f"/objects/{quote_seg(obj.KIND)}/{ns or '-'}/{quote_seg(obj.metadata.name)}",
        {"check_version": "1" if check_version else "0", "status_only": "1"},
        body,
    )


def _fake_v1_server(server: ApiHTTPServer) -> None:
    """Patch a live ApiHTTPServer instance to answer like a PRE-v2 host:
    404 on /batch, and LISTs that ignore limit/continue/fields entirely."""

    def no_batch(h):
        h._send(404, {"error": "NotFound", "message": "no route batch"})

    def v1_list(kind, q):
        selector = None
        if q.get("labelSelector"):
            selector = dict(
                pair.split("=", 1)
                for pair in q["labelSelector"].split(",") if "=" in pair
            )
        refs = server.api.list_refs(kind, q.get("namespace") or None, selector)
        return (
            b'{"items":['
            + b",".join(server._object_bytes(o) for o in refs)
            + b"]}"
        )

    server._batch = no_batch
    server._list_bytes = v1_list


class TestBatchEnvelope:
    def test_per_op_conflict_isolation(self, served):
        """One stale-version PUT inside a batch answers 409 in ITS slot;
        the ops before and after it land normally."""
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0)
        for name in ("a", "b", "c"):
            remote.create(_job(name))
        fresh = {n: cluster.api.get("JAXJob", "default", n) for n in "abc"}
        # Make b's client copy stale: bump it server-side once more.
        cluster.api.update(cluster.api.get("JAXJob", "default", "b"))
        for n, t in (("a", 1.0), ("b", 2.0), ("c", 3.0)):
            fresh[n].status.start_time = t
        results = remote._channel.execute(
            [_put_op(fresh["a"]), _put_op(fresh["b"]), _put_op(fresh["c"])]
        )
        assert [s for s, _ in results] == [200, 409, 200]
        assert cluster.api.get("JAXJob", "default", "a").status.start_time == 1.0
        assert cluster.api.get("JAXJob", "default", "b").status.start_time is None
        assert cluster.api.get("JAXJob", "default", "c").status.start_time == 3.0

    def test_ops_execute_in_order_and_split_by_depth(self, served):
        """An envelope deeper than pipeline_depth splits into several
        round trips but preserves op order end to end; mixed verbs
        (create/update/delete) keep their per-op status codes."""
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0, pipeline_depth=2)
        cm = ConfigMap(metadata=ObjectMeta(name="mixed"), data={"v": "0"})
        before = metrics.wire_batch_requests.total()
        ops = [(
            "POST", "/objects", None,
            json.dumps(wire.encode(cm), separators=(",", ":")).encode(),
        )]
        for i in range(4):
            step = ConfigMap(metadata=ObjectMeta(name="mixed"),
                             data={"v": str(i + 1)})
            op = ("PUT", "/objects/ConfigMap/default/mixed",
                  {"check_version": "0"},
                  json.dumps(wire.encode(step), separators=(",", ":")).encode())
            ops.append(op)
        ops.append(("DELETE", "/objects/ConfigMap/default/mixed", None, b""))
        results = remote._channel.execute(ops)
        assert [s for s, _ in results] == [201, 200, 200, 200, 200, 200]
        # 6 ops at depth 2 -> 3 envelopes, one wire round trip each.
        assert metrics.wire_batch_requests.total() - before == 3
        # The DELETE ran last: the final state reflects the full sequence.
        assert cluster.api.try_get("ConfigMap", "default", "mixed") is None
        gone = wire.decode(json.loads(results[-1][1]))
        assert gone.data == {"v": "4"}  # the last PUT won before the delete

    def test_unknown_batched_route_is_per_op_404(self, served):
        _, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0)
        results = remote._channel.execute([("GET", "/timelines/x/y", None, b"")])
        assert results[0][0] == 404

    def test_transport_failure_raises_unavailable_without_retry(self, served):
        """POST /batch is NOT idempotent: a mid-flight transport failure
        must surface as ApiUnavailableError — never the stale-keep-alive
        transparent replay idempotent GETs get — because the server may
        have executed any prefix of the lost envelope."""
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0)
        remote.create(_job("lost"))
        served_before = metrics.wire_batch_requests.total()

        class _DeadConn:
            def request(self, *a, **k):
                pass  # request "sent"...

            def getresponse(self):
                raise ConnectionResetError("wire cut mid-response")

            def close(self):
                pass

        remote._local.conn_main = _DeadConn()
        j = cluster.api.get("JAXJob", "default", "lost")
        j.status.start_time = 7.0
        with pytest.raises(ApiUnavailableError):
            remote._channel.execute([_put_op(j)])
        # No transparent second envelope was sent.
        assert metrics.wire_batch_requests.total() == served_before


class TestWriteCoalescing:
    def test_last_write_wins_one_round_trip(self, served):
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0,
                                 coalesce_window_ms=60_000.0)
        remote.create(_job("lww"))
        job = cluster.api.get("JAXJob", "default", "lww")
        reqs = metrics.wire_batch_requests.total()
        merged = metrics.wire_batch_coalesced.total()
        for t in (1.0, 2.0, 3.0):
            job.status.start_time = t
            remote.update(job, status_only=True)
        # Buffered: nothing on the wire yet, server state untouched.
        assert cluster.api.get("JAXJob", "default", "lww").status.start_time is None
        remote.flush_writes()
        got = cluster.api.get("JAXJob", "default", "lww")
        assert got.status.start_time == 3.0  # the LAST write, never reordered
        assert metrics.wire_batch_requests.total() - reqs == 1
        assert metrics.wire_batch_coalesced.total() - merged == 2

    def test_same_key_history_never_reordered(self, served):
        """Interleaved writes to two keys: each key's flushed value is its
        newest, and a second flush after new writes never resurrects an
        older buffered state (the re-enqueue arm keeps newer values)."""
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0,
                                 coalesce_window_ms=60_000.0)
        remote.create(_job("k1"))
        remote.create(_job("k2"))
        j1 = cluster.api.get("JAXJob", "default", "k1")
        j2 = cluster.api.get("JAXJob", "default", "k2")
        for t in (1.0, 2.0):
            j1.status.start_time = t
            remote.update(j1, status_only=True)
            j2.status.start_time = t * 10
            remote.update(j2, status_only=True)
        remote.flush_writes()
        assert cluster.api.get("JAXJob", "default", "k1").status.start_time == 2.0
        assert cluster.api.get("JAXJob", "default", "k2").status.start_time == 20.0
        j1.status.start_time = 5.0
        remote.update(j1, status_only=True)
        remote.flush_writes()
        assert cluster.api.get("JAXJob", "default", "k1").status.start_time == 5.0

    def test_conflict_resolved_at_flush_boundary(self, served):
        """A stale-version coalesced write resolves per-op with the
        engine's own arm (re-get, graft status, unconditional write) —
        the controller's tally is the truth source."""
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0,
                                 coalesce_window_ms=60_000.0)
        remote.create(_job("cfl"))
        stale = cluster.api.get("JAXJob", "default", "cfl")
        cluster.api.update(cluster.api.get("JAXJob", "default", "cfl"))
        stale.status.start_time = 4.0
        remote.update(stale, status_only=True)
        remote.flush_writes()
        assert cluster.api.get("JAXJob", "default", "cfl").status.start_time == 4.0

    def test_conflict_graft_keeps_annotation_bump(self, served):
        """The restart-budget annotation rides the same write as the
        status: a stale-rv retry must carry BOTH through the graft, or a
        crash-looping job's budget would reset on every raced write and
        never reach its backoff limit."""
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0,
                                 coalesce_window_ms=60_000.0)
        remote.create(_job("ann"))
        stale = cluster.api.get("JAXJob", "default", "ann")
        cluster.api.update(cluster.api.get("JAXJob", "default", "ann"))
        stale.status.start_time = 2.0
        stale.metadata.annotations["training.tpu.dev/total-restarts"] = "3"
        remote.update(stale, status_only=True)
        remote.flush_writes()
        got = cluster.api.get("JAXJob", "default", "ann")
        assert got.status.start_time == 2.0
        assert got.metadata.annotations["training.tpu.dev/total-restarts"] == "3"

    def test_coalesce_opt_out_stays_synchronous_and_conflicts(self, served):
        """update(..., coalesce=False) pins one write synchronous on a
        coalescing client — the v2 TrainJob controller's abandon-on-
        conflict contract (ConflictError must propagate, never be
        force-resolved at flush)."""
        from training_operator_tpu.cluster.apiserver import ConflictError

        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0,
                                 coalesce_window_ms=60_000.0)
        remote.create(_job("sync"))
        j = cluster.api.get("JAXJob", "default", "sync")
        j.status.start_time = 1.0
        remote.update(j, status_only=True, coalesce=False)
        # Synchronous: visible without a flush.
        assert cluster.api.get("JAXJob", "default", "sync").status.start_time == 1.0
        stale = cluster.api.get("JAXJob", "default", "sync")
        cluster.api.update(cluster.api.get("JAXJob", "default", "sync"))
        stale.status.start_time = 2.0
        with pytest.raises(ConflictError):
            remote.update(stale, status_only=True, coalesce=False)
        assert cluster.api.get("JAXJob", "default", "sync").status.start_time == 1.0

    def test_deleted_object_write_is_dropped(self, served):
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0,
                                 coalesce_window_ms=60_000.0)
        remote.create(_job("gone"))
        j = cluster.api.get("JAXJob", "default", "gone")
        j.status.start_time = 1.0
        remote.update(j, status_only=True)
        cluster.api.delete("JAXJob", "default", "gone")
        remote.flush_writes()  # per-op 404: dropped, batch unharmed
        assert cluster.api.try_get("JAXJob", "default", "gone") is None

    def test_unacked_writes_reenqueued_on_transport_failure(self, served):
        """Satellite fix: a lost envelope re-enqueues every unacknowledged
        write (the batch is exempt from the stale-keep-alive auto-retry);
        the next flush delivers them."""
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0,
                                 coalesce_window_ms=60_000.0)
        remote.create(_job("requeue"))
        j = cluster.api.get("JAXJob", "default", "requeue")
        j.status.start_time = 6.0
        remote.update(j, status_only=True)

        class _DeadConn:
            def request(self, *a, **k):
                pass

            def getresponse(self):
                raise ConnectionResetError("host restarted")

            def close(self):
                pass

        remote._local.conn_main = _DeadConn()
        with pytest.raises(ApiUnavailableError):
            remote.flush_writes()
        assert len(remote._coalescer) == 1  # held for the next flush
        remote._drop_conn("main")  # fresh connection heals
        remote.flush_writes()
        assert cluster.api.get("JAXJob", "default", "requeue").status.start_time == 6.0

    def test_events_ride_the_batch_envelope(self, served):
        """Lifecycle events buffer with the coalesced writes and travel in
        the same envelope; the client's own events() read flushes first
        (read-your-writes), so nothing is observably lost."""
        from training_operator_tpu.cluster.objects import Event

        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0,
                                 coalesce_window_ms=60_000.0)
        ops_before = metrics.wire_batch_ops.total()
        for i in range(3):
            remote.record_event(Event(
                object_kind="JAXJob", object_name="evj", namespace="default",
                event_type="Normal", reason=f"R{i}", message="m",
            ))
        assert cluster.api.events(object_name="evj") == []  # still buffered
        got = remote.events(object_name="evj")  # flushes, then reads
        assert [e.reason for e in got] == ["R0", "R1", "R2"]  # order kept
        assert metrics.wire_batch_ops.total() - ops_before == 3

    def test_job_read_served_from_mirror(self, served):
        """try_get_cached (the engine's get_job path on the remote
        operator) answers from the watch-fed mirror — a deep copy, and no
        direct GET per reconcile."""
        from training_operator_tpu.cluster.httpapi import CachedReadAPI

        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0)
        capi_view = CachedReadAPI(remote)
        remote.create(_job("mirror-j"))
        capi_view.list("JAXJob")  # prime + pump the shared session
        got = capi_view.try_get_cached("JAXJob", "default", "mirror-j")
        assert got is not None and got.metadata.name == "mirror-j"
        got.metadata.labels["mutated"] = "yes"  # copies never alias the mirror
        again = capi_view.try_get_cached("JAXJob", "default", "mirror-j")
        assert "mutated" not in again.metadata.labels
        assert capi_view.try_get_cached("JAXJob", "default", "nope") is None

    def test_window_and_depth_bounds_trigger_flush(self, served):
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0,
                                 coalesce_window_ms=60_000.0, pipeline_depth=2)
        remote.create(_job("d1"))
        remote.create(_job("d2"))
        j1 = cluster.api.get("JAXJob", "default", "d1")
        j2 = cluster.api.get("JAXJob", "default", "d2")
        j1.status.start_time = 1.0
        remote.update(j1, status_only=True)
        assert cluster.api.get("JAXJob", "default", "d1").status.start_time is None
        j2.status.start_time = 2.0
        remote.update(j2, status_only=True)  # buffer hit depth: auto-flush
        assert cluster.api.get("JAXJob", "default", "d1").status.start_time == 1.0
        assert cluster.api.get("JAXJob", "default", "d2").status.start_time == 2.0


class TestPaginatedList:
    def _seed(self, api, n: int, prefix: str = "pg"):
        for i in range(n):
            api.create(ConfigMap(metadata=ObjectMeta(name=f"{prefix}-{i:03d}")))

    def test_pages_partition_the_collection(self, served):
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0)
        self._seed(cluster.api, 10)
        pages_before = metrics.wire_list_pages.total()
        out = remote.list("ConfigMap", limit=3)
        assert sorted(o.metadata.name for o in out) == [
            f"pg-{i:03d}" for i in range(10)
        ]
        assert metrics.wire_list_pages.total() - pages_before == 4  # 3+3+3+1

    def test_continue_token_stable_under_concurrent_create_delete(self, served):
        """The k8s chunked-LIST contract: an object that exists for the
        whole walk appears exactly once, no matter what is created or
        deleted around the cursor between pages."""
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0)
        self._seed(cluster.api, 12)
        seen = []
        payload = remote._request("GET", "/objects/ConfigMap",
                                  query={"limit": "4"})
        seen += [d["metadata"]["name"] for d in payload["items"]]
        token = payload["continue"]
        # Churn on BOTH sides of the cursor between pages: a create before
        # it (must not be revisited), a create after it (fair game), and a
        # delete of a not-yet-walked object.
        cluster.api.create(ConfigMap(metadata=ObjectMeta(name="pg-000a")))
        cluster.api.create(ConfigMap(metadata=ObjectMeta(name="pg-0105")))
        cluster.api.delete("ConfigMap", "default", "pg-006")
        while token:
            payload = remote._request(
                "GET", "/objects/ConfigMap",
                query={"limit": "4", "continue": token},
            )
            seen += [d["metadata"]["name"] for d in payload["items"]]
            token = payload.get("continue")
        survivors = {f"pg-{i:03d}" for i in range(12)} - {"pg-006"}
        assert len(seen) == len(set(seen)), "pagination produced duplicates"
        assert survivors <= set(seen), "a stable object was skipped"
        assert "pg-000a" not in seen  # created behind the cursor
        assert "pg-0105" in seen  # created ahead of the cursor

    def test_continue_token_stable_across_resume_ring_eviction(self):
        """Watch-resume ring evictions (a tiny ring outrun by churn) must
        not disturb an in-flight chunked walk: the token is keyed on the
        collection order, not on the event stream."""
        cluster = Cluster()
        server = ApiHTTPServer(cluster.api, port=0, resume_ring_size=2)
        try:
            remote = RemoteAPIServer(server.url, timeout=10.0)
            self._seed(cluster.api, 8)
            evicted_before = metrics.wire_resume_ring_evictions.total()
            payload = remote._request("GET", "/objects/ConfigMap",
                                      query={"limit": "3"})
            seen = [d["metadata"]["name"] for d in payload["items"]]
            token = payload["continue"]
            # Outrun the 2-event ring mid-walk.
            for i in range(6):
                cluster.api.create(Pod(metadata=ObjectMeta(name=f"churn-{i}")))
            server._ring.sync()
            assert metrics.wire_resume_ring_evictions.total() > evicted_before
            while token:
                payload = remote._request(
                    "GET", "/objects/ConfigMap",
                    query={"limit": "3", "continue": token},
                )
                seen += [d["metadata"]["name"] for d in payload["items"]]
                token = payload.get("continue")
            assert sorted(seen) == [f"pg-{i:03d}" for i in range(8)]
        finally:
            server.close()

    def test_token_for_wrong_kind_rejected(self, served):
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0)
        self._seed(cluster.api, 2)
        token = encode_continue_token("Pod", 7, ("default", "x"))
        with pytest.raises(ValueError):
            remote._request("GET", "/objects/ConfigMap",
                            query={"limit": "1", "continue": token})
        after, rv = decode_continue_token(token, "Pod")
        assert after == ("default", "x") and rv == 7
        with pytest.raises(ValueError):
            decode_continue_token("garbage!!", "Pod")

    def test_too_old_relist_rides_pages(self, served):
        """Satellite fix: the full-relist fallback arm lists in pages of
        list_page_limit instead of one giant body."""
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0, resume=False,
                                 list_page_limit=3)
        self._seed(cluster.api, 7)
        wq = remote.watch(kinds=["ConfigMap"])
        wq.drain(timeout=0.0)
        pages_before = metrics.wire_list_pages.total()
        server.reap_all_sessions()
        # resume=False pins the full-relist heal; the poll discovers the
        # reap and relists every registry kind — ConfigMap in 3 pages.
        events = wq.drain(timeout=0.0)
        assert {e.obj.metadata.name for e in events} == {
            f"pg-{i:03d}" for i in range(7)
        }
        assert metrics.wire_list_pages.total() - pages_before >= 3


class TestProjection:
    def _pod(self) -> Pod:
        return Pod(
            metadata=ObjectMeta(name="proj-0", namespace="ns1",
                                labels={"a": "b"}),
            spec=PodTemplateSpec(
                containers=[Container(name="c", image="i",
                                      resources={"cpu": 2.0})],
            ),
            status=PodStatus(phase=PodPhase.RUNNING, message="placed"),
        )

    def test_projection_round_trip_vs_reflect_codec(self, served):
        """A projected body decodes through the SAME kind registry the
        reflection reference codec defines: requested paths round-trip
        exactly, absent fields take dataclass defaults."""
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0)
        cluster.api.create(self._pod())
        out = remote.list("Pod", "ns1", fields="metadata,status.phase")
        assert len(out) == 1
        got = out[0]
        reference = wire.reflect_decode(wire.reflect_encode(self._pod()))
        # Projected paths match the reflect-codec round trip field for field.
        assert got.metadata.name == reference.metadata.name
        assert got.metadata.namespace == reference.metadata.namespace
        assert got.metadata.labels == reference.metadata.labels
        assert got.status.phase is reference.status.phase
        # Pruned fields came back as defaults: the spec bytes were never paid.
        assert got.spec.containers == []
        assert got.status.message == ""

    def test_project_encoded_matches_manual_prune(self):
        pod = self._pod()
        full = wire.encode(pod)
        paths = wire.parse_field_paths("status.phase, metadata")
        projected = wire.project_encoded(full, paths)
        assert projected["kind"] == "Pod"
        assert projected["metadata"] == full["metadata"]
        assert projected["status"] == {"phase": full["status"]["phase"]}
        assert "spec" not in projected
        # Selector spelling doesn't matter: canonical path tuples agree.
        assert paths == wire.parse_field_paths("metadata,status.phase")

    def test_projected_bodies_served_from_lru(self, served):
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0)
        cluster.api.create(self._pod())
        full_hits = metrics.wire_body_cache_hits.total()
        full_misses = metrics.wire_body_cache_misses.total()
        remote.list("Pod", "ns1", fields="metadata")
        hits_before = metrics.wire_proj_cache_hits.total()
        remote.list("Pod", "ns1", fields="metadata")
        assert metrics.wire_proj_cache_hits.total() > hits_before
        assert len(server._proj_cache) == 1
        # Projection traffic must not pollute the FULL-body cache family.
        assert metrics.wire_body_cache_hits.total() == full_hits
        assert metrics.wire_body_cache_misses.total() == full_misses

    def test_projected_and_full_bodies_are_distinct_cache_entries(self, served):
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0)
        cluster.api.create(self._pod())
        slim = remote.list("Pod", "ns1", fields="metadata")[0]
        full = remote.list("Pod", "ns1")[0]
        assert slim.spec.containers == []
        assert full.spec.containers[0].resources == {"cpu": 2.0}


class TestCompatMatrix:
    def test_v1_pinned_client_stays_synchronous(self, served):
        """RemoteAPIServer(pipeline=False) pins protocol v1: no /batch
        envelopes, no coalescing, update() is one synchronous round trip —
        whatever the coalesce knob says."""
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=10.0, pipeline=False,
                                 coalesce_window_ms=60_000.0)
        assert remote._channel is None and remote._coalescer is None
        remote.create(_job("v1pin"))
        reqs_before = metrics.wire_batch_requests.total()
        j = cluster.api.get("JAXJob", "default", "v1pin")
        j.status.start_time = 8.0
        remote.update(j, status_only=True)
        # Synchronous: visible immediately, and no envelope was involved.
        assert cluster.api.get("JAXJob", "default", "v1pin").status.start_time == 8.0
        assert metrics.wire_batch_requests.total() == reqs_before

    def test_v2_client_degrades_against_old_server(self, served):
        """New client, old host: the first POST /batch answers 404; the
        client pins per-request HTTP for its lifetime but KEEPS the
        last-write-wins merge (duplicates were dropped client-side)."""
        cluster, server = served
        _fake_v1_server(server)
        remote = RemoteAPIServer(server.url, timeout=10.0,
                                 coalesce_window_ms=60_000.0)
        remote.create(_job("compat"))
        j = cluster.api.get("JAXJob", "default", "compat")
        for t in (1.0, 2.0):
            j.status.start_time = t
            remote.update(j, status_only=True)
        remote.flush_writes()
        assert remote._channel.supported is False
        assert cluster.api.get("JAXJob", "default", "compat").status.start_time == 2.0
        # Later flushes skip the doomed probe and still deliver.
        j.status.start_time = 3.0
        remote.update(j, status_only=True)
        remote.flush_writes()
        assert cluster.api.get("JAXJob", "default", "compat").status.start_time == 3.0

    def test_v2_degraded_conflicts_still_resolve(self, served):
        cluster, server = served
        _fake_v1_server(server)
        remote = RemoteAPIServer(server.url, timeout=10.0,
                                 coalesce_window_ms=60_000.0)
        remote.create(_job("compat-cfl"))
        stale = cluster.api.get("JAXJob", "default", "compat-cfl")
        cluster.api.update(cluster.api.get("JAXJob", "default", "compat-cfl"))
        stale.status.start_time = 4.0
        remote.update(stale, status_only=True)
        remote.flush_writes()
        assert (
            cluster.api.get("JAXJob", "default", "compat-cfl").status.start_time
            == 4.0
        )

    def test_paginated_client_against_old_server_terminates(self, served):
        """Old hosts ignore limit/continue and answer the FULL collection
        in one page: the client's page walk must see no continue token and
        stop — not loop, not double-count."""
        cluster, server = served
        _fake_v1_server(server)
        remote = RemoteAPIServer(server.url, timeout=10.0, list_page_limit=2)
        for i in range(5):
            cluster.api.create(ConfigMap(metadata=ObjectMeta(name=f"o-{i}")))
        out = remote.list("ConfigMap", limit=2)
        assert sorted(o.metadata.name for o in out) == [f"o-{i}" for i in range(5)]

    def test_remote_manager_converges_with_v2_coalescing(self):
        """End to end: an OperatorManager on a coalescing v2 client (the
        operator-role deployment shape) converges a job. The coalesce
        window is set absurdly high, so ONLY the tick-end flush hook and
        the engine's terminal-condition flush deliver status writes — and
        the terminal state must be visible on the host immediately after
        the reconcile that produced it (the SDK-poller contract)."""
        host = Cluster()
        from training_operator_tpu.cluster.inventory import make_cpu_pool

        host.add_nodes(make_cpu_pool(2, cpu_per_node=8.0))
        DefaultScheduler(host)
        SimKubelet(host)
        server = ApiHTTPServer(host.api, port=0)
        try:
            reqs_before = metrics.wire_batch_requests.total()
            remote = RemoteAPIServer(server.url, timeout=10.0,
                                     coalesce_window_ms=3_600_000.0,
                                     list_page_limit=100)
            runtime = RemoteRuntime(remote, tick_interval=0.0)
            mgr = OperatorManager(runtime, gang_enabled=False)
            mgr.register(JAXController(runtime.api))
            remote.create(_job("v2-conv", replicas=2))

            deadline = host.clock.now() + 30.0

            def succeeded():
                j = host.api.try_get("JAXJob", "default", "v2-conv")
                return j is not None and capi.is_succeeded(j.status)

            while host.clock.now() < deadline and not succeeded():
                host.step()
                runtime.step()
            assert succeeded(), host.api.try_get(
                "JAXJob", "default", "v2-conv"
            ).status
            # The status writes rode batch envelopes, not bare PUTs.
            assert metrics.wire_batch_requests.total() > reqs_before
            # Nothing terminal is stranded in the buffer.
            assert len(remote._coalescer) == 0
            mgr.stop()
        finally:
            server.close()


class TestWireV2Knobs:
    def test_cli_flags_reach_the_wire_client(self):
        from training_operator_tpu.__main__ import (
            build_config,
            make_remote_api,
            parse_args,
        )

        cfg = build_config(parse_args([
            "--wire-pipeline-depth", "16",
            "--coalesce-window-ms", "7",
            "--list-page-limit", "42",
        ]))
        client = make_remote_api(cfg, "http://127.0.0.1:1")
        assert client.pipeline is True
        assert client._channel.depth == 16
        assert client._coalescer is not None
        assert client._coalescer.window == pytest.approx(0.007)
        assert client.list_page_limit == 42

    def test_pipeline_depth_zero_pins_v1(self):
        from training_operator_tpu.__main__ import (
            build_config,
            make_remote_api,
            parse_args,
        )

        cfg = build_config(parse_args(["--wire-pipeline-depth", "0"]))
        client = make_remote_api(cfg, "http://127.0.0.1:1")
        assert client.pipeline is False
        assert client._channel is None and client._coalescer is None
        # ALL of v2 is pinned off — chunked LISTs included — so the escape
        # hatch reproduces real v1 wire traffic.
        assert client.list_page_limit == 0

    def test_defaults_and_validation(self):
        from training_operator_tpu.config import OperatorConfig

        cfg = OperatorConfig()
        assert cfg.wire_pipeline_depth == 64
        assert cfg.coalesce_window_ms == 20.0
        assert cfg.list_page_limit == 500
        for field, bad in (
            ("wire_pipeline_depth", -1),
            ("coalesce_window_ms", -0.5),
            ("list_page_limit", -2),
        ):
            broken = OperatorConfig(**{field: bad})
            with pytest.raises(ValueError):
                broken.validate()
