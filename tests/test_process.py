"""Operator process + config tests (reference cmd/training-operator.v1/
main.go flag surface and pkg/config/config.go defaults)."""

import json

import pytest

from training_operator_tpu import __main__ as process
from training_operator_tpu.config import OperatorConfig, current, set_current


def run_main(tmp_path, cluster, workload, extra_args=()):
    cpath = tmp_path / "cluster.json"
    cpath.write_text(json.dumps(cluster))
    argv = ["--cluster", str(cpath), "--virtual-clock", *extra_args]
    if workload is not None:
        wpath = tmp_path / "workload.json"
        wpath.write_text(json.dumps(workload))
        argv += ["--workload", str(wpath)]
    return process.main(argv)


CLUSTER = {
    "tpu_pools": [{"slices": 1, "topology": "4x4"}],
    "cpu_pools": [{"nodes": 2, "cpu_per_node": 8.0}],
}


class TestConfig:
    def test_defaults_valid(self):
        OperatorConfig().validate()

    def test_unknown_scheme_rejected(self):
        with pytest.raises(ValueError):
            OperatorConfig(enabled_schemes=["jax", "caffe"]).validate()

    def test_unknown_gang_scheduler_rejected(self):
        with pytest.raises(ValueError):
            OperatorConfig(gang_scheduler_name="volcano").validate()

    def test_from_file_rejects_unknown_keys(self, tmp_path):
        p = tmp_path / "cfg.json"
        p.write_text('{"no_such_knob": 1}')
        with pytest.raises(ValueError):
            OperatorConfig.from_file(str(p))

    def test_config_image_reaches_pytorch_init_container(self, tmp_path):
        prev = current()
        try:
            set_current(OperatorConfig(pytorch_init_container_image="busybox:9"))
            rc = run_main(
                tmp_path,
                CLUSTER,
                [{"kind": "pytorch", "name": "ddp", "workers": 1, "master": True,
                  "cpu": 1.0, "run_seconds": 1}],
                extra_args=["--gang-scheduler-name", "none", "--disable-v2"],
            )
            assert rc == 0
        finally:
            set_current(prev)


class TestProcess:
    def test_end_to_end_mixed_workload(self, tmp_path):
        rc = run_main(
            tmp_path,
            CLUSTER,
            [
                {"kind": "jax", "name": "pre", "workers": 4, "chips": 4.0,
                 "topology": "4x4", "run_seconds": 2},
                {"kind": "tensorflow", "name": "etl", "workers": 2, "cpu": 1.0,
                 "run_seconds": 1},
            ],
        )
        assert rc == 0

    def test_disabled_scheme_rejects_submission(self, tmp_path):
        # Only jax enabled: a pytorch workload entry cannot be reconciled, so
        # its job never finishes and the process exits non-zero.
        rc = run_main(
            tmp_path,
            CLUSTER,
            [{"kind": "pytorch", "name": "ddp", "workers": 1, "cpu": 1.0,
              "run_seconds": 1}],
            extra_args=["--enable-scheme", "jax", "--run-seconds", "30",
                        "--gang-scheduler-name", "none", "--disable-v2"],
        )
        assert rc == 1

    def test_namespace_scoped_manager_ignores_out_of_scope(self, tmp_path):
        rc = run_main(
            tmp_path,
            CLUSTER,
            [{"kind": "jax", "name": "other", "namespace": "other-ns",
              "workers": 1, "cpu": 1.0, "run_seconds": 1}],
            extra_args=["--namespace", "prod", "--run-seconds", "30",
                        "--gang-scheduler-name", "none", "--disable-v2"],
        )
        assert rc == 1  # out-of-scope job is never reconciled

    def test_gang_scheduler_selection_baseline(self, tmp_path):
        rc = run_main(
            tmp_path,
            CLUSTER,
            [{"kind": "jax", "name": "pre", "workers": 4, "chips": 4.0,
              "topology": "4x4", "run_seconds": 1}],
            extra_args=["--gang-scheduler-name", "baseline"],
        )
        assert rc == 0

    def test_metrics_dump(self, tmp_path):
        out = tmp_path / "metrics.txt"
        rc = run_main(
            tmp_path,
            CLUSTER,
            [{"kind": "jax", "name": "pre", "workers": 1, "cpu": 1.0,
              "run_seconds": 1}],
            extra_args=["--metrics-dump", str(out), "--gang-scheduler-name", "none"],
        )
        assert rc == 0
        text = out.read_text()
        assert "training_operator_jobs_created_total" in text


class TestSecureMetrics:
    def test_metrics_token_gates_endpoint(self):
        """The secure-serving analogue: /metrics 401s without the bearer
        token; probes stay open."""
        import urllib.request
        import urllib.error

        from training_operator_tpu.cluster.runtime import Cluster, VirtualClock

        cluster = Cluster(VirtualClock())
        server = process.serve_probes(cluster, 0, metrics_token="s3cret")
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            assert urllib.request.urlopen(f"{base}/healthz").status == 200
            try:
                urllib.request.urlopen(f"{base}/metrics")
                raise AssertionError("unauthenticated /metrics must 401")
            except urllib.error.HTTPError as e:
                assert e.code == 401
            req = urllib.request.Request(
                f"{base}/metrics",
                headers={"Authorization": "Bearer s3cret"},
            )
            assert urllib.request.urlopen(req).status == 200
        finally:
            server.shutdown()
            server.server_close()


def test_non_ascii_metrics_token_rejected():
    with pytest.raises(ValueError):
        OperatorConfig(metrics_token="café").validate()


class TestWireRoles:
    """CLI surface of the host/operator roles (the wire deployment)."""

    def test_host_rejects_virtual_clock(self):
        with pytest.raises(SystemExit):
            process.main(["--role", "host", "--virtual-clock"])

    def test_host_rejects_workload(self, tmp_path):
        wl = tmp_path / "w.json"
        wl.write_text("[]")
        with pytest.raises(SystemExit):
            process.main(["--role", "host", "--workload", str(wl)])

    def test_operator_requires_api_server(self):
        with pytest.raises(SystemExit):
            process.main(["--role", "operator"])

    def test_operator_rejects_workload(self, tmp_path):
        wl = tmp_path / "w.json"
        wl.write_text("[]")
        with pytest.raises(SystemExit):
            process.main([
                "--role", "operator", "--api-server", "http://127.0.0.1:1",
                "--workload", str(wl),
            ])

    def test_nonpositive_lease_duration_rejected(self):
        with pytest.raises(ValueError):
            process.main(["--leader-lease-seconds", "0", "--run-seconds", "0.1"])

    def test_host_serves_and_exits_on_deadline(self, tmp_path):
        """--role host with --run-seconds: comes up (WIRE_API reachable,
        presets installed, admission live) and exits at the deadline."""
        import json as _json
        import threading
        import urllib.request

        from training_operator_tpu.cluster.httpapi import RemoteAPIServer

        inv = tmp_path / "c.json"
        inv.write_text('{"cpu_pools": [{"nodes": 1, "cpu_per_node": 4.0}]}')
        # Capture the announced URL by running main in a thread with a
        # patched stdout... simpler: pick a free port explicitly.
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        t = threading.Thread(
            target=process.main,
            args=([
                "--role", "host", "--serve-port", str(port), "--insecure",
                # Long enough that late-binding under CI load can't close
                # the server while the assertions below still run.
                "--cluster", str(inv), "--run-seconds", "12",
                "--gang-scheduler-name", "none",
            ],),
        )
        t.start()
        try:
            api = RemoteAPIServer(f"http://127.0.0.1:{port}", timeout=5.0)
            import time as _time

            for _ in range(8 * 10):
                try:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz", timeout=1
                    ) as r:
                        assert _json.loads(r.read())["ok"]
                    break
                except OSError:
                    _time.sleep(0.1)
            else:
                raise AssertionError("host never became healthy")
            # presets installed by the host
            assert api.try_get("ClusterTrainingRuntime", "", "tpu-jax-default") is not None
            # v1 admission enforced server-side
            from training_operator_tpu.api.jobs import JAXJob, ObjectMeta

            with pytest.raises(ValueError):
                api.create(JAXJob(metadata=ObjectMeta(name="Bad!")))
        finally:
            t.join(timeout=30)
        assert not t.is_alive()


class TestReconcileMetrics:
    """controller-runtime metric parity: reconcile latency histogram,
    per-kind outcome counter, workqueue depth gauge."""

    def test_reconcile_metrics_populated(self, tmp_path):
        from training_operator_tpu.utils import metrics as m

        before_success = m.reconcile_total.value("JAXJob", "success")
        before_n = m.reconcile_seconds.count if hasattr(m.reconcile_seconds, "count") else None
        cluster_file = tmp_path / "c.json"
        cluster_file.write_text('{"cpu_pools": [{"nodes": 2, "cpu_per_node": 8.0}]}')
        wl = tmp_path / "w.json"
        wl.write_text('[{"kind": "jax", "name": "mx", "workers": 2, "cpu": 1.0, "run_seconds": 1}]')
        rc = process.main([
            "--cluster", str(cluster_file), "--workload", str(wl),
            "--virtual-clock", "--gang-scheduler-name", "none",
        ])
        assert rc == 0
        assert m.reconcile_total.value("JAXJob", "success") > before_success
        rendered = m.registry.render()
        assert "training_operator_reconcile_seconds" in rendered
        assert "training_operator_workqueue_depth" in rendered


class TestStackWiring:
    """build_stack must include the HPA control loop (kube-controller-
    manager's role): an elastic job scales with NO manually-attached
    autoscaler — the process stack provides it."""

    def test_elastic_scales_through_process_stack(self):
        import json as _json

        import training_operator_tpu.api.common as capi
        from training_operator_tpu.api.common import (
            Container,
            PodTemplateSpec,
            ReplicaSpec,
        )
        from training_operator_tpu.api.jobs import (
            ElasticPolicy,
            ObjectMeta,
            PyTorchJob,
        )
        from training_operator_tpu.cluster.inventory import (
            GPU_RESOURCE,
            make_gpu_pool,
        )
        from training_operator_tpu.cluster.runtime import Cluster, VirtualClock
        from training_operator_tpu.scheduler.elastic import (
            ANNOTATION_LOAD_PROFILE_PREFIX,
        )

        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_gpu_pool(8, gpus_per_node=8))
        cfg = OperatorConfig()
        mgr, _v2 = process.build_stack(cluster, cfg)

        template = PodTemplateSpec(
            containers=[Container(name="pytorch", image="t",
                                  resources={"cpu": 2.0, GPU_RESOURCE: 8.0})]
        )
        template.annotations[ANNOTATION_LOAD_PROFILE_PREFIX + "gpu_util"] = _json.dumps(
            [[0, 70.0], [40, 140.0]]
        )
        job = PyTorchJob(
            metadata=ObjectMeta(name="stack-elastic"),
            replica_specs={"Worker": ReplicaSpec(replicas=2, template=template)},
            elastic_policy=ElasticPolicy(
                min_replicas=2, max_replicas=4,
                metrics=[{"name": "gpu_util", "target": 70.0}],
            ),
        )
        mgr.submit(job)

        def running():
            return [
                p for p in cluster.api.list(
                    "Pod", "default", {capi.JOB_NAME_LABEL: "stack-elastic"}
                )
                if p.status.phase.value == "Running"
            ]

        assert cluster.run_until(lambda: len(running()) == 2, timeout=60)
        assert cluster.run_until(lambda: len(running()) == 4, timeout=600), (
            "the stack's HPA loop never scaled the elastic job"
        )
