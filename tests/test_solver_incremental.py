"""Incremental gang solver: oracle-parity and warm-start behavior.

Three layers of evidence that O(changed) never changes WHAT gets scheduled:

1. SnapshotMaintainer deltas == a from-scratch ClusterSnapshot after every
   kind of churn (bind, completion, node kill/recovery, cordon, admitted
   reservations, preemption) — property-tested over seeds via the
   maintainer's own selfcheck (which is exactly the snapshot_selfcheck_every
   probe a deployment can leave on).
2. The incremental scheduler arm and the pinned-legacy arm
   (solver_incremental=False) produce identical job outcomes — same jobs
   admitted at the same virtual-clock instants — on a staggered contended
   workload.
3. Incremental cycles really are incremental: a demand-only event re-solves
   one gang, not the whole pending queue.
"""

import random

import pytest

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import (
    Container,
    JobConditionType,
    PodTemplateSpec,
    ReplicaSpec,
)
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta, TPUPolicy
from training_operator_tpu.cluster.inventory import (
    TPU_RESOURCE,
    make_cpu_pool,
    make_tpu_pool,
)
from training_operator_tpu.cluster.objects import PodGroupPhase
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
)
from training_operator_tpu.controllers import OperatorManager, register_all
from training_operator_tpu.scheduler import ClusterSnapshot, GangScheduler, TPUPacker
from training_operator_tpu.scheduler.gang import GangScheduler as _GS
from training_operator_tpu.scheduler.snapshot import SnapshotMaintainer


def jax_job(name, workers, topology, num_slices=1, duration=None):
    chips = 1
    for d in topology.split("x"):
        chips *= int(d)
    t = PodTemplateSpec(
        containers=[Container(name="jax", image="trainer",
                              resources={"cpu": 1.0, TPU_RESOURCE: 4.0})]
    )
    if duration is not None:
        t.annotations[ANNOTATION_SIM_DURATION] = str(duration)
    return JAXJob(
        metadata=ObjectMeta(name=name),
        replica_specs={"Worker": ReplicaSpec(replicas=workers, template=t)},
        tpu_policy=TPUPolicy(accelerator=f"v5e-{chips}", topology=topology,
                             num_slices=num_slices),
    )


def gang_env(slices=2, incremental=True, selfcheck_every=0, heartbeat=None,
             grace=None, toleration=None):
    cluster = Cluster(VirtualClock())
    cluster.add_nodes(make_tpu_pool(slices, slice_topology="4x4"))
    cluster.add_nodes(make_cpu_pool(1))
    DefaultScheduler(cluster)
    kubelet = SimKubelet(
        cluster, **({"heartbeat_interval": heartbeat} if heartbeat else {})
    )
    if grace is not None:
        from training_operator_tpu.controllers.nodelifecycle import (
            NodeLifecycleController,
        )

        NodeLifecycleController(cluster, grace_period=grace,
                                toleration_seconds=toleration or 5.0)
    sched = GangScheduler(
        cluster, TPUPacker(), incremental=incremental,
        snapshot_selfcheck_every=selfcheck_every,
    )
    mgr = OperatorManager(cluster, gang_enabled=True)
    register_all(mgr)
    return cluster, mgr, sched, kubelet


def find_scheduler(cluster):
    return next(
        t.__self__ for t in cluster._tickers
        if isinstance(getattr(t, "__self__", None), _GS)
    )


class TestMaintainerDeltas:
    """Unit-level: every event class applied as a delta must leave the
    maintainer exactly equal to a cold rebuild (selfcheck returns [])."""

    def _env(self):
        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_tpu_pool(2, slice_topology="4x4"))
        m = SnapshotMaintainer(cluster.api)
        m.rebuild()
        watch = cluster.api.watch()
        return cluster, m, watch

    def _sync(self, m, watch):
        for ev in watch.drain():
            if ev.kind in ("Pod", "PodGroup", "Node"):
                m.observe(ev)

    def _assert_parity(self, m, watch):
        self._sync(m, watch)
        problems = m.selfcheck()
        assert not problems, problems

    def test_pod_lifecycle_deltas(self):
        from training_operator_tpu.cluster.objects import Pod, PodPhase

        cluster, m, watch = self._env()
        p = Pod(metadata=ObjectMeta(name="w0", namespace="default"))
        p.spec.containers = [
            Container(name="c", resources={"cpu": 1.0, TPU_RESOURCE: 4.0})
        ]
        cluster.api.create(p)
        self._assert_parity(m, watch)  # unbound: no capacity held
        live = cluster.api.get("Pod", "default", "w0")
        live.node_name = "slice-0-host-1"
        live.status.phase = PodPhase.RUNNING
        cluster.api.update(live, check_version=False)
        self._assert_parity(m, watch)  # bound: host capacity taken
        live = cluster.api.get("Pod", "default", "w0")
        live.status.phase = PodPhase.SUCCEEDED
        cluster.api.update(live, check_version=False)
        self._assert_parity(m, watch)  # terminal: capacity released
        cluster.api.delete("Pod", "default", "w0")
        self._assert_parity(m, watch)

    def test_admitted_reservation_and_bind_handoff(self):
        from training_operator_tpu.cluster.objects import Pod, PodGroup, PodPhase

        cluster, m, watch = self._env()
        job = jax_job("resv", workers=2, topology="2x4")
        cluster.api.create(job)
        pg = PodGroup(
            metadata=ObjectMeta(name="resv", namespace="default",
                                labels={"job-kind": "JAXJob"}),
            min_member=2,
            phase=PodGroupPhase.INQUEUE,
            placement={"resv-worker-0": "slice-0-host-0",
                       "resv-worker-1": "slice-0-host-1"},
        )
        cluster.api.create(pg)
        self._assert_parity(m, watch)  # reservation holds both hosts
        # One placed pod binds: the reservation for IT deactivates, the
        # bound pod's own resources take over.
        p = Pod(metadata=ObjectMeta(name="resv-worker-0", namespace="default"))
        p.spec.containers = [
            Container(name="c", resources={"cpu": 1.0, TPU_RESOURCE: 4.0})
        ]
        p.node_name = "slice-0-host-0"
        p.status.phase = PodPhase.RUNNING
        cluster.api.create(p)
        self._assert_parity(m, watch)
        # Preemption shape: placement cleared, phase back to Pending.
        live = cluster.api.get("PodGroup", "default", "resv")
        live.placement = {}
        live.phase = PodGroupPhase.PENDING
        cluster.api.update(live, check_version=False)
        self._assert_parity(m, watch)

    def test_whole_slice_reserved_nodes(self):
        from training_operator_tpu.cluster.objects import PodGroup

        cluster, m, watch = self._env()
        job = jax_job("whole", workers=1, topology="1x4")
        cluster.api.create(job)
        pg = PodGroup(
            metadata=ObjectMeta(name="whole", namespace="default",
                                labels={"job-kind": "JAXJob"}),
            min_member=1,
            phase=PodGroupPhase.INQUEUE,
            placement={"whole-worker-0": "slice-1-host-0"},
            reserved_nodes=["slice-1-host-1", "slice-1-host-2",
                            "slice-1-host-3"],
        )
        cluster.api.create(pg)
        self._assert_parity(m, watch)
        cluster.api.delete("PodGroup", "default", "whole")
        self._assert_parity(m, watch)

    def test_node_transitions(self):
        cluster, m, watch = self._env()
        node = cluster.api.get("Node", "", "slice-0-host-2")
        node.unschedulable = True
        cluster.api.update(node, check_version=False)
        self._assert_parity(m, watch)  # cordoned: out of the free map
        node = cluster.api.get("Node", "", "slice-0-host-2")
        node.unschedulable = False
        cluster.api.update(node, check_version=False)
        self._assert_parity(m, watch)
        cluster.api.delete("Node", "", "slice-0-host-3")
        self._assert_parity(m, watch)  # slice host index rebuilt

    def test_selfcheck_catches_and_repairs_corruption(self):
        cluster, m, watch = self._env()
        m.free["slice-0-host-0"][TPU_RESOURCE] -= 4.0  # simulate a missed delta
        problems = m.selfcheck()
        assert problems, "corruption not detected"
        assert m.selfcheck_mismatches == 1
        assert m.selfcheck() == []  # rebuild adopted: clean again


@pytest.mark.parametrize("seed", range(4))
class TestChurnParity:
    """Metamorphic property over random churn: submissions, completions,
    a node kill (NodeChaos), recovery — with selfcheck_every=1 the
    incremental snapshot must match a cold rebuild after EVERY solve."""

    def test_random_churn_snapshot_parity(self, seed):
        from training_operator_tpu.cluster.chaos import NodeChaos

        rng = random.Random(seed)
        cluster, mgr, sched, kubelet = gang_env(
            slices=3, incremental=True, selfcheck_every=1,
            heartbeat=2.0, grace=6.0, toleration=3.0,
        )
        shapes = [("1x4", 1), ("2x4", 2), ("4x4", 4)]
        jobs = []
        for i in range(rng.randint(6, 10)):
            topo, workers = rng.choice(shapes)
            name = f"churn-{seed}-{i}"
            jobs.append(name)
            delay = rng.uniform(0.0, 30.0)
            dur = rng.randint(5, 40)
            cluster.schedule_at(
                delay,
                (lambda j: lambda: mgr.submit(j))(
                    jax_job(name, workers, topo, duration=dur)
                ),
            )
        # One mid-run node kill + recovery: the hardest delta class
        # (NotReady transition, evictions, gang re-solve, ready again).
        victim = "slice-1-host-0"
        chaos = NodeChaos(cluster, kubelet)
        cluster.schedule_at(20.0, lambda: chaos.kill_node(victim))
        cluster.schedule_at(45.0, lambda: chaos.recover_node(victim))

        def all_done():
            return all(
                capi.is_finished(
                    cluster.api.get("JAXJob", "default", n).status
                )
                for n in jobs
                if cluster.api.try_get("JAXJob", "default", n) is not None
            ) and cluster.clock.now() > 50.0

        assert cluster.run_until(all_done, timeout=3000)
        assert sched.cycles > 0
        assert sched._maintainer.selfcheck_mismatches == 0, (
            "incremental snapshot diverged from the cold rebuild"
        )
        # Everything that could finish did (node recovery restores capacity).
        for n in jobs:
            job = cluster.api.get("JAXJob", "default", n)
            assert capi.is_succeeded(job.status), (n, job.status)


class TestPreemptionParity:
    """Snapshot parity through the checkpoint-aware preemption path: the
    reservation diffs (placement cleared, re-admitted elsewhere) are the
    deltas most likely to drift."""

    def test_preemption_churn_keeps_parity(self):
        from training_operator_tpu.tenancy import (
            ClusterQueue,
            PriorityClass,
            TenancyArbiter,
            register_tenancy_admission,
        )
        from training_operator_tpu.api.common import RunPolicy, SchedulingPolicy

        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_tpu_pool(2, slice_topology="4x4"))
        DefaultScheduler(cluster)
        SimKubelet(cluster)
        register_tenancy_admission(cluster.api)
        arbiter = TenancyArbiter(cluster.api, cluster.clock.now,
                                 starvation_seconds=100000.0)
        sched = GangScheduler(
            cluster, TPUPacker(), arbiter=arbiter,
            incremental=True, snapshot_selfcheck_every=1,
        )
        mgr = OperatorManager(cluster, gang_enabled=True)
        register_all(mgr)
        cluster.api.create(PriorityClass(metadata=ObjectMeta(name="high"),
                                         value=1000))
        cluster.api.create(PriorityClass(metadata=ObjectMeta(name="low"),
                                         value=10))
        cluster.api.create(ClusterQueue(
            metadata=ObjectMeta(name="q"),
            quota={TPU_RESOURCE: 128.0},
        ))

        def prio_job(name, prio, workers, topology, duration):
            job = jax_job(name, workers, topology, duration=duration)
            job.run_policy = RunPolicy(scheduling_policy=SchedulingPolicy(
                queue="q", priority_class=prio,
            ))
            return job

        for i in range(2):
            mgr.submit(prio_job(f"low-{i}", "low", 4, "4x4", 500))
        cluster.schedule_at(
            10.0, lambda: mgr.submit(prio_job("prod", "high", 4, "4x4", 30))
        )
        assert cluster.run_until(
            lambda: (
                (j := cluster.api.try_get("JAXJob", "default", "prod"))
                is not None
                and capi.is_succeeded(j.status)
            ),
            timeout=3000,
        )
        preempts = cluster.api.events(reason="Preempted")
        assert preempts, "scenario did not exercise preemption"
        assert sched._maintainer.selfcheck_mismatches == 0


class TestIncrementalVsLegacyOutcomes:
    """The compat-arm oracle: solver_incremental=True and False must admit
    the same jobs at the same virtual-clock instants on a staggered,
    contended workload (the placements may legally differ in node identity;
    the OUTCOME — who runs when — may not)."""

    def _run(self, incremental):
        cluster, mgr, sched, _ = gang_env(slices=2, incremental=incremental)
        plan = [
            ("a0", 4, "4x4", 1, 20, 0.0),
            ("a1", 4, "4x4", 1, 20, 0.0),
            ("b0", 2, "2x4", 1, 15, 5.0),   # arrives while both slices busy
            ("b1", 1, "1x4", 1, 10, 8.0),
            ("c0", 4, "4x4", 1, 10, 12.0),
            ("c1", 2, "2x4", 1, 10, 30.0),  # arrives after capacity freed
        ]
        names = [p[0] for p in plan]
        for name, workers, topo, ns, dur, at in plan:
            cluster.schedule_at(
                at,
                (lambda j: lambda: mgr.submit(j))(
                    jax_job(name, workers, topo, num_slices=ns, duration=dur)
                ),
            )
        running_at = {}
        watch = cluster.api.watch(kinds={"JAXJob"})

        def track():
            for ev in watch.drain():
                if ev.type != "Modified" or ev.obj.name in running_at:
                    continue
                cond = capi.get_condition(
                    ev.obj.status, JobConditionType.RUNNING
                )
                if cond is not None and cond.status:
                    running_at[ev.obj.name] = cond.last_transition_time

        cluster.add_ticker(track)
        assert cluster.run_until(
            lambda: all(
                (j := cluster.api.try_get("JAXJob", "default", n)) is not None
                and capi.is_finished(j.status)
                for n in names
            ),
            timeout=3000,
        )
        return running_at, sched

    def test_same_outcomes_both_arms(self):
        inc_times, inc_sched = self._run(incremental=True)
        legacy_times, legacy_sched = self._run(incremental=False)
        assert inc_times == legacy_times, (
            f"incremental {inc_times} != legacy {legacy_times}"
        )
        # And the incremental arm actually took the warm-start path at
        # least once (the b0/b1/c0 arrivals are demand-only events).
        assert any(
            r.get("mode") == "incremental" for r in inc_sched.dump_trace()
        )
        assert all(
            r.get("mode") == "full" for r in legacy_sched.dump_trace()
        )


class TestIncrementalCycleScope:
    def test_demand_event_solves_only_the_dirty_gang(self):
        """A stuck gang + a later arrival with no capacity change: the
        arrival's cycle must solve ONE gang (the new one), leaving the
        stuck gang's verdict untouched."""
        cluster, mgr, sched, _ = gang_env(slices=1)
        # Can never fit: needs 2 distinct slices on a 1-slice pool.
        mgr.submit(jax_job("stuck", 8, "4x4", num_slices=2))
        cluster.run_for(2.0)
        pg = cluster.api.get("PodGroup", "default", "stuck")
        assert pg.phase == PodGroupPhase.PENDING
        cycles_before = sched.cycles
        mgr.submit(jax_job("fresh", 1, "1x4", duration=5))
        assert cluster.run_until(
            lambda: capi.is_succeeded(
                cluster.api.get("JAXJob", "default", "fresh").status
            ),
            timeout=300,
        )
        incremental = [
            r for r in sched.dump_trace() if r["mode"] == "incremental"
        ]
        assert incremental, "no incremental cycle ran"
        # The arrival cycle considered exactly the dirty gang.
        assert incremental[0]["pending"] == 1
        assert sched.cycles > cycles_before
