"""Time-compressed fleet soak (PR 14): the smoke tier (a compressed hour
with all five chaos tiers live + one host failover, under the fail-fast
auditor), the single-seed replay pin, the bounded-growth/INV009 plane
(event-store cap, accumulator rule, expired-expectation cleanup, orphan
sweep), and the `slow`-marked compressed-day run at the 10k-node scale."""

from __future__ import annotations

import pytest

from training_operator_tpu.api.jobs import JAXJob, ObjectMeta
from training_operator_tpu.api.common import (
    Container,
    JOB_KIND_LABEL,
    JOB_NAME_LABEL,
    PodTemplateSpec,
    ReplicaSpec,
)
from training_operator_tpu.cluster.apiserver import APIServer
from training_operator_tpu.cluster.objects import Event, Pod
from training_operator_tpu.cluster.runtime import Cluster, VirtualClock
from training_operator_tpu.observe.invariants import (
    FleetSources,
    InvariantAuditor,
)
from training_operator_tpu.soak import SoakConfig, SoakHarness, derive_seed

# Chaos intensities cranked so every tier provably fires inside a
# compressed hour (base cadences are sized for a week): pod kills every
# ~10 sim-min, node tier (kills + slice kills + maintenance) every ~20.
SMOKE_CHAOS = {"pod": 12.0, "api": 1.5, "wire": 1.0, "node": 18.0, "host": 1.0}


def smoke_config(**overrides) -> SoakConfig:
    base = dict(
        sim_hours=1.0,
        arrival_per_minute=6.0,
        compression=1.0,
        chaos=dict(SMOKE_CHAOS),
        seed=14,
        tpu_slices=8,
        cpu_nodes=4,
        cpu_per_node=16.0,
        epoch_seconds=600.0,
        heartbeat_seconds=60.0,
        grace_seconds=150.0,
        toleration_seconds=60.0,
        recover_seconds=400.0,
        audit_seconds=120.0,
        resync_seconds=300.0,
        resolve_seconds=60.0,
        min_solve_seconds=5.0,
        job_ttl_seconds=600.0,
        compact_check_seconds=60.0,
        drain_hours=2.0,
        team_quota_chips=24.0,
        prod_quota_chips=32.0,
        slo_p50_ttr_s=1800.0,
        slo_high_p99_ttr_s=3600.0,
        max_wall_seconds=180.0,
    )
    base.update(overrides)
    return SoakConfig(**base)


class TestSoakSmoke:
    def test_compressed_hour_all_five_tiers(self, tmp_path):
        """The smoke soak: a compressed hour of fleet life with every
        chaos tier live at once and a mid-soak host failover, under the
        fail-fast INV001-INV009 auditor. Any invariant violation raises
        out of the run and fails this test with the replayable seed in
        the config."""
        h = SoakHarness(smoke_config(), str(tmp_path))
        report = h.run()

        jobs = report["jobs"]
        assert jobs["completed"] == jobs["submitted"] > 100
        assert jobs["failed"] == 0, report["jobs"]
        # No vacuous pass: every tier actually struck.
        counts = report["chaos"]
        assert counts.get("pod:kill", 0) > 0, counts
        assert counts.get("node:kill", 0) > 0, counts
        assert counts.get("node:maintenance_begin", 0) > 0, counts
        assert counts.get("host:failover", 0) == 1, counts
        assert sum(report["wire"]["injected"].values()) > 0
        assert report["api_chaos_conflicts"] > 0
        # The auditor lived through the storm and stayed green.
        assert report["auditor"]["audits"] > 10
        assert report["auditor"]["violations"] == 0
        # The failover recovered with byte-level replication parity.
        fo = report["failover"]
        assert fo is not None and fo["replication_parity"]
        assert fo["wal_records_replicated"] > 0
        # Bounded growth held over the whole run.
        for name, g in report["growth"].items():
            if isinstance(g, dict):
                assert g["within"], (name, g)
        # The mix exercised every workload kind, including v2.
        assert set(jobs["by_kind"]) >= {
            "jax-sub", "jax-host", "jax-full", "mpi", "cpu", "v2",
        }
        # Per-tier SLO attainment (PR 19): every disruption tier that
        # struck a running job reports its own attainment slice, plus the
        # undisrupted control group, all joined against the priority-aware
        # TTR targets. With zero invariant violations above, this is the
        # "per-tier SLO attainment under chaos" acceptance report.
        by_tier = report["slo"]["by_tier"]
        assert "undisrupted" in by_tier
        for tier, row in by_tier.items():
            assert row["jobs"] >= row["ran"] >= 0, (tier, row)
            if row["ran"]:
                assert 0.0 <= row["attainment"] <= 1.0, (tier, row)
                assert row["p50_ttr_s"] <= row["p99_ttr_s"], (tier, row)
            else:
                assert row["attainment"] is None, (tier, row)
        undis = by_tier["undisrupted"]
        assert undis["ran"] > 0
        assert undis["attainment"] >= 0.9, undis

    def test_disruptions_recover(self, tmp_path):
        """Node/pod kills and maintenance drains open MTTR records and the
        records close: nothing disrupted is left dangling un-recovered."""
        h = SoakHarness(smoke_config(), str(tmp_path))
        report = h.run()
        outcomes = report["mttr"]["disruptions"]
        assert sum(outcomes.values()) > 0, report["chaos"]
        assert outcomes.get("", 0) == 0, "open disruption records at end"
        assert outcomes.get("failed", 0) == 0


class TestReplayPin:
    """Satellite: one soak_seed deterministically derives all five tiers'
    schedules plus the arrival trace — two runs from the same seed produce
    identical kill/arrival logs."""

    def _run(self, tmp_path, tag):
        cfg = smoke_config(
            sim_hours=0.5, arrival_per_minute=4.0, tpu_slices=6,
            max_wall_seconds=120.0,
        )
        h = SoakHarness(cfg, str(tmp_path / tag))
        h.run()
        terminal = {
            name: (rec.succeeded, rec.finished is not None)
            for name, rec in h.tracker.jobs.items()
        }
        return (
            h.trace.log(),
            h.orch.replay_log(),
            dict(h.orch.wire.injected),
            terminal,
        )

    def test_same_seed_identical_logs(self, tmp_path):
        a = self._run(tmp_path, "a")
        b = self._run(tmp_path, "b")
        assert a[0] == b[0], "arrival traces diverged"
        assert a[1] == b[1], "chaos action logs diverged"
        assert a[2] == b[2], "wire fault decisions diverged"
        assert a[3] == b[3], "terminal job states diverged"
        assert any(
            action in ("kill", "kill_slice") for _, _, action, _ in a[1]
        ), "replay pin is vacuous: no kills in the log"

    def test_different_seed_diverges(self, tmp_path):
        a = self._run(tmp_path, "a2")
        cfg = smoke_config(
            sim_hours=0.5, arrival_per_minute=4.0, tpu_slices=6,
            max_wall_seconds=120.0, seed=77,
        )
        h = SoakHarness(cfg, str(tmp_path / "c"))
        h.run()
        assert h.trace.log() != a[0]

    def test_derive_seed_stable(self):
        assert derive_seed(14, "sched-pod") == derive_seed(14, "sched-pod")
        assert derive_seed(14, "sched-pod") != derive_seed(14, "sched-node")
        assert derive_seed(14, "wire") != derive_seed(15, "wire")


class TestInv009:
    """The unbounded-accumulator rule, fed by FleetSources.accumulators."""

    def _auditor(self, cluster, feed):
        return InvariantAuditor(
            cluster.api, cluster.clock.now,
            sources=FleetSources(accumulators=feed),
            interval=10.0,
        )

    def test_over_bound_fires_after_grace(self):
        cluster = Cluster(VirtualClock())
        state = {"size": 100}
        auditor = self._auditor(
            cluster, lambda: {"events": (state["size"], 50)})
        assert auditor.audit() == []  # grace absorbs a sampling transient
        cluster.clock.advance(31.0)
        violations = auditor.audit()
        assert [v.rule for v in violations] == ["INV009"]
        assert violations[0].name == "events"
        # Healing (trim caught up) clears the incident.
        state["size"] = 10
        assert auditor.audit() == []

    def test_within_bound_clean(self):
        cluster = Cluster(VirtualClock())
        auditor = self._auditor(
            cluster, lambda: {"events": (50, 50), "ring": (0, 8)})
        auditor.audit()
        cluster.clock.advance(31.0)
        assert auditor.audit() == []

    def test_zero_bound_disables(self):
        cluster = Cluster(VirtualClock())
        auditor = self._auditor(cluster, lambda: {"unbounded": (10**9, 0)})
        auditor.audit()
        cluster.clock.advance(31.0)
        assert auditor.audit() == []


class TestEventCap:
    """The accumulator fix INV009 guards: the store's Event list is
    bounded (k8s events-TTL analogue), trimmed oldest-first with the
    aggregation index rebuilt."""

    def test_trim_keeps_cap_and_aggregation(self):
        api = APIServer()
        api.set_event_cap(100)
        for i in range(300):
            api.record_event(Event(
                object_kind="Pod", object_name=f"p-{i}", event_type="Normal",
                reason="Touched", message=f"m{i}", timestamp=float(i),
            ))
        assert api.event_count() <= 100
        # Newest events retained, oldest dropped.
        assert api.events(object_name="p-299")
        assert not api.events(object_name="p-0")
        # Aggregation on a RETAINED event still bumps its count in place.
        before = api.event_count()
        api.record_event(Event(
            object_kind="Pod", object_name="p-299", event_type="Normal",
            reason="Touched", message="m299", timestamp=400.0,
        ))
        assert api.event_count() == before
        assert api.events(object_name="p-299")[0].count == 2
        # A repeat of a DROPPED event starts a fresh record (count 1),
        # like an expired k8s Event recurring.
        api.record_event(Event(
            object_kind="Pod", object_name="p-0", event_type="Normal",
            reason="Touched", message="m0", timestamp=401.0,
        ))
        assert api.events(object_name="p-0")[0].count == 1

    def test_default_cap_is_generous(self):
        assert APIServer().event_cap() == 16384


class TestSustainedLoadHealing:
    """The two manager self-healing passes the soak surfaced: expired
    expectations dropped at resync, and the cascade-GC orphan sweep."""

    def test_forget_expired_drops_only_expired(self):
        from training_operator_tpu.engine.expectations import (
            ControllerExpectations,
        )

        clock = VirtualClock()
        exp = ControllerExpectations(clock.now)
        exp.expect_creations("old/worker/pods", 2)
        clock.advance(301.0)
        exp.expect_creations("new/worker/pods", 1)
        assert exp.forget_expired() == 1
        assert "old/worker/pods" not in exp.unfulfilled()
        assert "new/worker/pods" in exp.unfulfilled()
        # Fulfilled entries are not "leaks" regardless of age.
        exp.creation_observed("new/worker/pods")
        clock.advance(301.0)
        assert exp.forget_expired() == 0

    def test_resync_sweeps_cascade_orphans(self):
        from training_operator_tpu.controllers import (
            JAXController,
            OperatorManager,
        )

        cluster = Cluster(VirtualClock())
        mgr = OperatorManager(cluster, resync_period=50.0)
        mgr.register(JAXController(cluster.api))
        live = cluster.api.create(JAXJob(
            metadata=ObjectMeta(name="alive"),
            replica_specs={"Worker": ReplicaSpec(
                replicas=1,
                template=PodTemplateSpec(containers=[Container(
                    name="jax", image="trainer", resources={"cpu": 1.0},
                )]),
            )},
        ))
        cluster.run_until(
            lambda: cluster.api.list("Pod", "default"), timeout=30)
        # An orphan whose recorded owner uid resolves to nothing (its job
        # was deleted but the cascade delete was lost to a wire fault).
        orphan = Pod(metadata=ObjectMeta(
            name="orphan", namespace="default",
            labels={JOB_KIND_LABEL: "JAXJob", JOB_NAME_LABEL: "ghost"},
            owner_uid="jaxjob-default-ghost-dead",
        ))
        cluster.api.create(orphan)
        cluster.run_for(60.0)  # one resync period
        assert cluster.api.try_get("Pod", "default", "orphan") is None
        owned = cluster.api.list("Pod", "default")
        assert owned and all(p.metadata.owner_uid == live.uid for p in owned)
        mgr.stop()


class TestShardedReplicaSoak:
    """PR 15 satellite: the compressed-hour smoke with THREE sharded
    operator replicas, one of which the orchestrator kills mid-soak (the
    sixth disruption class, HostChaos-seam SIGKILL semantics). Survivors
    adopt the dead replica's shards within the grace; the fail-fast
    auditor holds INV001-INV010 (INV010 armed by the live claims feed)
    the whole time."""

    def _cfg(self, **overrides):
        base = dict(
            operator_replicas=3,
            namespaces=6,
            shard_grace_seconds=120.0,
            # Host tier off: the replica tier is this test's failure
            # domain (the failover x replica-kill product is the slow
            # tier's job, not the smoke's).
            chaos={"pod": 12.0, "api": 1.5, "wire": 1.0, "node": 18.0,
                   "host": 0.0},
        )
        base.update(overrides)
        return smoke_config(**base)

    def test_replica_kill_mid_soak_converges_audit_clean(self, tmp_path):
        h = SoakHarness(self._cfg(), str(tmp_path))
        report = h.run()
        jobs = report["jobs"]
        assert jobs["completed"] == jobs["submitted"] > 100
        assert jobs["failed"] == 0, report["jobs"]
        # The replica kill actually fired and a replica actually died.
        assert report["chaos"].get("replica:kill", 0) == 1, report["chaos"]
        shards = report["shards"]
        assert shards["replicas"] == 3 and shards["survivors"] == 2
        # The dead replica's shards were adopted: survivors cover all 3.
        owned = sorted(s for v in shards["owned"].values() for s in v)
        assert owned == [0, 1, 2]
        assert shards["handoffs"] >= 1
        # Zero INV001-INV010 violations under fail-fast the whole run.
        assert report["auditor"]["violations"] == 0
        assert report["auditor"]["audits"] > 10
        # The mix really spread across shards: multiple namespaces ran.
        namespaces = {r.namespace for r in h.tracker.jobs.values()}
        assert len(namespaces) == 6

    def test_replay_pin_holds_with_replicas(self, tmp_path):
        """Same seed, same 3-replica config -> identical arrival/chaos/
        wire logs INCLUDING the replica-kill action, and identical
        terminal states."""
        def run(tag):
            cfg = self._cfg(sim_hours=0.5, arrival_per_minute=4.0,
                            tpu_slices=6, max_wall_seconds=120.0)
            h = SoakHarness(cfg, str(tmp_path / tag))
            h.run()
            terminal = {
                name: (rec.succeeded, rec.finished is not None)
                for name, rec in h.tracker.jobs.items()
            }
            return (h.trace.log(), h.orch.replay_log(),
                    dict(h.orch.wire.injected), terminal)

        a, b = run("a"), run("b")
        assert a == b
        assert any(tier == "replica" for _, tier, _a, _t in a[1]), (
            "replay pin is vacuous: no replica kill in the log"
        )


@pytest.mark.slow
class TestSoakCompressedDay:
    def test_compressed_day_at_fleet_scale(self, tmp_path):
        """A simulated day at the full 10k-node topology with the
        bench-soak defaults: all five tiers, one failover, fail-fast
        auditing, bounded growth. (The simulated WEEK is the bench-soak
        artifact; this is its CI-sized proof.)"""
        cfg = SoakConfig(sim_hours=24.0, max_wall_seconds=900.0)
        h = SoakHarness(cfg, str(tmp_path))
        report = h.run()
        jobs = report["jobs"]
        assert jobs["completed"] == jobs["submitted"] > 2000
        assert report["auditor"]["violations"] == 0
        assert report["failover"]["replication_parity"]
        assert report["nodes"] == 10064
        for name, g in report["growth"].items():
            if isinstance(g, dict):
                assert g["within"], (name, g)
