"""Wire fast-path tests: compiled codec equivalence, serialize-once watch
fanout, the version-keyed body cache, and the drain-retry/timer-lock
regressions from the round-5 review.

The perf claims in README's wire section rest on cache behavior that is easy
to silently break (a stale body served after an update, a second encode per
subscriber sneaking back in). These tests pin the behavior via the
`training_wire_*` counters — the same counters `bench.py --wire-overhead-only`
reports — so the claim and the test measure the same thing.
"""

import dataclasses
import enum
import http.client
import json
import random
import threading
import time
import typing
from typing import Any

import pytest

from training_operator_tpu.api.jobs import ObjectMeta
from training_operator_tpu.cluster import wire
from training_operator_tpu.cluster.httpapi import (
    ApiHTTPServer,
    ApiUnavailableError,
    RemoteAPIServer,
    RemoteRuntime,
)
from training_operator_tpu.cluster.objects import ConfigMap
from training_operator_tpu.cluster.runtime import Cluster
from training_operator_tpu.utils import metrics

# ---------------------------------------------------------------------------
# Compiled codec vs reflection reference: property test over EVERY wire kind
# ---------------------------------------------------------------------------


def _build_value(hint: Any, rng: random.Random, depth: int) -> Any:
    origin = typing.get_origin(hint)
    if origin is typing.Union:
        arms = [a for a in typing.get_args(hint) if a is not type(None)]
        if not arms or rng.random() < 0.3:
            return None
        return _build_value(arms[0], rng, depth)
    if origin in (list, tuple):
        args = typing.get_args(hint)
        elem = args[0] if args else str
        return [_build_value(elem, rng, depth + 1) for _ in range(rng.randint(0, 2))]
    if origin is dict:
        args = typing.get_args(hint)
        val_t = args[1] if len(args) == 2 else str
        return {
            f"k{i}": _build_value(val_t, rng, depth + 1)
            for i in range(rng.randint(0, 2))
        }
    if hint is Any:
        return rng.choice(["s", 3, 1.5, True, None, {"n": "v"}, ["x", 2]])
    if isinstance(hint, type):
        if dataclasses.is_dataclass(hint):
            return _build_dataclass(hint, rng, depth + 1)
        if issubclass(hint, enum.Enum):
            return rng.choice(list(hint))
        if hint is str:
            return f"s{rng.randint(0, 999)}"
        if hint is bool:
            return rng.random() < 0.5
        if hint is int:
            return rng.randint(0, 99)
        if hint is float:
            return round(rng.uniform(0.0, 10.0), 3)
    return None


def _build_dataclass(cls: type, rng: random.Random, depth: int = 0) -> Any:
    hints = typing.get_type_hints(cls)
    kwargs = {
        f.name: _build_value(hints.get(f.name, Any), rng, depth)
        for f in dataclasses.fields(cls)
    }
    return cls(**kwargs)


class TestCompiledCodecEquivalence:
    """The compiled codec must be indistinguishable from the reflection
    reference (`wire.reflect_encode`/`reflect_decode`) for every registered
    kind, over randomized field populations — not hand-picked fixtures."""

    @pytest.mark.parametrize("kind", sorted(wire.KIND_REGISTRY))
    def test_randomized_round_trip_matches_reference(self, kind):
        cls = wire.KIND_REGISTRY[kind]
        rng = random.Random(hash(kind) & 0xFFFF)
        for i in range(25):
            obj = _build_dataclass(cls, rng)
            enc_compiled = wire.encode(obj)
            enc_reference = wire.reflect_encode(obj)
            assert enc_compiled == enc_reference, (kind, i)
            # Must be pure JSON data, and survive the actual wire transform.
            data = json.loads(json.dumps(enc_compiled))
            dec_compiled = wire.decode(data)
            dec_reference = wire.reflect_decode(data)
            assert dec_compiled == dec_reference, (kind, i)
            assert dec_compiled == obj, (kind, i)
            assert type(dec_compiled) is cls

    def test_codec_compiles_once_then_hits(self):
        obj = ConfigMap(metadata=ObjectMeta(name="codec-probe"), data={"a": "1"})
        wire.encode(obj)  # ensure compiled
        compiles0 = metrics.wire_codec_compiles.total()
        hits0 = metrics.wire_codec_cache_hits.total()
        for _ in range(10):
            wire.decode(wire.encode(obj))
        assert metrics.wire_codec_compiles.total() == compiles0
        assert metrics.wire_codec_cache_hits.total() - hits0 == 20


# ---------------------------------------------------------------------------
# Serialize-once fanout + version-keyed body cache, over the real HTTP stack
# ---------------------------------------------------------------------------


@pytest.fixture()
def served():
    cluster = Cluster()
    server = ApiHTTPServer(cluster.api, port=0)
    try:
        yield cluster, server
    finally:
        server.close()


class TestSerializeOnceFanout:
    def test_one_encode_per_event_with_two_subscribers(self, served):
        """N watch sessions draining the same events must cost exactly ONE
        serialization per event — the (N-1) re-encodes are cache hits,
        observable via the counters the bench reports."""
        cluster, server = served
        c1 = RemoteAPIServer(server.url, timeout=5.0)
        c2 = RemoteAPIServer(server.url, timeout=5.0)
        w1 = c1.watch()
        w2 = c2.watch()
        encodes0 = metrics.wire_event_encodes.total()
        hits0 = metrics.wire_event_cache_hits.total()
        for i in range(5):
            cluster.api.create(ConfigMap(metadata=ObjectMeta(name=f"fan-{i}")))
        ev1 = w1.drain(timeout=2.0)
        ev2 = w2.drain(timeout=2.0)
        assert len(ev1) == 5 and len(ev2) == 5
        assert metrics.wire_event_encodes.total() - encodes0 == 5, (
            "each watch event must be serialized exactly once across all sessions"
        )
        assert metrics.wire_event_cache_hits.total() - hits0 == 5, (
            "the second subscriber's drain must reuse the cached bytes"
        )
        c1.unwatch(w1)
        c2.unwatch(w2)


class TestBodyCache:
    def test_get_served_from_cache_until_version_moves(self, served):
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=5.0)
        # The create RESPONSE rides the body cache too, seeding it with the
        # stored version — so every GET of that version is a hit.
        remote.create(ConfigMap(metadata=ObjectMeta(name="bc"), data={"a": "1"}))
        hits0 = metrics.wire_body_cache_hits.total()
        misses0 = metrics.wire_body_cache_misses.total()
        g1 = remote.get("ConfigMap", "default", "bc")
        g2 = remote.get("ConfigMap", "default", "bc")
        assert g1 == g2
        assert metrics.wire_body_cache_misses.total() - misses0 == 0
        assert metrics.wire_body_cache_hits.total() - hits0 == 2

    def test_update_bumps_version_and_invalidates(self, served):
        """The stale-cache regression: an update moves resourceVersion, so
        the next GET must serve the NEW body, never the cached old bytes."""
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=5.0)
        remote.create(ConfigMap(metadata=ObjectMeta(name="stale"), data={"v": "old"}))
        g1 = remote.get("ConfigMap", "default", "stale")
        rv_old = g1.metadata.resource_version
        g1.data["v"] = "new"
        remote.update(g1)  # seeds the cache with the bumped version
        g2 = remote.get("ConfigMap", "default", "stale")
        assert g2.data["v"] == "new", "stale cached body served after update"
        # The version moved, so old bytes and new bytes live under distinct
        # keys — the cache can never hand version N's body to an N+1 read.
        assert g2.metadata.resource_version > rv_old

    def test_list_assembled_from_cached_bytes(self, served):
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=5.0)
        for i in range(4):
            remote.create(ConfigMap(metadata=ObjectMeta(name=f"l-{i}"), data={"i": str(i)}))
        first = remote.list("ConfigMap", "default")  # encodes each object once
        hits0 = metrics.wire_body_cache_hits.total()
        misses0 = metrics.wire_body_cache_misses.total()
        second = remote.list("ConfigMap", "default")
        assert {o.metadata.name for o in second} == {o.metadata.name for o in first}
        assert metrics.wire_body_cache_misses.total() - misses0 == 0
        assert metrics.wire_body_cache_hits.total() - hits0 == 4

    def test_metrics_route_exposes_counters(self, served):
        _, server = served
        remote = RemoteAPIServer(server.url, timeout=5.0)
        snap = remote.metrics_snapshot()
        assert "training_wire_codec_compiles_total" in snap


# ---------------------------------------------------------------------------
# ADVICE r5 regressions
# ---------------------------------------------------------------------------


class _BoomConn:
    """A keep-alive connection the server already closed: the next use dies
    with RemoteDisconnected (exactly how a stale connection fails)."""

    def __init__(self):
        self.used = False

    def request(self, *a, **k):
        self.used = True
        raise http.client.RemoteDisconnected("server closed idle connection")

    def close(self):
        pass


class TestWatchDrainNotRetried:
    """ADVICE r5: GET /watches/{id} is a DESTRUCTIVE read — the server
    empties the queue into the response. A transparent stale-keep-alive
    retry would drop those events forever; the client must surface
    ApiUnavailableError and heal by relist instead."""

    def test_plain_get_still_transparently_retried(self, served):
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=5.0)
        remote.list("Pod")  # warm the connection
        boom = _BoomConn()
        remote._local.conn_main = boom
        assert remote.list("Pod") == []  # retried on a fresh connection
        assert boom.used

    def test_watch_poll_raises_and_marks_relist(self, served):
        cluster, server = served
        remote = RemoteAPIServer(server.url, timeout=5.0)
        wq = remote.watch()
        cluster.api.create(ConfigMap(metadata=ObjectMeta(name="pre")))
        assert len(wq.drain(timeout=1.0)) == 1
        boom = _BoomConn()
        remote._local.conn_watch = boom
        with pytest.raises(ApiUnavailableError):
            wq.drain(timeout=1.0)
        assert boom.used, "poisoned watch connection was never exercised"
        assert remote._shared_watch._needs_relist is True
        # Recovery: the next drain heals by watermark resume — the write
        # that raced the failure is REPLAYED from the server's resume ring
        # (delayed, never lost), while "pre" (already observed, watermark
        # covers it) is NOT duplicated: exactly-once, not at-least-once.
        cluster.api.create(ConfigMap(metadata=ObjectMeta(name="during-outage")))
        names = {e.obj.metadata.name for e in wq.drain(timeout=1.0)}
        assert "during-outage" in names and "pre" not in names
        remote.unwatch(wq)


class TestRemoteRuntimeTimerLock:
    """ADVICE r5: schedule_after from concurrent reconcile workers must not
    corrupt the timer heap (silently delayed/dropped requeues)."""

    def test_concurrent_schedule_after_fires_every_timer(self, served):
        _, server = served
        rt = RemoteRuntime(RemoteAPIServer(server.url, timeout=5.0),
                           tick_interval=0.0)
        fired = []
        lock = threading.Lock()

        def bump():
            with lock:
                fired.append(1)

        def worker():
            for _ in range(200):
                rt.schedule_after(0.0, bump)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        heap = rt._timers
        assert len(heap) == 1600
        # Heap invariant must hold after concurrent pushes.
        for i in range(1, len(heap)):
            assert heap[(i - 1) // 2][:2] <= heap[i][:2]
        deadline = time.monotonic() + 10.0
        while len(fired) < 1600 and time.monotonic() < deadline:
            rt.step()
        assert len(fired) == 1600
