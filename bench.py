#!/usr/bin/env python
"""Headline benchmark: 1k-job cold-start burst on a heterogeneous TPU+GPU pool.

BASELINE.md configs 2 & 5: 1000 jobs (JAX TPU gangs of several shapes, GPU DDP
gangs, CPU jobs) submitted at t=0 against 48 v5e-4x4 slices + 32x8-GPU nodes +
CPU pool. Two full simulation runs, identical workload:

  baseline — volcano-style gang scheduling (BaselinePlacer whole-slice mode:
             topology-unaware schedulers force slice-granularity dedication,
             so sub-slice jobs strand the rest of their slice)
  packer   — the JAX batched placement engine (TPUPacker: contiguous ICI
             sub-mesh packing, best-fit anti-fragmentation)
  (--all-baselines adds the stronger contiguity-aware first-fit straw-man)

The cluster runs on a virtual clock; each scheduler's real solve wall-time is
charged into simulated time (GangScheduler charge_solve_time), so the p50
schedule-to-running latency reflects both queueing quality (fragmentation)
and actual solver speed on this machine's accelerator.

Prints ONE JSON line:
  metric      p50 schedule-to-running latency of the packer run (seconds)
  vs_baseline baseline_p50 / packer_p50  (>1 = packer faster)
  seeds       per-seed p50/vs_baseline for --seeds independent workloads
              plus min/median aggregates — the headline is the PRIMARY
              seed, the stability claim quotes the MIN.
  extras      p90/p99, makespan, TPU-chip utilization %, fragmentation score
              (share of free TPU hosts stranded in partially-used slices,
              time-averaged), solver wall time, and two zero-cost greedy
              REFERENCE disciplines (not lower bounds — the packer is
              expected to beat them): oracle_fungible (SJF on fungible
              chips, no hosts/contiguity) and oracle_granular (SJF honoring
              ICI contiguity + node granularity). vs_granular_oracle < 1
              means the packer out-schedules the greedy granular reference.

Usage: python bench.py [--jobs N] [--seed S] [--seeds K] [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

# The lock-order witness is OFF in benches unless the --lockcheck arm is
# requested; utils/locks.py samples the env once at import, and the
# package imports right below construct module-level locks, so the flag
# must be set before them.
if "--lockcheck" in sys.argv or "--lockcheck-only" in sys.argv:
    os.environ.setdefault("TRAINING_LOCKCHECK", "1")

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import Container, JobConditionType, PodTemplateSpec, ReplicaSpec
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta, PyTorchJob, TFJob, TPUPolicy
from training_operator_tpu.cluster.inventory import (
    GPU_RESOURCE,
    TPU_RESOURCE,
    make_cpu_pool,
    make_gpu_pool,
    make_tpu_pool,
)
from training_operator_tpu.cluster.objects import PodGroupPhase, PodPhase
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
)
from training_operator_tpu.controllers import OperatorManager, register_all
from training_operator_tpu.scheduler import BaselinePlacer, GangScheduler, TPUPacker
from training_operator_tpu.scheduler.snapshot import ANNOTATION_EXPECTED_DURATION


# One shared pool geometry for the measured runs AND the oracle bounds —
# if these drift apart the published vs_*_oracle numbers are silently wrong.
TPU_SLICES = 48
HOSTS_PER_SLICE = 4
SLICE_TOPOLOGY = "4x4"
GPU_NODES = 32
GPUS_PER_NODE = 8
CPU_NODES = 16
CPU_PER_NODE = 64.0


def _chips(shape: str) -> int:
    chips = 1
    for d in shape.split("x"):
        chips *= int(d)
    return chips


CHIPS_PER_SLICE = _chips(SLICE_TOPOLOGY)


def _pct(sorted_vals, p):
    return sorted_vals[min(len(sorted_vals) - 1, int(p * len(sorted_vals)))] if sorted_vals else 0.0


def build_workload(n_jobs: int, seed: int):
    """Deterministic job mix: (kind, name, shape, workers, num_slices,
    sim_duration, declared_duration). `declared` is what the user TELLS the
    scheduler (ANNOTATION_EXPECTED_DURATION); `sim` is the truth. They start
    equal (the oracle condition); perturb_declared() degrades them."""
    rng = random.Random(seed)
    specs = []
    for i in range(n_jobs):
        r = rng.random()
        dur = str(rng.randint(30, 120))
        if r < 0.35:
            specs.append(("jax", f"jax-sub-{i}", "2x4", 2, 1, dur, dur))
        elif r < 0.55:
            specs.append(("jax", f"jax-host-{i}", "1x4", 1, 1, dur, dur))
        elif r < 0.70:
            specs.append(("jax", f"jax-full-{i}", "4x4", 4, 1, dur, dur))
        elif r < 0.75:
            specs.append(("jax", f"jax-multi-{i}", "4x4", 8, 2, dur, dur))
        elif r < 0.90:
            gpus = rng.choice([4.0, 8.0])
            workers = rng.choice([2, 4])
            specs.append(("gpu", f"ddp-{i}", gpus, workers, 1, dur, dur))
        else:
            specs.append(("cpu", f"tf-{i}", 2.0, rng.choice([1, 2]), 1, dur, dur))
    return specs


def perturb_declared(specs, seed: int, noise_factor: float = 3.0, missing_frac: float = 0.0):
    """Degrade the user estimates: multiply each declared duration by
    exp(U(-ln f, +ln f)) — i.e. off by up to x/÷ `noise_factor` — and drop a
    `missing_frac` share entirely (declared=None -> no annotation). The sim
    (true) durations are untouched, so results compare directly against the
    oracle-estimate runs."""
    import math

    rng = random.Random(seed ^ 0x5EED)
    out = []
    for kind, name, shape, workers, num_slices, dur, _decl in specs:
        if missing_frac and rng.random() < missing_frac:
            declared = None
        else:
            mult = math.exp(rng.uniform(-math.log(noise_factor), math.log(noise_factor)))
            declared = str(max(1, round(float(dur) * mult)))
        out.append((kind, name, shape, workers, num_slices, dur, declared))
    return out


def make_job(spec):
    kind, name, shape, workers, num_slices, dur, declared = spec
    if kind == "jax":
        chips = 1
        for d in shape.split("x"):
            chips *= int(d)
        t = PodTemplateSpec(
            containers=[Container(name="jax", image="trainer",
                                  resources={"cpu": 1.0, TPU_RESOURCE: 4.0})]
        )
        t.annotations[ANNOTATION_SIM_DURATION] = dur
        if declared is not None:
            t.annotations[ANNOTATION_EXPECTED_DURATION] = declared
        return JAXJob(
            metadata=ObjectMeta(name=name),
            replica_specs={"Worker": ReplicaSpec(replicas=workers, template=t)},
            tpu_policy=TPUPolicy(accelerator=f"v5e-{chips}", topology=shape,
                                 num_slices=num_slices),
        )
    if kind == "gpu":
        t = PodTemplateSpec(
            containers=[Container(name="pytorch", image="trainer",
                                  resources={"cpu": 2.0, GPU_RESOURCE: shape})]
        )
        t.annotations[ANNOTATION_SIM_DURATION] = dur
        if declared is not None:
            t.annotations[ANNOTATION_EXPECTED_DURATION] = declared
        return PyTorchJob(
            metadata=ObjectMeta(name=name),
            replica_specs={"Worker": ReplicaSpec(replicas=workers, template=t)},
        )
    t = PodTemplateSpec(
        containers=[Container(name="tensorflow", image="trainer",
                              resources={"cpu": shape})]
    )
    t.annotations[ANNOTATION_SIM_DURATION] = dur
    if declared is not None:
        t.annotations[ANNOTATION_EXPECTED_DURATION] = declared
    return TFJob(
        metadata=ObjectMeta(name=name),
        replica_specs={"Worker": ReplicaSpec(replicas=workers, template=t)},
    )


def oracle_bound(
    specs,
    tpu_chips=TPU_SLICES * float(CHIPS_PER_SLICE),
    gpus=GPU_NODES * float(GPUS_PER_NODE),
    cpus=CPU_NODES * CPU_PER_NODE,
):
    """Fluid-limit greedy reference: fungible capacity (no hosts, no
    contiguity, no scheduler latency), smallest-demand-first admission —
    what a topology-free greedy-SJF scheduler would achieve. A comparison
    point for interpreting the measured p50, not a provable bound (greedy
    SJF admission is not p50-optimal)."""
    import heapq

    pools = {"tpu": tpu_chips, "gpu": gpus, "cpu": cpus}
    jobs = {"tpu": [], "gpu": [], "cpu": []}
    for kind, _name, shape, workers, num_slices, dur, _decl in specs:
        if kind == "jax":
            jobs["tpu"].append((_chips(shape) * num_slices, float(dur)))
        elif kind == "gpu":
            jobs["gpu"].append((shape * workers, float(dur)))
        else:
            jobs["cpu"].append((shape * workers, float(dur)))
    starts = []
    makespan = 0.0
    for pool, pj in jobs.items():
        free = pools[pool]
        heap = []  # (finish_time, demand)
        t = 0.0
        for demand, dur in sorted(pj):
            if demand > pools[pool] + 1e-9:
                continue  # infeasible at any time: excluded from the bound
            while free < demand - 1e-9:
                finish, rd = heapq.heappop(heap)
                t = max(t, finish)
                free += rd
            starts.append(t)
            free -= demand
            heapq.heappush(heap, (t + dur, demand))
            makespan = max(makespan, t + dur)
    starts.sort()
    return {
        "p50_s": round(_pct(starts, 0.50), 3),
        "p90_s": round(_pct(starts, 0.90), 3),
        "p99_s": round(_pct(starts, 0.99), 3),
        "makespan_s": round(makespan, 1),
    }


def granular_oracle(
    specs,
    tpu_slices=TPU_SLICES,
    hosts_per_slice=HOSTS_PER_SLICE,
    gpu_nodes=GPU_NODES,
    cpus=CPU_NODES * CPU_PER_NODE,
):
    """Granularity-constrained greedy REFERENCE: demand-sorted SJF with ZERO
    scheduling cost, honoring the physical constraints any real placer must —
    ICI contiguity (1x4 = 1 host, 2x4 = adjacent host pair, 4x4 = whole
    slice, multi-slice = distinct whole slices) and node granularity on the
    GPU pool. NOT a lower bound: greedy SJF admission is not p50-optimal
    (the packer's duration-aware discipline beats it), so this is a
    comparison point that contextualizes the measured p50, nothing more.
    The gap between it and `oracle_bound` (fungible chips) is the price of
    physics at this granularity under the same greedy discipline."""
    import heapq

    S, H, N = tpu_slices, hosts_per_slice, gpu_nodes
    tpu_free = [[True] * H for _ in range(S)]
    gpu_free = [8.0] * N
    cpu_free = cpus
    jobs = []
    for kind, _name, shape, workers, num_slices, dur, _decl in specs:
        if kind == "jax":
            jobs.append(("tpu", _chips(shape) * num_slices, float(dur), shape, num_slices))
        elif kind == "gpu":
            jobs.append(("gpu", shape * workers, float(dur), shape, workers))
        else:
            jobs.append(("cpu", shape * workers, float(dur), None, workers))
    jobs.sort(key=lambda j: j[1])
    hosts_needed = {"1x4": 1, "2x4": 2, "4x4": 4}

    def place(job):
        nonlocal cpu_free
        pool, demand, _dur, shape, k = job
        if pool == "cpu":
            if cpu_free >= demand:
                cpu_free -= demand
                return ("cpu", demand)
            return None
        if pool == "gpu":
            got = []
            for _ in range(k):
                best = None
                for n in range(N):
                    if gpu_free[n] >= shape and (
                        best is None or gpu_free[n] < gpu_free[best]
                    ):
                        best = n
                if best is None:
                    for n, v in got:
                        gpu_free[n] += v
                    return None
                gpu_free[best] -= shape
                got.append((best, shape))
            return ("gpu", got)
        need = hosts_needed.get(shape)
        if need is None:
            return None
        got = []
        for _ in range(k):
            best = None
            for s in range(S):
                if any(s == g[0] for g in got):
                    continue  # multi-slice shares ride distinct slices
                fr = [h for h in range(H) if tpu_free[s][h]]
                if len(fr) < need:
                    continue
                if need == 2:
                    cand = None
                    for h in range(H - 1):
                        if tpu_free[s][h] and tpu_free[s][h + 1]:
                            cand = [h, h + 1]
                            break
                    if cand is None:
                        continue
                elif need == 1:
                    cand = [fr[0]]
                else:
                    if len(fr) < H:
                        continue
                    cand = fr
                if best is None or len(fr) < best[0]:
                    best = (len(fr), s, cand)  # best-fit: fullest slice
            if best is None:
                for s, hl in got:
                    for h in hl:
                        tpu_free[s][h] = True
                return None
            _, s, cand = best
            for h in cand:
                tpu_free[s][h] = False
            got.append((s, cand))
        return ("tpu", got)

    def release(token):
        nonlocal cpu_free
        pool, d = token
        if pool == "cpu":
            cpu_free += d
        elif pool == "gpu":
            for n, v in d:
                gpu_free[n] += v
        else:
            for s, hl in d:
                for h in hl:
                    tpu_free[s][h] = True

    def placeable_ever(job):
        pool, demand, _dur, shape, k = job
        if pool == "cpu":
            return demand <= cpus + 1e-9
        if pool == "gpu":
            # Must match place(): multiple workers can share a node, so k
            # workers of `shape` GPUs fit iff k <= N * floor(8/shape).
            return 0 < shape <= 8.0 and k <= N * int(8.0 // shape)
        return shape in hosts_needed and k <= S
    pending = [j for j in jobs if placeable_ever(j)]
    events = []
    t = 0.0
    starts = []
    while pending:
        rem = []
        for job in pending:
            tok = place(job)
            if tok is not None:
                starts.append(t)
                heapq.heappush(events, (t + job[2], tok))
            else:
                rem.append(job)
        pending = rem
        if not pending:
            break
        if not events:
            break  # nothing running yet nothing placeable: report what we have
        t2, tok = heapq.heappop(events)
        t = max(t, t2)
        release(tok)
        while events and events[0][0] <= t:
            _, tok = heapq.heappop(events)
            release(tok)
    starts.sort()
    return {"p50_s": round(_pct(starts, 0.50), 3), "p90_s": round(_pct(starts, 0.90), 3), "p99_s": round(_pct(starts, 0.99), 3)}


# Module default for run_burst's fail-fast auditor (set by --audit): every
# headline burst then runs under the standing invariant checker, and one
# violation anywhere fails the whole bench run.
AUDIT_BURSTS = False
AUDIT_INTERVAL_S = 15.0


def run_burst(specs, placer, tpu_slices=TPU_SLICES, gpu_nodes=GPU_NODES, cpu_nodes=CPU_NODES,
              return_latencies=False, chrome_trace=None, audit=None,
              incremental=True, extra_setup=None):
    cluster = Cluster(VirtualClock())
    cluster.add_nodes(make_tpu_pool(tpu_slices, slice_topology=SLICE_TOPOLOGY))
    cluster.add_nodes(make_gpu_pool(gpu_nodes, gpus_per_node=GPUS_PER_NODE, nodes_per_nvlink_domain=4))
    cluster.add_nodes(make_cpu_pool(cpu_nodes, cpu_per_node=CPU_PER_NODE))
    DefaultScheduler(cluster)
    SimKubelet(cluster)
    import inspect

    sched_kwargs = dict(
        charge_solve_time=True, prewarm=True, min_solve_interval=0.25,
        incremental=incremental,
    )
    # This harness also runs inside pre-PR worktrees (the bench-wire-v2
    # method): drop kwargs that code version does not know.
    known = inspect.signature(GangScheduler.__init__).parameters
    sched_kwargs = {k: v for k, v in sched_kwargs.items() if k in known}
    sched = GangScheduler(cluster, placer, **sched_kwargs)
    mgr = OperatorManager(cluster, gang_enabled=True, reconciles_per_tick=4096)
    register_all(mgr)
    auditor = None
    audit_enabled = AUDIT_BURSTS if audit is None else audit
    if audit_enabled:
        # Standing invariant checker in fail-fast mode: the rule catalog
        # audits the live store every AUDIT_INTERVAL_S of virtual time and
        # a single violation raises out of the tick — the burst becomes an
        # invariant regression test, not just a latency measurement.
        from training_operator_tpu.observe import FleetSources, InvariantAuditor

        auditor = InvariantAuditor(
            cluster.api,
            cluster.clock.now,
            sources=FleetSources(expectations=mgr.unfulfilled_expectations),
            interval=AUDIT_INTERVAL_S,
            fail_fast=True,
        ).attach(cluster)

    # Optional burst-resident instrumentation (the SLO-overhead block rides
    # this): called with the live cluster before submission; may register
    # tickers and may return a finalizer to run at quiescence, all inside
    # the measured wall.
    finalize = extra_setup(cluster) if extra_setup is not None else None

    jobs = [make_job(s) for s in specs]
    t_wall = time.perf_counter()
    for j in jobs:
        mgr.submit(j)

    total_chips = tpu_slices * float(CHIPS_PER_SLICE)
    # Schedule-to-running is captured from job status-update watch events
    # (the Running condition is cleared by terminal conditions, so it must be
    # read while live). O(events), not O(cluster x steps).
    running_at = {}
    finished = set()
    job_kinds = {j.kind for j in jobs}
    watch = cluster.api.watch(kinds=job_kinds)

    def track():
        for ev in watch.drain():
            if ev.type != "Modified":
                continue
            j = ev.obj
            if capi.is_finished(j.status):
                finished.add(j.name)
            if j.name in running_at:
                continue
            cond = capi.get_condition(j.status, JobConditionType.RUNNING)
            if cond is not None and cond.status:
                running_at[j.name] = cond.last_transition_time

    cluster.add_ticker(track)

    # Fragmentation sampler (BASELINE.md config 5 requires the score):
    # of the TPU hosts currently free, what fraction sit in partially-used
    # slices (i.e. cannot serve a whole-slice gang and constrain sub-mesh
    # shapes)? 0 = all free capacity is whole slices; 1 = all fragments.
    slice_hosts = {}
    for n in cluster.api.list("Node"):
        if n.accelerator.kind == "tpu" and n.accelerator.tpu_slice:
            slice_hosts.setdefault(n.accelerator.tpu_slice, []).append(n.name)
    frag_samples = []
    frag_state = {"next": 0.0}

    def frag_tick():
        now = cluster.clock.now()
        if now < frag_state["next"]:
            return
        frag_state["next"] = now + 5.0
        used = set()
        for p in cluster.informer.list("Pod"):
            if p.node_name and not p.is_terminal() and p.resources().get(TPU_RESOURCE, 0):
                used.add(p.node_name)
        for pg in cluster.informer.list("PodGroup"):
            if pg.phase in (PodGroupPhase.INQUEUE, PodGroupPhase.RUNNING):
                used.update(pg.reserved_nodes)
                used.update(pg.placement.values())
        free_hosts = 0
        whole_free = 0
        for hosts in slice_hosts.values():
            free = sum(1 for h in hosts if h not in used)
            free_hosts += free
            if free == len(hosts):
                whole_free += free
        if free_hosts:
            frag_samples.append(1.0 - whole_free / free_hosts)

    cluster.add_ticker(frag_tick)

    def all_done():
        # Copy-on-read: submitted objects never mutate in our hands; terminal
        # states are collected from watch events in track() above.
        return len(finished) >= len(jobs)

    ok = cluster.run_until(all_done, timeout=50_000, max_steps=5_000_000)
    wall = time.perf_counter() - t_wall
    if not ok:
        raise RuntimeError(f"burst did not finish: {len(jobs) - len(finished)} jobs pending")
    if auditor is not None:
        # Closing audit at quiescence: the converged fleet must be clean
        # too (orphans/wedged expectations would survive the burst).
        auditor.audit()
    if callable(finalize):
        finalize()

    latencies = []
    by_name = {} if return_latencies else None
    for j in jobs:
        created = j.metadata.creation_time or 0.0
        if j.name in running_at:
            lat = running_at[j.name] - created
            latencies.append(lat)
            if by_name is not None:
                by_name[j.name] = lat
    latencies.sort()

    # Utilization post-hoc from pod lifetimes: chip-seconds / capacity.
    makespan = cluster.clock.now()
    busy_area = 0.0
    cluster.informer.sync()  # absorb the final completion events
    for p in cluster.informer.list("Pod"):
        chips = p.resources().get(TPU_RESOURCE, 0.0)
        if chips and p.status.start_time is not None:
            end = p.status.finish_time if p.status.finish_time is not None else makespan
            busy_area += chips * (end - p.status.start_time)
    utilization = busy_area / (total_chips * makespan) if makespan else 0.0
    out = {
        "p50_s": round(_pct(latencies, 0.50), 3),
        "p90_s": round(_pct(latencies, 0.90), 3),
        "p99_s": round(_pct(latencies, 0.99), 3),
        "makespan_s": round(makespan, 1),
        "tpu_utilization": round(utilization, 4),
        "fragmentation": round(sum(frag_samples) / len(frag_samples), 4)
        if frag_samples
        else 0.0,
        "solver_wall_s": round(sched.solve_walltime_total, 3),
        "solver_cycles": sched.cycles,
        "solver_incremental_cycles": sum(
            1 for r in sched.trace if r.get("mode") == "incremental"
        ),
        "solver_groups_solved": sum(r.get("pending", 0) for r in sched.trace),
        "bench_wall_s": round(wall, 1),
        "jobs_measured": len(latencies),
    }
    if auditor is not None:
        out["audit"] = {
            "audits": auditor.audits,
            "violations": len(auditor.last_violations),
            "fail_fast": True,
        }
    if return_latencies:
        # Diagnostic-only (never serialized into the headline JSON): the
        # per-job latencies behind the percentiles, for tail analysis.
        out["latencies_by_name"] = by_name
    if chrome_trace:
        # Offline flame view of the burst's job-lifecycle phase structure
        # (admission / queue-wait / gang-solve / bind / time-to-running
        # spans per job) — load in chrome://tracing or Perfetto.
        from training_operator_tpu.observe import export_chrome_trace

        export_chrome_trace(cluster.api.timelines, chrome_trace)
    return out


# ---------------------------------------------------------------------------
# Wire overhead: the flagship deployment shape (host + operator as separate
# OS processes over HTTPS) vs the identical stack in-process.
# ---------------------------------------------------------------------------


def _read_announcement(proc, prefix, timeout=45.0):
    from training_operator_tpu.utils.procio import read_announcement

    return read_announcement(proc, prefix, timeout=timeout)


def _proc_cpu_seconds(pid: int) -> float:
    """utime+stime of a process from /proc (Linux)."""
    import os as _os

    try:
        with open(f"/proc/{pid}/stat") as f:
            fields = f.read().rsplit(")", 1)[1].split()
        ticks = int(fields[11]) + int(fields[12])  # utime, stime
        return ticks / _os.sysconf("SC_CLK_TCK")
    except (OSError, IndexError, ValueError):
        return float("nan")


def _overhead_jobs(n: int):
    """Control-plane-bound workload: tiny CPU pods on an uncontended pool,
    so submit->Running latency measures the control plane (admission,
    reconcile, scheduling hop, kubelet flip), not queueing."""
    jobs = []
    for i in range(n):
        tmpl = PodTemplateSpec(
            containers=[Container(name="jax", image="trainer",
                                  resources={"cpu": 0.25})],
            annotations={ANNOTATION_SIM_DURATION: "2.0"},
        )
        jobs.append(JAXJob(
            metadata=ObjectMeta(name=f"wire-{i}"),
            replica_specs={"Worker": ReplicaSpec(replicas=1, template=tmpl)},
        ))
    return jobs


def _submit_to_running_percentiles(jobs_live, pods):
    """submit -> pod-started latency per job: first pod start_time (stamped
    by the host kubelet) minus job creation_time (stamped by host
    admission). Both host-clock, and neither depends on the OPERATOR
    observing the transient Running state — a fast job can legitimately go
    Created -> Succeeded in job conditions, but its pod still carries the
    start timestamp. The operator's contribution (watch delivery + pod
    creation over the wire) sits on this path."""
    started_by_job = {}
    for p in pods:
        job = p.metadata.labels.get("training.tpu.dev/job-name")
        if job and p.status.start_time is not None:
            cur = started_by_job.get(job)
            if cur is None or p.status.start_time < cur:
                started_by_job[job] = p.status.start_time
    lats = []
    for j in jobs_live:
        if j is None or j.metadata.creation_time is None:
            continue
        started = started_by_job.get(j.metadata.name)
        if started is not None:
            lats.append(started - j.metadata.creation_time)
    lats.sort()
    return {
        "jobs_measured": len(lats),
        "submit_to_running_p50_s": round(_pct(lats, 0.50), 4),
        "submit_to_running_p90_s": round(_pct(lats, 0.90), 4),
        "submit_to_running_p99_s": round(_pct(lats, 0.99), 4),
    }


def _tls_available() -> bool:
    """The host role mints its CA via the `cryptography` package; a build
    container without it can still measure the wire path over cleartext
    loopback HTTP (the transport field records which mode ran)."""
    try:
        import cryptography  # noqa: F401

        return True
    except ImportError:
        return False


def _wire_leg(n_jobs: int):
    """host + 1 operator as real OS processes over HTTPS (the shipped
    default: TLS on, cond-var long-poll watches), submission via the SDK.
    Falls back to loopback HTTP where the TLS dependency is absent."""
    import os as _os
    import tempfile

    from training_operator_tpu.sdk.client import TrainingClient
    from training_operator_tpu.utils.procio import spawn_module_process

    tmp = tempfile.mkdtemp(prefix="wire-bench-")
    inv = _os.path.join(tmp, "cluster.json")
    with open(inv, "w") as f:
        json.dump({"cpu_pools": [{"nodes": CPU_NODES, "cpu_per_node": CPU_PER_NODE}]}, f)
    repo = _os.path.dirname(_os.path.abspath(__file__))
    tls = _tls_available()

    def spawn(*a):
        # Control-plane processes never touch the accelerator (gang
        # scheduler off); keep their JAX imports off the TPU plugin,
        # whose backend init can hang when the tunnel is down.
        return spawn_module_process(a, repo, env_extra={"JAX_PLATFORMS": "cpu"})

    host_args = ["--role", "host", "--serve-port", "0",
                 "--gang-scheduler-name", "none", "--cluster", inv]
    if not tls:
        host_args.append("--insecure")
    host = spawn(*host_args)
    procs = [host]
    try:
        url = _read_announcement(host, "WIRE_API=")
        ca = _read_announcement(host, "WIRE_CA=") if tls else None
        op_args = ["--role", "operator", "--api-server", url,
                   "--enable-scheme", "jax", "--gang-scheduler-name", "none"]
        if ca:
            op_args += ["--ca-cert", ca]
        op = spawn(*op_args)
        procs.append(op)
        _read_announcement(op, "OPERATOR_UP=")

        client = TrainingClient(url, ca_file=ca)
        cpu_before = _proc_cpu_seconds(host.pid)
        t0 = time.monotonic()
        for job in _overhead_jobs(n_jobs):
            client.create_job(job)
        submit_wall = time.monotonic() - t0

        deadline = time.monotonic() + 120
        api = client.api
        while time.monotonic() < deadline:
            pods = api.list("Pod", "default")
            if sum(1 for p in pods if p.status.start_time is not None) >= n_jobs:
                break
            time.sleep(0.25)
        wall = time.monotonic() - t0
        host_cpu = _proc_cpu_seconds(host.pid) - cpu_before
        out = _submit_to_running_percentiles(
            api.list("JAXJob", "default"), api.list("Pod", "default")
        )
        out.update({
            "submit_wall_s": round(submit_wall, 3),
            "burst_wall_s": round(wall, 2),
            "host_cpu_s": round(host_cpu, 2),
            "host_cpu_share": round(host_cpu / wall, 3) if wall > 0 else None,
        })

        # Watch-event delivery latency across the wire: write -> event seen
        # by a long-polling subscriber (exercises the cond-var path; a spin
        # server would show up here as burned host CPU instead of latency).
        from training_operator_tpu.cluster.objects import ConfigMap

        wq = api.watch(kinds=["ConfigMap"])
        import threading as _threading

        deltas = []
        seen = _threading.Event()

        def drainer():
            while not seen.is_set():
                for ev in wq.drain(timeout=2.0):
                    deltas.append(time.monotonic() - pending[0])
                    got.set()

        pending = [0.0]
        got = _threading.Event()
        t = _threading.Thread(target=drainer, daemon=True)
        t.start()
        for i in range(30):
            got.clear()
            pending[0] = time.monotonic()
            api.create(ConfigMap(metadata=ObjectMeta(name=f"w-probe-{i}")))
            got.wait(5.0)
        seen.set()
        t.join(timeout=5.0)
        api.unwatch(wq)
        deltas.sort()
        out["watch_delivery_p50_ms"] = round(1000 * _pct(deltas, 0.50), 1)
        out["watch_delivery_p95_ms"] = round(1000 * _pct(deltas, 0.95), 1)

        # Wire-cache hit rates from the HOST's registry (GET /metrics) — the
        # direct evidence for the serialize-once/body-cache claims, readable
        # by the driver instead of trusted from a self-run.
        try:
            snap = api.metrics_snapshot()
            hits = snap.get("training_wire_body_cache_hits_total", 0.0)
            misses = snap.get("training_wire_body_cache_misses_total", 0.0)
            enc = snap.get("training_wire_event_encodes_total", 0.0)
            reuse = snap.get("training_wire_event_cache_hits_total", 0.0)
            out["wire_cache"] = {
                "codec_cache_hits": snap.get("training_wire_codec_cache_hits_total", 0.0),
                "codec_compiles": snap.get("training_wire_codec_compiles_total", 0.0),
                "body_cache_hits": hits,
                "body_cache_misses": misses,
                "body_cache_hit_rate": round(hits / (hits + misses), 3)
                if hits + misses else None,
                "event_encodes": enc,
                "event_cache_hits": reuse,
            }
            # Wire protocol v2 counters (absent on an old host -> zeros):
            # ops/requests > 1 is the round-trips-saved evidence, coalesced
            # is the client-reported last-write-wins merge count.
            out["wire_v2"] = {
                "batch_requests": snap.get("training_wire_batch_requests_total", 0.0),
                "batch_ops": snap.get("training_wire_batch_ops_total", 0.0),
                "batch_coalesced": snap.get("training_wire_batch_coalesced_total", 0.0),
                "list_pages": snap.get("training_wire_list_pages_total", 0.0),
            }
        except Exception:  # noqa: BLE001 — bench must survive an old host
            out["wire_cache"] = None
        return out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.communicate(timeout=10)
            except Exception:
                pass


def _inproc_leg(n_jobs: int):
    """The identical stack in ONE process (standalone role): same admission,
    controllers, scheduler, kubelet; no sockets."""
    from training_operator_tpu.api.defaults import default_job
    from training_operator_tpu.api.validation import validate_job
    from training_operator_tpu.cluster.runtime import Clock, WallClock
    from training_operator_tpu.controllers.jax import JAXController

    cluster = Cluster(WallClock())
    cluster.add_nodes(make_cpu_pool(CPU_NODES, cpu_per_node=CPU_PER_NODE))

    def admit(job):
        default_job(job, now=cluster.clock.now())
        validate_job(job)

    cluster.api.register_admission("JAXJob", admit)
    DefaultScheduler(cluster)
    SimKubelet(cluster)
    mgr = OperatorManager(cluster, gang_enabled=False)
    mgr.register(JAXController(cluster.api))

    jobs = _overhead_jobs(n_jobs)
    t0 = time.monotonic()
    for job in jobs:
        cluster.api.create(job)
    # Drive the loop the way the standalone process main loop does.
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        cluster.step()
        pods = cluster.api.list("Pod", "default")
        if sum(1 for p in pods if p.status.start_time is not None) >= n_jobs:
            break
        time.sleep(0.01)
    wall = time.monotonic() - t0
    out = _submit_to_running_percentiles(
        cluster.api.list("JAXJob", "default"), cluster.api.list("Pod", "default")
    )
    out["burst_wall_s"] = round(wall, 2)
    mgr.stop()
    return out


def run_wire_overhead(n_jobs: int = 200):
    """The wire_overhead bench block (VERDICT r4 missing #4): the flagship
    deployment shape must add bounded overhead over in-process — target
    <= 1.5x on submit->Running p50 at the 200-job scale."""
    inproc = _inproc_leg(n_jobs)
    wire = _wire_leg(n_jobs)
    ratio = None
    if inproc.get("submit_to_running_p50_s") and wire.get("submit_to_running_p50_s"):
        ratio = round(
            wire["submit_to_running_p50_s"] / inproc["submit_to_running_p50_s"], 3
        )
    return {
        "jobs": n_jobs,
        "transport": (
            "https (TLS default, CA-pinned client)" if _tls_available()
            else "http (loopback; TLS dep unavailable on this machine)"
        ),
        "inproc": inproc,
        "wire": wire,
        "overhead_ratio_p50": ratio,
    }


def run_wire_ab(pairs: int, before_repo: str, n_jobs: int, out_path: str):
    """Interleaved before/after wire_overhead pairs (the BENCH_SELF_WIRE_r06
    method): each leg is a fresh `bench.py --wire-overhead-only` SUBPROCESS
    run from its own repo root, so the two code versions never share process
    state, and the pairs interleave so machine-load drift hits both sides.
    The 'before' repo is a worktree of the pre-change ref carrying THIS
    harness (harness-only differences don't affect measured code)."""
    import os as _os
    import subprocess

    repo = _os.path.dirname(_os.path.abspath(__file__))

    def leg(cwd):
        env = dict(_os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "bench.py", "--wire-overhead-only",
             "--wire-jobs", str(n_jobs)],
            cwd=cwd, env=env, capture_output=True, text=True, timeout=900,
        )
        lines = [ln for ln in proc.stdout.strip().splitlines() if ln.startswith("{")]
        if proc.returncode != 0 or not lines:
            raise RuntimeError(
                f"wire leg in {cwd} failed (rc={proc.returncode}): "
                f"{proc.stderr[-2000:]}"
            )
        return json.loads(lines[-1])["wire_overhead"]

    runs = []
    for i in range(pairs):
        try:
            before = leg(_os.path.abspath(before_repo))
            after = leg(repo)
        except (RuntimeError, subprocess.TimeoutExpired) as e:
            # One hung/failed leg must not discard hours of completed
            # pairs: the artifact is rewritten after every pair below, so
            # salvage what finished and stop.
            print(f"pair {i + 1}/{pairs} failed ({e}); keeping "
                  f"{len(runs)} completed pair(s)", file=sys.stderr)
            break
        runs.append({"pair": i + 1, "before": before, "after": after})
        print(
            f"pair {i + 1}/{pairs}: before={before['overhead_ratio_p50']}x "
            f"after={after['overhead_ratio_p50']}x",
            file=sys.stderr,
        )
        _write_wire_ab_artifact(runs, pairs, n_jobs, out_path)
    if not runs:
        raise RuntimeError("wire AB: no pair completed")
    artifact = _write_wire_ab_artifact(runs, pairs, n_jobs, out_path)
    print(json.dumps({
        "metric": "wire_v2_overhead_ratio_p50_median",
        "value": artifact["medians"]["after_overhead_ratio_p50"],
        "unit": "x (wire p50 / in-process p50; median of interleaved pairs)",
        "vs_baseline": artifact["medians"]["before_overhead_ratio_p50"],
        "artifact": out_path,
    }))
    return artifact


def _write_wire_ab_artifact(runs, pairs: int, n_jobs: int, out_path: str):
    import statistics

    def med(side, key):
        vals = [r[side][key] for r in runs if r[side].get(key) is not None]
        return round(statistics.median(vals), 3) if vals else None

    coalesced = [
        (r["after"]["wire"].get("wire_v2") or {}).get("batch_coalesced", 0.0)
        for r in runs
    ]
    batch_reqs = [
        (r["after"]["wire"].get("wire_v2") or {}).get("batch_requests", 0.0)
        for r in runs
    ]
    batch_ops = [
        (r["after"]["wire"].get("wire_v2") or {}).get("batch_ops", 0.0)
        for r in runs
    ]
    artifact = {
        "what": ("before/after of wire protocol v2 (POST /batch request "
                 "pipelining, client-side last-write-wins status-write "
                 "coalescing, paginated+projected LISTs), "
                 f"{n_jobs}-job wire_overhead block"),
        "machine": ("build container, one noisy shared core, loopback HTTP "
                    "(cryptography/TLS dep unavailable here; driver runs TLS)"),
        "method": (f"{len(runs)} of {pairs} interleaved before/after pairs; "
                   "'before' = pre-PR HEAD in a worktree with the same "
                   "bench harness"),
        "baseline_note": (
            "the driver-side 1.797x (BENCH_r05) is still the EXTERNAL "
            "baseline and has not been re-measured since PR 1 (VERDICT r05 "
            "standing hole) — the self-measured ratio below is the tracked "
            "proxy, on a different machine and transport"
        ),
        "driver_baseline_r05": {
            "wire_p50_s": 0.6621,
            "inproc_p50_s": 0.3684,
            "overhead_ratio_p50": 1.797,
            "target": "<= 1.5x on the driver machine",
        },
        "pairs": runs,
        "medians": {
            "before_overhead_ratio_p50": med("before", "overhead_ratio_p50"),
            "after_overhead_ratio_p50": med("after", "overhead_ratio_p50"),
            "after_batch_requests_median": (
                round(statistics.median(batch_reqs), 1) if batch_reqs else None
            ),
            "after_batch_ops_median": (
                round(statistics.median(batch_ops), 1) if batch_ops else None
            ),
            "after_batch_coalesced_median": (
                round(statistics.median(coalesced), 1) if coalesced else None
            ),
        },
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1)
    return artifact


# ---------------------------------------------------------------------------
# Watch-resume reconnect cost: O(delta) vs O(cluster) at 1k objects.
# ---------------------------------------------------------------------------


def run_wire_resume(n_objects: int = 1000, delta_events: int = 20):
    """The `wire_resume` bench block (VERDICT r5 Next #3 done-criterion):
    reap every watch session against an `n_objects` cluster, then measure
    what a reconnect COSTS for two identical clients that both observed the
    full state — one presenting its ResourceVersion watermark (delta
    resume), one with resume disabled (the pre-resume forced-relist arm).
    The artifact must show O(delta): the resume leg transfers
    `delta_events` events where the relist leg re-pulls the whole cluster,
    and the host's `training_wire_resume_*` counters (read over the wire,
    not trusted from a self-run) show delta > 0 with too_old == 0."""
    from training_operator_tpu.api.jobs import ObjectMeta
    from training_operator_tpu.cluster.httpapi import ApiHTTPServer, RemoteAPIServer
    from training_operator_tpu.cluster.objects import ConfigMap
    from training_operator_tpu.cluster.runtime import Cluster

    cluster = Cluster()
    server = ApiHTTPServer(cluster.api, port=0)
    try:
        resume_client = RemoteAPIServer(server.url, timeout=10.0)
        relist_client = RemoteAPIServer(server.url, timeout=10.0, resume=False)
        wq_resume = resume_client.watch(kinds=["ConfigMap"])
        wq_relist = relist_client.watch(kinds=["ConfigMap"])

        for i in range(n_objects):
            cluster.api.create(
                ConfigMap(metadata=ObjectMeta(name=f"rv-{i}"), data={"i": str(i)})
            )

        def drain_until(wq, want, deadline_s=120.0):
            got = []
            deadline = time.monotonic() + deadline_s
            while len(got) < want and time.monotonic() < deadline:
                got.extend(wq.drain(timeout=1.0))
            return got

        # Both clients observe the full state (their watermarks / knowledge
        # are current) BEFORE the storm.
        assert len(drain_until(wq_resume, n_objects)) == n_objects
        assert len(drain_until(wq_relist, n_objects)) == n_objects

        # The reap storm: every server-side session is gone at once.
        server.reap_all_sessions()
        for i in range(delta_events):
            cluster.api.create(
                ConfigMap(metadata=ObjectMeta(name=f"delta-{i}"), data={})
            )

        t0 = time.monotonic()
        got = drain_until(wq_resume, delta_events)
        delta_reconnect_s = time.monotonic() - t0
        delta_names = {e.obj.metadata.name for e in got}

        t0 = time.monotonic()
        # The relist leg re-announces EVERYTHING (n_objects + the delta).
        got_relist = drain_until(wq_relist, n_objects + delta_events)
        relist_reconnect_s = time.monotonic() - t0

        snap = resume_client.metrics_snapshot()
        assert delta_names == {f"delta-{i}" for i in range(delta_events)}, (
            "delta resume replayed the wrong events"
        )
        return {
            "objects": n_objects,
            "delta_events": delta_events,
            "delta_resume": {
                "reconnect_s": round(delta_reconnect_s, 4),
                "events_transferred": len(got),
            },
            "forced_relist": {
                "reconnect_s": round(relist_reconnect_s, 4),
                "events_transferred": len(got_relist),
            },
            # >1 = resume reconnects faster; the events ratio is the
            # structural O(delta)-vs-O(cluster) evidence, robust to timing
            # noise on a loaded box.
            "relist_over_delta_time": round(
                relist_reconnect_s / delta_reconnect_s, 2
            ) if delta_reconnect_s > 0 else None,
            "relist_over_delta_events": round(
                len(got_relist) / max(1, len(got)), 1
            ),
            "host_resume_counters": {
                "delta_total": snap.get("training_wire_resume_delta_total", 0.0),
                "replayed_events_total": snap.get(
                    "training_wire_resume_replayed_events_total", 0.0
                ),
                "too_old_total": snap.get("training_wire_resume_too_old_total", 0.0),
                "ring_evictions_total": snap.get(
                    "training_wire_resume_ring_evictions_total", 0.0
                ),
            },
        }
    finally:
        server.close()


# ---------------------------------------------------------------------------
# Observability overhead: the job-lifecycle tracing (observe/) must be free
# enough to leave ON — target < 5% on the scheduler/control-plane hot path.
# ---------------------------------------------------------------------------


def run_observe_overhead(n_jobs: int = 120, pairs: int = 5, seed: int = 11,
                         chrome_trace=None):
    """The `observe` bench block: run the SAME burst (virtual clock, gang
    scheduler + manager — every instrumented hot path) with tracing
    disabled vs enabled, and report the wall-time overhead of the
    instrumentation. Timeline recording (observe.set_enabled) is the
    toggle; the metric histograms stay on in both legs — they predate the
    tracer and are part of the baseline.

    Two estimators, because burst wall time on a shared box swings ±15%
    between IDENTICAL runs — far above the true cost:

    - direct: during one enabled burst, every tracer entry point
      (record_span/mark) is self-timed; `overhead_pct` is that time as a
      share of the burst wall. Deterministic, and conservative (the
      probe's own perf_counter calls are charged to the tracer).
    - wall pairs: back-to-back disabled/enabled pairs with the leg order
      alternating, summarized by the median per-pair ratio — the
      end-to-end corroboration, reported with its spread so the noise is
      visible rather than laundered into a point estimate."""
    from training_operator_tpu import observe
    from training_operator_tpu.observe import timeline as _tlmod

    specs = build_workload(n_jobs, seed)

    def leg(enabled, trace_path=None):
        observe.set_enabled(enabled)
        try:
            t0 = time.perf_counter()
            run_burst(specs, TPUPacker(), chrome_trace=trace_path)
            return time.perf_counter() - t0
        finally:
            observe.set_enabled(True)

    leg(True)  # warmup: codec + placer compiles land outside the measurement

    # Direct leg: self-timed tracer entry points over one enabled burst.
    counters = {"calls": 0, "time": 0.0}
    orig_span, orig_mark = (
        _tlmod.TimelineStore.record_span, _tlmod.TimelineStore.mark,
    )

    def _timed(orig):
        def probe(self, *a, **kw):
            t0 = time.perf_counter()
            try:
                return orig(self, *a, **kw)
            finally:
                counters["calls"] += 1
                counters["time"] += time.perf_counter() - t0
        return probe

    _tlmod.TimelineStore.record_span = _timed(orig_span)
    _tlmod.TimelineStore.mark = _timed(orig_mark)
    try:
        direct_wall = leg(True, trace_path=chrome_trace)
    finally:
        _tlmod.TimelineStore.record_span = orig_span
        _tlmod.TimelineStore.mark = orig_mark
    direct_share = counters["time"] / direct_wall if direct_wall > 0 else 0.0

    off, on, ratios = [], [], []
    for i in range(max(1, pairs)):
        if i % 2 == 0:
            d = leg(False)
            e = leg(True)
        else:
            e = leg(True)
            d = leg(False)
        off.append(d)
        on.append(e)
        ratios.append(e / d if d > 0 else 1.0)
    ratios.sort()
    med_ratio = ratios[len(ratios) // 2]
    out = {
        "jobs": n_jobs,
        "pairs": pairs,
        "direct": {
            "tracer_calls": counters["calls"],
            "tracer_time_s": round(counters["time"], 4),
            "burst_wall_s": round(direct_wall, 3),
            "share_pct": round(100 * direct_share, 3),
        },
        "wall_pairs": {
            "disabled_wall_s": [round(v, 3) for v in off],
            "enabled_wall_s": [round(v, 3) for v in on],
            "pair_ratios": [round(r, 4) for r in sorted(ratios)],
            "median_pair_ratio": round(med_ratio, 4),
        },
        "overhead_pct": round(100 * direct_share, 3),
        "under_5pct": direct_share < 0.05,
    }
    if chrome_trace:
        out["chrome_trace"] = chrome_trace
    return out


def run_audit_overhead(n_jobs: int = 120, pairs: int = 5, seed: int = 11):
    """The `audit` bench block (BENCH_SELF_OBSERVE method, applied to the
    standing invariant auditor): the SAME 120-job gang burst with the
    fail-fast auditor off vs on, overhead reported two ways —

    - direct: every `InvariantAuditor.audit` call self-timed during one
      audited burst; `overhead_pct` is that time as a share of the burst
      wall. Deterministic and conservative (probe cost charged to the
      auditor). This is the number the <2% acceptance budget reads.
    - wall pairs: alternating off/on pairs, median per-pair ratio with
      spread, as end-to-end corroboration (burst wall on a shared box
      swings more than the true cost).

    The audited legs run fail-fast, so the block doubles as the invariant
    regression gate: any violation in any audited burst fails the bench."""
    from training_operator_tpu.observe import invariants as _inv

    specs = build_workload(n_jobs, seed)

    def leg(audit):
        t0 = time.perf_counter()
        out = run_burst(specs, TPUPacker(), audit=audit)
        return time.perf_counter() - t0, out

    leg(True)  # warmup: codec + placer compiles land outside the measurement

    counters = {"calls": 0, "time": 0.0}
    orig_audit = _inv.InvariantAuditor.audit

    def probe(self):
        t0 = time.perf_counter()
        try:
            return orig_audit(self)
        finally:
            counters["calls"] += 1
            counters["time"] += time.perf_counter() - t0

    _inv.InvariantAuditor.audit = probe
    try:
        direct_wall, audited = leg(True)
    finally:
        _inv.InvariantAuditor.audit = orig_audit
    direct_share = counters["time"] / direct_wall if direct_wall > 0 else 0.0

    off, on, ratios = [], [], []
    for i in range(max(1, pairs)):
        if i % 2 == 0:
            d, _ = leg(False)
            e, _ = leg(True)
        else:
            e, _ = leg(True)
            d, _ = leg(False)
        off.append(d)
        on.append(e)
        ratios.append(e / d if d > 0 else 1.0)
    ratios.sort()
    return {
        "jobs": n_jobs,
        "pairs": pairs,
        "audit_interval_s": AUDIT_INTERVAL_S,
        "direct": {
            "audit_calls": counters["calls"],
            "audit_time_s": round(counters["time"], 4),
            "burst_wall_s": round(direct_wall, 3),
            "share_pct": round(100 * direct_share, 3),
        },
        "wall_pairs": {
            "disabled_wall_s": [round(v, 3) for v in off],
            "enabled_wall_s": [round(v, 3) for v in on],
            "pair_ratios": [round(r, 4) for r in ratios],  # sorted above
            "median_pair_ratio": round(ratios[len(ratios) // 2], 4),
        },
        "burst_audit": audited.get("audit"),
        "violations": (audited.get("audit") or {}).get("violations", 0),
        "overhead_pct": round(100 * direct_share, 3),
        "under_2pct": direct_share < 0.02,
    }


SLO_EVAL_INTERVAL_S = 15.0


def run_slo_overhead(n_jobs: int = 120, pairs: int = 5, seed: int = 11):
    """The `slo` bench block (the run_audit_overhead method, applied to the
    SLO engine): the SAME 120-job gang burst with the engine off vs on,
    overhead reported two ways —

    - direct: every `SLOEvaluator.evaluate` call (burn-rate pass every
      SLO_EVAL_INTERVAL_S of virtual time against a live SLOPolicy) and
      every `explain` call (per-job latency attribution for the full burst
      at quiescence) self-timed during one instrumented burst;
      `overhead_pct` is their summed time as a share of the burst wall.
      Deterministic and conservative (probe cost charged to the engine).
      This is the number the <2% acceptance budget reads.
    - wall pairs: alternating off/on pairs, median per-pair ratio with
      spread, as end-to-end corroboration."""
    from training_operator_tpu.api.jobs import ObjectMeta
    from training_operator_tpu.observe import attribution as _attr
    from training_operator_tpu.observe import slo as _slo

    specs = build_workload(n_jobs, seed)

    def slo_setup(cluster):
        _slo.register_slo_admission(cluster.api)
        cluster.api.create(_slo.SLOPolicy(
            metadata=ObjectMeta(name="bench-slo"),
            objectives=[
                _slo.SLOObjective(name="ttr-p99", metric="time_to_running",
                                  threshold_seconds=600.0, target=0.99),
                _slo.SLOObjective(name="queue-p95", metric="queue_wait",
                                  threshold_seconds=300.0, target=0.95),
            ],
        ))
        ev = _slo.SLOEvaluator(cluster.api, cluster.clock.now)
        state = {"next": 0.0}

        def tick():
            now = cluster.clock.now()
            if now >= state["next"]:
                state["next"] = now + SLO_EVAL_INTERVAL_S
                ev.evaluate(now)

        cluster.add_ticker(tick)

        def finalize():
            ev.evaluate(cluster.clock.now())
            for tl in cluster.api.timelines.timelines():
                _attr.explain(cluster.api, tl.namespace, tl.name,
                              now=cluster.clock.now())

        return finalize

    def leg(slo_on):
        t0 = time.perf_counter()
        out = run_burst(specs, TPUPacker(), audit=False,
                        extra_setup=slo_setup if slo_on else None)
        return time.perf_counter() - t0, out

    leg(True)  # warmup: codec + placer compiles land outside the measurement

    counters = {"evaluate_calls": 0, "evaluate_time": 0.0,
                "explain_calls": 0, "explain_time": 0.0}
    orig_evaluate = _slo.SLOEvaluator.evaluate
    orig_explain = _attr.explain

    def evaluate_probe(self, now=None):
        t0 = time.perf_counter()
        try:
            return orig_evaluate(self, now)
        finally:
            counters["evaluate_calls"] += 1
            counters["evaluate_time"] += time.perf_counter() - t0

    def explain_probe(api, namespace, name, now=None):
        t0 = time.perf_counter()
        try:
            return orig_explain(api, namespace, name, now=now)
        finally:
            counters["explain_calls"] += 1
            counters["explain_time"] += time.perf_counter() - t0

    _slo.SLOEvaluator.evaluate = evaluate_probe
    _attr.explain = explain_probe
    try:
        direct_wall, _ = leg(True)
    finally:
        _slo.SLOEvaluator.evaluate = orig_evaluate
        _attr.explain = orig_explain
    engine_time = counters["evaluate_time"] + counters["explain_time"]
    direct_share = engine_time / direct_wall if direct_wall > 0 else 0.0

    off, on, ratios = [], [], []
    for i in range(max(1, pairs)):
        if i % 2 == 0:
            d, _ = leg(False)
            e, _ = leg(True)
        else:
            e, _ = leg(True)
            d, _ = leg(False)
        off.append(d)
        on.append(e)
        ratios.append(e / d if d > 0 else 1.0)
    ratios.sort()
    return {
        "jobs": n_jobs,
        "pairs": pairs,
        "eval_interval_s": SLO_EVAL_INTERVAL_S,
        "direct": {
            "evaluate_calls": counters["evaluate_calls"],
            "evaluate_time_s": round(counters["evaluate_time"], 4),
            "explain_calls": counters["explain_calls"],
            "explain_time_s": round(counters["explain_time"], 4),
            "burst_wall_s": round(direct_wall, 3),
            "share_pct": round(100 * direct_share, 3),
        },
        "wall_pairs": {
            "disabled_wall_s": [round(v, 3) for v in off],
            "enabled_wall_s": [round(v, 3) for v in on],
            "pair_ratios": [round(r, 4) for r in ratios],  # sorted above
            "median_pair_ratio": round(ratios[len(ratios) // 2], 4),
        },
        "overhead_pct": round(100 * direct_share, 3),
        "under_2pct": direct_share < 0.02,
    }


def run_lockcheck_overhead(n_jobs: int = 120, pairs: int = 5, seed: int = 11):
    """The `lockcheck` bench block (the run_audit_overhead method, applied
    to the runtime lock-order witness): the SAME 120-job gang burst with
    the witness off vs on, overhead reported two ways —

    - direct: every `_note_acquire` call self-timed during one witnessed
      burst; `overhead_pct` is that time as a share of the burst wall.
      Deterministic and conservative (probe cost charged to the witness).
      This is the number the <2% acceptance budget reads.
    - wall pairs: alternating off/on pairs, median per-pair ratio with
      spread. The off-arm is wrapper-resident (locks were constructed
      under TRAINING_LOCKCHECK=1, so disabling leaves one flag check per
      acquire) — an upper bound on true production, where the factories
      return raw primitives outright.

    The witnessed legs run with witness fail-fast, so the block doubles as
    the lock-order regression gate: one acquisition-order cycle anywhere
    in the burst raises out of the acquire and fails the bench."""
    from training_operator_tpu.utils import locks as _locks

    if not _locks.lockcheck_enabled():
        raise SystemExit("run_lockcheck_overhead needs TRAINING_LOCKCHECK=1 "
                         "at process start (use --lockcheck/--lockcheck-only)")
    specs = build_workload(n_jobs, seed)

    def leg(check):
        _locks.enable(check)
        try:
            t0 = time.perf_counter()
            out = run_burst(specs, TPUPacker())
            return time.perf_counter() - t0, out
        finally:
            _locks.enable(True)

    _locks.reset_witness()
    _locks.set_fail_fast(True)
    try:
        leg(True)  # warmup: codec + placer compiles land outside the measurement

        counters = {"calls": 0, "time": 0.0}
        orig_note = _locks._note_acquire

        def probe(name):
            t0 = time.perf_counter()
            try:
                return orig_note(name)
            finally:
                counters["calls"] += 1
                counters["time"] += time.perf_counter() - t0

        _locks._note_acquire = probe
        try:
            direct_wall, _ = leg(True)
        finally:
            _locks._note_acquire = orig_note
        direct_share = counters["time"] / direct_wall if direct_wall > 0 else 0.0

        off, on, ratios = [], [], []
        for i in range(max(1, pairs)):
            if i % 2 == 0:
                d, _ = leg(False)
                e, _ = leg(True)
            else:
                e, _ = leg(True)
                d, _ = leg(False)
            off.append(d)
            on.append(e)
            ratios.append(e / d if d > 0 else 1.0)
        ratios.sort()
        violations = _locks.witness_violations()
    finally:
        _locks.set_fail_fast(False)
    return {
        "jobs": n_jobs,
        "pairs": pairs,
        "direct": {
            "tracked_acquisitions": counters["calls"],
            "witness_time_s": round(counters["time"], 4),
            "burst_wall_s": round(direct_wall, 3),
            "share_pct": round(100 * direct_share, 3),
        },
        "wall_pairs": {
            "disabled_wall_s": [round(v, 3) for v in off],
            "enabled_wall_s": [round(v, 3) for v in on],
            "pair_ratios": [round(r, 4) for r in ratios],  # sorted above
            "median_pair_ratio": round(ratios[len(ratios) // 2], 4),
        },
        "order_graph_nodes": len(_locks.order_graph()),
        "violations": len(violations),
        "overhead_pct": round(100 * direct_share, 3),
        "under_2pct": direct_share < 0.02,
    }


# ---------------------------------------------------------------------------
# Node-loss MTTR: kill one host of a whole-slice TPU gang and measure the
# recovery pipeline (detect -> evict -> re-solve -> Running again). The
# failure domain the TPU-first north star creates: one dead host breaks the
# slice's ICI mesh, so recovery is a whole-gang re-placement.
# ---------------------------------------------------------------------------


def run_node_chaos(heartbeat: float = 10.0, grace: float = 40.0,
                   toleration: float = 30.0):
    """The `node_chaos` bench block: deterministic VirtualClock scenario —
    a 4-host gang running on one of two slices, one host killed, MTTR
    measured as kill -> the job's Running condition re-transition. The
    breakdown separates policy cost (grace + toleration, deployment knobs)
    from mechanism cost (eviction -> re-solve -> rebind -> restart), which
    is the part this subsystem owns."""
    import training_operator_tpu.api.common as capi
    from training_operator_tpu.api.common import (
        Container, JobConditionType, PodTemplateSpec, ReplicaSpec,
        RestartPolicy,
    )
    from training_operator_tpu.api.jobs import JAXJob, ObjectMeta, TPUPolicy
    from training_operator_tpu.cluster.chaos import NodeChaos
    from training_operator_tpu.cluster.inventory import (
        TPU_RESOURCE as TPU_RES, make_tpu_pool as mk_pool,
    )
    from training_operator_tpu.cluster.runtime import (
        ANNOTATION_SIM_DURATION as SIM_DUR, Cluster as Cl,
        DefaultScheduler as DefSched, SimKubelet as Kubelet,
        VirtualClock as VClock,
    )
    from training_operator_tpu.controllers.jax import JAXController
    from training_operator_tpu.controllers.manager import OperatorManager
    from training_operator_tpu.controllers.nodelifecycle import (
        NodeLifecycleController,
    )

    cluster = Cl(VClock())
    cluster.add_nodes(mk_pool(2, slice_topology="4x4"))
    DefSched(cluster)
    kubelet = Kubelet(cluster, heartbeat_interval=heartbeat)
    NodeLifecycleController(cluster, grace_period=grace,
                            toleration_seconds=toleration)
    GangScheduler(cluster, TPUPacker())
    mgr = OperatorManager(cluster, gang_enabled=True)
    mgr.register(JAXController(cluster.api))

    tmpl = PodTemplateSpec(
        containers=[Container(name="jax", image="img",
                              resources={"cpu": 1.0, TPU_RES: 16.0})],
        annotations={SIM_DUR: "100000"},
    )
    mgr.submit(JAXJob(
        metadata=ObjectMeta(name="mttr"),
        replica_specs={"Worker": ReplicaSpec(
            replicas=4, template=tmpl, restart_policy=RestartPolicy.EXIT_CODE,
        )},
        tpu_policy=TPUPolicy(accelerator="v5e-16", topology="4x4"),
    ))

    def running_after(t):
        j = cluster.api.get("JAXJob", "default", "mttr")
        c = capi.get_condition(j.status, JobConditionType.RUNNING)
        return c is not None and c.status and c.last_transition_time > t

    assert cluster.run_until(lambda: running_after(-1.0), timeout=300)
    placed = sorted(p.node_name for p in cluster.api.list("Pod")
                    if not p.is_terminal())
    victim, victim_slice = placed[0], placed[0].rsplit("-host-", 1)[0]
    chaos = NodeChaos(cluster, kubelet)
    kill_t = cluster.clock.now()
    chaos.kill_node(victim)
    assert cluster.run_until(lambda: running_after(kill_t), timeout=3000)

    def first_event(reason):
        evs = [e.timestamp for e in cluster.api.events(reason=reason)
               if e.timestamp >= kill_t]
        return min(evs) if evs else None

    j = cluster.api.get("JAXJob", "default", "mttr")
    running_t = capi.get_condition(
        j.status, JobConditionType.RUNNING).last_transition_time
    detect_t = first_event("NodeNotReady")
    evict_t = first_event("PodEvicted")
    placed_after = sorted(p.node_name for p in cluster.api.list("Pod")
                          if not p.is_terminal())
    return {
        "grace_period_s": grace,
        "toleration_seconds": toleration,
        "heartbeat_interval_s": heartbeat,
        "killed_node": victim,
        "kill_schedule": [[round(t, 3), n] for t, n in chaos.kills],
        "detect_s": round(detect_t - kill_t, 3) if detect_t else None,
        "evict_s": round(evict_t - kill_t, 3) if evict_t else None,
        "mttr_s": round(running_t - kill_t, 3),
        "recovery_mechanism_s": (
            round(running_t - evict_t, 3) if evict_t else None
        ),
        "placement_before": placed,
        "placement_after": placed_after,
        "dead_node_absent": victim not in placed_after,
        "whole_slice_migration": all(
            not n.startswith(victim_slice) for n in placed_after
        ),
    }


# ---------------------------------------------------------------------------
# Control-plane host failover (PR 9 headline): WAL-shipping warm standby on
# real sockets + real clock, primary SIGKILL'd mid 120-job burst, standby
# auto-promoted via the replicated host lease. Reports failover MTTR
# (kill -> first successful write on the standby), the epoch-chained resume
# economics (events replayed vs what a forced relist would have delivered to
# the surviving watch sessions), and steady-state replication lag.
# ---------------------------------------------------------------------------


def _shards_burst_leg(replicas: int, n_jobs: int, namespaces: int = 12):
    """Host + `replicas` sharded operator OS processes over the wire: the
    honest scale-out measurement — each operator replica overlaps its own
    reconcile round trips, so jobs/minute vs replica count is real
    process parallelism, not a virtual-clock artifact."""
    import os as _os
    import tempfile

    from training_operator_tpu.sdk.client import TrainingClient
    from training_operator_tpu.utils.procio import spawn_module_process

    tmp = tempfile.mkdtemp(prefix=f"shards-bench-{replicas}-")
    inv = _os.path.join(tmp, "cluster.json")
    with open(inv, "w") as f:
        json.dump({"cpu_pools": [{"nodes": 16, "cpu_per_node": 16.0}]}, f)
    repo = _os.path.dirname(_os.path.abspath(__file__))
    tls = _tls_available()

    def spawn(*a):
        return spawn_module_process(a, repo, env_extra={"JAX_PLATFORMS": "cpu"})

    host_args = ["--role", "host", "--serve-port", "0",
                 "--gang-scheduler-name", "none", "--cluster", inv]
    if not tls:
        host_args.append("--insecure")
    host = spawn(*host_args)
    procs = [host]
    try:
        url = _read_announcement(host, "WIRE_API=")
        ca = _read_announcement(host, "WIRE_CA=") if tls else None
        for k in range(replicas):
            op_args = [
                "--role", "operator", "--api-server", url,
                "--enable-scheme", "jax", "--gang-scheduler-name", "none",
                "--operator-shards", str(replicas),
                "--shard-takeover-grace", "5",
                "--leader-identity", f"bench-op-{k}",
            ]
            if ca:
                op_args += ["--ca-cert", ca]
            op = spawn(*op_args)
            procs.append(op)
            _read_announcement(op, "OPERATOR_UP=")

        client = TrainingClient(url, ca_file=ca)
        api = client.api
        t0 = time.monotonic()
        for i in range(n_jobs):
            tmpl = PodTemplateSpec(
                containers=[Container(name="jax", image="trainer",
                                      resources={"cpu": 0.25})],
                annotations={ANNOTATION_SIM_DURATION: "0.5"},
            )
            client.create_job(JAXJob(
                metadata=ObjectMeta(
                    name=f"sh-{i}",
                    namespace=f"bench-ns-{i % namespaces}",
                ),
                replica_specs={"Worker": ReplicaSpec(replicas=1, template=tmpl)},
            ))
        submit_wall = time.monotonic() - t0

        import training_operator_tpu.api.common as _capi

        deadline = time.monotonic() + max(240, n_jobs // 2)
        done = 0
        while time.monotonic() < deadline:
            done = sum(
                1
                for ns in range(namespaces)
                for j in api.list("JAXJob", f"bench-ns-{ns}")
                if _capi.is_succeeded(j.status)
            )
            if done >= n_jobs:
                break
            time.sleep(0.25)
        wall = time.monotonic() - t0
        return {
            "replicas": replicas,
            "jobs": n_jobs,
            "succeeded": done,
            "submit_wall_s": round(submit_wall, 2),
            "burst_wall_s": round(wall, 2),
            "jobs_per_minute": round(60.0 * done / wall, 1) if wall else None,
        }
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.communicate(timeout=10)
            except Exception:
                pass


def run_shards(jobs: int = 5000, sessions: int = 1000,
               out: str = "BENCH_SELF_SHARDS_r15.json"):
    """Operator scale-out bench (PR 15), two blocks:

    burst    jobs/minute vs operator replica count (1/2/3) with the SAME
             host and the SAME job burst, replicas as real OS processes
             sharding reconcile ownership by namespace hash;
    reads    `sessions` concurrent watch sessions parked on the primary vs
             on the warm standby, with the primary's write p50 measured in
             both (plus a no-sessions baseline) — the follower-read claim
             is that shifting the read/watch fanout to standbys leaves the
             primary's write path alone.
    """
    import statistics
    import tempfile

    from training_operator_tpu.cluster.httpapi import RemoteAPIServer
    from training_operator_tpu.cluster.objects import ConfigMap
    from training_operator_tpu.utils import metrics as M

    burst = [
        _shards_burst_leg(replicas, jobs) for replicas in (1, 2, 3)
    ]

    # -- follower-read block ----------------------------------------------
    # Primary + warm standby as REAL OS processes (the run_host/run_standby
    # roles): the first cut ran both stacks in the bench interpreter and
    # their handler threads' GIL contention dwarfed the server-side effect
    # being measured — write p50 deltas here must come from the hosts, not
    # from the measuring process fighting itself.
    import os as _os

    from training_operator_tpu.utils.procio import spawn_module_process

    tmp = tempfile.mkdtemp(prefix="shards-reads-")
    repo = _os.path.dirname(_os.path.abspath(__file__))

    def spawn(*a):
        return spawn_module_process(a, repo, env_extra={"JAX_PLATFORMS": "cpu"})

    host = spawn(
        "--role", "host", "--serve-port", "0", "--insecure",
        "--gang-scheduler-name", "none",
        "--state-dir", tmp + "/primary",
        "--replication-lease-seconds", "2",
    )
    procs = [host]
    p_url = _read_announcement(host, "WIRE_API=")
    standby = spawn(
        "--standby-of", p_url, "--serve-port", "0", "--insecure",
        "--gang-scheduler-name", "none", "--no-auto-promote",
        "--state-dir", tmp + "/standby",
        "--replication-lease-seconds", "2",
    )
    procs.append(standby)
    s_url = _read_announcement(standby, "WIRE_API=")

    def write_p50(writer, n=150, tag="w"):
        lats = []
        for i in range(n):
            t0 = time.monotonic()
            writer.create(ConfigMap(
                metadata=ObjectMeta(name=f"{tag}-{i}-{int(t0 * 1e6) % 10 ** 9}"),
                data={},
            ))
            lats.append(time.monotonic() - t0)
        lats.sort()
        return {
            "p50_ms": round(1000 * statistics.median(lats), 3),
            "p95_ms": round(1000 * _pct(lats, 0.95), 3),
        }

    def session_swarm(base_url, n_sessions, pollers=8):
        """Park n watch sessions on one host and poll them round-robin —
        in a SUBPROCESS, so the swarm's threads never contend the bench
        interpreter's GIL with the write-latency measurement (the first
        cut did, and the contention dwarfed the server-side effect being
        measured). The child opens the sessions, prints READY, polls until
        a line arrives on stdin, deletes every session (a later leg must
        not pay this leg's fanout), and prints its poll count."""
        import subprocess
        import sys as _sys

        script = r"""
import sys, threading
sys.path.insert(0, sys.argv[3])
from training_operator_tpu.cluster.httpapi import RemoteAPIServer
base_url, n = sys.argv[1], int(sys.argv[2])
boot = RemoteAPIServer(base_url, timeout=5.0)
ids = [boot._request("POST", "/watches", body={"kinds": ["ConfigMap"]})["watch_id"]
       for _ in range(n)]
print("READY", flush=True)
stop = threading.Event()
polls = [0] * len(ids)
def loop(k):
    # One LONG-POLLING thread per session: the realistic watch-session
    # shape (parked on the server's condvar, ~zero CPU while idle, woken
    # per write) — a hot timeout=0 loop would measure an artificial
    # CPU-saturation load instead of session fanout.
    cli = RemoteAPIServer(base_url, timeout=10.0)
    wid = ids[k]
    while not stop.is_set():
        try:
            cli._request("GET", f"/watches/{wid}",
                         query={"timeout": "2"}, idempotent=False)
            polls[k] += 1
        except Exception:
            if stop.is_set():
                return
threads = [threading.Thread(target=loop, args=(k,), daemon=True)
           for k in range(len(ids))]
for t in threads: t.start()
sys.stdin.readline()
stop.set()
for t in threads: t.join(timeout=5.0)
for wid in ids:
    try:
        boot._request("DELETE", f"/watches/{wid}")
    except Exception:
        pass
print(f"POLLS={sum(polls)}", flush=True)
"""
        import os as _os

        repo = _os.path.dirname(_os.path.abspath(__file__))
        proc = subprocess.Popen(
            [_sys.executable, "-c", script, base_url, str(n_sessions), repo],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
            env={"PATH": _os.environ.get("PATH", ""), "HOME": "/tmp",
                 "JAX_PLATFORMS": "cpu"},
        )
        line = proc.stdout.readline()
        assert line.strip() == "READY", f"swarm never came up: {line!r}"

        def stop_fn():
            try:
                proc.stdin.write("\n")
                proc.stdin.flush()
                out, _ = proc.communicate(timeout=60)
                for ln in out.splitlines():
                    if ln.startswith("POLLS="):
                        return int(ln.split("=", 1)[1])
            except Exception:  # noqa: BLE001
                proc.kill()
            return 0

        return stop_fn

    try:
        writer = RemoteAPIServer(p_url, timeout=5.0)
        baseline = write_p50(writer, tag="base")
        stop_primary = session_swarm(p_url, sessions)
        on_primary = write_p50(writer, tag="onp")
        primary_polls = stop_primary()
        stop_standby = session_swarm(s_url, sessions)
        on_standby = write_p50(writer, tag="ons")
        standby_polls = stop_standby()

        # Follower-read staleness evidence: a read_from_standby client's
        # LISTs land on the standby, whose responses carry the
        # X-Training-Staleness header this process's histogram observes.
        reader = RemoteAPIServer(
            addresses=[p_url, s_url], timeout=5.0, read_from_standby=True,
        )
        stale_before = M.read_staleness_seconds.count
        for _ in range(20):
            reader.list("ConfigMap")
            time.sleep(0.02)
        staleness_observed = M.read_staleness_seconds.count - stale_before
        staleness_max = (
            round(M.read_staleness_seconds.max, 4)
            if staleness_observed else None
        )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.communicate(timeout=10)
            except Exception:
                pass

    p50_base = baseline["p50_ms"]
    p50_primary = on_primary["p50_ms"]
    p50_standby = on_standby["p50_ms"]
    reads = {
        "sessions": sessions,
        "baseline_no_sessions": baseline,
        "sessions_on_primary": {**on_primary, "polls_served": primary_polls},
        "sessions_on_standby": {**on_standby, "polls_served": standby_polls},
        "follower_read_staleness": {
            "reads_with_header": staleness_observed,
            "max_staleness_s": staleness_max,
        },
        "primary_p50_delta_vs_baseline": round(
            (p50_standby - p50_base) / p50_base, 3
        ) if p50_base else None,
        "primary_p50_saved_vs_sessions_on_primary": round(
            (p50_primary - p50_standby) / p50_primary, 3
        ) if p50_primary else None,
        "within_10pct_of_baseline": bool(
            p50_base and abs(p50_standby - p50_base) / p50_base <= 0.10
        ),
    }
    block = {"burst": burst, "follower_reads": reads}
    with open(out, "w") as f:
        json.dump({
            "bench": "shards",
            "method": (
                "burst: one wire host + N sharded operator OS processes "
                "(--operator-shards N, namespace-hash ownership), same "
                "job burst per leg, jobs/minute = succeeded / wall. "
                "follower_reads: primary (--role host) + warm standby "
                "(--standby-of) as real OS processes; {sessions} "
                "long-polling watch sessions (one parked thread each, the "
                "realistic informer shape) opened on each side in turn by "
                "a third process while a direct client measures the "
                "primary's ConfigMap-create p50, plus a no-sessions "
                "baseline; follower-read staleness observed from the "
                "X-Training-Staleness headers a read_from_standby client "
                "receives. CAVEAT: this build box has ONE core, so every "
                "process shares it — the vs-baseline delta includes "
                "machine-level contention no deployment would see; the "
                "load-bearing comparison is sessions-on-standby vs "
                "sessions-on-primary (the write-path session tax removed "
                "by follower reads)."
            ).format(sessions=sessions),
            **block,
        }, f, indent=2)
        f.write("\n")
    return block


# ---------------------------------------------------------------------------
# Sharded write plane (PR 17 headline): the SAME 5k-job create burst through
# 1, 2, and 4 fsync'd write-shard host OS processes behind the client-side
# shard router. Every shard host is a vanilla single-shard primary paying a
# real per-record journal fsync, so write latency is bounded by I/O the
# shards genuinely overlap across processes — the claim being measured.
# shards=1 runs a plain RemoteAPIServer against one host: the unrouted
# compat arm. Rounds interleave across shard counts (the bench-wire-v2
# method) so machine-load drift hits every arm.
# ---------------------------------------------------------------------------


# The writer side of one leg, run as ONE OS SUBPROCESS with `writers`
# threads sharing one pipelined client: the flagship bulk-submission
# shape — concurrent creates coalesce into wire-v2 POST /batch envelopes
# (per-op HTTP/parse CPU amortizes away), while the host still pays a
# per-record journal fsync inside its store lock, which is exactly the
# serial resource the write shards split. A subprocess (not bench
# threads) so the measuring interpreter's own work never sits between
# the writers and the hosts; single-threaded unpipelined writers were
# tried first and are CPU-bound end to end on this box — the shard
# count then only changes scheduler overhead, not the bottleneck.
# Waits for GO on stdin so import cost never pollutes the burst.
_STORE_SHARDS_WRITER = r"""
import sys, threading, time
sys.path.insert(0, sys.argv[1])
from training_operator_tpu.api.common import (
    Container, PodTemplateSpec, ReplicaSpec,
)
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta
from training_operator_tpu.cluster.httpapi import (
    RemoteAPIServer, ShardedRemoteAPIServer,
)
urls = sys.argv[2].split(";")
n_jobs, writers, namespaces = (int(a) for a in sys.argv[3:6])
if len(urls) == 1:
    cli = RemoteAPIServer(urls[0], timeout=10.0)
else:
    cli = ShardedRemoteAPIServer(
        shard_addresses=[[u] for u in urls], timeout=10.0)
tmpl = PodTemplateSpec(
    containers=[Container(name="jax", image="trainer",
                          resources={"cpu": 0.25})],
)
lats = [[] for _ in range(writers)]
errors = [0] * writers


def work(w):
    for i in range(w, n_jobs, writers):
        job = JAXJob(
            metadata=ObjectMeta(name=f"j-{i}",
                                namespace=f"bench-ns-{i % namespaces}"),
            replica_specs={"Worker": ReplicaSpec(replicas=1, template=tmpl)},
        )
        t0 = time.monotonic()
        try:
            cli.create(job)
        except Exception:
            errors[w] += 1
            continue
        lats[w].append(time.monotonic() - t0)


threads = [threading.Thread(target=work, args=(w,), daemon=True)
           for w in range(writers)]
print("READY", flush=True)
sys.stdin.readline()
for t in threads:
    t.start()
for t in threads:
    t.join()
print("ERRS=%d" % sum(errors), flush=True)
print("LATS=" + ",".join("%.0f" % (x * 1e6)
                         for per in lats for x in per), flush=True)
"""


# This VM's virtio disk acknowledges fsync in ~0.15ms — an order of
# magnitude faster than any durable cloud volume (EBS/PD-class disks sit
# at 1-10ms). At that speed the write path is pure CPU and a one-core box
# can't show I/O overlap at all, so the shard hosts run under an
# LD_PRELOAD shim that pads fsync/fdatasync to a configurable floor
# (default 2.5ms, a mid-range durable-disk figure). The pad is wall time
# the host thread sleeps with the GIL RELEASED — exactly the window a
# second write shard uses. Both arms run the same floor; the artifact
# records the floor AND the box's raw fsync cost so nothing hides.
_FSYNC_FLOOR_C = r"""
#define _GNU_SOURCE
#include <dlfcn.h>
#include <stdlib.h>
#include <time.h>

static long floor_us(void) {
    static long v = -1;
    if (v < 0) {
        const char *e = getenv("FSYNC_FLOOR_US");
        v = e ? atol(e) : 0;
    }
    return v;
}

static void pad(struct timespec *t0) {
    long us = floor_us();
    if (us <= 0) return;
    struct timespec t1;
    clock_gettime(CLOCK_MONOTONIC, &t1);
    long spent = (t1.tv_sec - t0->tv_sec) * 1000000L +
                 (t1.tv_nsec - t0->tv_nsec) / 1000L;
    long left = us - spent;
    if (left > 0) {
        struct timespec d = {left / 1000000L, (left % 1000000L) * 1000L};
        nanosleep(&d, NULL);
    }
}

int fsync(int fd) {
    static int (*real)(int) = NULL;
    if (!real) real = dlsym(RTLD_NEXT, "fsync");
    struct timespec t0;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    int rc = real(fd);
    pad(&t0);
    return rc;
}

int fdatasync(int fd) {
    static int (*real)(int) = NULL;
    if (!real) real = dlsym(RTLD_NEXT, "fdatasync");
    struct timespec t0;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    int rc = real(fd);
    pad(&t0);
    return rc;
}
"""


def _build_fsync_floor():
    """Compile the fsync-floor shim; None when no C compiler is around
    (the legs then run against the raw disk and the artifact says so)."""
    import os as _os
    import shutil
    import subprocess
    import tempfile

    cc = shutil.which("cc") or shutil.which("gcc")
    if cc is None:
        return None
    d = tempfile.mkdtemp(prefix="fsync-floor-")
    src = _os.path.join(d, "fsync_floor.c")
    so = _os.path.join(d, "fsync_floor.so")
    with open(src, "w") as f:
        f.write(_FSYNC_FLOOR_C)
    try:
        subprocess.run([cc, "-shared", "-fPIC", "-O2", "-o", so, src,
                        "-ldl"], check=True, capture_output=True, timeout=60)
    except Exception:  # noqa: BLE001
        return None
    return so


def _raw_fsync_us(n: int = 100):
    """The box disk's actual per-fsync cost, for the artifact record."""
    import os as _os
    import tempfile

    with tempfile.NamedTemporaryFile() as f:
        t0 = time.monotonic()
        for _ in range(n):
            _os.write(f.fileno(), b"x" * 256)
            _os.fsync(f.fileno())
        return round(1e6 * (time.monotonic() - t0) / n, 1)


def _store_shards_leg(num_shards: int, n_jobs: int, writers: int = 8,
                      namespaces: int = 16, shim=None, floor_us: int = 2500):
    import os as _os
    import statistics
    import subprocess
    import tempfile

    from training_operator_tpu.cluster.shards import shard_for
    from training_operator_tpu.utils.procio import spawn_module_process

    tmp = tempfile.mkdtemp(prefix=f"store-shards-{num_shards}-")
    repo = _os.path.dirname(_os.path.abspath(__file__))
    host_env = {"JAX_PLATFORMS": "cpu"}
    if shim is not None and floor_us > 0:
        host_env["LD_PRELOAD"] = shim
        host_env["FSYNC_FLOOR_US"] = str(floor_us)

    def spawn(*a):
        return spawn_module_process(a, repo, env_extra=host_env)

    # Loopback HTTP for every leg (not per-TLS-availability): with N hosts
    # each minting its own CA, a per-shard trust store would measure TLS
    # plumbing, not write-plane scaling — and the arms must share transport.
    procs, wprocs = [], []
    try:
        for k in range(num_shards):
            procs.append(spawn(
                "--role", "host", "--serve-port", "0", "--insecure",
                "--gang-scheduler-name", "none", "--journal-fsync",
                "--state-dir", _os.path.join(tmp, f"shard-{k}"),
            ))
        urls = [_read_announcement(h, "WIRE_API=") for h in procs]

        env = {"PATH": _os.environ.get("PATH", ""), "HOME": "/tmp",
               "JAX_PLATFORMS": "cpu"}
        wprocs.append(subprocess.Popen(
            [sys.executable, "-c", _STORE_SHARDS_WRITER, repo,
             ";".join(urls), str(n_jobs), str(writers), str(namespaces)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
            env=env,
        ))
        for p in wprocs:
            line = p.stdout.readline()
            assert line.strip() == "READY", f"writer never came up: {line!r}"

        t0 = time.monotonic()
        for p in wprocs:
            p.stdin.write("\n")
            p.stdin.flush()
        lats, errs = [], 0
        for p in wprocs:
            out, _ = p.communicate(timeout=max(600, n_jobs))
            for ln in out.splitlines():
                if ln.startswith("ERRS="):
                    errs += int(ln.split("=", 1)[1])
                elif ln.startswith("LATS="):
                    body = ln.split("=", 1)[1]
                    if body:
                        lats.extend(float(x) / 1e6 for x in body.split(","))
        wall = time.monotonic() - t0
    finally:
        for p in wprocs + procs:
            if p.poll() is None:
                p.kill()
        for p in wprocs + procs:
            try:
                p.communicate(timeout=10)
            except Exception:  # noqa: BLE001
                pass

    lats.sort()
    created = n_jobs - errs
    spread = {}
    for i in range(n_jobs):
        s = shard_for("JAXJob", f"bench-ns-{i % namespaces}", num_shards)
        spread[s] = spread.get(s, 0) + 1
    return {
        "shards": num_shards,
        "jobs": n_jobs,
        "created": created,
        "errors": errs,
        "writers": writers,
        "write_p50_ms": round(1000 * statistics.median(lats), 3),
        "write_p95_ms": round(1000 * _pct(lats, 0.95), 3),
        "write_p99_ms": round(1000 * _pct(lats, 0.99), 3),
        "burst_wall_s": round(wall, 2),
        "jobs_per_minute": round(60.0 * created / wall, 1) if wall else None,
        "shard_write_spread": {str(k): v for k, v in sorted(spread.items())},
        "fsync_floor_us": floor_us if shim is not None else 0,
    }


def _write_store_shards_artifact(legs, pairs, jobs, out_path,
                                 raw_fsync_us=None, floored=True):
    import statistics

    counts = sorted({leg["shards"] for leg in legs})

    def med(n, key):
        vals = [leg[key] for leg in legs
                if leg["shards"] == n and leg.get(key) is not None]
        return round(statistics.median(vals), 3) if vals else None

    medians = {
        str(n): {
            "write_p50_ms": med(n, "write_p50_ms"),
            "write_p99_ms": med(n, "write_p99_ms"),
            "jobs_per_minute": med(n, "jobs_per_minute"),
        }
        for n in counts
    }
    p50_1 = medians.get("1", {}).get("write_p50_ms")
    p50_2 = medians.get("2", {}).get("write_p50_ms")
    artifact = {
        "bench": "store-shards",
        "what": (f"write p50 + jobs/minute vs write-shard count at a "
                 f"{jobs}-JAXJob create burst through the client-side "
                 "shard router (cluster/wire_shards.py)"),
        "method": (
            "each leg: N independent --journal-fsync host OS processes "
            "(every record pays a per-record fsync — held to the "
            "realistic floor in the `disk` block — inside the store "
            "write lock: the serial resource the shards split), fresh "
            "state dirs, one writer SUBPROCESS with 8 threads sharing one "
            "pipelined client (concurrent creates coalesce into wire-v2 "
            "POST /batch envelopes, the flagship bulk-submission shape) "
            "splitting the same burst round-robin across 16 namespaces "
            "(crc32 namespace-hash routing, the PR 15 shard map); "
            "shards=1 is a plain unrouted RemoteAPIServer (the compat "
            "arm); legs interleave across shard counts per round "
            "(bench-wire-v2 method) so machine drift hits every arm; "
            "loopback HTTP on all arms. CAVEAT: this build box has ONE "
            "core — every host process shares it, so CPU-bound shard "
            "parallelism is invisible here and the measured speedup is "
            "the fsync/store-lock overlap floor; a multi-core "
            "deployment only widens the gap."
        ),
        "disk": {
            "box_raw_fsync_us": raw_fsync_us,
            "fsync_floor_applied": bool(floored),
            "fsync_floor_rationale": (
                "this VM's virtio disk acks fsync in ~0.15ms — far below "
                "any durable cloud volume (1-10ms); the shard hosts run "
                "under an LD_PRELOAD shim padding fsync to the floor in "
                "every leg's fsync_floor_us, with the GIL released during "
                "the pad, so the per-record durability wait is realistic "
                "and identically applied to every arm"
            ) if floored else (
                "no C compiler for the fsync-floor shim: legs ran against "
                "the raw disk, whose ~0.15ms fsync makes the write path "
                "CPU-bound — shard scaling is NOT expected to show on a "
                "single-core box in this mode"
            ),
        },
        "rounds_planned": pairs,
        "rounds_completed": max((leg.get("round", 0) for leg in legs),
                                default=0),
        "legs": legs,
        "medians_by_shard_count": medians,
        "two_shards_beat_one_write_p50": bool(
            p50_1 is not None and p50_2 is not None and p50_2 < p50_1
        ),
        "write_p50_speedup_2_over_1": (
            round(p50_1 / p50_2, 3) if p50_1 and p50_2 else None
        ),
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1)
        f.write("\n")
    return artifact


def run_store_shards(jobs: int = 5000, pairs: int = 2, counts=(1, 2, 4),
                     out: str = "BENCH_SELF_STORE_SHARDS_r17.json",
                     floor_us: int = 2500):
    shim = _build_fsync_floor()
    raw_us = _raw_fsync_us()
    if shim is None:
        print("store-shards: no C compiler for the fsync-floor shim; "
              "legs run against the raw (unrealistically fast) disk",
              file=sys.stderr)
    legs = []
    artifact = None
    for rnd in range(pairs):
        for n in counts:
            leg = _store_shards_leg(n, jobs, shim=shim, floor_us=floor_us)
            leg["round"] = rnd + 1
            legs.append(leg)
            print(
                f"round {rnd + 1}/{pairs} shards={n}: "
                f"p50={leg['write_p50_ms']}ms p99={leg['write_p99_ms']}ms "
                f"jobs/min={leg['jobs_per_minute']} errors={leg['errors']}",
                file=sys.stderr,
            )
            # Rewrite after every leg: a crashed later leg must not
            # discard completed measurements.
            artifact = _write_store_shards_artifact(
                legs, pairs, jobs, out,
                raw_fsync_us=raw_us, floored=shim is not None,
            )
    return artifact


def run_wire_driver_stub(out: str = "BENCH_SELF_WIRE_DRIVER_r17.json"):
    """The machine-readable stand-in for the driver-side wire baseline:
    the 1.797x overhead ratio (BENCH_r05) has not been externally
    re-measured since PR 6, and until a driver machine runs the wire leg
    again every README claim chains off a self-measured proxy. This stub
    runs the quick-sized wire_overhead block and emits it WITH an explicit
    `external_baseline_unmeasured: true`, so the hole is a queryable field
    instead of a README footnote."""
    proxy = run_wire_overhead(n_jobs=100)
    artifact = {
        "bench": "wire-driver-stub",
        "external_baseline_unmeasured": True,
        "external_baseline_r05": {
            "wire_p50_s": 0.6621,
            "inproc_p50_s": 0.3684,
            "overhead_ratio_p50": 1.797,
            "target": "<= 1.5x on the driver machine",
            "last_measured": "PR 6 (BENCH_r05); not re-measured since",
        },
        "self_measured_proxy": proxy,
        "method": (
            "quick-sized (100-job) wire-vs-inproc overhead block on the "
            "build container — a PROXY, not the driver baseline: different "
            "machine, and loopback HTTP when the TLS dep is absent. When a "
            "driver machine re-runs the wire leg, replace this artifact "
            "and flip external_baseline_unmeasured to false."
        ),
    }
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1)
        f.write("\n")
    return artifact


def run_failover(jobs: int = 120, watch_sessions: int = 4,
                 out: str = "BENCH_SELF_FAILOVER_r12.json"):
    import statistics
    import tempfile
    import threading

    import training_operator_tpu.api.common as capi
    from training_operator_tpu.api.common import (
        Container, PodTemplateSpec, ReplicaSpec,
    )
    from training_operator_tpu.api.defaults import default_job
    from training_operator_tpu.api.jobs import JAXJob, JOB_KINDS, ObjectMeta
    from training_operator_tpu.api.validation import validate_job
    from training_operator_tpu.cluster.chaos import HostChaos
    from training_operator_tpu.cluster.httpapi import (
        ApiHTTPServer, ApiUnavailableError, RemoteAPIServer,
    )
    from training_operator_tpu.cluster.inventory import make_cpu_pool
    from training_operator_tpu.cluster.objects import ConfigMap
    from training_operator_tpu.cluster.replication import (
        StandbyController, make_snapshot_source, start_host_lease,
    )
    from training_operator_tpu.cluster.runtime import (
        ANNOTATION_SIM_DURATION as SIM_DUR, Cluster as Cl, WallClock,
    )
    from training_operator_tpu.cluster.store import HostStore
    from training_operator_tpu.config import OperatorConfig
    from training_operator_tpu.observe.invariants import (
        FleetSources, InvariantAuditor,
    )
    from training_operator_tpu.utils import metrics as M
    from training_operator_tpu.__main__ import build_stack

    lease_s, poll_s = 1.0, 0.2
    cfg = OperatorConfig(
        enabled_schemes=["jax"], gang_scheduler_name="none", enable_v2=False,
        fleet_audit_interval=0.0, replication_lease_seconds=lease_s,
        replication_poll_timeout=poll_s,
    )

    def admit_all(cluster):
        def admit(job):
            default_job(job, now=cluster.clock.now())
            validate_job(job)

        for kind in JOB_KINDS:
            cluster.api.register_admission(kind, admit)

    def step_loop(cluster, stop, errors, extra=None):
        def loop():
            while not stop.is_set():
                try:
                    cluster.step()
                    if extra is not None:
                        extra()
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                    stop.set()
                    return
                time.sleep(0.005)

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return t

    tmp = tempfile.mkdtemp(prefix="bench-failover-")

    # -- primary host ------------------------------------------------------
    p_cluster = Cl(WallClock())
    p_store = HostStore(tmp + "/primary", wal_ring=cfg.replication_wal_ring)
    p_store.load_into(p_cluster.api)
    p_store.attach(p_cluster.api)
    p_cluster.add_nodes(make_cpu_pool(8, cpu_per_node=16.0))
    admit_all(p_cluster)
    p_mgr, _ = build_stack(p_cluster, cfg)
    p_server = ApiHTTPServer(p_cluster.api, port=0, now_fn=p_cluster.clock.now)
    p_server.wal_source = p_store.wal_page
    p_server.snapshot_source = make_snapshot_source(
        p_cluster.api, p_store, p_server.resume_ring
    )
    start_host_lease(p_cluster, "bench-primary", lease_s)
    p_errors, p_stop = [], threading.Event()
    p_thread = step_loop(p_cluster, p_stop, p_errors)

    # -- warm standby ------------------------------------------------------
    s_cluster = Cl(WallClock())
    s_store = HostStore(tmp + "/standby", wal_ring=cfg.replication_wal_ring)
    ctrl = StandbyController(
        s_cluster, p_server.url, store=s_store, poll_timeout=poll_s,
        lease_duration=lease_s, identity="bench-standby",
    )
    ctrl.bootstrap()
    admit_all(s_cluster)
    s_server = ApiHTTPServer(s_cluster.api, port=0, now_fn=s_cluster.clock.now)
    ctrl.attach_server(s_server)
    s_sources = s_server.fleet_sources
    s_sources.replication_lag = ctrl.lag

    def on_promote():
        mgr, _ = build_stack(s_cluster, cfg)
        s_sources.expectations = mgr.unfulfilled_expectations

    ctrl.on_promote.append(on_promote)
    # The burst runs under the standing fail-fast auditor (INV008 included,
    # fed by the live replication lag) — one violation fails the bench.
    auditor = InvariantAuditor(
        s_cluster.api, s_cluster.clock.now, sources=s_sources,
        interval=0.5, fail_fast=True,
    ).attach(s_cluster)
    ctrl.start()
    s_errors, s_stop = [], threading.Event()
    s_thread = step_loop(
        s_cluster, s_stop, s_errors, extra=ctrl.maybe_complete_promotion
    )

    # -- clients: one writer + N surviving watch sessions ------------------
    writer = RemoteAPIServer(
        addresses=[p_server.url, s_server.url], timeout=5.0
    )
    watchers = [
        RemoteAPIServer(addresses=[p_server.url, s_server.url], timeout=5.0)
        for _ in range(watch_sessions)
    ]
    queues = [w.watch(kinds=["JAXJob", "Pod"]) for w in watchers]
    relists = []
    for w in watchers:
        orig = w.list
        w.list = (lambda o: lambda *a, **k: relists.append(a) or o(*a, **k))(orig)

    def drain_all():
        n = 0
        for q in queues:
            try:
                n += len(q.drain(timeout=0.1))
            except ApiUnavailableError:
                pass
        return n

    def succeeded():
        try:
            return sum(1 for j in writer.list("JAXJob")
                       if capi.is_succeeded(j.status))
        except ApiUnavailableError:
            return -1

    # -- burst + steady-state lag ------------------------------------------
    for i in range(jobs):
        writer.create(JAXJob(
            metadata=ObjectMeta(name=f"fo-{i:03d}"),
            replica_specs={"Worker": ReplicaSpec(
                replicas=1,
                template=PodTemplateSpec(
                    containers=[Container(name="jax", image="trainer",
                                          resources={"cpu": 1.0})],
                    annotations={SIM_DUR: "0.3"},
                ),
            )},
        ))
    lag_records, lag_seconds = [], []
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and succeeded() < jobs // 4:
        lag = ctrl.lag()
        lag_records.append(lag["records"])
        lag_seconds.append(lag["seconds"])
        drain_all()
        time.sleep(0.05)
    mid_burst_succeeded = succeeded()
    ctrl_applied_before = ctrl.applied

    # -- SIGKILL the primary mid-burst -------------------------------------
    replay_before = M.wire_resume_replayed.total()
    delta_before = M.wire_resume_delta.total()
    too_old_before = M.wire_resume_too_old.total()
    chaos = HostChaos()
    kill_t = chaos.kill_inprocess(
        "bench-primary", server=p_server, store=p_store,
        stop=p_stop, threads=[p_thread],
    )
    # kill_t is WALL time (HostChaos logs wall times for replay parity
    # with NodeChaos) — every delta below diffs against time.time().
    promote_t = None
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if ctrl.promoted:
            promote_t = time.time()
            break
        time.sleep(0.005)
    assert promote_t is not None, "standby never promoted"

    # MTTR: kill -> first successful write, via the failover client's
    # ordinary retry arm (unique probe names: a lost-response retry must
    # not read as failure).
    mttr = None
    attempt = 0
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            writer.create(ConfigMap(
                metadata=ObjectMeta(name=f"mttr-probe-{attempt}"), data={}
            ))
            mttr = time.time() - kill_t
            break
        except ApiUnavailableError:
            attempt += 1
            time.sleep(0.02)
    assert mttr is not None, "no write ever succeeded on the standby"

    # -- converge the whole burst on the promoted standby ------------------
    post_kill_events = 0
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        post_kill_events += drain_all()
        if succeeded() == jobs:
            break
        time.sleep(0.05)
    all_done = succeeded() == jobs
    # Heal the sessions fully before counting (late resubscribes).
    for _ in range(10):
        post_kill_events += drain_all()

    # What a forced relist would have delivered to the same N sessions at
    # promotion time: one event per live object of each watched kind.
    try:
        relist_events_per_session = (
            len(writer.list("JAXJob")) + len(writer.list("Pod"))
        )
    except ApiUnavailableError:
        relist_events_per_session = -1

    replayed = M.wire_resume_replayed.total() - replay_before
    block = {
        "jobs": jobs,
        "watch_sessions": watch_sessions,
        "replication": {
            "lease_seconds": lease_s,
            "poll_timeout_s": poll_s,
            "steady_lag_records_p50": (
                statistics.median(lag_records) if lag_records else None
            ),
            "steady_lag_seconds_p50": (
                round(statistics.median(lag_seconds), 4) if lag_seconds else None
            ),
            "records_applied_before_kill": ctrl_applied_before,
            "bootstraps": ctrl.bootstraps,
        },
        "mid_burst_succeeded": mid_burst_succeeded,
        "promote_s": round(promote_t - kill_t, 3),
        "mttr_s": round(mttr, 3),
        "write_attempts_during_outage": attempt,
        "all_jobs_succeeded": all_done,
        "auditor": {
            "fail_fast": True,
            "audits": auditor.audits,
            "violations": len(auditor.last_violations),
            "primary_errors": [repr(e) for e in p_errors],
            "standby_errors": [repr(e) for e in s_errors],
        },
        "resume": {
            "delta_resumes": M.wire_resume_delta.total() - delta_before,
            "too_old_relists": M.wire_resume_too_old.total() - too_old_before,
            "client_relist_calls": len(relists),
            "events_replayed": replayed,
            "events_received_post_kill": post_kill_events,
            "forced_relist_events_per_session": relist_events_per_session,
            "forced_relist_events_total": (
                relist_events_per_session * watch_sessions
                if relist_events_per_session >= 0 else None
            ),
            "replay_over_received": (
                round(replayed / post_kill_events, 3)
                if post_kill_events else None
            ),
        },
    }

    s_stop.set()
    ctrl.stop()
    s_thread.join(timeout=5)
    try:
        s_server.close()
        s_store.close()
    except Exception:
        pass
    with open(out, "w") as f:
        json.dump({
            "bench": "failover",
            "method": (
                "two in-process host stacks on real sockets + real clock; "
                "primary (durable HostStore, WAL ring, host lease) killed "
                "with SIGKILL semantics (listener + established conns "
                "severed, store fd abandoned) mid-burst; standby tails "
                "GET /wal, auto-promotes on lease expiry + dead tail, and "
                "converges the burst under the fail-fast invariant auditor "
                "(INV001-INV008). MTTR = kill -> first acknowledged write "
                "through the failover client."
            ),
            **block,
        }, f, indent=2)
        f.write("\n")
    return block


def _jain(values):
    vals = [float(v) for v in values]
    total = sum(vals)
    if total <= 0:
        return None
    return round(total * total / (len(vals) * sum(v * v for v in vals)), 4)


def run_tenancy_contention(
    teams: int = 4,
    jobs_per_team: int = 12,
    pool_slices: int = 8,
    seed: int = 11,
):
    """The `tenancy` bench block: `teams` ClusterQueues with equal chip
    quotas, each submitting `jobs_per_team` long 2x4 gangs into a pool
    sized for exactly the sum of the quotas — over-subscribed ~3x. Team A
    submits its entire backlog FIRST (the realistic burst skew FCFS
    rewards), then a high-priority "prod" wave of whole-slice gangs lands
    at t=60 on a saturated pool, so serving it requires checkpoint-aware
    preemption.

    Two identical legs: `fcfs` (arbiter off — strict submission order)
    and `arbiter` (quota admission + DRF interleave + priority tiers +
    preemption). Fairness is Jain's index over each team's mean running
    chips while the pool is contended (until half the team jobs finish);
    the prod tier's schedule-to-running percentiles show what priority
    buys; and every preempted job must converge Succeeded with >= 1
    checkpoint resume and an untouched restart budget — checked here, not
    just claimed."""
    import re as _re

    from training_operator_tpu.cluster.objects import Event  # noqa: F401
    from training_operator_tpu.controllers.jax import JAXController
    from training_operator_tpu.engine.core import job_recreate_restarts
    from training_operator_tpu.tenancy import (
        ClusterQueue,
        PriorityClass,
        TenancyArbiter,
        register_tenancy_admission,
    )

    team_names = [f"team-{chr(ord('a') + i)}" for i in range(teams)]
    team_quota = pool_slices * float(CHIPS_PER_SLICE) / teams
    rng = random.Random(seed)
    durations = {
        f"{t}-j{i}": rng.randint(240, 420)
        for t in team_names
        for i in range(jobs_per_team)
    }

    def team_gang(name, queue, prio, duration, workers=2, topology="2x4"):
        chips = _chips(topology)
        tmpl = PodTemplateSpec(
            containers=[Container(name="jax", image="trainer",
                                  resources={"cpu": 1.0, TPU_RESOURCE: 4.0})],
            annotations={ANNOTATION_SIM_DURATION: str(duration)},
        )
        from training_operator_tpu.api.common import RunPolicy, SchedulingPolicy

        return JAXJob(
            metadata=ObjectMeta(name=name),
            replica_specs={"Worker": ReplicaSpec(
                replicas=workers, template=tmpl,
                restart_policy=capi.RestartPolicy.EXIT_CODE,
            )},
            tpu_policy=TPUPolicy(accelerator=f"v5e-{chips}", topology=topology),
            run_policy=RunPolicy(scheduling_policy=SchedulingPolicy(
                queue=queue, priority_class=prio,
            )),
        )

    def leg(arbiter_on: bool):
        cluster = Cluster(VirtualClock())
        cluster.add_nodes(make_tpu_pool(pool_slices, slice_topology=SLICE_TOPOLOGY))
        DefaultScheduler(cluster)
        SimKubelet(cluster)
        register_tenancy_admission(cluster.api)
        arbiter = None
        if arbiter_on:
            arbiter = TenancyArbiter(
                cluster.api, cluster.clock.now,
                starvation_seconds=100_000.0,  # isolate quota/priority effects
            )
        GangScheduler(
            cluster, TPUPacker(), charge_solve_time=True,
            min_solve_interval=0.25, arbiter=arbiter,
        )
        mgr = OperatorManager(cluster, gang_enabled=True,
                              reconciles_per_tick=4096)
        mgr.register(JAXController(cluster.api))

        # Same tenancy objects in BOTH legs: FCFS simply ignores them.
        cluster.api.create(PriorityClass(
            metadata=ObjectMeta(name="high"), value=1000))
        cluster.api.create(PriorityClass(
            metadata=ObjectMeta(name="normal"), value=500))
        for t in team_names:
            cluster.api.create(ClusterQueue(
                metadata=ObjectMeta(name=t),
                quota={TPU_RESOURCE: team_quota},
                borrowing_limit={TPU_RESOURCE: team_quota},
            ))
        cluster.api.create(ClusterQueue(
            metadata=ObjectMeta(name="prod"),
            quota={TPU_RESOURCE: 2 * team_quota},
        ))

        # Burst skew: team-a's ENTIRE backlog enters the queue first.
        team_jobs = {t: [] for t in team_names}
        for t in team_names:
            for i in range(jobs_per_team):
                name = f"{t}-j{i}"
                team_jobs[t].append(name)
                mgr.submit(team_gang(name, t, "normal", durations[name]))
        prod_jobs = [f"prod-p{i}" for i in range(teams)]

        def prod_wave():
            for name in prod_jobs:
                mgr.submit(team_gang(name, "prod", "high", 120,
                                     workers=4, topology="4x4"))

        cluster.schedule_at(60.0, prod_wave)

        # First-Running capture (preemption re-transitions must not
        # overwrite the schedule-to-running instant).
        running_at = {}
        finished = set()
        watch = cluster.api.watch(kinds={"JAXJob"})

        def track():
            for ev in watch.drain():
                if ev.type != "Modified":
                    continue
                j = ev.obj
                if capi.is_finished(j.status):
                    finished.add(j.name)
                if j.name in running_at:
                    continue
                cond = capi.get_condition(j.status, JobConditionType.RUNNING)
                if cond is not None and cond.status:
                    running_at[j.name] = cond.last_transition_time

        cluster.add_ticker(track)

        # Fairness sampling: each team's running chips every 5s while the
        # pool is contended (until half the team jobs have finished).
        all_team_jobs = [n for names in team_jobs.values() for n in names]
        job_team = {n: n.rsplit("-j", 1)[0] for n in all_team_jobs}
        samples = {t: [] for t in team_names}
        state = {"next": 0.0, "open": True}

        def sample_tick():
            if not state["open"]:
                return
            now = cluster.clock.now()
            if now < state["next"]:
                return
            state["next"] = now + 5.0
            if sum(1 for n in all_team_jobs if n in finished) * 2 >= len(all_team_jobs):
                state["open"] = False
                return
            by_team = {t: 0.0 for t in team_names}
            for p in cluster.informer.list("Pod"):
                if p.node_name and not p.is_terminal():
                    team = job_team.get(
                        p.metadata.labels.get("training.tpu.dev/job-name", ""))
                    if team:
                        by_team[team] += p.resources().get(TPU_RESOURCE, 0.0)
            for t, chips in by_team.items():
                samples[t].append(chips)

        cluster.add_ticker(sample_tick)

        everybody = all_team_jobs + prod_jobs
        ok = cluster.run_until(
            lambda: len(finished) >= len(everybody),
            timeout=50_000, max_steps=5_000_000,
        )
        if not ok:
            raise RuntimeError(
                f"tenancy leg (arbiter={arbiter_on}) did not converge: "
                f"{len(everybody) - len(finished)} jobs pending"
            )

        shares = {t: (sum(v) / len(v) if v else 0.0) for t, v in samples.items()}
        lat = {
            "normal": sorted(
                running_at[n] for n in all_team_jobs if n in running_at
            ),
            "high": sorted(
                running_at[n] - 60.0 for n in prod_jobs if n in running_at
            ),
        }
        preempt_events = [
            e for e in cluster.api.events(reason="Preempted")
            if e.object_kind == "PodGroup"
        ]
        preempted_jobs = sorted({e.object_name for e in preempt_events})
        resumes = {}
        for name in preempted_jobs:
            ckpt = 0.0
            for e in cluster.api.events(object_name=name, reason="Requeued"):
                m = _re.search(r"resumes from ([0-9.]+)s", e.message)
                if m:
                    ckpt = max(ckpt, float(m.group(1)))
            resumes[name] = ckpt
        preempted_ok = all(
            capi.is_succeeded(cluster.api.get("JAXJob", "default", n).status)
            and job_recreate_restarts(
                cluster.api.get("JAXJob", "default", n)) == 0
            and resumes.get(n, 0.0) > 0.0
            for n in preempted_jobs
        )
        return {
            "jain_fairness": _jain(shares.values()),
            "team_mean_chips": {t: round(v, 1) for t, v in shares.items()},
            "makespan_s": round(cluster.clock.now(), 1),
            "p50_schedule_to_running_s": {
                tier: round(_pct(v, 0.50), 1) for tier, v in lat.items()
            },
            "p99_schedule_to_running_s": {
                tier: round(_pct(v, 0.99), 1) for tier, v in lat.items()
            },
            "preemptions": sum(e.count for e in preempt_events),
            "preempted_jobs": preempted_jobs,
            "preempted_all_succeeded_with_checkpoint_resume_and_budget":
                preempted_ok if preempted_jobs else None,
            "checkpointed_seconds_by_job": {
                n: round(v, 1) for n, v in resumes.items()
            },
        }

    fcfs = leg(False)
    arb = leg(True)
    return {
        "teams": teams,
        "jobs_per_team": jobs_per_team,
        "pool_chips": pool_slices * float(CHIPS_PER_SLICE),
        "team_quota_chips": team_quota,
        "workload": (
            "team-a's full backlog submitted first (burst skew), normal "
            "priority, 240-420s 2x4 gangs; prod wave of whole-slice "
            "high-priority gangs at t=60 on the saturated pool"
        ),
        "fcfs": fcfs,
        "arbiter": arb,
        "fairness_target": ">= 0.9 Jain with the arbiter on",
        "fairness_met": (arb["jain_fairness"] or 0.0) >= 0.9,
    }


# ---------------------------------------------------------------------------
# Incremental gang solver (PR 10): the O(changed) solve cycle vs the pinned
# legacy path, plus the 10k-node/2k-gang single-solve scale block.
# ---------------------------------------------------------------------------


def _solver_subprocess_leg(repo_dir: str, leg: str, n_jobs: int, seed: int):
    """One solver burst leg in a SUBPROCESS from `repo_dir` (a worktree of
    the pre-PR ref carrying this harness — the bench-wire-v2 method), so
    the true pre-change code is measured, not the in-tree compat arm."""
    import os as _os
    import subprocess

    env = dict(_os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "bench.py", "--solver-leg", leg,
         "--solver-jobs", str(n_jobs), "--seed", str(seed)],
        cwd=repo_dir, env=env, capture_output=True, text=True, timeout=1800,
    )
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.startswith("{")]
    if proc.returncode != 0 or not lines:
        raise RuntimeError(
            f"solver leg in {repo_dir} failed (rc={proc.returncode}): "
            f"{proc.stderr[-2000:]}"
        )
    return json.loads(lines[-1])


def run_solver_bench(n_jobs: int = 1000, pairs: int = 3, seed: int = 42,
                     out: str = "BENCH_SELF_SOLVER_r13.json",
                     before_repo: str = None):
    """The `solver` bench block: the SAME 1k-job burst through two arms —

      legacy       solver_incremental=False + solver_kernel=jax (exactly the
                   pre-PR configuration: global dirty bit, per-cycle full
                   snapshot walk, jit kernel)
      incremental  solver_incremental=True + solver_kernel=numpy (the new
                   defaults: per-group dirty tracking, delta-maintained
                   snapshot, numpy kernel)

    run as interleaved pairs (machine-load drift hits both sides), headline
    = solver_wall/job ratio (target >= 10x), with scheduling-quality parity
    reported alongside: p50/p99 of both arms against each other and against
    the zero-cost granular oracle — the speedup must not buy worse packing.
    """
    import statistics

    specs = build_workload(n_jobs, seed)
    goracle = granular_oracle(specs)

    def leg(incremental):
        placer = TPUPacker(kernel="numpy" if incremental else "jax")
        run = run_burst(specs, placer, incremental=incremental)
        return run

    leg(True)  # warmup: codec + jit compiles land outside the measurement

    runs = {"legacy": [], "incremental": []}
    pre_pr = []
    for i in range(max(1, pairs)):
        order = (
            [("legacy", False), ("incremental", True)]
            if i % 2 == 0
            else [("incremental", True), ("legacy", False)]
        )
        for name, inc in order:
            runs[name].append(leg(inc))
        if before_repo:
            # Interleaved with the in-tree arms so machine drift hits all
            # three: the TRUE pre-PR code from its own worktree.
            pre_pr.append(_solver_subprocess_leg(
                before_repo, "legacy", n_jobs, seed,
            ))
        print(
            f"solver pair {i + 1}/{pairs}: "
            f"legacy {runs['legacy'][-1]['solver_wall_s']}s vs "
            f"incremental {runs['incremental'][-1]['solver_wall_s']}s"
            + (f" (pre-PR {pre_pr[-1]['solver_wall_s']}s)" if pre_pr else ""),
            file=sys.stderr,
        )

    def med(arm, key):
        return round(statistics.median(r[key] for r in runs[arm]), 4)

    legacy_wall = med("legacy", "solver_wall_s")
    inc_wall = med("incremental", "solver_wall_s")
    speedup = round(legacy_wall / inc_wall, 2) if inc_wall > 0 else None
    pre_pr_block = None
    if pre_pr:
        import statistics as _st

        pre_wall = round(_st.median(r["solver_wall_s"] for r in pre_pr), 3)
        pre_pr_block = {
            "arm": "true pre-PR code (worktree of the pre-change ref, this "
                   "harness copied in — bench-wire-v2 method)",
            "solver_wall_s": pre_wall,
            "solver_wall_per_job_ms": round(1000.0 * pre_wall / n_jobs, 4),
            "speedup_vs_incremental": (
                round(pre_wall / inc_wall, 2) if inc_wall > 0 else None
            ),
            "runs": pre_pr,
        }
    scale = run_solver_scale()
    block = {
        "jobs": n_jobs,
        "pairs": pairs,
        "arms": {
            "legacy": "solver_incremental=False, solver_kernel=jax "
                      "(pinned pre-PR behavior)",
            "incremental": "solver_incremental=True, solver_kernel=numpy "
                           "(the new defaults)",
        },
        "solver_wall_s": {"legacy": legacy_wall, "incremental": inc_wall},
        "solver_wall_per_job_ms": {
            "legacy": round(1000.0 * legacy_wall / n_jobs, 4),
            "incremental": round(1000.0 * inc_wall / n_jobs, 4),
        },
        "speedup": speedup,
        "target": ">= 10x solver_wall/job vs the pinned-legacy arm",
        "cycles": {
            "legacy": med("legacy", "solver_cycles"),
            "incremental": med("incremental", "solver_cycles"),
        },
        "incremental_cycle_share": round(
            med("incremental", "solver_incremental_cycles")
            / max(1.0, med("incremental", "solver_cycles")), 3
        ),
        "groups_solved": {
            # The O(changed) evidence: gangs handed to the placer across
            # the whole burst (legacy re-solves every pending gang every
            # dirty cycle; incremental only the dirty subset).
            "legacy": med("legacy", "solver_groups_solved"),
            "incremental": med("incremental", "solver_groups_solved"),
        },
        "quality": {
            "p50_s": {"legacy": med("legacy", "p50_s"),
                      "incremental": med("incremental", "p50_s")},
            "p99_s": {"legacy": med("legacy", "p99_s"),
                      "incremental": med("incremental", "p99_s")},
            "tpu_utilization": {
                "legacy": med("legacy", "tpu_utilization"),
                "incremental": med("incremental", "tpu_utilization"),
            },
            "granular_oracle": goracle,
            "p99_vs_oracle": {
                arm: round(med(arm, "p99_s") / goracle["p99_s"], 4)
                if goracle["p99_s"] else None
                for arm in ("legacy", "incremental")
            },
        },
        "runs": runs,
        **({"pre_pr_reference": pre_pr_block} if pre_pr_block else {}),
        "scale_10k": scale,
        "caps": (
            f"{pairs} interleaved pairs (median quoted); trace ring caps "
            "per-run cycle stats at 2048 cycles (not hit at this scale)"
        ),
    }
    doc = {
        "bench": "solver",
        "method": (
            "identical 1k-job burst (virtual clock, solve wall charged into "
            "sim time) through the pinned-legacy arm "
            "(solver_incremental=False + jax kernel) and the incremental arm "
            "(per-group dirty tracking + delta-maintained snapshot + numpy "
            "kernel), interleaved pairs; plus one cold 10k-node/2k-gang "
            "single solve against the bench budget"
        ),
        **block,
    }
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    return block


def run_solver_scale(n_slices: int = 2500, n_gangs: int = 2000,
                     budget_s: float = 2.0):
    """First 10k-node / 2k-gang block: ONE cold solve of the whole pending
    set against a 2500-slice (4 hosts each) inventory — the ROADMAP item 3
    scale. Reports snapshot-build and solve wall separately; the acceptance
    budget is solve wall < 2 s."""
    from training_operator_tpu.cluster.objects import PodGroup
    from training_operator_tpu.cluster.runtime import Cluster as Cl
    from training_operator_tpu.scheduler.snapshot import (
        ClusterSnapshot,
        GangRequest,
        PodRequest,
        SnapshotMaintainer,
    )

    rng = random.Random(7)
    cluster = Cl(VirtualClock())
    cluster.add_nodes(make_tpu_pool(n_slices, slice_topology=SLICE_TOPOLOGY))

    shapes = [("1x4", 1), ("1x4", 1), ("2x4", 2), ("4x4", 4)]
    requests = []
    for i in range(n_gangs):
        topo, hosts = rng.choice(shapes)
        pg = PodGroup(
            metadata=ObjectMeta(name=f"scale-{i}", namespace="default"),
            min_member=hosts,
            topology_request=topo,
        )
        pg.metadata.creation_time = float(i) * 0.001
        pods = [
            PodRequest(
                name=f"scale-{i}-w-{j}", replica_type="Worker", index=j,
                resources={"cpu": 1.0, TPU_RESOURCE: 4.0},
            )
            for j in range(hosts)
        ]
        requests.append(GangRequest(
            group=pg, pods=pods, topology=topo, num_slices=1, tpu_type="v5e",
        ))

    t0 = time.perf_counter()
    snapshot = ClusterSnapshot(cluster.api)
    cold_snapshot_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    maintainer = SnapshotMaintainer(cluster.api)
    maintainer.rebuild()
    maintainer_prime_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    inc_snapshot = maintainer.snapshot()
    inc_snapshot_s = time.perf_counter() - t0

    packer = TPUPacker(kernel="numpy")
    t0 = time.perf_counter()
    placements = packer.place(requests, inc_snapshot, now=10_000.0)
    solve_s = time.perf_counter() - t0
    admitted = sum(1 for p in placements.values() if p is not None)
    return {
        "nodes": n_slices * HOSTS_PER_SLICE,
        "slices": n_slices,
        "gangs": n_gangs,
        "admitted": admitted,
        "cold_snapshot_walk_s": round(cold_snapshot_s, 4),
        "maintainer_prime_s": round(maintainer_prime_s, 4),
        "incremental_snapshot_serve_s": round(inc_snapshot_s, 6),
        "solve_wall_s": round(solve_s, 4),
        "budget_s": budget_s,
        "within_budget": solve_s < budget_s,
        "solver_stats": dict(packer.last_solve_stats),
    }


def run_soak(hours: float = 168.0, arrival_per_minute: float = 2.0,
             compression: float = 4.0, chaos_spec: str = "",
             seed: int = 14, slices: int = 2500,
             wall_budget_s: float = 3600.0,
             out: str = "BENCH_SELF_SOAK_r14.json"):
    """The `soak` bench block: a time-compressed simulated WEEK of fleet
    life at 10k nodes — sustained heavy-tailed arrivals across every
    workload kind into oversubscribed ClusterQueues, all five chaos tiers
    live simultaneously (pod, api, wire, node incl. rolling maintenance,
    host incl. one mid-soak control-plane failover onto the WAL-lockstep
    standby), under the fail-fast INV001–INV009 auditor. Any invariant
    violation raises and fails the bench with the replayable seed.

    Headline: sustained jobs/minute over the week with the MTTR
    distribution and the tail time-to-running SLOs held, zero violations,
    bounded growth of every audited accumulator."""
    import logging as _logging
    import tempfile

    from training_operator_tpu.config import parse_chaos_intensity
    from training_operator_tpu.soak import SoakConfig, SoakHarness

    _logging.getLogger("training_operator_tpu").setLevel(_logging.ERROR)
    cfg = SoakConfig(
        sim_hours=hours,
        arrival_per_minute=arrival_per_minute,
        compression=compression,
        chaos=parse_chaos_intensity(chaos_spec),
        seed=seed,
        tpu_slices=slices,
        max_wall_seconds=wall_budget_s,
    )

    def progress(info):
        print(
            f"# soak {info['phase']} fleet-hour {info['fleet_hour']:g}: "
            f"{info['completed']}/{info['submitted']} done, "
            f"{info['pending']} pending, {info['violations']} violations, "
            f"epoch wall {info['wall_s']}s",
            file=sys.stderr,
        )

    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="bench-soak-") as td:
        harness = SoakHarness(cfg, td, progress=progress)
        report = harness.run()
    report["wall_seconds"] = round(time.monotonic() - t0, 1)
    doc = {
        "bench": "soak",
        "method": (
            "virtual-clock soak harness (training_operator_tpu/soak/): "
            f"one seeded run, {hours:g} simulated fleet-hours at "
            f"compression {compression:g}x on {slices * 4} TPU hosts + "
            "CPU pool; Poisson arrivals with truncated-Pareto durations "
            "across jax/elastic/mpi/tf/v2 kinds into oversubscribed "
            "ClusterQueues; ChaosMonkey + APIChaos + WireChaos (in-process "
            "wire boundary) + NodeChaos (kills, slice kills, rolling "
            "maintenance) + HostChaos (mid-soak failover onto the "
            "WAL-lockstep in-process standby, byte-parity verified) all "
            "live, under the fail-fast INV001-INV009 auditor. All numbers "
            "reported in fleet seconds."
        ),
        **report,
    }
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    return report


def _accelerator_reachable(timeout_s: float = 150.0) -> bool:
    """Probe the default JAX backend in a SUBPROCESS with a hard timeout.

    The TPU can be attached through a tunnel plugin whose backend init
    BLOCKS indefinitely when the tunnel is down; probing in-process would
    hang this benchmark the same way. A dead probe downgrades the run to
    CPU (scheduler numbers still valid — the solver is the same program;
    the trainer block reports the outage instead of numbers)."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True,
            timeout=timeout_s,
        )
        return r.returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--jobs", type=int, default=1000)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--seeds", type=int, default=3,
                    help="run this many consecutive seeds (seed, seed+1, ...); "
                         "headline = primary seed, seeds block carries min/median")
    ap.add_argument("--quick", action="store_true", help="100-job smoke run")
    ap.add_argument("--all-baselines", action="store_true",
                    help="also run the contiguity-aware first-fit straw-man")
    ap.add_argument("--no-noise-sweep", action="store_true",
                    help="skip the estimate-robustness packer runs "
                         "(duration_noise block)")
    ap.add_argument("--tail-breakdown", dest="tail_breakdown",
                    action="store_true", default=True,
                    help="include per-job-class latency percentiles in the "
                         "output (tail_by_class block) — the tail-latency "
                         "diagnostic behind the README's analysis (default on)")
    ap.add_argument("--no-tail-breakdown", dest="tail_breakdown",
                    action="store_false")
    ap.add_argument("--drain-reserve-seconds", type=float, default=300.0,
                    help="packer tail SLO: whole-slice gangs waiting longer "
                         "trigger drain reservations (<=0 disables)")
    ap.add_argument("--max-drain-fraction", type=float, default=0.08,
                    help="packer tail SLO: max fraction of slices withheld "
                         "for draining per cycle")
    ap.add_argument("--aging-seconds", type=float, default=300.0,
                    help="packer starvation bound (FIFO promotion age)")
    ap.add_argument("--no-wire-overhead", action="store_true",
                    help="skip the wire-deployment overhead block (host + "
                         "operator as OS processes over HTTPS vs in-process)")
    ap.add_argument("--wire-overhead-only", action="store_true",
                    help="run only the wire-overhead block")
    ap.add_argument("--wire-jobs", type=int, default=200,
                    help="burst size for the wire-overhead block")
    ap.add_argument("--wire-ab", type=int, default=0, metavar="PAIRS",
                    help="run PAIRS interleaved before/after wire_overhead "
                         "pairs (each leg a fresh subprocess) and write the "
                         "aggregate artifact; requires --before-repo")
    ap.add_argument("--before-repo", default=None, metavar="DIR",
                    help="repo root of the 'before' code (a worktree of the "
                         "pre-change ref carrying this bench.py)")
    ap.add_argument("--ab-out", default="BENCH_SELF_WIRE_V2_r09.json",
                    metavar="FILE", help="artifact path for --wire-ab")
    ap.add_argument("--no-wire-resume", action="store_true",
                    help="skip the watch-resume reconnect-cost block")
    ap.add_argument("--wire-resume-only", action="store_true",
                    help="run only the watch-resume reconnect-cost block "
                         "(delta-resume vs forced-relist after a session "
                         "reap against a 1k-object cluster)")
    ap.add_argument("--wire-resume-objects", type=int, default=1000,
                    help="cluster size for the wire-resume block")
    ap.add_argument("--failover-only", action="store_true",
                    help="run ONLY the control-plane failover block: "
                         "WAL-shipping standby, primary SIGKILL mid-burst, "
                         "promotion MTTR + epoch-chained resume economics "
                         "(writes BENCH_SELF_FAILOVER artifact)")
    ap.add_argument("--failover-jobs", type=int, default=120,
                    help="burst size for --failover-only (default 120)")
    ap.add_argument("--failover-sessions", type=int, default=4,
                    help="surviving watch sessions for --failover-only")
    ap.add_argument("--failover-out", default="BENCH_SELF_FAILOVER_r12.json",
                    help="artifact path for --failover-only")
    ap.add_argument("--shards-only", action="store_true",
                    help="run ONLY the operator scale-out block: jobs/min "
                         "vs sharded replica count (1/2/3, real OS "
                         "processes) + the follower-read watch-session "
                         "swarm -> BENCH_SELF_SHARDS artifact")
    ap.add_argument("--shards-jobs", type=int, default=5000,
                    help="burst size per replica-count leg (default 5000)")
    ap.add_argument("--shards-sessions", type=int, default=1000,
                    help="watch sessions parked per follower-read leg "
                         "(default 1000)")
    ap.add_argument("--shards-out", default="BENCH_SELF_SHARDS_r15.json",
                    help="artifact path for --shards-only")
    ap.add_argument("--store-shards-only", action="store_true",
                    help="run ONLY the sharded write-plane block: write p50 "
                         "+ jobs/minute vs write-shard count (1/2/4 fsync'd "
                         "host processes behind the client-side router, "
                         "interleaved legs) -> BENCH_SELF_STORE_SHARDS "
                         "artifact")
    ap.add_argument("--store-shards-jobs", type=int, default=5000,
                    help="create-burst size per write-shard leg")
    ap.add_argument("--store-shards-pairs", type=int, default=2,
                    help="interleaved rounds across shard counts")
    ap.add_argument("--store-shards-counts", default="1,2,4",
                    help="comma-separated shard counts to sweep")
    ap.add_argument("--store-shards-out",
                    default="BENCH_SELF_STORE_SHARDS_r17.json",
                    help="artifact path for --store-shards-only")
    ap.add_argument("--wire-driver-stub", action="store_true",
                    help="emit the self-measured wire-overhead proxy with "
                         "an explicit external_baseline_unmeasured=true "
                         "field (the driver-side 1.797x has not been "
                         "re-measured since PR 6)")
    ap.add_argument("--wire-driver-out",
                    default="BENCH_SELF_WIRE_DRIVER_r17.json",
                    help="artifact path for --wire-driver-stub")
    ap.add_argument("--node-chaos-only", action="store_true",
                    help="run only the node-loss MTTR block (kill one host "
                         "of a whole-slice TPU gang; measure detect -> "
                         "evict -> re-solve -> Running again)")
    ap.add_argument("--node-grace-period", type=float, default=40.0,
                    help="node-chaos block: heartbeat silence before "
                         "NotReady + unreachable taint")
    ap.add_argument("--node-toleration-seconds", type=float, default=30.0,
                    help="node-chaos block: taint age before eviction")
    ap.add_argument("--tenancy-only", action="store_true",
                    help="run only the multi-tenant contention block "
                         "(N teams over-subscribing the pool, FCFS vs the "
                         "fair-share arbiter: Jain index, preemptions, "
                         "per-tier latency) and write --tenancy-out")
    ap.add_argument("--tenancy-teams", type=int, default=4,
                    help="teams/queues in the contention block")
    ap.add_argument("--tenancy-jobs", type=int, default=12,
                    help="jobs per team in the contention block")
    ap.add_argument("--tenancy-out", default="BENCH_SELF_TENANCY_r11.json",
                    help="artifact path for --tenancy-only")
    ap.add_argument("--solver-leg", default=None,
                    choices=("legacy", "incremental"),
                    help="run ONE solver-bench burst leg and print its "
                         "stats as JSON — used to measure the true pre-PR "
                         "code from a worktree carrying this harness "
                         "(bench-wire-v2 method)")
    ap.add_argument("--solver-only", action="store_true",
                    help="run only the incremental-solver A/B block "
                         "(pinned-legacy vs incremental arms, interleaved "
                         "pairs, + the 10k-node/2k-gang single-solve scale "
                         "block) and write --solver-out")
    ap.add_argument("--solver-pairs", type=int, default=3,
                    help="interleaved pairs for the solver block")
    ap.add_argument("--solver-jobs", type=int, default=1000,
                    help="burst size for the solver block")
    ap.add_argument("--solver-out", default="BENCH_SELF_SOLVER_r13.json",
                    help="artifact path for --solver-only")
    ap.add_argument("--soak-only", action="store_true",
                    help="run only the time-compressed fleet soak: a "
                         "simulated week at 10k nodes, all five chaos "
                         "tiers live + one host failover, fail-fast "
                         "INV001-INV009 auditing (writes --soak-out)")
    ap.add_argument("--soak-hours", type=float, default=168.0,
                    help="simulated fleet hours (default 168 = one week)")
    ap.add_argument("--soak-arrival", type=float, default=2.0,
                    help="mean arrivals per fleet-minute (default 2)")
    ap.add_argument("--soak-compression", type=float, default=4.0,
                    help="duration-compression factor (default 4)")
    ap.add_argument("--soak-chaos", default="", metavar="SPEC",
                    help='per-tier intensity spec, e.g. "pod=1,node=2" '
                         "(default: every tier at 1.0)")
    ap.add_argument("--soak-slices", type=int, default=2500,
                    help="TPU slices (x4 hosts; default 2500 = 10k nodes)")
    ap.add_argument("--soak-seed", type=int, default=14,
                    help="the single replayable soak seed")
    ap.add_argument("--soak-wall-budget", type=float, default=3600.0,
                    help="abort if the soak exceeds this wall time (s)")
    ap.add_argument("--soak-out", default="BENCH_SELF_SOAK_r14.json",
                    help="artifact path for --soak-only")
    ap.add_argument("--audit", action="store_true",
                    help="run every burst under the standing invariant "
                         "auditor in fail-fast mode (observe/invariants.py): "
                         "one INV violation anywhere fails the bench")
    ap.add_argument("--audit-only", action="store_true",
                    help="run only the auditor-overhead block (on/off over "
                         "the same 120-job burst, BENCH_SELF_OBSERVE "
                         "method) and write --audit-out")
    ap.add_argument("--audit-jobs", type=int, default=120,
                    help="burst size for the audit-overhead block")
    ap.add_argument("--audit-out", default="BENCH_SELF_AUDIT_r10.json",
                    help="artifact path for --audit-only")
    ap.add_argument("--slo-only", action="store_true",
                    help="run only the SLO-engine-overhead block (evaluator "
                         "+ attribution on/off over the same 120-job burst, "
                         "run_audit_overhead method) and write --slo-out")
    ap.add_argument("--slo-jobs", type=int, default=120,
                    help="burst size for the SLO-overhead block")
    ap.add_argument("--slo-out", default="BENCH_SELF_SLO_r19.json",
                    help="artifact path for --slo-only")
    ap.add_argument("--lockcheck", action="store_true",
                    help="run the whole bench under the runtime lock-order "
                         "witness (TRAINING_LOCKCHECK=1; off by default in "
                         "benches)")
    ap.add_argument("--lockcheck-only", action="store_true",
                    help="run only the witness-overhead block (on/off over "
                         "the same 120-job burst, run_audit_overhead "
                         "method) and write --lockcheck-out")
    ap.add_argument("--lockcheck-jobs", type=int, default=120,
                    help="burst size for the lockcheck-overhead block")
    ap.add_argument("--lockcheck-out", default="BENCH_SELF_LOCKCHECK_r16.json",
                    help="artifact path for --lockcheck-only")
    ap.add_argument("--no-observe", action="store_true",
                    help="skip the observability-overhead block")
    ap.add_argument("--observe-only", action="store_true",
                    help="run only the observability-overhead block "
                         "(tracing on vs off over the same gang burst)")
    ap.add_argument("--observe-jobs", type=int, default=120,
                    help="burst size for the observe block")
    ap.add_argument("--observe-trace", default=None, metavar="FILE",
                    help="dump the observe block's final burst timelines "
                         "as Chrome Trace Event JSON")
    trainer_group = ap.add_mutually_exclusive_group()
    trainer_group.add_argument("--no-trainer", action="store_true",
                               help="skip the single-chip trainer compute benchmark")
    trainer_group.add_argument("--trainer-only", action="store_true",
                               help="run only the trainer compute benchmark")
    args = ap.parse_args()
    n = 100 if args.quick else args.jobs
    if args.audit:
        global AUDIT_BURSTS
        AUDIT_BURSTS = True

    if args.solver_leg:
        import inspect as _inspect

        specs = build_workload(args.solver_jobs, args.seed)
        inc = args.solver_leg == "incremental"
        packer_kwargs = {"kernel": "numpy" if inc else "jax"}
        if "kernel" not in _inspect.signature(TPUPacker.__init__).parameters:
            packer_kwargs = {}  # pre-PR packer: one (jit) kernel
        run = run_burst(specs, TPUPacker(**packer_kwargs), incremental=inc)
        print(json.dumps({"leg": args.solver_leg, **{
            k: run[k] for k in (
                "solver_wall_s", "solver_cycles", "p50_s", "p99_s",
                "tpu_utilization",
            )
        }}))
        return

    if args.solver_only:
        block = run_solver_bench(args.solver_jobs, pairs=args.solver_pairs,
                                 seed=args.seed, out=args.solver_out,
                                 before_repo=args.before_repo)
        print(json.dumps({
            "metric": "solver_wall_per_job_speedup",
            "value": block["speedup"],
            "unit": "x (pinned-legacy solver_wall/job over incremental, "
                    "median of interleaved pairs; scale_10k carries the "
                    "10k-node single-solve budget check)",
            "vs_baseline": block["solver_wall_s"]["legacy"],
            "solver": {k: v for k, v in block.items() if k != "runs"},
        }))
        return

    if args.soak_only:
        block = run_soak(
            hours=args.soak_hours, arrival_per_minute=args.soak_arrival,
            compression=args.soak_compression, chaos_spec=args.soak_chaos,
            seed=args.soak_seed, slices=args.soak_slices,
            wall_budget_s=args.soak_wall_budget, out=args.soak_out,
        )
        print(json.dumps({
            "metric": "soak_jobs_per_fleet_minute",
            "value": block["throughput"]["jobs_per_fleet_minute"],
            "unit": ("jobs/min sustained over the simulated week at "
                     "10k nodes, five chaos tiers live, zero invariant "
                     "violations (fail-fast INV001-INV009)"),
            "vs_baseline": None,
            "soak": {k: block[k] for k in (
                "nodes", "fleet_hours", "compression", "seed",
                "wall_seconds", "jobs", "throughput", "slo", "mttr",
                "chaos", "failover", "auditor", "growth",
            )},
        }))
        return

    if args.audit_only:
        block = run_audit_overhead(args.audit_jobs)
        doc = {
            "metric": "audit_overhead_pct",
            "value": block["overhead_pct"],
            "unit": "% of burst wall spent in InvariantAuditor.audit "
                    "(direct self-timed share; wall_pairs = on/off "
                    "corroboration with spread)",
            "vs_baseline": None,
            "audit": block,
        }
        print(json.dumps(doc))
        with open(args.audit_out, "w") as f:
            json.dump(doc, f, indent=1)
        return

    if args.slo_only:
        block = run_slo_overhead(args.slo_jobs)
        doc = {
            "metric": "slo_overhead_pct",
            "value": block["overhead_pct"],
            "unit": "% of burst wall spent in SLOEvaluator.evaluate + "
                    "explain (direct self-timed share; wall_pairs = on/off "
                    "corroboration with spread)",
            "vs_baseline": None,
            "slo": block,
        }
        print(json.dumps(doc))
        with open(args.slo_out, "w") as f:
            json.dump(doc, f, indent=1)
        return

    if args.lockcheck_only:
        block = run_lockcheck_overhead(args.lockcheck_jobs)
        doc = {
            "metric": "lockcheck_overhead_pct",
            "value": block["overhead_pct"],
            "unit": "% of burst wall spent in the lock-order witness "
                    "(direct self-timed _note_acquire share; wall_pairs = "
                    "on/off corroboration with spread; witnessed legs run "
                    "fail-fast, zero violations required)",
            "vs_baseline": None,
            "lockcheck": block,
        }
        print(json.dumps(doc))
        with open(args.lockcheck_out, "w") as f:
            json.dump(doc, f, indent=1)
        return

    if args.tenancy_only:
        block = run_tenancy_contention(
            teams=args.tenancy_teams, jobs_per_team=args.tenancy_jobs,
        )
        doc = {
            "metric": "tenancy_jain_fairness",
            "value": block["arbiter"]["jain_fairness"],
            "unit": "Jain index over per-team mean running chips during "
                    "contention (1.0 = perfectly fair; arbiter leg)",
            "vs_baseline": block["fcfs"]["jain_fairness"],
            "tenancy": block,
        }
        print(json.dumps(doc))
        with open(args.tenancy_out, "w") as f:
            json.dump(doc, f, indent=1)
        return

    if args.wire_ab:
        if not args.before_repo:
            ap.error("--wire-ab requires --before-repo")
        run_wire_ab(args.wire_ab, args.before_repo, args.wire_jobs, args.ab_out)
        return

    if args.wire_resume_only:
        block = run_wire_resume(args.wire_resume_objects)
        print(json.dumps({
            "metric": "wire_resume_relist_over_delta_events",
            "value": block["relist_over_delta_events"],
            "unit": "x (forced-relist events / delta-resume events per reconnect)",
            "vs_baseline": None,
            "wire_resume": block,
        }))
        return

    if args.failover_only:
        block = run_failover(jobs=args.failover_jobs,
                             watch_sessions=args.failover_sessions,
                             out=args.failover_out)
        print(json.dumps({
            "metric": "failover_mttr_s",
            "value": block["mttr_s"],
            "unit": "s (primary SIGKILL -> first acknowledged write on the "
                    "promoted standby, via the failover client's ordinary "
                    "retry arm; promote_s isolates the detection+promotion "
                    "share)",
            "vs_baseline": None,
            "failover": block,
        }))
        return

    if args.shards_only:
        block = run_shards(jobs=args.shards_jobs,
                           sessions=args.shards_sessions,
                           out=args.shards_out)
        legs = {leg["replicas"]: leg["jobs_per_minute"]
                for leg in block["burst"]}
        print(json.dumps({
            "metric": "shard_scaleout_jobs_per_minute",
            "value": legs,
            "unit": "jobs/min vs sharded operator replica count (real OS "
                    "processes over one wire host); follower_reads block "
                    "carries the 1k-session standby-offload write p50",
            "vs_baseline": None,
            "shards": block,
        }))
        return

    if args.store_shards_only:
        counts = tuple(
            int(x) for x in args.store_shards_counts.split(",") if x.strip()
        )
        artifact = run_store_shards(jobs=args.store_shards_jobs,
                                    pairs=args.store_shards_pairs,
                                    counts=counts,
                                    out=args.store_shards_out)
        print(json.dumps({
            "metric": "store_shard_write_p50_speedup_2_over_1",
            "value": artifact["write_p50_speedup_2_over_1"],
            "unit": "x (1-shard write p50 / 2-shard write p50, medians of "
                    "interleaved fsync'd create-burst legs through the "
                    "client-side shard router)",
            "vs_baseline": None,
            "store_shards": {
                "medians_by_shard_count": artifact["medians_by_shard_count"],
                "two_shards_beat_one_write_p50":
                    artifact["two_shards_beat_one_write_p50"],
                "artifact": args.store_shards_out,
            },
        }))
        return

    if args.wire_driver_stub:
        artifact = run_wire_driver_stub(out=args.wire_driver_out)
        print(json.dumps({
            "metric": "wire_driver_external_baseline_unmeasured",
            "value": artifact["external_baseline_unmeasured"],
            "unit": "bool (true until a driver machine re-measures the "
                    "1.797x wire ratio; self_measured_proxy is the tracked "
                    "stand-in)",
            "vs_baseline": artifact["external_baseline_r05"][
                "overhead_ratio_p50"],
            "wire_driver": {
                "self_measured_ratio_p50":
                    artifact["self_measured_proxy"]["overhead_ratio_p50"],
                "artifact": args.wire_driver_out,
            },
        }))
        return

    if args.node_chaos_only:
        block = run_node_chaos(grace=args.node_grace_period,
                               toleration=args.node_toleration_seconds)
        print(json.dumps({
            "metric": "node_chaos_mttr_s",
            "value": block["mttr_s"],
            "unit": "s (node kill -> gang Running again; includes the "
                    "grace + toleration policy window — "
                    "recovery_mechanism_s isolates evict -> re-solve -> "
                    "restart)",
            "vs_baseline": None,
            "node_chaos": block,
        }))
        return

    if args.observe_only:
        block = run_observe_overhead(args.observe_jobs,
                                     chrome_trace=args.observe_trace)
        print(json.dumps({
            "metric": "observe_overhead_pct",
            "value": block["overhead_pct"],
            "unit": "% of burst wall spent in tracer entry points "
                    "(direct self-timed share; wall_pairs = on/off "
                    "corroboration with spread)",
            "vs_baseline": None,
            "observe": block,
        }))
        return

    if args.wire_overhead_only:
        block = run_wire_overhead(args.wire_jobs)
        print(json.dumps({
            "metric": "wire_overhead_ratio_p50",
            "value": block["overhead_ratio_p50"],
            "unit": "x (wire p50 / in-process p50)",
            "vs_baseline": None,
            "wire_overhead": block,
        }))
        return

    def make_packer():
        # Same knobs a deployment sets via OperatorConfig / CLI flags —
        # the bench measures the shipped configuration surface, not a
        # hardcoded construction.
        return TPUPacker(
            drain_reserve_seconds=args.drain_reserve_seconds,
            max_drain_fraction=args.max_drain_fraction,
            aging_seconds=args.aging_seconds,
        )

    if args.no_trainer:
        # Scheduler-only run: the solver is CPU-pinned regardless, so skip
        # the (slow when the tunnel is dead) accelerator probe entirely and
        # keep backend init off the possibly-hung TPU plugin.
        degraded = True
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        degraded = not _accelerator_reachable()
        if degraded:
            print(
                "bench: accelerator backend unreachable (tunnel down?) — "
                "forcing CPU for the scheduler bench, skipping the trainer block",
                file=sys.stderr,
            )
            import jax

            jax.config.update("jax_platforms", "cpu")

    trainer = None
    if not args.no_trainer:
        if degraded:
            trainer = {"error": "accelerator backend unreachable (probe timed out)"}
        else:
            from training_operator_tpu.trainer.bench import run_trainer_bench

            trainer = run_trainer_bench(steps=5 if args.quick else 10)
    if (args.no_trainer or degraded) and not args.quick:
        # Scheduler-only / tunnel-down runs still publish the END-TO-END
        # trainer loop number on CPU (VERDICT r5 Next #5): the tokens/s +
        # data/ckpt-split methodology must exist in an artifact — platform-
        # labeled "cpu" so nobody mistakes it for the chip capture — before
        # the TPU tunnel returns, not after.
        try:
            from training_operator_tpu.trainer.bench import bench_trainer_e2e

            e2e_cpu = bench_trainer_e2e(steps=30, ckpt_every=10)
            if trainer is None:
                trainer = {}
            trainer["trainer_e2e"] = e2e_cpu
            trainer["note"] = (
                "trainer_e2e measured on cpu (scheduler-only run); "
                "the chip capture replaces it when the tunnel returns"
            )
        except Exception as e:  # noqa: BLE001 — the scheduler metric must survive
            if trainer is None:
                trainer = {}
            trainer["trainer_e2e"] = {"error": f"{type(e).__name__}: {e}"}
    if not args.no_trainer:
        if args.trainer_only:
            ts = (trainer or {}).get("train_step", {})
            print(json.dumps({
                "metric": "trainer_tokens_per_s",
                "value": ts.get("tokens_per_s"),
                "unit": "tokens/s",
                "vs_baseline": None,
                "trainer": trainer,
            }))
            return

    seed_list = [args.seed + i for i in range(1 if args.quick else max(1, args.seeds))]
    per_seed = []
    primary = None
    for s in seed_list:
        specs = build_workload(n, s)
        base = run_burst(specs, BaselinePlacer(whole_slice=True))
        pack = run_burst(specs, make_packer(),
                         return_latencies=(args.tail_breakdown and s == args.seed))
        vs = round(base["p50_s"] / pack["p50_s"], 3) if pack["p50_s"] > 0 else None
        per_seed.append({
            "seed": s,
            "p50_s": pack["p50_s"],
            "baseline_p50_s": base["p50_s"],
            "vs_baseline": vs,
        })
        if s == args.seed:
            primary = (specs, base, pack, vs)
    specs, base, pack, vs_primary = primary

    # Per-class tail breakdown (primary seed): which job shapes populate
    # the p90+ — the diagnostic behind the README tail-latency analysis.
    tail_by_class = None
    lat_by_name = pack.pop("latencies_by_name", None)
    if lat_by_name:
        import collections

        by = collections.defaultdict(list)
        for name, lat in lat_by_name.items():
            by[name.rsplit("-", 1)[0]].append(lat)
        tail_by_class = {
            cls: {
                "n": len(v),
                "p50_s": round(_pct(sorted(v), 0.50), 1),
                "p90_s": round(_pct(sorted(v), 0.90), 1),
                "p99_s": round(_pct(sorted(v), 0.99), 1),
            }
            for cls, v in sorted(by.items())
        }

    # Estimate-robustness sweep (primary seed): the headline above is
    # measured with EXACT declared durations — a best case no real user
    # hits. Re-run the packer with degraded estimates (true durations, and
    # therefore the baseline run, unchanged) so the claim carries its own
    # sensitivity analysis instead of leaning on an oracle.
    duration_noise = None
    if not args.quick and not args.no_noise_sweep:
        duration_noise = {}
        for label, noise, missing in (
            ("noise_x3", 3.0, 0.0),
            ("missing30", 1.0, 0.30),
            ("noise_x3_missing30", 3.0, 0.30),
        ):
            noisy = perturb_declared(specs, args.seed, noise_factor=noise,
                                     missing_frac=missing)
            run = run_burst(noisy, make_packer())
            duration_noise[label] = {
                "p50_s": run["p50_s"],
                "p90_s": run["p90_s"],
                "p99_s": run["p99_s"],
                "vs_baseline": round(base["p50_s"] / run["p50_s"], 3)
                if run["p50_s"] > 0 else None,
            }

    wire_overhead = None
    if not args.quick and not args.no_wire_overhead:
        wire_overhead = run_wire_overhead(args.wire_jobs)
    wire_resume = None
    if not args.quick and not args.no_wire_resume:
        wire_resume = run_wire_resume(args.wire_resume_objects)
    observe_block = None
    if not args.quick and not args.no_observe:
        observe_block = run_observe_overhead(args.observe_jobs,
                                             chrome_trace=args.observe_trace)

    oracle = oracle_bound(specs)
    goracle = granular_oracle(specs)
    ratios = sorted(e["vs_baseline"] for e in per_seed if e["vs_baseline"] is not None)
    p50s = sorted(e["p50_s"] for e in per_seed)
    out = {
        "metric": f"burst{n}_p50_schedule_to_running",
        "value": pack["p50_s"],
        "unit": "s",
        "vs_baseline": vs_primary,
        # Packer p50 over the zero-cost greedy granular reference discipline
        # (<1.0 = the packer out-schedules greedy-SJF-at-zero-cost; see
        # granular_oracle — a comparison point, not a bound). null when the
        # pool is so unloaded the reference p50 is ~0.
        "vs_granular_oracle": round(pack["p50_s"] / goracle["p50_s"], 3)
        if goracle["p50_s"] > 0
        else None,
        "utilization_gain_pp": round(100 * (pack["tpu_utilization"] - base["tpu_utilization"]), 1),
        "seeds": {
            "runs": per_seed,
            "vs_baseline_min": ratios[0] if ratios else None,
            "vs_baseline_median": ratios[len(ratios) // 2] if ratios else None,
            "p50_median_s": p50s[len(p50s) // 2] if p50s else None,
        },
        "packer": pack,
        "baseline": base,
        "oracle_fungible": oracle,
        "oracle_granular": goracle,
    }
    if duration_noise is not None:
        out["duration_noise"] = duration_noise
    if wire_overhead is not None:
        out["wire_overhead"] = wire_overhead
    if wire_resume is not None:
        out["wire_resume"] = wire_resume
    if observe_block is not None:
        out["observe"] = observe_block
    if tail_by_class is not None:
        out["tail_by_class"] = tail_by_class
    if trainer is not None:
        out["trainer"] = trainer
    if args.all_baselines:
        out["baseline_firstfit"] = run_burst(specs, BaselinePlacer(whole_slice=False))
    print(json.dumps(out))


if __name__ == "__main__":
    main()
