#!/usr/bin/env python
"""Headline benchmark: 1k-job cold-start burst on a heterogeneous TPU+GPU pool.

BASELINE.md configs 2 & 5: 1000 jobs (JAX TPU gangs of several shapes, GPU DDP
gangs, CPU jobs) submitted at t=0 against 48 v5e-4x4 slices + 32x8-GPU nodes +
CPU pool. Two full simulation runs, identical workload:

  baseline — volcano-style gang scheduling (BaselinePlacer whole-slice mode:
             topology-unaware schedulers force slice-granularity dedication,
             so sub-slice jobs strand the rest of their slice)
  packer   — the JAX batched placement engine (TPUPacker: contiguous ICI
             sub-mesh packing, best-fit anti-fragmentation)
  (--all-baselines adds the stronger contiguity-aware first-fit straw-man)

The cluster runs on a virtual clock; each scheduler's real solve wall-time is
charged into simulated time (GangScheduler charge_solve_time), so the p50
schedule-to-running latency reflects both queueing quality (fragmentation)
and actual solver speed on this machine's accelerator.

Prints ONE JSON line:
  metric      p50 schedule-to-running latency of the packer run (seconds)
  vs_baseline baseline_p50 / packer_p50  (>1 = packer faster)
  extras      p90/p99, makespan, TPU-chip utilization %, solver wall time

Usage: python bench.py [--jobs N] [--seed S] [--quick]
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import Container, JobConditionType, PodTemplateSpec, ReplicaSpec
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta, PyTorchJob, TFJob, TPUPolicy
from training_operator_tpu.cluster.inventory import (
    GPU_RESOURCE,
    TPU_RESOURCE,
    make_cpu_pool,
    make_gpu_pool,
    make_tpu_pool,
)
from training_operator_tpu.cluster.objects import PodPhase
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
)
from training_operator_tpu.controllers import OperatorManager, register_all
from training_operator_tpu.scheduler import BaselinePlacer, GangScheduler, TPUPacker


def build_workload(n_jobs: int, seed: int):
    """Deterministic job mix. Returns a list of constructor thunks so each
    run gets fresh objects."""
    rng = random.Random(seed)
    specs = []
    for i in range(n_jobs):
        r = rng.random()
        dur = str(rng.randint(30, 120))
        if r < 0.35:
            specs.append(("jax", f"jax-sub-{i}", "2x4", 2, 1, dur))
        elif r < 0.55:
            specs.append(("jax", f"jax-host-{i}", "1x4", 1, 1, dur))
        elif r < 0.70:
            specs.append(("jax", f"jax-full-{i}", "4x4", 4, 1, dur))
        elif r < 0.75:
            specs.append(("jax", f"jax-multi-{i}", "4x4", 8, 2, dur))
        elif r < 0.90:
            gpus = rng.choice([4.0, 8.0])
            workers = rng.choice([2, 4])
            specs.append(("gpu", f"ddp-{i}", gpus, workers, 1, dur))
        else:
            specs.append(("cpu", f"tf-{i}", 2.0, rng.choice([1, 2]), 1, dur))
    return specs


def make_job(spec):
    kind, name, shape, workers, num_slices, dur = spec
    if kind == "jax":
        chips = 1
        for d in shape.split("x"):
            chips *= int(d)
        t = PodTemplateSpec(
            containers=[Container(name="jax", image="trainer",
                                  resources={"cpu": 1.0, TPU_RESOURCE: 4.0})]
        )
        t.annotations[ANNOTATION_SIM_DURATION] = dur
        return JAXJob(
            metadata=ObjectMeta(name=name),
            replica_specs={"Worker": ReplicaSpec(replicas=workers, template=t)},
            tpu_policy=TPUPolicy(accelerator=f"v5e-{chips}", topology=shape,
                                 num_slices=num_slices),
        )
    if kind == "gpu":
        t = PodTemplateSpec(
            containers=[Container(name="pytorch", image="trainer",
                                  resources={"cpu": 2.0, GPU_RESOURCE: shape})]
        )
        t.annotations[ANNOTATION_SIM_DURATION] = dur
        return PyTorchJob(
            metadata=ObjectMeta(name=name),
            replica_specs={"Worker": ReplicaSpec(replicas=workers, template=t)},
        )
    t = PodTemplateSpec(
        containers=[Container(name="tensorflow", image="trainer",
                              resources={"cpu": shape})]
    )
    t.annotations[ANNOTATION_SIM_DURATION] = dur
    return TFJob(
        metadata=ObjectMeta(name=name),
        replica_specs={"Worker": ReplicaSpec(replicas=workers, template=t)},
    )


def run_burst(specs, placer, tpu_slices=48, gpu_nodes=32, cpu_nodes=16):
    cluster = Cluster(VirtualClock())
    cluster.add_nodes(make_tpu_pool(tpu_slices, slice_topology="4x4"))
    cluster.add_nodes(make_gpu_pool(gpu_nodes, gpus_per_node=8, nodes_per_nvlink_domain=4))
    cluster.add_nodes(make_cpu_pool(cpu_nodes, cpu_per_node=64.0))
    DefaultScheduler(cluster)
    SimKubelet(cluster)
    sched = GangScheduler(cluster, placer, charge_solve_time=True, prewarm=True)
    mgr = OperatorManager(cluster, gang_enabled=True, reconciles_per_tick=4096)
    register_all(mgr)

    jobs = [make_job(s) for s in specs]
    t_wall = time.perf_counter()
    for j in jobs:
        mgr.submit(j)

    total_chips = tpu_slices * 16.0
    # Schedule-to-running is captured from job status-update watch events
    # (the Running condition is cleared by terminal conditions, so it must be
    # read while live). O(events), not O(cluster x steps).
    running_at = {}
    job_kinds = {j.kind for j in jobs}
    watch = cluster.api.watch(kinds=job_kinds)

    def track():
        for ev in watch.drain():
            if ev.type != "Modified":
                continue
            j = ev.obj
            if j.name in running_at:
                continue
            cond = capi.get_condition(j.status, JobConditionType.RUNNING)
            if cond is not None and cond.status:
                running_at[j.name] = cond.last_transition_time

    cluster.add_ticker(track)

    def all_done():
        return all(capi.is_finished(j.status) for j in jobs)

    ok = cluster.run_until(all_done, timeout=50_000, max_steps=5_000_000)
    wall = time.perf_counter() - t_wall
    if not ok:
        unfinished = sum(1 for j in jobs if not capi.is_finished(j.status))
        raise RuntimeError(f"burst did not finish: {unfinished} jobs pending")

    latencies = []
    for j in jobs:
        created = j.metadata.creation_time or 0.0
        if j.name in running_at:
            latencies.append(running_at[j.name] - created)
    latencies.sort()

    def pct(p):
        return latencies[min(len(latencies) - 1, int(p * len(latencies)))] if latencies else 0.0

    # Utilization post-hoc from pod lifetimes: chip-seconds / capacity.
    makespan = cluster.clock.now()
    busy_area = 0.0
    for p in cluster.api.list("Pod"):
        chips = p.resources().get(TPU_RESOURCE, 0.0)
        if chips and p.status.start_time is not None:
            end = p.status.finish_time if p.status.finish_time is not None else makespan
            busy_area += chips * (end - p.status.start_time)
    utilization = busy_area / (total_chips * makespan) if makespan else 0.0
    return {
        "p50_s": round(pct(0.50), 3),
        "p90_s": round(pct(0.90), 3),
        "p99_s": round(pct(0.99), 3),
        "makespan_s": round(makespan, 1),
        "tpu_utilization": round(utilization, 4),
        "solver_wall_s": round(sched.solve_walltime_total, 3),
        "solver_cycles": sched.cycles,
        "bench_wall_s": round(wall, 1),
        "jobs_measured": len(latencies),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--jobs", type=int, default=1000)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--quick", action="store_true", help="100-job smoke run")
    ap.add_argument("--all-baselines", action="store_true",
                    help="also run the contiguity-aware first-fit straw-man")
    trainer_group = ap.add_mutually_exclusive_group()
    trainer_group.add_argument("--no-trainer", action="store_true",
                               help="skip the single-chip trainer compute benchmark")
    trainer_group.add_argument("--trainer-only", action="store_true",
                               help="run only the trainer compute benchmark")
    args = ap.parse_args()
    n = 100 if args.quick else args.jobs

    trainer = None
    if not args.no_trainer:
        from training_operator_tpu.trainer.bench import run_trainer_bench

        trainer = run_trainer_bench(steps=5 if args.quick else 10)
        if args.trainer_only:
            ts = trainer.get("train_step", {})
            print(json.dumps({
                "metric": "trainer_tokens_per_s",
                "value": ts.get("tokens_per_s"),
                "unit": "tokens/s",
                "vs_baseline": None,
                "trainer": trainer,
            }))
            return

    specs = build_workload(n, args.seed)
    base = run_burst(specs, BaselinePlacer(whole_slice=True))
    pack = run_burst(specs, TPUPacker())
    out = {
        "metric": f"burst{n}_p50_schedule_to_running",
        "value": pack["p50_s"],
        "unit": "s",
        "vs_baseline": round(base["p50_s"] / pack["p50_s"], 3) if pack["p50_s"] > 0 else float("inf"),
        "utilization_gain_pp": round(100 * (pack["tpu_utilization"] - base["tpu_utilization"]), 1),
        "packer": pack,
        "baseline": base,
    }
    if trainer is not None:
        out["trainer"] = trainer
    if args.all_baselines:
        out["baseline_firstfit"] = run_burst(specs, BaselinePlacer(whole_slice=False))
    print(json.dumps(out))


if __name__ == "__main__":
    main()
