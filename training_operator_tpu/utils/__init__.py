"""Cross-cutting utilities: metrics, structured logging, naming."""
