"""Lock discipline, runtime half: the lock-order witness.

Every lock in the control plane is constructed through this module
(codelint CL008 rejects raw `threading.Lock()` anywhere else — the CL005
"one declaration site" pattern applied to concurrency). The factories are
deliberately cheap in both modes:

  - `TRAINING_LOCKCHECK` unset/0 (production, benches unless --lockcheck):
    `TrackedLock()` returns a *raw* `threading.Lock` — one module-level
    flag check, no wrapper allocation, zero per-acquire overhead.
  - `TRAINING_LOCKCHECK=1` (the default in tests and the chaos/soak
    lanes, set in tests/conftest.py): the factories return witness
    wrappers that record, per thread, the set of locks currently held and
    maintain a process-global acquisition-order graph (lockdep/FreeBSD
    witness style). The first time an edge A->B closes a cycle against
    the recorded order, the witness reports ONCE per edge-pair — with the
    stack digest of both conflicting acquisition sites — via
    `training_lock_order_violations_total{pair}`, the optional violation
    sink (the soak harness points it at a Warning Event), and, under
    `set_fail_fast(True)`, an `InvariantViolationError` raised out of the
    acquire, turning every chaos tier into a lock-order regression test.

Order classes are NAMES, not lock instances: every `HostStore._lock`
shares the class "store", so an ordering observed between any store and
any apiserver generalizes — exactly what makes the graph meaningful when
open item 1 instantiates the store machinery per shard.

The graph, the reported-pair set, and the order-exception registry are
process-global mutable state (the CL006 re-registration lesson): exception
registration is idempotent under pytest re-imports, and the soak harness
calls `reset_witness()` between stack rebuilds so edges from a torn-down
deployment shape can't condemn the next one.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Tuple

__all__ = [
    "TrackedLock", "TrackedRLock", "TrackedCondition",
    "enable", "lockcheck_enabled", "set_fail_fast", "fail_fast_enabled",
    "reset_witness", "witness_violations", "order_graph",
    "register_order_exception", "order_exceptions", "set_violation_sink",
    "acquisitions",
]

# Module-level enable flag, captured from the environment at import. The
# factories read it per call, so tests/benches can flip it with enable();
# locks constructed before the flip keep their mode (a raw Lock cannot
# retroactively grow a witness).
_ENABLED = os.environ.get("TRAINING_LOCKCHECK", "") not in ("", "0")
_FAIL_FAST = os.environ.get("TRAINING_LOCKCHECK_FAILFAST", "") not in ("", "0")

# The witness's own meta-lock. Deliberately a RAW lock: it guards the graph
# itself and must never appear in it (it nests inside arbitrary tracked
# acquires by design).
_meta = threading.Lock()

# Per-thread stack of held order-class names, in acquisition order.
_tls = threading.local()

# name -> set of names acquired at least once while `name` was held.
_adj: Dict[str, set] = {}
# (held, acquired) -> human-readable site + stack digest of the FIRST
# observation of that edge (the evidence half of a later cycle report).
_edge_sites: Dict[Tuple[str, str], str] = {}
# Edge pairs already reported (once-per-incident: a hot inverted pair must
# not melt the metric family or spam the sink).
_reported: set = set()
# Violations observed this process (cleared by reset_witness).
_violations: List[Dict[str, Any]] = []
# frozenset({a, b}) -> reason. Sanctioned inversions (idempotent to
# re-register; survives reset_witness unless clear_exceptions=True).
_order_exceptions: Dict[FrozenSet[str], str] = {}
# Optional callable(violation_dict): the soak harness points this at a
# Warning Event on the live store.
_sink: Optional[Callable[[Dict[str, Any]], None]] = None
# Tracked acquisitions observed (enabled mode only) — the denominator the
# lockcheck bench reports next to its overhead share.
_acquisitions = 0


def enable(flag: bool = True) -> None:
    global _ENABLED
    _ENABLED = bool(flag)


def lockcheck_enabled() -> bool:
    return _ENABLED


def set_fail_fast(flag: bool = True) -> None:
    global _FAIL_FAST
    _FAIL_FAST = bool(flag)


def fail_fast_enabled() -> bool:
    return _FAIL_FAST


def set_violation_sink(fn: Optional[Callable[[Dict[str, Any]], None]]) -> None:
    global _sink
    _sink = fn


def acquisitions() -> int:
    return _acquisitions


def witness_violations() -> List[Dict[str, Any]]:
    with _meta:
        return [dict(v) for v in _violations]


def order_graph() -> Dict[str, List[str]]:
    """Copy of the observed acquisition-order graph (for report/tests)."""
    with _meta:
        return {a: sorted(bs) for a, bs in _adj.items()}


def register_order_exception(a: str, b: str, reason: str) -> None:
    """Sanction the {a, b} ordering pair. Idempotent: re-registration (the
    pytest re-import case) updates the reason instead of erroring."""
    if not reason or not reason.strip():
        raise ValueError("order exception requires a reason")
    with _meta:
        _order_exceptions[frozenset((a, b))] = reason.strip()


def order_exceptions() -> Dict[Tuple[str, ...], str]:
    with _meta:
        return {tuple(sorted(k)): v for k, v in _order_exceptions.items()}


def reset_witness(clear_exceptions: bool = False) -> None:
    """Drop the observed graph, reported pairs, and violation log. The
    soak harness calls this between stack rebuilds: a promotion tears one
    deployment shape down and builds another, and edges from the dead
    shape must not combine with the new one into phantom cycles. Order
    exceptions are declarations, not observations — kept unless asked."""
    global _acquisitions
    with _meta:
        _adj.clear()
        _edge_sites.clear()
        _reported.clear()
        del _violations[:]
        _acquisitions = 0
        if clear_exceptions:
            _order_exceptions.clear()


# -- witness core ----------------------------------------------------------


def _held() -> List[str]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _site() -> str:
    """file:line digest of the acquisition site (innermost frame outside
    this module), plus a short hash of the whole stack so two distinct
    paths to the same line stay distinguishable in a report."""
    stack = traceback.extract_stack()
    frames = [f for f in stack if not f.filename.endswith("locks.py")]
    tail = frames[-1] if frames else stack[0]
    digest = f"{abs(hash(tuple((f.filename, f.lineno) for f in frames))) & 0xFFFFFFFF:08x}"
    fname = tail.filename.rsplit(os.sep, 1)[-1]
    return f"{fname}:{tail.lineno}#{digest}"


def _reaches(src: str, dst: str) -> Optional[List[str]]:
    """Path src -> ... -> dst in the order graph (callers hold _meta)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _adj.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


_EMPTY: frozenset = frozenset()


def _note_acquire(name: str) -> None:
    global _acquisitions
    if not _ENABLED:
        # Wrapper-resident disabled mode (the bench's off-arm): locks
        # constructed while the witness was on stay wrappers, but pay only
        # this flag check per acquire. Toggle only with no locks held —
        # skipped acquires must not unbalance the held stack.
        return
    held = _held()
    # Unguarded counter bump: a stats denominator, not an invariant —
    # losing the odd increment to a race beats taking _meta per acquire.
    _acquisitions += 1
    if not held:
        held.append(name)
        return
    # Steady-state fast path, no _meta: every (held, name) edge already in
    # the graph. Dict/set reads ride the GIL; a stale miss only means one
    # redundant trip through the slow path below.
    if all(a == name or name in _adj.get(a, _EMPTY) for a in held):
        held.append(name)
        return
    fired: List[Dict[str, Any]] = []
    site = None
    with _meta:
        for a in held:
            if a == name:
                continue
            succ = _adj.setdefault(a, set())
            if name in succ:
                continue
            if site is None:
                site = _site()
            succ.add(name)
            _edge_sites[(a, name)] = site
            # Incremental cycle check: the new edge a->name closes a
            # cycle iff `a` was already reachable FROM `name`.
            back = _reaches(name, a)
            if back is None:
                continue
            pair = (a, name)
            if pair in _reported or (name, a) in _reported:
                continue
            if frozenset((a, name)) in _order_exceptions:
                continue
            _reported.add(pair)
            cycle = back + [name]
            v = {
                "pair": f"{a}->{name}",
                "cycle": cycle,
                "site": site,
                "other_sites": {
                    f"{x}->{y}": _edge_sites.get((x, y), "?")
                    for x, y in zip(cycle, cycle[1:])
                },
                "thread": threading.current_thread().name,
            }
            _violations.append(v)
            fired.append(v)
    held.append(name)
    if not fired:
        return
    # Report OUTSIDE _meta: the metric/sink paths take tracked locks of
    # their own, and _meta must never nest around one.
    from training_operator_tpu.utils import metrics

    for v in fired:
        metrics.lock_order_violations.inc(v["pair"])
        sink = _sink
        if sink is not None:
            try:
                sink(v)
            except Exception:
                pass
    if _FAIL_FAST:
        from training_operator_tpu.observe.invariants import (
            InvariantViolationError,
        )

        # The wrapper releases the inner lock when we raise; the held
        # entry just pushed must unwind with it or it haunts every later
        # acquisition on this thread as a phantom edge source.
        _note_release(name)
        raise InvariantViolationError(
            "; ".join(
                f"lock-order cycle {' -> '.join(v['cycle'])} at {v['site']}"
                for v in fired
            )
        )


def _note_release(name: str) -> None:
    held = _held()  # tolerate disabled-mode acquires: absent names no-op
    for i in range(len(held) - 1, -1, -1):
        if held[i] == name:
            del held[i]
            return


class _WitnessLock:
    """Witness wrapper over threading.Lock. Implements the Condition
    integration protocol (_release_save/_acquire_restore/_is_owned) so a
    `TrackedCondition` keeps the held-set honest across wait()."""

    __slots__ = ("_inner", "name", "_owner")

    def __init__(self, name: str):
        self._inner = threading.Lock()
        self.name = name
        self._owner: Optional[int] = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            try:
                _note_acquire(self.name)
            except BaseException:
                self._inner.release()
                raise
            self._owner = threading.get_ident()
        return ok

    def release(self) -> None:
        self._owner = None
        _note_release(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "_WitnessLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    # Condition protocol ---------------------------------------------------

    def _release_save(self):
        self._owner = None
        _note_release(self.name)
        self._inner.release()

    def _acquire_restore(self, state) -> None:
        self._inner.acquire()
        _note_acquire(self.name)
        self._owner = threading.get_ident()

    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def __repr__(self) -> str:
        return f"<TrackedLock {self.name} held={self._inner.locked()}>"


class _WitnessRLock:
    """Witness wrapper over threading.RLock: only the OUTERMOST acquire
    notes the witness (reentry cannot change ordering)."""

    __slots__ = ("_inner", "name", "_owner", "_count")

    def __init__(self, name: str):
        self._inner = threading.RLock()
        self.name = name
        self._owner: Optional[int] = None
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        if self._owner == me:
            self._inner.acquire()
            self._count += 1
            return True
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            try:
                _note_acquire(self.name)
            except BaseException:
                self._inner.release()
                raise
            self._owner = me
            self._count = 1
        return ok

    def release(self) -> None:
        if self._owner != threading.get_ident():
            raise RuntimeError("cannot release un-acquired lock")
        self._count -= 1
        if self._count == 0:
            self._owner = None
            _note_release(self.name)
        self._inner.release()

    def __enter__(self) -> "_WitnessRLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    # Condition protocol ---------------------------------------------------

    def _release_save(self):
        count = self._count
        self._owner = None
        self._count = 0
        _note_release(self.name)
        return (self._inner._release_save(), count)

    def _acquire_restore(self, state) -> None:
        inner_state, count = state
        self._inner._acquire_restore(inner_state)
        _note_acquire(self.name)
        self._owner = threading.get_ident()
        self._count = count

    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def __repr__(self) -> str:
        return f"<TrackedRLock {self.name} count={self._count}>"


# -- factories -------------------------------------------------------------


def TrackedLock(name: str = "anon"):
    """A mutex in the named order class. Disabled mode returns the raw
    primitive — no wrapper allocation, no per-acquire cost."""
    if not _ENABLED:
        return threading.Lock()
    return _WitnessLock(name)


def TrackedRLock(name: str = "anon"):
    if not _ENABLED:
        return threading.RLock()
    return _WitnessRLock(name)


def TrackedCondition(lock=None, name: str = "anon"):
    """threading.Condition over a tracked lock. Passing an existing
    TrackedLock shares its order class (the store's wal_cond rides the
    store lock, exactly like the raw Condition(self._lock) it replaces);
    Condition's wait() goes through the wrapper's _release_save /
    _acquire_restore hooks, so the held-set stays honest while parked."""
    if lock is None:
        lock = TrackedRLock(name)
    return threading.Condition(lock)
