"""JAX platform-selection helper for entry points and harnesses.

One shared implementation of the "honor an explicit JAX_PLATFORMS=cpu
request" workaround, for ENTRY POINTS to call explicitly (examples, the
graft entry, benches). Deliberately NOT invoked at package import time:
the control-plane package must stay importable without jax's startup cost,
and a library that silently mutates process-global jax config on import
would surprise every downstream importer.

Background: a site-injected accelerator plugin (a tunnel-attached TPU)
can import jax at interpreter startup and rewrite the platform list — an
ambient "cpu" in the env becomes "axon,cpu" in jax.config, and the first
backend init then dials the plugin's tunnel, hanging every CPU-only run
whenever the tunnel is down.
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger(__name__)


def honor_cpu_platform_request() -> None:
    """Force jax back onto CPU iff the environment explicitly asked for it
    (`JAX_PLATFORMS=cpu`). No-op otherwise, so real-accelerator runs are
    untouched. Failures are WARNED, not swallowed silently — if a backend
    already initialized on another platform, the redirect is impossible
    and the caller should know why the run may hang."""
    if os.environ.get("JAX_PLATFORMS", "").strip() != "cpu":
        return
    try:
        import jax

        if jax.config.jax_platforms != "cpu":
            jax.config.update("jax_platforms", "cpu")
    except ImportError:
        pass  # no jax in this interpreter: nothing to redirect
    except (RuntimeError, AttributeError) as e:
        # RuntimeError: a backend already initialized (too late to
        # redirect); AttributeError: a jax API change. Either way the
        # CPU request may not be honored — say so instead of hanging mute.
        log.warning("JAX_PLATFORMS=cpu could not be enforced: %s", e)
