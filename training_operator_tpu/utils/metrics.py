"""Prometheus-style counter/gauge registry.

Parity target: reference pkg/common/metrics.go:25-61 (jobs created/deleted/
successful/failed/restarted by namespace+framework) plus the pod/service/
podgroup counters in common/pod.go:57-70 and common/job_controller.go:51-58.
Metric names are kept compatible where sensible so dashboards translate.

Implemented standalone (no prometheus_client dependency); `render()` emits
text exposition format for scraping/export.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Tuple


def _label_str(label_names: Tuple[str, ...], labels: Tuple[str, ...]) -> str:
    """THE label rendering — render() and MetricsRegistry.snapshot() must
    agree on it or scrape text and the /metrics JSON silently diverge."""
    return ",".join(f'{n}="{val}"' for n, val in zip(label_names, labels))


class Counter:
    def __init__(self, name: str, help_text: str, label_names: Tuple[str, ...]):
        self.name = name
        self.help = help_text
        self.label_names = label_names
        self._values: Dict[Tuple[str, ...], float] = defaultdict(float)
        self._lock = threading.Lock()

    def inc(self, *label_values: str, amount: float = 1.0) -> None:
        if len(label_values) != len(self.label_names):
            raise ValueError(f"{self.name}: expected labels {self.label_names}")
        with self._lock:
            self._values[tuple(label_values)] += amount

    def value(self, *label_values: str) -> float:
        return self._values.get(tuple(label_values), 0.0)

    def total(self) -> float:
        return sum(self._values.values())

    def items(self) -> List[Tuple[Tuple[str, ...], float]]:
        """Stable copy for iteration: a concurrent inc() inserting a
        first-seen label tuple would otherwise blow up a reader mid-walk
        (render/snapshot run on scrape/network threads)."""
        with self._lock:
            return list(self._values.items())

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        for labels, v in sorted(self.items()):
            lines.append(f"{self.name}{{{_label_str(self.label_names, labels)}}} {v}")
        return lines


class Gauge(Counter):
    def set(self, *label_values: str, value: float = 0.0) -> None:
        with self._lock:
            self._values[tuple(label_values)] = value

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        for labels, v in sorted(self.items()):
            lines.append(f"{self.name}{{{_label_str(self.label_names, labels)}}} {v}")
        return lines


class Histogram:
    """Summary-style observation metric (count/sum/min/max) — enough for the
    scheduler-latency surface without bucket bookkeeping."""

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self.count = 0
        self.sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self.count += 1
            self.sum += value

    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def render(self) -> List[str]:
        return [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} summary",
            f"{self.name}_count {self.count}",
            f"{self.name}_sum {self.sum}",
        ]


class MetricsRegistry:
    def __init__(self) -> None:
        self._metrics: Dict[str, Counter] = {}

    def counter(self, name: str, help_text: str = "", labels: Tuple[str, ...] = ()) -> Counter:
        if name not in self._metrics:
            self._metrics[name] = Counter(name, help_text, labels)
        return self._metrics[name]

    def gauge(self, name: str, help_text: str = "", labels: Tuple[str, ...] = ()) -> Gauge:
        if name not in self._metrics:
            self._metrics[name] = Gauge(name, help_text, labels)
        return self._metrics[name]

    def histogram(self, name: str, help_text: str = "") -> Histogram:
        if name not in self._metrics:
            self._metrics[name] = Histogram(name, help_text)
        return self._metrics[name]

    def render(self) -> str:
        out: List[str] = []
        for m in self._metrics.values():
            out.extend(m.render())
        return "\n".join(out) + "\n"

    def snapshot(self) -> Dict[str, float]:
        """Flat {name or name{labels}: value} view of every metric — the
        JSON analogue of render(), for the wire API's GET /metrics (a remote
        bench/test can assert counter deltas without text parsing)."""
        out: Dict[str, float] = {}
        for m in self._metrics.values():
            if isinstance(m, Histogram):
                with m._lock:
                    out[f"{m.name}_count"] = m.count
                    out[f"{m.name}_sum"] = m.sum
                continue
            for labels, v in m.items():
                if labels:
                    out[f"{m.name}{{{_label_str(m.label_names, labels)}}}"] = v
                else:
                    out[m.name] = v
        return out


# Global registry + the reference's counter families.
registry = MetricsRegistry()

jobs_created = registry.counter(
    "training_operator_jobs_created_total",
    "Counts number of jobs created",
    ("job_namespace", "framework"),
)
jobs_deleted = registry.counter(
    "training_operator_jobs_deleted_total",
    "Counts number of jobs deleted",
    ("job_namespace", "framework"),
)
jobs_successful = registry.counter(
    "training_operator_jobs_successful_total",
    "Counts number of jobs successful",
    ("job_namespace", "framework"),
)
jobs_failed = registry.counter(
    "training_operator_jobs_failed_total",
    "Counts number of jobs failed",
    ("job_namespace", "framework", "reason"),
)
jobs_restarted = registry.counter(
    "training_operator_jobs_restarted_total",
    "Counts number of jobs restarted",
    ("job_namespace", "framework"),
)
created_pods = registry.counter(
    "training_operator_created_pods_total", "The number of created pods", ()
)
deleted_pods = registry.counter(
    "training_operator_deleted_pods_total", "The number of deleted pods", ()
)
restarted_pods = registry.counter(
    "training_operator_restarted_pods_total", "The number of restarted pods", ()
)
created_services = registry.counter(
    "training_operator_created_services_total", "The number of created services", ()
)
deleted_services = registry.counter(
    "training_operator_deleted_services_total", "The number of deleted services", ()
)
created_podgroups = registry.counter(
    "training_operator_created_podgroups_total", "The number of created podgroups", ()
)
deleted_podgroups = registry.counter(
    "training_operator_deleted_podgroups_total", "The number of deleted podgroups", ()
)
podgroups_admitted = registry.counter(
    "training_operator_podgroups_admitted_total",
    "The number of podgroups admitted by the gang scheduler", (),
)
pods_bound = registry.counter(
    "training_operator_pods_bound_total",
    "The number of pods bound by the gang scheduler", (),
)
scheduler_solve_seconds = registry.histogram(
    "training_operator_scheduler_solve_seconds",
    "Wall time of gang-scheduler placement solves",
)
# controller-runtime parity: per-reconcile latency + outcome and live
# workqueue depth (controller_runtime_reconcile_time_seconds /
# controller_runtime_reconcile_total / workqueue_depth).
reconcile_seconds = registry.histogram(
    "training_operator_reconcile_seconds",
    "Wall time of one reconcile pass (all kinds)",
)
reconcile_total = registry.counter(
    "training_operator_reconcile_total",
    "Reconcile passes by kind and result",
    ("kind", "result"),  # result: success | error
)
lint_diagnostics = registry.counter(
    "training_lint_diagnostics_total",
    "Spec-lint diagnostics emitted by admission-path dry-run analysis",
    ("rule", "severity"),
)
# Wire fast-path caches (cluster/wire.py + cluster/httpapi.py). Hit rates
# are the evidence behind the wire_overhead bench claims: exactly one
# serialization per watch event regardless of subscriber count, and GET/LIST
# bodies reused across requests until the object's resourceVersion moves.
wire_codec_cache_hits = registry.counter(
    "training_wire_codec_cache_hits_total",
    "encode/decode calls served by an already-compiled dataclass codec", (),
)
wire_codec_compiles = registry.counter(
    "training_wire_codec_compiles_total",
    "dataclass codec compilations (once per class per process)", (),
)
wire_body_cache_hits = registry.counter(
    "training_wire_body_cache_hits_total",
    "GET/LIST object bodies served from the version-keyed byte cache", (),
)
wire_body_cache_misses = registry.counter(
    "training_wire_body_cache_misses_total",
    "GET/LIST object bodies encoded fresh (new object or new resourceVersion)", (),
)
wire_event_encodes = registry.counter(
    "training_wire_event_encodes_total",
    "watch events serialized to wire bytes (once per event, all sessions)", (),
)
wire_event_cache_hits = registry.counter(
    "training_wire_event_cache_hits_total",
    "watch event drains served from the serialize-once byte cache", (),
)
# Watch-session resume (wire_server._ResumeRing + wire_watch._SharedWatch):
# the O(delta) reconnect path. In the steady state delta_total climbs while
# too_old_total stays 0 — a nonzero too_old means the ring was outrun (or a
# host restart changed the epoch) and the client fell back to a full relist.
wire_resume_delta = registry.counter(
    "training_wire_resume_delta_total",
    "watch resubscribes served by delta replay from the resume ring", (),
)
wire_resume_replayed = registry.counter(
    "training_wire_resume_replayed_events_total",
    "watch events replayed (byte-copied) across all delta resumes", (),
)
wire_resume_too_old = registry.counter(
    "training_wire_resume_too_old_total",
    "watch resubscribes whose watermark the ring had outrun (410-style full-relist fallback)", (),
)
wire_resume_ring_evictions = registry.counter(
    "training_wire_resume_ring_evictions_total",
    "watch events evicted from the bounded resume ring", (),
)
workqueue_depth = registry.gauge(
    "training_operator_workqueue_depth",
    "Keys pending in the manager workqueue after the current tick",
    (),
)
